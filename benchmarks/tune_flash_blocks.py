"""Sweep Pallas flash-attention tile sizes on the real chip (bf16 + f32).

Prints TFLOP/s per (block_q, block_k) for causal L=8192 forward and
train fwd+bwd, tunnel-corrected the same way run_benchmarks does (chained
applications inside one jitted program, fixed round trip subtracted).
The winner becomes DEFAULT_BLOCK_Q/DEFAULT_BLOCK_K in ops/attention.py.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main():
    import jax
    import jax.numpy as jnp

    from omldm_tpu.ops import attention as A

    assert jax.devices()[0].platform == "tpu", "tuner needs the real chip"
    rng = np.random.RandomState(0)
    b, l, h, dh = 4, 8192, 8, 64
    flops = 4 * b * h * l * l * dh / 2  # causal half

    def chain_time(apply, x0, chain):
        @jax.jit
        def run(x):
            def body(c, _):
                return apply(c), ()

            c, _ = jax.lax.scan(body, x, None, length=chain)
            return c.sum()

        @jax.jit
        def rt(x):
            return x.sum()

        float(np.asarray(run(x0)))
        float(np.asarray(rt(x0)))
        t0 = time.perf_counter()
        float(np.asarray(rt(x0)))
        t_rt = time.perf_counter() - t0
        t0 = time.perf_counter()
        float(np.asarray(run(x0)))
        total = time.perf_counter() - t0
        return max(total - t_rt, 1e-9) / chain

    import itertools

    configs = [
        tuple(int(x) for x in c.split("x"))
        for c in (sys.argv[1].split(",") if len(sys.argv) > 1 else
                  ["512x512", "512x1024", "1024x512", "1024x1024"])
    ]
    dtypes = (
        [jnp.bfloat16, jnp.float32] if len(sys.argv) <= 2
        else [dict(bf16=jnp.bfloat16, f32=jnp.float32)[d]
              for d in sys.argv[2].split(",")]
    )
    for dtype in dtypes:
        q = jnp.asarray(rng.randn(b, l, h, dh) * 0.1, dtype)
        k = jnp.asarray(rng.randn(b, l, h, dh) * 0.1, dtype)
        v = jnp.asarray(rng.randn(b, l, h, dh) * 0.1, dtype)
        q1, k1, v1 = q[:1], k[:1], v[:1]
        for bq, bk in configs:
            # set BOTH forward and backward defaults: the train rows
            # tune the full fwd+bwd pipeline at this tile shape
            A.DEFAULT_BLOCK_Q, A.DEFAULT_BLOCK_K = bq, bk
            A.DEFAULT_BWD_BLOCK_Q, A.DEFAULT_BWD_BLOCK_K = bq, bk
            name = np.dtype(dtype).name
            try:
                t_f = chain_time(
                    lambda x: A.flash_attention_pallas(
                        x, k, v, causal=True, block_q=bq, block_k=bk
                    ),
                    q, chain=32,
                )
                print(
                    f"{name:9s} bq={bq:5d} bk={bk:5d}  "
                    f"fwd {flops / t_f / 1e12:7.2f} TF/s", flush=True,
                )
                # grad over ALL inputs: a q-only grad lets XLA dead-code
                # -eliminate the whole dk/dv kernel and overstate train
                g = jax.grad(
                    lambda q_, k_, v_: A._flash_diff(
                        q_, k_, v_, True, 0, 0
                    ).sum(),
                    argnums=(0, 1, 2),
                )

                def train_step(x):
                    dq, dk, dv = g(x, k1, v1)
                    return dq + dk + dv

                t_g = chain_time(train_step, q1, chain=16)
                print(
                    f"{name:9s} bq={bq:5d} bk={bk:5d}  "
                    f"train {(flops / b) * 3.5 / t_g / 1e12:7.2f} TF/s",
                    flush=True,
                )
            except Exception as e:
                print(
                    f"{name:9s} bq={bq:5d} bk={bk:5d}  "
                    f"FAILED: {type(e).__name__}: {str(e)[:120]}",
                    flush=True,
                )


if __name__ == "__main__":
    main()
