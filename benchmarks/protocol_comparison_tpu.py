"""Per-protocol cost on the REAL TPU chip (dp=1 x hub=1).

The virtual-mesh comparison (protocol_comparison.py) pins protocol
SEMANTICS — score parity, traffic accounting — but its examples/sec is
8-virtual-devices-on-one-CPU-core emulation. This harness measures what
protocol synchronization actually costs on silicon, in the only
configuration one chip can host (dp=1, hub=1 — the reference's
parallelism-1 operating point; dp>1/hub>1 need more chips and are
validated on the virtual mesh):

- protocol-free baseline: the SAME learner/batch through MLPipeline's
  chained fit (no parameter-server machinery at all);
- all 6 collective protocols through SPMDTrainer.step_many_dense at the
  same shapes: examples/sec, per-step overhead vs the baseline, logical
  bytesShipped vs physical collective bytes (at dp=1 the fold/sync
  collectives are single-participant — the overhead measured here is the
  protocol's control flow: drift norms, votes, clock bookkeeping, the
  gated branches — the part that rides EVERY deployment).

Tunnel rules: chained steps inside one program, device-resident stages,
real D2H fetch as the barrier, best-of-3. Emits ONE JSON object and
writes PROTOCOL_TPU.json for RESULTS_r05. Reference vocabulary:
FlinkHub.scala:118-127 statistics.
"""

import json
import os
import time

import jax
import numpy as np

_cache = os.path.join(os.path.expanduser("~"), ".cache", "omldm_tpu", "xla")
os.makedirs(_cache, exist_ok=True)
jax.config.update("jax_compilation_cache_dir", _cache)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

DIM = 28
BATCH = 256
CHAIN = 64
ROUNDS = 40  # chained launches per timed sample

PROTOCOLS = ("Synchronous", "Asynchronous", "SSP", "EASGD", "GM", "FGM")


def materialize(tree):
    leaves = jax.tree_util.tree_leaves(tree)
    return float(np.asarray(leaves[0]).reshape(-1)[0])


def _data(rng):
    w = np.random.RandomState(42).randn(DIM)
    x = rng.randn(CHAIN, BATCH, DIM).astype(np.float32)
    y = (x @ w > 0).astype(np.float32)
    return jax.device_put(x), jax.device_put(y)


def bench_baseline(xs, ys):
    """Protocol-free chained fit: MLPipeline (no PS, no collectives)."""
    from omldm_tpu.api.requests import LearnerSpec
    from omldm_tpu.pipelines import MLPipeline

    pipe = MLPipeline(
        LearnerSpec("PA", hyper_parameters={"C": 1.0}), [], dim=DIM,
        rng=jax.random.PRNGKey(0),
    )
    masks = jax.device_put(np.ones((CHAIN, BATCH), np.float32))
    counts = [BATCH] * CHAIN

    def launch():
        for _ in range(ROUNDS):
            pipe.fit_many(xs, ys, masks, valid_counts=counts)
        materialize(pipe.state["params"])

    launch()  # warm/compile
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        launch()
        best = min(best, time.perf_counter() - t0)
    steps = ROUNDS * CHAIN
    return {
        "examples_per_sec": round(steps * BATCH / best, 1),
        "us_per_step": round(best / steps * 1e6, 2),
    }


def bench_protocol(protocol, xs, ys):
    from omldm_tpu.api.requests import LearnerSpec, TrainingConfiguration
    from omldm_tpu.parallel import SPMDTrainer, make_mesh

    extra = {"syncEvery": 4}
    if protocol in ("GM", "FGM"):
        extra["threshold"] = 0.5
    if protocol == "SSP":
        extra["staleness"] = 3
    tr = SPMDTrainer(
        LearnerSpec("PA", hyper_parameters={"C": 1.0}), [], dim=DIM,
        protocol=protocol, mesh=make_mesh(dp=1, hub=1),
        training_configuration=TrainingConfiguration(
            protocol=protocol, extra=extra
        ),
        batch_size=BATCH,
    )
    xs1 = xs[:, None]  # [CHAIN, dp=1, B, D]
    ys1 = ys[:, None]

    def launch():
        for _ in range(ROUNDS):
            tr.step_many_dense(xs1, ys1)
        materialize(tr.state["params"])

    launch()
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        launch()
        best = min(best, time.perf_counter() - t0)
    steps = ROUNDS * CHAIN
    return {
        "examples_per_sec": round(steps * BATCH / best, 1),
        "us_per_step": round(best / steps * 1e6, 2),
        "bytes_shipped_logical": tr.bytes_shipped(),
        "bytes_physical": tr.collective_bytes_physical(),
        "sync_count": tr.sync_count(),
    }


def main():
    print(f"devices: {jax.devices()}", flush=True)
    rng = np.random.RandomState(0)
    xs, ys = _data(rng)
    materialize((xs, ys))
    out = {"baseline_no_protocol": bench_baseline(xs, ys)}
    base_us = out["baseline_no_protocol"]["us_per_step"]
    for protocol in PROTOCOLS:
        r = bench_protocol(protocol, xs, ys)
        r["overhead_us_per_step_vs_free"] = round(r["us_per_step"] - base_us, 2)
        out[protocol] = r
        print(f"{protocol:14s} {r}", flush=True)
    doc = {
        "protocol_comparison_tpu": out,
        "basis": (
            f"real chip, dp=1 x hub=1, batch {BATCH}, {CHAIN}-step chained "
            f"launches x {ROUNDS} rounds, best-of-3; overhead = protocol "
            "step time minus the protocol-free MLPipeline chained fit at "
            "identical shapes. dp>1/hub>1 protocol semantics + traffic are "
            "pinned on the virtual mesh (protocol_comparison.py)"
        ),
    }
    print(json.dumps(doc, indent=1), flush=True)
    with open(
        os.path.join(os.path.dirname(__file__), "PROTOCOL_TPU.json"), "w"
    ) as f:
        json.dump(doc, f, indent=1)


if __name__ == "__main__":
    main()
