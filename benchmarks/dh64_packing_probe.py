"""Why dh=64 flash attention runs at ~half MXU rate — measured probe.

Round-4 result: dh=64 flash trains at 25.6% MFU vs 55% at dh=128. The
suggested fix was to pack two dh=64 heads into one 128-deep contraction.
This probe measures why no such packing exists for attention:

1. ``qk_depth``: raw MXU rate of [L, K] @ [K, L] at contraction depth
   K = 64 vs 128 (same output tile). The systolic array is 128 deep; a
   64-deep contraction zero-pads the other half — expect ~2x rate loss.
   This is the QK^T score matmul, whose contraction dim IS dh.
2. ``pv_width``: [L, 128] @ [128, dh] at output width dh = 64 vs 128 —
   the PV product's output lanes half-fill the same way.
3. ``blockdiag_pack``: the only algebraically-correct two-head packing,
   [P1 | P2] [Bq, 2Bk] @ blockdiag(V1, V2) [2Bk, 128]: full depth, full
   lanes — but HALF the operand entries are structural zeros, so the
   useful-FLOP rate is unchanged. Measured to confirm there is no win.

Why nothing better exists: attention scores are PER-HEAD bilinear forms
S_h = Q_h K_h^T. Any layout that feeds two heads' Q/K through one
contraction either sums their scores (concat along dh: Q1K1^T + Q2K2^T),
computes cross-head garbage quadrants (stacking: 4x FLOPs for 2 heads),
or pads with zeros (block-diagonal: 2x FLOPs) — in every case the useful
work per MXU pass is what a 64-deep contraction does. The dh=64 penalty
is intrinsic to the head width, which is why the TPU-native model family
uses dh=128 (benchmarks/_longctx_bench sizing note); dh=64 checkpoints
imported from other frameworks pay the hardware's depth mismatch, not a
kernel deficiency. Results land in RESULTS as `dh64_packing_probe`.
"""

import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np

_cache = os.path.join(os.path.expanduser("~"), ".cache", "omldm_tpu", "xla")
os.makedirs(_cache, exist_ok=True)
jax.config.update("jax_compilation_cache_dir", _cache)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

L = 2048
CHAIN = 64
ROUNDS = 5


def materialize(x):
    return float(np.asarray(x).reshape(-1)[0])


def timed_matmul(name, m, k, n, useful_frac=1.0, zero_frac_note=""):
    """Rate of CHAIN chained [m,k]@[k,n] bf16 matmuls (one program)."""
    rng = np.random.RandomState(0)
    a = jnp.asarray(rng.randn(m, k).astype(np.float32)).astype(jnp.bfloat16)
    b = jnp.asarray(rng.randn(k, n).astype(np.float32)).astype(jnp.bfloat16)

    @jax.jit
    def run(a_, b_):
        def body(acc, _):
            c = jax.lax.dot_general(
                a_, b_, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            # fold the result back so the chain has a data dependence and
            # XLA cannot hoist or elide any iteration
            return acc + c[0, 0], ()

        acc, _ = jax.lax.scan(body, jnp.float32(0.0), None, length=CHAIN)
        return acc

    materialize(run(a, b))
    best = float("inf")
    for _ in range(ROUNDS):
        t0 = time.perf_counter()
        materialize(run(a, b))
        best = min(best, time.perf_counter() - t0)
    tflops = CHAIN * 2 * m * k * n / best / 1e12
    useful = tflops * useful_frac
    print(
        f"{name:28s} {tflops:7.1f} TF/s raw"
        + (f"  ({useful:6.1f} useful{zero_frac_note})" if useful_frac < 1 else ""),
        flush=True,
    )
    return {"raw_tflops": round(tflops, 1), "useful_tflops": round(useful, 1)}


def main():
    print(f"devices: {jax.devices()}", flush=True)
    out = {}
    # 1. QK^T: contraction depth IS dh
    out["qk_depth_128"] = timed_matmul("qk depth=128", L, 128, L)
    out["qk_depth_64"] = timed_matmul("qk depth=64", L, 64, L)
    # 2. PV: output width IS dh
    out["pv_width_128"] = timed_matmul("pv width=128", L, L, 128)
    out["pv_width_64"] = timed_matmul("pv width=64", L, L, 64)
    # 3. block-diagonal two-head packing: full depth/lanes, half zeros
    out["blockdiag_pack"] = timed_matmul(
        "blockdiag 2-head pack", L, 2 * L, 128,
        useful_frac=0.5, zero_frac_note=", 50% structural zeros",
    )
    ratio = out["qk_depth_64"]["raw_tflops"] / max(
        out["qk_depth_128"]["raw_tflops"], 1e-9
    )
    out["depth64_vs_128_ratio"] = round(ratio, 3)
    out["conclusion"] = (
        "attention scores are per-head bilinear forms; every two-head "
        "packing is score-summing, cross-head garbage, or zero-padding — "
        "useful FLOPs per MXU pass stay those of a 64-deep contraction. "
        "dh=64 penalty is intrinsic; native models use dh=128."
    )
    print(json.dumps({"dh64_packing_probe": out}, indent=1), flush=True)
    with open(
        os.path.join(os.path.dirname(__file__), "DH64_PROBE.json"), "w"
    ) as f:
        json.dump({"dh64_packing_probe": out}, f, indent=1)


if __name__ == "__main__":
    main()
