"""Protocol comparison at the reference's own operating point.

The reference project's core experiment is comparing distributed online
learning protocols (its 8 worker/PS pairs, MLNodeGenerator.scala:20-76) on
throughput, communication traffic, and accuracy at job parallelism 16 (its
default, DefaultJobParameters.scala:5, observed live in
hs_err_pid77107.log:21). This harness reproduces that comparison on the
host plane of the streaming runtime: one identical synthetic stream
(BASELINE config-1 shape: 28 numeric features, linearly separable), one
StreamJob per protocol, measuring end-to-end examples/sec, final holdout
score, and the hub-side communication accounting (bytesShipped /
modelsShipped / numOfBlocks, FlinkHub.scala:118-127).

Runs on the CPU backend: the host plane's per-batch dispatch is what is
being compared (protocol logic + message traffic), and this environment's
TPU network tunnel would add a ~65 ms round trip per dispatch that no real
deployment pays.

The same comparison also runs on the SPMD COLLECTIVE engine (the 6
protocols with device-plane equivalents, `{"engine": "spmd"}` on an
8-worker virtual mesh): examples/sec, score, logical bytesShipped vs
physical collective bytes, and host-vs-SPMD score parity per protocol.

Usage: python benchmarks/protocol_comparison.py [--records N]
Prints ONE JSON line: {"config": "protocol_comparison", ...}.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


PROTOCOLS = (
    "Asynchronous",
    "Synchronous",
    "SSP",
    "EASGD",
    "GM",
    "FGM",
    "CentralizedTraining",
    "SingleLearner",
)


SPMD_PROTOCOLS = (
    "Asynchronous",
    "Synchronous",
    "SSP",
    "EASGD",
    "GM",
    "FGM",
)


def run_one(protocol: str, x, y, parallelism: int, batch: int,
            engine: str = "host"):
    import numpy as np

    from omldm_tpu.config import JobConfig
    from omldm_tpu.runtime import StreamJob
    from omldm_tpu.runtime.job import REQUEST_STREAM

    n = x.shape[0]
    job = StreamJob(
        JobConfig(
            parallelism=parallelism, batch_size=batch, test_set_size=64
        )
    )
    create = {
        "id": 0,
        "request": "Create",
        "learner": {
            "name": "PA",
            "hyperParameters": {"C": 1.0},
            "dataStructure": {"nFeatures": int(x.shape[1])},
        },
        "trainingConfiguration": {"protocol": protocol, "syncEvery": 4},
    }
    if engine == "spmd":
        create["trainingConfiguration"]["engine"] = "spmd"
        create["trainingConfiguration"]["stageChain"] = 4
    job.process_event(REQUEST_STREAM, json.dumps(create))
    op = np.zeros((n,), np.uint8)
    chunk = 8192
    t0 = time.perf_counter()
    for i in range(0, n, chunk):
        job.process_packed_batch(
            x[i : i + chunk], y[i : i + chunk], op[i : i + chunk]
        )
    report = job.terminate()
    elapsed = time.perf_counter() - t0
    [stats] = report.statistics
    out = {
        "examples_per_sec": round(n / elapsed, 1),
        "score": round(stats.score, 4),
        "fitted": stats.fitted,
        "bytes_shipped": stats.bytes_shipped,
        "models_shipped": stats.models_shipped,
        "num_of_blocks": stats.num_of_blocks,
    }
    if job.spmd_bridges:
        [bridge] = job.spmd_bridges.values()
        out["bytes_physical"] = bridge.trainer.collective_bytes_physical()
    return out


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--records", type=int, default=50_000)
    ap.add_argument("--parallelism", type=int, default=16)
    ap.add_argument("--batch", type=int, default=256)
    args = ap.parse_args()

    import os

    # the SPMD section wants a real multi-worker mesh: 8 virtual CPU
    # devices (must be set before the backend initializes)
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()

    import jax

    # host-plane comparison: protocol logic + traffic, not chip perf (and
    # not this environment's per-dispatch tunnel round trip)
    jax.config.update("jax_platforms", "cpu")

    import numpy as np

    rng = np.random.RandomState(0)
    w = np.random.RandomState(42).randn(28)
    x = rng.randn(args.records, 28).astype(np.float32)
    y = (x @ w > 0).astype(np.float32)

    # untimed warmup: the jitted fit/eval/chained-fit programs are shared
    # by (learner, dim, batch) spec, so one run compiles for all — sized
    # for several full batches per worker so the blocked-batch chain
    # program compiles too (it only traces once >= 2 batches are pending)
    warm = min(args.parallelism * args.batch * 4, args.records)
    run_one(PROTOCOLS[0], x[:warm], y[:warm], args.parallelism, args.batch)

    out = {}
    for protocol in PROTOCOLS:
        out[protocol] = run_one(protocol, x, y, args.parallelism, args.batch)

    # SPMD collective engine: same stream, same scoring, the 6 protocols
    # with device-plane equivalents on the 8-worker virtual mesh
    run_one(
        SPMD_PROTOCOLS[0], x[:warm], y[:warm], args.parallelism, args.batch,
        engine="spmd",
    )
    out_spmd = {}
    for protocol in SPMD_PROTOCOLS:
        r = run_one(
            protocol, x, y, args.parallelism, args.batch, engine="spmd"
        )
        host = out[protocol]
        r["speedup_vs_host_plane"] = round(
            r["examples_per_sec"] / max(host["examples_per_sec"], 1e-9), 2
        )
        r["score_parity_abs_diff"] = round(
            abs(r["score"] - host["score"]), 4
        )
        out_spmd[protocol] = r
    print(
        json.dumps(
            {
                "config": "protocol_comparison",
                "metric": "per-protocol examples/sec, score, traffic",
                "parallelism": args.parallelism,
                "records": args.records,
                "protocols": out,
                "protocols_spmd": out_spmd,
                "spmd_basis": (
                    "virtual 8-device CPU mesh: protocol SEMANTICS, score "
                    "parity and traffic accounting — NOT chip throughput "
                    "(8 virtual devices emulate collectives on one CPU "
                    "core, so examples/sec reflects XLA CPU emulation "
                    "overhead; the engine's real-chip throughput is the "
                    "avazu_softmax and e2e configs of run_benchmarks.py, "
                    "which exceed every host-plane figure here)"
                ),
                "note": (
                    "protocols_spmd: bytes_physical counts executed "
                    "collective rounds + scalar vote channels (gated "
                    "Async/SSP folds), bytes_shipped the application "
                    "payload accounting"
                ),
            }
        )
    )


if __name__ == "__main__":
    main()
