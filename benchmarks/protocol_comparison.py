"""Protocol comparison at the reference's own operating point.

The reference project's core experiment is comparing distributed online
learning protocols (its 8 worker/PS pairs, MLNodeGenerator.scala:20-76) on
throughput, communication traffic, and accuracy at job parallelism 16 (its
default, DefaultJobParameters.scala:5, observed live in
hs_err_pid77107.log:21). This harness reproduces that comparison on the
host plane of the streaming runtime: one identical synthetic stream
(BASELINE config-1 shape: 28 numeric features, linearly separable), one
StreamJob per protocol, measuring end-to-end examples/sec, final holdout
score, and the hub-side communication accounting (bytesShipped /
modelsShipped / numOfBlocks, FlinkHub.scala:118-127).

Runs on the CPU backend: the host plane's per-batch dispatch is what is
being compared (protocol logic + message traffic), and this environment's
TPU network tunnel would add a ~65 ms round trip per dispatch that no real
deployment pays.

Usage: python benchmarks/protocol_comparison.py [--records N]
Prints ONE JSON line: {"config": "protocol_comparison_host_plane", ...}.
"""

from __future__ import annotations

import argparse
import json
import time


PROTOCOLS = (
    "Asynchronous",
    "Synchronous",
    "SSP",
    "EASGD",
    "GM",
    "FGM",
    "CentralizedTraining",
    "SingleLearner",
)


def run_one(protocol: str, x, y, parallelism: int, batch: int):
    import numpy as np

    from omldm_tpu.config import JobConfig
    from omldm_tpu.runtime import StreamJob
    from omldm_tpu.runtime.job import REQUEST_STREAM

    n = x.shape[0]
    job = StreamJob(
        JobConfig(
            parallelism=parallelism, batch_size=batch, test_set_size=64
        )
    )
    create = {
        "id": 0,
        "request": "Create",
        "learner": {
            "name": "PA",
            "hyperParameters": {"C": 1.0},
            "dataStructure": {"nFeatures": int(x.shape[1])},
        },
        "trainingConfiguration": {"protocol": protocol, "syncEvery": 4},
    }
    job.process_event(REQUEST_STREAM, json.dumps(create))
    op = np.zeros((n,), np.uint8)
    chunk = 8192
    t0 = time.perf_counter()
    for i in range(0, n, chunk):
        job.process_packed_batch(
            x[i : i + chunk], y[i : i + chunk], op[i : i + chunk]
        )
    report = job.terminate()
    elapsed = time.perf_counter() - t0
    [stats] = report.statistics
    return {
        "examples_per_sec": round(n / elapsed, 1),
        "score": round(stats.score, 4),
        "fitted": stats.fitted,
        "bytes_shipped": stats.bytes_shipped,
        "models_shipped": stats.models_shipped,
        "num_of_blocks": stats.num_of_blocks,
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--records", type=int, default=50_000)
    ap.add_argument("--parallelism", type=int, default=16)
    ap.add_argument("--batch", type=int, default=256)
    args = ap.parse_args()

    import jax

    # host-plane comparison: protocol logic + traffic, not chip perf (and
    # not this environment's per-dispatch tunnel round trip)
    jax.config.update("jax_platforms", "cpu")

    import numpy as np

    rng = np.random.RandomState(0)
    w = np.random.RandomState(42).randn(28)
    x = rng.randn(args.records, 28).astype(np.float32)
    y = (x @ w > 0).astype(np.float32)

    # untimed warmup: the jitted fit/eval/chained-fit programs are shared
    # by (learner, dim, batch) spec, so one run compiles for all — sized
    # for several full batches per worker so the blocked-batch chain
    # program compiles too (it only traces once >= 2 batches are pending)
    warm = min(args.parallelism * args.batch * 4, args.records)
    run_one(PROTOCOLS[0], x[:warm], y[:warm], args.parallelism, args.batch)

    out = {}
    for protocol in PROTOCOLS:
        out[protocol] = run_one(protocol, x, y, args.parallelism, args.batch)
    print(
        json.dumps(
            {
                "config": "protocol_comparison_host_plane",
                "metric": "per-protocol examples/sec, score, traffic",
                "parallelism": args.parallelism,
                "records": args.records,
                "protocols": out,
            }
        )
    )


if __name__ == "__main__":
    main()
