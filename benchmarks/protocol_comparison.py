"""Protocol comparison at the reference's own operating point.

The reference project's core experiment is comparing distributed online
learning protocols (its 8 worker/PS pairs, MLNodeGenerator.scala:20-76) on
throughput, communication traffic, and accuracy at job parallelism 16 (its
default, DefaultJobParameters.scala:5, observed live in
hs_err_pid77107.log:21). This harness reproduces that comparison on the
host plane of the streaming runtime: one identical synthetic stream
(BASELINE config-1 shape: 28 numeric features, linearly separable), one
StreamJob per protocol, measuring end-to-end examples/sec, final holdout
score, and the hub-side communication accounting (bytesShipped /
modelsShipped / numOfBlocks, FlinkHub.scala:118-127).

Runs on the CPU backend: the host plane's per-batch dispatch is what is
being compared (protocol logic + message traffic), and this environment's
TPU network tunnel would add a ~65 ms round trip per dispatch that no real
deployment pays.

The same comparison also runs on the SPMD COLLECTIVE engine (the 6
protocols with device-plane equivalents, `{"engine": "spmd"}` on an
8-worker virtual mesh): examples/sec, score, logical bytesShipped vs
physical collective bytes, and host-vs-SPMD score parity per protocol.

`--codec` adds the TRANSPORT CODEC comparison (runtime.codec): the same
protocols on a params-dominated 256-feature stream, swept over the
requested codec(s), reporting bytes-on-wire, the reduction vs the
uncompressed baseline, codec encode+decode seconds, and final score —
plus the multi-process model-exchange route (the SPMDTrainer collective
the distributed job's psMessages-equivalent traffic rides) measured the
same way. `--smoke` is the CI mode: a small stream, the codec sections
only, and a NONZERO EXIT if an int8 run fails the >= 3.5x bytes-on-wire
reduction bar or drifts past the convergence envelope.

Usage: python benchmarks/protocol_comparison.py [--records N]
           [--codec none|fp16|int8|topk|sweep] [--smoke]
Prints ONE JSON line: {"config": "protocol_comparison", ...}.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


PROTOCOLS = (
    "Asynchronous",
    "Synchronous",
    "SSP",
    "EASGD",
    "GM",
    "FGM",
    "CentralizedTraining",
    "SingleLearner",
)


SPMD_PROTOCOLS = (
    "Asynchronous",
    "Synchronous",
    "SSP",
    "EASGD",
    "GM",
    "FGM",
)


def _codec_seconds(job) -> float:
    """Total transport-codec encode+decode time across every node."""
    total = 0.0
    for hub in job.hub_manager.hubs.values():
        c = getattr(hub.node, "codec", None)
        if c is not None:
            total += c.encode_seconds + c.decode_seconds
    for spoke in job.spokes:
        for net in spoke.nets.values():
            c = getattr(net.node, "codec", None)
            if c is not None:
                total += c.encode_seconds + c.decode_seconds
    return total


def run_one(protocol: str, x, y, parallelism: int, batch: int,
            engine: str = "host", codec: str = "none", chaos: str = "",
            sync_every: int = 4, guard: bool = False, telemetry: str = "",
            events: str = ""):
    import numpy as np

    from omldm_tpu.config import JobConfig
    from omldm_tpu.runtime import StreamJob
    from omldm_tpu.runtime.job import REQUEST_STREAM

    n = x.shape[0]
    job = StreamJob(
        JobConfig(
            parallelism=parallelism, batch_size=batch, test_set_size=64,
            chaos=chaos, telemetry=telemetry, events=events,
        )
    )
    create = {
        "id": 0,
        "request": "Create",
        "learner": {
            "name": "PA",
            "hyperParameters": {"C": 1.0},
            "dataStructure": {"nFeatures": int(x.shape[1])},
        },
        "trainingConfiguration": {"protocol": protocol, "syncEvery": sync_every},
    }
    if codec != "none":
        create["trainingConfiguration"]["comm"] = {"codec": codec}
    if guard:
        create["trainingConfiguration"]["guard"] = True
    if engine == "spmd":
        create["trainingConfiguration"]["engine"] = "spmd"
        create["trainingConfiguration"]["stageChain"] = 4
    job.process_event(REQUEST_STREAM, json.dumps(create))
    op = np.zeros((n,), np.uint8)
    chunk = 8192
    t0 = time.perf_counter()
    for i in range(0, n, chunk):
        job.process_packed_batch(
            x[i : i + chunk], y[i : i + chunk], op[i : i + chunk]
        )
    report = job.terminate()
    elapsed = time.perf_counter() - t0
    timing = job.launch_timing()
    [stats] = report.statistics
    out = {
        "examples_per_sec": round(n / elapsed, 1),
        "score": round(stats.score, 4),
        "fitted": stats.fitted,
        "bytes_shipped": stats.bytes_shipped,
        "bytes_on_wire": stats.bytes_on_wire,
        "models_shipped": stats.models_shipped,
        "num_of_blocks": stats.num_of_blocks,
        # resilience counters (runtime/messages receive windows + hub
        # liveness): zero on fault-free runs, nonzero under chaos — BENCH
        # rounds track chaos overhead through these
        "duplicates_dropped": stats.duplicates_dropped,
        "gaps_resynced": stats.gaps_resynced,
        "quorum_releases": stats.quorum_releases,
        # model-integrity guard counters (trainingConfiguration.guard):
        # zero on guard-off and clean guarded runs, nonzero when the
        # admission / rollback / quarantine / eviction paths engage
        "deltas_rejected": stats.deltas_rejected,
        "rollbacks_performed": stats.rollbacks_performed,
        "records_quarantined": stats.records_quarantined,
        "members_evicted": stats.members_evicted,
        # forecast serving telemetry (runtime/serving.py): served count +
        # enqueue->emit latency percentiles, populated by the per-record
        # path and the adaptive-batching plane alike (zero on the
        # all-training streams of the protocol section)
        "forecasts_served": stats.forecasts_served,
        "serve_latency_p50_ms": round(stats.serve_latency_p50_ms, 3),
        "serve_latency_p99_ms": round(stats.serve_latency_p99_ms, 3),
        "serve_latency_p999_ms": round(stats.serve_latency_p999_ms, 3),
        # model-lifecycle counters (runtime/lifecycle.py): zero with the
        # plane unarmed (the default here); shadow/canary activity and
        # the live version gauge engage under --lifecycle-smoke
        "shadow_scored": stats.shadow_scored,
        "canary_promotions": stats.canary_promotions,
        "canary_rollbacks": stats.canary_rollbacks,
        "active_version": stats.active_version,
        # overload-control counters (runtime/overload.py): zero with the
        # plane unarmed; under pressure the shed/throttle/pressure gauges
        # engage (--overload-smoke gates them)
        "forecasts_shed": stats.forecasts_shed,
        "records_throttled": stats.records_throttled,
        "pressure_level": stats.pressure_level,
        "shed_latency_ms": round(stats.shed_latency_ms, 3),
        # end-of-run queue-depth snapshot (uniform accessors: serving
        # rows, batcher backlog, throttled rows, paused rows) — nonzero
        # values at terminate mean stranded work
        "queue_depths": job.queue_depths(),
        # serving-LAUNCH percentiles (Spoke.serve_timer): per predict
        # dispatch ms on the immediate, batched-plane and gang serve
        # paths — the launch-cost twin of the enqueue->emit latencies
        "serve_launch_p50_ms": round(timing["serve_p50_ms"], 4),
        "serve_launch_p99_ms": round(timing["serve_p99_ms"], 4),
        # transport-codec wall time, surfaced from the Statistics report
        # itself (ISSUE 13 satellite: previously visible only on the
        # codec objects) — zero with codec none
        "codec_encode_seconds": round(stats.codec_encode_seconds, 4),
        "codec_decode_seconds": round(stats.codec_decode_seconds, 4),
        # launch-dispatch percentile gauges from the report (folded only
        # with the telemetry plane armed — they are wall-clock values,
        # and unarmed reports stay reproducible)
        "launch_p50_ms": round(stats.launch_p50_ms, 4),
        "launch_p99_ms": round(stats.launch_p99_ms, 4),
        # flight-recorder counters (runtime/events.py): zero with the
        # plane unarmed; decision events + watchdog alerts engage under
        # --incident-smoke
        "events_recorded": stats.events_recorded,
        "alerts_raised": stats.alerts_raised,
    }
    if telemetry:
        tel = job.telemetry
        out["heartbeats"] = tel.heartbeats_emitted
        out["spans_completed"] = tel.spans.completed
        out["phase_table"] = job.phase_table(elapsed)
    if codec != "none":
        out["codec_seconds"] = round(_codec_seconds(job), 4)
    if job.spmd_bridges:
        [bridge] = job.spmd_bridges.values()
        out["bytes_physical"] = bridge.trainer.collective_bytes_physical()
    return out


def run_multi_tenant_one(n_pipe, x, y, batch, cohort, test=False,
                         sync_every=4, protocol="Asynchronous",
                         shards="off"):
    """One multi-tenant job: N same-spec pipelines on one stream through
    the packed route (parallelism 1 — the co-hosted serving plane),
    cohort gang dispatch on or off, the tenant axis optionally laid
    across the device mesh (``shards``: off / auto / N)."""
    import numpy as np

    from omldm_tpu.config import JobConfig
    from omldm_tpu.runtime import StreamJob
    from omldm_tpu.runtime.job import REQUEST_STREAM

    records = x.shape[0]
    job = StreamJob(
        JobConfig(
            parallelism=1, batch_size=batch, test_set_size=64,
            cohort=cohort, cohort_min=2, test=test, cohort_shards=shards,
        )
    )
    for pid in range(n_pipe):
        job.process_event(REQUEST_STREAM, json.dumps({
            "id": pid,
            "request": "Create",
            "learner": {
                "name": "PA",
                "hyperParameters": {"C": 1.0},
                "dataStructure": {"nFeatures": int(x.shape[1])},
            },
            "trainingConfiguration": {
                "protocol": protocol, "syncEvery": sync_every,
            },
        }))
    op = np.zeros((records,), np.uint8)
    # untimed warmup chunk compiles the (shared) programs; clamped so
    # short streams keep a timed region instead of reporting negative
    # throughput
    chunk = min(8192, max(records // 2, 1))
    job.process_packed_batch(x[:chunk], y[:chunk], op[:chunk])
    t0 = time.perf_counter()
    for i in range(chunk, records, chunk):
        job.process_packed_batch(x[i:i+chunk], y[i:i+chunk], op[i:i+chunk])
    elapsed = time.perf_counter() - t0
    report = job.terminate()
    timing = job.launch_timing()
    # mesh-width attribution (ISSUE 9): the device count, the engaged
    # tenant shard count and the per-shard member placement ride every
    # sweep row so BENCH rounds can attribute throughput to mesh width
    topo = job.tenant_topology()
    timed = records - chunk
    return {
        "pipelines": n_pipe,
        "per_tenant_examples_per_sec": round(timed / elapsed, 1),
        "aggregate_examples_per_sec": round(timed * n_pipe / elapsed, 1),
        "program_launches": sum(
            s.program_launches for s in report.statistics
        ),
        "score": round(report.statistics[0].score, 4),
        "launch_p50_ms": round(timing["p50_ms"], 4),
        "launch_p99_ms": round(timing["p99_ms"], 4),
        "serve_launch_p50_ms": round(timing["serve_p50_ms"], 4),
        "serve_launch_p99_ms": round(timing["serve_p99_ms"], 4),
        "devices": topo["devices"],
        "cohort_shards": topo["cohort_shards"],
        "tenant_placement": topo["placement"],
        "queue_depths": topo["queues"],
    }


def _mt_stream(records, dim=28):
    """The multi-tenant synthetic stream (one definition for the sweep AND
    the CI gate, so they always measure the same task)."""
    import numpy as np

    rng = np.random.RandomState(0)
    w = np.random.RandomState(42).randn(dim)
    x = rng.randn(records, dim).astype(np.float32)
    y = (x @ w > 0).astype(np.float32)
    return x, y


# records for the holdout-scored parity legs: throughput runs use
# test=False (production serving mode, where every score is trivially 0),
# so score parity is checked on separate SHORT test=True runs
MT_PARITY_RECORDS = 16_384


def run_multi_tenant(pipeline_counts, records, batch, test=False):
    """Multi-tenant sweep: per-tenant and aggregate ex/s for N co-hosted
    same-spec pipelines, per-pipeline dispatch (cohort off) vs cohort gang
    dispatch (cohort auto) vs DEVICE-SHARDED cohort dispatch (cohort auto
    + cohort_shards auto — the tenant axis laid across the local mesh),
    with programLaunches, spoke-flush launch percentiles, and the device
    count / tenant placement per run — plus a holdout-scored (test=True)
    parity pair per point, whose scores must match bitwise."""
    import jax

    x, y = _mt_stream(records)
    px, py = _mt_stream(MT_PARITY_RECORDS)

    out = {}
    for n in pipeline_counts:
        per = run_multi_tenant_one(n, x, y, batch, "off", test=test)
        coh = run_multi_tenant_one(n, x, y, batch, "auto", test=test)
        coh["aggregate_speedup_vs_per_pipeline"] = round(
            coh["aggregate_examples_per_sec"]
            / max(per["aggregate_examples_per_sec"], 1e-9), 2
        )
        pp = run_multi_tenant_one(n, px, py, batch, "off", test=True)
        pc = run_multi_tenant_one(n, px, py, batch, "auto", test=True)
        coh["holdout_score"] = pc["score"]
        coh["holdout_score_parity"] = pc["score"] == pp["score"]
        row = {"per_pipeline": per, "cohort": coh}
        if jax.local_device_count() > 1:
            shd = run_multi_tenant_one(
                n, x, y, batch, "auto", test=test, shards="auto"
            )
            shd["aggregate_speedup_vs_per_pipeline"] = round(
                shd["aggregate_examples_per_sec"]
                / max(per["aggregate_examples_per_sec"], 1e-9), 2
            )
            shd["aggregate_speedup_vs_single_device_cohort"] = round(
                shd["aggregate_examples_per_sec"]
                / max(coh["aggregate_examples_per_sec"], 1e-9), 2
            )
            ps = run_multi_tenant_one(
                n, px, py, batch, "auto", test=True, shards="auto"
            )
            shd["holdout_score"] = ps["score"]
            shd["holdout_score_parity"] = ps["score"] == pp["score"]
            row["cohort_sharded"] = shd
        out[str(n)] = row
    return out


def run_shard_protocol_one(protocol, x, y, batch, shards, parallelism=2,
                           n_pipe=3, sync_every=4):
    """One multi-tenant multi-worker job for the shard-smoke protocol
    envelope: N same-spec pipelines under ``protocol`` at parallelism 2,
    cohort gang dispatch with the tenant axis on ``shards`` device
    shards. Returns {pipeline: holdout score}."""
    import numpy as np

    from omldm_tpu.config import JobConfig
    from omldm_tpu.runtime import StreamJob
    from omldm_tpu.runtime.job import REQUEST_STREAM

    job = StreamJob(
        JobConfig(
            parallelism=parallelism, batch_size=batch, test_set_size=64,
            cohort="auto", cohort_min=2, cohort_shards=shards,
        )
    )
    for pid in range(n_pipe):
        job.process_event(REQUEST_STREAM, json.dumps({
            "id": pid,
            "request": "Create",
            "learner": {
                "name": "PA",
                "hyperParameters": {"C": 1.0},
                "dataStructure": {"nFeatures": int(x.shape[1])},
            },
            "trainingConfiguration": {
                "protocol": protocol, "syncEvery": sync_every,
            },
        }))
    op = np.zeros((x.shape[0],), np.uint8)
    for i in range(0, x.shape[0], 2048):
        job.process_packed_batch(x[i:i+2048], y[i:i+2048], op[i:i+2048])
    report = job.terminate()
    return {s.pipeline: round(s.score, 4) for s in report.statistics}


def run_serving_one(n_pipe, x, y, op, batch, serving, cohort="off",
                    test=False, collect_preds=False,
                    protocol="Asynchronous", shards="off"):
    """One forecast-mix job: N same-spec pipelines on one mixed
    train/forecast stream through the packed route (parallelism 1 — the
    co-hosted serving plane), with the adaptive-batching serving config
    ``serving`` (None = the per-record reference path). Reports forecast
    throughput and the serving-latency percentiles from the pipeline
    statistics."""
    import numpy as np

    from omldm_tpu.config import JobConfig
    from omldm_tpu.runtime import StreamJob
    from omldm_tpu.runtime.job import REQUEST_STREAM

    records = x.shape[0]
    job = StreamJob(
        JobConfig(
            parallelism=1, batch_size=batch, test_set_size=64,
            cohort=cohort, cohort_min=2, test=test, cohort_shards=shards,
        )
    )
    for pid in range(n_pipe):
        tc = {"protocol": protocol, "syncEvery": 4}
        if serving is not None:
            tc["serving"] = serving
        job.process_event(REQUEST_STREAM, json.dumps({
            "id": pid,
            "request": "Create",
            "learner": {
                "name": "PA",
                "hyperParameters": {"C": 1.0},
                "dataStructure": {"nFeatures": int(x.shape[1])},
            },
            "trainingConfiguration": tc,
        }))
    # untimed warmup chunk compiles the fit AND the padded predict
    # programs (per pow2 queue bucket), so the timed region measures
    # dispatch, not compilation; clamped so short streams still leave a
    # timed region instead of reporting negative throughput
    chunk = min(4096, max(records // 2, 1))
    job.process_packed_batch(x[:chunk], y[:chunk], op[:chunk])
    t0 = time.perf_counter()
    for i in range(chunk, records, chunk):
        job.process_packed_batch(x[i:i+chunk], y[i:i+chunk], op[i:i+chunk])
    elapsed = time.perf_counter() - t0
    report = job.terminate()
    timing = job.launch_timing()
    n_forecast_timed = int((op[chunk:] != 0).sum())
    stats = report.statistics[0]
    out = {
        "pipelines": n_pipe,
        "records": records,
        "forecast_rows": int((op != 0).sum()),
        "examples_per_sec": round((records - chunk) / elapsed, 1),
        "forecasts_per_sec_per_tenant": round(n_forecast_timed / elapsed, 1),
        "aggregate_forecasts_per_sec": round(
            n_forecast_timed * n_pipe / elapsed, 1
        ),
        "forecasts_served": sum(
            s.forecasts_served for s in report.statistics
        ),
        "serve_latency_p50_ms": round(
            max(s.serve_latency_p50_ms for s in report.statistics), 3
        ),
        "serve_latency_p99_ms": round(
            max(s.serve_latency_p99_ms for s in report.statistics), 3
        ),
        "serve_latency_p999_ms": round(
            max(s.serve_latency_p999_ms for s in report.statistics), 3
        ),
        "serve_launch_p50_ms": round(timing["serve_p50_ms"], 4),
        "serve_launch_p99_ms": round(timing["serve_p99_ms"], 4),
        "program_launches": sum(
            s.program_launches for s in report.statistics
        ),
        "score": round(stats.score, 4),
        "queue_depths": job.queue_depths(),
    }
    if collect_preds:
        preds = {}
        for p in job.predictions:
            preds.setdefault(p.mlp_id, []).append(p.value)
        out["_preds"] = preds
        out["_scores"] = {
            s.pipeline: s.score for s in report.statistics
        }
    return out


# the serve-smoke latency budget: generous enough for a throttled CI box,
# tight enough that a deadline/flush regression (stranded queues) fails
SERVE_SMOKE_DELAY_MS = 250.0
SERVE_SMOKE_BATCH = 128


def run_serving_comparison(mix, records, batch, pipeline_counts=(64,)):
    """The forecast-mix serving sweep: per-record serving vs the adaptive-
    batching plane (exact and relaxed staleness) at each tenant count, on
    one shared forecast-heavy stream (benchmarks/streams.py) — measured on
    BOTH serving topologies: solo per-tenant dispatch (cohort off, the
    reference's serving semantics) and cohort gang dispatch (cohort auto,
    where PR6's cross-tenant gang already amortizes launches and the
    plane's remaining win is batching across stream positions)."""
    from benchmarks.streams import forecast_stream

    x, y, op = forecast_stream(records, mix=mix)
    serving_exact = {"maxBatch": SERVE_SMOKE_BATCH,
                     "maxDelayMs": SERVE_SMOKE_DELAY_MS,
                     "staleness": "exact"}
    serving_relaxed = {**serving_exact, "staleness": "relaxed",
                       "staleChunks": 4}
    out = {"forecast_mix": mix}
    for n in pipeline_counts:
        rows = {}
        for label, cohort in (("solo", "off"), ("cohort", "auto")):
            per = run_serving_one(n, x, y, op, batch, None, cohort=cohort)
            exact = run_serving_one(
                n, x, y, op, batch, serving_exact, cohort=cohort
            )
            relaxed = run_serving_one(
                n, x, y, op, batch, serving_relaxed, cohort=cohort
            )
            for row in (exact, relaxed):
                row["forecast_speedup_vs_per_record"] = round(
                    row["aggregate_forecasts_per_sec"]
                    / max(per["aggregate_forecasts_per_sec"], 1e-9), 2
                )
            rows[label] = {
                "per_record": per,
                "serving_exact": exact,
                "serving_relaxed": relaxed,
            }
        out[str(n)] = rows
    return out


# the overload-smoke operating point (ISSUE 10): 64 co-hosted tenants on
# a 50/50 train/forecast per-record stream, a 10x forecast burst flooding
# tenant 0 through the middle half of the stream, serving armed with a
# 500 ms delay budget (a fan-out forecast fills all 64 solo queues, so a
# fill cycle dispatches 64 predict launches back to back — a single-core
# CI box needs the headroom; tight enough that stranded queues or a
# burst-induced latency collapse still fails), and the controller tuned
# so the burst traverses the WHOLE ladder (ELEVATED throttling ->
# CRITICAL shedding) and decays back to OK inside the post-burst tail
OVERLOAD_SPEC = "window=32,share=2,hotHigh=24,hotCritical=48,cool=24"
OVERLOAD_SERVING = {"maxBatch": 64, "maxDelayMs": 500.0}
OVERLOAD_BURST = 10


def _overload_chaos(records: int) -> str:
    # burst window in FORECAST records (mix 0.5 => records/2 forecasts):
    # the middle half floods, leaving a clean ramp and a decay tail
    n_fore = records // 2
    return (
        f"seed=7,burst={OVERLOAD_BURST},burstFrom={n_fore // 4},"
        f"burstLen={n_fore // 2},hotTenant=0"
    )


def run_overload_one(n_pipe, x, y, burst, records=None, batch=256,
                     overload=OVERLOAD_SPEC, serving=OVERLOAD_SERVING):
    """One overload job: N same-spec pipelines fed the PER-RECORD route
    (tenant-addressed burst clones need record-level routing) with a
    50/50 train/forecast mix; ``burst`` arms the seeded hot-tenant
    injector. Reports hot/healthy split of the serving + shed counters."""
    import numpy as np

    from omldm_tpu.api.data import DataInstance, FORECASTING
    from omldm_tpu.config import JobConfig
    from omldm_tpu.runtime import StreamJob
    from omldm_tpu.runtime.job import (
        FORECASTING_STREAM,
        REQUEST_STREAM,
        TRAINING_STREAM,
    )

    records = records or x.shape[0]
    # cohort off: the smoke measures the overload plane on SOLO per-tenant
    # dispatch (the reference's serving semantics; the cohort axis has its
    # own gates), and the per-event gang bookkeeping would otherwise tax
    # every injected burst clone
    job = StreamJob(JobConfig(
        parallelism=1, batch_size=batch, test_set_size=64, test=False,
        cohort="off", overload=overload, serving="",
        chaos=_overload_chaos(records) if burst else "",
    ))
    for pid in range(n_pipe):
        job.process_event(REQUEST_STREAM, json.dumps({
            "id": pid, "request": "Create",
            "learner": {
                "name": "PA", "hyperParameters": {"C": 1.0},
                "dataStructure": {"nFeatures": int(x.shape[1])},
            },
            "trainingConfiguration": {
                "protocol": "Asynchronous", "syncEvery": 4,
                "serving": serving,
            },
        }))
    # untimed warmup (compiles fit + padded predict programs)
    warm = min(512, records // 4)
    for i in range(warm):
        if i % 2 == 0:
            job.process_event(FORECASTING_STREAM, DataInstance(
                numerical_features=x[i].tolist(), operation=FORECASTING))
        else:
            job.process_event(TRAINING_STREAM, DataInstance(
                numerical_features=x[i].tolist(), target=float(y[i])))
    t0 = time.perf_counter()
    for i in range(warm, records):
        if i % 2 == 0:
            job.process_event(FORECASTING_STREAM, DataInstance(
                numerical_features=x[i].tolist(), operation=FORECASTING))
        else:
            job.process_event(TRAINING_STREAM, DataInstance(
                numerical_features=x[i].tolist(), target=float(y[i])))
    elapsed = time.perf_counter() - t0
    level_after_feed = job.overload_level()
    report = job.terminate()
    by_pipe = {s.pipeline: s for s in report.statistics}
    hot = by_pipe[0]
    healthy = [s for p, s in by_pipe.items() if p != 0]
    healthy_served = sum(s.forecasts_served for s in healthy)
    return {
        "pipelines": n_pipe,
        "records": records,
        "burst": bool(burst),
        "elapsed_s": round(elapsed, 3),
        "healthy_forecasts_served": healthy_served,
        "healthy_forecasts_per_sec": round(healthy_served / elapsed, 1),
        "healthy_serve_p99_ms": round(
            max((s.serve_latency_p99_ms for s in healthy), default=0.0), 3
        ),
        "healthy_shed": sum(s.forecasts_shed for s in healthy),
        "hot_served": hot.forecasts_served,
        "hot_shed": hot.forecasts_shed,
        "hot_throttled": hot.records_throttled,
        "pressure_peak": max(s.pressure_level for s in by_pipe.values()),
        "level_after_feed": level_after_feed,
        "shed_latency_ms": round(
            max(s.shed_latency_ms for s in by_pipe.values()), 3
        ),
        "dead_letter_reasons": dict(job.dead_letter.by_reason),
        "queue_depths": job.queue_depths(),
    }


# the lifecycle-smoke operating point (ISSUE 11): one lifecycle-armed
# pipeline on a 50/50 per-record train/forecast stream; the canary ramps
# 0 -> 50% (step 0.125 every 64 canary-era forecasts), auto-promotion
# needs 128 canary serves at the full ramp + 2 healthy shadow evals
LIFECYCLE_SPEC = {
    "rampFrom": 0.0, "rampTo": 0.5, "rampEvery": 64, "rampStep": 0.125,
    "promoteAfter": 128, "shadowEvery": 8, "minShadowEvals": 2,
    "scoreEnvelope": 0.05, "seed": 7,
}


def run_lifecycle_one(x, y, mode, lifecycle=None, poison_at=1024):
    """One lifecycle job on a 50/50 per-record stream. ``mode``:

    - ``"off"``: lifecycle unarmed — the pre-plane reference leg;
    - ``"healthy"``: Shadow + Promote a healthy candidate (same learner,
      softer C) and let the ramp auto-promote it;
    - ``"hold"``: same canary but promoteAfter beyond the stream — the
      ramp serves the whole run, pinning baseline bitwise identity;
    - ``"poison"``: Shadow + Promote, then seed the candidate's params
      with an exploding vector at event ``poison_at`` — the candidate's
      guard must trip and auto-roll the canary back.

    Returns emitted predictions (value, version) in stream order plus the
    registry view and folded statistics."""
    import numpy as np

    from omldm_tpu.api.data import DataInstance, FORECASTING
    from omldm_tpu.config import JobConfig
    from omldm_tpu.runtime import StreamJob
    from omldm_tpu.runtime.job import (
        FORECASTING_STREAM,
        REQUEST_STREAM,
        TRAINING_STREAM,
    )

    records = x.shape[0]
    spec = dict(lifecycle or LIFECYCLE_SPEC)
    if mode == "hold":
        spec["promoteAfter"] = 10 * records
    job = StreamJob(JobConfig(
        parallelism=1, batch_size=64, test_set_size=64, test=True,
    ))
    tc = {"protocol": "Asynchronous", "syncEvery": 4}
    if mode != "off":
        tc["lifecycle"] = spec
    job.process_event(REQUEST_STREAM, json.dumps({
        "id": 0, "request": "Create",
        "learner": {
            "name": "PA", "hyperParameters": {"C": 1.0},
            "dataStructure": {"nFeatures": int(x.shape[1])},
        },
        "trainingConfiguration": tc,
    }))
    if mode != "off":
        job.process_event(REQUEST_STREAM, json.dumps({
            "id": 0, "request": "Shadow",
            "learner": {
                "name": "PA", "hyperParameters": {"C": 0.5},
                "dataStructure": {"nFeatures": int(x.shape[1])},
            },
        }))
        job.process_event(REQUEST_STREAM, json.dumps(
            {"id": 0, "request": "Promote"}
        ))
    net = job.spokes[0].nets[0]
    for i in range(records):
        if mode == "poison" and i == poison_at:
            entry = net.lifecycle.candidate_entry
            if entry is not None and entry.pipeline is not None:
                flat, _ = entry.pipeline.get_flat_params()
                entry.pipeline.set_flat_params(
                    np.full_like(flat, 1.0e9)
                )
        if i % 2 == 0:
            job.process_event(FORECASTING_STREAM, DataInstance(
                numerical_features=x[i].tolist(), operation=FORECASTING))
        else:
            job.process_event(TRAINING_STREAM, DataInstance(
                numerical_features=x[i].tolist(), target=float(y[i])))
    lc = net.lifecycle.describe() if net.lifecycle is not None else None
    preds = [(p.value, p.version) for p in job.predictions]
    report = job.terminate()
    [stats] = report.statistics
    return {
        "mode": mode,
        "predictions": preds,
        "lifecycle": lc,
        "score": round(stats.score, 4),
        "shadow_scored": stats.shadow_scored,
        "canary_promotions": stats.canary_promotions,
        "canary_rollbacks": stats.canary_rollbacks,
        "active_version": stats.active_version,
        "forecasts_served": stats.forecasts_served,
    }


# codecs swept by --codec sweep, and the host protocols the codec section
# compares (the model-shipping protocols; GM/FGM traffic is mostly votes)
CODEC_SWEEP = ("none", "fp16", "int8", "topk")
CODEC_PROTOCOLS = ("Asynchronous", "Synchronous", "EASGD", "GM")

# the acceptance chaos operating point (ISSUE 4): 5% drop, 5% dup,
# reorder window 4 on both directions of the hub<->spoke bridge
DEFAULT_CHAOS = "seed=7,drop=0.05,dup=0.05,reorder=0.1,window=4"


def run_chaos_resilience(protocols, records, parallelism, batch,
                         chaos=DEFAULT_CHAOS, dim=28):
    """Each protocol on the same stream, fault-free vs under the seeded
    chaos channel: final-score delta (the loss envelope) plus the
    resilience counters the reliable channel accumulated while repairing
    the damage."""
    import numpy as np

    rng = np.random.RandomState(11)
    w = np.random.RandomState(42).randn(dim)
    x = rng.randn(records, dim).astype(np.float32)
    y = (x @ w > 0).astype(np.float32)

    out = {"chaos_spec": chaos, "protocols": {}}
    for protocol in protocols:
        # syncEvery 1: the chaos section measures CHANNEL behavior, so it
        # wants message volume, not the codec section's params economy
        clean = run_one(protocol, x, y, parallelism, batch, sync_every=1)
        chaotic = run_one(
            protocol, x, y, parallelism, batch, chaos=chaos, sync_every=1
        )
        chaotic["score_delta_vs_clean"] = round(
            chaotic["score"] - clean["score"], 4
        )
        chaotic["overhead_examples_per_sec"] = round(
            clean["examples_per_sec"]
            / max(chaotic["examples_per_sec"], 1e-9),
            2,
        )
        out["protocols"][protocol] = {
            "clean_score": clean["score"],
            **chaotic,
        }
    return out


def run_codec_comparison(codecs, records, parallelism, batch,
                         protocols=CODEC_PROTOCOLS, dim=256):
    """Sweep transport codecs over a params-dominated stream: per
    (protocol, codec) bytes-on-wire, wire reduction vs the uncompressed
    run, codec CPU seconds, throughput and final score."""
    import numpy as np

    rng = np.random.RandomState(7)
    w = np.random.RandomState(43).randn(dim)
    x = rng.randn(records, dim).astype(np.float32)
    y = (x @ w > 0).astype(np.float32)

    out = {}
    for protocol in protocols:
        rows = {}
        for codec in codecs:
            r = run_one(protocol, x, y, parallelism, batch, codec=codec)
            rows[codec] = r
        base = max(rows.get("none", {}).get("bytes_on_wire", 0), 1)
        for codec, r in rows.items():
            if codec != "none":
                r["wire_reduction_vs_none"] = round(
                    base / max(r["bytes_on_wire"], 1), 2
                )
                r["score_delta_vs_none"] = round(
                    r["score"] - rows["none"]["score"], 4
                )
        out[protocol] = rows
    return out


def run_distributed_route(codecs, dim=256, steps=24, batch=32):
    """The multi-process model-exchange route: the SPMDTrainer collective
    sync that carries the distributed job's hub<->spoke traffic (the role
    of the reference's psMessages Kafka loop). Measures bytes-on-wire per
    codec on an 8-worker mesh and the parameter drift vs uncompressed."""
    import numpy as np

    from omldm_tpu.api.requests import LearnerSpec, TrainingConfiguration
    from omldm_tpu.parallel.mesh import make_mesh
    from omldm_tpu.parallel.spmd import SPMDTrainer

    mesh = make_mesh(dp=8, hub=1)
    w = np.random.RandomState(44).randn(dim)
    r = np.random.RandomState(5)
    batches = []
    for _ in range(steps):
        x = r.randn(8, batch, dim).astype(np.float32)
        batches.append((x, (x @ w > 0).astype(np.float32),
                        np.ones((8, batch), np.float32)))

    def run(codec):
        extra = {"syncEvery": 4}
        if codec != "none":
            extra["comm"] = {"codec": codec}
        t = SPMDTrainer(
            LearnerSpec("PA", hyper_parameters={"C": 1.0}), dim=dim,
            protocol="Synchronous", mesh=mesh,
            training_configuration=TrainingConfiguration(
                protocol="Synchronous", extra=extra
            ),
        )
        t0 = time.perf_counter()
        for x, y, m in batches:
            t.step(x, y, m)
        elapsed = time.perf_counter() - t0
        return t, elapsed

    out = {}
    base_t, base_s = run("none")
    base_wire = base_t.bytes_on_wire()
    base_flat = base_t.global_flat_params()
    out["none"] = {
        "bytes_on_wire": base_wire,
        "bytes_shipped": base_t.bytes_shipped(),
        "sync_seconds": round(base_s, 3),
    }
    for codec in codecs:
        if codec in ("none", "topk"):
            continue  # topk is host-plane only (dense allreduce operands)
        t, secs = run(codec)
        drift = float(
            np.linalg.norm(t.global_flat_params() - base_flat)
            / max(np.linalg.norm(base_flat), 1e-9)
        )
        out[codec] = {
            "bytes_on_wire": t.bytes_on_wire(),
            "wire_reduction_vs_none": round(
                base_wire / max(t.bytes_on_wire(), 1), 2
            ),
            "param_drift_rel": round(drift, 4),
            "sync_seconds": round(secs, 3),
        }
    return out


# the incident-smoke operating point (ISSUE 14): a guard-armed supervised
# in-process run with ONE seeded poisoned worker (its params explode at a
# fixed chunk, syncEvery=1 ships them before the worker-side guard can
# roll back) and a one-shot injected worker death a few chunks later. The
# run must leave ONE merged incident bundle whose fleet timeline carries
# the rejection -> strike -> retire -> restart chain in causal order on
# the transport stamps, at least one kind="alert" record on the
# performance sink, and arming the recorder on a clean stream must cost
# <= 3% (paired trials) with BITWISE-equal scores.
INCIDENT_RECORDS = 16_000
INCIDENT_EVENTS_SPEC = "watchdogEvery=2048,shedHigh=1"


def run_incident_smoke() -> None:
    import shutil
    import tempfile

    import numpy as np

    from omldm_tpu.config import JobConfig
    from omldm_tpu.runtime import StreamJob
    from omldm_tpu.runtime.job import PACKED_STREAM, REQUEST_STREAM
    from omldm_tpu.runtime.recovery import (
        FaultInjector,
        JobSupervisor,
        replayable,
    )

    records = INCIDENT_RECORDS
    dim, par, batch, chunk = 28, 2, 64, 512
    rng = np.random.RandomState(11)
    w = np.random.RandomState(42).randn(dim)
    gx = rng.randn(records, dim).astype(np.float32)
    gy = (gx @ w > 0).astype(np.float32)
    op = np.zeros((records,), np.uint8)
    create_line = json.dumps({
        "id": 0, "request": "Create",
        "learner": {"name": "PA", "hyperParameters": {"C": 1.0},
                    "dataStructure": {"nFeatures": dim}},
        "trainingConfiguration": {
            "protocol": "Asynchronous", "syncEvery": 1,
            "guard": {"maxStrikes": 1}, "comm": {"reliable": True},
        },
    })
    failures = []
    out = {}

    # --- paired clean legs: overhead + bitwise score identity ------------
    run_one("Asynchronous", gx[:2048], gy[:2048], par, batch, guard=True)
    run_one("Asynchronous", gx[:2048], gy[:2048], par, batch, guard=True,
            events=INCIDENT_EVENTS_SPEC)
    # 4 paired back-to-back trials, best pair (the guard/telemetry-smoke
    # rule: this box is share-throttled ±25%, and throttle noise only
    # ever inflates a pair's ratio, so the minimum over pairs is the
    # tightest available estimate of the systematic recorder overhead)
    pair_ratios = []
    clean_off = clean_on = None
    for _trial in range(4):
        r_off = run_one("Asynchronous", gx, gy, par, batch, guard=True)
        r_on = run_one("Asynchronous", gx, gy, par, batch, guard=True,
                       events=INCIDENT_EVENTS_SPEC)
        pair_ratios.append(
            r_off["examples_per_sec"] / max(r_on["examples_per_sec"], 1e-9)
        )
        if clean_off is None or (
            r_off["examples_per_sec"] > clean_off["examples_per_sec"]
        ):
            clean_off = r_off
        if clean_on is None or (
            r_on["examples_per_sec"] > clean_on["examples_per_sec"]
        ):
            clean_on = r_on
    overhead = min(pair_ratios)
    if clean_on["score"] != clean_off["score"]:
        failures.append(
            f"events-armed clean score {clean_on['score']} != unarmed "
            f"{clean_off['score']} (bitwise identity broken)"
        )
    if overhead > 1.03:
        failures.append(
            f"events-armed clean throughput {overhead:.3f}x slower than "
            "unarmed (> 3% bar)"
        )
    if clean_on["events_recorded"] < 1:
        failures.append("armed clean leg recorded no events at all")

    # --- the supervised incident leg -------------------------------------
    tmp = tempfile.mkdtemp(prefix="omldm-incident-smoke-")
    perf = []
    try:
        job = StreamJob(
            JobConfig(
                parallelism=par, batch_size=batch, test_set_size=64,
                events=INCIDENT_EVENTS_SPEC, blackbox_path=tmp,
            ),
            on_performance=perf.append,
        )
        holder = {"job": job}
        poisoned = [False]
        poison_chunk, death_rows = 6, 2500

        def make_events():
            yield (REQUEST_STREAM, create_line)
            for idx, i in enumerate(range(0, records, chunk)):
                if idx == poison_chunk and not poisoned[0]:
                    # the seeded poisoned worker: spoke 1's params explode
                    # right before this chunk, so its next syncEvery=1
                    # push ships the poison to the hub's admission gate
                    poisoned[0] = True
                    net = holder["job"].spokes[1].nets[0]
                    flat, _ = net.pipeline.get_flat_params()
                    net.pipeline.set_flat_params(np.full_like(flat, 1e9))
                yield (
                    PACKED_STREAM,
                    (gx[i:i + chunk], gy[i:i + chunk], op[i:i + chunk]),
                )

        injector = FaultInjector()
        injector.arm(job, worker_id=0, after_records=death_rows)
        sup = JobSupervisor(
            job, replayable(make_events), max_restarts=1,
            on_failure=lambda rec: holder.update(job=sup.job),
        )
        report = sup.run()
        out["incident"] = {
            "worker_death_fired": injector.fired,
            "restarts": len(sup.failures),
            "bundle": sup.bundle_path,
            "alerts_on_sink": sum(1 for p in perf if p.kind == "alert"),
            "final_score": (
                round(report.statistics[0].score, 4)
                if report is not None and report.statistics else None
            ),
        }
        if injector.fired != 1 or len(sup.failures) != 1:
            failures.append(
                "injected worker death did not produce exactly one "
                f"supervised restart (fired={injector.fired}, "
                f"restarts={len(sup.failures)})"
            )
        if not any(p.kind == "alert" for p in perf):
            failures.append(
                "no kind=\"alert\" record reached the performance sink"
            )
        if sup.bundle_path is None:
            failures.append("supervisor wrote no merged incident bundle")
        else:
            bundle = json.load(open(sup.bundle_path))
            timeline = bundle["timeline"]
            kinds = [e["kind"] for e in timeline]
            out["incident"]["by_kind"] = bundle["byKind"]

            def first(kind, pred=lambda e: True):
                for i, e in enumerate(timeline):
                    if e["kind"] == kind and pred(e):
                        return i
                return None

            i_rej = first(
                "delta_rejected", lambda e: e.get("strikes", 0) >= 1
            )
            i_ret = first(
                "worker_retired", lambda e: e["cause"] == "guard_strikes"
            )
            i_restart = first("restart")
            if i_rej is None or i_ret is None or i_restart is None:
                failures.append(
                    "bundle missing the rejection/strike/retire/restart "
                    f"chain (kinds present: {sorted(set(kinds))})"
                )
            elif not (i_rej < i_ret < i_restart):
                failures.append(
                    "bundle chain out of causal order: rejection@"
                    f"{i_rej}, retire@{i_ret}, restart@{i_restart}"
                )
            if i_rej is not None and timeline[i_rej].get("stamp") is None:
                failures.append(
                    "rejection event carries no transport stamp"
                )
            # stamped events must read in seq order PER SENDER STREAM
            # (merge_timeline's contract: independent seq counters —
            # other workers' channels, other hub shards — are never
            # cross-compared, so a pooled global assertion would be
            # stricter than the guarantee)
            per_stream: dict = {}
            for e in timeline:
                if e.get("stamp") and e["stamp"][0] == 0:
                    key = (e.get("worker"), e.get("hub"),
                           e.get("side", ""))
                    per_stream.setdefault(key, []).append(e["stamp"][1])
            for key, seqs in per_stream.items():
                if seqs != sorted(seqs):
                    failures.append(
                        f"stamped stream {key} not merge-sorted by "
                        f"seq: {seqs}"
                    )
            if "alert" not in kinds:
                failures.append("bundle carries no alert event")
    finally:
        shutil.rmtree(tmp, ignore_errors=True)

    print(json.dumps({
        "config": "protocol_comparison_incident_smoke",
        "records": records,
        "clean_events_off": clean_off,
        "clean_events_on": clean_on,
        "events_overhead_x": round(overhead, 3),
        **out,
        "failures": failures,
    }))
    if failures:
        sys.exit(1)


# the autoscale-smoke operating point (ISSUE 12): a preloaded burst on
# the file-backed Kafka broker, consumed by a SUPERVISED 1-process fleet
# with pressure-driven autoscaling armed. The burst outpaces the
# backlogCritical threshold every poll window, so the fleet sustains
# CRITICAL, scales out to 2 processes (checkpoint -> relaunch ->
# restore-with-rescale), drains, sustains OK, and scales back in to the
# floor — two full elastic transitions inside one CI run.
AUTOSCALE_ROWS = 8_000
AUTOSCALE_FORE_EVERY = 20


def run_autoscale_smoke() -> None:
    """CI gate (ISSUE 12 acceptance): the supervised fleet must scale
    out under a seeded sustained burst, lose ZERO records across the
    restarts (every training row fitted or held out, every forecast
    served exactly once — the EMITTED/output dedupe contract), and
    return to the floor process count after the burst drains. NONZERO
    EXIT otherwise."""
    import subprocess
    import tempfile

    import numpy as np

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    tests = os.path.join(repo, "tests")
    sys.path.insert(0, tests)
    import fskafka

    tmp = tempfile.mkdtemp(prefix="omldm-autoscale-smoke-")
    broker = os.path.join(tmp, "broker")
    os.environ["FSKAFKA_DIR"] = broker
    n_fore = 0
    try:
        rng = np.random.RandomState(0)
        w = rng.randn(12)
        for i in range(AUTOSCALE_ROWS):
            x = np.round(rng.randn(12), 6)
            if i % AUTOSCALE_FORE_EVERY == 0:
                n_fore += 1
                line = json.dumps({
                    "numericalFeatures": [float(v) for v in x],
                    "operation": "forecasting",
                })
            else:
                line = json.dumps({
                    "numericalFeatures": [float(v) for v in x],
                    "target": float(x @ w > 0),
                    "operation": "training",
                })
            fskafka.append("trainingData", line, partition=i % 4)
        fskafka.append("requests", json.dumps({
            "id": 0, "request": "Create",
            "learner": {"name": "PA", "hyperParameters": {"C": 1.0},
                        "dataStructure": {"nFeatures": 12}},
            "trainingConfiguration": {
                "protocol": "Synchronous", "syncEvery": 1,
            },
        }))
    finally:
        os.environ.pop("FSKAFKA_DIR", None)

    boot = (
        "import sys; sys.path.insert(0, {t!r}); "
        "import fskafka; fskafka.install(); "
        "from omldm_tpu.runtime.distributed_job import run_distributed; "
        "sys.exit(run_distributed(sys.argv[1:]))"
    ).format(t=tests)
    perf = os.path.join(tmp, "perf.jsonl")
    preds = os.path.join(tmp, "preds.jsonl")
    env = dict(os.environ)
    # one CPU device per worker process; the parent's 8-device XLA flag
    # must not leak into the fleet
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["FSKAFKA_DIR"] = broker
    t0 = time.perf_counter()
    out = subprocess.run(
        [sys.executable, "-m", "omldm_tpu.runtime.distributed_job",
         "--supervise", "true", "--processes", "1",
         "--autoscale", "true", "--minProcesses", "1",
         "--maxProcesses", "2",
         "--scaleUpAfterMs", "200", "--scaleDownAfterMs", "1200",
         "--scaleCooldownMs", "400",
         "--overload", "backlogHigh=40,backlogCritical=80",
         "--kafkaBrokers", "fs://local", "--workerBoot", boot,
         "--checkpointDir", os.path.join(tmp, "ckpts"),
         "--checkpointEvery", "8",
         "--chunkRows", "100", "--kafkaPollMs", "50",
         "--idleWindows", "60",
         "--batchSize", "64", "--testSetSize", "32",
         "--restartAttempts", "2", "--restartDelayMs", "50",
         "--performanceOut", perf, "--predictionsOut", preds],
        cwd=repo, env=env, capture_output=True, text=True, timeout=600,
    )
    wall_s = time.perf_counter() - t0
    err = out.stderr
    failures = []
    if out.returncode != 0:
        failures.append(
            f"supervised fleet exited {out.returncode}: {err[-2000:]}"
        )
    if "rescaling fleet 1 -> 2" not in err:
        failures.append("the burst never drove a scale-OUT decision")
    if "rescale-restore: redistributing a 1-process snapshot" not in err:
        failures.append("scale-out relaunch did not restore-with-rescale")
    if "rescaling fleet 2 -> 1" not in err:
        failures.append(
            "the fleet never scaled back IN after the burst drained"
        )
    report = {}
    stats = {}
    if not failures:
        report = json.loads(open(perf).read().strip())
        [stats] = report["statistics"]
        n_train = AUTOSCALE_ROWS - n_fore
        conserved = stats["fitted"] + report["holdout"]["0"]
        if conserved != n_train:
            failures.append(
                f"records lost across the restarts: fitted+holdout "
                f"{conserved} != {n_train} training rows"
            )
        payloads = [json.loads(l) for l in open(preds)]
        if len(payloads) != n_fore:
            failures.append(
                f"forecasts not served exactly once: {len(payloads)} "
                f"outputs for {n_fore} forecasts (output dedupe broken)"
            )
        if report.get("rescalesPerformed") != 2:
            failures.append(
                f"rescalesPerformed {report.get('rescalesPerformed')} != 2"
            )
        if report.get("fleetProcesses") != 1:
            failures.append(
                "fleet did not return to the floor process count "
                f"(fleetProcesses {report.get('fleetProcesses')})"
            )
    print(json.dumps({
        "config": "protocol_comparison_autoscale_smoke",
        "rows": AUTOSCALE_ROWS,
        "forecasts": n_fore,
        "wall_s": round(wall_s, 1),
        "rescales": report.get("rescalesPerformed"),
        "fleet_processes": report.get("fleetProcesses"),
        "fitted": stats.get("fitted"),
        "score": stats.get("score"),
        "failures": failures,
    }))
    if failures:
        sys.exit(1)


# the selfheal-smoke operating point (ISSUE 15): a supervised 2-process
# fleet with slot strikes + the collective hang watchdog armed; a seeded
# SIGSTOP freezes worker 1 at a fixed chunk, the survivor exits HANG_EXIT,
# the supervisor blames the silent slot, shrinks to the survivor via
# restore-with-rescale, probes back to full width once quiet, and heals —
# plus an unarmed-vs-armed-idle identity pair proving the new knobs add
# nothing to the data path when nothing fires.
SELFHEAL_ROWS = 6_000
SELFHEAL_FORE_EVERY = 20
SELFHEAL_IDENTITY_ROWS = 2_000


def _selfheal_identity_pair(tmp: str, env: dict, repo: str) -> list:
    """Two 1-process file-mode runs of the SAME stream — all self-heal
    knobs unset vs armed-but-idle (watchdog + fault state dir, no fault):
    predictions and the report's score/fitted must match BITWISE."""
    import subprocess

    import numpy as np

    rng = np.random.RandomState(1)
    w = rng.randn(12)
    data = os.path.join(tmp, "ident.jsonl")
    with open(data, "w") as f:
        for i in range(SELFHEAL_IDENTITY_ROWS):
            x = np.round(rng.randn(12), 6)
            if i % SELFHEAL_FORE_EVERY == 0:
                f.write(json.dumps({
                    "numericalFeatures": [float(v) for v in x],
                    "operation": "forecasting",
                }) + "\n")
            else:
                f.write(json.dumps({
                    "numericalFeatures": [float(v) for v in x],
                    "target": float(x @ w > 0), "operation": "training",
                }) + "\n")
    reqs = os.path.join(tmp, "ident_reqs.jsonl")
    with open(reqs, "w") as f:
        f.write(json.dumps({
            "id": 0, "request": "Create",
            "learner": {"name": "PA", "hyperParameters": {"C": 1.0},
                        "dataStructure": {"nFeatures": 12}},
            "trainingConfiguration": {
                "protocol": "Synchronous", "syncEvery": 1,
            },
        }) + "\n")
    failures = []
    outs = {}
    for leg, extra in (
        ("unarmed", []),
        ("armed_idle", [
            "--collectiveTimeoutMs", "60000",
            "--faultStateDir", os.path.join(tmp, "ident_fault"),
        ]),
    ):
        perf = os.path.join(tmp, f"ident_{leg}_perf.jsonl")
        preds = os.path.join(tmp, f"ident_{leg}_preds.jsonl")
        out = subprocess.run(
            [sys.executable, "-m", "omldm_tpu.runtime.distributed_job",
             "--processes", "1",
             "--trainingData", data, "--requests", reqs,
             "--chunkRows", "200", "--batchSize", "64",
             "--testSetSize", "32",
             "--performanceOut", perf, "--predictionsOut", preds]
            + extra,
            cwd=repo, env=env, capture_output=True, text=True, timeout=300,
        )
        if out.returncode != 0:
            failures.append(
                f"identity leg {leg} exited {out.returncode}: "
                f"{out.stderr[-1500:]}"
            )
            return failures
        report = json.loads(open(perf).read().strip())
        [stats] = report["statistics"]
        outs[leg] = (
            open(preds).read(), stats["score"], stats["fitted"],
        )
    if outs["unarmed"] != outs["armed_idle"]:
        failures.append(
            "armed-but-idle self-heal knobs changed the data path: "
            f"unarmed (score {outs['unarmed'][1]}, fitted "
            f"{outs['unarmed'][2]}) != armed (score "
            f"{outs['armed_idle'][1]}, fitted {outs['armed_idle'][2]}) "
            "or predictions differ"
        )
    return failures


def run_selfheal_smoke() -> None:
    """CI gate (ISSUE 15 acceptance): a SIGSTOP'd worker must be blamed
    (survivors exit HANG_EXIT within --collectiveTimeoutMs — no wedged
    collective), the fleet must shrink to the survivors via restore-with-
    rescale with fitted+holdout exactly equal to the training rows and
    every forecast served exactly once, a later probe must restore the
    full width and heal, the run's bundles must carry the
    classify -> strike -> degrade -> probe chain in causal order, and the
    new knobs must be bit-identical no-ops while nothing fires. NONZERO
    EXIT otherwise."""
    import subprocess
    import tempfile

    import numpy as np

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    tests = os.path.join(repo, "tests")
    sys.path.insert(0, tests)
    import fskafka

    tmp = tempfile.mkdtemp(prefix="omldm-selfheal-smoke-")
    broker = os.path.join(tmp, "broker")
    os.environ["FSKAFKA_DIR"] = broker
    n_fore = 0
    try:
        rng = np.random.RandomState(0)
        w = rng.randn(12)
        for i in range(SELFHEAL_ROWS):
            x = np.round(rng.randn(12), 6)
            if i % SELFHEAL_FORE_EVERY == 0:
                n_fore += 1
                line = json.dumps({
                    "numericalFeatures": [float(v) for v in x],
                    "operation": "forecasting",
                })
            else:
                line = json.dumps({
                    "numericalFeatures": [float(v) for v in x],
                    "target": float(x @ w > 0),
                    "operation": "training",
                })
            fskafka.append("trainingData", line, partition=i % 4)
        fskafka.append("requests", json.dumps({
            "id": 0, "request": "Create",
            "learner": {"name": "PA", "hyperParameters": {"C": 1.0},
                        "dataStructure": {"nFeatures": 12}},
            "trainingConfiguration": {
                "protocol": "Synchronous", "syncEvery": 1,
            },
        }))
    finally:
        os.environ.pop("FSKAFKA_DIR", None)

    boot = (
        "import sys; sys.path.insert(0, {t!r}); "
        "import fskafka; fskafka.install(); "
        "from omldm_tpu.runtime.distributed_job import run_distributed; "
        "sys.exit(run_distributed(sys.argv[1:]))"
    ).format(t=tests)
    perf = os.path.join(tmp, "perf.jsonl")
    preds = os.path.join(tmp, "preds.jsonl")
    blackbox = os.path.join(tmp, "blackbox")
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["FSKAFKA_DIR"] = broker
    t0 = time.perf_counter()
    out = subprocess.run(
        [sys.executable, "-m", "omldm_tpu.runtime.distributed_job",
         "--supervise", "true", "--processes", "2",
         "--slotStrikes", "1", "--minProcesses", "1",
         "--probeAfterMs", "2000", "--probeWindowMs", "1500",
         "--collectiveTimeoutMs", "5000", "--killDeadlineMs", "1000",
         "--hangProcess", "1", "--hangAfterChunks", "6",
         "--faultStateDir", os.path.join(tmp, "fault"),
         "--flightRecorder", "on", "--blackboxPath", blackbox,
         "--kafkaBrokers", "fs://local", "--workerBoot", boot,
         "--checkpointDir", os.path.join(tmp, "ckpts"),
         "--checkpointEvery", "2",
         "--chunkRows", "100", "--kafkaPollMs", "50",
         "--idleWindows", "60",
         "--batchSize", "64", "--testSetSize", "32",
         "--restartAttempts", "2", "--restartDelayMs", "50",
         "--performanceOut", perf, "--predictionsOut", preds],
        cwd=repo, env=env, capture_output=True, text=True, timeout=600,
    )
    wall_s = time.perf_counter() - t0
    err = out.stderr
    failures = []
    if out.returncode != 0:
        failures.append(
            f"supervised fleet exited {out.returncode}: {err[-2000:]}"
        )
    for marker, missing in (
        ("injected hang: SIGSTOP", "the hang fault never fired"),
        ("collective watchdog: no progress",
         "the survivor never exited HANG_EXIT (wedged collective)"),
        ("blaming wedged process 1",
         "the supervisor blamed the survivor, not the silent slot"),
        ("degrading fleet 2 -> 1",
         "the struck-out slot never triggered shrink-to-survivors"),
        ("redistributing a 2-process snapshot",
         "the degrade relaunch did not restore-with-rescale"),
        ("probing back 1 -> 2",
         "the degraded fleet never probed back toward full width"),
        ("fleet healed at 2", "the healthy probe never cleared the strikes"),
    ):
        if marker not in err:
            failures.append(missing)
    report = {}
    stats = {}
    if not failures:
        report = json.loads(open(perf).read().strip())
        [stats] = report["statistics"]
        n_train = SELFHEAL_ROWS - n_fore
        conserved = stats["fitted"] + report["holdout"]["0"]
        if conserved != n_train:
            failures.append(
                f"records lost across the hang/degrade/probe: "
                f"fitted+holdout {conserved} != {n_train} training rows"
            )
        pred_files = sorted(
            f for f in os.listdir(tmp) if f.startswith("preds.jsonl")
        )
        n_served = sum(
            1 for f in pred_files for _ in open(os.path.join(tmp, f))
        )
        if n_served != n_fore:
            failures.append(
                f"forecasts not served exactly once: {n_served} outputs "
                f"for {n_fore} forecasts"
            )
        if report.get("fleetProcesses") != 2:
            failures.append(
                "fleet did not return to full width "
                f"(fleetProcesses {report.get('fleetProcesses')})"
            )
        if report.get("fleetDegraded") != 0:
            failures.append(
                f"fleetDegraded {report.get('fleetDegraded')} != 0 after "
                "the heal"
            )
        bundles = sorted(
            f for f in os.listdir(blackbox) if f.startswith("incident-")
        )
        if not bundles:
            failures.append("no incident bundle written")
        else:
            final = json.load(open(os.path.join(blackbox, bundles[-1])))
            kinds = [e["kind"] for e in final["timeline"]]
            chain = [
                k for k in kinds if k in ("strike", "degrade", "probe")
            ]
            if chain[:3] != ["strike", "degrade", "probe"]:
                failures.append(
                    "run-end bundle missing the classify->strike->"
                    f"degrade->probe chain in order (saw {chain[:6]})"
                )
            all_kinds = set()
            for b in bundles:
                all_kinds.update(
                    e["kind"]
                    for e in json.load(
                        open(os.path.join(blackbox, b))
                    )["timeline"]
                )
            if "hang" not in all_kinds:
                failures.append(
                    "no bundle carries the worker-side hang event"
                )
    if not failures:
        failures += _selfheal_identity_pair(tmp, env, repo)
    print(json.dumps({
        "config": "protocol_comparison_selfheal_smoke",
        "rows": SELFHEAL_ROWS,
        "forecasts": n_fore,
        "wall_s": round(wall_s, 1),
        "fitted": stats.get("fitted"),
        "score": stats.get("score"),
        "fleet_processes": report.get("fleetProcesses"),
        "fleet_degraded": report.get("fleetDegraded"),
        "failures": failures,
    }))
    if failures:
        sys.exit(1)


SLO_SMOKE_TENANTS = 256
SLO_SMOKE_RECORDS = 256


def run_slo_smoke() -> None:
    """CI gate (ISSUE 19 acceptance): the deterministic load harness end
    to end at ~256 tenants —

    - the full-composition identity leg: every plane configured-but-
      unarmed must be BIT-IDENTICAL to the bare path;
    - a composed in-process storm (churn waves + diurnal curve +
      hot-tenant bursts + addressed traffic) through the ARMED plane
      matrix must pass every deterministic SLO gate (zero healthy-tenant
      forecast loss, exactly-once outputs, no stranded rows, shed scoped
      to the hot tenants), and a same-seed replay must produce a
      byte-identical deterministic report core;
    - a supervised fleet storm with two composed fault classes (launch
      refusal + mid-stream crash) must complete across the restarts with
      every gate green, heals observed and within budget.

    The serve-p99 budget is a throughput gate: ENFORCED only on hosts
    with >= 2 usable cores (on a 1-core box the serving deadline thread
    timeshares the training loop's core, so latency reflects the host,
    not the plane — same basis note as --shard-smoke); the measured p99
    is reported either way. NONZERO EXIT on any enforced breach."""
    import tempfile

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, repo)
    import jax

    jax.config.update("jax_platforms", "cpu")

    from benchmarks.load_harness import (
        build_composed_storm,
        default_storm_spec,
        run_composition_identity,
        run_inprocess_storm,
        run_supervised_storm,
    )
    from omldm_tpu.runtime.loadgen import LoadStorm, StormSpec
    from omldm_tpu.runtime.slo import SLOBudgets

    try:
        n_cores = len(os.sched_getaffinity(0))
    except AttributeError:
        n_cores = os.cpu_count() or 1
    failures = []
    warnings = []
    t0 = time.perf_counter()

    # (a) full-composition identity: uniform broadcast traffic, the one
    # regime where EVERY plane must be transparent
    bare, composed = run_composition_identity(LoadStorm(StormSpec(
        seed=5, tenants=SLO_SMOKE_TENANTS, records=128, chunk_rows=64,
        n_features=4, forecast_ratio=0.4,
    )))
    if bare != composed:
        failures.append(
            "configured-but-unarmed plane matrix diverges bitwise from "
            "the bare path"
        )

    # (b) the armed composed storm + same-seed replay
    def _armed_run():
        storm = LoadStorm(default_storm_spec(
            seed=7, tenants=SLO_SMOKE_TENANTS, records=SLO_SMOKE_RECORDS,
            chunk_rows=64,
        ))
        budgets = SLOBudgets(
            serve_p99_ms=250.0,
            allow_shed_tenants=storm.hot_tenant_ids(),
            max_stranded_rows=0,
        )
        return run_inprocess_storm(storm, budgets)[0]

    armed = _armed_run()
    p99_ms = None
    for c in armed.checks:
        if c.name == "serve_p99":
            p99_ms = c.detail.get("p99Ms")
        if c.ok:
            continue
        msg = f"in-process {c.name} breached: {c.detail}"
        if c.name == "serve_p99" and n_cores < 2:
            warnings.append(msg + f" (not enforced: {n_cores} core host)")
        else:
            failures.append(msg)
    if armed.core_digest() != _armed_run().core_digest():
        failures.append(
            "same-seed replay produced a different deterministic "
            "report core"
        )

    # (c) the supervised fleet under the composed fault storm
    storm = build_composed_storm(
        3, tenants=16, records=192, chunk_rows=32, processes=1,
    )
    sup_budgets = SLOBudgets(
        heal_after_fault_s=120.0, expected_heals=2,
        allow_shed_tenants=storm.hot_tenant_ids(), max_stranded_rows=0,
    )
    tmp = tempfile.mkdtemp(prefix="omldm-slo-smoke-")
    sup_report, merged, _ = run_supervised_storm(
        storm, tmp, sup_budgets, processes=1,
    )
    heals = 0
    for c in sup_report.checks:
        if c.name == "heal_after_fault":
            heals = c.detail.get("heals", 0)
        if not c.ok:
            failures.append(f"supervised {c.name} breached: {c.detail}")

    print(json.dumps({
        "config": "protocol_comparison_slo_smoke",
        "tenants": SLO_SMOKE_TENANTS,
        "records": SLO_SMOKE_RECORDS,
        "cores": n_cores,
        "wall_s": round(time.perf_counter() - t0, 1),
        "serve_p99_ms": p99_ms,
        "supervised_heals": heals,
        "core_digest": armed.core_digest(),
        "p99_basis": (
            "serve-p99 enforced (>= 2 usable cores)" if n_cores >= 2
            else "serve-p99 reported only: 1-core host, the serving "
                 "deadline timeshares the training loop's core"
        ),
        "warnings": warnings,
        "failures": failures,
    }))
    if failures:
        sys.exit(1)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--records", type=int, default=50_000)
    ap.add_argument("--parallelism", type=int, default=16)
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument(
        "--codec", default="none",
        choices=("none", "fp16", "int8", "topk", "sweep"),
        help="transport codec section: one codec (vs none) or sweep",
    )
    ap.add_argument(
        "--smoke", action="store_true",
        help="CI mode: small stream, codec sections only, hard asserts",
    )
    ap.add_argument(
        "--chaos", default="",
        help="chaos resilience section: run the parameter protocols "
             "fault-free vs under this seeded chaos spec ('default' for "
             f"'{DEFAULT_CHAOS}') and report score deltas + resilience "
             "counters",
    )
    ap.add_argument(
        "--pipelines", default="",
        help="multi-tenant sweep: comma-separated pipeline counts (e.g. "
             "'1,8,64,256') run per-pipeline vs cohort gang dispatch",
    )
    ap.add_argument(
        "--cohort-smoke", action="store_true",
        help="CI gate: 64 co-hosted same-spec pipelines, cohort gang "
             "dispatch vs per-pipeline dispatch; NONZERO EXIT if the "
             "aggregate-throughput speedup is < 3x or the cohort run's "
             "score diverges from the per-pipeline run",
    )
    ap.add_argument(
        "--shard-smoke", action="store_true",
        help="CI gate: 64 co-hosted tenants on the forced 8-device host "
             "mesh — device-sharded cohort dispatch vs single-device "
             "cohort dispatch. NONZERO EXIT if the sharded leg never "
             "engages the tenant mesh, launch counts stop collapsing to "
             "one sharded launch per gang cycle, shard count 1 diverges "
             "bitwise from the single-device cohort path, the 8-shard "
             "parameter protocols leave the 0.05 score envelope, or (on "
             "hosts with >= 2 usable cores) the sharded aggregate "
             "throughput is < 2x the single-device cohort's",
    )
    ap.add_argument(
        "--ingest-smoke", action="store_true",
        help="CI gate: the sharded multi-process ingest plane vs "
             "single-process ingest. NONZERO EXIT if the sharded block "
             "stream is not bitwise the single-process parse, a "
             "sharded-driven StreamJob diverges bitwise from the packed "
             "event route, phase coverage drops below 0.9 with the shard "
             "clocks folded in, or (on hosts with >= 2 usable cores) the "
             "sharded ingest throughput is < 1.5x single-process",
    )
    ap.add_argument(
        "--forecast-mix", type=float, default=0.0,
        help="serving section: sweep per-record vs adaptive-batching "
             "serving (exact + relaxed) on a forecast-heavy stream with "
             "this forecast fraction (e.g. 0.5), 64 co-hosted tenants",
    )
    ap.add_argument(
        "--serve-smoke", action="store_true",
        help="CI gate: 64 co-hosted tenants on a 50/50 train/forecast "
             "stream; NONZERO EXIT if adaptive-batching serving delivers "
             "< 5x the per-record forecast throughput, exact-mode "
             "predictions/scores diverge from per-record serving, or the "
             "serving p99 latency exceeds the maxDelayMs budget",
    )
    ap.add_argument(
        "--overload-smoke", action="store_true",
        help="CI gate: 64 co-hosted tenants, 50/50 train/forecast "
             "per-record stream, a seeded 10x forecast burst flooding one "
             "hot tenant through the middle of the stream; NONZERO EXIT "
             "if the shed/throttle counters never engage, a healthy "
             "tenant gets shed, healthy tenants' serving p99 leaves the "
             "maxDelayMs budget, healthy forecast throughput drops more "
             "than 10%% vs the no-burst baseline, or the controller "
             "fails to return to OK after the burst",
    )
    ap.add_argument(
        "--lifecycle-smoke", action="store_true",
        help="CI gate: model-lifecycle plane end to end — a healthy "
             "Shadow candidate must ramp 0%%->50%% and auto-PROMOTE, a "
             "seeded-poison candidate must auto-ROLL-BACK via its guard "
             "with zero forecast loss, and with a canary armed the "
             "baseline-version predictions must stay BITWISE equal to a "
             "no-lifecycle run; NONZERO EXIT otherwise",
    )
    ap.add_argument(
        "--autoscale-smoke", action="store_true",
        help="CI gate: pressure-driven elastic autoscaling end to end — "
             "a preloaded burst on a (file-backed) Kafka broker must "
             "drive the supervised 1-process fleet out to 2 processes "
             "(checkpoint -> relaunch -> restore-with-rescale), healthy "
             "tenants must lose ZERO records across the restarts and "
             "serve every forecast exactly once, and the fleet must "
             "scale back in to the floor once the burst drains; NONZERO "
             "EXIT otherwise",
    )
    ap.add_argument(
        "--selfheal-smoke", action="store_true",
        help="CI gate: self-healing fleet end to end — a seeded SIGSTOP "
             "must be detected (survivors exit HANG_EXIT within "
             "--collectiveTimeoutMs, no wedged collective), the fleet "
             "must shrink to the survivors via restore-with-rescale with "
             "fitted+holdout exactly equal to the training rows and every "
             "forecast served exactly once, a later probe must restore "
             "full width, the bundles must carry the classify -> strike "
             "-> degrade -> probe chain in causal order, and unarmed "
             "knobs must be bit-identical no-ops; NONZERO EXIT otherwise",
    )
    ap.add_argument(
        "--chaos-smoke", action="store_true",
        help="CI gate: short Synchronous + Asynchronous runs under seeded "
             "drop+dup+reorder chaos; NONZERO EXIT if a run crashes or "
             "leaves the fault-free loss envelope",
    )
    ap.add_argument(
        "--telemetry-smoke", action="store_true",
        help="CI gate: telemetry plane end to end — the armed leg must "
             "match the unarmed leg's score/counters BITWISE (the plane "
             "only adds performance entries), cost <= 3%% throughput on "
             "paired trials, emit heartbeats on the count-clocked "
             "cadence, attribute the hot loop to phases, and write "
             "sampled round spans; NONZERO EXIT otherwise",
    )
    ap.add_argument(
        "--incident-smoke", action="store_true",
        help="CI gate: flight recorder end to end — a chaos+guard-armed "
             "supervised run with a seeded poisoned worker must leave ONE "
             "merged incident bundle carrying the rejection -> strike -> "
             "retire -> restart chain in causal order on the transport "
             "stamps, at least one kind=\"alert\" record must reach the "
             "performance sink, and arming the recorder on a clean "
             "stream must cost <= 3%% (paired trials) with BITWISE-equal "
             "scores; NONZERO EXIT otherwise",
    )
    ap.add_argument(
        "--slo-smoke", action="store_true",
        help="CI gate: the deterministic load harness end to end at ~256 "
             "tenants — the configured-but-unarmed plane matrix must be "
             "bit-identical to the bare path, a composed armed storm "
             "(churn + diurnal + bursts + addressed traffic) must pass "
             "every deterministic SLO gate with a byte-identical "
             "same-seed replay core, and a supervised fleet storm with "
             "two composed fault classes must heal within budget with "
             "zero healthy-tenant loss and exactly-once outputs; the "
             "serve-p99 budget self-enforces only on hosts with >= 2 "
             "usable cores (basis note in the output); NONZERO EXIT "
             "otherwise",
    )
    ap.add_argument(
        "--guard-smoke", action="store_true",
        help="CI gate: model-integrity guard end to end — a poisoned run "
             "(seeded NaN + exploding deltas) must finish inside the "
             "fault-free score envelope with the guard counters engaged, "
             "and a guard-armed CLEAN run must stay within 3%% of "
             "guard-off throughput on the packed host path; NONZERO EXIT "
             "otherwise",
    )
    args = ap.parse_args()

    if args.autoscale_smoke:
        # subprocess-driven (the fleet runs in real worker processes):
        # dispatch BEFORE the in-process jax/XLA setup below so the
        # parent stays light and its 8-device flag never leaks
        run_autoscale_smoke()
        return

    if args.selfheal_smoke:
        # subprocess-driven like the autoscale gate
        run_selfheal_smoke()
        return

    if args.slo_smoke:
        # dispatched before the 8-device XLA flag below: the in-process
        # legs run single-device and the supervised leg spawns its own
        # clean-env workers
        run_slo_smoke()
        return

    import os

    # the SPMD section wants a real multi-worker mesh: 8 virtual CPU
    # devices (must be set before the backend initializes)
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()

    import jax

    # host-plane comparison: protocol logic + traffic, not chip perf (and
    # not this environment's per-dispatch tunnel round trip)
    jax.config.update("jax_platforms", "cpu")

    import numpy as np

    codecs = (
        CODEC_SWEEP if args.codec == "sweep"
        else ("none", args.codec) if args.codec != "none"
        else ()
    )

    if args.ingest_smoke:
        # CI gate (ISSUE 17 acceptance): the sharded multi-process ingest
        # plane (runtime/ingest_shard.py) against single-process ingest:
        #   (a) raw block-stream parity — the sharded plane's
        #       concatenated (x, y, op) rows must be BITWISE the
        #       single-process parse of the same file;
        #   (b) full-driver parity — a StreamJob consuming the file
        #       through run_file_sharded must match the packed event
        #       route bitwise (fitted, score, holdout contents, trained
        #       params);
        #   (c) phase coverage >= 0.9 on the sharded run with the shard
        #       parse/read clocks folded into the phase table;
        #   (d) sharded ingest throughput >= 1.5x single-process —
        #       ENFORCED only on hosts with >= 2 usable cores: parser
        #       processes timeshare the driver's core on a 1-core box, so
        #       parallel speedup is physically unavailable there (same
        #       basis note as --shard-smoke); the measured ratio is
        #       reported either way.
        import tempfile

        import numpy as np

        from run_benchmarks import _gen_stream_file
        from omldm_tpu.config import JobConfig
        from omldm_tpu.runtime import StreamJob
        from omldm_tpu.runtime.fast_ingest import iter_file_batches
        from omldm_tpu.runtime.ingest_shard import (
            IngestConfig,
            ShardedIngest,
        )
        from omldm_tpu.runtime.job import REQUEST_STREAM

        dim = 16
        records = min(args.records, 80_000)
        tmp = tempfile.NamedTemporaryFile(suffix=".jsonl", delete=False)
        tmp.close()
        _gen_stream_file(tmp.name, records, dim)
        try:
            n_cores = len(os.sched_getaffinity(0))
        except AttributeError:
            n_cores = os.cpu_count() or 1
        n_shards = max(n_cores - 1, 1)
        failures = []
        warnings = []

        # (a) raw block-stream parity
        def _collect_sharded(shards, chunk_kb=256):
            si = ShardedIngest(
                tmp.name, dim, IngestConfig(shards=shards, chunk_kb=chunk_kb)
            )
            xs, ys, ops = [], [], []
            try:
                for x, y, op in si.blocks():
                    xs.append(x)
                    ys.append(y)
                    ops.append(op)
            finally:
                si.close()
            return (
                np.concatenate(xs), np.concatenate(ys), np.concatenate(ops)
            )

        ref_parts = list(iter_file_batches(tmp.name, dim, 32768))
        ref = tuple(
            np.concatenate([p[i] for p in ref_parts]) for i in range(3)
        )
        got = _collect_sharded(max(n_shards, 2))
        if not all(np.array_equal(ref[i], got[i]) for i in range(3)):
            failures.append(
                "sharded block stream is not bitwise the single-process "
                "parse"
            )

        # (d) throughput: sharded plane vs single-process packed iterator
        def _t_single():
            t0 = time.perf_counter()
            for _ in iter_file_batches(tmp.name, dim, 32768):
                pass
            return time.perf_counter() - t0

        def _t_sharded():
            si = ShardedIngest(
                tmp.name, dim, IngestConfig(shards=n_shards)
            )
            t0 = time.perf_counter()
            try:
                for _ in si.blocks():
                    pass
            finally:
                si.close()
            return time.perf_counter() - t0

        _t_single(), _t_sharded()  # warm (page cache, fork paths)
        t_single = min(_t_single() for _ in range(2))
        t_sharded = min(_t_sharded() for _ in range(2))
        ratio = t_single / max(t_sharded, 1e-9)
        if ratio < 1.5:
            msg = (
                f"sharded ingest speedup {ratio:.2f}x < 1.5x at "
                f"{n_shards} shards"
            )
            if n_cores >= 2:
                failures.append(msg)
            else:
                warnings.append(
                    msg + f" — NOT enforced: {n_cores} usable core means "
                    "the parser processes timeshare the driver's core, "
                    "so parallel speedup is physically unavailable on "
                    "this host"
                )

        # (b) full-driver bitwise parity + (c) phase coverage
        create = json.dumps({
            "id": 0,
            "request": "Create",
            "learner": {
                "name": "PA",
                "hyperParameters": {"C": 1.0},
                "dataStructure": {"nFeatures": dim},
            },
            "trainingConfiguration": {"protocol": "CentralizedTraining"},
        })

        def _driver_run(sharded):
            job = StreamJob(JobConfig(
                parallelism=2, batch_size=128, test_set_size=64,
                telemetry="statsEvery=1000000",
                ingest="shards=2,chunkKb=256" if sharded else "",
            ))
            job.process_event(REQUEST_STREAM, create)
            job.ensure_deployed(dim)
            t0 = time.perf_counter()
            if sharded:
                assert job.run_file_sharded(tmp.name, dim=dim)
            else:
                for blk in iter_file_batches(tmp.name, dim, 32768):
                    job.process_packed_batch(*blk)
            e2e = time.perf_counter() - t0
            table = job.phase_table(e2e)
            rep = job.terminate()
            st = rep.statistics[0]
            return {
                "fitted": st.fitted,
                "score": st.score,
                "coverage": table.get("_coverage", 0.0),
                "examples_per_sec": round(records / e2e, 1),
            }

        _driver_run(True)  # warmup compiles the fit programs
        base_run = _driver_run(False)
        # parity must hold on EVERY sharded run; coverage takes the best
        # of two (attribution is deterministic, but a loaded CI box can
        # steal wall-clock from the driver loop between phase hooks)
        shard_runs = [_driver_run(True) for _ in range(2)]
        for shard_run in shard_runs:
            if (
                base_run["fitted"] != shard_run["fitted"]
                or base_run["score"] != shard_run["score"]
            ):
                failures.append(
                    "sharded-driven StreamJob diverged from the packed "
                    f"route (fitted {shard_run['fitted']} vs "
                    f"{base_run['fitted']}, score {shard_run['score']} "
                    f"vs {base_run['score']})"
                )
                break
        shard_run = max(shard_runs, key=lambda r: r["coverage"])
        if shard_run["coverage"] < 0.9:
            failures.append(
                f"phase coverage {shard_run['coverage']} < 0.9 on the "
                "sharded run"
            )

        os.unlink(tmp.name)
        print(json.dumps({
            "config": "protocol_comparison_ingest_smoke",
            "records": records,
            "usable_cores": n_cores,
            "shards": n_shards,
            "sharded_speedup_vs_single_process": round(ratio, 2),
            "single_process_ingest_examples_per_sec": round(
                records / t_single, 1
            ),
            "sharded_ingest_examples_per_sec": round(
                records / t_sharded, 1
            ),
            "packed_route": base_run,
            "sharded_route": shard_run,
            "warnings": warnings,
            "failures": failures,
        }))
        if failures:
            sys.exit(1)
        return

    if args.shard_smoke:
        # CI gate (ISSUE 9 acceptance): at 64 co-hosted tenants on the
        # forced 8-device host mesh, device-sharded cohort execution
        # (cohort_shards auto) against single-device cohort dispatch
        # (cohort auto, shards off):
        #   (a) the sharded leg must actually engage the tenant mesh
        #       (cohort_shards gauge > 1, members placed on > 1 shard);
        #   (b) launch counts must stay collapsed — ONE sharded launch
        #       per gang cycle, i.e. no more programLaunches than the
        #       single-device cohort run;
        #   (c) shard count 1 must be BITWISE the single-device cohort
        #       path (holdout-scored parity pair), and the 8-shard parity
        #       leg must match too (lax.map member iteration is exact on
        #       CPU);
        #   (d) the 6 parameter protocols at 8 shards must stay inside
        #       the 0.05 score envelope vs their unsharded runs;
        #   (e) aggregate throughput must beat the single-device cohort
        #       by >= 2x — ENFORCED only on hosts with >= 2 usable cores:
        #       the CI mesh is 8 virtual devices, so the sharded gang
        #       parallelizes across real cores where they exist, but on a
        #       single-core box all 8 devices share one core and parallel
        #       speedup is physically unavailable (same basis note as
        #       protocols_spmd); the measured ratio is reported either way.
        records = min(args.records, 40_000)
        x, y = _mt_stream(records)
        try:
            n_cores = len(os.sched_getaffinity(0))
        except AttributeError:
            n_cores = os.cpu_count() or 1
        # warmup compiles both program families (single-device + sharded)
        run_multi_tenant_one(64, x[:8192], y[:8192], 256, "auto")
        run_multi_tenant_one(
            64, x[:8192], y[:8192], 256, "auto", shards="auto"
        )
        best = None
        for _trial in range(2):
            base = run_multi_tenant_one(64, x, y, 256, "auto")
            shard = run_multi_tenant_one(
                64, x, y, 256, "auto", shards="auto"
            )
            ratio = (
                shard["aggregate_examples_per_sec"]
                / max(base["aggregate_examples_per_sec"], 1e-9)
            )
            if best is None or ratio > best[0]:
                best = (ratio, base, shard)
        ratio, base, shard = best
        failures = []
        warnings = []
        if shard["cohort_shards"] < 2 or not any(
            sum(1 for c in p if c) > 1 for p in shard["tenant_placement"]
        ):
            failures.append(
                "sharded leg never engaged the tenant mesh "
                f"(cohort_shards={shard['cohort_shards']}, "
                f"placement={shard['tenant_placement']})"
            )
        if shard["program_launches"] > base["program_launches"] * 1.1:
            failures.append(
                "sharding broke the one-launch-per-gang-cycle collapse "
                f"({shard['program_launches']} launches vs single-device "
                f"cohort {base['program_launches']})"
            )
        if ratio < 2.0:
            msg = (
                f"sharded aggregate speedup {ratio:.2f}x < 2x at 64 "
                f"tenants on {shard['cohort_shards']} shards"
            )
            if n_cores >= 2:
                failures.append(msg)
            else:
                warnings.append(
                    msg + f" — NOT enforced: {n_cores} usable core "
                    "shares all 8 virtual devices, so parallel speedup "
                    "is physically unavailable on this host"
                )
        # (c) bitwise parity: shards=1 == the single-device cohort path,
        # and the 8-shard leg matches too (exact lax.map on CPU)
        px, py = _mt_stream(MT_PARITY_RECORDS)
        p_base = run_multi_tenant_one(64, px, py, 256, "auto", test=True)
        p_one = run_multi_tenant_one(
            64, px, py, 256, "auto", test=True, shards="1"
        )
        p_shard = run_multi_tenant_one(
            64, px, py, 256, "auto", test=True, shards="auto"
        )
        if p_one["score"] != p_base["score"]:
            failures.append(
                f"shard-count-1 holdout score {p_one['score']} != "
                f"single-device cohort {p_base['score']}"
            )
        if p_shard["score"] != p_base["score"]:
            failures.append(
                f"8-shard holdout score {p_shard['score']} != "
                f"single-device cohort {p_base['score']}"
            )
        if p_base["score"] <= 0.5:
            failures.append(
                f"parity legs never learned (score {p_base['score']}) — "
                "the parity check would be vacuous"
            )
        # (d) protocol envelope at 8 shards, parallelism 2
        ex, ey = _mt_stream(8_192)
        envelope = {}
        for protocol in SPMD_PROTOCOLS:
            s_off = run_shard_protocol_one(protocol, ex, ey, 64, "off")
            s_on = run_shard_protocol_one(protocol, ex, ey, 64, "auto")
            deltas = {
                pid: round(abs(s_on[pid] - s_off[pid]), 4)
                for pid in s_off
            }
            envelope[protocol] = {
                "unsharded": s_off, "sharded": s_on, "abs_delta": deltas,
            }
            worst = max(deltas.values()) if deltas else 1.0
            if worst > 0.05:
                failures.append(
                    f"{protocol}: 8-shard score delta {worst} outside "
                    "the 0.05 envelope"
                )
        print(json.dumps({
            "config": "protocol_comparison_shard_smoke",
            "records": records,
            "usable_cores": n_cores,
            "sharded_speedup_vs_single_device_cohort": round(ratio, 2),
            "single_device_cohort": base,
            "sharded_cohort": shard,
            "shard1_parity": {
                "single_device": p_base, "shard_count_1": p_one,
                "sharded": p_shard,
            },
            "protocol_envelope": envelope,
            "warnings": warnings,
            "failures": failures,
        }))
        if failures:
            sys.exit(1)
        return

    if args.serve_smoke:
        # CI gate (ISSUE 8 acceptance): at 64 co-hosted tenants on a 50/50
        # train/forecast stream, the adaptive-batching serving plane must
        # deliver >= 5x the forecast throughput of per-record serving
        # (test=False production mode; best of 3 paired trials — the
        # per-record baseline is dispatch-bound and noisy on shared CI
        # boxes). Both legs run SOLO per-tenant dispatch (cohort off):
        # that is the reference's serving semantics — one padded predict
        # launch per tenant per forecasting record (FlinkSpoke.scala:
        # 92-107) — and it isolates the axis THIS plane adds (batching
        # across stream positions and tenants) from PR6's cross-tenant
        # gang, which has its own --cohort-smoke gate; the --forecast-mix
        # sweep records both topologies. Exact-staleness predictions and
        # scores must match the per-record run BITWISE on scored parity
        # pairs (solo AND cohort), and the serving run's p99 enqueue->emit
        # latency must stay under the configured maxDelayMs budget.
        from benchmarks.streams import forecast_stream

        records = min(args.records, 8_192)
        x, y, op = forecast_stream(records, mix=0.5)
        serving = {"maxBatch": SERVE_SMOKE_BATCH,
                   "maxDelayMs": SERVE_SMOKE_DELAY_MS,
                   "staleness": "exact"}
        # warmup compiles both program families (per-record + batched)
        run_serving_one(64, x[:4096], y[:4096], op[:4096], 256, None)
        run_serving_one(64, x[:4096], y[:4096], op[:4096], 256, serving)
        best = None
        for _trial in range(3):
            per = run_serving_one(64, x, y, op, 256, None)
            srv = run_serving_one(64, x, y, op, 256, serving)
            ratio = (
                srv["aggregate_forecasts_per_sec"]
                / max(per["aggregate_forecasts_per_sec"], 1e-9)
            )
            if best is None or ratio > best[0]:
                best = (ratio, per, srv)
        ratio, per, srv = best
        px, py, pop = forecast_stream(6_144, mix=0.5, seed=1)
        parity = {}
        failures = []
        for label, cohort in (("solo", "off"), ("cohort", "auto")):
            pp = run_serving_one(16, px, py, pop, 256, None, cohort=cohort,
                                 test=True, collect_preds=True)
            pc = run_serving_one(16, px, py, pop, 256, serving,
                                 cohort=cohort, test=True,
                                 collect_preds=True)
            if pc.pop("_preds") != pp.pop("_preds"):
                failures.append(
                    f"{label}: exact-staleness predictions diverge from "
                    "per-record serving"
                )
            if pc.pop("_scores") != pp.pop("_scores"):
                failures.append(
                    f"{label}: exact-staleness scores diverge from "
                    "per-record serving"
                )
            if pp["forecasts_served"] == 0:
                failures.append(
                    f"{label}: parity legs served no forecasts — the "
                    "parity check is vacuous"
                )
            parity[label] = {"per_record": pp, "serving": pc}
        if ratio < 5.0:
            failures.append(
                f"serving forecast speedup {ratio:.2f}x < 5x at 64 tenants"
            )
        if srv["serve_latency_p99_ms"] > SERVE_SMOKE_DELAY_MS:
            failures.append(
                f"serving p99 latency {srv['serve_latency_p99_ms']}ms over "
                f"the {SERVE_SMOKE_DELAY_MS}ms maxDelayMs budget"
            )
        if srv["program_launches"] >= per["program_launches"]:
            failures.append(
                "batched serving did not reduce programLaunches "
                f"({srv['program_launches']} vs {per['program_launches']})"
            )
        print(json.dumps({
            "config": "protocol_comparison_serve_smoke",
            "records": records,
            "forecast_speedup": round(ratio, 2),
            "per_record": per,
            "serving": srv,
            "exact_parity": parity,
            "failures": failures,
        }))
        if failures:
            sys.exit(1)
        return

    if args.cohort_smoke:
        # CI gate (ISSUE 6 acceptance): at 64 same-spec pipelines on the
        # co-hosted serving plane, cohort gang dispatch must deliver >= 3x
        # the aggregate throughput of per-pipeline dispatch (test=False —
        # production serving mode), with programLaunches collapsed, AND a
        # holdout-scored (test=True) parity pair must agree BITWISE (the
        # production-mode scores are trivially 0, so parity needs its own
        # short scored runs). Two throughput trials, best ratio — the
        # per-pipeline baseline is python-dispatch-bound and noisy on
        # shared CI boxes.
        records = min(args.records, 40_000)
        x, y = _mt_stream(records)
        best = None
        for _trial in range(2):
            per = run_multi_tenant_one(64, x, y, 256, "off")
            coh = run_multi_tenant_one(64, x, y, 256, "auto")
            ratio = (
                coh["aggregate_examples_per_sec"]
                / max(per["aggregate_examples_per_sec"], 1e-9)
            )
            if best is None or ratio > best[0]:
                best = (ratio, per, coh)
        ratio, per, coh = best
        px, py = _mt_stream(MT_PARITY_RECORDS)
        pp = run_multi_tenant_one(64, px, py, 256, "off", test=True)
        pc = run_multi_tenant_one(64, px, py, 256, "auto", test=True)
        failures = []
        if ratio < 3.0:
            failures.append(
                f"cohort aggregate speedup {ratio:.2f}x < 3x at 64 pipelines"
            )
        if pc["score"] != pp["score"]:
            failures.append(
                f"cohort holdout score {pc['score']} != per-pipeline "
                f"{pp['score']}"
            )
        if pp["score"] <= 0.5:
            failures.append(
                f"parity leg never learned (score {pp['score']}) — the "
                "parity check would be vacuous"
            )
        if coh["program_launches"] >= per["program_launches"]:
            failures.append(
                "cohort dispatch did not reduce programLaunches "
                f"({coh['program_launches']} vs {per['program_launches']})"
            )
        print(json.dumps({
            "config": "protocol_comparison_cohort_smoke",
            "records": records,
            "aggregate_speedup": round(ratio, 2),
            "per_pipeline": per,
            "cohort": coh,
            "holdout_parity": {"per_pipeline": pp, "cohort": pc},
            "failures": failures,
        }))
        if failures:
            sys.exit(1)
        return

    if args.telemetry_smoke:
        # CI gate (ISSUE 13 acceptance):
        #  (a) UNARMED bit-identity — the telemetry-armed leg's score /
        #      fitted / communication counters must equal the unarmed
        #      leg's exactly (the plane only ever ADDS performance
        #      entries; it must never perturb the computation);
        #  (b) armed overhead <= 3% on the packed host path (4 paired
        #      off/on trials, best pair ratio — the same share-throttled-
        #      box methodology as the guard gate);
        #  (c) the plane ENGAGES: count-clocked heartbeats at the
        #      statsEvery cadence, a phase table attributing >= half the
        #      measured wall (stage/holdout/fit; hub protocol math is
        #      deliberately unattributed), and a nonempty sampled-span
        #      JSONL keyed by the transport stamps.
        import tempfile

        records = min(args.records, 48_000)
        par = min(args.parallelism, 4)
        batch = min(args.batch, 64)
        stats_every = 4_096
        rng = np.random.RandomState(13)
        w = np.random.RandomState(42).randn(28)
        tx = rng.randn(records, 28).astype(np.float32)
        ty = (tx @ w > 0).astype(np.float32)
        span_path = os.path.join(
            tempfile.mkdtemp(prefix="omldm-telemetry-smoke-"),
            "spans.jsonl",
        )
        tel_spec = (
            f"statsEvery={stats_every},traceSample=16,spanPath={span_path}"
        )
        failures = []
        # warmup compiles the shared programs for both legs
        run_one("Synchronous", tx[:2048], ty[:2048], par, batch)
        run_one(
            "Synchronous", tx[:2048], ty[:2048], par, batch,
            telemetry=f"statsEvery={stats_every}",
        )
        best_off = best_on = None
        pair_ratios = []
        for _trial in range(4):
            r_off = run_one("Synchronous", tx, ty, par, batch)
            r_on = run_one(
                "Synchronous", tx, ty, par, batch, telemetry=tel_spec
            )
            pair_ratios.append(
                r_off["examples_per_sec"]
                / max(r_on["examples_per_sec"], 1e-9)
            )
            if best_off is None or (
                r_off["examples_per_sec"] > best_off["examples_per_sec"]
            ):
                best_off = r_off
            if best_on is None or (
                r_on["examples_per_sec"] > best_on["examples_per_sec"]
            ):
                best_on = r_on
        overhead = min(pair_ratios)
        for key in ("score", "fitted", "models_shipped", "bytes_on_wire",
                    "num_of_blocks"):
            if best_off[key] != best_on[key]:
                failures.append(
                    f"armed leg diverged on {key}: {best_on[key]} != "
                    f"unarmed {best_off[key]}"
                )
        if overhead > 1.03:
            failures.append(
                f"telemetry-armed throughput {overhead:.3f}x slower than "
                "unarmed (> 3% bar)"
            )
        # heartbeats fire at the first event/block boundary at/after
        # statsEvery records — the packed route feeds 8192-row blocks,
        # so the cadence clamps to block granularity here
        expected_beats = max(records // max(stats_every, 8192) - 1, 1)
        if best_on.get("heartbeats", 0) < expected_beats:
            failures.append(
                f"heartbeat cadence did not engage: "
                f"{best_on.get('heartbeats', 0)} beats < {expected_beats} "
                f"expected at statsEvery={stats_every}"
            )
        coverage = best_on.get("phase_table", {}).get("_coverage", 0.0)
        if coverage < 0.5:
            failures.append(
                f"phase table attributes only {coverage:.2f} of the "
                "measured wall (< 0.5)"
            )
        if best_on.get("spans_completed", 0) == 0:
            failures.append("no protocol-round spans completed")
        try:
            span_lines = open(span_path).read().splitlines()
        except OSError:
            span_lines = []
        if not span_lines:
            failures.append(f"span file {span_path} is empty/missing")
        else:
            span = json.loads(span_lines[0])
            for key in ("networkId", "seq", "op", "rttMs"):
                if key not in span:
                    failures.append(f"span records missing {key!r}")
        print(json.dumps({
            "config": "protocol_comparison_telemetry_smoke",
            "records": records,
            "telemetry_spec": tel_spec,
            "telemetry_overhead_x": round(overhead, 3),
            "pair_ratios": [round(r, 3) for r in pair_ratios],
            "phase_coverage": coverage,
            "spans_written": len(span_lines),
            "unarmed": best_off,
            "armed": best_on,
            "failures": failures,
        }))
        if failures:
            sys.exit(1)
        return

    if args.incident_smoke:
        # CI gate (ISSUE 14 acceptance): see run_incident_smoke
        run_incident_smoke()
        return

    if args.guard_smoke:
        # CI gate (ISSUE 7 acceptance): (a) seeded poison injection — NaN
        # and exploding worker deltas on the hub<->spoke bridge — against
        # guard-armed Synchronous + Asynchronous runs must finish with the
        # admission counters engaged and the final score inside the 0.05
        # fault-free envelope; (b) arming the guard on a CLEAN stream must
        # cost <= 3% throughput on the packed CPU host path (4 paired
        # off/on trials, best pair ratio — the python-dispatch baseline is
        # noisy on shared CI boxes) and must not move the score at all.
        records = min(args.records, 48_000)
        par = min(args.parallelism, 4)
        batch = min(args.batch, 64)
        rng = np.random.RandomState(11)
        w = np.random.RandomState(42).randn(28)
        gx = rng.randn(records, 28).astype(np.float32)
        gy = (gx @ w > 0).astype(np.float32)
        poison_spec = "seed=7,up.nan=0.02,up.explode=0.02"
        failures = []
        out = {}
        # warmup compiles both program families (guarded + unguarded)
        run_one("Synchronous", gx[:2048], gy[:2048], par, batch)
        run_one("Synchronous", gx[:2048], gy[:2048], par, batch, guard=True)
        for protocol in ("Synchronous", "Asynchronous"):
            # paired back-to-back A/B trials: this box is share-throttled
            # (+-25%, BASELINE notes), so each off/on pair samples the
            # same throttle window and the gate takes the BEST pair ratio
            # — throttle noise only ever inflates a pair's ratio, so the
            # minimum over pairs is the tightest available estimate of
            # the systematic guard overhead
            clean_off = clean_on = None
            pair_ratios = []
            for _trial in range(4):
                r_off = run_one(protocol, gx, gy, par, batch)
                r_on = run_one(protocol, gx, gy, par, batch, guard=True)
                pair_ratios.append(
                    r_off["examples_per_sec"]
                    / max(r_on["examples_per_sec"], 1e-9)
                )
                if clean_off is None or (
                    r_off["examples_per_sec"]
                    > clean_off["examples_per_sec"]
                ):
                    clean_off = r_off
                if clean_on is None or (
                    r_on["examples_per_sec"] > clean_on["examples_per_sec"]
                ):
                    clean_on = r_on
            poisoned = run_one(
                protocol, gx, gy, par, batch, guard=True, chaos=poison_spec
            )
            overhead = min(pair_ratios)
            row = {
                "clean_guard_off": clean_off,
                "clean_guard_on": clean_on,
                "poisoned_guard_on": poisoned,
                "guard_overhead_x": round(overhead, 3),
                "poisoned_score_delta": round(
                    poisoned["score"] - clean_off["score"], 4
                ),
            }
            out[protocol] = row
            if clean_on["score"] != clean_off["score"]:
                failures.append(
                    f"{protocol}: guard-armed clean score "
                    f"{clean_on['score']} != guard-off {clean_off['score']}"
                )
            if overhead > 1.03:
                failures.append(
                    f"{protocol}: guard-armed clean throughput "
                    f"{overhead:.3f}x slower than guard-off (> 3% bar)"
                )
            if poisoned["deltas_rejected"] == 0:
                failures.append(
                    f"{protocol}: poison injection never engaged the "
                    "admission counters — the envelope check is vacuous"
                )
            if abs(row["poisoned_score_delta"]) > 0.05:
                failures.append(
                    f"{protocol}: poisoned score delta "
                    f"{row['poisoned_score_delta']} outside the 0.05 envelope"
                )
        print(json.dumps({
            "config": "protocol_comparison_guard_smoke",
            "records": records,
            "poison_spec": poison_spec,
            **out,
            "failures": failures,
        }))
        if failures:
            sys.exit(1)
        return

    if args.overload_smoke:
        # CI gate (ISSUE 10 acceptance): at 64 co-hosted tenants on a
        # 50/50 per-record stream with a seeded 10x forecast burst
        # flooding tenant 0:
        #   (a) the overload counters must ENGAGE — the hot tenant sheds
        #       forecasts (reason-coded dead letters) and has training
        #       rows deprioritized, and the pressure gauge records
        #       CRITICAL;
        #   (b) fairness must hold — NO healthy tenant sheds, and every
        #       healthy tenant serves EXACTLY the forecasts it serves in
        #       the no-burst leg (count equality: the schedule is
        #       deterministic);
        #   (c) healthy tenants' serving p99 stays inside the maxDelayMs
        #       budget and their aggregate forecast throughput within 10%
        #       of the no-burst baseline (best of 3 paired trials — the
        #       per-record baseline is dispatch-bound and noisy on shared
        #       CI boxes);
        #   (d) the controller must RECOVER: pressure back to OK by the
        #       end of the post-burst tail, with no stranded queue rows.
        records = min(args.records, 4_096)
        x, y = _mt_stream(records)
        # warmup job compiles the fit + padded-predict program families
        # into the shared jit cache (same-spec jobs reuse them)
        run_overload_one(64, x[:1024], y[:1024], burst=False)
        best = None
        for _trial in range(3):
            base = run_overload_one(64, x, y, burst=False)
            burst = run_overload_one(64, x, y, burst=True)
            ratio = (
                burst["healthy_forecasts_per_sec"]
                / max(base["healthy_forecasts_per_sec"], 1e-9)
            )
            if best is None or ratio > best[0]:
                best = (ratio, base, burst)
        ratio, base, burst = best
        failures = []
        if burst["hot_shed"] == 0:
            failures.append(
                "the burst never engaged shedding (hot_shed == 0) — the "
                "fairness checks are vacuous"
            )
        if burst["hot_throttled"] == 0:
            failures.append(
                "the burst never engaged training deprioritization "
                "(hot_throttled == 0)"
            )
        if burst["pressure_peak"] < 2:
            failures.append(
                f"pressure never reached CRITICAL (peak "
                f"{burst['pressure_peak']})"
            )
        if burst["healthy_shed"] != 0:
            failures.append(
                f"{burst['healthy_shed']} healthy-tenant forecasts were "
                "shed — fairness violated"
            )
        if burst["healthy_forecasts_served"] != base["healthy_forecasts_served"]:
            failures.append(
                "healthy tenants' served-forecast count diverged under "
                f"the burst ({burst['healthy_forecasts_served']} vs "
                f"{base['healthy_forecasts_served']})"
            )
        budget = OVERLOAD_SERVING["maxDelayMs"]
        if burst["healthy_serve_p99_ms"] > budget:
            failures.append(
                f"healthy serving p99 {burst['healthy_serve_p99_ms']}ms "
                f"over the {budget}ms maxDelayMs budget under the burst"
            )
        if burst["healthy_serve_p99_ms"] > base["healthy_serve_p99_ms"] * 1.5:
            failures.append(
                "the burst degraded healthy serving p99 "
                f"({burst['healthy_serve_p99_ms']}ms vs "
                f"{base['healthy_serve_p99_ms']}ms no-burst — > 1.5x)"
            )
        if ratio < 0.9:
            failures.append(
                f"healthy forecast throughput {ratio:.2f}x of the "
                "no-burst baseline (< 0.9x bar)"
            )
        if burst["level_after_feed"] != 0:
            failures.append(
                "controller did not return to OK after the burst "
                f"(level {burst['level_after_feed']})"
            )
        stranded = {
            k: v for k, v in burst["queue_depths"].items()
            if k != "pressure_level" and v
        }
        if stranded:
            failures.append(f"stranded queue rows at terminate: {stranded}")
        print(json.dumps({
            "config": "protocol_comparison_overload_smoke",
            "records": records,
            "overload_spec": OVERLOAD_SPEC,
            "chaos_spec": _overload_chaos(records),
            "healthy_throughput_ratio": round(ratio, 3),
            "no_burst": base,
            "burst": burst,
            "failures": failures,
        }))
        if failures:
            sys.exit(1)
        return

    if args.lifecycle_smoke:
        # CI gate (ISSUE 11 acceptance): one lifecycle-armed pipeline on
        # a 50/50 per-record stream, four legs on the SAME deterministic
        # stream:
        #   (a) HEALTHY — a Shadow candidate ramps 0 -> 50% and
        #       auto-promotes (canaryPromotions engages, the registry's
        #       active version advances, shadow scoring ran);
        #   (b) HOLD — the canary serves the whole stream without
        #       promoting: every baseline-version (untagged) prediction
        #       must be BITWISE equal to the no-lifecycle leg's value at
        #       the same stream position — candidate training and canary
        #       routing never perturb the active model;
        #   (c) POISON — the candidate's params are seeded with an
        #       exploding vector mid-canary: its guard must trip and
        #       auto-roll the canary back (canaryRollbacks engages, the
        #       active version stays 0) with ZERO forecast loss (every
        #       forecast answered) and the same baseline bitwise pin.
        records = min(args.records, 6_144)
        x, y = _mt_stream(records)
        off = run_lifecycle_one(x, y, "off")
        healthy = run_lifecycle_one(x, y, "healthy")
        hold = run_lifecycle_one(x, y, "hold")
        poison = run_lifecycle_one(x, y, "poison")
        failures = []
        if healthy["canary_promotions"] < 1:
            failures.append(
                "the healthy candidate never promoted "
                f"(canary_promotions {healthy['canary_promotions']})"
            )
        if healthy["canary_rollbacks"]:
            failures.append(
                f"{healthy['canary_rollbacks']} rollbacks on the healthy "
                "candidate"
            )
        if healthy["active_version"] != 1:
            failures.append(
                "the registry's active version did not advance after the "
                f"healthy promotion (gauge {healthy['active_version']})"
            )
        if healthy["shadow_scored"] < 2:
            failures.append(
                "shadow scoring never ran on the healthy candidate "
                f"(shadow_scored {healthy['shadow_scored']})"
            )
        if poison["canary_rollbacks"] < 1:
            failures.append(
                "the seeded-poison candidate never rolled back "
                f"(canary_rollbacks {poison['canary_rollbacks']})"
            )
        if poison["canary_promotions"]:
            failures.append("the poisoned candidate PROMOTED")
        if poison["lifecycle"]["activeVersion"] != 0:
            failures.append(
                "the poison leg's active version moved off the baseline "
                f"({poison['lifecycle']['activeVersion']})"
            )
        for leg in (healthy, hold, poison):
            if len(leg["predictions"]) != len(off["predictions"]):
                failures.append(
                    f"{leg['mode']} leg answered "
                    f"{len(leg['predictions'])} forecasts vs "
                    f"{len(off['predictions'])} without the plane — "
                    "forecast loss"
                )
        for leg in (hold, poison):
            mismatches = sum(
                1
                for (v, ver), (v0, _) in zip(
                    leg["predictions"], off["predictions"]
                )
                if ver is None and v != v0
            )
            if mismatches:
                failures.append(
                    f"{mismatches} baseline-version predictions of the "
                    f"{leg['mode']} leg diverged from the no-lifecycle "
                    "run — the bitwise pin"
                )
        canary_served = sum(
            1 for _v, ver in hold["predictions"] if ver is not None
        )
        if canary_served == 0:
            failures.append(
                "the hold leg's canary never served — the bitwise pin "
                "is vacuous"
            )
        summary = {
            k: {
                "score": leg["score"],
                "shadow_scored": leg["shadow_scored"],
                "canary_promotions": leg["canary_promotions"],
                "canary_rollbacks": leg["canary_rollbacks"],
                "active_version": leg["active_version"],
                "forecasts": len(leg["predictions"]),
                "canary_tagged": sum(
                    1 for _v, ver in leg["predictions"] if ver is not None
                ),
            }
            for k, leg in (
                ("off", off), ("healthy", healthy),
                ("hold", hold), ("poison", poison),
            )
        }
        print(json.dumps({
            "config": "protocol_comparison_lifecycle_smoke",
            "records": records,
            "lifecycle_spec": LIFECYCLE_SPEC,
            **summary,
            "failures": failures,
        }))
        if failures:
            sys.exit(1)
        return

    if args.chaos_smoke:
        # CI gate: a short Sync + Async run under seeded drop+dup+reorder
        # chaos — the job must finish (zero crashes) with the final score
        # inside the fault-free loss envelope, and the reliable channel
        # must actually have worked (nonzero resilience counters). The dup
        # rate is cranked above the acceptance operating point so the
        # ~200-message smoke stream statistically guarantees duplicate
        # deliveries for the counter gate
        res = run_chaos_resilience(
            ("Synchronous", "Asynchronous"),
            min(args.records, 6_000),
            min(args.parallelism, 4),
            min(args.batch, 64),
            chaos="seed=7,drop=0.05,dup=0.25,reorder=0.1,window=4",
        )
        failures = []
        for protocol, r in res["protocols"].items():
            if abs(r["score_delta_vs_clean"]) > 0.05:
                failures.append(
                    f"{protocol} chaos score delta "
                    f"{r['score_delta_vs_clean']} outside the 0.05 envelope"
                )
            if r["duplicates_dropped"] == 0:
                failures.append(
                    f"{protocol} saw no duplicates under dup chaos — the "
                    "reliable channel is not engaged"
                )
        print(
            json.dumps(
                {
                    "config": "protocol_comparison_chaos_smoke",
                    **res,
                    "failures": failures,
                }
            )
        )
        if failures:
            sys.exit(1)
        return

    if args.smoke:
        # CI gate: the codec path end to end on a small stream, with the
        # acceptance bars enforced (nonzero exit on regression)
        records = min(args.records, 6_000)
        par = min(args.parallelism, 4)
        sweep = codecs or ("none", "int8")
        comp = run_codec_comparison(
            sweep, records, par, min(args.batch, 64),
            protocols=("Asynchronous", "Synchronous"),
        )
        dist = run_distributed_route(sweep, steps=12)
        failures = []
        for protocol, rows in comp.items():
            for codec, r in rows.items():
                if codec == "int8":
                    if r["wire_reduction_vs_none"] < 3.5:
                        failures.append(
                            f"{protocol}/int8 host wire reduction "
                            f"{r['wire_reduction_vs_none']}x < 3.5x"
                        )
                    if abs(r["score_delta_vs_none"]) > 0.05:
                        failures.append(
                            f"{protocol}/int8 score drift "
                            f"{r['score_delta_vs_none']} > 0.05"
                        )
        if "int8" in dist:
            if dist["int8"]["wire_reduction_vs_none"] < 3.5:
                failures.append(
                    "distributed route int8 wire reduction "
                    f"{dist['int8']['wire_reduction_vs_none']}x < 3.5x"
                )
            if dist["int8"]["param_drift_rel"] > 0.05:
                failures.append(
                    "distributed route int8 param drift "
                    f"{dist['int8']['param_drift_rel']} > 0.05"
                )
        print(
            json.dumps(
                {
                    "config": "protocol_comparison_smoke",
                    "records": records,
                    "codec_comparison": comp,
                    "distributed_route": dist,
                    "failures": failures,
                }
            )
        )
        if failures:
            sys.exit(1)
        return

    rng = np.random.RandomState(0)
    w = np.random.RandomState(42).randn(28)
    x = rng.randn(args.records, 28).astype(np.float32)
    y = (x @ w > 0).astype(np.float32)

    # untimed warmup: the jitted fit/eval/chained-fit programs are shared
    # by (learner, dim, batch) spec, so one run compiles for all — sized
    # for several full batches per worker so the blocked-batch chain
    # program compiles too (it only traces once >= 2 batches are pending)
    warm = min(args.parallelism * args.batch * 4, args.records)
    run_one(PROTOCOLS[0], x[:warm], y[:warm], args.parallelism, args.batch)

    out = {}
    for protocol in PROTOCOLS:
        # the full-comparison rows run telemetry-armed (heartbeats off,
        # phases on) so every result row carries the phase-breakdown
        # table + launch gauges alongside the traffic counters — BENCH
        # rounds see WHERE each protocol's wall time goes
        out[protocol] = run_one(
            protocol, x, y, args.parallelism, args.batch,
            telemetry="statsEvery=100000000",
        )

    # SPMD collective engine: same stream, same scoring, the 6 protocols
    # with device-plane equivalents on the 8-worker virtual mesh
    run_one(
        SPMD_PROTOCOLS[0], x[:warm], y[:warm], args.parallelism, args.batch,
        engine="spmd",
    )
    out_spmd = {}
    for protocol in SPMD_PROTOCOLS:
        r = run_one(
            protocol, x, y, args.parallelism, args.batch, engine="spmd"
        )
        host = out[protocol]
        r["speedup_vs_host_plane"] = round(
            r["examples_per_sec"] / max(host["examples_per_sec"], 1e-9), 2
        )
        r["score_parity_abs_diff"] = round(
            abs(r["score"] - host["score"]), 4
        )
        out_spmd[protocol] = r

    # transport-codec sections (--codec): params-dominated host stream
    # sweep + the distributed model-exchange route
    codec_out = {}
    if codecs:
        codec_out["codec_comparison"] = run_codec_comparison(
            codecs, max(args.records // 2, 10_000), args.parallelism,
            args.batch,
        )
        codec_out["distributed_route"] = run_distributed_route(codecs)
    # multi-tenant sweep (--pipelines): N co-hosted same-spec pipelines,
    # per-pipeline dispatch vs cohort gang dispatch (runtime.cohort)
    if args.pipelines:
        counts = [int(p) for p in args.pipelines.split(",") if p]
        codec_out["multi_tenant"] = run_multi_tenant(
            counts, min(args.records, 40_000), 256
        )
    # forecast-mix serving section (--forecast-mix): per-record serving vs
    # the adaptive-batching plane (exact + relaxed) on a forecast-heavy
    # stream at 64 co-hosted tenants (runtime/serving.py)
    if args.forecast_mix > 0:
        codec_out["serving"] = run_serving_comparison(
            args.forecast_mix, min(args.records, 40_000), 256
        )
    # chaos resilience section (--chaos): protocols under the seeded lossy
    # channel, score envelope + resilience counters
    if args.chaos:
        spec = DEFAULT_CHAOS if args.chaos == "default" else args.chaos
        codec_out["chaos_resilience"] = run_chaos_resilience(
            SPMD_PROTOCOLS, max(args.records // 4, 8_000),
            args.parallelism, args.batch, chaos=spec,
        )
    print(
        json.dumps(
            {
                "config": "protocol_comparison",
                "metric": "per-protocol examples/sec, score, traffic",
                "parallelism": args.parallelism,
                "records": args.records,
                # the host-plane protocol rows run TELEMETRY-ARMED as of
                # PR 13 (phase tables + launch gauges in every row):
                # examples_per_sec carries the plane's <= 3% hook
                # overhead, so cross-round trends against earlier
                # unarmed rows see that baseline shift, not a protocol
                # change
                "telemetry_armed_rows": True,
                "protocols": out,
                "protocols_spmd": out_spmd,
                **codec_out,
                "spmd_basis": (
                    "virtual 8-device CPU mesh: protocol SEMANTICS, score "
                    "parity and traffic accounting — NOT chip throughput "
                    "(8 virtual devices emulate collectives on one CPU "
                    "core, so examples/sec reflects XLA CPU emulation "
                    "overhead; the engine's real-chip throughput is the "
                    "avazu_softmax and e2e configs of run_benchmarks.py, "
                    "which exceed every host-plane figure here)"
                ),
                "note": (
                    "protocols_spmd: bytes_physical counts executed "
                    "collective rounds + scalar vote channels (gated "
                    "Async/SSP folds), bytes_shipped the application "
                    "payload accounting"
                ),
            }
        )
    )


if __name__ == "__main__":
    main()
