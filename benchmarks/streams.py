"""Shared synthetic stream generators for the benchmark harnesses.

``run_benchmarks.py``'s legs historically exercised training-dominated
streams only (forecast ops were a thin sprinkle, e.g. the multi-tenant
sweep's 0%); the serving plane needs forecast-HEAVY streams measured the
same way everywhere. This module is the one definition shared by the
``protocol_comparison.py --serve-smoke`` CI gate and the
``--forecast-mix`` sweep that ``run_benchmarks.py`` records each BENCH
round — so the gate and the trajectory always measure the same task.
"""

from __future__ import annotations

import numpy as np


def forecast_stream(records: int, dim: int = 28, mix: float = 0.5,
                    seed: int = 0, tail_train: int = 768):
    """A linearly-separable stream with a ``mix`` fraction of forecasting
    rows spread evenly across stream positions.

    Returns ``(x, y, op)`` for the packed route: ``x [n, dim]`` float32
    features, ``y [n]`` float32 targets (zeros on forecast rows — the
    packed path ignores them), ``op [n]`` uint8 (0=training,
    1=forecasting). The forecast positions are deterministic in
    ``(records, mix)``: every ``round(1/mix)``-th row when mix <= 0.5,
    the complement pattern above — so a 0.5 mix strictly alternates and
    consecutive runs are reproducible without an op-level RNG draw.

    The last ``tail_train`` rows are training-only: forecasts queued by
    the adaptive-batching plane then drain through the LIVE flush
    triggers (fill / model fence / deadline) rather than the terminate
    probe, so measured latency percentiles reflect steady-state serving,
    not shutdown."""
    if not 0.0 <= mix < 1.0:
        raise ValueError(f"forecast mix must be in [0, 1), got {mix}")
    rng = np.random.RandomState(seed)
    w = np.random.RandomState(42).randn(dim)
    x = rng.randn(records, dim).astype(np.float32)
    y = (x @ w > 0).astype(np.float32)
    op = np.zeros((records,), np.uint8)
    if mix > 0:
        if mix <= 0.5:
            stride = int(round(1.0 / mix))
            op[::stride] = 1
        else:
            # mostly-forecast stream: mark the TRAINING rows by stride
            stride = int(round(1.0 / (1.0 - mix)))
            op[:] = 1
            op[::stride] = 0
        if 0 < tail_train < records:
            op[records - tail_train:] = 0
    y[op != 0] = 0.0
    return x, y, op
