"""Per-component breakdown of the L4096 LM train step (real chip).

The whole-model MFU (39-40% in round 4) sits well below the flash
attention kernel's 55-56%; this script decomposes the gap by measuring
each component AT THE MODEL'S OWN SHAPES with the same chained-dispatch
methodology as the benchmarks (one jitted program per measurement, real
D2H fetch as the barrier):

- full train step (fused cross-entropy)       <- the headline
- full train step (unfused log_softmax loss)  <- the round-4 baseline
- forward-only (loss, no grad)
- flash attention fwd+bwd alone at [B*H, L, dh]
- FFN + qkv/out projections alone (the dense matmul stack), fwd+bwd
- LM head cross-entropy alone: fused chunked vs unfused, fwd+bwd
- embedding gather + rms norms alone, fwd+bwd

Residual = full - (attention + matmuls + head + embed) ~ optimizer,
reductions, fusion boundaries. Components overlap slightly (norms ride
with blocks), so the table is a decomposition, not an exact partition;
it is committed to RESULTS as `lm_step_breakdown` and answers WHERE the
non-attention time goes (VERDICT round-4 weak #4).
"""

import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np

_cache = os.path.join(os.path.expanduser("~"), ".cache", "omldm_tpu", "xla")
os.makedirs(_cache, exist_ok=True)
jax.config.update("jax_compilation_cache_dir", _cache)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

from omldm_tpu.models.transformer import (  # noqa: E402
    TransformerConfig, init_transformer, lm_loss,
)
from omldm_tpu.parallel.seq_trainer import SeqTrainer, make_seq_mesh  # noqa: E402

B, L, V, D, FF, NL, NH = 2, 4096, 8192, 512, 2048, 4, 4
CHAIN = 8
ROUNDS = 6


def materialize(x):
    return float(np.asarray(jax.tree_util.tree_leaves(x)[0]).reshape(-1)[0])


def timed(name, launch, work_per_round):
    launch()  # compile + warm
    best = float("inf")
    for _ in range(ROUNDS):
        t0 = time.perf_counter()
        launch()
        best = min(best, time.perf_counter() - t0)
    print(f"{name:34s} {best * 1e3 / CHAIN:9.2f} ms/step", flush=True)
    return {"ms_per_step": best * 1e3 / CHAIN, "per_sec": work_per_round / best}


def chain_grad(loss_fn, params, batches):
    """CHAIN chained grad+sgd steps in one program (tunnel rules)."""

    @jax.jit
    def run(p, bs):
        def body(pp, b_):
            g = jax.grad(loss_fn)(pp, *b_)
            pp = jax.tree_util.tree_map(lambda w, gg: w - 1e-3 * gg, pp, g)
            return pp, ()

        p, _ = jax.lax.scan(body, p, bs)
        return p

    return lambda: materialize(run(params, batches)["head"])


def main():
    print(f"devices: {jax.devices()}", flush=True)
    rng = np.random.RandomState(0)
    out = {}

    cfg_fused = TransformerConfig(
        vocab_size=V, d_model=D, n_heads=NH, n_layers=NL, d_ff=FF,
        max_len=L, dtype=jnp.bfloat16, loss_chunk=1024,
    )
    cfg_plain = TransformerConfig(
        vocab_size=V, d_model=D, n_heads=NH, n_layers=NL, d_ff=FF,
        max_len=L, dtype=jnp.bfloat16,
    )
    toks = jnp.asarray(
        rng.randint(0, V, size=(CHAIN, B, L)).astype(np.int32)
    )
    tgts = jnp.asarray(np.roll(np.asarray(toks), -1, 2))
    mask = jnp.ones((CHAIN, B, L), jnp.float32)
    tok_per_round = CHAIN * B * L

    # full step through the production trainer (fused + unfused)
    for tag, cfg in (("full_step_fused", cfg_fused),
                     ("full_step_unfused", cfg_plain)):
        tr = SeqTrainer(cfg, mesh=make_seq_mesh(1, 1, 1), lr=1e-3)

        def launch(tr=tr):
            losses = tr.step_many(toks, tgts, mask)
            return materialize(losses[-1])

        out[tag] = timed(tag, launch, tok_per_round)

    # forward-only loss
    params = init_transformer(cfg_fused, jax.random.PRNGKey(0))

    @jax.jit
    def fwd_chain(p, ts, gs, ms):
        def body(acc, b_):
            t, g, m = b_
            return acc + lm_loss(cfg_fused, p, t, g, m), ()

        acc, _ = jax.lax.scan(body, jnp.float32(0.0), (ts, gs, ms))
        return acc

    out["forward_only"] = timed(
        "forward_only",
        lambda: materialize(fwd_chain(params, toks, tgts, mask)),
        tok_per_round,
    )

    # flash attention alone at the model's shapes [B*H, L, dh]
    from omldm_tpu.ops.attention import attention

    dh = D // NH
    q = jnp.asarray(rng.randn(B * NH, L, dh).astype(np.float32)).astype(jnp.bfloat16)
    k, v = q + 1e-3, q - 1e-3

    def attn_loss(qkv):
        qq, kk, vv = qkv
        return attention(qq, kk, vv, causal=True).astype(jnp.float32).sum()

    @jax.jit
    def attn_chain(qkv):
        def body(acc, _):
            g = jax.grad(attn_loss)((qkv[0], qkv[1], qkv[2]))
            return (acc + g[0][0, 0, 0].astype(jnp.float32), ())
        # NL layers per model step, CHAIN steps
        acc, _ = jax.lax.scan(
            body, jnp.float32(0.0), None, length=CHAIN * NL
        )
        return acc

    out["attention_fwd_bwd"] = timed(
        "attention_fwd_bwd (xNL layers)",
        lambda: materialize(attn_chain((q, k, v))),
        tok_per_round,
    )

    # dense matmul stack alone (qkv + out + mlp per layer), fwd+bwd
    x0 = jnp.asarray(rng.randn(B * L, D).astype(np.float32)).astype(jnp.bfloat16)
    wq = jnp.asarray(rng.randn(D, 3 * D).astype(np.float32)).astype(jnp.bfloat16)
    wo = jnp.asarray(rng.randn(D, D).astype(np.float32)).astype(jnp.bfloat16)
    w1 = jnp.asarray(rng.randn(D, FF).astype(np.float32)).astype(jnp.bfloat16)
    w2 = jnp.asarray(rng.randn(FF, D).astype(np.float32)).astype(jnp.bfloat16)

    def stack_loss(ws, xx):
        wq_, wo_, w1_, w2_ = ws
        h = xx @ wq_
        h = h[:, :D] @ wo_
        h = jax.nn.gelu(h @ w1_) @ w2_
        return h.astype(jnp.float32).sum()

    @jax.jit
    def stack_chain(ws, xx):
        def body(acc, _):
            g = jax.grad(stack_loss)(ws, xx)
            return acc + g[0][0, 0].astype(jnp.float32), ()

        acc, _ = jax.lax.scan(body, jnp.float32(0.0), None, length=CHAIN * NL)
        return acc

    out["dense_matmuls_fwd_bwd"] = timed(
        "dense matmuls fwd+bwd (xNL)",
        lambda: materialize(stack_chain((wq, wo, w1, w2), x0)),
        tok_per_round,
    )

    # LM head cross-entropy alone: fused vs unfused
    from omldm_tpu.models.transformer import _lm_nll_fused

    head = jnp.asarray(rng.randn(D, V).astype(np.float32)).astype(jnp.bfloat16)
    ts_flat = jnp.asarray(rng.randint(0, V, size=(B * L,)).astype(np.int32))
    ms_flat = jnp.ones((B * L,), jnp.float32)

    def head_fused(h_, x_):
        return _lm_nll_fused(h_, x_, ts_flat, ms_flat, 1024)

    def head_unfused(h_, x_):
        logits = (x_ @ h_).astype(jnp.float32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, ts_flat[:, None], axis=-1)[:, 0]
        return jnp.sum(nll * ms_flat)

    for tag, fn in (("head_ce_fused", head_fused),
                    ("head_ce_unfused", head_unfused)):

        @jax.jit
        def head_chain(h_, x_, fn=fn):
            def body(acc, _):
                g = jax.grad(fn)(h_, x_)
                return acc + g[0, 0].astype(jnp.float32), ()

            acc, _ = jax.lax.scan(body, jnp.float32(0.0), None, length=CHAIN)
            return acc

        out[tag] = timed(
            tag, lambda hc=head_chain: materialize(hc(head, x0)), tok_per_round
        )

    full = out["full_step_fused"]["ms_per_step"]
    attn = out["attention_fwd_bwd"]["ms_per_step"]
    mats = out["dense_matmuls_fwd_bwd"]["ms_per_step"]
    headt = out["head_ce_fused"]["ms_per_step"]
    out["residual_ms_per_step"] = round(full - attn - mats - headt, 3)
    print(json.dumps({"lm_step_breakdown": out}, indent=1), flush=True)
    with open(
        os.path.join(os.path.dirname(__file__), "LM_BREAKDOWN.json"), "w"
    ) as f:
        json.dump({"lm_step_breakdown": out}, f, indent=1)


if __name__ == "__main__":
    main()
