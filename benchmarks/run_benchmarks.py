"""The five BASELINE.md benchmark configs + prediction-latency measurement.

Each config reproduces the shape of its dataset (no network egress in this
environment, so streams are synthetic with matching dimensionality and task):

1. HIGGS binary (28 numeric)            -> online logistic regression
2. YearPredictionMSD (90 numeric, reg)  -> online ridge regression (ORR)
3. Criteo CTR (13 numeric + 26 hashed)  -> PA-I / PA-II classifier
4. SUSY (18 numeric)                    -> pegasos SVM + random-Fourier feats
5. Avazu CTR (hashed categorical)       -> softmax + hashed features,
                                           8-way data-parallel allreduce
                                           (SPMD; virtual devices when only
                                           one chip is present)

Plus the second north-star metric: prediction-stream p50 latency through the
serving path (single record, padded predict batch).

Usage: python benchmarks/run_benchmarks.py [--steps N]
Prints one JSON line per config.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _materialize(tree) -> float:
    """TRUE completion barrier: fetch one element to host. On this
    environment's TPU tunnel, ``jax.block_until_ready`` returns without
    waiting for some executables (measured: a 9600-step scatter chain
    "completed" in 0.14 ms under block_until_ready; the same chain takes
    23 s when an output element is actually fetched) — every timed region
    must end in a device->host read or it times the dispatch, not the
    work."""
    import jax

    leaf = jax.tree_util.tree_leaves(tree)[0]
    return float(np.asarray(leaf).ravel()[0])


def _throughput(pipe, stage, steps):
    """Steady-state training throughput with device-resident staged batches
    (models a double-buffered prefetch pipeline; in this environment the TPU
    sits behind a network tunnel whose host->device bandwidth would otherwise
    dominate and measure the tunnel, not the framework). Batches chain
    through MLPipeline.fit_many — the same one-launch-per-T-batches path the
    protocol workers use to drain a backlog (WorkerNode.drain_blocked)."""
    import jax

    xs = np.stack([b[0] for b in stage])
    ys = np.stack([b[1] for b in stage])
    masks = np.stack([b[2] for b in stage])
    counts = masks.sum(axis=tuple(range(1, masks.ndim)))
    xs_d, ys_d, masks_d = (jax.device_put(a) for a in (xs, ys, masks))
    t = xs.shape[0]
    pipe.fit_many(xs_d, ys_d, masks_d, valid_counts=counts)  # warmup/compile
    _materialize(pipe.state["params"])
    rounds = max(steps // t, 1)
    t0 = time.perf_counter()
    for _ in range(rounds):
        pipe.fit_many(xs_d, ys_d, masks_d, valid_counts=counts)
    _materialize(pipe.state["params"])
    return rounds * t * stage[0][0].shape[0] / (time.perf_counter() - t0)


def _stage_binary(dim, batch, n_stage=16, seed=0):
    rng = np.random.RandomState(seed)
    w = rng.randn(dim)
    out = []
    for _ in range(n_stage):
        x = rng.randn(batch, dim).astype(np.float32)
        y = (x @ w > 0).astype(np.float32)
        out.append((x, y, np.ones(batch, np.float32)))
    return out


def _stage_regression(dim, batch, n_stage=16, seed=0):
    rng = np.random.RandomState(seed)
    w = rng.randn(dim)
    out = []
    for _ in range(n_stage):
        x = rng.randn(batch, dim).astype(np.float32)
        y = (x @ w + 0.1 * rng.randn(batch)).astype(np.float32)
        out.append((x, y, np.ones(batch, np.float32)))
    return out


def bench_higgs_lr(steps):
    from omldm_tpu.api.requests import LearnerSpec, PreprocessorSpec
    from omldm_tpu.pipelines import MLPipeline

    pipe = MLPipeline(
        LearnerSpec("Softmax", hyper_parameters={"learningRate": 0.05, "nClasses": 2}),
        [PreprocessorSpec("StandardScaler")],
        dim=28,
    )
    return "higgs_logreg", _throughput(pipe, _stage_binary(28, 4096), steps), {"basis": "hot-loop"}


def bench_msd_orr(steps):
    from omldm_tpu.api.requests import LearnerSpec, PreprocessorSpec
    from omldm_tpu.pipelines import MLPipeline

    pipe = MLPipeline(
        LearnerSpec("ORR", hyper_parameters={"lambda": 1.0}),
        [PreprocessorSpec("StandardScaler")],
        dim=90,
    )
    return "yearpredictionmsd_orr", _throughput(pipe, _stage_regression(90, 4096), steps), {"basis": "hot-loop"}


def bench_criteo_pa(steps):
    from omldm_tpu.api.requests import LearnerSpec
    from omldm_tpu.pipelines import MLPipeline

    dim = 13 + 256  # 13 numeric + 26 categoricals hashed into 256 buckets
    pipe = MLPipeline(
        LearnerSpec("PA", hyper_parameters={"C": 0.1, "variant": "PA-II"}),
        dim=dim,
    )
    return "criteo_pa", _throughput(pipe, _stage_binary(dim, 4096), steps), {"basis": "hot-loop"}


def bench_susy_rff_svm(steps):
    from omldm_tpu.api.requests import LearnerSpec
    from omldm_tpu.pipelines import MLPipeline

    pipe = MLPipeline(
        LearnerSpec(
            "SVM",
            hyper_parameters={"lambda": 1e-4},
            data_structure={"rffDim": 512, "gamma": 0.5},
        ),
        dim=18,
    )
    return "susy_rff_svm", _throughput(pipe, _stage_binary(18, 4096), steps), {"basis": "hot-loop"}


def bench_avazu_softmax_dp8(steps):
    """8-way data-parallel softmax over the SPMD engine."""
    import jax

    from omldm_tpu.api.requests import LearnerSpec, TrainingConfiguration
    from omldm_tpu.parallel import SPMDTrainer, make_mesh

    n_dev = len(jax.devices())
    dp = min(8, n_dev)
    mesh = make_mesh(dp=dp, hub=1)
    dim, batch = 13 + 512, 2048 // dp if dp > 1 else 2048
    trainer = SPMDTrainer(
        LearnerSpec("Softmax", hyper_parameters={"learningRate": 0.05, "nClasses": 2}),
        dim=dim,
        protocol="Synchronous",
        mesh=mesh,
        training_configuration=TrainingConfiguration(
            protocol="Synchronous", extra={"syncEvery": 1}
        ),
        batch_size=batch,
    )
    from jax.sharding import NamedSharding, PartitionSpec as P

    rng = np.random.RandomState(0)
    w = rng.randn(dim)
    t = 8
    xs = rng.randn(t, dp, batch, dim).astype(np.float32)
    ys = (xs @ w > 0).astype(np.float32)
    masks = np.ones((t, dp, batch), np.float32)
    counts = masks.sum(axis=(1, 2))
    sharding = NamedSharding(mesh, P(None, "dp"))
    xs_d = jax.device_put(xs, sharding)
    ys_d = jax.device_put(ys, sharding)
    masks_d = jax.device_put(masks, sharding)
    # chained fleet steps: one launch per T batches (protocol collectives
    # included in every scanned step)
    trainer.step_many(xs_d, ys_d, masks_d, valid_counts=counts)  # warmup
    _materialize(trainer.state["params"])
    rounds = max(steps // t, 1)
    t0 = time.perf_counter()
    for _ in range(rounds):
        trainer.step_many(xs_d, ys_d, masks_d, valid_counts=counts)
    _materialize(trainer.state["params"])
    thr = rounds * t * dp * batch / (time.perf_counter() - t0)
    return f"avazu_softmax_dp{dp}", thr, {"basis": "hot-loop"}


def bench_longctx_transformer(steps):
    """Long-context extension: causal-LM transformer tokens/sec on one chip
    (the multi-chip sp/tp/pp paths are validated on the virtual CPU mesh;
    this measures the single-chip compute path with the dispatched
    flash-attention kernel)."""
    return _longctx_bench(
        "longctx_transformer_lm", steps, max_len=1024, b=8, t=8
    )


def bench_longctx_transformer_4k(steps):
    """Attention-dominant regime: the same LM at 4096-token context,
    training through the Pallas flash forward+backward kernels (at this
    length attention is the majority of the step FLOPs)."""
    return _longctx_bench(
        "longctx_transformer_lm_L4096", steps, max_len=4096, b=2, t=4
    )


def _lm_train_flops_per_token(cfg) -> float:
    """Matmul training FLOPs per token, computed from the actual layer
    dims (no 6N hand-waving): fwd = qkv + attn(causal) + out-proj + mlp +
    lm-head, train = 3x fwd (bwd ~ 2x fwd for matmul-dominated nets)."""
    d, ff, l = cfg.d_model, cfg.d_ff, cfg.max_len
    per_layer = (
        2 * d * 3 * d          # qkv projection
        + 2 * 2 * l * d / 2    # QK^T + PV, causal half
        + 2 * d * d            # output projection
        + 2 * d * ff * 2       # mlp up + down
    )
    head = 2 * d * cfg.vocab_size
    return 3.0 * (cfg.n_layers * per_layer + head)


def _longctx_bench(name, steps, max_len, b, t):
    """One shared LM (only context length and batch vary between the
    configs, so the L1024 vs L4096 comparison stays apples-to-apples).
    TPU-native sizing: dh = d_model/n_heads = 128 fills the MXU's
    128-deep systolic array in the attention contractions."""
    import jax.numpy as jnp

    from omldm_tpu.models.transformer import TransformerConfig
    from omldm_tpu.parallel.seq_trainer import SeqTrainer, make_seq_mesh

    cfg = TransformerConfig(
        vocab_size=8192, d_model=512, n_heads=4, n_layers=4, d_ff=2048,
        max_len=max_len, dtype=jnp.bfloat16,  # fp32 master, bf16 compute
        # fused chunked cross-entropy: never materializes the [B*L, 8192]
        # f32 logits (the dominant non-attention HBM traffic of this model)
        loss_chunk=1024,
    )
    trainer = SeqTrainer(cfg, mesh=make_seq_mesh(1, 1, 1), lr=1e-3)
    rng = np.random.RandomState(0)
    tokens = rng.randint(0, 8192, size=(t, b, max_len)).astype(np.int32)
    return _longctx_run(trainer, tokens, steps, name, cfg)


def _longctx_run(trainer, tokens, steps, name, cfg=None):
    import jax

    t, b, l = tokens.shape
    targets = np.roll(tokens, -1, axis=2)
    masks = np.ones((t, b, l), np.float32)
    counts = masks.sum(axis=(1, 2))
    # pre-stage on device and chain T steps per launch: this environment's
    # TPU tunnel costs a full round trip per program dispatch, which would
    # otherwise dominate the step time
    tokens_d, targets_d, masks_d = (
        jax.device_put(a) for a in (tokens, targets, masks)
    )
    losses = trainer.step_many(tokens_d, targets_d, masks_d, valid_counts=counts)
    float(np.asarray(losses[-1]))  # warmup + true completion barrier
    rounds = max(steps // t, 4)
    t0 = time.perf_counter()
    for _ in range(rounds):
        losses = trainer.step_many(tokens_d, targets_d, masks_d, valid_counts=counts)
    float(np.asarray(losses[-1]))  # materialize: full end-to-end barrier
    thr = rounds * t * b * l / (time.perf_counter() - t0)
    if cfg is None:
        return name, thr
    # FLOPs accounting: tokens/sec of an unspecified model is not a perf
    # claim — report the model size, train FLOPs/token and MFU alongside
    n_params = sum(
        int(np.prod(p.shape))
        for p in jax.tree_util.tree_leaves(trainer.params)
    )
    fpt = _lm_train_flops_per_token(cfg)
    tflops = thr * fpt / 1e12
    return name, thr, {
        "basis": "hot-loop",
        "model": (
            f"d{cfg.d_model} h{cfg.n_heads} (dh="
            f"{cfg.d_model // cfg.n_heads}) x{cfg.n_layers}L "
            f"ff{cfg.d_ff} V{cfg.vocab_size}"
        ),
        "params_m": round(n_params / 1e6, 2),
        "train_flops_per_token_m": round(fpt / 1e6, 3),
        "achieved_tflops": round(tflops, 2),
        "peak_tflops": V5E_BF16_PEAK_TFLOPS,
        "mfu": round(tflops / V5E_BF16_PEAK_TFLOPS, 3),
    }


def _bench_sparse(name, learner_spec, dim, k, steps, batch=4096):
    """Sparse padded-COO training throughput at a realistic hashed width:
    the model vector stays dense on device, each record touches k active
    features (gather-dot forward, scatter-add update).

    The staged batches are device_put ONCE, like every other hot-loop
    config. Round 3 passed host numpy arrays into each chained call, so
    the timed loop re-uploaded ~20 MB of idx/val per round through this
    environment's ~15 MB/s TPU tunnel — the committed 133k examples/sec
    was a transfer artifact 1000x below the device rate, not a sparse-op
    ceiling (the gather/scatter path itself clears 100M examples/sec)."""
    import jax
    import jax.numpy as jnp

    from omldm_tpu.learners.registry import make_learner

    learner = make_learner(learner_spec)
    params = learner.init(dim, jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    n_stage = 8
    idx = rng.randint(0, dim, size=(n_stage, batch, k)).astype(np.int32)
    val = rng.randn(n_stage, batch, k).astype(np.float32)
    w_hid = rng.randn(dim).astype(np.float32) * 0.2
    y = np.stack([
        (np.take(w_hid, idx[t]).reshape(batch, k) * val[t]).sum(1) > 0
        for t in range(n_stage)
    ]).astype(np.float32)
    rounds = max(steps // n_stage, 8)

    @jax.jit
    def big_chain(p, idxs, vals, ys, mask):
        # the whole measurement is ONE program (rounds x n_stage scanned
        # steps): per-dispatch tunnel round trips would otherwise dominate
        # a sub-millisecond chain (the device rate is >100M examples/sec).
        # mask is a real ARGUMENT — a closed-over device array becomes an
        # executable-embedded constant that this environment re-stages
        # through the TPU tunnel on EVERY call (~85 ms per dispatch,
        # measured; see PARITY.md round-4 notes)
        def round_body(pp, _):
            def body(ppp, b):
                ii, vv, yy = b
                ppp, loss = learner.update(ppp, (ii, vv), yy, mask)
                return ppp, loss

            pp, losses = jax.lax.scan(body, pp, (idxs, vals, ys))
            return pp, losses[-1]

        p, _ = jax.lax.scan(round_body, p, None, length=rounds)
        return p

    idx_d, val_d, y_d, mask_d = (
        jax.device_put(a)
        for a in (idx, val, y, np.ones((batch,), np.float32))
    )
    _materialize((idx_d, val_d, y_d, mask_d))
    params = big_chain(params, idx_d, val_d, y_d, mask_d)  # warmup/compile
    _materialize(params)
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        params = big_chain(params, idx_d, val_d, y_d, mask_d)
        _materialize(params)  # real barrier; see _materialize
        best = min(best, time.perf_counter() - t0)
    thr = rounds * n_stage * batch / best
    return name, thr, {
        "basis": "hot-loop",
        "nnz_per_record": k,
        "model_width": dim,
        "steps_per_dispatch": rounds * n_stage,
        "note": (
            "bound by XLA's TPU scatter element rate (~66M scattered "
            "updates/sec measured at this width); the gather-dot forward "
            "alone runs >100x faster. k scattered updates per example."
        ),
    }


def bench_criteo_sparse_pa(steps):
    """BASELINE config 3 at REAL Criteo dimensionality: 13 numeric + 26
    categoricals hashed into 2^18 (not densified through a fixed width)."""
    from omldm_tpu.api.requests import LearnerSpec

    dim = 13 + (1 << 18)
    return _bench_sparse(
        "criteo_sparse_pa_2e18",
        LearnerSpec("PA", hyper_parameters={"C": 0.1, "variant": "PA-II"},
                    data_structure={"sparse": True, "nFeatures": dim}),
        dim=dim, k=39, steps=steps,
    )


def bench_avazu_sparse_softmax(steps):
    """BASELINE config 5 at REAL Avazu dimensionality: 21 categorical slots
    hashed into 2^20."""
    from omldm_tpu.api.requests import LearnerSpec

    dim = 1 << 20
    return _bench_sparse(
        "avazu_sparse_softmax_2e20",
        LearnerSpec("Softmax",
                    hyper_parameters={"learningRate": 0.05, "nClasses": 2},
                    data_structure={"sparse": True, "nFeatures": dim}),
        dim=dim, k=21, steps=steps,
    )


def _gen_sparse_stream_file(path, n_records, n_num=13, n_cat=26, seed=0):
    """Criteo-shaped sparse stream: 13 numerics + 26 categorical strings."""
    rng = np.random.RandomState(seed)
    w = rng.randn(n_num)
    with open(path, "w") as f:
        chunk = 20_000
        written = 0
        while written < n_records:
            n = min(chunk, n_records - written)
            x = np.round(rng.randn(n, n_num), 6)
            y = (x @ w > 0).astype(np.float32)
            cats = rng.randint(0, 1000, size=(n, n_cat))
            lines = [
                '{"numericalFeatures": [%s], "categoricalFeatures": [%s], '
                '"target": %.1f, "operation": "training"}'
                % (
                    ", ".join("%.6f" % v for v in x[i]),
                    ", ".join('"f%d_v%d"' % (j, cats[i, j])
                              for j in range(n_cat)),
                    y[i],
                )
                for i in range(n)
            ]
            f.write("\n".join(lines) + "\n")
            written += n
    return os.path.getsize(path)


def bench_criteo_sparse_stream_e2e(steps, n_records=300_000):
    """SPARSE end-to-end: JSON bytes (13 numerics + 26 categorical strings)
    -> padded-COO -> trained 2^18-width sparse params, through the REAL
    sparse CLI route (C COO parser with in-C zlib-CRC32 hashing ->
    SparseSPMDBridge staging -> collective steps). The sparse twin of
    e2e_json_to_params, decomposed the same way (host ceiling vs device
    rate; tunnel-corrected)."""
    import tempfile

    from omldm_tpu.config import JobConfig
    from omldm_tpu.runtime import StreamJob
    from omldm_tpu.runtime.job import REQUEST_STREAM

    dim = 13 + (1 << 18)
    tmp = tempfile.NamedTemporaryFile(suffix=".jsonl", delete=False)
    tmp.close()
    n_bytes = _gen_sparse_stream_file(tmp.name, n_records)  # not timed

    def make_job():
        create = {
            "id": 0,
            "request": "Create",
            "learner": {
                "name": "PA",
                "hyperParameters": {"C": 0.1, "variant": "PA-II"},
                "dataStructure": {
                    "sparse": True, "nFeatures": dim,
                    "hashSpace": 1 << 18, "maxNnz": 40,
                },
            },
            "preProcessors": [],
            "trainingConfiguration": {
                "protocol": "Synchronous", "engine": "spmd", "syncEvery": 4,
            },
        }
        job = StreamJob(JobConfig(parallelism=1, batch_size=4096))
        job.process_event(REQUEST_STREAM, json.dumps(create))
        [bridge] = job.spmd_bridges.values()
        return job, bridge

    # host ceiling: device stubbed, best of 3 after warmup
    job_h, bridge_h = make_job()

    class _Nop:
        fitted = 0

        def step(self, *a, **k):
            pass

        def predict(self, x):
            return np.zeros((1,))

    bridge_h.trainer = _Nop()
    assert bridge_h.supports_fused_ingest(), (
        "sparse fused ingest unavailable (native parser missing?) — "
        "refusing to fabricate an e2e figure"
    )
    bridge_h.ingest_file(tmp.name)  # warmup (page cache, lib build)
    host_samples = []
    for _ in range(3):
        t0 = time.perf_counter()
        bridge_h.ingest_file(tmp.name)  # SERIAL: the parse ceiling
        bridge_h.flush()
        host_samples.append(time.perf_counter() - t0)
    t_host = min(host_samples)

    # raw run on the TPU (includes the tunnel) as a field — serial, so
    # raw vs raw_overlapped shows what the producer/consumer split buys
    job, bridge = make_job()
    t0 = time.perf_counter()
    bridge.ingest_file(tmp.name)
    bridge.flush()
    _materialize(bridge.trainer.state["params"])
    t_raw = time.perf_counter() - t0
    fitted = bridge.trainer.fitted

    # raw OVERLAPPED run (the route the CLI now takes): C parse + holdout
    # fill stage k+1 while the dispatch thread scatters stage k
    job_o, bridge_o = make_job()
    t0 = time.perf_counter()
    bridge_o.ingest_file_overlapped(tmp.name)
    bridge_o.flush()
    _materialize(bridge_o.trainer.state["params"])
    t_raw_overlapped = time.perf_counter() - t0

    # device rate: the sparse hot loop at the same width/nnz (honest
    # barrier inside _bench_sparse)
    _, dev_rate, _ = _bench_sparse(
        "sparse_dev_probe",
        __import__("omldm_tpu.api.requests", fromlist=["LearnerSpec"])
        .LearnerSpec(
            "PA", hyper_parameters={"C": 0.1, "variant": "PA-II"},
            data_structure={"sparse": True, "nFeatures": dim},
        ),
        dim=dim, k=40, steps=max(steps, 64),
    )
    t_device = n_records / dev_rate
    corrected = n_records / max(t_host, t_device)

    # MEASURED overlapped run with the device stubbed at its measured
    # rate (same design as the dense e2e: time.sleep models an
    # asynchronous accelerator without stealing this one-core host's CPU)
    job_m, bridge_m = make_job()
    bridge_m.trainer = _Nop()
    stub = lambda si, sv, sy, n: time.sleep(n / dev_rate)
    bridge_m.ingest_file_overlapped(tmp.name, train_fn=stub)  # warm
    bridge_m.flush()
    overlapped_samples = []
    for _ in range(3):
        t0 = time.perf_counter()
        # the final partial stage drains THROUGH the dispatch queue, so
        # the stub charges its device time inside the measured interval
        bridge_m.ingest_file_overlapped(tmp.name, train_fn=stub)
        bridge_m.flush()
        overlapped_samples.append(time.perf_counter() - t0)
    t_overlapped = min(overlapped_samples)
    overlapped_measured = n_records / t_overlapped

    os.unlink(tmp.name)
    from omldm_tpu.ops.sparse import _resolve_impl

    n_threads = bridge_h._make_coo_parser().n_threads
    return "criteo_sparse_stream_e2e_2e18", overlapped_measured, {
        "basis": "e2e stream-fed, MEASURED double-buffered overlapped run",
        "records": n_records,
        "stream_mb": round(n_bytes / 1e6, 1),
        # which of the three scatter kernels the calibration table picked
        # for this width/batch on the active backend, and which sparse
        # ingest route the bridge resolved (ops/sparse_dispatch.json;
        # SparseSPMDBridge._use_fused_coo)
        "scatter_impl": _resolve_impl(dim, 4096 * 40),
        "ingest_route": (
            "mt-parse+c-staging" if n_threads > 1 else "fused-line-loop"
        ),
        "parser_threads": n_threads,
        "overlapped_measured_examples_per_sec": round(overlapped_measured, 1),
        "overlapped_samples_s": [round(t, 3) for t in overlapped_samples],
        "overlapped_vs_bound": round(overlapped_measured / corrected, 3),
        "bound_examples_per_sec": round(corrected, 1),
        "host_pipeline_examples_per_sec": round(n_records / t_host, 1),
        "device_exec_examples_per_sec": round(dev_rate, 1),
        "raw_examples_per_sec": round(n_records / t_raw, 1),
        "raw_overlapped_examples_per_sec": round(
            n_records / t_raw_overlapped, 1
        ),
        "host_samples_s": [round(t, 3) for t in host_samples],
        "t_host_s": round(t_host, 3),
        "t_device_s": round(t_device, 3),
        "t_raw_s": round(t_raw, 3),
        "t_raw_overlapped_s": round(t_raw_overlapped, 3),
        "fitted": fitted,
        "note": (
            "value = MEASURED wall clock of the double-buffered run "
            "(C COO parse + holdout fill stage k+1 while the dispatch "
            "thread applies stage k at the separately-measured device "
            "scatter rate); bound = n / max(t_host, t_device). The host "
            "side is the C padded-COO parser (zlib-CRC32 categorical "
            "hashing in C) feeding the fused C holdout/staging pass; the "
            "device side the scatter path, dispatched from the "
            "calibration table (ops/sparse_dispatch.json)"
        ),
    }


V5E_BF16_PEAK_TFLOPS = 197.0  # TPU v5e (v5 lite) bf16 MXU peak, per chip


def bench_flash_attention(steps):
    """Pallas flash kernel vs the lax blockwise scan on the same chip:
    causal attention at L=8192 (the long-context hot op), bf16 operands
    with f32 accumulation. Reported value is the TPU-native head layout's
    (dh=128, full MXU systolic depth) causal forward TFLOP/s; the dh=64
    rows, MFU against the chip's bf16 peak, the lax figure and the
    speedup ride along as fields. Training figures differentiate w.r.t.
    ALL of q/k/v — a q-only grad lets XLA dead-code-eliminate the dk/dv
    kernel (the round-3 numbers had that bug and overstated train)."""
    import jax
    import jax.numpy as jnp

    from omldm_tpu.ops.attention import (
        blockwise_attention, flash_attention_pallas,
    )

    on_tpu = jax.devices()[0].platform == "tpu"
    rng = np.random.RandomState(0)
    b, l, h, dh = 4, 8192, 8, 64
    q = jnp.asarray(rng.randn(b, l, h, dh) * 0.1, jnp.bfloat16)
    k = jnp.asarray(rng.randn(b, l, h, dh) * 0.1, jnp.bfloat16)
    v = jnp.asarray(rng.randn(b, l, h, dh) * 0.1, jnp.bfloat16)
    flops = 4 * b * h * l * l * dh / 2  # causal half

    def measure_round_trip(x0):
        """One trivial jitted scalar fetch: the fixed dispatch + tunnel
        round-trip cost that chain_time must subtract so slow and fast
        kernels are not amortized unequally."""

        @jax.jit
        def rt(x):
            return x.sum()

        float(np.asarray(rt(x0)))  # compile + warm
        t0 = time.perf_counter()
        float(np.asarray(rt(x0)))
        return time.perf_counter() - t0

    def chain_time(apply, x0, chain):
        """Time ``chain`` data-dependent applications inside ONE jitted
        program, materializing a scalar: robust against async-dispatch
        artifacts (per-call timings through this environment's TPU tunnel
        can read near zero). The measured fixed round trip is subtracted
        before dividing, so comparisons between kernels of different
        speeds are not skewed by the per-launch overhead."""

        @jax.jit
        def run(x):
            def body(c, _):
                return apply(c), ()

            c, _ = jax.lax.scan(body, x, None, length=chain)
            return c.sum()

        float(np.asarray(run(x0)))  # compile + warm
        t0 = time.perf_counter()
        float(np.asarray(run(x0)))  # scalar fetch = full completion barrier
        total = time.perf_counter() - t0
        return max(total - measure_round_trip(x0), 1e-9) / chain

    # chains sized so kernel time >> the ~70 ms (and noisy) tunnel round
    # trip being subtracted — a chain comparable to the RT lets RT noise
    # inflate the result past physical peak. Chains may differ between the
    # fast pallas kernel and the slow lax scan: each side only needs its
    # own chain to dwarf the RT (the slow side reaches that with fewer
    # links).
    t_lax = chain_time(
        lambda x: blockwise_attention(x, k, v, causal=True), q, chain=32
    )
    if on_tpu:
        t_pl = chain_time(
            lambda x: flash_attention_pallas(x, k, v, causal=True), q,
            chain=96,
        )
    else:  # interpret mode is not a performance path; report lax only
        t_pl = t_lax
    # TRAINING path: forward + backward through the custom VJP (the Pallas
    # dq and dk/dv kernels recomputing scores from the saved logsumexp) vs
    # the lax blockwise VJP. Backward FLOPs ~ 2.5x forward (+1x for the
    # fwd pass the grad call re-runs). Measured at batch 1: the lax VJP's
    # saved score-sized temporaries OOM HBM at batch 4 / L=8192 (exactly
    # the blowup the kernel's recompute-from-logsumexp avoids).
    from omldm_tpu.ops.attention import attention

    q1, k1, v1 = q[:1], k[:1], v[:1]

    def grad_apply(use_pallas):
        # grad over ALL inputs — a q-only grad lets XLA dead-code-eliminate
        # the dk/dv kernel entirely and overstate the training figure (the
        # round-3 train numbers had exactly this bug)
        g = jax.grad(
            lambda q_, k_, v_: attention(
                q_, k_, v_, causal=True, use_pallas=use_pallas
            ).sum(),
            argnums=(0, 1, 2),
        )

        def apply(x):
            dq, dk, dv = g(x, k1, v1)
            return dq + dk + dv  # lq == lk: chainable

        return apply

    bwd_flops = (flops / b) * 3.5
    t_lax_g = chain_time(grad_apply(False), q1, chain=16)
    t_pl_g = (
        chain_time(grad_apply(True), q1, chain=48) if on_tpu else t_lax_g
    )

    # TPU-native head layout: dh=128 fills the MXU's 128-deep systolic
    # array on the QK^T/PV contractions — dh=64 caps those matmuls at half
    # rate, so this is the configuration the framework's models default to
    h2, dh2 = 4, 128
    q2 = jnp.asarray(rng.randn(b, l, h2, dh2) * 0.1, jnp.bfloat16)
    k2 = jnp.asarray(rng.randn(b, l, h2, dh2) * 0.1, jnp.bfloat16)
    v2 = jnp.asarray(rng.randn(b, l, h2, dh2) * 0.1, jnp.bfloat16)
    flops2 = 4 * b * h2 * l * l * dh2 / 2
    if on_tpu:
        t_pl2 = chain_time(
            lambda x: flash_attention_pallas(x, k2, v2, causal=True), q2,
            chain=96,
        )
        g2 = jax.grad(
            lambda q_, k_, v_: attention(
                q_, k_, v_, causal=True, use_pallas=True
            ).sum(),
            argnums=(0, 1, 2),
        )
        q21, k21, v21 = q2[:1], k2[:1], v2[:1]

        def train2(x):
            dq, dk, dv = g2(x, k21, v21)
            return dq + dk + dv

        t_pl2_g = chain_time(train2, q21, chain=48)
    else:
        t_pl2 = t_pl
        t_pl2_g = t_pl_g
    fwd128 = flops2 / t_pl2 / 1e12
    train128 = (flops2 / b) * 3.5 / t_pl2_g / 1e12

    return "flash_attention_L8192", fwd128, {
        "basis": "hot-loop",
        "dtype": "bfloat16 (f32 accum)",
        "peak_tflops": V5E_BF16_PEAK_TFLOPS,
        "dh128_fwd_tflops": round(fwd128, 2),
        "dh128_fwd_mfu": round(fwd128 / V5E_BF16_PEAK_TFLOPS, 3),
        "dh128_train_fwdbwd_tflops": round(train128, 2),
        "dh128_train_mfu": round(train128 / V5E_BF16_PEAK_TFLOPS, 3),
        "dh64_fwd_tflops": round(flops / t_pl / 1e12, 2),
        "dh64_fwd_mfu": round(flops / t_pl / 1e12 / V5E_BF16_PEAK_TFLOPS, 3),
        "dh64_train_fwdbwd_tflops": round(bwd_flops / t_pl_g / 1e12, 2),
        "dh64_train_mfu": round(
            bwd_flops / t_pl_g / 1e12 / V5E_BF16_PEAK_TFLOPS, 3
        ),
        "pallas_ms": round(t_pl * 1000, 2),
        "lax_blockwise_ms": round(t_lax * 1000, 2),
        "lax_blockwise_tflops": round(flops / t_lax / 1e12, 2),
        "speedup_vs_lax": round(t_lax / t_pl, 1),
        "pallas_compiled": on_tpu,
        "train_fwdbwd_pallas_ms": round(t_pl_g * 1000, 2),
        "train_fwdbwd_lax_ms": round(t_lax_g * 1000, 2),
        "train_speedup_vs_lax": round(t_lax_g / t_pl_g, 1),
        "note": (
            "dh=64 contractions run the 128-deep MXU at half rate; dh=128 "
            "is the TPU-native head sizing. Train differentiates q/k/v "
            "(all three backward kernels execute)."
        ),
    }


def _gen_stream_file(path, n_records, dim, seed=0):
    import numpy as np

    rng = np.random.RandomState(seed)
    w = rng.randn(dim)
    with open(path, "w") as f:
        chunk = 20_000
        written = 0
        while written < n_records:
            n = min(chunk, n_records - written)
            x = np.round(rng.randn(n, dim), 6)
            y = (x @ w > 0).astype(np.float32)
            lines = [
                '{"numericalFeatures": [%s], "target": %.1f, "operation": "training"}'
                % (", ".join("%.6f" % v for v in x[i]), y[i])
                for i in range(n)
            ]
            f.write("\n".join(lines) + "\n")
            written += n
    return os.path.getsize(path)


def bench_phase_attribution(path, dim, n_records, batch=256):
    """Phase-attributed breakdown of the streaming host-plane run (ISSUE
    13): the SAME JSON-lines stream through the packed host route with
    the telemetry plane armed — file read + C parse timed around the
    batch iterator, stage/holdout attributed by the spoke's phase hooks,
    fit by the flush StepTimer — so the ingest-wall work of ROADMAP #5
    starts from measured attribution. ``coverage`` is the fraction of the
    measured end-to-end wall the phase table accounts for (the acceptance
    bar is >= 0.9: anything unattributed is runtime glue, not a hot
    phase)."""
    import numpy as np

    from omldm_tpu.config import JobConfig
    from omldm_tpu.runtime import StreamJob
    from omldm_tpu.runtime.fast_ingest import iter_file_batches
    from omldm_tpu.runtime.job import REQUEST_STREAM

    def _make_job():
        job = StreamJob(JobConfig(
            parallelism=1, batch_size=batch, test_set_size=64,
            telemetry="statsEvery=1000000",
        ))
        job.process_event(REQUEST_STREAM, json.dumps({
            "id": 0,
            "request": "Create",
            "learner": {
                "name": "PA",
                "hyperParameters": {"C": 1.0},
                "dataStructure": {"nFeatures": dim},
            },
            "trainingConfiguration": {"protocol": "CentralizedTraining"},
        }))
        return job

    def _timed_run(job):
        phases = job.telemetry.phases
        it = iter_file_batches(path, dim, 32768)
        t_start = time.perf_counter()
        while True:
            t0 = time.perf_counter()
            b = next(it, None)
            # file read + C block parse live inside the iterator; the
            # fused route cannot split them, so both attribute to parse
            phases.note("parse", time.perf_counter() - t0)
            if b is None:
                break
            job.process_packed_batch(*b)
        return time.perf_counter() - t_start

    warm = _make_job()
    _timed_run(warm)  # warmup job compiles the shared fit programs
    warm.terminate()
    job = _make_job()  # fresh accounting: phases cover ONE measured run
    e2e = _timed_run(job)
    table = job.phase_table(e2e)
    job.terminate()
    return {
        "examples_per_sec": round(n_records / e2e, 1),
        "e2e_s": round(e2e, 3),
        "coverage": table.get("_coverage", 0.0),
        "phases": {
            k: v for k, v in table.items() if k != "_coverage"
        },
    }


def _make_e2e_job(dim, parallelism, chain):
    from omldm_tpu.config import JobConfig
    from omldm_tpu.runtime import StreamJob
    from omldm_tpu.runtime.job import REQUEST_STREAM

    create = {
        "id": 0,
        "request": "Create",
        "learner": {
            "name": "Softmax",
            "hyperParameters": {"learningRate": 0.05, "nClasses": 2},
            "dataStructure": {"nFeatures": dim},
        },
        "preProcessors": [],
        "trainingConfiguration": {
            "protocol": "Synchronous",
            "engine": "spmd",
            "extra": {"stageChain": chain},
        },
    }
    job = StreamJob(JobConfig(parallelism=parallelism, batch_size=4096))
    job.process_event(REQUEST_STREAM, json.dumps(create))
    [bridge] = job.spmd_bridges.values()
    return job, bridge


def bench_e2e_stream(n_records=1_000_000, parallelism=1, chain=32):
    """JSON-bytes -> trained-params END-TO-END throughput: the real CLI
    ingest route (C++ block parse -> prefetch thread -> packed batches ->
    SPMD staged chained steps), timed from first byte consumed to the
    trained parameters materialized on host. Nothing is pre-staged on the
    device; this is the number the reference's whole-job throughput maps to
    (Job.scala:42-70 -> FlinkSpoke.scala:92-107 hot loop).

    Reports THREE directly-measured runs so the environment's TPU network
    tunnel (which serializes every host->device byte through a remote RPC)
    can be separated from the framework's own cost:

    - raw        : the full run on the TPU (ingest loop + device drain);
    - host       : the identical pipeline with the device stubbed out --
                   parse + holdout + staging at full speed (what the host
                   side sustains feeding a local accelerator);
    - device     : the same chained launches on device-resident stages
                   (what the chip sustains when fed).

    tunnel-corrected = n / max(t_host, t_device): the standard pipeline
    bottleneck once transfers ride PCIe/DMA instead of the tunnel. On real
    hardware raw converges to the corrected figure; here raw is dominated
    by the tunnel's effective ~15-20 MB/s upload path."""
    import tempfile

    import numpy as np

    from omldm_tpu.runtime.fast_ingest import iter_file_batches
    from omldm_tpu.runtime.prefetch import prefetch
    from omldm_tpu.runtime.spmd_bridge import TAIL_BATCH

    dim = 28
    tmp = tempfile.NamedTemporaryFile(suffix=".jsonl", delete=False)
    tmp.close()
    n_bytes = _gen_stream_file(tmp.name, n_records, dim)  # not timed

    import jax
    import jax.numpy as jnp

    # --- host-ceiling run: device dispatch stubbed out ---
    # The CLI file route is the fused C parse->holdout->stage loop
    # (StreamJob.run_file_fused); the packed numpy route stays as the
    # fallback. Timed best-of-3 after a warmup pass: this one-core box's
    # throughput swings ~2x between runs, and the committed number should
    # reflect the pipeline, not one noisy scheduler window (raw samples are
    # reported alongside).
    job_h, bridge_h = _make_e2e_job(dim, parallelism, chain)

    class _NopTrainer:
        fitted = 0

        def step_many_dense(self, *a, **k):
            pass

        def step(self, *a, **k):
            pass

        def predict(self, x):
            return np.zeros(x.shape[0])

    bridge_h.trainer = _NopTrainer()
    use_fused = bridge_h.supports_fused_ingest() and job_h.fused_file_bridge()

    def _host_pass():
        if use_fused:
            # SERIAL fused ingest explicitly: t_host is defined as the
            # single-thread parse ceiling (run_file_fused now auto-routes
            # to the overlapped loop, which is measured separately below)
            bridge_h.ingest_file(tmp.name)
        else:
            for batch in prefetch(
                iter_file_batches(tmp.name, dim, 32768), depth=3
            ):
                job_h.process_packed_batch(*batch)
        bridge_h.flush()

    _host_pass()  # warmup (page cache, lazy imports, first-launch paths)
    host_samples = []
    for _ in range(3):
        t0 = time.perf_counter()
        _host_pass()
        host_samples.append(time.perf_counter() - t0)
    t_host = min(host_samples)

    # --- raw run: the real thing on the TPU ---
    job, bridge = _make_e2e_job(dim, parallelism, chain)
    tr = bridge.trainer
    # deep-copy: the jitted steps donate their input state buffers
    state0 = jax.tree.map(
        lambda a: jnp.array(a, copy=True) if isinstance(a, jax.Array) else a,
        tr.state,
    )
    dp, b = bridge.dp, 4096
    tb = min(b, TAIL_BATCH)
    zx = np.zeros((chain, dp, b, dim), bridge.feed_dtype)
    zy = np.zeros((chain, dp, b), bridge.feed_dtype)
    tr.step_many_dense(zx, zy)
    tr.step(
        np.zeros((dp, b, dim), np.float32), np.zeros((dp, b), np.float32),
        np.ones((dp, b), np.float32), valid_count=dp * b,
    )
    tr.step(
        np.zeros((dp, tb, dim), np.float32), np.zeros((dp, tb), np.float32),
        np.ones((dp, tb), np.float32), valid_count=dp * tb,
    )
    _materialize(tr.state["params"])  # warm compiles for real
    tr.state = state0
    # reset the host-side counters the warmup advanced
    tr._fitted_host = 0
    tr._steps_host = 0
    tr._curve = []

    t0 = time.perf_counter()
    if use_fused and job.fused_file_bridge():
        bridge.ingest_file(tmp.name)  # serial: raw vs raw_overlapped
    else:
        for batch in prefetch(iter_file_batches(tmp.name, dim, 32768), depth=3):
            job.process_packed_batch(*batch)
    bridge.flush()
    t_loop = time.perf_counter() - t0
    # materialized host params = the full-pipeline completion barrier
    flat = bridge.trainer.global_flat_params()
    float(np.asarray(flat[0]))
    t_raw = time.perf_counter() - t0
    fitted_raw = bridge.trainer.fitted

    # --- device-exec run: same chained program, stages already resident ---
    xs_d = jax.device_put(jnp.asarray(zx))
    ys_d = jax.device_put(jnp.asarray(zy))
    _materialize((xs_d, ys_d))
    tr.step_many_dense(xs_d, ys_d)
    _materialize(tr.state["params"])
    rounds = 8
    t0 = time.perf_counter()
    for _ in range(rounds):
        tr.step_many_dense(xs_d, ys_d)
    _materialize(tr.state["params"])  # real barrier; see _materialize
    t_dev_per_rec = (time.perf_counter() - t0) / (rounds * chain * dp * b)
    t_device = t_dev_per_rec * n_records

    corrected = n_records / max(t_host, t_device)

    # --- MEASURED overlapped run (double-buffered ingest) ---
    # The tunnel-corrected bound above assumes parse and device exec can
    # overlap; this run DEMONSTRATES it end to end: the C parse thread
    # fills stage k+1 while the dispatch thread 'trains' stage k through a
    # device stub calibrated to the measured per-stage device time
    # (time.sleep models an accelerator executing asynchronously without
    # stealing this one-core host's CPU, exactly like a local chip would
    # behave; the REAL-device overlapped run is reported separately but
    # is tunnel-transfer-bound in this environment). Wall clock of this
    # run ~ max(t_host, t_device) makes the corrected figure a
    # measurement, not a model.
    t_stage_dev = t_dev_per_rec * chain * dp * b
    job_o, bridge_o = _make_e2e_job(dim, parallelism, chain)
    bridge_o.trainer = _NopTrainer()
    stub = lambda sx, sy, n: time.sleep(t_stage_dev * n / (chain * dp * b))
    overlapped_samples = []
    bridge_o.ingest_file_overlapped(tmp.name, train_fn=stub)  # warm
    bridge_o.flush()
    for _ in range(3):
        t0 = time.perf_counter()
        # the final partial stage drains THROUGH the dispatch queue, so
        # the stub charges its device time inside the measured interval
        bridge_o.ingest_file_overlapped(tmp.name, train_fn=stub)
        bridge_o.flush()
        overlapped_samples.append(time.perf_counter() - t0)
    t_overlapped = min(overlapped_samples)
    overlapped_measured = n_records / t_overlapped

    # real-device overlapped run (through the tunnel: transfer-bound here,
    # but the dispatch thread now hides device exec under the parse)
    job_r, bridge_r = _make_e2e_job(dim, parallelism, chain)
    tr_r = bridge_r.trainer
    tr_r.step_many_dense(zx, zy)
    tr_r.step(
        np.zeros((dp, b, dim), np.float32), np.zeros((dp, b), np.float32),
        np.ones((dp, b), np.float32), valid_count=dp * b,
    )
    tr_r.step(
        np.zeros((dp, tb, dim), np.float32), np.zeros((dp, tb), np.float32),
        np.ones((dp, tb), np.float32), valid_count=dp * tb,
    )
    _materialize(tr_r.state["params"])
    t0 = time.perf_counter()
    bridge_r.ingest_file_overlapped(tmp.name)
    bridge_r.flush()
    float(np.asarray(bridge_r.trainer.global_flat_params()[0]))
    t_raw_overlapped = time.perf_counter() - t0

    # --- sharded ingest leg (ISSUE 17): N parser processes striping the
    # file's byte-grid chunks, the driver consuming blocks in stream
    # order through shared-memory rings (bit-identical row order). Same
    # stubbed-device basis as t_host, so the ratio is the ingest plane's
    # own scaling — on a 1-core host the extra processes just timeshare
    # and the ratio reports the (honest) IPC overhead instead.
    from omldm_tpu.runtime.ingest_shard import IngestConfig, ShardedIngest

    n_cores = os.cpu_count() or 1
    n_shards = max(n_cores - 1, 1)
    job_s, bridge_s = _make_e2e_job(dim, parallelism, chain)
    bridge_s.trainer = _NopTrainer()

    def _sharded_pass():
        si = ShardedIngest(tmp.name, dim, IngestConfig(shards=n_shards))
        try:
            for block in si.blocks():
                bridge_s.handle_batch(*block)
        finally:
            si.close()
        bridge_s.flush()

    _sharded_pass()  # warmup (fork + ring setup paths)
    sharded_samples = []
    for _ in range(3):
        t0 = time.perf_counter()
        _sharded_pass()
        sharded_samples.append(time.perf_counter() - t0)
    t_sharded = min(sharded_samples)

    # --- phase-attributed breakdown of the streaming host run (ISSUE 13):
    # the same stream through the telemetry-armed packed host route, so
    # the e2e number above ships with measured per-phase attribution
    phase_attribution = bench_phase_attribution(tmp.name, dim, n_records)

    os.unlink(tmp.name)
    return "e2e_json_to_params", overlapped_measured, {
        "basis": "e2e stream-fed, MEASURED double-buffered overlapped run",
        "records": n_records,
        "phase_attribution": phase_attribution,
        "stream_mb": round(n_bytes / 1e6, 1),
        "overlapped_measured_examples_per_sec": round(overlapped_measured, 1),
        "overlapped_samples_s": [round(t, 3) for t in overlapped_samples],
        "overlapped_vs_bound": round(
            overlapped_measured / corrected, 3
        ),
        "bound_examples_per_sec": round(corrected, 1),
        "raw_examples_per_sec": round(n_records / t_raw, 1),
        "raw_overlapped_examples_per_sec": round(
            n_records / t_raw_overlapped, 1
        ),
        "raw_loop_examples_per_sec": round(n_records / t_loop, 1),
        "host_pipeline_examples_per_sec": round(n_records / t_host, 1),
        "device_exec_examples_per_sec": round(1.0 / t_dev_per_rec, 1),
        "host_samples_s": [round(t, 3) for t in host_samples],
        "sharded_ingest_examples_per_sec": round(n_records / t_sharded, 1),
        "sharded_samples_s": [round(t, 3) for t in sharded_samples],
        "sharded_shards": n_shards,
        "sharded_host_cores": n_cores,
        "sharded_vs_single": round(t_host / t_sharded, 3),
        "sharded_basis": (
            "driver-visible, device stubbed (same basis as t_host); "
            "shards = cores-1; on a 1-core host the shards timeshare the "
            "driver's core, so the ratio measures IPC overhead, not "
            "scaling"
        ),
        "ingest_route": "fused-c" if use_fused else "packed-numpy",
        "t_host_s": round(t_host, 3),
        "t_device_s": round(t_device, 3),
        "t_raw_s": round(t_raw, 3),
        "t_raw_overlapped_s": round(t_raw_overlapped, 3),
        "t_drain_s": round(t_raw - t_loop, 3),
        "fitted": fitted_raw,
        "note": (
            "value = MEASURED wall-clock of the double-buffered run "
            "(parse thread fills stage k+1 while the dispatch thread "
            "trains stage k through a stub calibrated to the measured "
            "per-stage device time) — the n/max(t_host, t_device) bound "
            "observed, not modeled. raw figures include this "
            "environment's TPU network tunnel, whose upload path "
            "dominates t_drain; raw_overlapped hides device exec (but "
            "not the tunnel transfer) under the parse"
        ),
    }


def _tunnel_floor_ms(samples=100):
    """p50 of a trivial jitted dispatch+materialize round trip — the
    environment's per-dispatch cost (network tunnel to the TPU). Subtracting
    it from serving latency gives the tunnel-corrected framework latency."""
    import jax
    import jax.numpy as jnp

    f = jax.jit(lambda v: v + 1.0)
    x = jnp.zeros(())
    for _ in range(5):
        np.asarray(f(x))
    lat = []
    for _ in range(samples):
        t0 = time.perf_counter()
        np.asarray(f(x))
        lat.append((time.perf_counter() - t0) * 1000.0)
    return float(np.percentile(lat, 50))


def bench_prediction_latency():
    """p50/p99 single-record serving latency through the padded predict path."""
    import jax

    from omldm_tpu.api.requests import LearnerSpec, PreprocessorSpec
    from omldm_tpu.pipelines import MLPipeline
    from omldm_tpu.runtime.spoke import PREDICT_BATCH

    pipe = MLPipeline(
        LearnerSpec("Softmax", hyper_parameters={"nClasses": 2}),
        [PreprocessorSpec("StandardScaler")],
        dim=28,
    )
    rng = np.random.RandomState(0)
    xb = np.zeros((PREDICT_BATCH, 28), np.float32)
    # warm
    for _ in range(5):
        np.asarray(pipe.predict(xb))
    lat = []
    for _ in range(500):
        xb[0] = rng.randn(28)
        t0 = time.perf_counter()
        np.asarray(pipe.predict(xb))  # materialize = full round trip
        lat.append((time.perf_counter() - t0) * 1000.0)
    return float(np.percentile(lat, 50)), float(np.percentile(lat, 99))


def _next_slo_round() -> int:
    """The next SLO trajectory index: SLO_r01.json, SLO_r02.json, ...
    alongside the RESULTS_rXX.json rounds in benchmarks/."""
    import glob
    import re

    here = os.path.dirname(os.path.abspath(__file__))
    rounds = [
        int(m.group(1))
        for p in glob.glob(os.path.join(here, "SLO_r*.json"))
        for m in [re.match(r"SLO_r(\d+)\.json$", os.path.basename(p))]
        if m
    ]
    return max(rounds, default=0) + 1


def emit_slo_round(tenants: int, records: int, out_path: str = "") -> str:
    """One SLO trajectory round (ISSUE 19): the seeded composed storm
    (churn waves + diurnal curve + hot-tenant bursts + two fault
    classes) through the supervised fleet, evaluated against the SLO
    budgets, run TWICE — the round records the verdict sheet plus
    whether the same-seed replay reproduced a byte-identical
    deterministic core. Writes SLO_rXX.json next to the RESULTS rounds
    and returns the path."""
    import tempfile

    from benchmarks.load_harness import (
        build_composed_storm,
        run_supervised_storm,
    )
    from omldm_tpu.runtime.slo import SLOBudgets

    here = os.path.dirname(os.path.abspath(__file__))
    out_path = out_path or os.path.join(
        here, f"SLO_r{_next_slo_round():02d}.json"
    )
    t0 = time.time()
    reports = []
    tmp = tempfile.mkdtemp(prefix="omldm-slo-round-")
    for run in ("run1", "run2"):
        storm = build_composed_storm(
            7, tenants=tenants, records=records, chunk_rows=64,
            processes=1,
        )
        budgets = SLOBudgets(
            # generous heal wall budget: a relaunch restores every
            # tenant pipeline from the snapshot before its first beat
            heal_after_fault_s=600.0,
            expected_heals=2,
            allow_shed_tenants=storm.hot_tenant_ids(),
            max_stranded_rows=0,
        )
        rep, _, _ = run_supervised_storm(
            storm, os.path.join(tmp, run), budgets, processes=1,
            timeout_s=3000,
        )
        reports.append(rep)
    result = reports[0].to_dict()
    result["replayIdentical"] = (
        reports[0].core_digest() == reports[1].core_digest()
    )
    if not result["replayIdentical"]:
        result["passed"] = False
    result["wallS"] = round(time.time() - t0, 1)
    with open(out_path, "w") as f:
        json.dump(result, f, indent=2, sort_keys=True)
        f.write("\n")
    print(json.dumps({
        "config": "slo_round",
        "out": os.path.basename(out_path),
        "passed": result["passed"],
        "replay_identical": result["replayIdentical"],
        "wall_s": result["wallS"],
    }))
    return out_path


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=100)
    ap.add_argument("--e2e-records", type=int, default=300_000)
    ap.add_argument(
        "--slo-only", action="store_true",
        help="record one SLO trajectory round (SLO_rXX.json) and exit",
    )
    ap.add_argument(
        "--slo-tenants", type=int, default=10_000,
        help="tenant count for the SLO round's composed storm",
    )
    ap.add_argument(
        "--slo-records", type=int, default=256,
        help="record count for the SLO round's composed storm",
    )
    args = ap.parse_args()

    if args.slo_only:
        emit_slo_round(args.slo_tenants, args.slo_records)
        return

    # persistent XLA compile cache: the suite's first-compile cost (tens of
    # seconds per program on TPU) drops out of repeat runs
    try:
        import jax

        cache = os.path.join(
            os.path.expanduser("~"), ".cache", "omldm_tpu", "xla"
        )
        os.makedirs(cache, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache)
    except Exception:
        pass

    for fn in (
        bench_higgs_lr,
        bench_msd_orr,
        bench_criteo_pa,
        bench_susy_rff_svm,
        bench_avazu_softmax_dp8,
        bench_criteo_sparse_pa,
        bench_avazu_sparse_softmax,
        bench_criteo_sparse_stream_e2e,
        bench_longctx_transformer,
        bench_longctx_transformer_4k,
        bench_flash_attention,
    ):
        out = fn(args.steps)
        name, thr = out[0], out[1]
        extra = out[2] if len(out) > 2 else {}
        unit = (
            "TFLOP/s (causal)" if "flash" in name
            else "tokens/sec/chip" if "transformer" in name
            else "examples/sec/chip"
        )
        print(
            json.dumps(
                {
                    "config": name,
                    "metric": unit,
                    "value": round(thr, 1),
                    **extra,
                }
            )
        )
    # the reference's core experiment — 8 protocols compared on one
    # stream at parallelism 16 — runs in a subprocess so its CPU-backend
    # choice cannot disturb this process's TPU state
    import subprocess

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    child_path = repo_root + (
        os.pathsep + os.environ["PYTHONPATH"]
        if os.environ.get("PYTHONPATH") else ""
    )
    try:
        proto = subprocess.run(
            [sys.executable,
             os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "protocol_comparison.py"),
             # sweep the transport codecs too, so every BENCH round
             # records bytes_on_wire per protocol (comm volume, not just
             # throughput) in the results JSON; the sweep roughly doubles
             # the section's work, so the timeout doubles with it
             "--codec", "sweep",
             # and the chaos resilience section: every BENCH round records
             # the lossy-channel counters (duplicatesDropped, gapsResynced,
             # quorumReleases) and the chaos throughput/score overhead per
             # protocol, so regressions in the hardening layer show up in
             # the results JSON, not just in CI
             "--chaos", "default",
             # multi-tenant sweep: per-tenant + aggregate ex/s for N
             # co-hosted same-spec pipelines — per-pipeline dispatch vs
             # cohort gang dispatch vs DEVICE-SHARDED cohort dispatch
             # (tenant axis across the local mesh), with programLaunches
             # plus the device count and per-shard tenant placement per
             # run so BENCH rounds attribute throughput to mesh width
             "--pipelines", "1,8,64,256",
             # forecast-heavy serving sweep (benchmarks/streams.py): the
             # run_benchmarks legs are otherwise training-dominated, so
             # BENCH rounds record the serving-throughput axis here —
             # per-record vs adaptive-batching serving (exact + relaxed)
             # at a 50/50 train/forecast mix, 64 co-hosted tenants, with
             # forecastsServed + latency percentiles per run
             "--forecast-mix", "0.5"],
            capture_output=True, text=True, timeout=3600,
            env={**os.environ, "PYTHONPATH": child_path},
        )
        if proto.returncode != 0:
            print(
                "protocol_comparison failed "
                f"(rc {proto.returncode}):\n{proto.stderr[-2000:]}",
                file=sys.stderr,
            )
        for line in proto.stdout.splitlines():
            if line.startswith("{"):
                print(line)
    except subprocess.TimeoutExpired:
        print("protocol_comparison timed out (1800s)", file=sys.stderr)

    name, thr, extra = bench_e2e_stream(n_records=args.e2e_records)
    print(
        json.dumps(
            {
                "config": name,
                "metric": "examples/sec (JSON bytes -> trained params)",
                "value": round(thr, 1),
                **extra,
            }
        )
    )
    floor = _tunnel_floor_ms()
    p50, p99 = bench_prediction_latency()
    print(
        json.dumps(
            {
                "config": "prediction_latency",
                "metric": "single-record p50/p99 ms",
                "p50_ms": round(p50, 3),
                "p99_ms": round(p99, 3),
                "dispatch_floor_p50_ms": round(floor, 3),
                "p50_tunnel_corrected_ms": round(max(p50 - floor, 0.0), 3),
                "note": (
                    "raw latency includes this environment's TPU "
                    "network-tunnel round trip; the corrected figure "
                    "subtracts the p50 of a trivial jitted dispatch "
                    "(the tunnel floor) and is the framework's own cost"
                ),
            }
        )
    )
    # every BENCH round also records an SLO trajectory point: the
    # supervised fleet under the composed fault storm, gated and
    # replay-checked (the storm runs on the CPU worker fleet, so a
    # failure here never reflects chip state)
    try:
        emit_slo_round(args.slo_tenants, args.slo_records)
    except Exception as exc:
        print(f"slo round failed: {exc}", file=sys.stderr)


if __name__ == "__main__":
    main()
