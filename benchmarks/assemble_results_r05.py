"""Assemble RESULTS_r05.json from the round-5 chip measurement logs.

The chip queue (see PARITY round-5 notes) writes:
  /tmp/chip_results_main.log   — run_benchmarks.py (one JSON line/config)
  /tmp/scatter_exp.log         — sparse_scatter_experiment.py (text table)
  benchmarks/PROTOCOL_TPU.json — protocol_comparison_tpu.py
  benchmarks/LM_BREAKDOWN.json — profile_lm_step.py
  benchmarks/DH64_PROBE.json   — dh64_packing_probe.py

This merges whatever exists into benchmarks/RESULTS_r05.json, keeping the
CPU-measured provisional entries for anything the chip logs do not cover
(the tunnel was down for most of round 5; see RESULTS notes).

Usage: python benchmarks/assemble_results_r05.py
"""

import json
import os

HERE = os.path.dirname(os.path.abspath(__file__))
OUT = os.path.join(HERE, "RESULTS_r05.json")


def main():
    entries = []
    covered = set()

    main_log = "/tmp/chip_results_main.log"
    if os.path.exists(main_log):
        for line in open(main_log):
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                obj = json.loads(line)
            except ValueError:
                continue
            if "config" in obj:
                entries.append(obj)
                covered.add(obj["config"])

    for fname, key in (
        ("PROTOCOL_TPU.json", "protocol_comparison_tpu"),
        ("LM_BREAKDOWN.json", "lm_step_breakdown"),
        ("DH64_PROBE.json", "dh64_packing_probe"),
    ):
        path = os.path.join(HERE, fname)
        if os.path.exists(path):
            obj = json.load(open(path))
            obj["config"] = key
            entries.append(obj)
            covered.add(key)

    scatter_log = "/tmp/scatter_exp.log"
    if os.path.exists(scatter_log):
        text = open(scatter_log).read()
        if "updates/s" in text:
            entries.append({
                "config": "sparse_scatter_experiment",
                "raw_output": [
                    l for l in text.splitlines()
                    if ("updates/s" in l or "parity" in l or
                        "roofline" in l or "best:" in l or "needs" in l)
                ],
            })
            covered.add("sparse_scatter_experiment")

    # keep provisional CPU-measured entries not superseded by chip runs
    if os.path.exists(OUT):
        for prev in json.load(open(OUT)):
            if prev.get("config") not in covered:
                entries.append(prev)

    json.dump(entries, open(OUT, "w"), indent=1)
    print(f"wrote {OUT}: {len(entries)} entries "
          f"({len(covered)} from chip logs)")


if __name__ == "__main__":
    main()
