"""Profile where host-pipeline time goes on the e2e bench path (one core).

Stages measured independently over the same generated stream file:
  read    : file readinto loop only
  cparse  : read + C block parse (no postprocess/emit)
  batches : full iter_file_batches (parse + postprocess + emit)
  host    : full host pipeline (job.process_packed_batch, device stubbed)
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np

from run_benchmarks import _gen_stream_file, _make_e2e_job


def main(n=1_000_000):
    import tempfile

    dim = 28
    tmp = tempfile.NamedTemporaryFile(suffix=".jsonl", delete=False)
    tmp.close()
    n_bytes = _gen_stream_file(tmp.name, n, dim)
    print(f"stream: {n} records, {n_bytes/1e6:.1f} MB")

    # read only
    for _ in range(2):
        buf = bytearray(1 << 22)
        t0 = time.perf_counter()
        with open(tmp.name, "rb") as f:
            while f.readinto(buf):
                pass
        t_read = time.perf_counter() - t0
    print(f"read    : {t_read:.3f}s  {n/t_read/1e6:.2f} M rec/s")

    # C parse only
    from omldm_tpu.ops.native import FastParser

    for _ in range(2):
        p = FastParser(dim, 1)
        buf = bytearray(1 << 22)
        carry = 0
        t0 = time.perf_counter()
        with open(tmp.name, "rb") as f:
            while True:
                k = f.readinto(memoryview(buf)[carry:])
                if not k:
                    break
                end = carry + k
                cut = buf.rfind(b"\n", 0, end)
                if cut < 0:
                    carry = end
                    continue
                p.parse_range(buf, 0, cut + 1)
                carry = end - (cut + 1)
                if carry:
                    buf[:carry] = buf[cut + 1 : end]
        t_cparse = time.perf_counter() - t0
    print(f"cparse  : {t_cparse:.3f}s  {n/t_cparse/1e6:.2f} M rec/s")

    # full batcher
    from omldm_tpu.runtime.fast_ingest import iter_file_batches

    for _ in range(2):
        t0 = time.perf_counter()
        total = 0
        for bx, by, bop in iter_file_batches(tmp.name, dim, 32768):
            total += bx.shape[0]
        t_batches = time.perf_counter() - t0
    print(f"batches : {t_batches:.3f}s  {n/t_batches/1e6:.2f} M rec/s ({total})")

    # with prefetch thread
    from omldm_tpu.runtime.prefetch import prefetch

    for _ in range(2):
        t0 = time.perf_counter()
        total = 0
        for bx, by, bop in prefetch(iter_file_batches(tmp.name, dim, 32768), depth=3):
            total += bx.shape[0]
        t_pf = time.perf_counter() - t0
    print(f"batch+pf: {t_pf:.3f}s  {n/t_pf/1e6:.2f} M rec/s")

    # full host pipeline, device stubbed
    job_h, bridge_h = _make_e2e_job(dim, 1, 32)

    class _NopTrainer:
        fitted = 0

        def step_many_dense(self, *a, **k):
            pass

        def step(self, *a, **k):
            pass

        def predict(self, x):
            return np.zeros(x.shape[0])

    bridge_h.trainer = _NopTrainer()
    for _ in range(2):
        t0 = time.perf_counter()
        for batch in prefetch(iter_file_batches(tmp.name, dim, 32768), depth=3):
            job_h.process_packed_batch(*batch)
        bridge_h.flush()
        t_host = time.perf_counter() - t0
    print(f"host    : {t_host:.3f}s  {n/t_host/1e6:.2f} M rec/s")
    os.unlink(tmp.name)


if __name__ == "__main__":
    main(int(sys.argv[1]) if len(sys.argv) > 1 else 1_000_000)
