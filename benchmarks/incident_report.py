#!/usr/bin/env python
"""Pretty-print a flight-recorder incident bundle.

Usage::

    python benchmarks/incident_report.py /path/to/incident-0.json
    python benchmarks/incident_report.py --blackbox /path/to/blackboxdir

The first form renders a merged bundle written by a supervisor
(runtime/events.write_bundle). The second gathers the raw per-process ring
dumps (``blackbox-*.jsonl``) under a directory and merges them on the fly
(runtime/events.merge_timeline) — useful when a fleet died before any
supervisor could bundle it.

Output: the bundle meta, the per-kind event counts, any alerts, and the
fleet timeline as one row per event — relative time, process, count-clock
position, kind, cause, pipeline/worker and the (networkId, seq) transport
stamp that ordered it.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def _fmt_stamp(event: dict) -> str:
    stamp = event.get("stamp")
    if stamp is None:
        return ""
    return f"net{stamp[0]}#{stamp[1]}"


def _fmt_extra(event: dict) -> str:
    skip = {
        "id", "kind", "cause", "clock", "wall", "pid", "pipeline",
        "tenant", "worker", "stamp",
    }
    parts = [
        f"{k}={event[k]}" for k in sorted(event) if k not in skip
    ]
    return " ".join(parts)


def render(bundle: dict, out=sys.stdout) -> None:
    meta = bundle.get("meta", {})
    timeline = bundle.get("timeline", [])
    print("incident bundle", file=out)
    for k, v in sorted(meta.items()):
        print(f"  {k}: {v}", file=out)
    print(f"  processes: {len(bundle.get('processes', []))} "
          f"({', '.join(str(p.get('pid')) for p in bundle.get('processes', []))})",
          file=out)
    print(f"  events: {len(timeline)}", file=out)
    by_kind = bundle.get("byKind") or {}
    if by_kind:
        print("  by kind: " + ", ".join(
            f"{k}={v}" for k, v in sorted(by_kind.items())
        ), file=out)
    alerts = [e for e in timeline if e.get("kind") == "alert"]
    if alerts:
        print(f"  ALERTS ({len(alerts)}):", file=out)
        for a in alerts:
            print(f"    [{a.get('pid')}] {a.get('cause')} "
                  f"{_fmt_extra(a)}", file=out)
    if not timeline:
        return
    t0 = min(e.get("wall", 0.0) for e in timeline)
    print("  timeline:", file=out)
    header = (f"    {'+s':>8}  {'pid':>4} {'clock':>8}  "
              f"{'kind':<18} {'cause':<24} {'pipe':>4} {'wrk':>3}  "
              f"{'stamp':<10} detail")
    print(header, file=out)
    for e in timeline:
        rel = e.get("wall", 0.0) - t0
        print(
            f"    {rel:>8.3f}  {str(e.get('pid', '')):>4} "
            f"{e.get('clock', 0):>8}  "
            f"{e.get('kind', ''):<18} {str(e.get('cause', ''))[:24]:<24} "
            f"{str(e.get('pipeline', '')):>4} "
            f"{str(e.get('worker', '')):>3}  "
            f"{_fmt_stamp(e):<10} {_fmt_extra(e)}",
            file=out,
        )


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("bundle", nargs="?", help="incident bundle JSON file")
    ap.add_argument(
        "--blackbox",
        help="gather + merge raw blackbox-*.jsonl dumps under a directory "
        "instead of reading a pre-merged bundle",
    )
    args = ap.parse_args(argv)
    if args.blackbox:
        from omldm_tpu.runtime.events import gather_blackbox, merge_timeline

        streams = gather_blackbox(args.blackbox)
        if not streams:
            print(f"no blackbox-*.jsonl dumps under {args.blackbox!r}",
                  file=sys.stderr)
            return 1
        timeline = merge_timeline(streams)
        counts: dict = {}
        for e in timeline:
            counts[e["kind"]] = counts.get(e["kind"], 0) + 1
        bundle = {
            "meta": {"reason": "raw_blackbox", "source": args.blackbox},
            "processes": [
                {"pid": s[0].get("pid") if s else None, "events": len(s)}
                for s in streams
            ],
            "byKind": counts,
            "timeline": timeline,
        }
    elif args.bundle:
        with open(args.bundle, encoding="utf-8") as f:
            bundle = json.load(f)
    else:
        ap.error("pass a bundle file or --blackbox DIR")
        return 2
    render(bundle)
    return 0


if __name__ == "__main__":
    sys.exit(main())
