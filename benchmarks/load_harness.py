"""Fleet-scale load harness: seeded storms through the full plane matrix,
gated on SLO budgets.

Two legs, together covering the complete composition matrix (cohort x
codec x guard x serving x overload x lifecycle x telemetry x events x
selfheal x sharded ingest) — the first tooling that runs every plane at
once under duress (ROADMAP open item 5):

- :func:`run_inprocess_storm` — the in-process StreamJob engine with the
  host planes armed (cohort, codec, guard, serving, overload, lifecycle,
  telemetry, flight recorder, chaos), storm events interleaved at exact
  record positions;
- :func:`run_supervised_storm` — the supervised autoscaling fleet
  (distributed engine subprocesses) with composed fault storms (crash /
  hang / launch-refusal via the selfheal drivers), checkpoint/restore,
  the count-clocked ``--requestSchedule`` churn, flight-recorder
  incident bundles, and exactly-once output files.

Both evaluate the same way: the storm's exact per-tenant accounting
(runtime/loadgen) against the artifacts the run produced, through the
SLO gates (runtime/slo). Replays of the same seed produce byte-identical
deterministic report cores.

CLI::

    python -m benchmarks.load_harness --tenants 10000 --records 3000 \
        --seed 7 --processes 2 --out /tmp/storm

No reference counterpart: the reference ships no test or load tooling
at all (PAPER.md §0).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import subprocess
import sys
from typing import Dict, List, Optional, Sequence, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from omldm_tpu.runtime.loadgen import FaultSpec, LoadStorm, StormSpec
from omldm_tpu.runtime import slo as slomod
from omldm_tpu.runtime.slo import SLOBudgets, SLOReport


# trainingConfiguration extras arming the per-pipeline planes on every
# storm Create/Update (serving + overload + codec + guard); the job-wide
# planes (cohort, lifecycle, telemetry, events, ingest) arm via
# JobConfig / worker flags
FULL_MATRIX_TC = {
    "serving": {"maxBatch": 32, "maxDelayMs": 50},
    "overload": "window=64,share=4,hotHigh=192,hotCritical=512",
    "comm": {"codec": "int8"},
    "guard": True,
}


def default_storm_spec(
    seed: int = 7,
    tenants: int = 256,
    records: int = 1024,
    chunk_rows: int = 64,
    *,
    faults: Sequence[FaultSpec] = (),
    training_extra: Optional[dict] = None,
    churn: bool = True,
    protocol: str = "CentralizedTraining",
) -> StormSpec:
    """The canonical composed storm: churn waves + diurnal curve +
    hot-tenant bursts + mixed traffic, scaled by tenant/record count."""
    return StormSpec(
        seed=seed,
        tenants=tenants,
        records=records,
        chunk_rows=chunk_rows,
        n_features=4,
        forecast_ratio=0.3,
        diurnal_amplitude=0.5,
        diurnal_period=max(records // 4, 1),
        hot_tenants=min(2, tenants),
        burst_every=max(records // 8, 1),
        burst_len=max(records // 64, 1),
        addressed_fraction=0.1,
        churn_waves=3 if churn else 0,
        churn_tenants_per_wave=4 if churn else 0,
        churn_updates_per_wave=1 if churn else 0,
        protocol=protocol,
        training_extra=dict(training_extra or {}),
        faults=tuple(faults),
    )


# every plane CONFIGURED (objects constructed, code paths installed) in a
# state that must not alter the data path: overload thresholds uniform
# broadcast traffic can never trip, serving at immediate emission
# (maxBatch=1 — armed batching defers forecasts past training records,
# which legitimately changes values), lifecycle/telemetry/events
# observe-only. The composition-identity leg pins a bare run ==
# bit-identical to all of this at once.
UNARMED_MATRIX_KW = dict(
    cohort="auto",
    cohort_min=8,
    overload="window=64,share=4,hotHigh=192,hotCritical=512",
    serving="maxBatch=1,maxDelayMs=0",
    lifecycle="on",
    telemetry="statsEvery=256",
    events="cap=256,watchdogEvery=256",
)


def prediction_digest(job) -> Dict[int, list]:
    """Bit-identity evidence: per-tenant ordered (features, value)
    pairs over the complete output stream."""
    out: Dict[int, list] = {}
    for p in job.predictions:
        feats = tuple(p.data_instance.numerical_features)
        out.setdefault(p.mlp_id, []).append((feats, p.value))
    return out


def run_composition_identity(storm: LoadStorm) -> Tuple[dict, dict]:
    """The full-composition identity leg: the storm through a bare
    StreamJob and through every plane configured-but-unarmed
    (UNARMED_MATRIX_KW). Returns both prediction digests — equal iff
    the unarmed matrix is bit-transparent."""
    from omldm_tpu.config import JobConfig
    from omldm_tpu.runtime.job import StreamJob

    digests = []
    for kw in ({}, UNARMED_MATRIX_KW):
        job = StreamJob(JobConfig(batch_size=16, test_set_size=16, **kw))
        for line in storm.request_lines():
            job.process_event("requests", line)
        for stream, line in storm.events():
            job.process_event(stream, line)
        job.terminate()
        digests.append(prediction_digest(job))
    return digests[0], digests[1]


# --- in-process leg ------------------------------------------------------


def run_inprocess_storm(
    storm: LoadStorm,
    budgets: Optional[SLOBudgets] = None,
    *,
    armed: bool = True,
    blackbox_dir: Optional[str] = None,
) -> Tuple[SLOReport, "object"]:
    """Drive the storm through the in-process StreamJob with the host
    planes armed (or, ``armed=False``, every plane configured but
    unarmed — the full-composition identity leg). Returns (slo_report,
    job) — callers needing raw artifacts read the job."""
    from omldm_tpu.config import JobConfig
    from omldm_tpu.runtime.job import StreamJob

    spec = storm.spec
    kw: Dict[str, object] = dict(
        batch_size=32,
        test_set_size=16,
        cohort="auto",
        cohort_min=8,
    )
    if armed:
        kw.update(
            overload="window=64,share=4,hotHigh=192,hotCritical=512",
            serving="maxBatch=32,maxDelayMs=50",
            lifecycle="on",
            telemetry="statsEvery=256",
            events="cap=256,watchdogEvery=256",
        )
        if blackbox_dir:
            kw["blackbox_path"] = blackbox_dir
    job = StreamJob(JobConfig(**kw))
    # the initial Create wave precedes the stream; churn arrives
    # interleaved at exact record positions via storm.events()
    for line in storm.request_lines():
        job.process_event("requests", line)
    for stream, line in storm.events():
        job.process_event(stream, line)
    job_report = job.terminate()
    actual: Dict[int, int] = {}
    for p in job.predictions:
        actual[p.mlp_id] = actual.get(p.mlp_id, 0) + 1
    budgets = budgets or SLOBudgets()
    report_dict = None
    if job_report is not None:
        report_dict = {
            "statistics": [s.to_dict() for s in job_report.statistics]
        }
    expected = storm.expected_forecasts(
        routed=armed, update_discards=False
    )
    stranded = None
    if job.terminate_accounting is not None:
        stranded = sum(
            int(job.terminate_accounting.get(k, 0))
            for k in (
                "serving", "batcher", "throttled", "paused",
                "pre_create", "backlog",
            )
        )
    shed: Dict[int, int] = {}
    if job_report is not None:
        for s in job_report.statistics:
            if s.forecasts_shed:
                shed[s.pipeline] = s.forecasts_shed
    slo_report = slomod.evaluate(
        budgets,
        expected=expected,
        actual=actual,
        healthy=storm.healthy_tenants(),
        report=report_dict,
        stranded_rows=stranded,
        shed_by_tenant=shed,
        fingerprint=storm.fingerprint(),
        seed=spec.seed,
        scenario={"leg": "inprocess", "armed": armed,
                  "tenants": spec.tenants, "records": spec.records},
    )
    return slo_report, job


# --- supervised fleet leg ------------------------------------------------


def run_supervised_storm(
    storm: LoadStorm,
    out_dir: str,
    budgets: Optional[SLOBudgets] = None,
    *,
    processes: int = 1,
    restart_attempts: int = 3,
    checkpoint_every: int = 2,
    batch_size: int = 32,
    test_set_size: int = 16,
    timeout_s: int = 600,
    extra_flags: Sequence[str] = (),
    env_extra: Optional[Dict[str, str]] = None,
) -> Tuple[SLOReport, Optional[dict], str]:
    """Drive the storm through the supervised fleet: worker subprocesses
    with the fault storm armed, checkpoint/restore, the count-clocked
    churn schedule, flight-recorder bundles. Returns (slo_report,
    merged_job_report, stderr)."""
    os.makedirs(out_dir, exist_ok=True)
    blackbox = os.path.join(out_dir, "blackbox")
    preds = os.path.join(out_dir, "preds.jsonl")
    perf = os.path.join(out_dir, "perf.jsonl")
    args = storm.worker_args(
        out_dir, checkpoint_every=checkpoint_every,
    )
    args += [
        "--supervise", "true",
        "--processes", str(processes),
        "--restartAttempts", str(restart_attempts),
        "--restartDelayMs", "50",
        "--batchSize", str(batch_size),
        "--testSetSize", str(test_set_size),
        "--predictionsOut", preds,
        "--performanceOut", perf,
        "--flightRecorder", "on",
        "--blackboxPath", blackbox,
        # arm heartbeats so the supervisor can stamp a HEAL event on the
        # relaunched fleet's first beat (the heal-after-fault endpoint).
        # We want the beat files, not the reaper: workers beat mid-deploy
        # every 256 pipelines, but one CHUNK of fan-out records through a
        # 10k-pipeline fleet on a starved host can legitimately outlast a
        # fixed window, so the timeout scales with fleet size
        "--heartbeatTimeoutMs",
        str(max(120_000, storm.spec.tenants * 100)),
        # distributed-engine plane arming: overload backpressure +
        # codec-through-trainingConfiguration ride the request lines;
        # events/selfheal/checkpointing arm here
        "--overload", "window=64,share=4,hotHigh=192,hotCritical=512",
    ]
    args += list(extra_flags)
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env.update(env_extra or {})
    out = subprocess.run(
        [sys.executable, "-m", "omldm_tpu.runtime.distributed_job"] + args,
        cwd=REPO, env=env, capture_output=True, text=True,
        timeout=timeout_s,
    )
    stderr = out.stderr
    if out.returncode != 0:
        raise RuntimeError(
            f"supervised storm run failed rc={out.returncode}:\n"
            f"{out.stdout[-2000:]}\n{stderr[-4000:]}"
        )
    report: Optional[dict] = None
    if os.path.exists(perf):
        lines = [l for l in open(perf).read().splitlines() if l.strip()]
        if lines:
            report = json.loads(lines[-1])
    # prediction outputs: bare path at nproc==1, .pN suffixed otherwise
    pred_paths = (
        [preds] if os.path.exists(preds)
        else sorted(glob.glob(preds + ".p*"))
    )
    actual = slomod.count_prediction_files(pred_paths)
    # flight-recorder timeline: the last incident bundle carries the
    # merged fleet history (supervisor decisions + worker rings)
    events: List[dict] = []
    bundles = sorted(
        glob.glob(os.path.join(blackbox, "incident-*.json")),
        key=lambda p: int(
            os.path.basename(p).split("-")[1].split(".")[0]
        ),
    )
    if bundles:
        events = slomod.load_bundle_events(bundles[-1])
    budgets = budgets or SLOBudgets()
    slo_report = slomod.evaluate(
        budgets,
        expected=storm.expected_forecasts(routed=False),
        actual=actual,
        healthy=storm.healthy_tenants(),
        report=report,
        events=events,
        fingerprint=storm.fingerprint(),
        seed=storm.spec.seed,
        scenario={
            "leg": "supervised",
            "tenants": storm.spec.tenants,
            "records": storm.spec.records,
            "processes": processes,
            "faults": [f.kind for f in storm.spec.faults],
        },
    )
    return slo_report, report, stderr


# --- CLI -----------------------------------------------------------------


def build_composed_storm(
    seed: int, tenants: int, records: int, chunk_rows: int,
    processes: int,
) -> LoadStorm:
    """The acceptance storm: churn + diurnal + bursts + two fault
    classes (launch refusal then a mid-stream crash), sized so the crash
    lands past the first checkpoint."""
    faults = [
        FaultSpec(kind="launch", process=max(processes - 1, 0), count=1),
        FaultSpec(kind="crash", process=0, at_records=records // 2),
    ]
    spec = default_storm_spec(
        seed=seed, tenants=tenants, records=records,
        chunk_rows=chunk_rows, faults=faults,
        # the SPMD engine hosts the collective protocols only;
        # CentralizedTraining is the host-multiplexed (in-process) leg's
        protocol="Synchronous",
        training_extra={"syncEvery": 1, "comm": {"codec": "int8"}},
    )
    return LoadStorm(spec)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--tenants", type=int, default=256)
    ap.add_argument("--records", type=int, default=1024)
    ap.add_argument("--chunk-rows", type=int, default=64)
    ap.add_argument("--processes", type=int, default=1)
    ap.add_argument("--out", default="/tmp/omldm-storm")
    ap.add_argument(
        "--heal-budget-s", type=float, default=120.0,
        help="heal-after-fault wall budget (measured gate)",
    )
    ap.add_argument(
        "--p99-budget-ms", type=float, default=0.0,
        help="serve p99 budget, 0 disables (measured gate)",
    )
    ap.add_argument(
        "--replay", action="store_true",
        help="run the storm twice and assert identical report cores",
    )
    ap.add_argument("--json", action="store_true", help="one-line JSON")
    args = ap.parse_args(argv)

    storm = build_composed_storm(
        args.seed, args.tenants, args.records, args.chunk_rows,
        args.processes,
    )
    budgets = SLOBudgets(
        serve_p99_ms=args.p99_budget_ms or None,
        heal_after_fault_s=args.heal_budget_s,
        expected_heals=2,  # launch refusal + crash, both restarted
        allow_shed_tenants=storm.hot_tenant_ids(),
        max_stranded_rows=0,
    )
    slo_report, _, _ = run_supervised_storm(
        storm, os.path.join(args.out, "run1"), budgets,
        processes=args.processes,
    )
    result = slo_report.to_dict()
    if args.replay:
        replay_storm = build_composed_storm(
            args.seed, args.tenants, args.records, args.chunk_rows,
            args.processes,
        )
        slo2, _, _ = run_supervised_storm(
            replay_storm, os.path.join(args.out, "run2"), budgets,
            processes=args.processes,
        )
        result["replayIdentical"] = (
            slo_report.core_digest() == slo2.core_digest()
        )
        if not result["replayIdentical"]:
            result["passed"] = False
    if args.json:
        print(json.dumps(result))
    else:
        print(json.dumps(result, indent=2))
    return 0 if result["passed"] else 1


if __name__ == "__main__":
    sys.exit(main())
