"""Sparse scatter-add ceiling experiment (run on the real TPU chip).

The sparse hot loop (benchmarks/run_benchmarks._bench_sparse) is bound by
the scatter-add of B*K randomly-indexed updates into the dense model
vector w[D] (D = 13 + 2^18, K = 39 — Criteo-shaped, reference
DataPointParser.scala:4,20-47). This script measures every TPU-native
formulation of that scatter head-to-head, one jitted chain per candidate
(tunnel-timing rules: one program per measurement, real D2H fetch as the
barrier, chain long enough to dwarf the ~70 ms round trip):

1. xla-scatter:    w.at[idx].add(u)                  (the current engine)
2. mxu-kron-bf16x2: scatter as ONE MXU contraction — factor the index
   space D <= R*C as (hi, lo) = divmod(idx, C); then
       delta[hi, lo] = sum_n u_n * e(hi_n) (x) e(lo_n)
                     = OneHotHi[N, R]^T @ (OneHotLo[N, C] * u_n)
   One-hot entries are exact in bf16; u is split u = hi(u) + lo(u)
   (two bf16 addends per update, concatenated along the contraction dim)
   so every MXU product is exact and only the f32 accumulation order
   differs from the scatter's — the same error class as any reduction
   reorder.
3. mxu-kron-f32:   same contraction with f32 operands (no split).
4. sort-segment:   sort_key_val(idx, u) + segment boundaries + cumsum
   collapse, then scatter the collapsed updates.

It prints measured updates/sec per candidate plus the roofline math: at
D = 2^18 the dense reformulation costs 2*D FLOPs per update (x2 for the
bf16x2 split), so N updates/sec costs N * 2^20 FLOP/s — 200M updates/sec
(the 5M examples/sec bar at K=39) is ~210 TFLOP/s, ABOVE the chip's bf16
peak. The scatter formulation is serialization-bound, the matmul
formulation is MXU-peak-bound; the crossover between them is what this
experiment locates empirically.
"""

import os
import time

import jax
import jax.numpy as jnp
import numpy as np

# persistent compile cache: 8 tunnel compiles otherwise dominate the run
_cache = os.path.join(os.path.expanduser("~"), ".cache", "omldm_tpu", "xla")
os.makedirs(_cache, exist_ok=True)
jax.config.update("jax_compilation_cache_dir", _cache)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

D = 13 + (1 << 18)
K = 39
B = 4096
N = B * K  # scattered updates per step


def materialize(tree):
    """Real completion barrier: fetch one scalar D2H (block_until_ready is
    not a completion barrier for some executables on the axon tunnel)."""
    leaves = jax.tree_util.tree_leaves(tree)
    return float(jnp.asarray(leaves[0]).reshape(-1)[0])


def chain(fn, steps):
    """steps sequential applications inside ONE jitted program."""

    @jax.jit
    def run(w, idx, u):
        def body(carry, _):
            w = fn(carry, idx, u)
            return w, ()

        w, _ = jax.lax.scan(body, w, None, length=steps)
        return w

    return run


def timed(name, fn, steps, idx, u, w0):
    run = chain(fn, steps)
    w = run(w0, idx, u)
    materialize(w)  # compile + warm
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        w = run(w0, idx, u)
        materialize(w)
        best = min(best, time.perf_counter() - t0)
    rate = steps * N / best
    print(
        f"{name:18s} {best:7.3f}s for {steps} steps -> "
        f"{rate / 1e6:8.1f}M updates/s  ({rate / K / 1e6:6.2f}M ex/s at K={K})",
        flush=True,
    )
    return rate


def xla_scatter(w, idx, u):
    return w.at[idx].add(u)


C_LANES = 512
R_ROWS = -(-D // C_LANES)  # 513 for D = 13 + 2^18
D_PAD = R_ROWS * C_LANES


def mxu_kron_bf16x2(w, idx, u):
    """The SHIPPED kernel (ops/sparse.py:sparse_scatter_add_mxu), driven
    through its library entry so the measurement covers production code."""
    from omldm_tpu.ops.sparse import sparse_scatter_add_mxu

    return sparse_scatter_add_mxu(w, idx[:, None], u, jnp.ones_like(u)[:, None])


def mxu_kron_f32(w, idx, u):
    hi = idx // C_LANES
    lo = idx % C_LANES
    a = jax.nn.one_hot(hi, R_ROWS, dtype=jnp.float32)
    b = jax.nn.one_hot(lo, C_LANES, dtype=jnp.float32) * u[:, None]
    delta = jax.lax.dot_general(
        a, b, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    return w + delta.reshape(-1)[:D] if w.shape[0] == D else w + delta.reshape(-1)


PALLAS_BLOCK = 1024
PALLAS_LANES = 128


def pallas_serial(w, idx, u):
    """Pallas: the whole w lives in VMEM as [R8, 128] (1 MB at 2^18) and a
    serial loop applies each update as a dynamic-row read-modify-write
    with a 128-lane one-hot add. This measures the SERIALIZATION bound of
    exact scatter with zero HBM traffic per update — if this lands near
    XLA's ~66M updates/s, the ceiling is RMW serialization, not memory."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    interpret = jax.default_backend() != "tpu"
    params_cls = getattr(pltpu, "CompilerParams", None) or getattr(
        pltpu, "TPUCompilerParams", None
    )
    extra = {} if interpret or params_cls is None else {
        "compiler_params": params_cls(dimension_semantics=("arbitrary",))
    }
    d = w.shape[0]
    rows = -(-d // PALLAS_LANES)
    n = idx.shape[0]
    w2 = jnp.zeros((rows * PALLAS_LANES,), w.dtype).at[:d].set(w)
    w2 = w2.reshape(rows, PALLAS_LANES)

    def kernel(idx_ref, u_ref, w_ref):
        @pl.when(pl.program_id(0) == 0)
        def _init():
            w_ref[...] = jnp.zeros_like(w_ref)

        lanes = jax.lax.broadcasted_iota(
            jnp.int32, (1, PALLAS_LANES), 1
        )

        def body(i, _):
            t = idx_ref[pl.ds(i, 1)][0]
            uu = u_ref[pl.ds(i, 1)][0]
            r = t // PALLAS_LANES
            l = t % PALLAS_LANES
            row = w_ref[pl.ds(r, 1), :]
            row = row + jnp.where(lanes == l, uu, 0.0)
            w_ref[pl.ds(r, 1), :] = row
            return 0

        jax.lax.fori_loop(0, PALLAS_BLOCK, body, 0)

    grid = n // PALLAS_BLOCK
    out = pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((PALLAS_BLOCK,), lambda g: (g,)),
            pl.BlockSpec((PALLAS_BLOCK,), lambda g: (g,)),
        ],
        # constant index_map: the accumulator block stays resident in
        # VMEM across every grid step (initialized at step 0 above)
        out_specs=pl.BlockSpec((rows, PALLAS_LANES), lambda g: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((rows, PALLAS_LANES), w.dtype),
        interpret=interpret,
        **extra,
    )(idx[: grid * PALLAS_BLOCK], u[: grid * PALLAS_BLOCK])
    return (w2 + out).reshape(-1)[:d]


def sort_segment(w, idx, u):
    """Sort by index, collapse duplicate runs via cumsum differences, then
    scatter one value per RUN (non-run positions land in a pad row). The
    scatter still issues N updates — the question is whether duplicate-free
    target rows let XLA's scatter run meaningfully faster."""
    si, su = jax.lax.sort_key_val(idx, u)
    cs = jnp.cumsum(su)
    is_end = jnp.concatenate([si[1:] != si[:-1], jnp.ones((1,), bool)])
    run_start = jnp.concatenate([jnp.ones((1,), bool), si[1:] != si[:-1]])
    start_cs = jnp.concatenate([jnp.zeros((1,)), cs[:-1]])
    # per-run total = cs[end] - cs[start - 1]; scatter both halves
    pos = jnp.where(is_end, si, D)       # pad row D for non-ends
    neg = jnp.where(run_start, si, D)
    w_pad = jnp.zeros(D + 1, w.dtype)
    acc = (
        w_pad.at[pos].add(jnp.where(is_end, cs, 0.0))
        .at[neg].add(-jnp.where(run_start, start_cs, 0.0))
    )
    return w + acc[:D]


def main():
    print(f"devices: {jax.devices()}")
    rng = np.random.RandomState(0)
    idx = jnp.asarray(rng.randint(0, D, size=(N,)).astype(np.int32))
    u = jnp.asarray(rng.randn(N).astype(np.float32))
    w0 = jnp.zeros((D,), jnp.float32)
    materialize((idx, u, w0))

    # numerical parity first (sum of exact products, reordered)
    ref = np.zeros(D, np.float32)
    np.add.at(ref, np.asarray(idx), np.asarray(u))
    candidates = [
        ("xla-scatter", xla_scatter, 64),
        ("mxu-kron-bf16x2", mxu_kron_bf16x2, 256),
        ("mxu-kron-f32", mxu_kron_f32, 64),
        ("sort-segment", sort_segment, 64),
        ("pallas-serial", pallas_serial, 16),
    ]
    for name, fn, _ in candidates:
        try:
            out = np.asarray(jax.jit(fn)(w0, idx, u))
        except Exception as exc:
            print(f"parity {name:18s} FAILED: {exc}", flush=True)
            continue
        err = np.max(np.abs(out - ref)) / max(np.max(np.abs(ref)), 1e-9)
        print(f"parity {name:18s} max rel err {err:.2e}", flush=True)

    rates = {}
    for name, fn, steps in candidates:
        try:
            rates[name] = timed(name, fn, steps, idx, u, w0)
        except Exception as exc:
            print(f"{name:18s} FAILED: {type(exc).__name__}", flush=True)

    print("\nroofline:")
    flop_per_upd = 2 * 2 * D_PAD / 1.0  # bf16x2: two 2*D_pad-FLOP addends
    print(
        f"  dense reformulation: {flop_per_upd / 1e6:.2f} MFLOP/update "
        f"(bf16x2) -> 200M upd/s (the 5M ex/s bar) needs "
        f"{200e6 * flop_per_upd / 1e12:.0f} TFLOP/s vs ~197 bf16 peak"
    )
    best = max(rates, key=rates.get)
    print(f"  best: {best} at {rates[best]/1e6:.1f}M upd/s")


if __name__ == "__main__":
    main()
