"""Sparse scatter-add ceiling experiment (run on the real TPU chip).

The sparse hot loop (benchmarks/run_benchmarks._bench_sparse) is bound by
the scatter-add of B*K randomly-indexed updates into the dense model
vector w[D] (D = 13 + 2^18, K = 39 — Criteo-shaped, reference
DataPointParser.scala:4,20-47). This script measures every TPU-native
formulation of that scatter head-to-head, one jitted chain per candidate
(tunnel-timing rules: one program per measurement, real D2H fetch as the
barrier, chain long enough to dwarf the ~70 ms round trip):

1. xla-scatter:    w.at[idx].add(u)                  (the current engine)
2. mxu-kron-bf16x2: scatter as ONE MXU contraction — factor the index
   space D <= R*C as (hi, lo) = divmod(idx, C); then
       delta[hi, lo] = sum_n u_n * e(hi_n) (x) e(lo_n)
                     = OneHotHi[N, R]^T @ (OneHotLo[N, C] * u_n)
   One-hot entries are exact in bf16; u is split u = hi(u) + lo(u)
   (two bf16 addends per update, concatenated along the contraction dim)
   so every MXU product is exact and only the f32 accumulation order
   differs from the scatter's — the same error class as any reduction
   reorder.
3. mxu-kron-f32:   same contraction with f32 operands (no split).
4. sort-segment:   sort_key_val(idx, u) + segment boundaries + cumsum
   collapse, then scatter the collapsed updates.

It prints measured updates/sec per candidate plus the roofline math: at
D = 2^18 the dense reformulation costs 2*D FLOPs per update (x2 for the
bf16x2 split), so N updates/sec costs N * 2^20 FLOP/s — 200M updates/sec
(the 5M examples/sec bar at K=39) is ~210 TFLOP/s, ABOVE the chip's bf16
peak. The scatter formulation is serialization-bound, the matmul
formulation is MXU-peak-bound; the crossover between them is what this
experiment locates empirically.
"""

import os
import time

import jax
import jax.numpy as jnp
import numpy as np

# persistent compile cache: 8 tunnel compiles otherwise dominate the run
_cache = os.path.join(os.path.expanduser("~"), ".cache", "omldm_tpu", "xla")
os.makedirs(_cache, exist_ok=True)
jax.config.update("jax_compilation_cache_dir", _cache)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

D = 13 + (1 << 18)
K = 39
B = 4096
N = B * K  # scattered updates per step


def materialize(tree):
    """Real completion barrier: fetch one scalar D2H (block_until_ready is
    not a completion barrier for some executables on the axon tunnel)."""
    leaves = jax.tree_util.tree_leaves(tree)
    return float(jnp.asarray(leaves[0]).reshape(-1)[0])


def chain(fn, steps):
    """steps sequential applications inside ONE jitted program."""

    @jax.jit
    def run(w, idx, u):
        def body(carry, _):
            w = fn(carry, idx, u)
            return w, ()

        w, _ = jax.lax.scan(body, w, None, length=steps)
        return w

    return run


def timed(name, fn, steps, idx, u, w0):
    run = chain(fn, steps)
    w = run(w0, idx, u)
    materialize(w)  # compile + warm
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        w = run(w0, idx, u)
        materialize(w)
        best = min(best, time.perf_counter() - t0)
    rate = steps * N / best
    print(
        f"{name:18s} {best:7.3f}s for {steps} steps -> "
        f"{rate / 1e6:8.1f}M updates/s  ({rate / K / 1e6:6.2f}M ex/s at K={K})"
    )
    return rate


def xla_scatter(w, idx, u):
    return w.at[idx].add(u)


C_LANES = 512
R_ROWS = -(-D // C_LANES)  # 513 for D = 13 + 2^18
D_PAD = R_ROWS * C_LANES


def mxu_kron_bf16x2(w, idx, u):
    hi = idx // C_LANES
    lo = idx % C_LANES
    a = jax.nn.one_hot(hi, R_ROWS, dtype=jnp.bfloat16)          # [N, R]
    lo_oh = jax.nn.one_hot(lo, C_LANES, dtype=jnp.float32)      # [N, C]
    u_hi = u.astype(jnp.bfloat16).astype(jnp.float32)
    u_lo = u - u_hi
    b = jnp.concatenate(
        [
            (lo_oh * u_hi[:, None]).astype(jnp.bfloat16),
            (lo_oh * u_lo[:, None]).astype(jnp.bfloat16),
        ],
        axis=0,
    )                                                            # [2N, C]
    a2 = jnp.concatenate([a, a], axis=0)                         # [2N, R]
    delta = jax.lax.dot_general(
        a2, b, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )                                                            # [R, C]
    return w + delta.reshape(-1)[:D] if w.shape[0] == D else w + delta.reshape(-1)


def mxu_kron_f32(w, idx, u):
    hi = idx // C_LANES
    lo = idx % C_LANES
    a = jax.nn.one_hot(hi, R_ROWS, dtype=jnp.float32)
    b = jax.nn.one_hot(lo, C_LANES, dtype=jnp.float32) * u[:, None]
    delta = jax.lax.dot_general(
        a, b, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    return w + delta.reshape(-1)[:D] if w.shape[0] == D else w + delta.reshape(-1)


def sort_segment(w, idx, u):
    """Sort by index, collapse duplicate runs via cumsum differences, then
    scatter one value per RUN (non-run positions land in a pad row). The
    scatter still issues N updates — the question is whether duplicate-free
    target rows let XLA's scatter run meaningfully faster."""
    si, su = jax.lax.sort_key_val(idx, u)
    cs = jnp.cumsum(su)
    is_end = jnp.concatenate([si[1:] != si[:-1], jnp.ones((1,), bool)])
    run_start = jnp.concatenate([jnp.ones((1,), bool), si[1:] != si[:-1]])
    start_cs = jnp.concatenate([jnp.zeros((1,)), cs[:-1]])
    # per-run total = cs[end] - cs[start - 1]; scatter both halves
    pos = jnp.where(is_end, si, D)       # pad row D for non-ends
    neg = jnp.where(run_start, si, D)
    w_pad = jnp.zeros(D + 1, w.dtype)
    acc = (
        w_pad.at[pos].add(jnp.where(is_end, cs, 0.0))
        .at[neg].add(-jnp.where(run_start, start_cs, 0.0))
    )
    return w + acc[:D]


def main():
    print(f"devices: {jax.devices()}")
    rng = np.random.RandomState(0)
    idx = jnp.asarray(rng.randint(0, D, size=(N,)).astype(np.int32))
    u = jnp.asarray(rng.randn(N).astype(np.float32))
    w0 = jnp.zeros((D,), jnp.float32)
    materialize((idx, u, w0))

    # numerical parity first (sum of exact products, reordered)
    ref = np.zeros(D, np.float32)
    np.add.at(ref, np.asarray(idx), np.asarray(u))
    for name, fn in [
        ("xla-scatter", xla_scatter),
        ("mxu-kron-bf16x2", mxu_kron_bf16x2),
        ("mxu-kron-f32", mxu_kron_f32),
        ("sort-segment", sort_segment),
    ]:
        out = np.asarray(jax.jit(fn)(w0, idx, u))
        err = np.max(np.abs(out - ref)) / max(np.max(np.abs(ref)), 1e-9)
        print(f"parity {name:18s} max rel err {err:.2e}", flush=True)

    rates = {}
    rates["xla-scatter"] = timed("xla-scatter", xla_scatter, 64, idx, u, w0)
    rates["mxu-kron-bf16x2"] = timed(
        "mxu-kron-bf16x2", mxu_kron_bf16x2, 256, idx, u, w0
    )
    rates["mxu-kron-f32"] = timed("mxu-kron-f32", mxu_kron_f32, 64, idx, u, w0)
    rates["sort-segment"] = timed("sort-segment", sort_segment, 64, idx, u, w0)

    print("\nroofline:")
    flop_per_upd = 2 * 2 * D_PAD / 1.0  # bf16x2: two 2*D_pad-FLOP addends
    print(
        f"  dense reformulation: {flop_per_upd / 1e6:.2f} MFLOP/update "
        f"(bf16x2) -> 200M upd/s (the 5M ex/s bar) needs "
        f"{200e6 * flop_per_upd / 1e12:.0f} TFLOP/s vs ~197 bf16 peak"
    )
    best = max(rates, key=rates.get)
    print(f"  best: {best} at {rates[best]/1e6:.1f}M upd/s")


if __name__ == "__main__":
    main()
