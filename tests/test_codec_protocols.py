"""Transport codec through the protocol stack: codec-none bit-identity,
bytes-on-wire reduction, and convergence parity per protocol family —
host plane (all 8 protocols route through the ship/deliver boundary) and
the SPMD collective engine (QDQ at the allreduce boundary)."""

import json

import numpy as np
import pytest

from omldm_tpu.config import JobConfig
from omldm_tpu.runtime import StreamJob
from omldm_tpu.runtime.job import REQUEST_STREAM, TRAINING_STREAM

ALL_PROTOCOLS = [
    "Asynchronous",
    "Synchronous",
    "SSP",
    "EASGD",
    "GM",
    "FGM",
]


def stream_lines(n, dim=6, seed=0):
    rng = np.random.RandomState(seed)
    w = rng.randn(dim)
    x = rng.randn(n, dim)
    y = (x @ w > 0).astype(np.float64)
    return [
        json.dumps(
            {"numericalFeatures": list(np.round(x[i], 5)), "target": float(y[i])}
        )
        for i in range(n)
    ]


def run_job(protocol, lines, dim, comm=None, parallelism=4, batch=32,
            extra=None):
    cfg = JobConfig(parallelism=parallelism, batch_size=batch, test_set_size=32)
    job = StreamJob(cfg)
    tc = {"protocol": protocol, "syncEvery": 2}
    if comm is not None:
        tc["comm"] = comm
    if extra:
        tc.update(extra)
    create = {
        "id": 0,
        "request": "Create",
        "learner": {
            "name": "PA",
            "hyperParameters": {"C": 1.0},
            "dataStructure": {"nFeatures": dim},
        },
        "trainingConfiguration": tc,
    }
    events = [(REQUEST_STREAM, json.dumps(create))] + [
        (TRAINING_STREAM, l) for l in lines
    ]
    report = job.run(events)
    assert report is not None
    [stats] = report.statistics
    return job, stats


def worker_flats(job):
    return [
        s.nets[0].pipeline.get_flat_params()[0]
        for s in job.spokes
        if 0 in s.nets
    ]


def mean_stream_loss(job):
    """Final cumulative loss per fitted record, summed over replicas —
    a deterministic convergence figure independent of holdout sampling."""
    cum = sum(
        float(s.nets[0].pipeline.cumulative_loss)
        for s in job.spokes if 0 in s.nets
    )
    fitted = sum(
        int(s.nets[0].pipeline.fitted) for s in job.spokes if 0 in s.nets
    )
    return cum / max(fitted, 1)


class TestCodecNoneBitIdentical:
    """The acceptance pin: with codec ``none`` (explicit or default)
    every route produces byte-for-byte the models of the pre-codec path."""

    @pytest.mark.parametrize("protocol", ALL_PROTOCOLS)
    def test_explicit_none_matches_default(self, protocol):
        lines = stream_lines(800, dim=12)
        job_a, stats_a = run_job(protocol, lines, 12)
        job_b, stats_b = run_job(protocol, lines, 12, comm={"codec": "none"})
        for fa, fb in zip(worker_flats(job_a), worker_flats(job_b)):
            assert np.array_equal(fa, fb), protocol
        assert stats_a.bytes_shipped == stats_b.bytes_shipped
        assert stats_a.bytes_on_wire == stats_b.bytes_on_wire

    def test_none_wire_equals_logical_for_model_shippers(self):
        """Without a codec the wire carries the raw payloads, so the new
        counter agrees with the logical accounting for the protocols whose
        traffic is pure model pushes + updates."""
        lines = stream_lines(800, dim=12)
        _, stats = run_job("Asynchronous", lines, 12)
        assert stats.bytes_on_wire == stats.bytes_shipped > 0


class TestWireReduction:
    def test_int8_cuts_wire_3_5x_on_params_dominated_stream(self):
        dim = 256
        lines = stream_lines(1200, dim=dim, seed=1)
        _, none_stats = run_job("Asynchronous", lines, dim)
        _, int8_stats = run_job(
            "Asynchronous", lines, dim, comm={"codec": "int8"}
        )
        assert int8_stats.bytes_shipped == none_stats.bytes_shipped
        reduction = none_stats.bytes_on_wire / max(int8_stats.bytes_on_wire, 1)
        assert reduction >= 3.5, f"int8 wire reduction {reduction:.2f}x"

    def test_fp16_cuts_wire_about_2x(self):
        dim = 256
        lines = stream_lines(1200, dim=dim, seed=1)
        _, none_stats = run_job("Synchronous", lines, dim)
        _, fp16_stats = run_job(
            "Synchronous", lines, dim, comm={"codec": "fp16"}
        )
        reduction = none_stats.bytes_on_wire / max(fp16_stats.bytes_on_wire, 1)
        assert 1.8 <= reduction <= 2.2, f"fp16 reduction {reduction:.2f}x"

    def test_topk_cuts_wire_hardest(self):
        dim = 256
        lines = stream_lines(1200, dim=dim, seed=1)
        _, none_stats = run_job("Asynchronous", lines, dim)
        _, topk_stats = run_job(
            "Asynchronous", lines, dim, comm={"codec": "topk"}
        )
        reduction = none_stats.bytes_on_wire / max(topk_stats.bytes_on_wire, 1)
        assert reduction >= 6.0, f"topk reduction {reduction:.2f}x"


class TestCodecValidation:
    """Bad codec config is dropped at the gate (PipelineMap.scala:34,46
    semantics) — it must never raise out of node construction and kill
    the job."""

    def test_unknown_codec_dropped_job_survives(self):
        lines = stream_lines(400, dim=8)
        cfg = JobConfig(parallelism=2, batch_size=16, test_set_size=16)
        job = StreamJob(cfg)
        bad = {
            "id": 1, "request": "Create",
            "learner": {"name": "PA", "hyperParameters": {"C": 1.0}},
            "trainingConfiguration": {
                "protocol": "Asynchronous", "comm": {"codec": "zstd"},
            },
        }
        good = {
            "id": 0, "request": "Create",
            "learner": {"name": "PA", "hyperParameters": {"C": 1.0}},
            "trainingConfiguration": {"protocol": "Asynchronous"},
        }
        events = (
            [(REQUEST_STREAM, json.dumps(bad)),
             (REQUEST_STREAM, json.dumps(good))]
            + [(TRAINING_STREAM, l) for l in lines]
        )
        report = job.run(events)
        assert report is not None
        [stats] = report.statistics  # only the valid pipeline deployed
        assert stats.pipeline == 0
        assert stats.fitted > 100

    def test_topk_on_spmd_engine_rejected_at_gate(self):
        from omldm_tpu.api.requests import Request
        from omldm_tpu.runtime.control import PipelineManager

        req = Request.from_dict({
            "id": 0, "request": "Create",
            "learner": {
                "name": "PA",
                "dataStructure": {"nFeatures": 8},
            },
            "trainingConfiguration": {
                "protocol": "Synchronous", "engine": "spmd",
                "comm": {"codec": "topk"},
            },
        })
        err = PipelineManager().validate(req)
        assert err is not None and "host-plane" in err

    def test_topk_spmd_gate_is_case_blind(self):
        """spmd_engine_requested lowercases the engine key; the gate must
        match it or a casing variant deploys and raises past the gate."""
        from omldm_tpu.api.requests import Request
        from omldm_tpu.runtime.control import PipelineManager

        req = Request.from_dict({
            "id": 0, "request": "Create",
            "learner": {
                "name": "PA",
                "dataStructure": {"nFeatures": 8},
            },
            "trainingConfiguration": {
                "protocol": "Synchronous", "engine": "SPMD",
                "comm": {"codec": "topk"},
            },
        })
        err = PipelineManager().validate(req)
        assert err is not None and "host-plane" in err


class TestQuickParity:
    def test_int8_async_score_parity(self):
        dim = 64
        lines = stream_lines(1500, dim=dim, seed=2)
        _, none_stats = run_job("Asynchronous", lines, dim)
        _, int8_stats = run_job(
            "Asynchronous", lines, dim, comm={"codec": "int8"}
        )
        assert none_stats.score > 0.8
        assert abs(int8_stats.score - none_stats.score) <= 0.05

    def test_int8_with_hub_sharding(self):
        """Per-hub shard streams keep independent EF residuals; the
        sharded PS still converges under compression."""
        dim = 64
        lines = stream_lines(1500, dim=dim, seed=2)
        job, stats = run_job(
            "Asynchronous", lines, dim,
            comm={"codec": "int8"}, extra={"HubParallelism": 2},
        )
        assert len(job.hub_manager.hubs) == 2
        assert stats.score > 0.8
        for key, hub in job.hub_manager.hubs.items():
            assert hub.node.stats.bytes_on_wire > 0, f"hub {key} idle"

    def test_topk_sparse_linear_hashed_weights(self):
        """topk's target workload: sparse_linear's hashed weight vector —
        the model stays wide, each sync ships only the hot coordinates."""
        dense, hash_space, dim = 8, 504, 512
        rng = np.random.RandomState(3)
        w = rng.randn(dense)
        lines = []
        for i in range(1000):
            x = rng.randn(dense)
            lines.append(json.dumps({
                "numericalFeatures": list(np.round(x, 5)),
                "categoricalFeatures": [f"c{rng.randint(40)}"],
                "target": float(x @ w > 0),
            }))
        cfg = JobConfig(parallelism=2, batch_size=16, test_set_size=32)
        jobs = {}
        for comm in (None, {"codec": "topk", "topK": 64}):
            job = StreamJob(cfg)
            create = {
                "id": 0,
                "request": "Create",
                "learner": {
                    "name": "PA",
                    "hyperParameters": {"C": 1.0},
                    "dataStructure": {
                        "sparse": True, "nFeatures": dim,
                        "maxNnz": 16, "hashSpace": hash_space,
                    },
                },
                "trainingConfiguration": {
                    "protocol": "Asynchronous", "syncEvery": 2,
                    **({"comm": comm} if comm else {}),
                },
            }
            events = [(REQUEST_STREAM, json.dumps(create))] + [
                (TRAINING_STREAM, l) for l in lines
            ]
            report = job.run(events)
            [stats] = report.statistics
            jobs["topk" if comm else "none"] = stats
        assert jobs["none"].score > 0.7
        assert jobs["topk"].score > 0.7
        assert abs(jobs["topk"].score - jobs["none"].score) <= 0.1
        reduction = jobs["none"].bytes_on_wire / max(
            jobs["topk"].bytes_on_wire, 1
        )
        assert reduction >= 3.5, f"topk sparse reduction {reduction:.2f}x"


@pytest.mark.slow
class TestConvergenceParitySlow:
    """The acceptance envelope: int8 + error feedback matches the
    uncompressed final loss per protocol family on the seed workload."""

    ENVELOPE_SCORE = 0.05
    ENVELOPE_LOSS = 0.05

    @pytest.mark.parametrize(
        "protocol", ["Synchronous", "Asynchronous", "SSP", "EASGD", "GM", "FGM"]
    )
    def test_int8_final_loss_parity(self, protocol):
        dim = 64
        lines = stream_lines(6000, dim=dim, seed=4)
        extra = {"threshold": 0.8} if protocol in ("GM", "FGM") else None
        job_n, stats_n = run_job(protocol, lines, dim, extra=extra)
        job_q, stats_q = run_job(
            protocol, lines, dim, comm={"codec": "int8"}, extra=extra
        )
        assert stats_n.score > 0.8, f"{protocol} baseline failed to learn"
        assert abs(stats_q.score - stats_n.score) <= self.ENVELOPE_SCORE, (
            f"{protocol}: int8 score {stats_q.score} vs {stats_n.score}"
        )
        loss_n = mean_stream_loss(job_n)
        loss_q = mean_stream_loss(job_q)
        assert abs(loss_q - loss_n) <= self.ENVELOPE_LOSS + 0.1 * loss_n, (
            f"{protocol}: int8 mean loss {loss_q:.4f} vs {loss_n:.4f}"
        )


class TestSPMDCodec:
    """The collective engine's QDQ codec (the distributed job's
    model-exchange route): none stays bit-identical, int8 cuts the wire
    accounting >= 3.5x and holds the parameter-drift envelope."""

    def _trainer(self, comm, steps=10, dim=256, protocol="Synchronous"):
        import jax

        from omldm_tpu.api.requests import LearnerSpec, TrainingConfiguration
        from omldm_tpu.parallel.mesh import make_mesh
        from omldm_tpu.parallel.spmd import SPMDTrainer

        n_dev = len(jax.devices())
        mesh = make_mesh(dp=n_dev, hub=1)
        extra = {"syncEvery": 2}
        if comm is not None:
            extra["comm"] = comm
        t = SPMDTrainer(
            LearnerSpec("PA", hyper_parameters={"C": 1.0}), dim=dim,
            protocol=protocol, mesh=mesh,
            training_configuration=TrainingConfiguration(
                protocol=protocol, extra=extra
            ),
            batch_size=16,
        )
        w = np.random.RandomState(45).randn(dim)
        r = np.random.RandomState(6)
        for _ in range(steps):
            x = r.randn(n_dev, 16, dim).astype(np.float32)
            y = (x @ w > 0).astype(np.float32)
            t.step(x, y, np.ones((n_dev, 16), np.float32))
        return t

    def test_none_bit_identical(self):
        t_def = self._trainer(None)
        t_none = self._trainer({"codec": "none"})
        assert np.array_equal(
            t_def.global_flat_params(), t_none.global_flat_params()
        )
        assert "ef" not in t_def.state  # codec-none state tree unchanged

    def test_int8_wire_reduction_and_drift(self):
        t_none = self._trainer(None)
        t_q = self._trainer({"codec": "int8"})
        assert "ef" in t_q.state
        assert t_q.bytes_shipped() == t_none.bytes_shipped()
        assert t_none.bytes_on_wire() == t_none.bytes_shipped()
        reduction = t_none.bytes_on_wire() / max(t_q.bytes_on_wire(), 1)
        assert reduction >= 3.5, f"SPMD int8 reduction {reduction:.2f}x"
        base = t_none.global_flat_params()
        drift = np.linalg.norm(t_q.global_flat_params() - base)
        assert drift <= 0.05 * np.linalg.norm(base) + 1e-3

    def test_topk_rejected_on_collective_engine(self):
        with pytest.raises(ValueError, match="host-plane"):
            self._trainer({"codec": "topk"}, steps=0)

    @pytest.mark.slow
    def test_async_fold_int8_parity(self):
        t_none = self._trainer(None, steps=32, protocol="Asynchronous")
        t_q = self._trainer(
            {"codec": "int8"}, steps=32, protocol="Asynchronous"
        )
        base = t_none.global_flat_params()
        drift = np.linalg.norm(t_q.global_flat_params() - base)
        assert drift <= 0.1 * np.linalg.norm(base) + 1e-3
