"""C sparse (padded-COO) parser parity with the Python codec path.

omldm_parse_lines_sparse must agree with DataInstance.from_json +
SparseVectorizer.vectorize on keep/drop AND on the exact (idx, val, y, op)
arrays — categoricals hash with zlib-CRC32 and the signed rule, dense
values keep positional slots, max_nnz truncation matches, and every shape
the C walk cannot reproduce bit-exactly (escaped category strings,
out-of-order keys, metadata) defers to Python (valid=2) rather than
guessing.
"""

import json

import numpy as np
import pytest

from omldm_tpu.api.data import FORECASTING, DataInstance
from omldm_tpu.ops.native import fast_parser_available
from omldm_tpu.runtime.vectorizer import F32_MAX, SparseVectorizer

pytestmark = pytest.mark.skipif(
    not fast_parser_available(), reason="native parser unavailable"
)

DENSE = 6
HASH = 1 << 10
DIM = DENSE + HASH
K = 8


def reference_rows(block: bytes):
    vec = SparseVectorizer(DIM, HASH, K)
    idxs, vals, ys, ops = [], [], [], []
    for line in block.split(b"\n"):
        inst = DataInstance.from_json(line.decode("utf-8", errors="replace"))
        if inst is None:
            continue
        i, v = vec.vectorize(inst)
        idxs.append(i)
        vals.append(v)
        ys.append(
            0.0 if inst.target is None
            else min(max(float(inst.target), -F32_MAX), F32_MAX)
        )
        ops.append(1 if inst.operation == FORECASTING else 0)
    if not idxs:
        return (
            np.zeros((0, K), np.int32), np.zeros((0, K), np.float32),
            np.zeros((0,), np.float32), np.zeros((0,), np.uint8),
        )
    return (
        np.stack(idxs), np.stack(vals),
        np.asarray(ys, np.float32), np.asarray(ops, np.uint8),
    )


def packed_rows(block: bytes):
    from omldm_tpu.ops.native import SparseFastParser

    p = SparseFastParser(DENSE, HASH, K)
    idx, val, y, op, valid = p.parse(block)
    vec = SparseVectorizer(DIM, HASH, K)
    lines = block.split(b"\n")
    out_i, out_v, out_y, out_o = [], [], [], []
    for r in range(idx.shape[0]):
        if valid[r] == 2:  # Python-codec fallback, like the dense batcher
            inst = DataInstance.from_json(
                lines[r].decode("utf-8", errors="replace")
            )
            if inst is None:
                continue
            i, v = vec.vectorize(inst)
            out_i.append(i)
            out_v.append(v)
            out_y.append(
                0.0 if inst.target is None
                else min(max(float(inst.target), -F32_MAX), F32_MAX)
            )
            out_o.append(1 if inst.operation == FORECASTING else 0)
        elif valid[r] == 1:
            out_i.append(idx[r])
            out_v.append(val[r])
            out_y.append(y[r])
            out_o.append(op[r])
    if not out_i:
        return (
            np.zeros((0, K), np.int32), np.zeros((0, K), np.float32),
            np.zeros((0,), np.float32), np.zeros((0,), np.uint8),
        )
    return (
        np.stack(out_i), np.stack(out_v),
        np.asarray(out_y, np.float32), np.asarray(out_o, np.uint8),
    )


def make_lines(rng, n):
    lines = []
    for i in range(n):
        kind = rng.randint(0, 10)
        num = [round(float(v), 5) for v in rng.randn(rng.randint(0, DENSE + 3))]
        cats = [
            rng.choice(["red", "blue", "big", "小さい", "x" * rng.randint(1, 9)])
            for _ in range(rng.randint(0, 6))
        ]
        rec = {"numericalFeatures": num, "categoricalFeatures": cats}
        if kind < 6:
            rec["target"] = float(rng.randn())
            rec["operation"] = "training"
            lines.append(json.dumps(rec, ensure_ascii=False))
        elif kind == 6:
            rec["operation"] = "forecasting"
            lines.append(json.dumps(rec, ensure_ascii=False))
        elif kind == 7:  # escapes in category strings -> Python fallback
            rec["categoricalFeatures"] = ["a\\b", "tab\there", 'q"uote']
            rec["target"] = 1.0
            lines.append(json.dumps(rec))
        elif kind == 8:  # out-of-order keys / oddities
            lines.append(rng.choice([
                '{"categoricalFeatures": ["z"], "numericalFeatures": [1.5]}',
                '{"discreteFeatures": [2.0], "numericalFeatures": [1.0]}',
                '{"numericalFeatures": [0.0, 1.0], "target": 1e308}',
                '{"numericalFeatures": [1.0], "metadata": {"a": 1}}',
                '{"numericalFeatures": [1.0, "x"], "target": 1.0}',
                '{"categoricalFeatures": [1.0], "target": 1.0}',
                '{"categoricalFeatures": ["a", "b", "c", "d", "e", "f", '
                '"g", "h", "i", "j"], "numericalFeatures": []}',
                # PRESENT-but-zero features: is_valid keeps them (a zero
                # COO row trains as a no-op) — validity is presence
                '{"numericalFeatures": [0.0], "target": 1.0, '
                '"operation": "training"}',
                '{"numericalFeatures": [0.0, 0.00000], "target": 0.0}',
                "EOS",
                "garbage {",
            ]))
        else:  # many nonzero dense values (max_nnz truncation)
            rec = {
                "numericalFeatures":
                    [round(float(v) + 1.0, 4) for v in rng.rand(DENSE + 2)],
                "categoricalFeatures": ["a", "b", "c", "d", "e"],
                "target": 0.0,
                "operation": "training",
            }
            lines.append(json.dumps(rec))
    return lines


@pytest.mark.parametrize("seed", range(6))
def test_sparse_fuzz_matches_python_codec(seed):
    rng = np.random.RandomState(seed)
    block = ("\n".join(make_lines(rng, 250)) + "\n").encode()
    pi, pv, py_, po = packed_rows(block)
    ri, rv, ry, ro = reference_rows(block)
    assert pi.shape == ri.shape
    np.testing.assert_array_equal(pi, ri)
    np.testing.assert_allclose(pv, rv, rtol=1e-6, atol=1e-7)
    np.testing.assert_allclose(py_, ry, rtol=1e-6, atol=0)
    np.testing.assert_array_equal(po, ro)


@pytest.mark.parametrize("seed", range(4))
def test_sparse_template_mutations_match_python_codec(seed):
    """The sparse whole-line schema template (fastparse.cpp) must agree
    with the general walk AND the Python codec on near-misses of its
    exact shape — every mutation must fall through with identical
    keep/drop/fallback semantics."""
    rng = np.random.RandomState(5000 + seed)
    lines = []
    for _ in range(200):
        num = ", ".join("%.6f" % v for v in rng.randn(rng.randint(1, 6)))
        cats = ", ".join(
            '"%s"' % c for c in rng.choice(
                ["red", "blue", "c%d" % rng.randint(99)],
                size=rng.randint(1, 5),
            )
        )
        line = (
            '{"numericalFeatures": [%s], "categoricalFeatures": [%s], '
            '"target": %.2f, "operation": "training"}'
            % (num, cats, rng.rand())
        )
        r = rng.rand()
        if r < 0.5:
            lines.append(line)  # exact template shape
        elif r < 0.7:  # single-byte mutation anywhere
            i = rng.randint(len(line))
            line = line[:i] + chr(rng.randint(32, 127)) + line[i + 1 :]
            lines.append(line)
        elif r < 0.8:  # truncation
            lines.append(line[: rng.randint(1, len(line))])
        elif r < 0.9:  # trailing junk / whitespace
            lines.append(line + rng.choice([" ", "\t", " x", "\x0c", "}"]))
        else:  # near-miss keys and operations
            lines.append(
                line.replace("training", rng.choice(
                    ["Training", "training ", "train", "forecasting"]
                ))
            )
    block = ("\n".join(lines) + "\n").encode()
    pi, pv, py_, po = packed_rows(block)
    ri, rv, ry, ro = reference_rows(block)
    assert pi.shape == ri.shape
    np.testing.assert_array_equal(pi, ri)
    np.testing.assert_allclose(pv, rv, rtol=1e-6, atol=1e-7)
    np.testing.assert_allclose(py_, ry, rtol=1e-6, atol=0)
    np.testing.assert_array_equal(po, ro)


def test_multithreaded_sparse_parse_matches_single():
    """omldm_parse_lines_sparse_mt must produce IDENTICAL outputs to the
    single-thread entry (disjoint line ranges, thread_local CRC caches) —
    including fallback/drop/forecast rows and uneven range splits."""
    from omldm_tpu.ops.native import SparseFastParser

    rng = np.random.RandomState(77)
    block = ("\n".join(make_lines(rng, 503)) + "\n").encode()
    si, sv, sy, so, svd = SparseFastParser(DENSE, HASH, K).parse(block)
    keep = svd == 1  # dropped/fallback rows leave idx/val unspecified
    assert keep.sum() > 100
    for nt in (2, 3, 7):
        mi, mv, my, mo, mvd = SparseFastParser(
            DENSE, HASH, K, n_threads=nt
        ).parse(block)
        np.testing.assert_array_equal(mvd, svd)
        np.testing.assert_array_equal(mo, so)
        np.testing.assert_array_equal(my[keep], sy[keep])
        np.testing.assert_array_equal(mi[keep], si[keep])
        np.testing.assert_array_equal(mv[keep], sv[keep])


def test_hash_space_beyond_uint32_defers_to_python():
    """hash_space must fit uint32 for the C fastmod; larger spaces defer
    every categorical line to the full-precision Python hasher (valid=2)
    instead of crashing FastMod construction (divide-by-zero at exactly
    2^32) or hashing modulo a truncated divisor."""
    from omldm_tpu.ops.native import SparseFastParser

    line = (
        b'{"numericalFeatures": [1.5], "categoricalFeatures": ["red"], '
        b'"target": 1.0, "operation": "training"}\n'
    )
    for space in (1 << 32, (1 << 32) + 7):
        p = SparseFastParser(DENSE, space, K)
        _, _, _, _, valid = p.parse(line)
        assert valid[0] == 2, f"hash_space {space} should defer to Python"
    # the boundary value itself stays in C
    p = SparseFastParser(DENSE, 0xFFFFFFFF, K)
    _, _, _, _, valid = p.parse(line)
    assert valid[0] == 1


def test_crc32_hash_parity_exact():
    """The C CRC32 must match zlib.crc32 bit-for-bit (bucket AND sign)."""
    import zlib

    block_lines = []
    cats = ["red", "large", "café", "з", "0", "=weird=", " "]
    for c in cats:
        block_lines.append(json.dumps(
            {"numericalFeatures": [], "categoricalFeatures": [c, c],
             "target": 1.0, "operation": "training"},
            ensure_ascii=False,
        ))
    block = ("\n".join(block_lines) + "\n").encode()
    pi, pv, _, _ = packed_rows(block)
    for row, c in zip(range(len(cats)), cats):
        for j in range(2):
            h = zlib.crc32(f"{j}={c}".encode())
            assert pi[row, j] == DENSE + (h % HASH)
            assert pv[row, j] == (1.0 if (h >> 1) % 2 == 0 else -1.0)
