"""Model-lifecycle plane (runtime/lifecycle.py).

Pins, per ISSUE 11 acceptance:

- ``lifecycle`` unset runs the exact pre-plane routes — zero lifecycle
  objects anywhere — across the composition matrix (cohort x codec int8
  x guard x serving exact x overload), and an ARMED-but-idle registry
  (no Shadow issued) is bit-identical to unarmed;
- with a canary armed, baseline-version (untagged) predictions stay
  BITWISE equal to a no-lifecycle run — candidate training and canary
  routing never perturb the active model;
- the canary split is a deterministic, seeded, count-clocked hash of the
  forecast stream (same seed => same route schedule, replayable);
- a healthy Shadow candidate ramps and auto-promotes, retaining the
  outgoing version for operator Rollback; a poisoned candidate trips its
  guard (or regresses past scoreEnvelope) and auto-rolls-back with zero
  forecast loss — healthy co-tenants serve EXACTLY their no-canary
  forecast counts;
- the registry, candidate state and canary clocks persist through
  checkpoint/restore: a supervised restart mid-ramp converges to the
  fault-free promotion decision;
- Statistics plumbing (shadowScored / canaryPromotions / canaryRollbacks
  / activeVersion gauge), the Query-response registry view, and the
  tenant_topology() lifecycle section.
"""

import json

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from omldm_tpu.api.data import FORECASTING, DataInstance, Prediction
from omldm_tpu.api.requests import TrainingConfiguration
from omldm_tpu.api.responses import QueryResponse
from omldm_tpu.api.stats import Statistics
from omldm_tpu.config import JobConfig
from omldm_tpu.runtime import StreamJob
from omldm_tpu.runtime.job import (
    FORECASTING_STREAM,
    REQUEST_STREAM,
    TRAINING_STREAM,
)
from omldm_tpu.runtime.lifecycle import (
    ACTIVE,
    CANARY,
    REGISTERED,
    ROLLED_BACK,
    SHADOW,
    LifecycleConfig,
    LifecycleState,
    canary_hash,
    lifecycle_config,
    parse_lifecycle_spec,
    validate_lifecycle,
)
from omldm_tpu.runtime.recovery import (
    FaultInjector,
    JobSupervisor,
    replayable,
)

DIM = 8

# a ramp small enough that a ~300-record (150-forecast) stream completes
# it: full ramp at clock 16, promotion after 16 canary serves + 1 eval
LC = {
    "rampFrom": 0.0, "rampTo": 0.5, "rampEvery": 8, "rampStep": 0.25,
    "promoteAfter": 16, "shadowEvery": 4, "minShadowEvals": 1,
    "scoreEnvelope": 0.05, "seed": 7,
}


# --- config parsing / validation ---------------------------------------------


class TestLifecycleConfig:
    def test_unset_is_none(self):
        assert parse_lifecycle_spec(None) is None
        assert parse_lifecycle_spec(False) is None
        assert parse_lifecycle_spec("") is None
        assert lifecycle_config(TrainingConfiguration()) is None

    def test_defaults_and_spec_strings(self):
        assert parse_lifecycle_spec(True) == LifecycleConfig()
        assert parse_lifecycle_spec("on") == LifecycleConfig()
        cfg = parse_lifecycle_spec("rampTo=0.4,rampEvery=64,seed=9")
        assert (cfg.ramp_to, cfg.ramp_every, cfg.seed) == (0.4, 64, 9)
        cfg = parse_lifecycle_spec(LC)
        assert (cfg.ramp_from, cfg.ramp_to, cfg.promote_after,
                cfg.shadow_every, cfg.min_shadow_evals,
                cfg.score_envelope) == (0.0, 0.5, 16, 4, 1, 0.05)

    def test_job_default_and_per_pipeline_override(self):
        tc = TrainingConfiguration()
        assert lifecycle_config(tc, "rampTo=0.25").ramp_to == 0.25
        tc_off = TrainingConfiguration(extra={"lifecycle": False})
        assert lifecycle_config(tc_off, "rampTo=0.25") is None
        tc_own = TrainingConfiguration(extra={"lifecycle": {"rampTo": 0.75}})
        assert lifecycle_config(tc_own, "rampTo=0.25").ramp_to == 0.75

    @pytest.mark.parametrize("bad", [
        {"rampFrom": 0.6, "rampTo": 0.4}, {"rampTo": 1.5},
        {"rampEvery": 0}, {"rampStep": 0}, {"promoteAfter": 0},
        {"shadowEvery": 0}, {"minShadowEvals": -1},
        {"scoreEnvelope": -0.1}, {"maxVersions": 1},
        {"notAKnob": 1}, "rampTo", 7,
    ])
    def test_invalid_specs_raise_and_gate(self, bad):
        with pytest.raises((ValueError, TypeError)):
            parse_lifecycle_spec(bad)
        req = _create_req(0, lifecycle=bad)
        assert validate_lifecycle(req) is not None

    def test_sparse_and_spmd_rejected(self):
        req = _create_req(0, lifecycle=LC)
        req.learner.data_structure = {"nFeatures": DIM, "sparse": True}
        assert "dense" in validate_lifecycle(req)
        req = _create_req(0, lifecycle=LC)
        req.training_configuration.extra["engine"] = "spmd"
        assert "host-plane" in validate_lifecycle(req)

    def test_bad_request_quarantined_not_fatal(self):
        job = StreamJob(JobConfig(parallelism=1))
        job.process_event(REQUEST_STREAM, json.dumps({
            "id": 0, "request": "Create",
            "learner": {"name": "PA", "hyperParameters": {"C": 1.0},
                        "dataStructure": {"nFeatures": DIM}},
            "trainingConfiguration": {"lifecycle": {"rampEvery": 0}},
        }))
        assert 0 not in job.pipeline_manager.node_map
        assert "rejected_request" in [
            e["reason"] for e in job.dead_letter.entries
        ]

    def test_bad_job_default_fails_fast(self):
        with pytest.raises(ValueError):
            StreamJob(JobConfig(parallelism=1, lifecycle="rampStep=0"))


class TestCanaryHash:
    def test_deterministic_and_bounded(self):
        a = [canary_hash(7, n) for n in range(512)]
        b = [canary_hash(7, n) for n in range(512)]
        assert a == b
        assert all(0.0 <= v < 1.0 for v in a)

    def test_seed_and_clock_sensitivity(self):
        assert canary_hash(7, 0) != canary_hash(8, 0)
        assert len({canary_hash(7, n) for n in range(64)}) == 64

    def test_roughly_uniform(self):
        hits = sum(canary_hash(3, n) < 0.5 for n in range(4096))
        assert abs(hits / 4096 - 0.5) < 0.05


# --- registry state machine (policy units, no runtime) ------------------------


def _armed_state(**kw):
    spec = dict(LC)
    spec.update(kw)
    state = LifecycleState(parse_lifecycle_spec(spec))
    return state


class _FakePipe:
    """Registry-row stand-in: flat params + a version tag slot."""

    def __init__(self, val=1.0):
        self._flat = np.full((4,), val, np.float32)
        self.version = 0
        self.guard = None

    def get_flat_params(self):
        return self._flat.copy(), None


class TestStateMachine:
    def test_version_zero_active(self):
        lc = _armed_state()
        assert lc.active_version == 0
        assert lc.versions[0].state == ACTIVE
        assert lc.candidate is None and not lc.training_active

    def test_shadow_then_canary(self):
        lc = _armed_state()
        v = lc.arm_shadow(_FakePipe(), {"learner": {}})
        assert v == 1 and lc.candidate == 1
        assert lc.versions[1].state == SHADOW
        assert lc.training_active and not lc.canary_active
        assert lc.start_canary()
        assert lc.versions[1].state == CANARY and lc.canary_active
        assert not lc.start_canary()  # already canarying

    def test_reissued_shadow_replaces_silently(self):
        lc = _armed_state()
        lc.arm_shadow(_FakePipe(), {})
        lc.arm_shadow(_FakePipe(), {})
        assert lc.candidate == 2
        assert lc.versions[1].state == REGISTERED
        assert lc.versions[1].trip_reason is None
        assert lc.totals["canary_rollbacks"] == 0

    def test_demote_counts_rollback_and_releases_pipeline(self):
        lc = _armed_state()
        lc.arm_shadow(_FakePipe(2.0), {})
        entry = lc.demote_candidate("non_finite")
        assert entry.state == ROLLED_BACK
        assert entry.trip_reason == "non_finite"
        assert entry.pipeline is None
        assert entry.flat is not None and entry.flat[0] == 2.0
        assert lc.candidate is None and lc.canary_pct == 0.0
        assert lc.totals["canary_rollbacks"] == 1

    def test_route_clock_deterministic_and_ramping(self):
        lc = _armed_state(rampFrom=0.5, rampTo=0.5)
        lc.arm_shadow(_FakePipe(), {})
        lc.candidate_entry.fits = 1  # a trained candidate
        lc.start_canary()
        takes = [lc.route_candidate() for _ in range(256)]
        # pure function of (seed, clock): an identical registry replays
        # the identical schedule
        lc2 = _armed_state(rampFrom=0.5, rampTo=0.5)
        lc2.arm_shadow(_FakePipe(), {})
        lc2.candidate_entry.fits = 1
        lc2.start_canary()
        assert [lc2.route_candidate() for _ in range(256)] == takes
        frac = sum(takes) / 256
        assert 0.35 < frac < 0.65
        assert lc.versions[lc.candidate].canary_served == sum(takes)

    def test_ramp_steps_on_clock(self):
        lc = _armed_state()  # rampEvery=8, step 0.25, to 0.5
        lc.arm_shadow(_FakePipe(), {})
        lc.candidate_entry.fits = 1
        lc.start_canary()
        assert lc.canary_pct == 0.0
        for _ in range(9):
            lc.route_candidate()
        assert lc.canary_pct == 0.25
        for _ in range(16):
            lc.route_candidate()
        assert lc.canary_pct == 0.5  # capped at rampTo

    def test_untrained_candidate_never_takes_traffic(self):
        """A canary whose candidate has zero fits (a spoke whose stream
        share carried no training rows) serves nothing — init-model
        predictions are never exposed — while the clock still ticks so
        the hash schedule stays aligned with the forecast count."""
        lc = _armed_state(rampFrom=0.5, rampTo=0.5)
        lc.arm_shadow(_FakePipe(), {})
        lc.start_canary()
        assert not any(lc.route_candidate() for _ in range(64))
        assert lc.forecast_clock == 64
        lc.candidate_entry.fits = 1
        assert any(lc.route_candidate() for _ in range(16))

    def test_registry_trim_bound(self):
        lc = _armed_state(maxVersions=3)
        for _ in range(6):
            lc.arm_shadow(_FakePipe(), {})
            lc.demote_candidate(None, to_state=REGISTERED)
        assert len(lc.versions) <= 3
        assert 0 in lc.versions  # the active version never trims

    def test_take_counters_drains_once(self):
        lc = _armed_state()
        lc.arm_shadow(_FakePipe(), {})
        lc.demote_candidate("operator")
        assert lc.take_counters() == {"canary_rollbacks": 1}
        assert lc.take_counters() == {}
        assert lc.totals["canary_rollbacks"] == 1  # totals survive


# --- job harness -------------------------------------------------------------


def _create_req(pid, lifecycle=None, **tc_extra):
    from omldm_tpu.api.requests import Request

    tc = {"protocol": "Asynchronous", "syncEvery": 4, **tc_extra}
    if lifecycle is not None:
        tc["lifecycle"] = lifecycle
    return Request.from_dict({
        "id": pid, "request": "Create",
        "learner": {"name": "PA", "hyperParameters": {"C": 1.0},
                    "dataStructure": {"nFeatures": DIM}},
        "trainingConfiguration": tc,
    })


def _job(lifecycle=None, n_pipe=1, serving=None, cohort="off", codec=None,
         guard=False, overload=None, protocol="Asynchronous", parallelism=1,
         test=True, job_lifecycle="", batch=16):
    cfg = JobConfig(parallelism=parallelism, batch_size=batch,
                    test_set_size=16, cohort=cohort, cohort_min=2,
                    test=test, lifecycle=job_lifecycle)
    job = StreamJob(cfg)
    for pid in range(n_pipe):
        tc = {"protocol": protocol, "syncEvery": 4}
        if lifecycle is not None:
            tc["lifecycle"] = lifecycle
        if serving is not None:
            tc["serving"] = serving
        if overload is not None:
            tc["overload"] = overload
        if codec:
            tc["comm"] = {"codec": codec}
        if guard:
            tc["guard"] = True
        job.process_event(REQUEST_STREAM, json.dumps({
            "id": pid, "request": "Create",
            "learner": {"name": "PA", "hyperParameters": {"C": 1.0},
                        "dataStructure": {"nFeatures": DIM}},
            "trainingConfiguration": tc,
        }))
    return job


def _shadow(job, pid=0, C=0.5, learner="PA"):
    job.process_event(REQUEST_STREAM, json.dumps({
        "id": pid, "request": "Shadow",
        "learner": {"name": learner, "hyperParameters": {"C": C},
                    "dataStructure": {"nFeatures": DIM}},
    }))


def _promote(job, pid=0):
    job.process_event(REQUEST_STREAM, json.dumps(
        {"id": pid, "request": "Promote"}))


def _rollback(job, pid=0):
    job.process_event(REQUEST_STREAM, json.dumps(
        {"id": pid, "request": "Rollback"}))


def _feed(job, records=320, seed=3, terminate=True):
    rng = np.random.RandomState(seed)
    w = np.random.RandomState(5).randn(DIM)
    for i in range(records):
        f = rng.randn(DIM).astype(np.float32)
        if i % 2 == 0:
            job.process_event(FORECASTING_STREAM, json.dumps(
                {"numericalFeatures": f.tolist()}))
        else:
            job.process_event(TRAINING_STREAM, json.dumps(
                {"numericalFeatures": f.tolist(),
                 "target": float(f @ w > 0)}))
    return job.terminate() if terminate else None


def _digest(job, report):
    ordered = {}
    for p in job.predictions:
        feats = tuple(np.asarray(p.data_instance.numerical_features).tolist())
        ordered.setdefault(p.mlp_id, []).append((feats, p.value, p.version))
    scores = {s.pipeline: s.score for s in report.statistics}
    return ordered, scores


def _per_net_preds(job):
    """Per-net prediction sequence (value, version), in stream order."""
    out = {}
    for p in job.predictions:
        out.setdefault(p.mlp_id, []).append((p.value, p.version))
    return out


# --- unset identity (the composition matrix) ---------------------------------


MATRIX = [
    dict(),
    dict(cohort="on", n_pipe=4),
    dict(codec="int8"),
    dict(guard=True),
    dict(serving={"maxBatch": 8, "maxDelayMs": 200.0}),
    dict(overload="window=8,share=2,hotHigh=6,hotCritical=12"),
    dict(cohort="on", n_pipe=4, codec="int8", guard=True,
         serving={"maxBatch": 8, "maxDelayMs": 200.0}),
]


class TestUnsetIdentity:
    @pytest.mark.parametrize("kw", MATRIX)
    def test_no_lifecycle_objects_when_unset(self, kw):
        job = _job(None, **kw)
        _feed(job, records=64)
        for spoke in job.spokes:
            assert not spoke._any_lifecycle
            for net in spoke.nets.values():
                assert net.lifecycle is None

    @pytest.mark.parametrize("kw", MATRIX)
    def test_armed_idle_bit_identical(self, kw):
        """An armed registry with no Shadow issued must not perturb a
        single bit of the stream (no candidate => no twin training, no
        routing ticks, no extra launches on the data path)."""
        off = _job(None, **kw)
        d_off = _digest(off, _feed(off))
        on = _job(LC, **kw)
        d_on = _digest(on, _feed(on))
        assert d_off == d_on
        for spoke in on.spokes:
            for net in spoke.nets.values():
                assert net.lifecycle is not None
                assert net.lifecycle.describe()["counters"] == {
                    "shadow_scored": 0, "canary_promotions": 0,
                    "canary_rollbacks": 0,
                }

    def test_job_default_arms_every_pipeline(self):
        job = _job(None, n_pipe=3, job_lifecycle="rampTo=0.25")
        for spoke in job.spokes:
            for net in spoke.nets.values():
                assert net.lifecycle is not None
                assert net.lifecycle.cfg.ramp_to == 0.25

    def test_armed_parallel_2_identity(self):
        off = _job(None, protocol="Synchronous", parallelism=2)
        d_off = _digest(off, _feed(off))
        on = _job(LC, protocol="Synchronous", parallelism=2)
        d_on = _digest(on, _feed(on))
        assert d_off == d_on


class TestCanaryBaselineIdentity:
    @pytest.mark.parametrize("kw", [
        dict(),
        dict(serving={"maxBatch": 8, "maxDelayMs": 200.0}),
        dict(guard=True, codec="int8"),
    ])
    def test_baseline_predictions_bitwise_under_canary(self, kw):
        """With a canary serving traffic the whole run (promoteAfter past
        the stream), every BASELINE-version prediction must be bitwise
        the no-lifecycle run's value at the same per-net stream position:
        candidate twin-training and hash routing never touch the active
        model, its batcher, or its holdout cycle."""
        off = _job(None, **kw)
        _feed(off)
        on = _job({**LC, "promoteAfter": 100_000}, **kw)
        _shadow(on)
        _promote(on)  # canary starts; never completes
        _feed(on)
        # candidate-routed forecasts serve immediately while baseline
        # forecasts may sit in a serving queue, so emission order can
        # interleave differently; pair by the record's (unique random)
        # feature payload instead of the emission index
        off_vals = {}
        for p in off.predictions:
            key = (p.mlp_id,
                   tuple(np.float32(p.data_instance.numerical_features)))
            off_vals[key] = p.value
        tagged = 0
        assert len(on.predictions) == len(off.predictions)  # zero loss
        for p in on.predictions:
            if p.version is not None:
                tagged += 1
                continue
            key = (p.mlp_id,
                   tuple(np.float32(p.data_instance.numerical_features)))
            assert p.value == off_vals[key]
        assert tagged > 0  # the canary actually served

    def test_same_seed_same_route_schedule(self):
        runs = []
        for _ in range(2):
            job = _job({**LC, "promoteAfter": 100_000})
            _shadow(job)
            _promote(job)
            _feed(job)
            runs.append([ver for _v, ver in _per_net_preds(job)[0]])
        assert runs[0] == runs[1]
        job = _job({**LC, "promoteAfter": 100_000, "seed": 99})
        _shadow(job)
        _promote(job)
        _feed(job)
        other = [ver for _v, ver in _per_net_preds(job)[0]]
        assert other != runs[0]


# --- shadow scoring / promotion ----------------------------------------------


class TestShadowAndPromotion:
    def test_shadow_trains_and_scores_without_serving(self):
        job = _job(LC)
        _shadow(job)
        _feed(job, terminate=False)
        lc = job.spokes[0].nets[0].lifecycle
        entry = lc.candidate_entry
        assert entry.state == SHADOW
        assert entry.fits > 0 and entry.shadow_evals > 0
        assert entry.shadow_score is not None
        assert entry.canary_served == 0
        # serving stayed 100% on the active version
        assert all(p.version is None for p in job.predictions)
        job.terminate()

    def test_healthy_candidate_auto_promotes(self):
        job = _job(LC)
        _shadow(job)
        _promote(job)
        report = _feed(job)
        lc = job.spokes[0].nets[0].lifecycle.describe()
        assert lc["activeVersion"] == 1
        assert lc["candidateVersion"] is None
        states = {v["version"]: v["state"] for v in lc["versions"]}
        assert states[1] == ACTIVE
        assert states[0] == REGISTERED  # retained for operator Rollback
        [stats] = report.statistics
        assert stats.canary_promotions == 1
        assert stats.canary_rollbacks == 0
        assert stats.shadow_scored >= 1
        assert stats.active_version == 1

    def test_promoted_model_serves_after_swap(self):
        """After promotion the (previously candidate) pipeline IS the
        serving model: the node's pipeline object carries the candidate
        version tag and subsequent predictions are untagged (it is the
        active version now, not a canary)."""
        job = _job(LC)
        _shadow(job)
        _promote(job)
        _feed(job, terminate=False)
        net = job.spokes[0].nets[0]
        assert net.pipeline.version == 1
        n_before = len(job.predictions)
        job.process_event(FORECASTING_STREAM, json.dumps(
            {"numericalFeatures": [0.1] * DIM}))
        assert len(job.predictions) == n_before + 1
        assert job.predictions[-1].version is None
        job.terminate()

    def test_score_regression_rolls_back(self):
        """A candidate whose holdout score regresses past scoreEnvelope
        demotes without any guard trip: the C=1e-6 PA candidate barely
        learns while the baseline converges."""
        job = _job(LC)
        _shadow(job, C=1e-6)
        _feed(job, records=480, terminate=False)
        lc = job.spokes[0].nets[0].lifecycle
        entry = lc.versions[1]
        assert entry.state == ROLLED_BACK
        assert entry.trip_reason == "score_regressed"
        assert lc.active_version == 0
        report = job.terminate()
        [stats] = report.statistics
        assert stats.canary_rollbacks == 1
        assert stats.canary_promotions == 0

    def test_production_mode_needs_min_shadow_evals_zero(self):
        """test=False has no holdout, so shadow scoring cannot run; the
        documented escape hatch (minShadowEvals=0) still promotes."""
        job = _job({**LC, "minShadowEvals": 0}, test=False)
        _shadow(job)
        _promote(job)
        _feed(job)
        assert job.spokes[0].nets[0].lifecycle.active_version == 1


# --- guard-fenced rollback ----------------------------------------------------


def _poison_candidate(job, pid=0, value=1.0e9):
    entry = job.spokes[0].nets[pid].lifecycle.candidate_entry
    flat, _ = entry.pipeline.get_flat_params()
    entry.pipeline.set_flat_params(np.full_like(flat, value))


class TestGuardFencedRollback:
    def _poisoned_run(self, n_pipe=1, poison_at=120, **kw):
        job = _job(LC, n_pipe=n_pipe, **kw)
        _shadow(job)
        _promote(job)
        rng = np.random.RandomState(3)
        w = np.random.RandomState(5).randn(DIM)
        for i in range(320):
            if i == poison_at:
                _poison_candidate(job)
            f = rng.randn(DIM).astype(np.float32)
            if i % 2 == 0:
                job.process_event(FORECASTING_STREAM, json.dumps(
                    {"numericalFeatures": f.tolist()}))
            else:
                job.process_event(TRAINING_STREAM, json.dumps(
                    {"numericalFeatures": f.tolist(),
                     "target": float(f @ w > 0)}))
        return job

    def test_poisoned_candidate_rolls_back_via_guard(self):
        job = self._poisoned_run()
        lc = job.spokes[0].nets[0].lifecycle
        entry = lc.versions[1]
        assert entry.state == ROLLED_BACK
        assert entry.trip_reason in ("non_finite", "norm_exploded")
        assert lc.active_version == 0
        report = job.terminate()
        [stats] = report.statistics
        assert stats.canary_rollbacks == 1 and stats.canary_promotions == 0
        assert stats.active_version == 0

    def test_rollback_restores_baseline_serving_bitwise(self):
        """After the rollback every subsequent forecast serves through
        the untouched baseline: the full untagged prediction sequence is
        bitwise the no-canary run's, and not one forecast is lost."""
        off = _job(None)
        _feed(off)
        on = self._poisoned_run()
        p_off, p_on = _per_net_preds(off)[0], _per_net_preds(on)[0]
        assert len(p_on) == len(p_off)  # zero forecast loss
        assert sum(1 for _v, ver in p_on if ver is not None) > 0
        for (v0, _), (v1, ver) in zip(p_off, p_on):
            if ver is None:
                assert v1 == v0
        # the rollback point splits the stream: after it, EVERY forecast
        # is baseline-served (routing snapped to 100% baseline)
        last_tagged = max(
            i for i, (_v, ver) in enumerate(p_on) if ver is not None
        )
        assert all(ver is None for _v, ver in p_on[last_tagged + 1:])
        on.terminate()

    def test_healthy_cotenants_keep_exact_forecast_counts(self):
        """The ISSUE 11 blast-radius pin: tenants WITHOUT a canary serve
        exactly their no-canary forecast counts (and values) while
        tenant 0's poisoned candidate trips and rolls back."""
        off = _job(None, n_pipe=4)
        r_off = _feed(off)
        on = self._poisoned_run(n_pipe=4)
        r_on = on.terminate()
        off_served = {s.pipeline: s.forecasts_served
                      for s in r_off.statistics}
        on_served = {s.pipeline: s.forecasts_served
                     for s in r_on.statistics}
        for pid in (1, 2, 3):
            assert on_served[pid] == off_served[pid]
        p_off, p_on = _per_net_preds(off), _per_net_preds(on)
        for pid in (1, 2, 3):
            assert p_on[pid] == p_off[pid]
        by_pipe = {s.pipeline: s for s in r_on.statistics}
        assert by_pipe[0].canary_rollbacks == 1


# --- operator verbs -----------------------------------------------------------


class TestOperatorVerbs:
    def test_rollback_demotes_live_candidate(self):
        job = _job(LC)
        _shadow(job)
        _feed(job, records=64, terminate=False)
        _rollback(job)
        lc = job.spokes[0].nets[0].lifecycle
        assert lc.candidate is None
        assert lc.versions[1].state == ROLLED_BACK
        assert lc.versions[1].trip_reason == "operator"
        job.terminate()

    def test_rollback_after_promotion_reactivates_previous(self):
        job = _job(LC)
        _shadow(job)
        _promote(job)
        _feed(job, records=320, terminate=False)
        net = job.spokes[0].nets[0]
        assert net.lifecycle.active_version == 1
        flat_promoted, _ = net.pipeline.get_flat_params()
        _rollback(job)
        lc = net.lifecycle
        assert lc.active_version == 0
        assert net.pipeline.version == 0
        states = {v.version: v.state for v in lc.versions.values()}
        assert states[0] == ACTIVE and states[1] == ROLLED_BACK
        flat_back, _ = net.pipeline.get_flat_params()
        assert not np.array_equal(flat_back, flat_promoted)
        job.terminate()

    def test_promote_on_canary_force_completes(self):
        job = _job({**LC, "promoteAfter": 100_000})
        _shadow(job)
        _promote(job)  # shadow -> canary
        _feed(job, records=160, terminate=False)
        assert job.spokes[0].nets[0].lifecycle.active_version == 0
        _promote(job)  # canary -> active, operator override of the ramp
        assert job.spokes[0].nets[0].lifecycle.active_version == 1
        job.terminate()

    def test_verbs_on_unarmed_pipeline_quarantined(self):
        job = _job(None)
        _shadow(job)
        assert job.spokes[0].nets[0].lifecycle is None
        entries = [e for e in job.dead_letter.entries
                   if e["reason"] == "rejected_request"]
        assert any("not armed" in (e.get("detail") or "") for e in entries)

    def test_verbs_on_missing_pipeline_quarantined(self):
        job = _job(LC)
        job.process_event(REQUEST_STREAM, json.dumps(
            {"id": 9, "request": "Promote"}))
        assert any(e["reason"] == "rejected_request"
                   for e in job.dead_letter.entries)

    def test_shadow_with_sparse_candidate_rejected(self):
        job = _job(LC)
        job.process_event(REQUEST_STREAM, json.dumps({
            "id": 0, "request": "Shadow",
            "learner": {"name": "PA", "hyperParameters": {"C": 0.5},
                        "dataStructure": {"nFeatures": DIM,
                                          "sparse": True}},
        }))
        assert job.spokes[0].nets[0].lifecycle.candidate is None
        assert any(e["reason"] == "rejected_request"
                   for e in job.dead_letter.entries)

    def test_shadow_without_learner_rejected(self):
        job = _job(LC)
        job.process_event(REQUEST_STREAM, json.dumps(
            {"id": 0, "request": "Shadow"}))
        assert job.spokes[0].nets[0].lifecycle.candidate is None

    def test_shape_changing_candidate_quarantined(self):
        """A candidate whose flat-parameter size differs from the
        baseline's (here: a PolynomialFeatures chain widening the learner
        dim) must quarantine instead of arming — a promotion would hand
        the protocol's next sync round mismatched shapes. Architecture
        changes stay on the destructive Update path."""
        job = _job(LC)
        job.process_event(REQUEST_STREAM, json.dumps({
            "id": 0, "request": "Shadow",
            "learner": {"name": "PA", "hyperParameters": {"C": 0.5},
                        "dataStructure": {"nFeatures": DIM}},
            "preProcessors": [{"name": "PolynomialFeatures",
                               "hyperParameters": {"degree": 2}}],
        }))
        assert job.spokes[0].nets[0].lifecycle.candidate is None
        entries = [e for e in job.dead_letter.entries
                   if e["reason"] == "rejected_request"]
        assert any("parameter shape" in (e.get("detail") or "")
                   for e in entries)

    def test_sparse_pipeline_job_default_verbs_quarantined(self):
        """A job-wide lifecycle default does not arm sparse nets (the
        candidate paths are dense); a verb aimed at one quarantines at
        the job instead of vanishing spoke-side."""
        job = StreamJob(JobConfig(parallelism=1, lifecycle="on"))
        job.process_event(REQUEST_STREAM, json.dumps({
            "id": 0, "request": "Create",
            "learner": {"name": "PA", "hyperParameters": {"C": 1.0},
                        "dataStructure": {"nFeatures": 64, "sparse": True,
                                          "maxNnz": 8}},
            "trainingConfiguration": {"protocol": "Asynchronous"},
        }))
        assert 0 in job.pipeline_manager.node_map
        assert job.spokes[0].nets[0].lifecycle is None
        job.process_event(REQUEST_STREAM, json.dumps(
            {"id": 0, "request": "Promote"}))
        entries = [e for e in job.dead_letter.entries
                   if e["reason"] == "rejected_request"]
        assert any("not armed" in (e.get("detail") or "") for e in entries)


# --- checkpoint / kill-recovery ----------------------------------------------


def _events(n=2_000, lifecycle=LC, shadow_C=0.5):
    rng = np.random.RandomState(3)
    w = np.random.RandomState(5).randn(DIM)
    x = rng.randn(n, DIM).astype(np.float32)

    def gen():
        yield REQUEST_STREAM, json.dumps({
            "id": 0, "request": "Create",
            "learner": {"name": "PA", "hyperParameters": {"C": 1.0},
                        "dataStructure": {"nFeatures": DIM}},
            "trainingConfiguration": {"protocol": "Asynchronous",
                                      "syncEvery": 4,
                                      "lifecycle": lifecycle},
        })
        yield REQUEST_STREAM, json.dumps({
            "id": 0, "request": "Shadow",
            "learner": {"name": "PA", "hyperParameters": {"C": shadow_C},
                        "dataStructure": {"nFeatures": DIM}},
        })
        yield REQUEST_STREAM, json.dumps({"id": 0, "request": "Promote"})
        for i in range(n):
            if i % 2 == 0:
                yield FORECASTING_STREAM, DataInstance(
                    numerical_features=x[i].tolist(),
                    operation=FORECASTING)
            else:
                yield TRAINING_STREAM, DataInstance(
                    numerical_features=x[i].tolist(),
                    target=float(x[i] @ w > 0))

    return gen


class TestCheckpointRecovery:
    def test_snapshot_roundtrip_mid_canary(self, tmp_path):
        from omldm_tpu.checkpoint import CheckpointManager

        job = StreamJob(JobConfig(
            parallelism=1, batch_size=16, test_set_size=16,
            checkpointing=True, checkpoint_dir=str(tmp_path)))
        held = {**LC, "promoteAfter": 100_000}  # stay mid-ramp
        for stream, payload in _events(400, lifecycle=held)():
            job.process_event(stream, payload)
        lc = job.spokes[0].nets[0].lifecycle
        assert lc.canary_active  # mid-ramp
        view = lc.describe()
        cand_flat, _ = lc.candidate_entry.pipeline.get_flat_params()
        path = job.checkpoint_manager.save(job)
        restored = CheckpointManager(str(tmp_path)).restore(path=path)
        rlc = restored.spokes[0].nets[0].lifecycle
        assert rlc.describe() == view  # registry, clocks, counters
        rflat, _ = rlc.candidate_entry.pipeline.get_flat_params()
        np.testing.assert_array_equal(rflat, cand_flat)
        # the candidate's guard survived too (its ring fences the canary)
        assert rlc.candidate_entry.pipeline.guard is not None

    def test_restore_after_promotion_installs_promoted_pipeline(
        self, tmp_path
    ):
        from omldm_tpu.checkpoint import CheckpointManager

        job = StreamJob(JobConfig(
            parallelism=1, batch_size=16, test_set_size=16,
            checkpointing=True, checkpoint_dir=str(tmp_path)))
        for stream, payload in _events(1_200)():
            job.process_event(stream, payload)
        net = job.spokes[0].nets[0]
        assert net.lifecycle.active_version == 1  # promoted mid-stream
        flat, _ = net.pipeline.get_flat_params()
        path = job.checkpoint_manager.save(job)
        restored = CheckpointManager(str(tmp_path)).restore(path=path)
        rnet = restored.spokes[0].nets[0]
        assert rnet.lifecycle.active_version == 1
        assert rnet.pipeline.version == 1
        # the promoted-spec pipeline carries the promoted params (not the
        # Create-spec model the deploy constructed)
        rflat, _ = rnet.pipeline.get_flat_params()
        np.testing.assert_array_equal(rflat, flat)
        assert rnet.pipeline.learner.hp["C"] == 0.5
        # the retained version 0 is still reactivatable
        assert rnet.lifecycle.previous is not None

    def test_guard_lkg_ring_survives_restart(self, tmp_path):
        from omldm_tpu.checkpoint import CheckpointManager

        job = _job(None, guard=True)
        job.config.checkpointing = True
        job.config.checkpoint_dir = str(tmp_path)
        from omldm_tpu.checkpoint import CheckpointManager as CM

        job.checkpoint_manager = CM(str(tmp_path))
        _feed(job, records=160, terminate=False)
        guard = job.spokes[0].nets[0].pipeline.guard
        ring = [r.copy() for r in guard._ring]
        assert ring
        path = job.checkpoint_manager.save(job)
        restored = CheckpointManager(str(tmp_path)).restore(path=path)
        rring = restored.spokes[0].nets[0].pipeline.guard._ring
        assert len(rring) == len(ring)
        for a, b in zip(ring, rring):
            np.testing.assert_array_equal(a, b)

    @pytest.mark.slow
    def test_kill_mid_ramp_converges_to_fault_free_decision(self, tmp_path):
        """The ISSUE 11 kill-recovery pin: a worker crash mid-canary with
        supervised restart resumes MID-RAMP (registry + clocks + candidate
        state restored) and reaches the same promotion decision — and the
        same final counters — as the fault-free run."""
        gen = _events(2_000)

        def run(fault):
            job = StreamJob(JobConfig(
                parallelism=1, batch_size=16, test_set_size=16,
                checkpointing=bool(fault),
                checkpoint_dir=str(tmp_path), check_interval_ms=0))
            if fault:
                FaultInjector().arm(job, 0, 700)
                sup = JobSupervisor(job, replayable(gen), max_restarts=2)
                report = sup.run()
                assert sup.failures  # the crash really happened
                return sup.job, report
            return job, job.run(gen())

        clean_job, clean_report = run(False)
        fault_job, fault_report = run(True)
        clean_lc = clean_job.spokes[0].nets[0].lifecycle.describe()
        fault_lc = fault_job.spokes[0].nets[0].lifecycle.describe()
        assert fault_lc["activeVersion"] == clean_lc["activeVersion"] == 1
        assert fault_lc["counters"] == clean_lc["counters"]
        [cs] = clean_report.statistics
        [fs] = fault_report.statistics
        assert fs.canary_promotions == cs.canary_promotions == 1
        assert fs.canary_rollbacks == cs.canary_rollbacks == 0


# --- observability / statistics plumbing -------------------------------------


class TestObservability:
    def test_prediction_version_tag_wire_format(self):
        p = Prediction(0, None, 1.0)
        assert "version" not in p.to_dict()  # pre-plane wire shape
        p = Prediction(0, None, 1.0, version=3)
        assert p.to_dict()["version"] == 3

    def test_query_response_carries_registry_view(self):
        job = _job(LC)
        _shadow(job)
        _feed(job, records=160, terminate=False)
        job.process_event(REQUEST_STREAM, json.dumps(
            {"id": 0, "request": "Query", "requestId": 1}))
        [resp] = job.responses
        assert resp.lifecycle is not None
        assert resp.lifecycle["activeVersion"] == 0
        assert resp.lifecycle["candidateVersion"] == 1
        versions = {v["version"]: v for v in resp.lifecycle["versions"]}
        assert versions[1]["state"] == SHADOW
        assert versions[1]["shadowEvals"] > 0
        # wire round trip
        again = QueryResponse.from_dict(json.loads(resp.to_json()))
        assert again.lifecycle["candidateVersion"] == 1
        job.terminate()

    def test_query_response_without_plane_keeps_wire_shape(self):
        job = _job(None)
        _feed(job, records=64, terminate=False)
        job.process_event(REQUEST_STREAM, json.dumps(
            {"id": 0, "request": "Query", "requestId": 1}))
        [resp] = job.responses
        assert resp.lifecycle is None
        assert "lifecycle" not in resp.to_dict()
        job.terminate()

    def test_tenant_topology_lifecycle_section(self):
        job = _job(LC, n_pipe=2)
        _shadow(job, pid=1)
        _feed(job, records=160, terminate=False)
        topo = job.tenant_topology()
        assert set(topo["lifecycle"]) == {0, 1}
        assert topo["lifecycle"][1]["candidateVersion"] == 1
        assert topo["lifecycle"][0]["candidateVersion"] is None
        job.terminate()

    def test_statistics_counters_merge_and_dict(self):
        a, b = Statistics(0), Statistics(0)
        a.update_stats(shadow_scored=2, canary_promotions=1,
                       canary_rollbacks=0, active_version=1)
        b.update_stats(shadow_scored=1, canary_rollbacks=2,
                       active_version=3)
        m = a.merge(b)
        assert m.shadow_scored == 3
        assert m.canary_promotions == 1
        assert m.canary_rollbacks == 2
        assert m.active_version == 3  # gauge: max-combine
        d = m.to_dict()
        assert (d["shadowScored"], d["canaryPromotions"],
                d["canaryRollbacks"], d["activeVersion"]) == (3, 1, 2, 3)

    def test_active_version_gauge_tracks_rollback_down(self):
        """The gauge is last-write per fold: a Query mid-promotion folds
        activeVersion=1, but an operator Rollback afterwards must bring
        the FINAL report back to 0 — a max would pin the historical peak
        and report a rolled-back version as live forever."""
        job = _job(LC)
        _shadow(job)
        _promote(job)
        _feed(job, records=320, terminate=False)
        assert job.spokes[0].nets[0].lifecycle.active_version == 1
        job.process_event(REQUEST_STREAM, json.dumps(
            {"id": 0, "request": "Query", "requestId": 1}))
        assert job.hub_manager.hubs[(0, 0)].node.stats.active_version == 1
        _rollback(job)  # reactivate the retained version 0
        report = job.terminate()
        [stats] = report.statistics
        assert stats.active_version == 0
        assert stats.canary_rollbacks == 1

    def test_counters_fold_once_per_query(self):
        job = _job(LC)
        _shadow(job)
        _feed(job, records=160, terminate=False)
        job.process_event(REQUEST_STREAM, json.dumps(
            {"id": 0, "request": "Query", "requestId": 1}))
        hub = job.hub_manager.hubs[(0, 0)]
        folded = hub.node.stats.shadow_scored
        assert folded > 0
        report = job.terminate()
        [stats] = report.statistics
        # the terminate fold adds only the NEW evals since the query
        assert stats.shadow_scored >= folded
        lc = job.spokes[0].nets[0].lifecycle
        assert stats.shadow_scored == lc.totals["shadow_scored"]


# --- live rescale composed with the lifecycle plane (ISSUE 12 satellite) -----


class TestRescaleComposition:
    """Live ``rescale()`` mid-canary must keep the registry/clock state
    consistent: the candidate survives on the surviving spoke, healthy
    co-tenant forecast counts stay exactly equal to a canary-free run of
    the same stream + rescale, and the rescaled fleet keeps serving and
    promoting without a crash."""

    def _run(self, rescale_to, par=1, canary=True, records_pre=160,
             records_post=160, post_cycle=2):
        """``post_cycle=2`` keeps the module's alternating stream shape;
        3 breaks the train/forecast <-> round-robin parity lock (at par 2
        an alternating stream pins ALL forecasts to one spoke and ALL
        training rows to the other — a degenerate split real streams
        don't sustain) so per-spoke promotion conditions can complete."""
        job = _job(LC if canary else None, n_pipe=2, parallelism=par)
        if canary:
            _shadow(job)
            _promote(job)
        _feed(job, records=records_pre, terminate=False)
        job.rescale(rescale_to)
        # continue the SAME stream past the rescale point
        rng = np.random.RandomState(3)
        w = np.random.RandomState(5).randn(DIM)
        for _ in range(records_pre):
            rng.randn(DIM)  # replay the consumed prefix of the stream
        for i in range(records_post):
            f = rng.randn(DIM).astype(np.float32)
            if (records_pre + i) % post_cycle == 0:
                job.process_event(FORECASTING_STREAM, json.dumps(
                    {"numericalFeatures": f.tolist()}))
            else:
                job.process_event(TRAINING_STREAM, json.dumps(
                    {"numericalFeatures": f.tolist(),
                     "target": float(f @ w > 0)}))
        report = job.terminate()
        return job, report

    def test_grow_mid_canary_consistent(self):
        job, report = self._run(rescale_to=2)
        by = {s.pipeline: s for s in report.statistics}
        # zero forecast loss on every tenant across the grow
        assert by[0].forecasts_served == 160
        assert by[1].forecasts_served == 160
        assert by[0].rescales_performed == 1
        # the candidate lives on (worker-0 registry is the representative
        # view; rescaled-in spokes serve 100% baseline)
        lc = job.spokes[0].nets[0].lifecycle
        assert lc.candidate is not None or lc.totals["canary_promotions"] >= 1
        # no phantom rollback from the rescale itself
        assert by[0].canary_rollbacks == 0
        # registry view still coherent through the topology report
        topo = job.tenant_topology()
        assert 0 in topo["lifecycle"]

    def test_grow_mid_canary_healthy_tenant_unchanged(self):
        """Healthy-tenant (net 1) forecast count under a mid-canary grow
        equals the canary-free run of the identical stream + rescale."""
        _, with_canary = self._run(rescale_to=2, canary=True)
        _, without = self._run(rescale_to=2, canary=False)
        served = lambda r, p: {  # noqa: E731
            s.pipeline: s.forecasts_served for s in r.statistics
        }[p]
        assert served(with_canary, 1) == served(without, 1)

    def test_shrink_mid_canary_candidate_survives(self):
        job, report = self._run(rescale_to=1, par=2)
        by = {s.pipeline: s for s in report.statistics}
        assert by[0].forecasts_served == 160
        assert by[1].forecasts_served == 160
        # the SURVIVING spoke's candidate is intact; the retired
        # replica's registry row released silently (no rollback count)
        lc = job.spokes[0].nets[0].lifecycle
        assert lc.candidate is not None or lc.totals["canary_promotions"] >= 1
        assert by[0].canary_rollbacks == 0
        assert by[0].rescales_performed == 1

    def test_shrink_mid_canary_healthy_tenant_unchanged(self):
        _, with_canary = self._run(rescale_to=1, par=2, canary=True)
        _, without = self._run(rescale_to=1, par=2, canary=False)
        served = lambda r, p: {  # noqa: E731
            s.pipeline: s.forecasts_served for s in r.statistics
        }[p]
        assert served(with_canary, 1) == served(without, 1)

    def test_grow_then_promote_completes(self):
        """The canary keeps training AND ramping after a grow — the
        replicated registry twin-trains on the new spoke too, so with
        enough post-rescale stream the ramp completes and the candidate
        promotes on the spokes that host it."""
        job, report = self._run(rescale_to=2, records_pre=64,
                                records_post=420, post_cycle=3)
        [s0] = [s for s in report.statistics if s.pipeline == 0]
        assert s0.canary_promotions >= 1
        # both spokes' replicated registries kept twin-training
        for spoke in job.spokes:
            assert spoke.nets[0].lifecycle.describe()["versions"][-1][
                "fits"
            ] > 1
