"""Lossy-channel hardening: reliable hub<->spoke transport, hub-side worker
liveness with quorum round release, and the deterministic chaos channel.

The reference's PS->worker feedback edge rides Kafka (psMessages,
Job.scala:76-87,135-142) — at-least-once, so messages duplicate, reorder,
delay, and vanish on broker restarts. These tests pin the hardening layer:
per-stream sequence numbers + receive windows (dedupe / bounded reorder /
gap->NACK->resync), hub-side worker-deadline clocks with k-of-n quorum
round release, and the seeded ChaosChannel that makes every fault schedule
a pure function of (seed, name, call sequence).
"""

import json

import numpy as np
import pytest

from omldm_tpu.api.requests import TrainingConfiguration
from omldm_tpu.config import JobConfig
from omldm_tpu.runtime import StreamJob
from omldm_tpu.runtime.codec import TransportCodec
from omldm_tpu.runtime.job import REQUEST_STREAM, TRAINING_STREAM
from omldm_tpu.runtime.messages import (
    OP_RESYNC,
    ReceiveWindow,
    StreamSequencer,
    reliability_armed,
)
from omldm_tpu.runtime.supervisor import (
    ChaosChannel,
    ChaosConsumer,
    parse_chaos_spec,
)

# the acceptance operating point (ISSUE 4): 5% drop, 5% dup, reorder
# window 4, both directions
ACCEPTANCE_CHAOS = "seed=7,drop=0.05,dup=0.05,reorder=0.1,window=4"

PARAM_PROTOCOLS = ["Asynchronous", "Synchronous", "SSP", "EASGD", "GM", "FGM"]


def stream_lines(n, dim=6, seed=0):
    rng = np.random.RandomState(seed)
    w = rng.randn(dim)
    x = rng.randn(n, dim)
    y = (x @ w > 0).astype(np.float64)
    return [
        json.dumps(
            {"numericalFeatures": list(np.round(x[i], 5)), "target": float(y[i])}
        )
        for i in range(n)
    ]


def run_protocol(protocol, n=2500, parallelism=4, chaos="", comm=None,
                 extra=None, lines=None):
    cfg = JobConfig(
        parallelism=parallelism, batch_size=32, test_set_size=32, chaos=chaos
    )
    job = StreamJob(cfg)
    tc = {"protocol": protocol, "syncEvery": 2}
    if comm is not None:
        tc["comm"] = comm
    if extra:
        tc.update(extra)
    create = {
        "id": 0,
        "request": "Create",
        "learner": {"name": "PA", "hyperParameters": {"C": 1.0}},
        "trainingConfiguration": tc,
    }
    events = [(REQUEST_STREAM, json.dumps(create))] + [
        (TRAINING_STREAM, l) for l in (lines or stream_lines(n))
    ]
    report = job.run(events)
    assert report is not None, f"{protocol}: no job statistics emitted"
    [stats] = report.statistics
    return job, stats


# --- unit: sequencer + receive window ---------------------------------------


class TestStreamSequencer:
    def test_monotonic_per_stream(self):
        s = StreamSequencer()
        assert [s.next("a"), s.next("a"), s.next("b"), s.next("a")] == [0, 1, 0, 2]

    def test_drop_streams_restarts_at_zero(self):
        s = StreamSequencer()
        s.next(3), s.next(3), s.next(1)
        s.drop_streams([3])
        assert s.next(3) == 0
        assert s.next(1) == 1


class TestReceiveWindow:
    def test_in_order_passthrough(self):
        w = ReceiveWindow(4)
        for i in range(5):
            res = w.offer(i, "op", i)
            assert res.deliver == [("op", i)]
            assert not res.gap and not res.duplicates

    def test_duplicates_dropped(self):
        w = ReceiveWindow(4)
        w.offer(0, "op", "a")
        res = w.offer(0, "op", "a")
        assert res.deliver == [] and res.duplicates == 1
        # duplicate of a HELD (not yet delivered) message drops too
        w.offer(2, "op", "c")
        res = w.offer(2, "op", "c")
        assert res.duplicates == 1
        assert w.duplicates_dropped == 2

    def test_reorder_within_window(self):
        w = ReceiveWindow(4)
        assert w.offer(1, "op", "b").deliver == []
        assert w.offer(2, "op", "c").deliver == []
        res = w.offer(0, "op", "a")
        assert res.deliver == [("op", "a"), ("op", "b"), ("op", "c")]
        assert w.expected == 3

    def test_gap_fast_forward_and_flag(self):
        w = ReceiveWindow(2)
        w.offer(0, "op", "a")
        # seq 1 lost; 2, 3 hold; 4 breaches the window => gap declared,
        # held messages deliver in order and the window skips the hole
        assert w.offer(2, "op", "c").deliver == []
        assert w.offer(3, "op", "d").gap is False
        res = w.offer(4, "op", "e")
        assert res.gap is True
        assert res.deliver == [("op", "c"), ("op", "d"), ("op", "e")]
        assert w.expected == 5
        assert w.gaps_resynced == 1

    def test_resync_supersedes_held(self):
        w = ReceiveWindow(8)
        w.offer(0, "op", "a")
        w.offer(3, "op", "stale-held")
        res = w.offer(5, OP_RESYNC, {"params": 1})
        assert res.deliver == [(OP_RESYNC, {"params": 1})]
        assert w.expected == 6
        # the held pre-resync message is gone; later traffic flows in order
        assert w.offer(6, "op", "f").deliver == [("op", "f")]

    def test_stale_duplicate_resync_does_not_rewind(self):
        """A late DUPLICATE of an already-processed resync (dup chaos
        delivers held copies late) must drop like any duplicate — not
        rewind the window onto stale state."""
        w = ReceiveWindow(8)
        w.offer(5, OP_RESYNC, {"params": "fresh"})
        for s in range(6, 10):
            w.offer(s, "op", s)
        res = w.offer(5, OP_RESYNC, {"params": "fresh"})
        assert res.duplicates == 1 and res.deliver == []
        assert w.expected == 10

    def test_window_born_in_passthrough_delivers_immediately(self):
        """A window created after stream quiesce (first-ever message from
        a peer whose earlier traffic was all lost) must not hold the
        terminate-time push behind its zero expectation."""
        w = ReceiveWindow(8, passthrough=True)
        assert w.offer(3, "op", "late-final-push").deliver == [
            ("op", "late-final-push")
        ]

    def test_flush_then_passthrough(self):
        w = ReceiveWindow(8)
        w.offer(0, "op", "a")
        w.offer(2, "op", "c")
        assert w.flush() == [("op", "c")]
        # post-quiesce: messages pass through even over holes...
        assert w.offer(7, "op", "h").deliver == [("op", "h")]
        # ...but stale duplicates still drop
        assert w.offer(2, "op", "c").duplicates == 1


# --- unit: chaos channel determinism ----------------------------------------


class TestChaosSpec:
    def test_parse_directions_and_defaults(self):
        spec = parse_chaos_spec("seed=9,drop=0.1,up.dup=0.2,window=6")
        assert spec["seed"] == 9 and spec["window"] == 6
        assert spec["up"] == {
            "drop": 0.1, "dup": 0.2, "reorder": 0.0, "delay": 0.0,
            "nan": 0.0, "explode": 0.0, "poison": 0.0,
        }
        assert spec["down"]["dup"] == 0.0 and spec["down"]["drop"] == 0.1
        assert parse_chaos_spec("") is None
        with pytest.raises(ValueError):
            parse_chaos_spec("dorp=0.1")


class TestChaosChannelDeterminism:
    def _schedule(self, seed, n=300, **params):
        out = []
        chan = ChaosChannel(
            lambda *args: out.append(args), seed=seed, name="t", **params
        )
        for i in range(n):
            chan.send(i)
        chan.quiesce()
        return out, chan.counters()

    def test_same_seed_identical_schedule(self):
        """Satellite: same seed => identical drop/dup/reorder schedule,
        down to the exact delivery order."""
        a, ca = self._schedule(7, drop=0.1, dup=0.1, reorder=0.2, window=4)
        b, cb = self._schedule(7, drop=0.1, dup=0.1, reorder=0.2, window=4)
        assert a == b
        assert ca == cb
        assert ca["dropped"] > 0 and ca["duplicated"] > 0 and ca["reordered"] > 0

    def test_different_seed_different_schedule(self):
        a, _ = self._schedule(7, drop=0.1, dup=0.1, reorder=0.2, window=4)
        b, _ = self._schedule(8, drop=0.1, dup=0.1, reorder=0.2, window=4)
        assert a != b

    def test_conservation_without_drop(self):
        """dup/reorder-only chaos conserves (and adds) messages — nothing
        vanishes once the channel quiesces."""
        out, c = self._schedule(3, dup=0.2, reorder=0.3, window=4)
        assert len(out) == 300 + c["duplicated"]
        assert sorted(m[0] for m in set(out)) == list(range(300))

    def test_zero_probabilities_pass_through_in_order(self):
        out, c = self._schedule(5)
        assert [m[0] for m in out] == list(range(300))
        assert c["dropped"] == c["duplicated"] == c["reordered"] == 0

    def test_quiesce_flushes_and_disables(self):
        out = []
        chan = ChaosChannel(
            lambda *a: out.append(a), seed=1, drop=1.0, name="q"
        )
        chan.send("eaten")
        chan.quiesce()
        chan.send("after")
        assert out == [("after",)]

    def test_consumer_same_seed_same_schedule(self):
        def records():
            return iter(range(200))

        def consume(seed):
            out, chaos = [], ChaosConsumer(
                records(), seed=seed, drop=0.1, dup=0.15, reorder=0.2
            )
            for rec in chaos:
                out.append(rec)
            return out

        assert consume(4) == consume(4)
        assert consume(4) != consume(5)


# --- the reliable layer is transparent when nothing misbehaves ---------------


class TestReliableTransparency:
    @pytest.mark.parametrize("protocol", ["Synchronous", "SSP", "FGM"])
    def test_armed_faultless_is_bit_identical(self, protocol):
        """comm.reliable=true with a clean channel must not change a single
        statistic: sequence stamping, windows, and watchdogs are invisible
        until something actually goes wrong."""
        lines = stream_lines(2500)
        _, base = run_protocol(protocol, lines=lines)
        _, armed = run_protocol(protocol, comm={"reliable": True}, lines=lines)
        assert base.to_dict() == armed.to_dict()

    def test_reliability_arming_rules(self):
        tc = TrainingConfiguration(protocol="Synchronous")
        assert not reliability_armed(tc, "")
        assert reliability_armed(tc, "seed=1,drop=0.1")
        tc_q = TrainingConfiguration(
            protocol="Synchronous", extra={"comm": {"quorum": 2}}
        )
        assert reliability_armed(tc_q, "")
        tc_off = TrainingConfiguration(
            protocol="Synchronous", extra={"comm": {"reliable": False}}
        )
        assert not reliability_armed(tc_off, "seed=1,drop=0.1")


# --- duplicate-delivery idempotence (all parameter protocols) ----------------


class TestDuplicateIdempotence:
    @pytest.mark.parametrize("protocol", PARAM_PROTOCOLS)
    def test_dup_only_chaos_is_bit_identical(self, protocol):
        """Satellite: under dup-ONLY chaos (nothing lost, nothing
        reordered away — duplicates arrive late but every original arrives
        on time) the receive windows drop every duplicate, so the stats are
        BIT-IDENTICAL to the fault-free run except for the duplicate
        counter itself."""
        lines = stream_lines(2500)
        _, clean = run_protocol(protocol, comm={"reliable": True}, lines=lines)
        _, dup = run_protocol(
            protocol, chaos="seed=3,dup=0.3,window=4", lines=lines
        )
        d_clean, d_dup = clean.to_dict(), dup.to_dict()
        dropped = d_dup.pop("duplicatesDropped")
        d_clean.pop("duplicatesDropped")
        assert dropped > 0, f"{protocol}: no duplicates delivered (seed too kind?)"
        assert d_clean == d_dup


# --- gap -> NACK -> resync ---------------------------------------------------


class TestGapResync:
    def test_drop_chaos_triggers_resync_and_converges(self):
        """Heavy drop chaos with a tight receive window forces gap
        declarations; the NACK/resync cycle must both fire (counter) and
        repair (score)."""
        job, stats = run_protocol(
            "Asynchronous",
            chaos="seed=11,drop=0.2,window=2",
            comm={"windowSize": 2},
            extra={"syncEvery": 1},
        )
        assert stats.gaps_resynced > 0
        assert stats.score > 0.8

    def test_blocking_protocol_survives_heavy_loss(self):
        """BSP under 20% drop: lost pushes and lost releases both stall
        rounds; the stall watchdog's NACK/re-push and the hub resync must
        keep the job moving to a converged model with zero crashes."""
        job, stats = run_protocol(
            "Synchronous",
            chaos="seed=13,drop=0.2,window=4",
            comm={"windowSize": 4, "stallAfter": 4},
            extra={"syncEvery": 1},
        )
        assert stats.score > 0.8


# --- acceptance: convergence under the ISSUE operating point -----------------


class TestChaosConvergence:
    @pytest.mark.parametrize("protocol", PARAM_PROTOCOLS)
    def test_protocol_converges_under_seeded_chaos(self, protocol):
        """Acceptance: drop=0.05, dup=0.05, reorder window 4 => every
        parameter protocol finishes (zero crashes) with the final holdout
        score within 5% of the fault-free run."""
        lines = stream_lines(2500)
        _, clean = run_protocol(protocol, lines=lines)
        _, chaotic = run_protocol(protocol, chaos=ACCEPTANCE_CHAOS, lines=lines)
        assert chaotic.score > 0.0
        assert abs(chaotic.score - clean.score) <= 0.05, (
            f"{protocol}: chaos score {chaotic.score} vs clean {clean.score}"
        )


# --- hub-side liveness: quorum round release + re-admission ------------------


def _silent_worker_job(protocol="Synchronous", parallelism=3, quorum=2,
                       timeout_ms=1000, extra=None):
    job = StreamJob(
        JobConfig(parallelism=parallelism, batch_size=16, test_set_size=16)
    )
    tc = {
        "protocol": protocol,
        "syncEvery": 1,
        "comm": {"quorum": quorum, "workerTimeoutMs": timeout_ms},
    }
    if extra:
        tc.update(extra)
    create = {
        "id": 0,
        "request": "Create",
        "learner": {
            "name": "PA",
            "hyperParameters": {"C": 1.0},
            "dataStructure": {"nFeatures": 6},
        },
        "trainingConfiguration": tc,
    }
    job.process_event(REQUEST_STREAM, json.dumps(create))
    hub = job.hub_manager.hubs[(0, 0)].node
    now = [0.0]
    hub._clock = lambda: now[0]
    return job, hub, now


class TestQuorumRelease:
    def test_bsp_round_releases_on_quorum_within_timeout(self):
        """Acceptance: a BSP round with one silent worker releases via
        quorum once comm.workerTimeoutMs elapses — the survivors unblock
        and keep training instead of buffering forever."""
        job, hub, now = _silent_worker_job()
        silent = job.spokes[2].nets[0]
        silent.node.send = lambda *a, **k: None  # dead NIC
        lines = stream_lines(600)
        for l in lines[:300]:
            job.process_event(TRAINING_STREAM, l)
        w0 = job.spokes[0].nets[0].node
        assert w0.waiting, "precondition: survivors blocked on the silent worker"
        assert hub.stats.quorum_releases == 0
        fitted_before = job.spokes[0].nets[0].pipeline.fitted

        now[0] = 2.0  # past the 1s deadline; records are the clock
        for l in lines[300:]:
            job.process_event(TRAINING_STREAM, l)
        assert hub._retired_live == {2}
        assert hub.stats.quorum_releases > 0
        assert not w0.waiting
        assert job.spokes[0].nets[0].pipeline.fitted > fitted_before

    def test_silent_worker_readmitted_as_fresh_join(self):
        """A retired worker that speaks again is re-admitted: barriers
        count it once more and it is caught up with an authoritative
        resync (the fresh-join seed)."""
        job, hub, now = _silent_worker_job()
        silent = job.spokes[2].nets[0]
        real_send = silent.node.send
        silent.node.send = lambda *a, **k: None
        lines = stream_lines(900, seed=2)
        for l in lines[:300]:
            job.process_event(TRAINING_STREAM, l)
        now[0] = 2.0
        for l in lines[300:600]:
            job.process_event(TRAINING_STREAM, l)
        assert hub._retired_live == {2}

        silent.node.send = real_send  # the worker comes back
        fitted_back = silent.pipeline.fitted
        for l in lines[600:]:
            job.process_event(TRAINING_STREAM, l)
        assert hub._retired_live == set()
        assert silent.pipeline.fitted > fitted_back
        report = job.terminate()
        [stats] = report.statistics
        assert stats.score > 0.8

    def test_quorum_floor_is_respected(self):
        """Liveness must never retire below the quorum floor: with
        quorum=2 of 3 and TWO silent workers, only one retires."""
        job, hub, now = _silent_worker_job()
        for w in (1, 2):
            job.spokes[w].nets[0].node.send = lambda *a, **k: None
        lines = stream_lines(400, seed=4)
        for l in lines[:200]:
            job.process_event(TRAINING_STREAM, l)
        now[0] = 2.0
        for l in lines[200:]:
            job.process_event(TRAINING_STREAM, l)
        assert len(hub._retired_live) == 1
        assert hub.round_target() == 2

    def test_default_n_of_n_never_retires(self):
        """comm.quorum unset => the exact pre-liveness behavior: the hub
        waits for everyone, timeout or not."""
        job = StreamJob(
            JobConfig(parallelism=3, batch_size=16, test_set_size=16)
        )
        create = {
            "id": 0,
            "request": "Create",
            "learner": {
                "name": "PA",
                "hyperParameters": {"C": 1.0},
                "dataStructure": {"nFeatures": 6},
            },
            "trainingConfiguration": {"protocol": "Synchronous", "syncEvery": 1},
        }
        job.process_event(REQUEST_STREAM, json.dumps(create))
        hub = job.hub_manager.hubs[(0, 0)].node
        assert not hub.liveness_armed
        job.spokes[2].nets[0].node.send = lambda *a, **k: None
        for l in stream_lines(300, seed=5):
            job.process_event(TRAINING_STREAM, l)
        assert hub._retired_live == set()
        assert job.spokes[0].nets[0].node.waiting  # still blocked: n-of-n


# --- satellite: SSP wait-set release when the last straggler retires ---------


class TestSSPRetiredStraggler:
    def _ssp_hub(self, n_workers=3, staleness=1):
        from omldm_tpu.protocols.sync import SSPParameterServer

        sent = []
        tc = TrainingConfiguration(
            protocol="SSP",
            extra={"staleness": staleness,
                   "comm": {"quorum": 2, "workerTimeoutMs": 1000}},
        )
        hub = SSPParameterServer(
            0, 0, n_workers, 1, tc,
            lambda w, op, p: sent.append((w, op, p)),
            lambda op, p: sent.append(("*", op, p)),
        )
        return hub, sent

    def _push(self, hub, worker, clock):
        # mirror the runtime boundary (Hub.receive): every message marks
        # the sender alive before protocol dispatch
        hub.note_worker(worker)
        hub.receive(worker, "push", {
            "params": np.ones(4, np.float32) * clock,
            "clock": clock, "curve": [], "fitted": 0,
        })

    def test_survivor_waiting_only_on_retired_straggler_releases(self):
        """Satellite regression: workers 0 and 1 run ahead and block on
        straggler 2's clock; liveness retires 2 mid-round — the release
        loop must re-fire for the survivors even though the straggler was
        the LAST member of their wait-set."""
        hub, sent = self._ssp_hub()
        now = [0.0]
        hub._clock = lambda: now[0]
        self._push(hub, 2, 1)   # straggler pushed once, then went silent
        now[0] = 0.5
        for clock in (1, 2, 3):
            self._push(hub, 0, clock)
            self._push(hub, 1, clock)
        assert hub._waiting[0] and hub._waiting[1]

        now[0] = 2.0  # straggler past the deadline
        # blocked survivors stay visibly alive through their stall-watchdog
        # NACKs (Hub.receive -> note_worker); emulate those heartbeats
        hub.note_worker(0)
        hub.note_worker(1)
        hub.check_liveness()
        assert hub._retired_live == {2}
        assert 2 not in hub._clocks, "retired clock must leave the window"
        assert not hub._waiting.get(0, False) and not hub._waiting.get(1, False)
        released = [m for m in sent if m[1] == "update" and not m[2]["wait"]]
        assert len(released) >= 2
        assert hub.stats.quorum_releases >= 2

    def test_shrink_rescale_release_still_works(self):
        """The pre-existing rescale path: pruning retired ids on shrink
        re-evaluates the wait-set the same way."""
        hub, sent = self._ssp_hub()
        self._push(hub, 2, 1)
        for clock in (1, 2, 3):
            self._push(hub, 0, clock)
            self._push(hub, 1, clock)
        assert hub._waiting[0] and hub._waiting[1]
        hub.set_parallelism(2)
        assert not hub._waiting.get(0, False) and not hub._waiting.get(1, False)

    def test_never_pushed_straggler_releases_too(self):
        """The straggler never pushed at all (clock-0 by absence): its
        retirement must stop it from anchoring ``slowest`` at 0."""
        hub, sent = self._ssp_hub()
        now = [0.0]
        hub._clock = lambda: now[0]
        for clock in (1, 2, 3):
            self._push(hub, 0, clock)
            self._push(hub, 1, clock)
        assert hub._waiting[0] and hub._waiting[1]
        now[0] = 2.0
        hub.note_worker(0)
        hub.note_worker(1)
        hub.check_liveness()
        assert hub._retired_live == {2}
        assert not hub._waiting.get(0, False) and not hub._waiting.get(1, False)


# --- satellite: codec stream state for retired worker slots ------------------


class TestCodecRetiredWorkerStreams:
    def _seeded_codec(self):
        codec = TransportCodec("topk", top_k=4)
        for stream in ("w0>h0", "w2>h0", "h0>w0", "h0>w2", "h0>*"):
            codec.encode({"params": np.arange(64, dtype=np.float32)}, stream)
        # receive-side bases for both worker streams
        for stream in ("w0>h0", "w2>h0"):
            enc = codec.encode(
                {"params": np.arange(64, dtype=np.float32)}, stream
            )
        codec._rx_base[("w0>h0", ".params")] = np.zeros(64, np.float32)
        codec._rx_base[("w2>h0", ".params")] = np.ones(64, np.float32)
        return codec

    def test_reset_retired_clears_rx_and_tx_state(self):
        """Satellite: after shrink-absorb, NO codec state — receive-side
        delta bases included — may survive for retired worker node-ids: a
        reused slot would decode against a dead worker's stale base."""
        codec = self._seeded_codec()
        codec.reset_retired_worker_streams(2)
        for d in (codec._residual, codec._tx_base, codec._tx_seq,
                  codec._rx_base):
            for (stream, _path) in d:
                assert "w2" not in stream, f"stale retired-worker stream {stream}"
        # surviving workers' and broadcast streams stay intact
        assert any(k[0] == "w0>h0" for k in codec._tx_base)
        assert any(k[0] == "h0>*" for k in codec._tx_base)
        assert any(k[0] == "w0>h0" for k in codec._rx_base)

    def test_rescale_under_topk_converges(self):
        """Pin the end-to-end path: topk codec + shrink + grow back into
        the SAME worker slot. The hub's codec must hold no retired-slot
        state after the shrink, and the regrown fleet must keep
        converging (a stale base would wreck the decoded models)."""
        cfg = JobConfig(parallelism=3, batch_size=16, test_set_size=16)
        job = StreamJob(cfg)
        create = {
            "id": 0,
            "request": "Create",
            "learner": {
                "name": "PA",
                "hyperParameters": {"C": 1.0},
                # wide enough that the flat params clear the codec's
                # min-leaf-size floor (tiny leaves ship raw)
                "dataStructure": {"nFeatures": 32},
            },
            "trainingConfiguration": {
                "protocol": "Asynchronous",
                "syncEvery": 1,
                "comm": {"codec": "topk", "anchorEvery": 8},
            },
        }
        job.process_event(REQUEST_STREAM, json.dumps(create))
        lines = stream_lines(1800, dim=32, seed=6)
        for l in lines[:600]:
            job.process_event(TRAINING_STREAM, l)
        hub_codec = job.hub_manager.hubs[(0, 0)].node.codec
        assert any("w2" in k[0] for k in hub_codec._rx_base), (
            "precondition: worker 2 streams exist before the shrink"
        )
        job.rescale(2)
        for d in (hub_codec._residual, hub_codec._tx_base,
                  hub_codec._tx_seq, hub_codec._rx_base):
            assert not any("w2" in k[0] for k in d), (
                "retired worker 2's codec state must not survive the shrink"
            )
        for l in lines[600:1200]:
            job.process_event(TRAINING_STREAM, l)
        job.rescale(3)  # worker slot 2 is reused by a fresh join
        for l in lines[1200:]:
            job.process_event(TRAINING_STREAM, l)
        report = job.terminate()
        [stats] = report.statistics
        assert stats.score > 0.8
