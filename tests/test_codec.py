"""Transport codec unit tests: kernel round-trip bounds, error-feedback
behavior, payload walking, wire-byte accounting, and config plumbing."""

import numpy as np
import pytest

from omldm_tpu.api.requests import TrainingConfiguration
from omldm_tpu.ops.codec import (
    fp16_decode,
    fp16_encode,
    int8_affine_decode,
    int8_affine_encode,
    int8_quantization_step,
    topk_decode,
    topk_encode,
)
from omldm_tpu.runtime.codec import (
    EncodedLeaf,
    TransportCodec,
    comm_codec_name,
    decode_payload,
    make_transport_codec,
)
from omldm_tpu.runtime.messages import payload_size


def tc_for(codec, **comm):
    return TrainingConfiguration(
        protocol="Asynchronous", extra={"comm": {"codec": codec, **comm}}
    )


SHAPES = [(17,), (257,), (64, 3), (1024,)]


class TestKernelRoundTrips:
    @pytest.mark.parametrize("shape", SHAPES)
    def test_int8_affine_within_one_grid_step(self, shape):
        rng = np.random.RandomState(0)
        x = (rng.randn(*shape) * rng.uniform(0.1, 50)).astype(np.float32)
        q, scale, zero = int8_affine_encode(x)
        dec = int8_affine_decode(q, scale, zero).reshape(shape)
        bound = int8_quantization_step(x) + 1e-6
        assert np.max(np.abs(dec - x)) <= bound

    def test_int8_constant_vector_exact(self):
        x = np.full((100,), 3.25, np.float32)
        q, scale, zero = int8_affine_encode(x)
        dec = int8_affine_decode(q, scale, zero)
        np.testing.assert_allclose(dec, x, atol=1e-6)

    @pytest.mark.parametrize("shape", SHAPES)
    def test_fp16_round_trip_relative_bound(self, shape):
        rng = np.random.RandomState(1)
        x = rng.randn(*shape).astype(np.float32)
        dec = fp16_decode(fp16_encode(x)).reshape(shape)
        # fp16 has a 10-bit mantissa: relative error < 2^-10 per element
        assert np.max(np.abs(dec - x) / np.maximum(np.abs(x), 1e-3)) < 2**-10

    def test_topk_keeps_largest_and_scatter_inverts(self):
        x = np.zeros((64,), np.float32)
        hot = [3, 17, 40, 63]
        x[hot] = [5.0, -7.0, 2.0, 1.5]
        idx, val = topk_encode(x, 3)
        assert set(idx.tolist()) == {3, 17, 40}  # largest magnitudes
        dec = topk_decode(idx, val, 64)
        np.testing.assert_allclose(dec[idx], x[idx])
        assert dec[63] == 0.0  # dropped mass stays for error feedback

    def test_topk_k_covers_everything(self):
        x = np.arange(10, dtype=np.float32)
        idx, val = topk_encode(x, 100)
        np.testing.assert_allclose(topk_decode(idx, val, 10), x)


class TestErrorFeedback:
    def test_int8_residual_drains_on_constant_stream(self):
        """Shipping the SAME vector repeatedly must not accumulate
        transport error: with error feedback the time-averaged decode
        converges to the true value and the residual stays bounded by
        one quantization step."""
        codec = TransportCodec("int8", min_leaf_size=4)
        rng = np.random.RandomState(2)
        x = rng.randn(257).astype(np.float32)
        decs = []
        for _ in range(64):
            leaf = codec.encode({"params": x}, stream="w0>h0")["params"]
            decs.append(decode_payload({"params": leaf})["params"])
        step = int8_quantization_step(x)
        resid = codec._residual[("w0>h0", ".params")]
        assert np.max(np.abs(resid)) <= 2 * step + 1e-6
        avg = np.mean(decs, axis=0)
        # time-averaged transport error well below one grid step
        assert np.max(np.abs(avg - x)) < step / 2

    def test_fp16_residual_drains_on_constant_stream(self):
        codec = TransportCodec("fp16", min_leaf_size=4)
        x = (np.random.RandomState(3).randn(64) * 100).astype(np.float32)
        decs = []
        for _ in range(32):
            leaf = codec.encode({"params": x}, stream="s")["params"]
            decs.append(decode_payload({"params": leaf})["params"])
        avg = np.mean(decs, axis=0)
        assert np.max(np.abs(avg - x) / np.maximum(np.abs(x), 1e-3)) < 2**-12

    def test_topk_converges_on_constant_stream(self):
        """Repeated syncs of a static vector ship the missed mass via the
        residual until the receiver base equals the vector exactly."""
        tx = TransportCodec("topk", top_k=8, min_leaf_size=4)
        rx = TransportCodec("topk", top_k=8, min_leaf_size=4)
        x = np.random.RandomState(4).randn(64).astype(np.float32)
        dec = None
        for _ in range(64 // 8 + 2):
            leaf = tx.encode({"params": x}, stream="w0>h0")["params"]
            dec = rx.decode({"params": leaf})["params"]
        np.testing.assert_allclose(dec, x, atol=1e-5)

    def test_topk_gapped_receiver_recovers_at_anchor(self):
        """A receiver that misses deltas (or joins a live stream late)
        desyncs its base — the periodic stream anchor (sender restarts
        from a zero base at seq 0) must bring it back within one cycle."""
        tx = TransportCodec("topk", top_k=16, min_leaf_size=4,
                            anchor_every=8)
        rx = TransportCodec("topk", top_k=16, min_leaf_size=4,
                            anchor_every=8)
        rng = np.random.RandomState(10)
        x = rng.randn(64).astype(np.float32)
        dec = None
        for i in range(24):
            if i < 16:  # drift during the first two cycles, then settle
                x = x + rng.randn(64).astype(np.float32) * 0.01
            leaf = tx.encode({"params": x}, stream="h0>*")["params"]
            if 3 <= i <= 5:
                continue  # receiver misses these messages entirely
            dec = rx.decode({"params": leaf})["params"]
        # 24 messages = 3 anchor cycles; the gap sat in cycle 0, and the
        # final cycle re-shipped the settled vector from a fresh base
        assert np.max(np.abs(dec - x)) < 1e-4

    def test_reset_streams_drops_all_state(self):
        codec = TransportCodec("int8", min_leaf_size=4)
        codec.encode({"params": np.ones((32,), np.float32)}, stream="s")
        assert codec._residual
        codec.reset_streams()
        assert not codec._residual and not codec._tx_base

    def test_streams_are_independent(self):
        codec = TransportCodec("int8", min_leaf_size=4)
        a = np.random.RandomState(5).randn(32).astype(np.float32)
        b = (np.random.RandomState(6).randn(32) * 100).astype(np.float32)
        codec.encode({"params": a}, stream="w0>h0")
        codec.encode({"params": b}, stream="w0>h1")
        assert ("w0>h0", ".params") in codec._residual
        assert ("w0>h1", ".params") in codec._residual
        ra = codec._residual[("w0>h0", ".params")]
        assert np.max(np.abs(ra)) <= 2 * int8_quantization_step(a) + 1e-6


class TestPayloadWalking:
    def test_non_array_payloads_pass_through(self):
        codec = TransportCodec("int8")
        payload = {"violation": True, "curve": [(0.5, 10)], "fitted": 3}
        enc = codec.encode(payload, stream="s")
        assert enc["violation"] is True
        assert enc["fitted"] == 3
        assert list(enc["curve"]) == [(0.5, 10)]

    def test_small_and_int_leaves_stay_raw(self):
        codec = TransportCodec("int8", min_leaf_size=16)
        small = np.ones((4,), np.float32)
        ints = np.arange(64, dtype=np.int32)
        enc = codec.encode({"a": small, "b": ints}, stream="s")
        assert enc["a"] is small
        assert enc["b"] is ints

    def test_bare_array_payload(self):
        codec = TransportCodec("fp16", min_leaf_size=4)
        x = np.random.RandomState(7).randn(32).astype(np.float32)
        enc = codec.encode(x, stream="s")
        assert isinstance(enc, EncodedLeaf)
        dec = decode_payload(enc)
        assert dec.shape == x.shape and dec.dtype == np.float32

    def test_nested_structures_round_trip(self):
        codec = TransportCodec("int8", min_leaf_size=4)
        x = np.random.RandomState(8).randn(40).astype(np.float32)
        payload = {"params": x, "extra": {"clock": 3}, "pair": [x * 2, "tag"]}
        dec = decode_payload(codec.encode(payload, stream="s"))
        step = int8_quantization_step(x)
        assert np.max(np.abs(dec["params"] - x)) <= step + 1e-6
        assert dec["extra"]["clock"] == 3
        assert dec["pair"][1] == "tag"

    def test_stateless_decode_rejects_topk(self):
        codec = TransportCodec("topk", top_k=4, min_leaf_size=4)
        enc = codec.encode(np.ones((32,), np.float32), stream="s")
        with pytest.raises(ValueError, match="stateful"):
            decode_payload(enc)


class TestWireAccounting:
    def test_int8_wire_size(self):
        codec = TransportCodec("int8", min_leaf_size=4)
        x = np.zeros((257,), np.float32)
        enc = codec.encode({"params": x}, stream="s")
        leaf = enc["params"]
        assert leaf.nbytes == 257 + 8  # 1 B/element + scale/zero meta
        assert leaf.logical_nbytes == 257 * 4
        assert payload_size(enc) == 257 + 8
        assert payload_size({"params": x}) == 257 * 4

    def test_fp16_wire_size(self):
        codec = TransportCodec("fp16", min_leaf_size=4)
        enc = codec.encode(np.zeros((100,), np.float32), stream="s")
        assert enc.nbytes == 200
        assert payload_size(enc) == 200

    def test_topk_wire_size(self):
        codec = TransportCodec("topk", top_k=16, min_leaf_size=4)
        enc = codec.encode(np.ones((256,), np.float32), stream="s")
        assert enc.nbytes == 16 * 8  # int32 idx + float32 val per entry

    def test_int8_reduction_beats_3_5x_on_params_vector(self):
        codec = TransportCodec("int8", min_leaf_size=4)
        x = np.random.RandomState(9).randn(257).astype(np.float32)
        enc = codec.encode({"params": x}, stream="s")
        assert payload_size({"params": x}) / payload_size(enc) >= 3.5

    def test_instrumentation_counters(self):
        codec = TransportCodec("int8", min_leaf_size=4)
        x = np.zeros((64,), np.float32)
        codec.encode({"params": x}, stream="s")
        assert codec.leaves_encoded == 1
        assert codec.bytes_logical == 256
        assert codec.bytes_wire == 64 + 8
        assert codec.encode_seconds >= 0.0


class TestConfigPlumbing:
    def test_default_is_none(self):
        tc = TrainingConfiguration(protocol="Asynchronous")
        assert comm_codec_name(tc) == "none"
        assert make_transport_codec(tc) is None

    def test_comm_codec_selected(self):
        codec = make_transport_codec(tc_for("int8"))
        assert codec is not None and codec.kind == "int8"

    def test_flat_codec_key_accepted(self):
        tc = TrainingConfiguration(
            protocol="Asynchronous", extra={"codec": "fp16"}
        )
        assert comm_codec_name(tc) == "fp16"

    def test_topk_options(self):
        codec = make_transport_codec(tc_for("topk", topK=7, minLeafSize=2))
        assert codec.top_k == 7 and codec.min_leaf_size == 2

    def test_unknown_codec_rejected(self):
        with pytest.raises(ValueError, match="unknown comm codec"):
            comm_codec_name(tc_for("zstd"))

    def test_explicit_none_builds_nothing(self):
        assert make_transport_codec(tc_for("none")) is None


class TestInt8DegeneratePaths:
    """ISSUE 7 satellite: the int8 degenerate-scale path must be a clean
    passthrough for constant/zero leaves (no spurious error-feedback
    residual) and must FAIL LOUDLY on non-finite leaves instead of
    encoding garbage."""

    @pytest.mark.parametrize("value", [0.0, 3.25, -1e-30])
    def test_constant_leaf_round_trips_exactly(self, value):
        x = np.full((64,), value, np.float32)
        q, scale, zero = int8_affine_encode(x)
        dec = int8_affine_decode(q, scale, zero)
        np.testing.assert_array_equal(dec, x)  # bitwise, not approximate

    def test_constant_leaf_leaves_zero_residual(self):
        codec = TransportCodec("int8", min_leaf_size=4)
        x = np.full((64,), 7.5, np.float32)
        for _ in range(8):
            leaf = codec.encode({"params": x}, stream="w0>h0")["params"]
            dec = decode_payload({"params": leaf})["params"]
            np.testing.assert_array_equal(dec, x)
        resid = codec._residual[("w0>h0", ".params")]
        np.testing.assert_array_equal(resid, np.zeros_like(resid))

    def test_subnormal_span_stays_finite(self):
        # a span whose /255 underflows must take the passthrough branch,
        # not divide by zero
        x = np.full((32,), 1.0, np.float32)
        x[0] = 1.0 + 1e-45
        q, scale, zero = int8_affine_encode(x)
        dec = int8_affine_decode(q, scale, zero)
        assert np.isfinite(dec).all()

    @pytest.mark.parametrize("bad", [np.nan, np.inf, -np.inf])
    def test_non_finite_leaf_fails_loudly(self, bad):
        x = np.ones((64,), np.float32)
        x[13] = bad
        with pytest.raises(ValueError, match="non-finite"):
            int8_affine_encode(x)

    def test_non_finite_leaf_fails_loudly_through_codec(self):
        codec = TransportCodec("int8", min_leaf_size=4)
        x = np.ones((64,), np.float32)
        x[0] = np.nan
        with pytest.raises(ValueError, match="non-finite"):
            codec.encode({"params": x}, stream="w0>h0")
