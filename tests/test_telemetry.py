"""Telemetry plane (ISSUE 13): unified metrics registry, continuous
performance heartbeats on the ``performance`` sink, phase-attributed
hot-loop profiling, sampled round spans, and the heartbeat-frame channel
to the autoscaling supervisor.

Pins:

- spec parsing (unknown knobs drop at the gate, arms-nothing rejected,
  per-pipeline override wins over the job default);
- MetricsRegistry semantics (counters sum, gauges last-write vs
  max-combine, bounded-ring histograms with exact totals, probes read at
  snapshot, merge);
- heartbeat cadence: count-clocked (``statsEvery`` records) and therefore
  DETERMINISTIC under replay — same stream, same beat schedule — with the
  packed route ticking row counts; payload schema (kind/seq/extras); the
  wall-clock idle tick; the terminate-time final report BIT-IDENTICAL to
  the pre-telemetry schema (no ``kind`` key, same statistics);
- unarmed = zero telemetry objects and bitwise-identical predictions /
  scores / stats vs an armed run (the plane only ever ADDS performance
  entries), including under the cohort x serving composition;
- sampled spans: 1/N cadence, one outstanding per stream, JSONL records
  keyed by the transport (networkId, seq) stamps;
- codec seconds + launch percentiles surfaced in Statistics.to_dict;
- the overload ladder's serve-p99 signal available once telemetry is
  armed, without the separate p99HighMs measurement knob;
- worker heartbeat frames: rich ``<epoch> <level> k=v`` bodies parse,
  legacy two-token and torn frames degrade (never crash), the supervisor
  folds fleet signals, and an armed AutoscalePolicy threshold flips a
  scale decision that the backlog-derived level alone would not.
"""

import json
import os

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from omldm_tpu.api.requests import TrainingConfiguration
from omldm_tpu.config import JobConfig
from omldm_tpu.runtime.job import (
    FORECASTING_STREAM,
    REQUEST_STREAM,
    TRAINING_STREAM,
    StreamJob,
)
from omldm_tpu.runtime.supervisor import (
    AutoscalePolicy,
    DistributedJobSupervisor,
)
from omldm_tpu.runtime.telemetry import (
    MetricsRegistry,
    PhaseProfile,
    SpanLog,
    TelemetryConfig,
    TelemetryPlane,
    parse_telemetry_spec,
    telemetry_config,
)

DIM = 6


def _create_line(nid=0, protocol="CentralizedTraining", tc_extra=None):
    tc = {"protocol": protocol, "syncEvery": 2}
    tc.update(tc_extra or {})
    return json.dumps({
        "id": nid,
        "request": "Create",
        "learner": {
            "name": "PA",
            "hyperParameters": {"C": 1.0},
            "dataStructure": {"nFeatures": DIM},
        },
        "trainingConfiguration": tc,
    })


def _stream(n, fore_every=5, seed=0):
    rng = np.random.RandomState(seed)
    w = np.random.RandomState(1).randn(DIM)
    events = []
    for i in range(n):
        x = np.round(rng.randn(DIM), 6)
        feats = [float(v) for v in x]
        if i % fore_every == 4:
            events.append(
                (FORECASTING_STREAM,
                 json.dumps({"numericalFeatures": feats}))
            )
        else:
            events.append(
                (TRAINING_STREAM,
                 json.dumps({
                     "numericalFeatures": feats,
                     "target": float(x @ w > 0),
                 }))
            )
    return events


def _run_job(telemetry="", n=200, protocol="CentralizedTraining",
             parallelism=1, creates=(0,), tc_extra=None, **cfg_kw):
    job = StreamJob(JobConfig(
        parallelism=parallelism, batch_size=16, test_set_size=16,
        telemetry=telemetry, **cfg_kw,
    ))
    for nid in creates:
        job.process_event(
            REQUEST_STREAM, _create_line(nid, protocol, tc_extra)
        )
    for stream, line in _stream(n):
        job.process_event(stream, line)
    report = job.terminate()
    return job, report


# --- spec parsing ------------------------------------------------------------


class TestSpecParsing:
    def test_unset_unarmed(self):
        assert parse_telemetry_spec("") is None
        assert parse_telemetry_spec(None) is None
        assert parse_telemetry_spec(False) is None

    def test_on_defaults(self):
        cfg = parse_telemetry_spec("on")
        assert cfg.stats_every == 10_000
        assert cfg.trace_sample == 0

    def test_kv_spec(self):
        cfg = parse_telemetry_spec(
            "statsEvery=64,idleMs=500,traceSample=8,spanPath=/tmp/s.jsonl"
        )
        assert (cfg.stats_every, cfg.idle_ms, cfg.trace_sample) == (
            64, 500.0, 8
        )
        assert cfg.span_path == "/tmp/s.jsonl"

    def test_unknown_knob_raises(self):
        with pytest.raises(ValueError, match="unknown telemetry"):
            parse_telemetry_spec("statEvery=64")

    def test_arms_nothing_rejected(self):
        with pytest.raises(ValueError, match="arms nothing"):
            parse_telemetry_spec("statsEvery=0,idleMs=0,traceSample=0")

    def test_pipeline_override_wins(self):
        tc = TrainingConfiguration(
            protocol="Synchronous", extra={"telemetry": False}
        )
        assert telemetry_config(tc, "statsEvery=64") is None
        tc2 = TrainingConfiguration(
            protocol="Synchronous", extra={"telemetry": "statsEvery=32"}
        )
        assert telemetry_config(tc2, "").stats_every == 32

    def test_gate_drops_bad_table(self):
        job = StreamJob(JobConfig(parallelism=1))
        job.process_event(REQUEST_STREAM, _create_line(
            0, tc_extra={"telemetry": "bogusKnob=1"}
        ))
        assert 0 not in job.pipeline_manager.node_map
        assert job.dead_letter.entries[-1]["reason"] == "rejected_request"

    def test_bad_job_spec_fails_fast(self):
        with pytest.raises(ValueError):
            StreamJob(JobConfig(telemetry="nope=1"))


# --- registry ----------------------------------------------------------------


class TestMetricsRegistry:
    def test_counters_sum(self):
        r = MetricsRegistry()
        r.counter("a")
        r.counter("a", 4)
        assert r.snapshot()["counters"]["a"] == 5

    def test_gauge_last_write_vs_max(self):
        r = MetricsRegistry()
        r.gauge("v", 3)
        r.gauge("v", 1)
        r.gauge_max("peak", 3)
        r.gauge_max("peak", 1)
        snap = r.snapshot()["gauges"]
        assert snap["v"] == 1 and snap["peak"] == 3

    def test_histogram_exact_totals_windowed_percentiles(self):
        r = MetricsRegistry()
        for v in range(100):
            r.observe("lat", float(v))
        h = r.snapshot()["histograms"]["lat"]
        assert h["count"] == 100
        assert h["total"] == pytest.approx(sum(range(100)))
        assert h["p50"] == pytest.approx(49.5)

    def test_probe_read_at_snapshot(self):
        r = MetricsRegistry()
        state = {"v": 1.0}
        r.probe("live", lambda: state["v"])
        assert r.snapshot()["gauges"]["live"] == 1.0
        state["v"] = 7.0
        assert r.snapshot()["gauges"]["live"] == 7.0
        r.probe("dead", lambda: 1 / 0)
        assert "dead" not in r.snapshot()["gauges"]  # degrade, not crash

    def test_merge(self):
        a, b = MetricsRegistry(), MetricsRegistry()
        a.counter("n", 2)
        b.counter("n", 3)
        a.gauge_max("peak", 1)
        b.gauge_max("peak", 5)
        b.observe("lat", 2.0)
        a.merge(b)
        snap = a.snapshot()
        assert snap["counters"]["n"] == 5
        assert snap["gauges"]["peak"] == 5
        assert snap["histograms"]["lat"]["count"] == 1


class TestPhaseProfile:
    def test_table_shares_and_coverage(self):
        p = PhaseProfile()
        p.note("parse", 0.25)
        p.note("stage", 0.25)
        table = p.table(1.0, extra={"fit": 0.4})
        assert table["parse"]["share"] == pytest.approx(0.25)
        assert table["fit"]["seconds"] == pytest.approx(0.4)
        assert table["_coverage"] == pytest.approx(0.9)

    def test_ctx_manager_accumulates(self):
        p = PhaseProfile()
        with p.phase("fit"):
            pass
        with p.phase("fit"):
            pass
        assert p.table()["fit"]["count"] == 2
        assert p.seconds("fit") >= 0.0


class TestSpanLog:
    def test_sampling_and_one_outstanding(self):
        log = SpanLog(sample=2)
        log.maybe_open(0, 0, 0, "push", 0)   # sampled (send 0)
        log.maybe_open(0, 0, 0, "push", 1)   # not sampled (send 1)
        log.maybe_open(0, 0, 0, "push", 2)   # sampled but outstanding
        assert log.opened == 1
        log.maybe_close(0, 0, 0, "release")
        assert log.completed == 1
        [span] = log.spans
        assert span["seq"] == 0 and span["rttMs"] >= 0.0
        log.maybe_close(0, 0, 0, "release")  # nothing outstanding: no-op
        assert log.completed == 1

    def test_jsonl_file(self, tmp_path):
        path = str(tmp_path / "spans.jsonl")
        log = SpanLog(sample=1, path=path)
        log.maybe_open(3, 0, 1, "push", 17)
        log.maybe_close(3, 0, 1, "release")
        log.close()
        [line] = open(path).read().splitlines()
        span = json.loads(line)
        assert span["networkId"] == 3 and span["seq"] == 17
        assert span["workerId"] == 1 and span["op"] == "push"


# --- heartbeats --------------------------------------------------------------


class TestHeartbeatCadence:
    def test_count_clocked_deterministic(self):
        runs = []
        for _ in range(2):
            job, report = _run_job(telemetry="statsEvery=64", n=200)
            beats = [p for p in job.performance if p.kind == "heartbeat"]
            runs.append([
                (p.seq, p.extra["eventsProcessed"]) for p in beats
            ])
            # 201 events (1 create + 200 records) / 64 -> 3 beats
            assert len(beats) == 3
            assert report is job.performance[-1]
            assert report.kind is None
        assert runs[0] == runs[1]  # replay => identical schedule

    def test_packed_route_ticks_rows(self):
        job = StreamJob(JobConfig(
            parallelism=1, batch_size=16, test_set_size=16,
            telemetry="statsEvery=100",
        ))
        job.process_event(REQUEST_STREAM, _create_line(0))
        rng = np.random.RandomState(0)
        x = rng.randn(350, DIM).astype(np.float32)
        y = (x[:, 0] > 0).astype(np.float32)
        op = np.zeros((350,), np.uint8)
        for i in range(0, 350, 50):
            job.process_packed_batch(x[i:i+50], y[i:i+50], op[i:i+50])
        # 1 create event + 350 rows = 351 ticks -> beats at 100/200/300
        assert job.telemetry.heartbeats_emitted == 3
        job.terminate()

    def test_heartbeat_payload_schema(self):
        job, _ = _run_job(telemetry="statsEvery=64", n=200)
        beat = next(p for p in job.performance if p.kind == "heartbeat")
        d = beat.to_dict()
        assert d["kind"] == "heartbeat" and d["seq"] == 1
        assert d["eventsProcessed"] >= 64
        assert "counters" in d["telemetry"]
        assert d["telemetry"]["counters"]["records"] >= 64
        assert "queues" in d and "phases" in d
        [row] = d["statistics"]
        assert row["pipeline"] == 0
        assert row["fitted"] > 0          # incremental, mid-stream
        assert row["programLaunches"] > 0
        assert row["score"] == 0.0        # heartbeats never run holdout

    def test_final_report_schema_unchanged(self):
        job, report = _run_job(telemetry="statsEvery=64", n=200)
        d = report.to_dict()
        assert "kind" not in d and "seq" not in d
        assert set(d) == {
            "jobName", "parallelism", "durationMs", "statistics"
        }

    def test_idle_tick(self):
        wall = {"t": 1000.0}
        plane = TelemetryPlane(
            TelemetryConfig(stats_every=1000, idle_ms=500),
            wall=lambda: wall["t"],
        )
        assert not plane.idle_due()          # nothing pending
        plane.note_records(3)
        assert not plane.idle_due()          # first pending record arms it
        wall["t"] += 0.4
        assert not plane.idle_due()
        wall["t"] += 0.2
        assert plane.idle_due()              # 600 ms of pending silence
        plane.mark_beat()
        assert not plane.idle_due()          # clock reset, nothing pending

    def test_job_idle_tick_emits(self):
        job = StreamJob(JobConfig(
            parallelism=1, batch_size=16, test_set_size=16,
            telemetry="statsEvery=100000,idleMs=1",
            timeout_ms=10_000_000,
        ))
        job.process_event(REQUEST_STREAM, _create_line(0))
        for stream, line in _stream(20):
            job.process_event(stream, line)
        assert job.telemetry.heartbeats_emitted == 0
        job.check_silence()   # arms the idle clock at first pending check
        import time as _time

        _time.sleep(0.01)
        job.check_silence()
        assert job.telemetry.heartbeats_emitted == 1


# --- unarmed identity --------------------------------------------------------


class TestUnarmedIdentity:
    def test_unarmed_no_objects(self):
        job, _ = _run_job(telemetry="", n=50)
        assert job.telemetry is None
        for spoke in job.spokes:
            assert spoke.telemetry is None and spoke._phases is None

    # the serving legs pin maxDelayMs far out: the wall-clock deadline
    # makes flush positions (and with par-2 hub rounds, values) load-
    # dependent on BOTH legs — pre-existing behavior (an unarmed pair
    # diverges under CPU load the same way), not what this pin is about.
    # Fill- and fence-triggered flushes are count-clocked = deterministic.
    # The third leg is the full composition matrix of the acceptance bar:
    # cohort x codec int8 x guard x serving exact x overload x lifecycle.
    @pytest.mark.parametrize("compose,tc_extra", [
        ({}, None),
        ({"cohort": "on", "cohort_min": 2,
          "serving": "maxBatch=8,maxDelayMs=1000000"}, None),
        ({"cohort": "on", "cohort_min": 2,
          "serving": "maxBatch=8,maxDelayMs=1000000",
          "overload": "window=64", "lifecycle": "on"},
         {"comm": {"codec": "int8"}, "guard": True}),
    ])
    def test_armed_bitwise_identical(self, compose, tc_extra):
        creates = (0, 1) if compose else (0,)
        base_job, base = _run_job(
            telemetry="", n=240, protocol="Synchronous", parallelism=2,
            creates=creates, tc_extra=tc_extra, **compose,
        )
        tel_job, tel = _run_job(
            telemetry="statsEvery=64,traceSample=4", n=240,
            protocol="Synchronous", parallelism=2, creates=creates,
            tc_extra=tc_extra, **compose,
        )
        assert [p.value for p in base_job.predictions] == [
            p.value for p in tel_job.predictions
        ]
        assert [p.mlp_id for p in base_job.predictions] == [
            p.mlp_id for p in tel_job.predictions
        ]
        for sb, st in zip(base.statistics, tel.statistics):
            assert sb.score == st.score
            assert sb.fitted == st.fitted
            assert sb.models_shipped == st.models_shipped
            assert sb.bytes_on_wire == st.bytes_on_wire
        # the armed run ADDED heartbeats, nothing else
        assert len(tel_job.performance) > len(base_job.performance)


# --- spans in the job --------------------------------------------------------


class TestSpansInJob:
    def test_protocol_rounds_traced(self, tmp_path):
        path = str(tmp_path / "spans.jsonl")
        job, _ = _run_job(
            telemetry=f"statsEvery=100000,traceSample=1,spanPath={path}",
            n=200, protocol="Synchronous", parallelism=2,
        )
        spans = job.telemetry.spans
        assert spans.opened > 0 and spans.completed > 0
        lines = [json.loads(l) for l in open(path).read().splitlines()]
        assert len(lines) == spans.completed
        for span in lines[:5]:
            assert span["networkId"] == 0
            assert span["rttMs"] >= 0.0
            assert span["op"]

    def test_pipeline_opt_out_excluded(self):
        job = StreamJob(JobConfig(
            parallelism=2, batch_size=16, test_set_size=16,
            telemetry="statsEvery=100000,traceSample=1",
        ))
        job.process_event(REQUEST_STREAM, _create_line(
            0, "Synchronous", tc_extra={"telemetry": False}
        ))
        for stream, line in _stream(100):
            job.process_event(stream, line)
        job.terminate()
        assert job.telemetry.spans.opened == 0


# --- codec seconds + launch percentiles in Statistics ------------------------


class TestStatisticsSurfacing:
    def test_codec_seconds_and_launch_gauges(self):
        # codec seconds fold unconditionally (they only engage when a
        # codec is armed); the wall-clock LAUNCH gauges fold only with
        # telemetry armed, keeping unarmed reports reproducible
        job, report = _run_job(
            telemetry="statsEvery=100000",
            n=240, protocol="Synchronous", parallelism=2,
            tc_extra={"comm": {"codec": "int8"}},
        )
        [stats] = report.statistics
        assert stats.codec_encode_seconds > 0.0
        assert stats.codec_decode_seconds > 0.0
        assert stats.launch_p99_ms > 0.0
        assert stats.launch_p99_ms >= stats.launch_p50_ms
        d = stats.to_dict()
        assert d["codecEncodeSeconds"] == stats.codec_encode_seconds
        assert d["launchP50Ms"] == stats.launch_p50_ms
        assert d["serveLaunchP99Ms"] >= d["serveLaunchP50Ms"]

    def test_serve_launch_gauge_engages_on_forecasts(self):
        job, report = _run_job(telemetry="statsEvery=100000", n=200)
        [stats] = report.statistics
        assert stats.forecasts_served > 0
        assert stats.serve_launch_p99_ms > 0.0

    def test_launch_gauges_stay_zero_unarmed(self):
        # wall-clock gauges must not make unarmed reports irreproducible
        _, report = _run_job(telemetry="", n=200)
        [stats] = report.statistics
        assert stats.launch_p50_ms == 0.0
        assert stats.serve_launch_p99_ms == 0.0

    def test_query_terminate_never_double_counts(self):
        # a Query folds the codec delta; terminate must fold only the
        # remainder — total <= live codec clock on every node
        job = StreamJob(JobConfig(
            parallelism=2, batch_size=16, test_set_size=16,
        ))
        job.process_event(REQUEST_STREAM, _create_line(
            0, "Synchronous", tc_extra={"comm": {"codec": "int8"}}
        ))
        events = _stream(240)
        for stream, line in events[:120]:
            job.process_event(stream, line)
        job.process_event(REQUEST_STREAM, json.dumps(
            {"id": 0, "request": "Query", "requestId": 7}
        ))
        for stream, line in events[120:]:
            job.process_event(stream, line)
        report = job.terminate()
        [stats] = report.statistics
        live_enc, live_dec = job.codec_seconds()
        assert 0.0 < stats.codec_encode_seconds <= live_enc + 1e-9
        assert 0.0 < stats.codec_decode_seconds <= live_dec + 1e-9


# --- phase attribution -------------------------------------------------------


class TestPhaseAttribution:
    def test_job_phase_table_covers_packed_run(self):
        job = StreamJob(JobConfig(
            parallelism=1, batch_size=64, test_set_size=32,
            telemetry="statsEvery=100000",
        ))
        job.process_event(REQUEST_STREAM, _create_line(0))
        rng = np.random.RandomState(0)
        x = rng.randn(4096, DIM).astype(np.float32)
        y = (x[:, 0] > 0).astype(np.float32)
        op = np.zeros((4096,), np.uint8)
        import time as _time

        t0 = _time.perf_counter()
        for i in range(0, 4096, 512):
            job.process_packed_batch(x[i:i+512], y[i:i+512], op[i:i+512])
        e2e = _time.perf_counter() - t0
        table = job.phase_table(e2e)
        assert table["stage"]["seconds"] > 0.0
        assert table["holdout"]["seconds"] > 0.0
        assert table["fit"]["seconds"] > 0.0
        assert 0.0 < table["_coverage"] <= 1.05  # attributed, no nesting
        job.terminate()

    def test_overload_p99_signal_via_telemetry(self):
        # arming telemetry makes the ladder's latency signal available
        # without the separate p99HighMs measurement knob
        job_t, _ = _run_job(
            telemetry="statsEvery=100000", n=60,
            tc_extra={"overload": "window=16"},
        )
        [spoke] = job_t.spokes
        assert "p99_ms" in spoke.overload.signals()
        job_u, _ = _run_job(
            telemetry="", n=60, tc_extra={"overload": "window=16"},
        )
        [spoke_u] = job_u.spokes
        assert "p99_ms" not in spoke_u.overload.signals()


# --- heartbeat frames + supervisor fold --------------------------------------


class TestHeartbeatFrames:
    def _sup(self, tmp_path, **kw):
        kw.setdefault("autoscale", AutoscalePolicy(
            min_processes=1, max_processes=8, up_after_s=1.0,
            down_after_s=2.0, cooldown_s=0.5,
        ))
        return DistributedJobSupervisor(
            ["--checkpointDir", str(tmp_path / "ck")], 2,
            run_dir=str(tmp_path / "run"), **kw,
        )

    def _write_beat(self, sup, pid, body):
        os.makedirs(sup.hb_dir, exist_ok=True)
        with open(os.path.join(sup.hb_dir, f"proc{pid}.hb"), "w") as f:
            f.write(body)

    def test_rich_frame_parses(self, tmp_path):
        sup = self._sup(tmp_path)
        self._write_beat(
            sup, 0, "123.0 1 serveP99=42.5 imbalance=7.25 backlog=900"
        )
        frame = sup._beat_frame(0)
        assert frame == {
            "level": 1.0, "serveP99": 42.5, "imbalance": 7.25,
            "backlog": 900.0, "events": 0.0, "alerts": 0.0,
        }
        assert sup._beat_level(0) == 1
        # flight-recorder fields (ISSUE 14): events high-water + alert
        # count parse from the same kv tail
        self._write_beat(
            sup, 0, "123.0 1 serveP99=1 events=37 alerts=2"
        )
        frame = sup._beat_frame(0)
        assert frame["events"] == 37.0 and frame["alerts"] == 2.0

    def test_legacy_and_torn_frames_degrade(self, tmp_path):
        sup = self._sup(tmp_path)
        self._write_beat(sup, 0, "123.0 2")        # legacy two-token
        assert sup._beat_frame(0)["level"] == 2.0
        assert sup._beat_frame(0)["serveP99"] == 0.0
        self._write_beat(sup, 0, "123.0")          # bare epoch
        assert sup._beat_frame(0)["level"] == 0.0
        self._write_beat(sup, 0, "123.0 garb=")    # torn level token
        assert sup._beat_frame(0)["level"] == 0.0
        self._write_beat(
            sup, 0, "123.0 1 serveP99=4x2 backlog=10"
        )                                          # one torn kv token
        frame = sup._beat_frame(0)
        assert frame["serveP99"] == 0.0 and frame["backlog"] == 10.0
        assert sup._beat_frame(1) is None          # never beat

    def test_fleet_signals_fold(self, tmp_path):
        sup = self._sup(tmp_path)
        assert sup.fleet_signals() is None
        self._write_beat(
            sup, 0, "123.0 0 serveP99=10 imbalance=1 backlog=5"
        )
        self._write_beat(
            sup, 1, "123.0 1 serveP99=80 imbalance=0.5 backlog=7"
        )
        sig = sup.fleet_signals()
        assert sig == {
            "level": 1.0, "serveP99": 80.0, "imbalance": 1.0,
            "backlog": 12.0,
        }

    def test_streamjob_frame_keys(self):
        job, _ = _run_job(n=60)
        frame = job.heartbeat_frame()
        assert set(frame) == {
            "level", "serveP99", "imbalance", "backlog", "events", "alerts",
        }
        assert frame["level"] == 0 and frame["serveP99"] >= 0.0
        # flight recorder unarmed: the fields ride at zero
        assert frame["events"] == 0 and frame["alerts"] == 0

    def test_distributed_frame_rides_file(self, tmp_path):
        from omldm_tpu.runtime.distributed_job import _heartbeat

        flags = {"heartbeatDir": str(tmp_path)}
        _heartbeat(flags, 0, {
            "level": 2, "serveP99": 12.5, "imbalance": 0.0, "backlog": 44,
        })
        body = open(tmp_path / "proc0.hb").read().split()
        assert body[1] == "2"
        assert "serveP99=12.5" in body and "backlog=44" in body
        _heartbeat(flags, 1, 1)  # legacy int frame still writes
        assert open(tmp_path / "proc1.hb").read().split()[1] == "1"


class TestAutoscaleHostSignal:
    """The acceptance pin: a host-plane signal (serve p99) carried in
    heartbeat frames reaches AutoscalePolicy and flips a scale decision
    the staging-backlog level alone would NOT have made."""

    def _policy(self, **kw):
        kw.setdefault("min_processes", 1)
        kw.setdefault("max_processes", 8)
        kw.setdefault("up_after_s", 1.0)
        kw.setdefault("down_after_s", 60.0)
        kw.setdefault("cooldown_s", 0.1)
        return AutoscalePolicy(**kw)

    def test_p99_threshold_flips_decision(self):
        hot = {"serveP99": 120.0, "imbalance": 0.0, "backlog": 0.0}
        # backlog-only policy: level 0 (OK) holds forever
        p_base = self._policy()
        assert p_base.decide(2, 0, 0.0, signals=hot) is None
        assert p_base.decide(2, 0, 2.0, signals=hot) is None
        # p99-armed policy: the SAME frames read CRITICAL and scale out
        p_sig = self._policy(serve_p99_critical_ms=100.0)
        assert p_sig.decide(2, 0, 0.0, signals=hot) is None  # streak starts
        assert p_sig.decide(2, 0, 1.5, signals=hot) == 4

    def test_imbalance_threshold_flips_decision(self):
        hot = {"serveP99": 0.0, "imbalance": 300.0, "backlog": 0.0}
        p = self._policy(imbalance_critical=256.0)
        assert p.decide(2, 0, 0.0, signals=hot) is None
        assert p.decide(2, 0, 1.5, signals=hot) == 4
        calm = {"serveP99": 0.0, "imbalance": 10.0, "backlog": 0.0}
        p2 = self._policy(imbalance_critical=256.0)
        assert p2.decide(2, 0, 0.0, signals=calm) is None
        assert p2.decide(2, 0, 1.5, signals=calm) is None

    def test_supervisor_folds_frames_into_decision(self, tmp_path):
        policy = self._policy(serve_p99_critical_ms=100.0)
        sup = DistributedJobSupervisor(
            ["--checkpointDir", str(tmp_path / "ck")], 2,
            run_dir=str(tmp_path / "run"), autoscale=policy,
        )
        os.makedirs(sup.hb_dir, exist_ok=True)
        for pid in (0, 1):
            with open(os.path.join(sup.hb_dir, f"proc{pid}.hb"), "w") as f:
                f.write("123.0 0 serveP99=150 imbalance=0 backlog=0")
        level = sup.fleet_pressure()
        signals = sup.fleet_signals()
        assert level == 0                      # backlog alone says calm
        assert policy.effective_level(level, signals) == 2
        assert policy.decide(2, level, 0.0, signals=signals) is None
        assert policy.decide(2, level, 1.5, signals=signals) == 4

    def test_unknown_stays_unknown(self):
        p = self._policy(serve_p99_critical_ms=100.0)
        assert p.effective_level(-1, None) == -1
        assert p.decide(2, -1, 0.0, signals=None) is None
