"""Multi-process streaming deployment (DistributedStreamJob).

Spawns REAL separate Python processes joined via jax.distributed (CPU
backend + Gloo collectives): process 0 owns the control plane and
broadcasts the Create over the fabric; each process trains its strided
partition of the stream; statistics merge collectively. Score must agree
with the same job run single-process.
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _write_stream(path, n=3000, dim=12, seed=0, forecast_every=0):
    rng = np.random.RandomState(seed)
    w = rng.randn(dim)
    n_fore = 0
    with open(path, "w") as f:
        for i in range(n):
            x = np.round(rng.randn(dim), 6)
            # forecast slots at index 0 of each cycle: EVEN stream
            # indices whenever forecast_every is even (partition-targeted
            # imbalance for the SSP test)
            if forecast_every and i % forecast_every == 0:
                n_fore += 1
                f.write(
                    json.dumps(
                        {
                            "numericalFeatures": [float(v) for v in x],
                            "operation": "forecasting",
                        }
                    )
                    + "\n"
                )
                continue
            f.write(
                json.dumps(
                    {
                        "numericalFeatures": [float(v) for v in x],
                        "target": float(x @ w > 0),
                        "operation": "training",
                    }
                )
                + "\n"
            )
    return n_fore


CREATE = {
    "id": 0,
    "request": "Create",
    "learner": {
        "name": "PA",
        "hyperParameters": {"C": 1.0},
        "dataStructure": {"nFeatures": 12},
    },
    "preProcessors": [],
    "trainingConfiguration": {"protocol": "Synchronous", "syncEvery": 1},
}


def _run_procs(tmp_path, nproc, train, reqs, timeout=300):
    """Launch nproc real processes; returns (merged report, predictions)."""
    port = _free_port()
    procs = []
    outs = []
    pred_files = []
    for pid in range(nproc):
        perf = tmp_path / f"perf_{nproc}_{pid}.jsonl"
        preds = tmp_path / f"preds_{nproc}_{pid}.jsonl"
        outs.append(perf)
        pred_files.append(preds)
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)  # one CPU device per process
        env["JAX_PLATFORMS"] = "cpu"
        args = [
            sys.executable, "-m", "omldm_tpu.runtime.distributed_job",
            "--requests", str(reqs),
            "--trainingData", str(train),
            "--performanceOut", str(perf),
            "--predictionsOut", str(preds),
            "--batchSize", "64",
            "--testSetSize", "32",
        ]
        if nproc > 1:
            args += [
                "--coordinator", f"127.0.0.1:{port}",
                "--processes", str(nproc),
                "--processId", str(pid),
            ]
        procs.append(
            subprocess.Popen(
                args, cwd=REPO, env=env,
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            )
        )
    for p in procs:
        out, err = p.communicate(timeout=timeout)
        assert p.returncode == 0, f"proc failed:\n{out}\n{err[-3000:]}"
    report_path = outs[0]
    [line] = report_path.read_text().strip().splitlines()
    preds = []
    for pf in pred_files:
        if pf.exists():
            preds.extend(
                json.loads(l) for l in pf.read_text().strip().splitlines()
            )
    return json.loads(line), preds


@pytest.mark.slow
class TestDistributedStreamJob:
    def test_two_processes_match_single(self, tmp_path):
        train = tmp_path / "train.jsonl"
        reqs = tmp_path / "reqs.jsonl"
        _write_stream(str(train))
        reqs.write_text(json.dumps(CREATE) + "\n")

        single, _ = _run_procs(tmp_path, 1, train, reqs)
        double, _ = _run_procs(tmp_path, 2, train, reqs)

        # every row lands somewhere: fitted + holdout-resident == total
        assert single["fitted"] + single["holdout"] == 3000
        assert double["fitted"] + double["holdout"] == 3000
        assert double["processes"] == 2
        assert double["parallelism"] == 2  # one device per process
        # the learned model separates the stream on BOTH deployments, and
        # the scores agree (staging order differs slightly between the
        # partitionings, so parity is close, not bit-equal)
        assert single["score"] > 0.85
        assert double["score"] > 0.85
        assert abs(single["score"] - double["score"]) < 0.05
        # protocol traffic happened on the distributed run
        assert double["syncCount"] > 0
        assert double["bytesShipped"] > 0

    def test_ssp_two_processes_conserves_rows(self, tmp_path):
        """SSP across processes with DELIBERATELY imbalanced partitions
        (forecasts land only in process 0's stride, starving its worker):
        the staleness bound refuses the fast worker's batches, every
        refused row is requeued (never dropped), and the fitted count
        stays conserved."""
        train = tmp_path / "train.jsonl"
        reqs = tmp_path / "reqs.jsonl"
        # forecast rows at EVEN stream indices -> all in process 0's
        # partition (strided i % 2); its training rows lag process 1's
        n_fore = _write_stream(str(train), n=2400, forecast_every=4)
        assert n_fore > 0
        create = json.loads(json.dumps(CREATE))
        create["trainingConfiguration"] = {
            "protocol": "SSP", "syncEvery": 1, "staleness": 1,
        }
        reqs.write_text(json.dumps(create) + "\n")
        report, preds = _run_procs(tmp_path, 2, train, reqs)
        assert report["fitted"] + report["holdout"] == 2400 - n_fore
        assert len(preds) == n_fore
        assert report["syncCount"] > 0

    def test_forecasts_served_across_processes(self, tmp_path):
        """Forecast rows in any partition produce predictions (served
        collectively — the model is sharded across processes)."""
        train = tmp_path / "train.jsonl"
        reqs = tmp_path / "reqs.jsonl"
        n_fore = _write_stream(str(train), n=1500, forecast_every=100)
        assert n_fore > 0
        reqs.write_text(json.dumps(CREATE) + "\n")
        report, preds = _run_procs(tmp_path, 2, train, reqs)
        assert len(preds) == n_fore
        assert all(np.isfinite(p["value"]) for p in preds)
        assert report["fitted"] + report["holdout"] == 1500 - n_fore
