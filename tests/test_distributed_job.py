"""Multi-process streaming deployment (DistributedStreamJob).

Spawns REAL separate Python processes joined via jax.distributed (CPU
backend + Gloo collectives): process 0 owns the control plane and
broadcasts requests over the fabric; each process trains its partition of
the stream; statistics merge collectively into the JobStatistics schema.
Covers the full control-plane vocabulary in the cluster shape (multiple
pipelines, Query answered collectively, Delete honored, invalid requests
logged — PipelineMap.scala:37-57 semantics), distributed checkpoint/resume
(FlinkSpoke.scala:233-334), and partitioned Kafka ingest against a
file-backed broker fake (KafkaUtils.scala:11-31, README.md:21-26).
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TESTS = os.path.join(REPO, "tests")

# bootstrap that installs the file-backed kafka fake before production code
# imports `kafka` (real subprocesses cannot share an in-process fake)
FSKAFKA_BOOT = (
    "import sys; sys.path.insert(0, {tests!r}); "
    "import fskafka; fskafka.install(); "
    "from omldm_tpu.runtime.distributed_job import run_distributed; "
    "sys.exit(run_distributed(sys.argv[1:]))"
).format(tests=TESTS)


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _rows(n, dim, seed=0, forecast_every=0):
    """(record JSON lines, number of forecast rows)."""
    rng = np.random.RandomState(seed)
    w = rng.randn(dim)
    lines, n_fore = [], 0
    for i in range(n):
        x = np.round(rng.randn(dim), 6)
        if forecast_every and i % forecast_every == 0:
            n_fore += 1
            lines.append(json.dumps({
                "numericalFeatures": [float(v) for v in x],
                "operation": "forecasting",
            }))
        else:
            lines.append(json.dumps({
                "numericalFeatures": [float(v) for v in x],
                "target": float(x @ w > 0),
                "operation": "training",
            }))
    return lines, n_fore


def _write_stream(path, n=3000, dim=12, seed=0, forecast_every=0):
    lines, n_fore = _rows(n, dim, seed, forecast_every)
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")
    return n_fore


def _create(net_id=0, protocol="Synchronous", dim=12, **tc_extra):
    tc = {"protocol": protocol, "syncEvery": 1}
    tc.update(tc_extra)
    return json.dumps({
        "id": net_id,
        "request": "Create",
        "learner": {
            "name": "PA",
            "hyperParameters": {"C": 1.0},
            "dataStructure": {"nFeatures": dim},
        },
        "preProcessors": [],
        "trainingConfiguration": tc,
    })


def _stat(report, net_id):
    [s] = [s for s in report["statistics"] if s["pipeline"] == net_id]
    return s


def _launch(tmp_path, nproc, extra_flags, tag, boot=None, env_extra=None,
            expect_rc=0, timeout=420, file_sinks=True):
    """Run nproc processes of the distributed job; returns
    (report or None, predictions, joined stderr). ``file_sinks=False``
    omits the file outputs so Kafka-mode runs exercise the output-topic
    route (file sinks take precedence over the producer)."""
    port = _free_port()
    perf = tmp_path / f"perf_{tag}.jsonl"
    preds = tmp_path / f"preds_{tag}.jsonl"
    procs = []
    for pid in range(nproc):
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)  # one CPU device per process
        env["JAX_PLATFORMS"] = "cpu"
        env.update(env_extra or {})
        head = (
            [sys.executable, "-c", boot]
            if boot
            else [sys.executable, "-m", "omldm_tpu.runtime.distributed_job"]
        )
        sink_flags = (
            ["--performanceOut", str(perf), "--predictionsOut", str(preds)]
            if file_sinks else []
        )
        args = head + sink_flags + [
            "--batchSize", "64",
            "--testSetSize", "32",
        ] + extra_flags
        if nproc > 1:
            args += [
                "--coordinator", f"127.0.0.1:{port}",
                "--processes", str(nproc),
                "--processId", str(pid),
            ]
        procs.append(
            subprocess.Popen(
                args, cwd=REPO, env=env,
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            )
        )
    errs = []
    for p in procs:
        out, err = p.communicate(timeout=timeout)
        errs.append(err)
        assert p.returncode == expect_rc, (
            f"proc exited {p.returncode} (wanted {expect_rc}):\n{out}\n{err[-3000:]}"
        )
    report = None
    if perf.exists():
        [line] = perf.read_text().strip().splitlines()
        report = json.loads(line)
    predictions = []
    pred_paths = (
        [preds] if nproc == 1
        else [tmp_path / f"preds_{tag}.jsonl.p{i}" for i in range(nproc)]
    )
    for pf in pred_paths:
        if pf.exists() and pf.read_text().strip():
            predictions.extend(
                json.loads(l) for l in pf.read_text().strip().splitlines()
            )
    return report, predictions, "\n".join(errs)


@pytest.mark.slow
class TestDistributedStreamJob:
    def test_two_processes_match_single(self, tmp_path):
        train = tmp_path / "train.jsonl"
        reqs = tmp_path / "reqs.jsonl"
        _write_stream(str(train))
        reqs.write_text(_create() + "\n")
        flags = ["--requests", str(reqs), "--trainingData", str(train)]

        single, _, _ = _launch(tmp_path, 1, flags, "single")
        double, _, _ = _launch(tmp_path, 2, flags, "double")

        # the report is the reference's JobStatistics schema
        for rep in (single, double):
            assert set(rep) >= {
                "jobName", "parallelism", "durationMs", "statistics",
                "processes", "holdout",
            }
        s1, s2 = _stat(single, 0), _stat(double, 0)
        # every row lands somewhere: fitted + holdout-resident == total
        assert s1["fitted"] + single["holdout"]["0"] == 3000
        assert s2["fitted"] + double["holdout"]["0"] == 3000
        assert double["processes"] == 2
        assert double["parallelism"] == 2  # one device per process
        # the learned model separates the stream on BOTH deployments, and
        # the scores agree (staging order differs slightly between the
        # partitionings, so parity is close, not bit-equal)
        assert s1["score"] > 0.85
        assert s2["score"] > 0.85
        assert abs(s1["score"] - s2["score"]) < 0.05
        # protocol traffic happened and the learning curve was recorded
        assert s2["numOfBlocks"] > 0
        assert s2["bytesShipped"] > 0
        assert len(s2["learningCurve"]) > 0
        assert s2["LCX"] == sorted(s2["LCX"])
        assert s2["LCX"][-1] <= s2["fitted"]

    def test_ssp_two_processes_conserves_rows(self, tmp_path):
        """SSP across processes with DELIBERATELY imbalanced partitions
        (forecasts land only in process 0's stride, starving its worker):
        the staleness bound refuses the fast worker's batches, every
        refused row is requeued (never dropped), and the fitted count
        stays conserved."""
        train = tmp_path / "train.jsonl"
        reqs = tmp_path / "reqs.jsonl"
        # forecast rows at EVEN stream indices -> all in process 0's
        # partition (strided i % 2); its training rows lag process 1's
        n_fore = _write_stream(str(train), n=2400, forecast_every=4)
        assert n_fore > 0
        reqs.write_text(_create(protocol="SSP", staleness=1) + "\n")
        report, preds, _ = _launch(
            tmp_path, 2,
            ["--requests", str(reqs), "--trainingData", str(train)],
            "ssp",
        )
        s = _stat(report, 0)
        assert s["fitted"] + report["holdout"]["0"] == 2400 - n_fore
        assert len(preds) == n_fore
        assert s["numOfBlocks"] > 0

    def test_forecasts_served_across_processes(self, tmp_path):
        """Forecast rows in any partition produce predictions (served
        collectively — the model is sharded across processes), written to
        per-process output files (a shared path would be clobbered)."""
        train = tmp_path / "train.jsonl"
        reqs = tmp_path / "reqs.jsonl"
        n_fore = _write_stream(str(train), n=1500, forecast_every=100)
        assert n_fore > 0
        reqs.write_text(_create() + "\n")
        report, preds, _ = _launch(
            tmp_path, 2,
            ["--requests", str(reqs), "--trainingData", str(train)],
            "fore",
        )
        assert len(preds) == n_fore
        assert all(np.isfinite(p["value"]) for p in preds)
        s = _stat(report, 0)
        assert s["fitted"] + report["holdout"]["0"] == 1500 - n_fore

    def test_nn_preprocessor_gm_two_processes(self, tmp_path):
        """A deeper pipeline in the cluster shape: NN learner with a
        StandardScaler preprocessor under the GM (violation-gated)
        protocol — the collective eval/predict programs must thread the
        preprocessor state, and the drift-gated sync must fire across
        processes."""
        train = tmp_path / "train.jsonl"
        reqs = tmp_path / "reqs.jsonl"
        n_fore = _write_stream(str(train), n=2000, forecast_every=200)
        reqs.write_text(json.dumps({
            "id": 0,
            "request": "Create",
            "learner": {
                "name": "NN",
                "hyperParameters": {"learningRate": 5e-3},
                "dataStructure": {"nFeatures": 12, "hiddenLayers": [16]},
            },
            "preProcessors": [{"name": "StandardScaler"}],
            "trainingConfiguration": {
                "protocol": "GM", "syncEvery": 1, "threshold": 0.05,
            },
        }) + "\n")
        report, preds, _ = _launch(
            tmp_path, 2,
            ["--requests", str(reqs), "--trainingData", str(train)],
            "nn_gm",
        )
        s = _stat(report, 0)
        assert s["protocol"] == "GM"
        assert s["fitted"] + report["holdout"]["0"] == 2000 - n_fore
        assert len(preds) == n_fore
        assert all(np.isfinite(p["value"]) for p in preds)
        assert np.isfinite(s["score"])

    def test_multi_pipeline_query_delete(self, tmp_path):
        """The cluster deployment hosts the FULL control plane: two
        concurrent pipelines (SpokeLogic.scala:28-29), invalid requests
        logged and dropped (PipelineMap.scala:34,46), a Query answered
        collectively with bucketed parameters (FlinkNetwork.scala:196-231),
        and a Delete that removes its pipeline from the final report."""
        train = tmp_path / "train.jsonl"
        reqs = tmp_path / "reqs.jsonl"
        final = tmp_path / "final_reqs.jsonl"
        resp = tmp_path / "responses.jsonl"
        _write_stream(str(train), n=2000)
        bad_learner = json.dumps({
            "id": 5, "request": "Create",
            "learner": {"name": "NoSuchLearner",
                        "dataStructure": {"nFeatures": 12}},
            "trainingConfiguration": {"protocol": "Synchronous"},
        })
        sparse_create = json.dumps({
            "id": 6, "request": "Create",
            "learner": {"name": "PA",
                        "dataStructure": {"sparse": True, "nFeatures": 1024}},
            "trainingConfiguration": {"protocol": "Synchronous"},
        })
        reqs.write_text("\n".join([
            _create(0), _create(1, protocol="EASGD"),
            bad_learner, sparse_create,
        ]) + "\n")
        final.write_text("\n".join([
            json.dumps({"id": 0, "request": "Query", "requestId": 7}),
            json.dumps({"id": 1, "request": "Delete"}),
        ]) + "\n")
        report, _, err = _launch(
            tmp_path, 2,
            ["--requests", str(reqs), "--trainingData", str(train),
             "--requestsFinal", str(final), "--responsesOut", str(resp)],
            "ctrl",
        )
        # invalid learner + sparse Create were rejected WITH a reason
        assert "rejecting Create for pipeline 5" in err
        assert "rejecting pipeline 6" in err
        assert "sparse pipeline cannot share its parse route" in err
        # pipeline 1 trained, then was deleted: only pipeline 0 reports
        assert [s["pipeline"] for s in report["statistics"]] == [0]
        assert "pipeline 1 deleted" in err
        s0 = _stat(report, 0)
        assert s0["fitted"] + report["holdout"]["0"] == 2000
        assert s0["score"] > 0.8
        # the Query was answered collectively and merged on process 0
        [resp_line] = resp.read_text().strip().splitlines()
        q = json.loads(resp_line)
        assert q["responseId"] == 7
        assert q["mlpId"] == 0
        assert q["dataFitted"] == s0["fitted"]
        assert q["learner"]["name"] == "PA"
        params = q["learner"]["parameters"]["values"]
        assert len(params) >= 12 and np.isfinite(params).all()
        assert q["score"] is not None

    def test_checkpoint_resume_matches_unfaulted(self, tmp_path):
        """Kill both processes mid-stream (deterministic injected fault at
        the same chunk), relaunch with --restore: the resumed run must
        reproduce the unfaulted run's fitted/holdout counts and score —
        the distributed form of restore-from-checkpoint
        (FlinkSpoke.scala:233-334)."""
        train = tmp_path / "train.jsonl"
        reqs = tmp_path / "reqs.jsonl"
        ckpt = tmp_path / "ckpts"
        _write_stream(str(train), n=3000, forecast_every=50)
        reqs.write_text(_create() + "\n")
        base = [
            "--requests", str(reqs), "--trainingData", str(train),
            "--chunkRows", "256",
        ]
        clean, clean_preds, _ = _launch(tmp_path, 2, base, "clean")
        # faulted attempt: checkpoints at chunks 2 & 4, dies after chunk 5
        _launch(
            tmp_path, 2,
            base + ["--checkpointDir", str(ckpt), "--checkpointEvery", "2",
                    "--failAfterChunks", "5"],
            "faulted", expect_rc=3,
        )
        assert (ckpt / "LATEST").exists()
        resumed, res_preds, err = _launch(
            tmp_path, 2,
            base + ["--checkpointDir", str(ckpt), "--restore", "true"],
            "resumed",
        )
        assert "restored; resuming at row" in err
        sc, sr = _stat(clean, 0), _stat(resumed, 0)
        assert sr["fitted"] == sc["fitted"]
        assert resumed["holdout"]["0"] == clean["holdout"]["0"]
        # identical step sequence -> float-equal score
        assert abs(sr["score"] - sc["score"]) < 1e-6
        assert len(res_preds) == len(clean_preds)

    def test_kafka_partition_ingest(self, tmp_path):
        """Each process consumes an ASSIGNED set of Kafka partitions
        (partition index mod nproc — Flink's static per-subtask
        assignment) from a file-backed broker fake real processes share;
        the Create arrives on the requests topic; row counts conserve."""
        sys.path.insert(0, TESTS)
        import fskafka

        broker = tmp_path / "broker"
        os.environ["FSKAFKA_DIR"] = str(broker)
        try:
            lines, _ = _rows(2000, 12)
            for i, line in enumerate(lines):
                fskafka.append("trainingData", line, partition=i % 4)
            fskafka.append("requests", _create())
        finally:
            os.environ.pop("FSKAFKA_DIR", None)
        # NO file sinks: the outputs must ride the reference's output
        # topics (README.md:21-26; file sinks would take precedence)
        _, _, err = _launch(
            tmp_path, 2, ["--kafkaBrokers", "fs://local"],
            "kafka", boot=FSKAFKA_BOOT,
            env_extra={"FSKAFKA_DIR": str(broker)}, file_sinks=False,
        )
        perf_log = broker / "performance--0.log"
        assert perf_log.exists(), "report not published to the topic"
        report = json.loads(perf_log.read_text().strip().splitlines()[-1])
        s = _stat(report, 0)
        assert s["fitted"] + report["holdout"]["0"] == 2000
        assert s["score"] > 0.8

    def test_kafka_three_processes_two_topics(self, tmp_path):
        """Uneven partition counts across topics and processes: 5 train
        partitions + a single-partition forecast topic over 3 processes.
        The rotating stripe base must spread single-partition topics off
        process 0, and every partition of BOTH topics must be consumed
        (row conservation + forecasts served)."""
        sys.path.insert(0, TESTS)
        import fskafka

        broker = tmp_path / "broker"
        os.environ["FSKAFKA_DIR"] = str(broker)
        try:
            lines, _ = _rows(1500, 12, seed=5)
            for i, line in enumerate(lines):
                fskafka.append("trainingData", line, partition=i % 5)
            fore, n_fore = _rows(60, 12, seed=6, forecast_every=1)
            for line in fore:
                fskafka.append("forecastingData", line, partition=0)
            fskafka.append("requests", _create())
        finally:
            os.environ.pop("FSKAFKA_DIR", None)
        assert n_fore == 60
        report, preds, _ = _launch(
            tmp_path, 3, ["--kafkaBrokers", "fs://local"],
            "kafka3", boot=FSKAFKA_BOOT,
            env_extra={"FSKAFKA_DIR": str(broker)},
        )
        s = _stat(report, 0)
        assert s["fitted"] + report["holdout"]["0"] == 1500
        assert len(preds) == 60
        assert all(np.isfinite(p["value"]) for p in preds)

    def test_kafka_offset_resume(self, tmp_path):
        """Crash mid-consumption with per-partition offsets checkpointed;
        the resumed deployment seeks each assigned partition back to its
        snapshot offset — no row lost, none double-trained (conservation
        exact)."""
        sys.path.insert(0, TESTS)
        import fskafka

        broker = tmp_path / "broker"
        os.environ["FSKAFKA_DIR"] = str(broker)
        try:
            lines, _ = _rows(2000, 12, seed=3)
            for i, line in enumerate(lines):
                fskafka.append("trainingData", line, partition=i % 4)
            fskafka.append("requests", _create())
        finally:
            os.environ.pop("FSKAFKA_DIR", None)
        ckpt = tmp_path / "kafka_ckpts"
        base = ["--kafkaBrokers", "fs://local", "--chunkRows", "300",
                "--checkpointDir", str(ckpt)]
        _launch(
            tmp_path, 2, base + ["--checkpointEvery", "1",
                                 "--failAfterChunks", "2"],
            "kafka_fault", boot=FSKAFKA_BOOT,
            env_extra={"FSKAFKA_DIR": str(broker)}, expect_rc=3,
        )
        assert (ckpt / "LATEST").exists()
        report, _, err = _launch(
            tmp_path, 2, base + ["--restore", "true"],
            "kafka_resumed", boot=FSKAFKA_BOOT,
            env_extra={"FSKAFKA_DIR": str(broker)},
        )
        assert "restored; resuming at offsets" in err
        # request-topic offsets were checkpointed too: the restore must NOT
        # replay the Create (a replayed Update would wipe restored state)
        assert "already exists" not in err
        s = _stat(report, 0)
        assert s["fitted"] + report["holdout"]["0"] == 2000
        assert s["score"] > 0.8


@pytest.mark.slow
def test_unified_cli_single_process(tmp_path):
    """`python -m omldm_tpu --processes 1 ...` reaches the distributed
    deployment through the ONE entry point (Job.scala:110-120)."""
    train = tmp_path / "train.jsonl"
    reqs = tmp_path / "reqs.jsonl"
    perf = tmp_path / "perf.jsonl"
    _write_stream(str(train), n=600)
    reqs.write_text(_create() + "\n")
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    out = subprocess.run(
        [sys.executable, "-m", "omldm_tpu",
         "--processes", "1",
         "--requests", str(reqs), "--trainingData", str(train),
         "--performanceOut", str(perf),
         "--batchSize", "64", "--testSetSize", "32"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=300,
    )
    assert out.returncode == 0, out.stderr[-3000:]
    report = json.loads(perf.read_text().strip())
    assert report["processes"] == 1
    s = _stat(report, 0)
    assert s["fitted"] + report["holdout"]["0"] == 600
