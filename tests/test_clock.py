"""utils/clock.py — the one injectable-clock seam.

Pins (a) ManualClock semantics (monotone, advance/set/sleep, refuses
running backwards) and (b) that every wall-clock-coupled plane named by
the unification actually accepts a ManualClock and reads time from it:
SelfHealPolicy probe windows, HangWatchdog deadlines, the serving
maxDelayMs deadline, the flight recorder's silence poll, and the
supervisor's own clock pair. These are the seams the load harness
fast-forwards to test wall-clock SLOs without sleeping."""

import pytest

from omldm_tpu.utils import clock as uclock
from omldm_tpu.utils.clock import ManualClock


# --- ManualClock semantics ----------------------------------------------


def test_manual_clock_starts_at_start_and_is_callable():
    mc = ManualClock(start=100.0)
    assert mc() == 100.0
    assert mc.now == 100.0


def test_manual_clock_advance_returns_new_now():
    mc = ManualClock()
    assert mc.advance(2.5) == 2.5
    assert mc() == 2.5
    mc.advance(0.5)
    assert mc() == 3.0


def test_manual_clock_refuses_negative_advance():
    mc = ManualClock(start=10.0)
    with pytest.raises(ValueError):
        mc.advance(-1.0)
    assert mc() == 10.0


def test_manual_clock_set_jumps_forward_only():
    mc = ManualClock(start=5.0)
    assert mc.set(9.0) == 9.0
    with pytest.raises(ValueError):
        mc.set(8.0)
    assert mc() == 9.0


def test_manual_clock_sleep_advances_instead_of_blocking():
    mc = ManualClock()
    mc.sleep(4.0)
    assert mc() == 4.0


def test_resolve_defaults_and_passthrough():
    mc = ManualClock()
    assert uclock.resolve(None) is uclock.MONOTONIC
    assert uclock.resolve(None, uclock.WALL) is uclock.WALL
    assert uclock.resolve(mc, uclock.WALL) is mc


def test_named_clocks_tick():
    # the canonical system clocks return floats and do not go backwards
    for clk in (uclock.MONOTONIC, uclock.WALL, uclock.PERF):
        a, b = clk(), clk()
        assert isinstance(a, float)
        assert b >= a


# --- SelfHealPolicy probe windows ---------------------------------------


def test_selfheal_probe_window_on_manual_clock():
    from omldm_tpu.runtime.selfheal import SelfHealPolicy

    mc = ManualClock()
    pol = SelfHealPolicy(
        strike_threshold=1,
        configured=2,
        min_processes=1,
        probe_after_s=30.0,
        probe_window_s=10.0,
        clock=mc,
    )
    # one strike at threshold 1 degrades 2 -> 1
    assert pol.note_failure([1], nproc=2) == 1
    assert pol.degraded
    # quiet period shorter than probe_after_s: hold
    mc.advance(29.0)
    assert pol.probe_target(1) is None
    # past the window: probe back toward the configured width
    mc.advance(2.0)
    assert pol.probe_target(1) == 2


def test_selfheal_probe_heals_after_window_on_manual_clock():
    from omldm_tpu.runtime.selfheal import SelfHealPolicy

    mc = ManualClock()
    pol = SelfHealPolicy(
        strike_threshold=1,
        configured=2,
        probe_after_s=5.0,
        probe_window_s=10.0,
        clock=mc,
    )
    pol.note_failure([0], nproc=2)
    mc.advance(6.0)
    assert pol.probe_target(1) == 2
    pol.note_probe_signaled()
    pol.note_spawn()  # probe fleet up; window clock starts here
    mc.advance(9.0)
    assert not pol.tick_healthy()  # still inside the probe window
    mc.advance(2.0)
    assert pol.tick_healthy()  # survived the window: healed
    assert not pol.degraded


# --- HangWatchdog deadlines ---------------------------------------------


def test_hang_watchdog_deadline_on_manual_clock():
    from omldm_tpu.runtime.selfheal import HangWatchdog

    mc = ManualClock()
    fired = []
    wd = HangWatchdog(
        timeout_s=10.0, on_expire=fired.append, clock=mc, thread=False
    )
    with wd.guard("allreduce"):
        mc.advance(9.0)
        assert not wd.check()
        mc.advance(2.0)
        assert wd.check()
    assert fired == ["allreduce"]


def test_hang_watchdog_disarmed_does_not_fire():
    from omldm_tpu.runtime.selfheal import HangWatchdog

    mc = ManualClock()
    fired = []
    wd = HangWatchdog(
        timeout_s=1.0, on_expire=fired.append, clock=mc, thread=False
    )
    with wd.guard("step"):
        pass  # exits before any advance
    mc.advance(100.0)
    assert not wd.check()
    assert fired == []


# --- serving maxDelayMs deadline ----------------------------------------


class _StubQueueNet:
    """Minimal net for ServingPlane unit tests (matches the unit-test
    stub convention _limits() documents)."""

    def __init__(self, net_id, serving_cfg):
        from omldm_tpu.runtime.serving import ServeQueue

        class _Req:
            id = net_id

        self.request = _Req()
        self.serving = serving_cfg
        self.serve_queue = ServeQueue()


def test_serving_deadline_flush_on_manual_clock():
    from omldm_tpu.api.data import DataInstance
    from omldm_tpu.runtime.serving import ServingConfig, ServingPlane

    mc = ManualClock()
    out = []
    plane = ServingPlane(emit_prediction=out.append, clock=mc)
    net = _StubQueueNet(7, ServingConfig(max_batch=64, max_delay_ms=50.0))
    inst = DataInstance(
        id=1, numerical_features=[0.0], operation="forecasting"
    )
    plane.admit(net, inst, None)
    assert net.serve_queue.t_oldest == 0.0  # stamped from the manual clock
    # under the deadline: poll() leaves the queue pending
    mc.advance(0.049)
    plane.poll()
    assert plane.queued() == 1


# --- flight recorder silence poll ---------------------------------------


def test_events_watchdog_silence_on_manual_clock():
    from omldm_tpu.runtime.events import (
        EventJournal,
        EventsConfig,
        Watchdog,
    )

    mc = ManualClock(start=1000.0)
    alerts = []
    cfg = EventsConfig(silence_ms=500.0)
    wd = Watchdog(
        cfg, EventJournal(cap=8, pid=0), on_alert=alerts.append, clock=mc
    )
    last_activity = mc()
    mc.advance(0.4)
    assert wd.poll_silence(last_activity) == []
    mc.advance(0.2)  # 600ms of silence > 500ms budget
    fired = wd.poll_silence(last_activity)
    assert [f["cause"] for f in fired] == ["heartbeat_silence"]
    assert alerts


# --- supervisor clock pair ----------------------------------------------


def test_supervisor_accepts_injected_clock_pair(tmp_path):
    from omldm_tpu.runtime.supervisor import DistributedJobSupervisor

    wall = ManualClock(start=5000.0)
    mono = ManualClock(start=1.0)
    sup = DistributedJobSupervisor(
        worker_args=["--data", "x"],
        num_processes=1,
        run_dir=str(tmp_path),
        clock=mono,
        wall=wall,
    )
    # the blackbox floor is stamped from the injected wall clock
    assert sup._blackbox_floor == 5000.0
    assert sup._clock is mono and sup._wall is wall


def test_supervisor_defaults_to_system_clocks(tmp_path):
    from omldm_tpu.runtime.supervisor import DistributedJobSupervisor

    sup = DistributedJobSupervisor(
        worker_args=["--data", "x"],
        num_processes=1,
        run_dir=str(tmp_path),
    )
    assert sup._clock is uclock.MONOTONIC
    assert sup._wall is uclock.WALL
