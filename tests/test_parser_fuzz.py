"""Fuzz the native bulk-ingest parser against the Python codec.

The C++ parser must NEVER crash, and for every line it must either (a)
produce exactly what `DataInstance.from_json` + `Vectorizer` produce, or
(b) flag the line for the Python fallback / drop it — the same contract
`tests/test_packed_path.py` pins on well-formed streams, here pushed
through mutated/garbage input (truncation, byte flips, spliced structure,
huge numbers, unicode)."""

import json

import numpy as np
import pytest

from omldm_tpu.api.data import FORECASTING, DataInstance
from omldm_tpu.runtime.fast_ingest import PackedBatcher
from omldm_tpu.runtime.vectorizer import Vectorizer


DIM = 8


def reference_rows(block: bytes):
    """What the pure-Python path produces for a byte block (including the
    float32-range clamp both production paths apply to targets)."""
    from omldm_tpu.runtime.vectorizer import F32_MAX

    vec = Vectorizer(DIM, 0)
    xs, ys, ops = [], [], []
    for line in block.split(b"\n"):
        inst = DataInstance.from_json(line.decode("utf-8", errors="replace"))
        if inst is None:
            continue
        xs.append(vec.vectorize(inst))
        ys.append(
            0.0 if inst.target is None
            else min(max(float(inst.target), -F32_MAX), F32_MAX)
        )
        ops.append(1 if inst.operation == FORECASTING else 0)
    if not xs:
        return (
            np.zeros((0, DIM), np.float32),
            np.zeros((0,), np.float32),
            np.zeros((0,), np.uint8),
        )
    return np.stack(xs), np.asarray(ys, np.float32), np.asarray(ops, np.uint8)


def packed_rows(block: bytes):
    b = PackedBatcher(DIM, batch_size=1 << 20)
    list(b.feed(block))
    tail = b.flush()
    if tail is None:
        return (
            np.zeros((0, DIM), np.float32),
            np.zeros((0,), np.float32),
            np.zeros((0,), np.uint8),
        )
    return tail


def make_lines(rng, n):
    """Valid lines + adversarial mutations."""
    lines = []
    for i in range(n):
        kind = rng.randint(0, 10)
        x = np.round(rng.randn(rng.randint(1, DIM + 1)), 5)
        base = {"numericalFeatures": list(x), "target": float(i % 2)}
        if kind == 0:
            lines.append(json.dumps(base))
        elif kind == 1:  # forecast record
            lines.append(json.dumps({"numericalFeatures": list(x),
                                     "operation": "forecasting"}))
        elif kind == 2:  # truncate a valid line at a random byte
            s = json.dumps(base)
            lines.append(s[: rng.randint(0, len(s))])
        elif kind == 3:  # flip one byte of a valid line
            s = bytearray(json.dumps(base).encode())
            s[rng.randint(0, len(s))] = rng.randint(1, 255)
            lines.append(s.decode("utf-8", errors="replace"))
        elif kind == 4:  # huge / extreme numbers
            lines.append(json.dumps({
                "numericalFeatures": [1e308, -1e308, 1e-320, 0.0],
                "target": 12345678901234567890.0,
            }))
        elif kind == 5:  # string-typed numerics, nulls
            lines.append(
                '{"numericalFeatures": ["1.5", null, 2], "target": "0"}'
            )
        elif kind == 6:  # nested garbage / unknown keys
            lines.append(json.dumps({
                "numericalFeatures": list(x),
                "metadata": {"a": [1, {"b": 2}]},
                "target": 1.0,
            }))
        elif kind == 7:  # categorical features (python-fallback route)
            lines.append(json.dumps({
                "numericalFeatures": list(x),
                "categoricalFeatures": ["a", "b"],
                "target": 0.0,
            }))
        elif kind == 8:  # pure garbage
            raw = bytes(rng.randint(1, 255, size=rng.randint(1, 40)))
            lines.append(raw.decode("utf-8", errors="replace")
                         .replace("\n", " "))
        else:  # EOS markers and blanks
            lines.append(rng.choice(["EOS", '"EOS"', "", "   "]))
    # deterministic adversarial grammar cases (strict json.loads drops and
    # near-misses that must stay keeps), shuffled into the stream
    lines.extend([
        '{"numericalFeatures": [.5, 2.0], "target": 1.0}',     # drop
        '{"numericalFeatures": [1., 2.0], "target": 1.0}',     # drop
        '{"numericalFeatures": [01.0, 2.0], "target": 1.0}',   # drop
        '{"numericalFeatures": [+1.5, 2.0], "target": 1.0}',   # drop
        '{"numericalFeatures": [-0.5, 0.0, 0], "target": 1.0}',  # keep
        '{"numericalFeatures": [1.0], "k": "a\\qb", "target": 1.0}',  # drop
        '{"numericalFeatures": [1.0], "k": "a\\u12зb", "target": 1.0}',  # drop
        '{"numericalFeatures": [1.0], "k": "a\\u12ab\\n", "target": 1.0}',  # keep
        '{"numericalFeatures": [1.0, 2.0], "target": 1.0}\x0c',  # keep
        '{"numericalFeatures": [1.0, 2.0], "target": 1.0}\x1d',  # keep
        '{"numericalFeatures": [1.0, 2.0], "target": 1.0} x',  # drop
        '{"numericalFeatures": [1.0, 2.0], "target": 1.0',     # drop
        '{"numericalFeatures": [1e3, 1E+2, 1e-2], "target": 0.0}',  # keep
        '{"numericalFeatures": [1e, 2.0], "target": 1.0}',     # drop
        # object-level grammar (comma discipline)
        '{"numericalFeatures": [1.0, 2.0] "target": 1.0}',     # drop
        '{"numericalFeatures": [1.0], "target": 1.0,}',        # drop
        '{,"numericalFeatures": [1.0]}',                       # drop
        '{"numericalFeatures": [1.0], , "target": 1.0}',       # drop
        # unknown-key values must be valid JSON; composites defer to Python
        '{"numericalFeatures": [1.0], "zz": blah garbage, "target": 1.0}',
        '{"numericalFeatures": [1.0], "zz": true, "id": null, "w": false}',
        '{"numericalFeatures": [1.0], "zz": {"n": [1, "x"]}, "target": 1.0}',
        # overflow under an ignored key: json.loads -> inf, record KEPT
        '{"numericalFeatures": [1.0], "zz": 1e999, "target": 1.0}',
        '{"numericalFeatures": [1.0], "id": 1e1234567, "target": 1.0}',
        # overflow in FEATURES: is_valid rejects non-finite -> drop
        '{"numericalFeatures": [1e999], "target": 1.0}',
        # finite-but-beyond-float32 magnitudes: KEPT, clamped to +/-FLT_MAX
        # identically by the C parser and the Python boundary (no inf may
        # reach device state)
        '{"numericalFeatures": [1e308, -4e38], "target": 1e308}',
        '{"numericalFeatures": [3.5e38], "target": -1e40}',
        '{"numericalFeatures": [1.0], "target": 4.1e38}',
        # operation: exact spelling, last key wins, non-strings drop
        '{"numericalFeatures": [1.0], "operation": "forecaster"}',  # drop
        '{"numericalFeatures": [1.0], "operation": "forecasting"}',  # keep
        '{"numericalFeatures": [1.0], "operation": "training", '
        '"operation": "bogus"}',                               # drop
        '{"numericalFeatures": [1.0], "operation": 5}',        # drop
        # target coercion corners (the codec's float() decides)
        '{"numericalFeatures": [1.0], "target": null}',        # keep
        '{"numericalFeatures": [1.0], "target": "0"}',         # keep!
        '{"numericalFeatures": [1.0], "target": "x"}',         # drop
        '{"numericalFeatures": [1.0], "target": true}',        # keep!
        '{"numericalFeatures": [1.0], "target": 1.0, "target": null}',
    ])
    rng.shuffle(lines)
    return lines


@pytest.mark.parametrize("seed", range(8))
def test_fuzzed_blocks_match_python_codec(seed):
    rng = np.random.RandomState(seed)
    block = ("\n".join(make_lines(rng, 300)) + "\n").encode()
    px, py, pop = packed_rows(block)
    rx, ry, rop = reference_rows(block)
    assert px.shape == rx.shape
    np.testing.assert_allclose(px, rx, rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(py, ry, rtol=1e-6, atol=0)
    np.testing.assert_array_equal(pop, rop)


@pytest.mark.parametrize("seed", range(4))
def test_template_shape_mutations_match_python_codec(seed):
    """The whole-line schema-template fast path (fastparse.cpp) must agree
    with the general walk AND the Python codec on near-misses of its exact
    shape: every mutation must fall through to identical semantics."""
    rng = np.random.RandomState(1000 + seed)
    base = (
        '{"numericalFeatures": [%s], "target": %s, '
        '"operation": "training"}'
    )
    lines = []
    for _ in range(200):
        vals = ", ".join(
            "%.6f" % v for v in rng.randn(rng.randint(1, 8))
        )
        line = base % (vals, "%.1f" % rng.rand())
        r = rng.rand()
        if r < 0.5:
            lines.append(line)  # exact template shape
        elif r < 0.7:  # single-byte mutation anywhere
            i = rng.randint(len(line))
            line = line[:i] + chr(rng.randint(32, 127)) + line[i + 1 :]
            lines.append(line)
        elif r < 0.8:  # truncation
            lines.append(line[: rng.randint(1, len(line))])
        elif r < 0.9:  # trailing junk / whitespace
            lines.append(line + rng.choice([" ", "\t", " x", "\x0c", "}"]))
        else:  # near-miss keys and values
            lines.append(
                line.replace("training", rng.choice(
                    ["Training", "training ", "train", "forecasting"]
                ))
            )
    block = ("\n".join(lines) + "\n").encode()
    px, py, pop = packed_rows(block)
    rx, ry, rop = reference_rows(block)
    assert px.shape == rx.shape
    np.testing.assert_allclose(px, rx, rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(py, ry, rtol=1e-6, atol=0)
    np.testing.assert_array_equal(pop, rop)


def test_binary_garbage_never_crashes():
    rng = np.random.RandomState(99)
    blob = bytes(rng.randint(0, 256, size=100_000, dtype=np.uint8).data)
    x, y, op = packed_rows(blob)  # must not raise
    # and whatever it kept, the python codec would have kept too
    rx, _, _ = reference_rows(blob)
    assert x.shape == rx.shape


def test_request_codec_fuzz_never_raises():
    """Request.from_json mirrors RequestParser.scala:12-17: malformed
    requests drop silently — no mutation may raise. A full StreamJob must
    likewise survive a hostile request stream without deploying anything
    invalid."""
    from omldm_tpu.api.requests import Request
    from omldm_tpu.config import JobConfig
    from omldm_tpu.runtime import StreamJob
    from omldm_tpu.runtime.job import REQUEST_STREAM

    base = {
        "id": 0,
        "request": "Create",
        "learner": {"name": "PA", "hyperParameters": {"C": 1.0}},
        "trainingConfiguration": {"protocol": "Synchronous"},
    }
    rng = np.random.RandomState(7)
    payloads = []
    for i in range(400):
        kind = rng.randint(0, 8)
        if kind == 0:
            payloads.append(json.dumps(base))
        elif kind == 1:  # byte flip
            s = bytearray(json.dumps(base).encode())
            s[rng.randint(0, len(s))] = rng.randint(1, 255)
            payloads.append(s.decode("utf-8", errors="replace"))
        elif kind == 2:  # truncation
            s = json.dumps(base)
            payloads.append(s[: rng.randint(0, len(s))])
        elif kind == 3:  # wrong types
            payloads.append(json.dumps({
                "id": "zero", "request": 5, "learner": "PA",
            }))
        elif kind == 4:  # unknown request kinds / missing fields
            payloads.append(json.dumps({"id": i, "request": "Explode"}))
        elif kind == 5:  # deep nesting
            payloads.append(json.dumps({
                "id": i % 4, "request": "Query",
                "requestId": i,
                "learner": {"name": "PA", "dataStructure": {"a": [[[1]]]}},
            }))
        elif kind == 6:  # non-object JSON
            payloads.append(rng.choice(["[]", "5", '"x"', "null", "true"]))
        else:  # binary garbage
            raw = bytes(rng.randint(1, 255, size=rng.randint(1, 50)))
            payloads.append(raw.decode("utf-8", errors="replace"))
    for text in payloads:
        Request.from_json(text)  # must not raise
    job = StreamJob(JobConfig(parallelism=1))
    for text in payloads:
        job.process_event(REQUEST_STREAM, text)  # must not raise
    # nothing hostile deployed except well-formed Creates (id 0)
    assert set(job.pipeline_manager.live_pipelines) <= {0}


@pytest.mark.parametrize("seed", range(2))
def test_fuzzed_stream_quarantined_not_silently_dropped(seed):
    """Every fuzzed-invalid record fed through the per-record JSON route
    must land in the dead-letter sink with a reason code (EOS markers and
    blank lines are protocol, not poison), must never crash the job, and
    must never mutate model state — the quarantine twin of the reference's
    silent ``DataInstance.isValid`` drop (DataPointParser.scala:13-21)."""
    from omldm_tpu.config import JobConfig
    from omldm_tpu.runtime import StreamJob
    from omldm_tpu.runtime.job import REQUEST_STREAM, TRAINING_STREAM

    rng = np.random.RandomState(500 + seed)
    lines = make_lines(rng, 150)

    # the reference verdict per line, via the SAME parse the job uses
    expected_reasons = []
    n_valid = 0
    for line in lines:
        inst, reason = DataInstance.parse(line)
        if reason is not None:
            expected_reasons.append(reason)
        elif inst is not None:
            n_valid += 1

    def run(stream_lines):
        job = StreamJob(JobConfig(parallelism=1, batch_size=8, test=False))
        job.process_event(REQUEST_STREAM, json.dumps({
            "id": 0, "request": "Create",
            "learner": {"name": "PA", "hyperParameters": {"C": 1.0},
                        "dataStructure": {"nFeatures": DIM}},
            "trainingConfiguration": {"protocol": "Asynchronous"},
        }))
        for line in stream_lines:
            job.process_event(TRAINING_STREAM, line)  # must not raise
        return job

    job = run(lines)
    assert job.dead_letter.record_count == len(expected_reasons)
    assert [e["reason"] for e in job.dead_letter.entries] == expected_reasons
    assert all(e["payload"] for e in job.dead_letter.entries)
    # invalid records never mutate model state: the mixed stream's final
    # params equal a valid-only replay's, bitwise
    valid_only = [l for l in lines if DataInstance.parse(l)[0] is not None]
    assert len(valid_only) == n_valid
    job_valid = run(valid_only)
    np.testing.assert_array_equal(
        job.spokes[0].nets[0].pipeline.get_flat_params()[0],
        job_valid.spokes[0].nets[0].pipeline.get_flat_params()[0],
    )


def test_cli_backend_fallback(monkeypatch):
    """--ensure-backend falls back to CPU when the accelerator cannot
    initialize instead of crashing the job (__main__._ensure_backend)."""
    import jax

    from omldm_tpu.__main__ import _ensure_backend

    calls = {"n": 0, "updates": []}

    def fake_devices():
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("tunnel down")
        return ["cpu0"]

    monkeypatch.setattr(jax, "devices", fake_devices)
    monkeypatch.setattr(
        jax.config, "update",
        lambda k, v: calls["updates"].append((k, v)),
    )
    _ensure_backend()
    assert ("jax_platforms", "cpu") in calls["updates"]
    assert calls["n"] == 2
