"""Checkpoint / resume / rescale-merge tests."""

import json

import numpy as np
import pytest

from omldm_tpu.api.requests import LearnerSpec, TrainingConfiguration
from omldm_tpu.checkpoint import CheckpointManager
from omldm_tpu.config import JobConfig
from omldm_tpu.runtime import StreamJob
from omldm_tpu.runtime.job import REQUEST_STREAM, TRAINING_STREAM


def stream_lines(n, dim=5, seed=0):
    # the concept (separating hyperplane) is fixed; seed only varies the draws
    w = np.random.RandomState(42).randn(dim)
    rng = np.random.RandomState(seed)
    x = rng.randn(n, dim)
    y = (x @ w > 0).astype(np.float64)
    return [
        json.dumps({"numericalFeatures": list(np.round(x[i], 5)), "target": float(y[i])})
        for i in range(n)
    ]


CREATE = {
    "id": 0,
    "request": "Create",
    "learner": {"name": "PA", "hyperParameters": {"C": 1.0}},
    "trainingConfiguration": {"protocol": "Synchronous", "syncEvery": 2},
}


def trained_job(tmp_path, parallelism=4, n=1500):
    cfg = JobConfig(parallelism=parallelism, batch_size=32, test_set_size=32)
    job = StreamJob(cfg)
    events = [(REQUEST_STREAM, json.dumps(CREATE))] + [
        (TRAINING_STREAM, l) for l in stream_lines(n)
    ]
    job.run(events, terminate_on_end=False)
    return job


class TestSaveRestore:
    def test_roundtrip_same_parallelism(self, tmp_path):
        job = trained_job(tmp_path)
        mgr = CheckpointManager(str(tmp_path / "ck"))
        mgr.save(job)
        restored = mgr.restore()
        assert restored.pipeline_manager.live_pipelines == [0]
        for old, new in zip(job.spokes, restored.spokes):
            w_old, _ = old.nets[0].pipeline.get_flat_params()
            w_new, _ = new.nets[0].pipeline.get_flat_params()
            np.testing.assert_allclose(w_old, w_new, rtol=1e-6)
            assert len(new.nets[0].test_set) == len(old.nets[0].test_set)
            assert new.nets[0].pipeline.fitted == old.nets[0].pipeline.fitted

    def test_restored_job_continues_training(self, tmp_path):
        job = trained_job(tmp_path)
        mgr = CheckpointManager(str(tmp_path / "ck"))
        mgr.save(job)
        restored = mgr.restore()
        report = restored.run(
            [(TRAINING_STREAM, l) for l in stream_lines(1500, seed=1)]
        )
        [stats] = report.statistics
        assert stats.score > 0.85

    def test_rescale_down_merges_exactly_when_quiesced(self, tmp_path):
        """With empty buffers, a 4->2 rescale must land exactly the averaged
        replicas on every new worker (the assignment the reference's restore
        forgot, FlinkSpoke.scala:291-305)."""
        job = trained_job(tmp_path, parallelism=4)
        for s in job.spokes:  # quiesce: no pending work to re-train
            s.nets[0].flush_batch()
            s.nets[0].test_set.clear()
        mgr = CheckpointManager(str(tmp_path / "ck"))
        mgr.save(job)
        restored = mgr.restore(parallelism=2)
        assert len(restored.spokes) == 2
        saved = [s.nets[0].pipeline.get_flat_params()[0] for s in job.spokes]
        expect = np.stack(saved).mean(0)
        for s in restored.spokes:
            got, _ = s.nets[0].pipeline.get_flat_params()
            np.testing.assert_allclose(got, expect, rtol=1e-5)

    def test_rescale_down_retrains_overflow_and_converges(self, tmp_path):
        """With live buffers, rescale redistributes holdout points (capacity
        overflow re-trained, the evicted-holdout rule) and keeps learning."""
        job = trained_job(tmp_path, parallelism=4)
        mgr = CheckpointManager(str(tmp_path / "ck"))
        mgr.save(job)
        restored = mgr.restore(parallelism=2)
        total_test = sum(len(s.nets[0].test_set) for s in restored.spokes)
        assert total_test > 0
        report = restored.run(
            [(TRAINING_STREAM, l) for l in stream_lines(800, seed=2)]
        )
        assert report.statistics[0].score > 0.85

    def test_rescale_up_replicates(self, tmp_path):
        job = trained_job(tmp_path, parallelism=2)
        mgr = CheckpointManager(str(tmp_path / "ck"))
        mgr.save(job)
        restored = mgr.restore(parallelism=4)
        assert len(restored.spokes) == 4
        report = restored.run(
            [(TRAINING_STREAM, l) for l in stream_lines(800, seed=3)]
        )
        assert report.statistics[0].score > 0.8

    def test_hub_stats_continuity(self, tmp_path):
        job = trained_job(tmp_path)
        before = job.hub_manager.network_statistics(0)
        mgr = CheckpointManager(str(tmp_path / "ck"))
        mgr.save(job)
        restored = mgr.restore()
        after = restored.hub_manager.hubs[(0, 0)].node.stats
        assert after.bytes_shipped == before.bytes_shipped
        assert after.fitted == before.fitted

    def test_periodic_maybe_save(self, tmp_path):
        cfg = JobConfig(
            parallelism=1,
            checkpointing=True,
            check_interval_ms=0,  # save on every opportunity
            checkpoint_dir=str(tmp_path / "auto"),
            batch_size=16,
        )
        job = StreamJob(cfg)
        events = [(REQUEST_STREAM, json.dumps(CREATE))] + [
            (TRAINING_STREAM, l) for l in stream_lines(100)
        ]
        job.run(events, terminate_on_end=False)
        assert job.checkpoint_manager.latest_path() is not None

    def test_restore_without_checkpoint_raises(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path / "empty"))
        with pytest.raises(FileNotFoundError):
            mgr.restore()


class TestSPMDCheckpoint:
    def test_spmd_save_load(self, tmp_path):
        from omldm_tpu.parallel import SPMDTrainer, make_mesh

        mesh = make_mesh(dp=4, hub=2)
        t = SPMDTrainer(
            LearnerSpec("PA", hyper_parameters={"C": 1.0}),
            dim=6,
            protocol="Synchronous",
            mesh=mesh,
            training_configuration=TrainingConfiguration(
                protocol="Synchronous", extra={"syncEvery": 1}
            ),
        )
        rng = np.random.RandomState(0)
        for _ in range(5):
            x = rng.randn(4, 32, 6).astype(np.float32)
            y = (x.sum(-1) > 0).astype(np.float32)
            t.step(x, y, np.ones((4, 32), np.float32))
        w_before = t.global_flat_params()
        t.save(str(tmp_path / "spmd"))

        t2 = SPMDTrainer(
            LearnerSpec("PA", hyper_parameters={"C": 1.0}),
            dim=6,
            protocol="Synchronous",
            mesh=make_mesh(dp=4, hub=2),
            training_configuration=TrainingConfiguration(
                protocol="Synchronous", extra={"syncEvery": 1}
            ),
        )
        t2.load(str(tmp_path / "spmd"))
        np.testing.assert_allclose(t2.global_flat_params(), w_before, rtol=1e-6)


class TestStatisticsContinuity:
    def test_cumulative_loss_restored(self, tmp_path):
        job = trained_job(tmp_path)
        losses = [s.nets[0].pipeline.cumulative_loss for s in job.spokes]
        assert sum(losses) > 0
        mgr = CheckpointManager(str(tmp_path / "ck"))
        mgr.save(job)
        restored = mgr.restore()
        for spoke, expected in zip(restored.spokes, losses):
            assert spoke.nets[0].pipeline.cumulative_loss == pytest.approx(
                expected, rel=1e-6
            )

    def test_cumulative_loss_sum_survives_rescale(self, tmp_path):
        job = trained_job(tmp_path, parallelism=4)
        total = sum(s.nets[0].pipeline.cumulative_loss for s in job.spokes)
        mgr = CheckpointManager(str(tmp_path / "ck"))
        mgr.save(job)
        restored = mgr.restore(parallelism=2)
        got = sum(s.nets[0].pipeline.cumulative_loss for s in restored.spokes)
        # the merged replicas may retrain overflow records (which adds loss),
        # so the restored sum is at least the saved sum
        assert got >= total * (1 - 1e-6)


class TestRetention:
    def test_prunes_to_keep_newest(self, tmp_path):
        job = trained_job(tmp_path, parallelism=2, n=400)
        mgr = CheckpointManager(str(tmp_path / "ck"), keep=3)
        paths = [mgr.save(job) for _ in range(7)]
        import os

        snaps = sorted(
            f for f in os.listdir(tmp_path / "ck")
            if f.startswith("ckpt_") and f.endswith(".pkl")
        )
        assert len(snaps) == 3
        # the retained set is the newest three, and latest still restores
        assert snaps[-1] == os.path.basename(paths[-1])
        assert mgr.latest_path().endswith(snaps[-1])
        mgr.restore()

    def test_empty_latest_pointer_reads_as_no_checkpoint(self, tmp_path):
        """A crash between pointer truncate and write must not turn into
        IsADirectoryError deep inside recovery: an empty/ dangling pointer
        means 'no checkpoint'."""
        import os

        job = trained_job(tmp_path, parallelism=2, n=400)
        mgr = CheckpointManager(str(tmp_path / "ck"), keep=3)
        mgr.save(job)
        with open(os.path.join(str(tmp_path / "ck"), "latest"), "w"):
            pass  # truncated pointer
        assert mgr.latest_path() is None
        with pytest.raises(FileNotFoundError):
            mgr.restore()
        # dangling pointer (file pruned externally) reads the same way
        with open(os.path.join(str(tmp_path / "ck"), "latest"), "w") as f:
            f.write("ckpt_gone.pkl")
        assert mgr.latest_path() is None

    def test_same_millisecond_saves_do_not_collide(self, tmp_path):
        job = trained_job(tmp_path, parallelism=2, n=400)
        mgr = CheckpointManager(str(tmp_path / "ck"), keep=0)
        paths = {mgr.save(job) for _ in range(5)}
        assert len(paths) == 5  # unique names even within one ms

    def test_keep_zero_retains_everything(self, tmp_path):
        job = trained_job(tmp_path, parallelism=2, n=400)
        mgr = CheckpointManager(str(tmp_path / "ck"), keep=0)
        for _ in range(5):
            mgr.save(job)
        import os

        snaps = [
            f for f in os.listdir(tmp_path / "ck") if f.endswith(".pkl")
        ]
        assert len(snaps) == 5

    def test_sequence_survives_new_manager_on_same_dir(self, tmp_path):
        """A manager built mid-recovery on a live directory must continue
        the name sequence: its first save must sort after (never collide
        with) the existing snapshots, or pruning could delete the file
        `latest` points at."""
        import os

        job = trained_job(tmp_path, parallelism=2, n=400)
        m1 = CheckpointManager(str(tmp_path / "ck"), keep=2)
        m1.save(job)
        p2 = m1.save(job)
        m2 = CheckpointManager(str(tmp_path / "ck"), keep=2)
        p3 = m2.save(job)
        assert os.path.basename(p3) > os.path.basename(p2)
        assert m2.latest_path() == p3
        m2.restore()  # latest survived pruning
