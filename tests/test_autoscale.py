"""Pressure-driven autoscaling (ISSUE 12 tentpole, supervisor half).

Pins:

- AutoscalePolicy semantics: sustained CRITICAL scales out (factor,
  bounded by maxProcesses), sustained OK scales in (floored at
  minProcesses), ELEVATED holds, unknown pressure (compiling fleet)
  holds and clears streaks, cooldown gates consecutive decisions,
  validation rejects nonsense bounds;
- the supervisor plumbing: autoscale arms the heartbeat/pressure
  channel and the signal/count flags, refuses to arm without a
  checkpoint dir, folds beat-file pressure levels (missing beats read
  UNKNOWN, not calm);
- the worker side: a standing rescale signal checkpoints the consistent
  cut and exits with RESCALE_EXIT; a same-count or absent signal is a
  no-op; a signal without a checkpoint dir warns and keeps running;
- (slow) the full loop: a preloaded burst drives a supervised 1-process
  fleet out to 2 processes and back in to the floor, with exact row
  conservation and every forecast served exactly once.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from omldm_tpu.config import JobConfig
from omldm_tpu.runtime.distributed_job import (
    DistributedStreamJob,
    _maybe_rescale_exit,
)
from omldm_tpu.runtime.supervisor import (
    RESCALE_EXIT,
    AutoscalePolicy,
    DistributedJobSupervisor,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TESTS = os.path.join(REPO, "tests")
DIM = 6

FSKAFKA_BOOT = (
    "import sys; sys.path.insert(0, {tests!r}); "
    "import fskafka; fskafka.install(); "
    "from omldm_tpu.runtime.distributed_job import run_distributed; "
    "sys.exit(run_distributed(sys.argv[1:]))"
).format(tests=TESTS)


# --- policy units ------------------------------------------------------------


def _policy(**kw):
    kw.setdefault("min_processes", 1)
    kw.setdefault("max_processes", 8)
    kw.setdefault("up_after_s", 1.0)
    kw.setdefault("down_after_s", 2.0)
    kw.setdefault("cooldown_s", 0.5)
    return AutoscalePolicy(**kw)


class TestAutoscalePolicy:
    def test_sustained_critical_scales_out(self):
        p = _policy()
        assert p.decide(2, 2, 0.0) is None  # streak starts
        assert p.decide(2, 2, 0.5) is None  # not sustained yet
        assert p.decide(2, 2, 1.0) == 4     # doubled

    def test_bounded_by_max(self):
        p = _policy(max_processes=3)
        p.decide(2, 2, 0.0)
        assert p.decide(2, 2, 1.5) == 3
        p2 = _policy(max_processes=2)
        p2.decide(2, 2, 0.0)
        assert p2.decide(2, 2, 1.5) is None  # already at the ceiling

    def test_sustained_ok_scales_in(self):
        p = _policy()
        p.decide(4, 0, 0.0)
        assert p.decide(4, 0, 1.0) is None
        assert p.decide(4, 0, 2.0) == 2

    def test_floored_by_min(self):
        p = _policy(min_processes=3)
        p.decide(4, 0, 0.0)
        assert p.decide(4, 0, 2.5) == 3
        p2 = _policy(min_processes=1)
        p2.decide(1, 0, 0.0)
        assert p2.decide(1, 0, 99.0) is None  # at the floor

    def test_elevated_holds_and_clears_streaks(self):
        p = _policy()
        p.decide(2, 2, 0.0)
        assert p.decide(2, 1, 0.9) is None   # ELEVATED clears critical streak
        assert p.decide(2, 2, 1.0) is None   # streak restarted
        assert p.decide(2, 2, 2.0) == 4

    def test_unknown_pressure_holds(self):
        p = _policy()
        p.decide(2, 0, 0.0)
        assert p.decide(2, -1, 1.0) is None  # compiling fleet: no evidence
        assert p.decide(2, 0, 2.5) is None   # calm streak restarted at 2.5
        assert p.decide(2, 0, 4.5) == 1

    def test_level_flap_never_fires(self):
        p = _policy()
        for i in range(40):
            assert p.decide(2, 2 if i % 2 else 0, i * 0.3) is None

    def test_cooldown_gates_consecutive_decisions(self):
        p = _policy(cooldown_s=10.0)
        p.decide(1, 2, 0.0)
        assert p.decide(1, 2, 1.0) == 2
        p.note_rescaled(1.0)
        p.decide(2, 2, 2.0)
        assert p.decide(2, 2, 9.0) is None   # sustained but cooling down
        assert p.decide(2, 2, 11.5) == 4

    def test_reset_forgets_streaks(self):
        p = _policy()
        p.decide(1, 2, 0.0)
        p.reset()
        assert p.decide(1, 2, 1.5) is None   # streak must re-prove itself

    @pytest.mark.parametrize("kw", [
        {"min_processes": 0},
        {"min_processes": 4, "max_processes": 2},
        {"scale_factor": 1},
    ])
    def test_validation(self, kw):
        with pytest.raises(ValueError):
            _policy(**kw)


# --- supervisor plumbing -----------------------------------------------------


class TestSupervisorWiring:
    def test_autoscale_requires_checkpoint_dir(self, tmp_path):
        with pytest.raises(ValueError, match="checkpointDir"):
            DistributedJobSupervisor(
                ["--trainingData", "x.jsonl"], 1, autoscale=_policy(),
                run_dir=str(tmp_path),
            )

    def test_supervise_flags_reject_autoscale_without_ckpt(self):
        from omldm_tpu.runtime.supervisor import supervise_from_flags

        with pytest.raises(SystemExit, match="checkpointDir"):
            supervise_from_flags({"autoscale": "true", "processes": "1"})

    def _sup(self, tmp_path, **kw):
        return DistributedJobSupervisor(
            ["--checkpointDir", str(tmp_path / "ck")], 2,
            run_dir=str(tmp_path / "run"), **kw,
        )

    def test_worker_argv_arms_pressure_channel(self, tmp_path):
        sup = self._sup(tmp_path, autoscale=_policy())
        argv = sup._worker_argv(0, 9999, restore=False)
        assert "--heartbeatDir" in argv
        assert "--rescaleSignalDir" in argv
        assert argv[argv.index("--rescaleCount") + 1] == "0"

    def test_worker_argv_unarmed_without_autoscale(self, tmp_path):
        sup = self._sup(tmp_path)
        argv = sup._worker_argv(0, 9999, restore=False)
        assert "--rescaleSignalDir" not in argv
        assert "--heartbeatDir" not in argv

    def test_fleet_pressure_folds_beats(self, tmp_path):
        sup = self._sup(tmp_path, autoscale=_policy())
        os.makedirs(sup.hb_dir)
        assert sup.fleet_pressure() == -1  # nobody has beaten: unknown
        with open(os.path.join(sup.hb_dir, "proc0.hb"), "w") as f:
            f.write("123.0 0")
        assert sup.fleet_pressure() == 0
        with open(os.path.join(sup.hb_dir, "proc1.hb"), "w") as f:
            f.write("123.0 2")
        assert sup.fleet_pressure() == 2
        # legacy single-token beats read level 0, not a crash
        with open(os.path.join(sup.hb_dir, "proc1.hb"), "w") as f:
            f.write("123.0")
        assert sup.fleet_pressure() == 0


# --- worker-side rescale signal ----------------------------------------------


CREATE = json.dumps({
    "id": 0, "request": "Create",
    "learner": {"name": "PA", "hyperParameters": {"C": 1.0},
                "dataStructure": {"nFeatures": DIM}},
    "preProcessors": [],
    "trainingConfiguration": {"protocol": "Synchronous", "syncEvery": 1},
})


def _worker_job():
    job = DistributedStreamJob(JobConfig(batch_size=8, test_set_size=16))
    job.sync_requests([CREATE])
    rng = np.random.RandomState(0)
    x = rng.randn(64, DIM).astype(np.float32)
    job.handle_partition_rows(x, (x[:, 0] > 0).astype(np.float32))
    job.pump()
    return job


class TestRescaleSignalExit:
    def test_signal_checkpoints_and_exits(self, tmp_path):
        job = _worker_job()
        sig = tmp_path / "run"
        sig.mkdir()
        (sig / "RESCALE").write_text("2")
        flags = {"rescaleSignalDir": str(sig),
                 "checkpointDir": str(tmp_path / "ck")}
        with pytest.raises(SystemExit) as exc:
            _maybe_rescale_exit(job, flags, 64)
        assert exc.value.code == RESCALE_EXIT
        assert (tmp_path / "ck" / "LATEST").exists()

    def test_same_count_signal_noop(self, tmp_path):
        job = _worker_job()
        sig = tmp_path / "run"
        sig.mkdir()
        (sig / "RESCALE").write_text("1")  # == current nproc
        _maybe_rescale_exit(
            job, {"rescaleSignalDir": str(sig),
                  "checkpointDir": str(tmp_path / "ck")}, 64,
        )  # no exit

    def test_absent_signal_noop(self, tmp_path):
        job = _worker_job()
        _maybe_rescale_exit(
            job, {"rescaleSignalDir": str(tmp_path),
                  "checkpointDir": str(tmp_path / "ck")}, 64,
        )
        _maybe_rescale_exit(job, {}, 64)  # unarmed: zero-cost

    def test_signal_without_ckpt_dir_warns_keeps_running(
        self, tmp_path, capsys
    ):
        job = _worker_job()
        sig = tmp_path / "run"
        sig.mkdir()
        (sig / "RESCALE").write_text("2")
        _maybe_rescale_exit(job, {"rescaleSignalDir": str(sig)}, 64)
        assert "rescale signal ignored" in capsys.readouterr().err


# --- the full loop (slow) ----------------------------------------------------


@pytest.mark.slow
def test_supervised_autoscale_out_and_back(tmp_path):
    """A preloaded burst drives the supervised fleet 1 -> 2 processes;
    once drained, sustained OK brings it back to the floor; the final
    report conserves every training row and serves every forecast
    exactly once across both restore-with-rescale relaunches."""
    sys.path.insert(0, TESTS)
    import fskafka

    broker = tmp_path / "broker"
    os.environ["FSKAFKA_DIR"] = str(broker)
    try:
        rng = np.random.RandomState(0)
        w = rng.randn(12)
        n_rows, n_fore = 8000, 0
        for i in range(n_rows):
            x = np.round(rng.randn(12), 6)
            if i % 20 == 0:
                n_fore += 1
                line = json.dumps({
                    "numericalFeatures": [float(v) for v in x],
                    "operation": "forecasting",
                })
            else:
                line = json.dumps({
                    "numericalFeatures": [float(v) for v in x],
                    "target": float(x @ w > 0), "operation": "training",
                })
            fskafka.append("trainingData", line, partition=i % 4)
        fskafka.append("requests", json.dumps({
            "id": 0, "request": "Create",
            "learner": {"name": "PA", "hyperParameters": {"C": 1.0},
                        "dataStructure": {"nFeatures": 12}},
            "trainingConfiguration": {
                "protocol": "Synchronous", "syncEvery": 1,
            },
        }))
    finally:
        os.environ.pop("FSKAFKA_DIR", None)

    perf = tmp_path / "perf.jsonl"
    preds = tmp_path / "preds.jsonl"
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["FSKAFKA_DIR"] = str(broker)
    out = subprocess.run(
        [sys.executable, "-m", "omldm_tpu.runtime.distributed_job",
         "--supervise", "true", "--processes", "1",
         "--autoscale", "true", "--minProcesses", "1",
         "--maxProcesses", "2",
         "--scaleUpAfterMs", "200", "--scaleDownAfterMs", "1200",
         "--scaleCooldownMs", "400",
         "--overload", "backlogHigh=40,backlogCritical=80",
         "--kafkaBrokers", "fs://local", "--workerBoot", FSKAFKA_BOOT,
         "--checkpointDir", str(tmp_path / "ckpts"),
         "--checkpointEvery", "8",
         "--chunkRows", "100", "--kafkaPollMs", "50",
         "--idleWindows", "60",
         "--batchSize", "64", "--testSetSize", "32",
         "--restartAttempts", "2", "--restartDelayMs", "50",
         "--performanceOut", str(perf), "--predictionsOut", str(preds)],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=420,
    )
    assert out.returncode == 0, out.stderr[-4000:]
    err = out.stderr
    assert "signaling rescale 1 -> 2" in err
    assert "rescaling fleet 1 -> 2" in err
    assert "rescale-restore: redistributing a 1-process snapshot" in err
    assert "rescaling fleet 2 -> 1" in err
    report = json.loads(perf.read_text().strip())
    [s] = report["statistics"]
    assert s["fitted"] + report["holdout"]["0"] == n_rows - n_fore
    assert report["rescalesPerformed"] == 2
    assert report["fleetProcesses"] == 1  # back at the floor
    assert s["rescalesPerformed"] == 2 and s["fleetProcesses"] == 1
    payloads = [json.loads(l) for l in preds.read_text().splitlines()]
    assert len(payloads) == n_fore  # exactly once across the relaunches
