"""Self-healing fleet (ISSUE 15): failure classification, slot strikes,
shrink-to-survivors, probed re-expansion, hang watchdogs, kill escalation.

Pins:

- the failure taxonomy: crash exits, heartbeat-silent hangs, survivors'
  HANG_EXITs blaming the wedged peer, never-beat launch failures (and the
  in-process classify_exception twin);
- SelfHealPolicy's strike/degrade/probe state machine with an injectable
  clock: per-slot consecutive strikes, threshold-triggered shrink targets
  (floored at minProcesses), strike reset on width change, probe cadence,
  probe-window healing, immediate re-degrade on a failed probe;
- HangWatchdog semantics: re-entrant deadline guards refreshed on entry,
  per-phase cold-compile warmup allowance, fire-once expiry (deterministic
  non-threaded form + a real-thread firing test);
- kill escalation: SIGTERM -> deadline -> SIGKILL so a stopped/wedged
  process cannot stall the supervisor's restart path;
- supervisor wiring: classified FleetFailures, strike accounting that
  survives fleet restarts, degrade relaunches that burn no restart
  attempt, the --fleetDegraded gauge, deterministic restart jitter;
- checkpoint integrity: sha256 digests in the distributed manifest /
  shard metas, digest-mismatch rejection with generation fallback, and
  the single-process CheckpointManager's generation fallback;
- ENOSPC survival: black-box ring dumps, dead-letter appends and
  heartbeat files degrade to dropped-write counters, never a raise;
- (slow) the full loop: a SIGSTOP'd worker is detected, survivors exit
  HANG_EXIT within the collective timeout, the fleet shrinks to the
  survivors with exact row conservation and exactly-once forecasts, then
  probes back to full width and heals.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from omldm_tpu.runtime.selfheal import (
    CRASH,
    HANG,
    HANG_EXIT,
    LAUNCH,
    HangWatchdog,
    RestartPolicy,
    SelfHealPolicy,
    classify_exception,
    classify_failure,
    kill_escalate,
)
from omldm_tpu.runtime.supervisor import (
    DistributedFaultInjector,
    DistributedJobSupervisor,
    FleetFailure,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TESTS = os.path.join(REPO, "tests")
DIM = 6

FSKAFKA_BOOT = (
    "import sys; sys.path.insert(0, {tests!r}); "
    "import fskafka; fskafka.install(); "
    "from omldm_tpu.runtime.distributed_job import run_distributed; "
    "sys.exit(run_distributed(sys.argv[1:]))"
).format(tests=TESTS)


# --- classification ----------------------------------------------------------


class TestClassification:
    def test_crash(self):
        assert classify_failure(returncode=3, ever_beat=True) == CRASH
        assert classify_failure(returncode=1) == CRASH

    def test_hang_from_silence(self):
        assert classify_failure(heartbeat_silent=True) == HANG
        # silence outranks the never-beat heuristic (a wedged worker that
        # froze before its first beat is still a hang, not a launch)
        assert (
            classify_failure(heartbeat_silent=True, ever_beat=False) == HANG
        )

    def test_hang_exit_is_hang(self):
        assert classify_failure(returncode=HANG_EXIT, ever_beat=True) == HANG

    def test_launch_never_beat(self):
        assert classify_failure(returncode=3, ever_beat=False) == LAUNCH

    def test_unarmed_beats_degrade_to_crash(self):
        # without the heartbeat channel, launch is indistinguishable
        assert classify_failure(returncode=3, ever_beat=None) == CRASH

    def test_exception_twin(self):
        assert classify_exception(RuntimeError("x"), progressed=True) == CRASH
        assert classify_exception(RuntimeError("x"), progressed=False) == LAUNCH
        assert classify_exception(TimeoutError(), progressed=True) == HANG


# --- restart policy ----------------------------------------------------------


class TestRestartPolicy:
    def test_backoff_fields(self):
        rp = RestartPolicy(
            max_restarts=3, base_delay_s=0.5, growth=2.0, jitter_s=0.1
        )
        policy = rp.backoff()
        assert policy.attempts == 4
        assert policy.base_delay == 0.5
        assert policy.growth == 2.0
        assert policy.jitter == 0.1

    def test_exponential_delays(self):
        policy = RestartPolicy(base_delay_s=0.1, growth=2.0).backoff()
        rng = RestartPolicy(seed=0).rng()
        assert policy.delay(1, rng) == pytest.approx(0.1)
        assert policy.delay(2, rng) == pytest.approx(0.2)
        assert policy.delay(3, rng) == pytest.approx(0.4)

    def test_deterministic_jitter(self):
        a = [RestartPolicy(seed=7).rng()() for _ in range(8)]
        b = [RestartPolicy(seed=7).rng()() for _ in range(8)]
        c = [RestartPolicy(seed=8).rng()() for _ in range(8)]
        assert a == b          # same seed: same delay schedule
        assert a != c          # different seed: desynchronized
        assert all(0.0 <= u < 1.0 for u in a)

    def test_default_seed_is_pid_derived(self):
        # unset seed: the stream keys off the supervisor's pid (co-hosted
        # fleets desynchronize without an operator remembering a knob);
        # within one process that's still a stable, usable stream
        d = [RestartPolicy().rng()() for _ in range(4)]
        e = [RestartPolicy(seed=os.getpid()).rng()() for _ in range(4)]
        assert d == e


# --- strike/degrade/probe state machine --------------------------------------


def _policy(**kw):
    kw.setdefault("min_processes", 1)
    kw.setdefault("probe_after_s", 5.0)
    kw.setdefault("probe_window_s", 3.0)
    kw.setdefault("clock", lambda: 0.0)
    return SelfHealPolicy(kw.pop("threshold", 2), kw.pop("configured", 4), **kw)


class TestSelfHealPolicy:
    def test_strikes_accrue_per_slot(self):
        p = _policy()
        assert p.note_failure([1], {1: CRASH}, 4, 0.0) is None
        assert p.strikes == {1: 1}
        assert p.note_failure([2], {2: CRASH}, 4, 1.0) is None
        assert p.strikes == {1: 1, 2: 1}  # different slot: no threshold

    def test_threshold_degrades_to_survivors(self):
        p = _policy()
        p.note_failure([1], {1: CRASH}, 4, 0.0)
        assert p.note_failure([1], {1: HANG}, 4, 1.0) == 3
        assert p.degraded and p.degraded_by == 1
        assert p.strikes == {}  # widths renumber: counts reset

    def test_healthy_attempt_resets_streak(self):
        p = _policy()
        p.note_failure([1], {1: CRASH}, 4, 0.0)
        p.note_healthy_attempt()
        assert p.note_failure([1], {1: CRASH}, 4, 1.0) is None  # not consec.

    def test_multi_slot_failure_degrades_by_all(self):
        p = _policy(threshold=1)
        assert p.note_failure([1, 3], {1: HANG, 3: HANG}, 4, 0.0) == 2
        assert p.degraded_by == 2

    def test_floor(self):
        p = _policy(threshold=1, configured=2, min_processes=2)
        # already at the floor: nothing to shrink away
        assert p.note_failure([0], {0: CRASH}, 2, 0.0) is None
        assert not p.degraded

    def test_probe_cadence(self):
        p = _policy(threshold=1)
        p.note_failure([1], {1: CRASH}, 4, 10.0)
        assert p.probe_target(3, 14.9) is None  # quiet < probe_after_s
        assert p.probe_target(3, 15.1) == 4
        p.note_probe_signaled()
        assert p.probing
        assert p.probe_target(4, 99.0) is None  # one probe at a time

    def test_probe_heals_after_window(self):
        p = _policy(threshold=1)
        p.note_failure([1], {1: CRASH}, 4, 0.0)
        p.note_probe_signaled()
        p.note_spawn(20.0)
        assert not p.tick_healthy(22.9)
        assert p.tick_healthy(23.1)
        assert not p.tick_healthy(24.0)  # fires exactly once
        assert not p.degraded and p.strikes == {} and p.heals == 1

    def test_failed_probe_redegrades_immediately(self):
        p = _policy(threshold=2)
        p.note_failure([1], {1: CRASH}, 4, 0.0)
        p.note_failure([1], {1: CRASH}, 4, 1.0)  # degrade to 3
        p.note_probe_signaled()
        p.note_spawn(10.0)
        # failure inside the window: back to 3, no strike budget consumed
        assert p.note_failure([1], {1: CRASH}, 4, 11.0) == 3
        assert p.probe_failures == 1 and not p.probing
        assert p.degraded_by == 1

    def test_spawn_starts_window_not_signal(self):
        p = _policy(threshold=1)
        p.note_failure([1], {1: CRASH}, 4, 0.0)
        p.note_probe_signaled()
        # checkpoint+relaunch latency between signal and spawn must not
        # eat the health window
        p.note_spawn(50.0)
        assert not p.tick_healthy(52.0)
        assert p.tick_healthy(53.5)

    def test_validation(self):
        with pytest.raises(ValueError):
            SelfHealPolicy(0, 4)
        with pytest.raises(ValueError):
            SelfHealPolicy(1, 4, min_processes=0)
        with pytest.raises(ValueError):
            SelfHealPolicy(1, 1, min_processes=2)

    def test_snapshot_shape(self):
        p = _policy(threshold=1)
        p.note_failure([1], {1: HANG}, 4, 0.0)
        snap = p.snapshot()
        assert snap["degradedBy"] == 1 and snap["degrades"] == 1
        json.dumps(snap)  # strike-file serializable


# --- kill escalation ---------------------------------------------------------


class _FakeProc:
    """Popen-shaped: ``polite`` dies on terminate(), a stubborn (SIGSTOP'd
    / native-wedged) one only on kill()."""

    def __init__(self, polite: bool):
        self.polite = polite
        self.rc = None
        self.terminated = False
        self.killed = False

    def poll(self):
        return self.rc

    def terminate(self):
        self.terminated = True
        if self.polite:
            self.rc = -15

    def kill(self):
        self.killed = True
        self.rc = -9

    def wait(self):
        return self.rc


class TestKillEscalate:
    def test_polite_fleet_never_escalates(self):
        procs = [_FakeProc(True), _FakeProc(True)]
        clock = [0.0]
        escalated = kill_escalate(
            procs, 1.0, clock=lambda: clock[0],
            sleep=lambda s: clock.__setitem__(0, clock[0] + s),
        )
        assert escalated == []
        assert all(p.terminated and not p.killed for p in procs)

    def test_stubborn_proc_gets_sigkill(self):
        procs = [_FakeProc(True), _FakeProc(False)]
        clock = [0.0]
        escalated = kill_escalate(
            procs, 1.0, clock=lambda: clock[0],
            sleep=lambda s: clock.__setitem__(0, clock[0] + s),
        )
        assert escalated == [1]
        assert procs[1].killed and procs[1].rc == -9
        assert not procs[0].killed

    def test_already_dead_fleet_untouched(self):
        p = _FakeProc(True)
        p.rc = 0
        assert kill_escalate([p], 1.0) == []
        assert not p.terminated and not p.killed


# --- hang watchdog -----------------------------------------------------------


class TestHangWatchdog:
    def _wd(self, timeout=10.0, warmup=None, clock=None):
        fired = []
        wd = HangWatchdog(
            timeout, fired.append, warmup_s=warmup,
            clock=clock or (lambda: self.now), thread=False,
        )
        return wd, fired

    def test_unarmed_never_fires(self):
        self.now = 0.0
        wd, fired = self._wd()
        self.now = 1e9
        assert not wd.check()
        assert fired == []

    def test_guard_deadline_fires_once(self):
        self.now = 0.0
        wd, fired = self._wd(timeout=10.0, warmup=10.0)
        with wd.guard("pump"):
            self.now = 9.0
            assert not wd.check()
            self.now = 11.0
            assert wd.check()
            assert not wd.check()  # fire-once
        assert fired == ["pump"]

    def test_exit_disarms(self):
        self.now = 0.0
        wd, fired = self._wd(timeout=5.0, warmup=5.0)
        with wd.guard("pump"):
            pass
        self.now = 100.0
        assert not wd.check()
        assert fired == []

    def test_reentrant_refresh(self):
        self.now = 0.0
        wd, fired = self._wd(timeout=5.0, warmup=5.0)
        with wd.guard("pump"):
            for _ in range(10):
                self.now += 4.0
                with wd.guard("reduce"):  # progress refreshes the deadline
                    pass
                assert not wd.check()
            # inner exits must NOT disarm the outer guard
            self.now += 6.0
            assert wd.check()
        assert len(fired) == 1

    def test_warmup_allowance_first_entry_per_phase(self):
        self.now = 0.0
        wd, fired = self._wd(timeout=5.0, warmup=60.0)
        with wd.guard("pump"):  # first entry: cold-compile allowance
            self.now = 50.0
            assert not wd.check()
        with wd.guard("pump"):  # warmed: normal timeout
            self.now = 56.0
            assert wd.check()
        assert fired == ["pump"]

    def test_threaded_fires_for_real(self):
        import threading

        fired = threading.Event()
        wd = HangWatchdog(
            0.05, lambda phase: fired.set(), warmup_s=0.05, poll_s=0.01
        )
        try:
            with wd.guard("pump"):
                assert fired.wait(2.0)
        finally:
            wd.stop()

    def test_validation(self):
        with pytest.raises(ValueError):
            HangWatchdog(0.0, lambda p: None, thread=False)


# --- supervisor wiring -------------------------------------------------------


def _sup(tmp_path, threshold=2, nproc=2, **kw):
    heal = SelfHealPolicy(
        threshold, nproc, min_processes=1,
        probe_after_s=5.0, probe_window_s=3.0,
    )
    return DistributedJobSupervisor(
        ["--checkpointDir", str(tmp_path / "ck")], nproc,
        run_dir=str(tmp_path / "run"), selfheal=heal, **kw,
    )


class TestSupervisorWiring:
    def test_selfheal_requires_checkpoint_dir(self, tmp_path):
        heal = SelfHealPolicy(1, 2)
        with pytest.raises(ValueError, match="slotStrikes"):
            DistributedJobSupervisor(
                ["--trainingData", "x.jsonl"], 2, selfheal=heal,
                run_dir=str(tmp_path),
            )

    def test_flags_reject_strikes_without_ckpt(self):
        from omldm_tpu.runtime.supervisor import supervise_from_flags

        with pytest.raises(SystemExit, match="slotStrikes"):
            supervise_from_flags({"slotStrikes": "2", "processes": "2"})

    def test_worker_argv_arms_channels_and_gauge(self, tmp_path):
        sup = _sup(tmp_path)
        argv = sup._worker_argv(0, 9999, restore=False)
        assert "--heartbeatDir" in argv
        assert "--rescaleSignalDir" in argv
        assert argv[argv.index("--fleetDegraded") + 1] == "0"
        sup.selfheal.degraded_by = 1
        sup.nproc = 1
        argv = sup._worker_argv(0, 9999, restore=True)
        assert argv[argv.index("--fleetDegraded") + 1] == "1"

    def test_classify_exits_blames_wedged_peer(self, tmp_path):
        sup = _sup(tmp_path)
        os.makedirs(sup.hb_dir, exist_ok=True)
        exc = sup._classify_exits([HANG_EXIT, None], [0])
        assert exc.failed == [1]
        assert exc.kinds == {1: HANG}
        assert "blaming wedged process 1" in exc.cause

    def test_classify_exits_launch_vs_crash(self, tmp_path):
        sup = _sup(tmp_path)
        os.makedirs(sup.hb_dir, exist_ok=True)
        with open(os.path.join(sup.hb_dir, "proc0.hb"), "w") as f:
            f.write("123.0 0")
        exc = sup._classify_exits([3, 3], [0, 1])
        assert exc.kinds == {0: CRASH, 1: LAUNCH}  # proc1 never beat
        assert exc.kind() == LAUNCH

    def test_strikes_degrade_without_burning_attempts(self, tmp_path):
        sup = _sup(tmp_path, threshold=2, blackbox_dir=str(tmp_path / "bb"))
        fail = FleetFailure("p1 died", 3, [1], kinds={1: CRASH})
        assert sup._note_strikes(fail) is None       # strike 1: restart
        target = sup._note_strikes(fail)             # strike 2: degrade
        assert target == 1
        sup._apply_degrade(fail, target)
        assert sup.nproc == 1
        assert sup.failures == []                    # no attempt burned
        assert [d.to_procs for d in sup.degrades] == [1]
        kinds = [e["kind"] for e in sup.journal.events]
        assert kinds.count("strike") == 2
        assert "degrade" in kinds
        with open(os.path.join(sup.run_dir, "STRIKES")) as f:
            assert json.load(f)["degradedBy"] == 1

    def test_hang_exit_code_distinct(self):
        from omldm_tpu.runtime.supervisor import RESCALE_EXIT

        assert HANG_EXIT not in (0, RESCALE_EXIT,
                                 DistributedFaultInjector.EXIT_CODE)


# --- fault injector: hang + launch refusal -----------------------------------


class TestInjectorFaults:
    def test_hang_sigstops_once_across_incarnations(
        self, tmp_path, monkeypatch
    ):
        stops = []
        monkeypatch.setattr(
            "omldm_tpu.runtime.selfheal.sigstop_self",
            lambda: stops.append(True),
        )
        flags = {
            "hangProcess": "1", "hangAfterChunks": "3",
            "faultStateDir": str(tmp_path / "fault"),
        }
        inj = DistributedFaultInjector(flags, pid=1)
        inj.on_chunk(1)
        assert stops == []
        inj.on_chunk(2)  # chunk_idx+1 == 3: fires
        assert stops == [True]
        # a relaunched incarnation re-runs the injector: the marker file
        # keeps the hang one-shot
        inj2 = DistributedFaultInjector(flags, pid=1)
        inj2.on_chunk(5)
        assert stops == [True]

    def test_hang_other_process_inert(self, tmp_path, monkeypatch):
        stops = []
        monkeypatch.setattr(
            "omldm_tpu.runtime.selfheal.sigstop_self",
            lambda: stops.append(True),
        )
        inj = DistributedFaultInjector(
            {"hangProcess": "1", "hangAfterChunks": "1"}, pid=0
        )
        inj.on_chunk(5)
        assert stops == []

    def test_launch_refusal_counts_down(self, tmp_path, monkeypatch):
        died = []
        monkeypatch.setattr(
            DistributedFaultInjector, "_die",
            lambda self, why: died.append(why),
        )
        flags = {
            "refuseLaunchProcess": "0", "refuseLaunchCount": "2",
            "faultStateDir": str(tmp_path / "fault"),
        }
        for _ in range(3):
            DistributedFaultInjector(flags, pid=0).on_launch()
        assert len(died) == 2  # third incarnation launches fine
        DistributedFaultInjector(flags, pid=1).on_launch()
        assert len(died) == 2  # other slots unaffected


# --- dropped-write counters (ENOSPC survival) --------------------------------


class TestDroppedWriteCounters:
    def test_blackbox_dump_counts_not_raises(self, tmp_path):
        from omldm_tpu.runtime.events import EventJournal

        blocker = tmp_path / "file"
        blocker.write_text("x")
        # the "directory" is a plain file: every dump gets OSError
        j = EventJournal(cap=8, pid=0, path=str(blocker / "sub"))
        j.record("terminate", "x")
        assert j.dump() is None
        assert j.dump() is None
        assert j.write_errors == 2
        assert j.events  # ring intact

    def test_deadletter_counts_not_raises(self, tmp_path):
        from omldm_tpu.runtime.deadletter import DeadLetterSink

        blocker = tmp_path / "file"
        blocker.write_text("x")
        sink = DeadLetterSink(path=str(blocker / "sub" / "dl.jsonl"))
        sink.quarantine("training", "{bad", "malformed_json")
        sink.quarantine("training", "{bad2", "malformed_json")
        assert sink.write_errors == 1  # degrades once, loudly
        assert sink.record_count == 2  # in-memory quarantine continues

    def test_heartbeat_returns_false_not_raises(self, tmp_path):
        from omldm_tpu.runtime.distributed_job import _heartbeat

        blocker = tmp_path / "file"
        blocker.write_text("x")
        assert _heartbeat({"heartbeatDir": str(blocker / "sub")}, 0, 1) is False
        assert _heartbeat({}, 0, 1) is True  # unarmed: trivially fine
        ok_dir = tmp_path / "hb"
        assert _heartbeat({"heartbeatDir": str(ok_dir)}, 0, 1) is True


# --- distributed checkpoint integrity (sha256 + generation fallback) ---------


jax = pytest.importorskip("jax")

from omldm_tpu.config import JobConfig  # noqa: E402
from omldm_tpu.runtime.distributed_job import (  # noqa: E402
    DistributedStreamJob,
    _file_sha256,
)

CREATE = json.dumps({
    "id": 0, "request": "Create",
    "learner": {"name": "PA", "hyperParameters": {"C": 1.0},
                "dataStructure": {"nFeatures": DIM}},
    "preProcessors": [],
    "trainingConfiguration": {"protocol": "Synchronous", "syncEvery": 1},
})


def _job():
    job = DistributedStreamJob(JobConfig(batch_size=8, test_set_size=16))
    job.sync_requests([CREATE])
    rng = np.random.RandomState(0)
    x = rng.randn(64, DIM).astype(np.float32)
    job.handle_partition_rows(x, (x[:, 0] > 0).astype(np.float32))
    job.pump()
    return job


class TestCheckpointIntegrity:
    def test_digests_recorded(self, tmp_path):
        job = _job()
        d = job.save_checkpoint(str(tmp_path), 100)
        manifest = json.load(open(os.path.join(d, "manifest.json")))
        assert manifest["digests"]["fleet_0.npz"] == _file_sha256(
            os.path.join(d, "fleet_0.npz")
        )
        meta = json.load(open(os.path.join(d, "proc0.json")))
        assert meta["sha256"] == _file_sha256(os.path.join(d, "proc0.npz"))

    def test_digest_mismatch_rejected(self, tmp_path, capsys):
        job = _job()
        d = job.save_checkpoint(str(tmp_path), 100)
        # same-length corruption: np.load may well decode this fine —
        # only the digest catches it
        path = os.path.join(d, "fleet_0.npz")
        data = bytearray(open(path, "rb").read())
        data[len(data) // 2] ^= 0xFF
        open(path, "wb").write(bytes(data))
        assert job._validate_checkpoint(d) is None
        assert "sha256 mismatch" in capsys.readouterr().err

    def test_corrupt_generation_falls_back_to_previous(self, tmp_path):
        job = _job()
        job.save_checkpoint(str(tmp_path), 100)
        d2 = job.save_checkpoint(str(tmp_path), 200)
        path = os.path.join(d2, "proc0.npz")
        data = bytearray(open(path, "rb").read())
        data[len(data) // 2] ^= 0xFF
        open(path, "wb").write(bytes(data))
        restored = _job()
        cur = restored.restore_checkpoint(str(tmp_path))
        assert cur == 100  # the previous surviving generation
        assert restored.pipelines  # pipelines redeployed from it

    def test_predigest_snapshots_still_restore(self, tmp_path):
        job = _job()
        d = job.save_checkpoint(str(tmp_path), 100)
        # strip the digests (an old-format snapshot): load checks remain
        manifest = json.load(open(os.path.join(d, "manifest.json")))
        manifest.pop("digests")
        json.dump(manifest, open(os.path.join(d, "manifest.json"), "w"))
        meta = json.load(open(os.path.join(d, "proc0.json")))
        meta.pop("sha256")
        json.dump(meta, open(os.path.join(d, "proc0.json"), "w"))
        restored = _job()
        assert restored.restore_checkpoint(str(tmp_path)) == 100


class TestRecoveryGenerationFallback:
    def _ckpt_job(self, tmp_path):
        from omldm_tpu.runtime import StreamJob
        from omldm_tpu.runtime.job import REQUEST_STREAM, TRAINING_STREAM

        cfg = JobConfig(
            parallelism=2, batch_size=16, test_set_size=16,
            checkpointing=True, checkpoint_dir=str(tmp_path / "ck"),
            check_interval_ms=0,
        )
        job = StreamJob(cfg)
        rng = np.random.RandomState(0)
        events = [(REQUEST_STREAM, json.dumps({
            "id": 0, "request": "Create",
            "learner": {"name": "PA", "hyperParameters": {"C": 1.0}},
            "trainingConfiguration": {"protocol": "Synchronous",
                                      "syncEvery": 2},
        }))] + [
            (TRAINING_STREAM, json.dumps({
                "numericalFeatures": [float(v) for v in rng.randn(5)],
                "target": 1.0,
            }))
            for _ in range(64)
        ]
        return job, events

    def test_torn_latest_falls_back(self, tmp_path):
        from omldm_tpu.runtime.recovery import recover_job

        job, events = self._ckpt_job(tmp_path)
        for stream, payload in events:
            job.process_event(stream, payload)
            job.checkpoint_manager.maybe_save(job)
        candidates = job.checkpoint_manager.candidate_paths()
        assert len(candidates) >= 2
        # torn newest generation (truncated pickle)
        with open(candidates[0], "r+b") as f:
            f.truncate(os.path.getsize(candidates[0]) // 2)
        recovered, path = recover_job(job)
        assert path == candidates[1]  # the previous surviving generation
        assert recovered.events_processed > 0

    def test_all_torn_degrades_to_fresh(self, tmp_path):
        from omldm_tpu.runtime.recovery import recover_job

        job, events = self._ckpt_job(tmp_path)
        for stream, payload in events:
            job.process_event(stream, payload)
            job.checkpoint_manager.maybe_save(job)
        for c in job.checkpoint_manager.candidate_paths():
            with open(c, "r+b") as f:
                f.truncate(1)
        recovered, path = recover_job(job)
        assert path is None
        assert recovered.events_processed == 0  # fresh, offset 0


# --- the full loop (slow) ----------------------------------------------------


@pytest.mark.slow
def test_selfheal_sigstop_degrade_probe_heal(tmp_path):
    """A SIGSTOP'd worker wedges its peer's collective: the survivor exits
    HANG_EXIT within --collectiveTimeoutMs, the supervisor blames the
    silent slot, shrinks the fleet 2 -> 1 through restore-with-rescale
    (exact row conservation, exactly-once forecasts), probes back to 2
    once quiet, and heals — with the classify -> strike -> degrade ->
    probe chain journaled."""
    sys.path.insert(0, TESTS)
    import fskafka

    broker = tmp_path / "broker"
    os.environ["FSKAFKA_DIR"] = str(broker)
    n_rows, n_fore = 6000, 0
    try:
        rng = np.random.RandomState(0)
        w = rng.randn(12)
        for i in range(n_rows):
            x = np.round(rng.randn(12), 6)
            if i % 20 == 0:
                n_fore += 1
                line = json.dumps({
                    "numericalFeatures": [float(v) for v in x],
                    "operation": "forecasting",
                })
            else:
                line = json.dumps({
                    "numericalFeatures": [float(v) for v in x],
                    "target": float(x @ w > 0), "operation": "training",
                })
            fskafka.append("trainingData", line, partition=i % 4)
        fskafka.append("requests", json.dumps({
            "id": 0, "request": "Create",
            "learner": {"name": "PA", "hyperParameters": {"C": 1.0},
                        "dataStructure": {"nFeatures": 12}},
            "trainingConfiguration": {
                "protocol": "Synchronous", "syncEvery": 1,
            },
        }))
    finally:
        os.environ.pop("FSKAFKA_DIR", None)

    perf = tmp_path / "perf.jsonl"
    preds = tmp_path / "preds.jsonl"
    blackbox = tmp_path / "blackbox"
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["FSKAFKA_DIR"] = str(broker)
    out = subprocess.run(
        [sys.executable, "-m", "omldm_tpu.runtime.distributed_job",
         "--supervise", "true", "--processes", "2",
         "--slotStrikes", "1", "--minProcesses", "1",
         "--probeAfterMs", "2000", "--probeWindowMs", "1500",
         "--collectiveTimeoutMs", "5000",
         "--killDeadlineMs", "1000",
         "--hangProcess", "1", "--hangAfterChunks", "6",
         "--faultStateDir", str(tmp_path / "fault"),
         "--flightRecorder", "on", "--blackboxPath", str(blackbox),
         "--kafkaBrokers", "fs://local", "--workerBoot", FSKAFKA_BOOT,
         "--checkpointDir", str(tmp_path / "ckpts"),
         "--checkpointEvery", "2",
         "--chunkRows", "100", "--kafkaPollMs", "50",
         "--idleWindows", "60",
         "--batchSize", "64", "--testSetSize", "32",
         "--restartAttempts", "2", "--restartDelayMs", "50",
         "--performanceOut", str(perf), "--predictionsOut", str(preds)],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=420,
    )
    assert out.returncode == 0, out.stderr[-4000:]
    err = out.stderr
    # the chain, in the log
    assert "injected hang: SIGSTOP" in err
    assert "collective watchdog: no progress" in err  # survivor HANG_EXIT
    assert "blaming wedged process 1" in err
    assert "degrading fleet 2 -> 1" in err
    assert "redistributing a 2-process snapshot" in err
    assert "probing back 1 -> 2" in err
    assert "re-expansion probe" in err
    assert "fleet healed at 2" in err
    # conservation + exactly-once across hang, degrade and probe
    report = json.loads(perf.read_text().strip())
    [s] = report["statistics"]
    assert s["fitted"] + report["holdout"]["0"] == n_rows - n_fore
    # the fleet finishes at width 2: per-process prediction files
    pred_files = sorted(
        f for f in os.listdir(tmp_path) if f.startswith("preds.jsonl")
    )
    payloads = [
        json.loads(l)
        for f in pred_files
        for l in open(tmp_path / f).read().splitlines()
    ]
    assert len(payloads) == n_fore
    assert report["fleetProcesses"] == 2   # back at full width
    assert report["fleetDegraded"] == 0    # healed
    # the run-end bundle carries the decision chain in causal order
    bundles = sorted(
        f for f in os.listdir(blackbox) if f.startswith("incident-")
    )
    assert bundles
    final = json.load(open(blackbox / bundles[-1]))
    kinds = [e["kind"] for e in final["timeline"]]
    chain = [k for k in kinds if k in ("strike", "degrade", "probe")]
    assert chain[:3] == ["strike", "degrade", "probe"]
    # the worker-side hang event survives in a bundle (the degrade-time
    # gather, before the relaunch overwrote the rings)
    all_kinds = set()
    for b in bundles:
        all_kinds.update(
            e["kind"] for e in json.load(open(blackbox / b))["timeline"]
        )
    assert "hang" in all_kinds
