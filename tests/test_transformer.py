"""Transformer family: forward shapes, training, and sharded-equivalence.

The sharded-equivalence tests are the load-bearing ones: a SeqTrainer step
over a real (dp, sp, tp) mesh must match the single-device step bit-for-bit
(up to fp tolerance) — this pins down ring attention, the Megatron psums,
the MoE all_to_all dispatch, and the gradient psums inserted by shard_map's
varying-axis tracking, all at once.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from omldm_tpu.models.transformer import (
    AxisSpec,
    TransformerConfig,
    init_transformer,
    lm_loss,
    transformer_forward,
)
from omldm_tpu.parallel.seq_trainer import SeqTrainer, make_seq_mesh

CFG = TransformerConfig(
    vocab_size=32, d_model=16, n_heads=2, n_layers=2, d_ff=32,
    max_len=64, objective="lm",
)


def _copy_batch(rng, b, l, vocab):
    """Repeating-pattern sequences: next token is predictable."""
    base = rng.randint(1, vocab, size=(b, 4))
    toks = np.tile(base, (1, l // 4 + 1))[:, : l + 1]
    return (
        toks[:, :-1].astype(np.int32),
        toks[:, 1:].astype(np.int32),
        np.ones((b, l), np.float32),
    )


def test_forward_shapes():
    params = init_transformer(CFG, jax.random.PRNGKey(0))
    tokens = jnp.zeros((2, 16), jnp.int32)
    logits = transformer_forward(CFG, params, tokens)
    assert logits.shape == (2, 16, CFG.vocab_size)

    ccfg = TransformerConfig(
        vocab_size=32, d_model=16, n_heads=2, n_layers=1, d_ff=32,
        max_len=64, objective="classify", n_classes=3, causal=False,
    )
    cparams = init_transformer(ccfg, jax.random.PRNGKey(0))
    out = transformer_forward(ccfg, cparams, tokens)
    assert out.shape == (2, 3)


def test_moe_forward_matches_shapes_and_is_finite():
    cfg = TransformerConfig(
        vocab_size=32, d_model=16, n_heads=2, n_layers=1, d_ff=32,
        max_len=64, n_experts=4,
    )
    params = init_transformer(cfg, jax.random.PRNGKey(0))
    tokens = jnp.zeros((2, 16), jnp.int32)
    logits = transformer_forward(cfg, params, tokens)
    assert logits.shape == (2, 16, 32)
    assert bool(jnp.all(jnp.isfinite(logits)))


def test_single_device_training_learns_copy_task():
    rng = np.random.RandomState(0)
    trainer = SeqTrainer(CFG, mesh=make_seq_mesh(1, 1, 1), lr=3e-3, seed=1)
    tokens, targets, mask = _copy_batch(rng, 8, 16, CFG.vocab_size)
    first = float(np.asarray(trainer.step(tokens, targets, mask)))
    for _ in range(60):
        loss = trainer.step(tokens, targets, mask)
    assert float(np.asarray(loss)) < first * 0.5
    assert trainer.fitted == 61 * 8 * 16


# pre-vma jax falls back to check_rep=False shard_map with MANUAL gradient
# psums (jaxcompat.grad_sync): the sharded step then matches the single
# device only to ~1e-3 (reduction reorder amplified by Adam), not this
# test's 1e-4 envelope. The classify/ulysses/remat/checkpoint sharded
# tests pass the tight envelope on the fallback too and stay live, so the
# compat path's correctness remains pinned in tier-1.
_vma_exact = pytest.mark.skipif(
    not __import__(
        "omldm_tpu.utils.jaxcompat", fromlist=["auto_grad_sync"]
    ).auto_grad_sync(),
    reason="pre-vma jax: manual grad_sync reorder exceeds the 1e-4 "
    "equality envelope (classify/ulysses/remat/ckpt cases still pin "
    "the fallback path)",
)


@pytest.mark.parametrize("dp,sp,tp", [
    pytest.param(2, 2, 2, marks=_vma_exact),
    pytest.param(1, 4, 2, marks=_vma_exact),
    pytest.param(4, 1, 2, marks=_vma_exact),
    pytest.param(2, 4, 1, marks=_vma_exact),
])
def test_sharded_step_matches_single_device(dp, sp, tp):
    rng = np.random.RandomState(1)
    tokens, targets, mask = _copy_batch(rng, 4, 16, CFG.vocab_size)

    ref = SeqTrainer(CFG, mesh=make_seq_mesh(1, 1, 1), lr=1e-2, seed=3)
    shr = SeqTrainer(CFG, mesh=make_seq_mesh(dp, sp, tp), lr=1e-2, seed=3)
    for _ in range(3):
        l_ref = ref.step(tokens, targets, mask)
        l_shr = shr.step(tokens, targets, mask)
    np.testing.assert_allclose(
        float(np.asarray(l_ref)), float(np.asarray(l_shr)), atol=1e-4
    )
    p_ref, p_shr = ref.host_params(), shr.host_params()
    flat_ref = jax.tree_util.tree_leaves(p_ref)
    flat_shr = jax.tree_util.tree_leaves(p_shr)
    for a, b in zip(flat_ref, flat_shr):
        np.testing.assert_allclose(a, b, atol=2e-4)


def test_moe_expert_parallel_matches_dense_dispatch():
    """EP all_to_all routing == single-device dense dispatch when capacity
    is ample (no token drops)."""
    cfg = TransformerConfig(
        vocab_size=32, d_model=16, n_heads=2, n_layers=1, d_ff=32,
        max_len=64, n_experts=4, capacity_factor=4.0,
    )
    rng = np.random.RandomState(2)
    tokens, targets, mask = _copy_batch(rng, 4, 16, cfg.vocab_size)
    ref = SeqTrainer(cfg, mesh=make_seq_mesh(1, 1, 1), lr=1e-2, seed=5)
    shr = SeqTrainer(cfg, mesh=make_seq_mesh(4, 2, 1), lr=1e-2, seed=5)
    for _ in range(2):
        l_ref = ref.step(tokens, targets, mask)
        l_shr = shr.step(tokens, targets, mask)
    np.testing.assert_allclose(
        float(np.asarray(l_ref)), float(np.asarray(l_shr)), atol=1e-4
    )


@pytest.mark.parametrize("dp,sp,tp", [(2, 1, 2), (2, 2, 1), (1, 4, 2)])
def test_classify_objective_sharded(dp, sp, tp):
    """classify must sequence-shard its tokens too — replicating them over
    sp would double-count keys in ring attention."""
    cfg = TransformerConfig(
        vocab_size=32, d_model=16, n_heads=2, n_layers=1, d_ff=32,
        max_len=64, objective="classify", n_classes=2, causal=False,
    )
    rng = np.random.RandomState(3)
    tokens = rng.randint(0, 32, size=(8, 16)).astype(np.int32)
    labels = (tokens.sum(axis=1) % 2).astype(np.int32)
    ref = SeqTrainer(cfg, mesh=make_seq_mesh(1, 1, 1), lr=1e-2, seed=7)
    shr = SeqTrainer(cfg, mesh=make_seq_mesh(dp, sp, tp), lr=1e-2, seed=7)
    for _ in range(3):
        l_ref = ref.step(tokens, labels)
        l_shr = shr.step(tokens, labels)
    np.testing.assert_allclose(
        float(np.asarray(l_ref)), float(np.asarray(l_shr)), atol=1e-4
    )


def test_step_many_matches_sequential_steps():
    """One scanned launch over T batches == T step() calls (dense + mesh)."""
    rng = np.random.RandomState(5)
    batches = [_copy_batch(rng, 4, 16, CFG.vocab_size) for _ in range(4)]
    seq = SeqTrainer(CFG, mesh=make_seq_mesh(2, 2, 2), lr=1e-2, seed=11)
    for b in batches:
        seq.step(*b)
    many = SeqTrainer(CFG, mesh=make_seq_mesh(2, 2, 2), lr=1e-2, seed=11)
    losses = many.step_many(
        np.stack([b[0] for b in batches]),
        np.stack([b[1] for b in batches]),
        np.stack([b[2] for b in batches]),
    )
    assert losses.shape == (4,)
    assert many.fitted == seq.fitted == 4 * 4 * 16
    for a, b in zip(
        jax.tree_util.tree_leaves(seq.host_params()),
        jax.tree_util.tree_leaves(many.host_params()),
    ):
        np.testing.assert_allclose(a, b, atol=2e-4)


def test_remat_matches_no_remat():
    """jax.checkpoint rematerialization changes memory, not math — sharded
    training with remat equals the plain single-device run."""
    rng = np.random.RandomState(7)
    tokens, targets, mask = _copy_batch(rng, 4, 16, CFG.vocab_size)
    plain = SeqTrainer(CFG, mesh=make_seq_mesh(1, 1, 1), lr=1e-2, seed=17)
    rcfg = dataclasses.replace(CFG, remat=True)
    remat = SeqTrainer(rcfg, mesh=make_seq_mesh(2, 2, 2), lr=1e-2, seed=17)
    for _ in range(3):
        l_a = plain.step(tokens, targets, mask)
        l_b = remat.step(tokens, targets, mask)
    np.testing.assert_allclose(
        float(np.asarray(l_a)), float(np.asarray(l_b)), atol=1e-4
    )
    for a, b in zip(
        jax.tree_util.tree_leaves(plain.host_params()),
        jax.tree_util.tree_leaves(remat.host_params()),
    ):
        np.testing.assert_allclose(a, b, atol=2e-4)


def test_fused_loss_chunk_matches_unfused():
    """The fused chunked cross-entropy (loss_chunk > 0: per-chunk head
    matmul + checkpointed logsumexp, no [T, V] logits tensor) equals the
    whole-tensor log_softmax path in loss AND gradients to f32 reduction
    order — including ragged chunking and masked tokens."""
    import jax.flatten_util as fu

    from omldm_tpu.models.transformer import lm_loss

    rng = np.random.RandomState(11)
    tokens, targets, _ = _copy_batch(rng, 3, 24, CFG.vocab_size)
    mask = jnp.asarray((rng.rand(3, 24) > 0.2).astype(np.float32))
    params = init_transformer(CFG, jax.random.PRNGKey(3))
    fused_cfg = dataclasses.replace(CFG, loss_chunk=13)  # ragged: 72 % 13 != 0

    l_plain = lm_loss(CFG, params, tokens, targets, mask)
    l_fused = lm_loss(fused_cfg, params, tokens, targets, mask)
    np.testing.assert_allclose(
        float(l_plain), float(l_fused), rtol=1e-6, atol=1e-6
    )
    g_plain, _ = fu.ravel_pytree(
        jax.grad(lambda p: lm_loss(CFG, p, tokens, targets, mask))(params)
    )
    g_fused, _ = fu.ravel_pytree(
        jax.grad(lambda p: lm_loss(fused_cfg, p, tokens, targets, mask))(params)
    )
    np.testing.assert_allclose(
        np.asarray(g_plain), np.asarray(g_fused), rtol=1e-4, atol=1e-6
    )


def test_fused_loss_trains_sharded():
    """The fused loss composes with the sharded trainer (dp x sp x tp):
    same loss trajectory as the unfused single-device run."""
    rng = np.random.RandomState(12)
    tokens, targets, mask = _copy_batch(rng, 4, 16, CFG.vocab_size)
    plain = SeqTrainer(CFG, mesh=make_seq_mesh(1, 1, 1), lr=1e-2, seed=5)
    fcfg = dataclasses.replace(CFG, loss_chunk=16)
    fused = SeqTrainer(fcfg, mesh=make_seq_mesh(2, 2, 2), lr=1e-2, seed=5)
    for _ in range(3):
        l_a = plain.step(tokens, targets, mask)
        l_b = fused.step(tokens, targets, mask)
    np.testing.assert_allclose(
        float(np.asarray(l_a)), float(np.asarray(l_b)), atol=1e-4
    )


def test_bf16_mixed_precision_trains_and_matches_sharded():
    """bf16 compute keeps fp32 master weights: training works, and the
    sharded step still equals single-device (same bf16 compute path)."""
    cfg = TransformerConfig(
        vocab_size=32, d_model=16, n_heads=2, n_layers=2, d_ff=32,
        max_len=64, dtype=jnp.bfloat16,
    )
    rng = np.random.RandomState(6)
    tokens, targets, mask = _copy_batch(rng, 4, 16, cfg.vocab_size)
    ref = SeqTrainer(cfg, mesh=make_seq_mesh(1, 1, 1), lr=1e-2, seed=13)
    shr = SeqTrainer(cfg, mesh=make_seq_mesh(2, 2, 2), lr=1e-2, seed=13)
    first = float(np.asarray(ref.step(tokens, targets, mask)))
    shr.step(tokens, targets, mask)
    for _ in range(30):
        l_ref = ref.step(tokens, targets, mask)
        l_shr = shr.step(tokens, targets, mask)
    assert float(np.asarray(l_ref)) < first * 0.7  # learns despite bf16
    # bf16 accumulation differs slightly shard-vs-single; loose tolerance
    np.testing.assert_allclose(
        float(np.asarray(l_ref)), float(np.asarray(l_shr)), atol=0.15
    )
    # master weights stay fp32
    assert ref.host_params()["embed"].dtype == np.float32


def test_lm_loss_perfect_prediction_near_zero():
    """Sanity: a model that always predicts the right token has ~0 loss —
    checked by training until the copy task is nearly solved."""
    rng = np.random.RandomState(4)
    trainer = SeqTrainer(CFG, mesh=make_seq_mesh(1, 1, 1), lr=5e-3, seed=9)
    tokens, targets, mask = _copy_batch(rng, 8, 16, CFG.vocab_size)
    for _ in range(200):
        loss = trainer.step(tokens, targets, mask)
    assert float(np.asarray(loss)) < 0.5


def test_moe_dense_applies_capacity_like_ep():
    """The dense path must enforce the SAME per-expert capacity rule as the
    EP path: under routing imbalance, over-capacity tokens drop to the
    residual in BOTH deployments (a model trained dense and served
    expert-parallel computes the same function)."""
    import jax
    import jax.numpy as jnp

    from omldm_tpu.models.transformer import (
        _moe_block_dense,
        _moe_block_ep,
    )
    from jax.sharding import Mesh, PartitionSpec as P

    rng = np.random.RandomState(0)
    d, f, e = 8, 16, 4
    b, lc = 2, 8  # T = 16 tokens
    layer = {
        # router rigged so EVERY token picks expert 0 -> maximal imbalance
        "router": jnp.asarray(
            np.concatenate(
                [np.full((d, 1), 5.0), np.zeros((d, e - 1))], axis=1
            ).astype(np.float32)
        ),
        "w1": jnp.asarray(rng.randn(e, d, f).astype(np.float32) * 0.1),
        "w2": jnp.asarray(rng.randn(e, f, d).astype(np.float32) * 0.1),
    }
    x = jnp.asarray(np.abs(rng.randn(b, lc, d)).astype(np.float32) * 0.5)

    cf = 1.0  # cap = T/E = 4 slots on expert 0; 12 of 16 tokens must drop
    out_dense = _moe_block_dense(layer, x, cf)
    t = out_dense.reshape(-1, d)
    nonzero = np.count_nonzero(np.abs(np.asarray(t)).sum(axis=1) > 1e-9)
    assert nonzero == 4, f"expected cap=4 kept tokens, got {nonzero}"

    # ep=1 EP path == dense path exactly, including the dropped tokens
    from omldm_tpu.utils.jaxcompat import shard_map as _compat_shard_map

    mesh = Mesh(np.array(jax.devices()[:1]), ("ep",))
    ep_fn = _compat_shard_map(
        lambda xx: _moe_block_ep(layer, xx, "ep", cf),
        mesh=mesh,
        in_specs=P(),
        out_specs=P(),
        check_vma=False,
    )
    out_ep = ep_fn(x)
    np.testing.assert_allclose(
        np.asarray(out_dense), np.asarray(out_ep), atol=1e-5
    )
