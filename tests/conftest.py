"""Test configuration: force an 8-device virtual CPU mesh.

Multi-chip hardware is not available in CI; sharding/protocol tests run on 8
virtual CPU devices (the TPU-native analogue of the reference's manual
16-subtask workstation runs, hs_err_pid77107.log:21).

NOTE: this environment ships a jax build where the ``JAX_PLATFORMS`` env var
is overridden by the platform plugin ('axon' TPU); only
``jax.config.update("jax_platforms", ...)`` reliably selects the backend, and
``XLA_FLAGS`` must be set before jax initializes its CPU client.
"""

import os
import re

flags = os.environ.get("XLA_FLAGS", "")
# replace (not merely keep) any preset device count: the suite requires 8
flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "", flags)
os.environ["XLA_FLAGS"] = (
    flags + " --xla_force_host_platform_device_count=8"
).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

assert len(jax.devices()) == 8, (
    f"expected 8 virtual CPU devices, got {jax.devices()}"
)
