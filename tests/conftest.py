"""Test configuration: force an 8-device virtual CPU mesh.

Multi-chip hardware is not available in CI; sharding/protocol tests run on 8
virtual CPU devices (the TPU-native analogue of the reference's manual
16-subtask workstation runs, hs_err_pid77107.log:21). Must set env before jax
import anywhere in the process.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platform_name", "cpu")
