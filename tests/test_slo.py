"""SLO evaluator (runtime/slo.py).

Pins, per ISSUE 19 satellite 4: every budget breach is individually
triggerable and produces a reason-coded failing report —

- ``HEALTHY_LOSS``     a healthy tenant under-produces;
- ``DUPLICATE_OUTPUT`` over-production or a tenant that never existed;
- ``STRANDED_ROWS``    rows left behind at terminate;
- ``HEAL_TIMEOUT``     a slow heal, or fewer heals than scheduled;
- ``SHED_SCOPE``       shed charged outside the allowed set;
- ``P99_BUDGET``       serve p99 over budget (measured).

Plus the report split: measured gates stay out of the deterministic
core, the core digest is stable across evaluations, and the
flight-recorder heal-time extraction (restart -> HEAL / first worker
event, supersede rule) behaves.
"""

import pytest

from omldm_tpu.runtime.slo import (
    DUPLICATE_OUTPUT,
    HEAL_TIMEOUT,
    HEALTHY_LOSS,
    P99_BUDGET,
    SHED_SCOPE,
    STRANDED_ROWS,
    SLOBudgets,
    count_prediction_lines,
    evaluate,
    heal_times_from_events,
    p99_from_report,
    shed_from_report,
    stranded_from_report,
)

# a clean baseline every breach test perturbs ONE axis of
EXPECTED = {0: 10, 1: 10, 2: 5}
ACTUAL = {0: 10, 1: 10, 2: 5}
HEALTHY = [0, 1]


def _eval(**kw):
    base = dict(
        expected=dict(EXPECTED),
        actual=dict(ACTUAL),
        healthy=list(HEALTHY),
        stranded_rows=0,
        shed_by_tenant={},
        fingerprint="f" * 64,
        seed=7,
    )
    budgets = kw.pop("budgets", None) or SLOBudgets(
        allow_shed_tenants=[2], max_stranded_rows=0,
    )
    base.update(kw)
    return evaluate(budgets, **base)


def _failing(report):
    return {c.reason for c in report.failing()}


class TestCleanRun:
    def test_baseline_passes(self):
        rep = _eval()
        assert rep.passed
        assert rep.failing() == []

    def test_core_digest_stable_and_fingerprinted(self):
        a, b = _eval(), _eval()
        assert a.core_digest() == b.core_digest()
        assert a.deterministic_core()["fingerprint"] == "f" * 64
        assert _eval(fingerprint="0" * 64).core_digest() != a.core_digest()


# --- each breach, individually (satellite 4) ---------------------------------


class TestBreaches:
    def test_healthy_loss(self):
        rep = _eval(actual={0: 9, 1: 10, 2: 5})
        assert not rep.passed
        assert _failing(rep) == {HEALTHY_LOSS}
        detail = rep.failing()[0].detail
        assert detail["first"] == [
            {"tenant": 0, "expected": 10, "actual": 9}
        ]

    def test_unhealthy_tenant_loss_is_not_a_breach(self):
        # tenant 2 is churned (not in healthy): its under-production is
        # the Update-discard semantics, not loss
        assert _eval(actual={0: 10, 1: 10, 2: 3}).passed

    def test_duplicate_output(self):
        rep = _eval(actual={0: 10, 1: 11, 2: 5})
        assert _failing(rep) == {DUPLICATE_OUTPUT}

    def test_output_for_unknown_tenant_is_duplicate(self):
        rep = _eval(actual={**ACTUAL, 99: 1})
        assert _failing(rep) == {DUPLICATE_OUTPUT}
        assert rep.failing()[0].detail["first"][0]["tenant"] == 99

    def test_stranded_rows(self):
        rep = _eval(stranded_rows=3)
        assert _failing(rep) == {STRANDED_ROWS}
        assert rep.failing()[0].detail == {"strandedRows": 3, "budget": 0}

    def test_stranded_budget_allows_slack(self):
        budgets = SLOBudgets(allow_shed_tenants=[2], max_stranded_rows=4)
        assert _eval(budgets=budgets, stranded_rows=3).passed

    def test_shed_scope(self):
        rep = _eval(shed_by_tenant={0: 2})
        assert _failing(rep) == {SHED_SCOPE}
        assert rep.failing()[0].detail["first"] == [
            {"tenant": 0, "shed": 2}
        ]

    def test_shed_inside_scope_passes(self):
        assert _eval(shed_by_tenant={2: 100}).passed

    def test_heal_timeout_slow_heal(self):
        budgets = SLOBudgets(
            heal_after_fault_s=1.0, expected_heals=1,
            allow_shed_tenants=[2],
        )
        events = [
            {"pid": "sup", "kind": "restart", "wall": 100.0},
            {"pid": "sup", "kind": "heal", "wall": 105.0},
        ]
        rep = _eval(budgets=budgets, events=events)
        assert _failing(rep) == {HEAL_TIMEOUT}
        assert rep.failing()[0].detail["healSeconds"] == [5.0]

    def test_heal_timeout_missing_heal(self):
        # the fault storm scheduled 2 restarts; only 1 heal observed —
        # a fault that never fired proves nothing, so this FAILS
        budgets = SLOBudgets(
            heal_after_fault_s=60.0, expected_heals=2,
            allow_shed_tenants=[2],
        )
        events = [
            {"pid": "sup", "kind": "restart", "wall": 100.0},
            {"pid": "sup", "kind": "heal", "wall": 100.5},
        ]
        rep = _eval(budgets=budgets, events=events)
        assert _failing(rep) == {HEAL_TIMEOUT}

    def test_p99_budget(self):
        budgets = SLOBudgets(serve_p99_ms=10.0, allow_shed_tenants=[2])
        report = {"statistics": [
            {"pipeline": 0, "serveLatencyP99Ms": 3.0},
            {"pipeline": 1, "serveLatencyP99Ms": 25.0},
        ]}
        rep = _eval(budgets=budgets, report=report)
        assert _failing(rep) == {P99_BUDGET}
        assert rep.failing()[0].detail == {"p99Ms": 25.0, "budgetMs": 10.0}

    def test_detail_caps_offender_list(self):
        actual = {t: 0 for t in range(20)}
        rep = _eval(
            expected={t: 1 for t in range(20)}, actual=actual,
            healthy=list(range(20)),
        )
        detail = rep.failing()[0].detail
        assert detail["offenders"] == 20
        assert len(detail["first"]) == 8


# --- report split ------------------------------------------------------------


class TestReportSplit:
    def test_measured_gates_stay_out_of_the_core(self):
        budgets = SLOBudgets(
            serve_p99_ms=10.0, heal_after_fault_s=60.0, expected_heals=0,
            allow_shed_tenants=[2],
        )
        rep = _eval(budgets=budgets, report={"statistics": []}, events=[])
        core_names = {c["name"] for c in rep.deterministic_core()["checks"]}
        measured = {c.name for c in rep.checks if c.measured}
        assert measured == {"serve_p99", "heal_after_fault"}
        assert not core_names & measured

    def test_measured_breach_fails_overall_but_not_core(self):
        budgets = SLOBudgets(
            serve_p99_ms=1.0, allow_shed_tenants=[2],
        )
        slow = {"statistics": [{"pipeline": 0, "serveLatencyP99Ms": 50.0}]}
        bad = _eval(budgets=budgets, report=slow)
        ok = _eval(budgets=budgets, report={"statistics": [
            {"pipeline": 0, "serveLatencyP99Ms": 0.5}]})
        assert not bad.passed and ok.passed
        assert bad.core_digest() == ok.core_digest()

    def test_to_dict_shape(self):
        d = _eval().to_dict()
        assert d["passed"] is True
        assert d["coreDigest"]
        assert {c["name"] for c in d["deterministic"]["checks"]} == {
            "healthy_forecast_loss", "exactly_once_outputs",
            "stranded_rows", "shed_scope",
        }


# --- artifact extraction -----------------------------------------------------


class TestExtraction:
    def test_count_prediction_lines(self):
        lines = [
            '{"mlpId": 0, "value": 1.0}', "", '{"mlpId": 0, "value": 2.0}',
            '{"mlpId": 3, "value": 0.5}',
        ]
        assert count_prediction_lines(lines) == {0: 2, 3: 1}

    def test_p99_from_report_ignores_unmeasured(self):
        assert p99_from_report({"statistics": [
            {"pipeline": 0, "serveLatencyP99Ms": 0.0},
            {"pipeline": 1},
        ]}) is None
        assert p99_from_report({"statistics": [
            {"pipeline": 0, "serveLatencyP99Ms": 2.0},
            {"pipeline": 1, "serveLatencyP99Ms": 7.0},
        ]}) == 7.0

    def test_shed_from_report(self):
        assert shed_from_report({"statistics": [
            {"pipeline": 0, "forecastsShed": 0},
            {"pipeline": 1, "forecastsShed": 4},
        ]}) == {1: 4}

    def test_stranded_from_report(self):
        assert stranded_from_report({}) is None
        assert stranded_from_report(
            {"terminateAccounting": {"backlogRows": 2}}
        ) == 2
        assert stranded_from_report({"terminateAccounting": {
            "serving": 1, "paused": 2, "pressure_level": 9,
        }}) == 3


class TestHealTimes:
    def test_restart_to_heal_event(self):
        events = [
            {"pid": "sup", "kind": "restart", "wall": 10.0},
            {"pid": "sup", "kind": "heal", "wall": 11.5},
            {"pid": "sup", "kind": "restart", "wall": 20.0},
            {"pid": "sup", "kind": "heal", "wall": 20.25},
        ]
        assert heal_times_from_events(events) == [1.5, 0.25]

    def test_worker_event_closes_the_window_too(self):
        events = [
            {"pid": "sup", "kind": "restart", "wall": 10.0},
            {"pid": 0, "kind": "strike", "wall": 12.0},
        ]
        assert heal_times_from_events(events) == [2.0]

    def test_later_restart_supersedes(self):
        # the fleet never rose between the two restarts: the heal we
        # time is decision -> the fleet that actually came up
        events = [
            {"pid": "sup", "kind": "restart", "wall": 10.0},
            {"pid": "sup", "kind": "restart", "wall": 30.0},
            {"pid": "sup", "kind": "heal", "wall": 31.0},
        ]
        assert heal_times_from_events(events) == [1.0]

    def test_other_sup_events_do_not_close(self):
        events = [
            {"pid": "sup", "kind": "restart", "wall": 10.0},
            {"pid": "sup", "kind": "rescale", "wall": 11.0},
            {"pid": "sup", "kind": "heal", "wall": 12.0},
        ]
        assert heal_times_from_events(events) == [2.0]
