"""Multi-host helpers degrade correctly to single-process and build the
documented mesh/batch layouts (true multi-host needs real hosts; the layout
logic and API contracts are what is testable here)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from omldm_tpu.models.transformer import TransformerConfig
from omldm_tpu.parallel.multihost import (
    host_local_array,
    initialize_multihost,
    make_multihost_mesh,
)
from omldm_tpu.parallel.seq_trainer import SeqTrainer


def test_initialize_single_host_noop():
    pid, count = initialize_multihost()
    assert (pid, count) == (0, 1)


def test_make_mesh_default_all_dp():
    mesh = make_multihost_mesh()
    assert mesh.axis_names == ("dp", "sp", "tp")
    assert mesh.shape["dp"] == 8 and mesh.shape["sp"] == 1


def test_make_mesh_ici_shape():
    mesh = make_multihost_mesh(ici_shape=(2, 2, 2))
    assert mesh.shape == {"dp": 2, "sp": 2, "tp": 2}


def test_make_mesh_rejects_bad_shape():
    with pytest.raises(ValueError, match="must multiply"):
        make_multihost_mesh(ici_shape=(3, 1, 1))


def test_host_local_array_single_process():
    mesh = make_multihost_mesh(ici_shape=(4, 2, 1))
    x = np.arange(8 * 3, dtype=np.float32).reshape(8, 3)
    arr = host_local_array(x, mesh, P("dp", None))
    np.testing.assert_array_equal(np.asarray(arr), x)
    assert arr.sharding.spec == P("dp", None)


def test_multihost_mesh_drives_seq_trainer():
    """A mesh built by the multihost helper is a valid SeqTrainer mesh."""
    mesh = make_multihost_mesh(ici_shape=(2, 2, 2))
    cfg = TransformerConfig(
        vocab_size=32, d_model=16, n_heads=2, n_layers=1, d_ff=32, max_len=32,
    )
    tr = SeqTrainer(cfg, mesh=mesh, lr=1e-2)
    rng = np.random.RandomState(0)
    tokens = rng.randint(0, 32, size=(4, 16)).astype(np.int32)
    loss = tr.step(tokens, np.roll(tokens, -1, 1))
    assert np.isfinite(float(np.asarray(loss)))
