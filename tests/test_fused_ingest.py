"""Fused C ingest (omldm_parse_stage) parity with the packed numpy route.

The fused loop (SPMDBridge.ingest_file) must be indistinguishable from
feeding the same file through iter_file_batches -> process_packed_batch:
same trained parameters, same fitted count, same holdout ring, same
predictions in the same order — including forecasts mid-stream, Python-
fallback lines (categorical features), invalid lines, EOS markers, and
hashed-categorical layouts.
"""

import json

import numpy as np
import pytest

from omldm_tpu.config import JobConfig
from omldm_tpu.ops.native import fast_parser_available
from omldm_tpu.runtime import StreamJob
from omldm_tpu.runtime.fast_ingest import iter_file_batches
from omldm_tpu.runtime.job import REQUEST_STREAM

pytestmark = pytest.mark.skipif(
    not fast_parser_available(), reason="native parser unavailable"
)

DIM = 12


def _create_request(protocol="Synchronous", extra=None, learner=None):
    return {
        "id": 0,
        "request": "Create",
        "learner": learner
        or {
            "name": "PA",
            "hyperParameters": {"C": 0.1},
            "dataStructure": {"nFeatures": DIM},
        },
        "preProcessors": [],
        "trainingConfiguration": {
            "protocol": protocol,
            "engine": "spmd",
            "extra": {"stageChain": 2, **(extra or {})},
        },
    }


def _write_stream(path, n=4000, dim=DIM, seed=0, specials=True):
    rng = np.random.RandomState(seed)
    w = rng.randn(dim)
    with open(path, "w") as f:
        for i in range(n):
            x = np.round(rng.randn(dim), 6)
            y = 1.0 if float(x @ w) > 0 else -1.0
            if specials and i % 97 == 13:
                f.write("EOS\n")
            if specials and i % 211 == 50:
                f.write("{bad json]\n")
            if specials and i % 89 == 7:
                # forecast row (no target)
                f.write(
                    json.dumps(
                        {
                            "numericalFeatures": [round(float(v), 6) for v in x],
                            "operation": "forecasting",
                        }
                    )
                    + "\n"
                )
                continue
            if specials and i % 131 == 29:
                # categorical features: Python-codec fallback line
                f.write(
                    json.dumps(
                        {
                            "numericalFeatures": [round(float(v), 6) for v in x],
                            "categoricalFeatures": ["red", "large"],
                            "target": y,
                            "operation": "training",
                        }
                    )
                    + "\n"
                )
                continue
            f.write(
                json.dumps(
                    {
                        "numericalFeatures": [round(float(v), 6) for v in x],
                        "target": y,
                        "operation": "training",
                    }
                )
                + "\n"
            )


def _make_job(request, parallelism=2, batch_size=64, test=True):
    preds = []
    config = JobConfig(
        parallelism=parallelism, batch_size=batch_size, test=test,
        test_set_size=32,
    )
    job = StreamJob(config)
    job.set_sinks(on_prediction=preds.append)
    job.process_event(REQUEST_STREAM, json.dumps(request))
    return job, preds


def _dim_for(request):
    hash_dims = int(
        request["trainingConfiguration"]["extra"].get("hashDims", 0)
    )
    return request["learner"]["dataStructure"]["nFeatures"] + hash_dims


def _run_packed(request, path, **job_kw):
    job, preds = _make_job(request, **job_kw)
    dim = _dim_for(request)
    hash_dims = int(
        request["trainingConfiguration"]["extra"].get("hashDims", 0)
    )
    for batch in iter_file_batches(path, dim, 1024, hash_dims):
        job.process_packed_batch(*batch)
    [bridge] = job.spmd_bridges.values()
    bridge.flush()
    return job, bridge, preds


def _run_fused(request, path, **job_kw):
    job, preds = _make_job(request, **job_kw)
    job.ensure_deployed(_dim_for(request))
    assert job.run_file_fused(path), "job should qualify for fused ingest"
    [bridge] = job.spmd_bridges.values()
    bridge.flush()
    return job, bridge, preds


def _assert_parity(request, path, **job_kw):
    job_a, bridge_a, preds_a = _run_packed(request, path, **job_kw)
    job_b, bridge_b, preds_b = _run_fused(request, path, **job_kw)
    np.testing.assert_allclose(
        np.asarray(bridge_a.trainer.global_flat_params()),
        np.asarray(bridge_b.trainer.global_flat_params()),
        rtol=1e-6, atol=1e-6,
    )
    assert bridge_a.trainer.fitted == bridge_b.trainer.fitted
    assert bridge_a.holdout_count == bridge_b.holdout_count
    assert bridge_a.test_set._n == bridge_b.test_set._n
    assert bridge_a.test_set._head == bridge_b.test_set._head
    np.testing.assert_array_equal(bridge_a.test_set._x, bridge_b.test_set._x)
    np.testing.assert_array_equal(bridge_a.test_set._y, bridge_b.test_set._y)
    assert len(preds_a) == len(preds_b)
    for pa, pb in zip(preds_a, preds_b):
        assert pa.value == pytest.approx(pb.value, rel=1e-6)


class TestFusedParity:
    def test_mixed_stream(self, tmp_path):
        path = str(tmp_path / "train.jsonl")
        _write_stream(path)
        _assert_parity(_create_request(), path)

    def test_no_holdout(self, tmp_path):
        path = str(tmp_path / "train.jsonl")
        _write_stream(path, n=2000)
        _assert_parity(_create_request(), path, test=False)

    def test_hashed_categoricals(self, tmp_path):
        path = str(tmp_path / "train.jsonl")
        _write_stream(path, n=2000)
        req = _create_request(extra={"hashDims": 4})
        _assert_parity(req, path)

    def test_ssp_paced(self, tmp_path):
        path = str(tmp_path / "train.jsonl")
        _write_stream(path, n=2000, specials=False)
        req = _create_request(
            protocol="SSP", extra={"staleness": 2, "syncEvery": 4}
        )
        _assert_parity(req, path)

    def test_plain_stream_counts(self, tmp_path):
        """No specials: every row lands in training or the holdout ring."""
        path = str(tmp_path / "train.jsonl")
        _write_stream(path, n=3000, specials=False)
        _, bridge, preds = _run_fused(_create_request(), path)
        assert not preds
        assert bridge.holdout_count == 3000
        # 20% of rows enter the ring; the ring holds the last 32
        assert bridge.test_set._n == 32
        assert bridge.trainer.fitted + bridge.test_set._n == 3000


class TestFusedQualification:
    def test_host_plane_job_does_not_qualify(self, tmp_path):
        path = str(tmp_path / "train.jsonl")
        _write_stream(path, n=100, specials=False)
        req = _create_request()
        del req["trainingConfiguration"]["engine"]
        job, _ = _make_job(req)
        job.ensure_deployed(DIM)
        assert job.fused_file_bridge() is None
        assert not job.run_file_fused(path)

    def test_fp16_feed_does_not_qualify(self, tmp_path):
        req = _create_request(extra={"feedDtype": "float16"})
        job, _ = _make_job(req)
        job.ensure_deployed(DIM)
        assert job.fused_file_bridge() is None
