"""Sparse (padded-COO) pipelines on the SPMD collective engine.

A Create with ``dataStructure.sparse`` AND ``{"engine": "spmd"}`` deploys on
:class:`SparseSPMDBridge`: the dense model vector is hub-sharded on the
mesh, each record ships only its K active features, and the streaming
contract (holdout, forecasts, termination stats, checkpoints) matches the
host-plane sparse pipeline.
"""

import json

import numpy as np
import pytest

from omldm_tpu.config import JobConfig
from omldm_tpu.runtime import StreamJob
from omldm_tpu.runtime.job import REQUEST_STREAM, TRAINING_STREAM
from omldm_tpu.runtime.spmd_bridge import SparseSPMDBridge

HASH_SPACE = 1 << 12
DIM = 3 + HASH_SPACE


def _create(protocol="Synchronous", engine=True, extra=None):
    tc = {"protocol": protocol, "syncEvery": 2, **(extra or {})}
    if engine:
        tc["engine"] = "spmd"
    return {
        "id": 0,
        "request": "Create",
        "learner": {
            "name": "PA",
            "hyperParameters": {"C": 1.0, "variant": "PA-II"},
            "dataStructure": {
                "sparse": True, "nFeatures": DIM,
                "hashSpace": HASH_SPACE, "maxNnz": 8,
            },
        },
        "preProcessors": [],
        "trainingConfiguration": tc,
    }


def _lines(n, seed=0, forecast_every=0):
    rng = np.random.RandomState(seed)
    hidden = {}
    lines = []
    for i in range(n):
        num = rng.randn(3)
        cats = [f"c{rng.randint(40)}", f"d{rng.randint(40)}"]
        m = float(num.sum())
        for j, c in enumerate(cats):
            if (j, c) not in hidden:
                hidden[(j, c)] = rng.randn() * 2.0
            m += hidden[(j, c)]
        rec = {
            "numericalFeatures": [round(float(v), 5) for v in num],
            "categoricalFeatures": cats,
        }
        if forecast_every and i % forecast_every == 3:
            rec["operation"] = "forecasting"
        else:
            rec["target"] = float(m > 0)
            rec["operation"] = "training"
        lines.append(json.dumps(rec))
    return lines


def _run_job(create, lines, parallelism=2, batch=32):
    job = StreamJob(JobConfig(
        parallelism=parallelism, batch_size=batch, test_set_size=32,
    ))
    events = [(REQUEST_STREAM, json.dumps(create))] + [
        (TRAINING_STREAM, l) for l in lines
    ]
    report = job.run(events)
    return job, report


def _run_job_events(create, lines, parallelism=2, batch=32):
    """Per-record delivery WITHOUT termination (parity-vs-file runs)."""
    job = StreamJob(JobConfig(
        parallelism=parallelism, batch_size=batch, test_set_size=32,
    ))
    events = [(REQUEST_STREAM, json.dumps(create))] + [
        (TRAINING_STREAM, l) for l in lines
    ]
    job.run(events, terminate_on_end=False)
    return job, None


class TestSparseSPMDBridge:
    def test_deploys_on_sparse_bridge_and_learns(self):
        job, report = _run_job(_create(), _lines(4000))
        [bridge] = job.spmd_bridges.values()
        assert isinstance(bridge, SparseSPMDBridge)
        [stats] = report.statistics
        assert stats.fitted > 2500
        assert stats.score > 0.75
        assert stats.bytes_shipped > 0

    def test_forecasts_served(self):
        job, report = _run_job(_create(), _lines(1200, forecast_every=50))
        assert len(job.predictions) == len(
            [l for l in _lines(1200, forecast_every=50)
             if "forecasting" in l]
        )
        assert all(np.isfinite(p.value) for p in job.predictions)

    def test_score_tracks_host_plane(self):
        """Same stream, same learner: the collective engine and the host
        plane land comparable holdout scores."""
        lines = _lines(4000)
        _, rep_spmd = _run_job(_create(engine=True), lines)
        _, rep_host = _run_job(_create(engine=False), lines)
        s_spmd = rep_spmd.statistics[0].score
        s_host = rep_host.statistics[0].score
        assert s_spmd > 0.7 and s_host > 0.7
        assert abs(s_spmd - s_host) < 0.12

    def test_ssp_requeue_conserves_rows(self):
        create = _create(
            protocol="SSP", extra={"staleness": 1, "syncEvery": 2}
        )
        lines = _lines(1500)
        job, report = _run_job(create, lines)
        [bridge] = job.spmd_bridges.values()
        [stats] = report.statistics
        # every training row either fitted or resident in the holdout ring
        assert stats.fitted + len(bridge.test_set) == 1500

    def test_bulk_coo_ingest_matches_per_record(self, tmp_path):
        """The C padded-COO file route (SparseSPMDBridge.ingest_file) is
        indistinguishable from per-record event delivery: same params,
        fitted count, holdout ring, predictions — forecasts, codec
        fallbacks and drops included."""
        from omldm_tpu.ops.native import fast_parser_available

        if not fast_parser_available():
            pytest.skip("native parser unavailable")
        lines = _lines(2500, forecast_every=90)
        lines.insert(100, "not json")
        lines.insert(700, "EOS")

        job_a, _ = _run_job_events(_create(), lines)
        [bridge_a] = job_a.spmd_bridges.values()

        path = tmp_path / "train.jsonl"
        path.write_text("\n".join(lines) + "\n")
        job_b = StreamJob(JobConfig(
            parallelism=2, batch_size=32, test_set_size=32,
        ))
        job_b.process_event(REQUEST_STREAM, json.dumps(_create()))
        job_b.ensure_deployed(DIM)
        assert job_b.run_file_fused(str(path)), "sparse fused route refused"
        [bridge_b] = job_b.spmd_bridges.values()
        bridge_a.flush()
        bridge_b.flush()
        np.testing.assert_allclose(
            np.asarray(bridge_a.trainer.global_flat_params()),
            np.asarray(bridge_b.trainer.global_flat_params()),
            rtol=1e-6, atol=1e-6,
        )
        assert bridge_a.trainer.fitted == bridge_b.trainer.fitted
        assert bridge_a.holdout_count == bridge_b.holdout_count
        assert len(bridge_a.test_set) == len(bridge_b.test_set)
        assert len(job_a.predictions) == len(job_b.predictions)
        for pa, pb in zip(job_a.predictions, job_b.predictions):
            assert pa.value == pytest.approx(pb.value, rel=1e-6)

    def test_checkpoint_roundtrip(self, tmp_path):
        from omldm_tpu.checkpoint import CheckpointManager

        job = StreamJob(JobConfig(
            parallelism=2, batch_size=32, test_set_size=32,
        ))
        events = [(REQUEST_STREAM, json.dumps(_create()))] + [
            (TRAINING_STREAM, l) for l in _lines(900)
        ]
        job.run(events, terminate_on_end=False)
        [bridge] = job.spmd_bridges.values()
        mgr = CheckpointManager(str(tmp_path / "ck"))
        mgr.save(job)
        restored = mgr.restore()
        [rbridge] = restored.spmd_bridges.values()
        assert isinstance(rbridge, SparseSPMDBridge)
        np.testing.assert_allclose(
            bridge.trainer.global_flat_params(),
            rbridge.trainer.global_flat_params(),
            rtol=1e-6,
        )
        assert rbridge.trainer.fitted == bridge.trainer.fitted
        assert len(rbridge.test_set) == len(bridge.test_set)
        assert rbridge._stage_n == bridge._stage_n
        # restored job keeps learning
        rep = restored.run(
            [(TRAINING_STREAM, l) for l in _lines(900, seed=1)]
        )
        assert rep.statistics[0].fitted > bridge.trainer.fitted


class TestFusedSparseStaging:
    """The three serial sparse file routes — numpy block staging, the fused
    C line loop (omldm_parse_stage_sparse), and MT block parse + C staging
    (omldm_stage_coo_rows) — must produce BIT-IDENTICAL staging: same
    trained params, fitted count, holdout ring and predictions. The
    overlapped route rides the same contract (≤ bit-identical, pinned
    exactly). Streams include forecasts, escaped-category fallbacks and
    DUPLICATE-HEAVY categoricals (tiny vocabularies, the hashed-collision
    case the segsum pre-combine targets)."""

    def _dup_heavy_lines(self, n, seed=7):
        """Categoricals drawn from 3-value vocabularies: most batch rows
        collide onto the same hashed slots."""
        rng = np.random.RandomState(seed)
        lines = []
        for i in range(n):
            num = [round(float(v), 5) for v in rng.randn(3)]
            cats = [f"c{rng.randint(3)}", f"d{rng.randint(3)}"]
            if i % 311 == 50:
                lines.append(json.dumps({
                    "numericalFeatures": num,
                    "categoricalFeatures": cats,
                    "operation": "forecasting",
                }))
                continue
            if i % 401 == 9:  # escaped category -> Python codec fallback
                cats[0] = 'a"b'
            lines.append(json.dumps({
                "numericalFeatures": num, "categoricalFeatures": cats,
                "target": float(rng.randint(2)), "operation": "training",
            }))
        return lines

    def _bridge(self, extra=None):
        from omldm_tpu.ops.native import fast_parser_available

        if not fast_parser_available():
            pytest.skip("native parser unavailable")
        preds = []
        job = StreamJob(JobConfig(
            parallelism=2, batch_size=32, test_set_size=32,
        ))
        job.set_sinks(on_prediction=preds.append)
        job.process_event(
            REQUEST_STREAM, json.dumps(_create(extra=extra or {}))
        )
        [bridge] = job.spmd_bridges.values()
        return bridge, preds

    def _assert_identical(self, a, b, preds_a, preds_b, label):
        assert a.trainer.fitted == b.trainer.fitted, label
        assert a.holdout_count == b.holdout_count, label
        np.testing.assert_array_equal(
            np.asarray(a.trainer.global_flat_params()),
            np.asarray(b.trainer.global_flat_params()),
            err_msg=label,
        )
        ai, av, ay = a.test_set.arrays()
        bi, bv, by = b.test_set.arrays()
        np.testing.assert_array_equal(ai, bi, err_msg=label)
        np.testing.assert_array_equal(av, bv, err_msg=label)
        np.testing.assert_array_equal(ay, by, err_msg=label)
        assert len(preds_a) == len(preds_b) > 0, label
        for pa, pb in zip(preds_a, preds_b):
            assert pa.value == pb.value, label

    def test_serial_routes_bit_identical(self, tmp_path):
        path = tmp_path / "dup.jsonl"
        path.write_text("\n".join(self._dup_heavy_lines(3000)) + "\n")
        ref, ref_p = self._bridge(
            {"sparseFusedIngest": "false", "parserThreads": 1}
        )
        ref.ingest_file(str(path))
        ref.flush()
        for label, extra in (
            ("numpy block MT", {"sparseFusedIngest": "false",
                                "parserThreads": 2}),
            ("fused line loop", {"parserThreads": 1}),
            ("MT parse + C staging", {"parserThreads": 2}),
        ):
            b, p = self._bridge(extra)
            b.ingest_file(str(path))
            b.flush()
            self._assert_identical(b, ref, p, ref_p, label)

    def test_overlapped_matches_serial_duplicate_heavy(self, tmp_path):
        path = tmp_path / "dup.jsonl"
        path.write_text("\n".join(self._dup_heavy_lines(3000)) + "\n")
        ref, ref_p = self._bridge()
        ref.ingest_file(str(path))
        ref.flush()
        for label, extra, kw in (
            ("overlapped fused line", {"parserThreads": 1}, {"depth": 2}),
            ("overlapped MT + C", {"parserThreads": 2}, {"depth": 2}),
            ("overlapped small chunks", {"parserThreads": 2},
             {"depth": 4, "chunk_bytes": 999}),
        ):
            b, p = self._bridge(extra)
            b.ingest_file_overlapped(str(path), **kw)
            b.flush()
            self._assert_identical(b, ref, p, ref_p, label)

    def test_segsum_pipeline_stays_in_twin_envelope(self, tmp_path):
        """A sparse pipeline trained with the segsum pre-combine pinned
        (dataStructure.scatterImpl) diverges from the plain-scatter run by
        <= 2e-5 per parameter on a duplicate-heavy stream — the bridge-level
        form of the ops twin tests."""
        path = tmp_path / "dup.jsonl"
        path.write_text("\n".join(self._dup_heavy_lines(2000)) + "\n")
        flats = {}
        for impl in ("scatter", "segsum"):
            create = _create()
            create["learner"]["dataStructure"]["scatterImpl"] = impl
            preds = []
            job = StreamJob(JobConfig(
                parallelism=2, batch_size=32, test_set_size=32,
            ))
            job.set_sinks(on_prediction=preds.append)
            job.process_event(REQUEST_STREAM, json.dumps(create))
            [bridge] = job.spmd_bridges.values()
            bridge.ingest_file(str(path))
            bridge.flush()
            flats[impl] = np.asarray(bridge.trainer.global_flat_params())
        np.testing.assert_allclose(
            flats["segsum"], flats["scatter"], rtol=2e-5, atol=2e-5
        )
