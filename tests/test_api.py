"""Tests for the external JSON contract (omldm_tpu.api)."""

import json

from omldm_tpu.api import (
    DataInstance,
    JobStatistics,
    QueryResponse,
    Request,
    RequestType,
    Statistics,
)
from omldm_tpu.config import JobConfig


class TestDataInstance:
    def test_parse_training_record(self):
        rec = DataInstance.from_json(
            '{"numericalFeatures": [1.0, 2.0, 3.0], "target": 1.0,'
            ' "operation": "training"}'
        )
        assert rec is not None
        assert rec.numerical_features == [1.0, 2.0, 3.0]
        assert rec.target == 1.0
        assert rec.operation == "training"

    def test_parse_forecasting_record(self):
        rec = DataInstance.from_json(
            '{"id": 7, "numericalFeatures": [0.5], "operation": "forecasting"}'
        )
        assert rec is not None
        assert rec.operation == "forecasting"
        assert rec.id == 7

    def test_drops_eos_marker(self):
        # DataInstanceParser.scala:14 drops the "EOS" marker
        assert DataInstance.from_json("EOS") is None
        assert DataInstance.from_json('"EOS"') is None

    def test_drops_invalid(self):
        assert DataInstance.from_json("not json at all {") is None
        assert DataInstance.from_json('{"operation": "training"}') is None  # no features
        assert (
            DataInstance.from_json('{"numericalFeatures": [1], "operation": "bogus"}')
            is None
        )

    def test_roundtrip(self):
        rec = DataInstance(
            numerical_features=[1.0], discrete_features=[2], target=0.0
        )
        back = DataInstance.from_json(rec.to_json())
        assert back is not None
        assert back.numerical_features == [1.0]
        assert back.discrete_features == [2]
        assert back.target == 0.0


class TestRequest:
    CREATE = {
        "id": 0,
        "request": "Create",
        "learner": {"name": "PA", "hyperParameters": {"C": 0.01}},
        "preProcessors": [{"name": "StandardScaler"}],
        "trainingConfiguration": {"protocol": "Synchronous", "HubParallelism": 2},
    }

    def test_parse_create(self):
        req = Request.from_json(json.dumps(self.CREATE))
        assert req is not None
        assert req.id == 0
        assert req.request == RequestType.CREATE
        assert req.learner.name == "PA"
        assert req.learner.hyper_parameters["C"] == 0.01
        assert req.preprocessors[0].name == "StandardScaler"
        assert req.training_configuration.protocol == "Synchronous"
        assert req.training_configuration.hub_parallelism == 2

    def test_parse_malformed_returns_none(self):
        assert Request.from_json("{") is None
        assert Request.from_json('{"request": "Create"}') is None  # no id

    def test_roundtrip(self):
        req = Request.from_json(json.dumps(self.CREATE))
        back = Request.from_json(req.to_json())
        assert back.to_dict() == req.to_dict()

    def test_default_protocol_is_asynchronous(self):
        # MLNodeGenerator.scala:28 falls back to the async protocol
        req = Request.from_json('{"id": 1, "request": "Create", "learner": {"name": "PA"}}')
        assert req.training_configuration.protocol == "Asynchronous"
        assert req.training_configuration.hub_parallelism == 1


class TestStatistics:
    def test_merge_sums_and_concatenates(self):
        a = Statistics(pipeline=0, protocol="FGM", models_shipped=3, bytes_shipped=100)
        a.extend_curve([(0.5, 100), (0.4, 300)])
        b = Statistics(pipeline=0, protocol="FGM", models_shipped=2, bytes_shipped=50)
        b.extend_curve([(0.45, 200)])
        m = a.merge(b)
        assert m.models_shipped == 5
        assert m.bytes_shipped == 150
        assert m.lcx == [100, 200, 300]  # x-sorted concatenation
        assert m.learning_curve == [0.5, 0.45, 0.4]

    def test_job_statistics_json(self):
        s = Statistics(pipeline=0, protocol="Synchronous", fitted=1000, score=0.8)
        js = JobStatistics("job", 8, 1234.5, [s])
        obj = json.loads(js.to_json())
        assert obj["jobName"] == "job"
        assert obj["parallelism"] == 8
        assert obj["statistics"][0]["fitted"] == 1000


class TestQueryResponse:
    def test_roundtrip(self):
        qr = QueryResponse(
            response_id=5, mlp_id=0, bucket=1, num_buckets=3,
            protocol="EASGD", data_fitted=10, loss=0.3, score=0.9,
        )
        back = QueryResponse.from_dict(json.loads(qr.to_json()))
        assert back.response_id == 5
        assert back.bucket == 1
        assert back.num_buckets == 3
        assert back.score == 0.9


class TestJobConfig:
    def test_reference_defaults(self):
        # DefaultJobParameters.scala:4-11
        cfg = JobConfig()
        assert cfg.parallelism == 16
        assert cfg.max_msg_params == 2000
        assert cfg.timeout_ms == 30000
        assert cfg.test_set_size == 256
        assert cfg.test is True

    def test_from_args_camel_and_snake(self):
        cfg = JobConfig.from_args(
            {"parallelism": "8", "testSetSize": "64", "test": "false"}
        )
        assert cfg.parallelism == 8
        assert cfg.test_set_size == 64
        assert cfg.test is False
