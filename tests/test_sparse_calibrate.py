"""Sparse scatter calibration: table format, lookup, dispatch wiring.

The crossover table (ops/sparse_dispatch.json) replaces round 5's guessed
``D >= 2^16`` TPU threshold: `sparse_scatter_add_auto` resolves its kernel
from the nearest measured (D, updates) grid point for the active backend.
These tests pin the table format the CI smoke run
(``python -m omldm_tpu.ops.sparse_calibrate --smoke``) regenerates, the
nearest-neighbor lookup, the merge-per-backend write, and the dispatch
precedence (env/config overrides beat the table)."""

import json

import numpy as np
import pytest

from omldm_tpu.ops import sparse_calibrate as cal
from omldm_tpu.ops.sparse import SCATTER_IMPLS, _resolve_impl


def _table(backends):
    return {"version": 1, "backends": backends}


def _entry(d, updates, winner):
    return {
        "d": d, "batch": 32, "nnz": 4, "updates": updates,
        "duplicate_factor": 1.0,
        "rates_updates_per_sec": {"scatter": 1.0, "mxu": 1.0, "segsum": 1.0},
        "winner": winner,
    }


class TestLookup:
    def test_nearest_grid_point_log2(self, tmp_path, monkeypatch):
        path = tmp_path / "table.json"
        path.write_text(json.dumps(_table({
            "cpu": {"entries": [
                _entry(1 << 12, 1 << 10, "scatter"),
                _entry(1 << 18, 1 << 10, "segsum"),
            ]},
        })))
        monkeypatch.setenv(cal.ENV_TABLE, str(path))
        assert cal.lookup_winner("cpu", 1 << 12, 1 << 10) == "scatter"
        assert cal.lookup_winner("cpu", 1 << 19, 2048) == "segsum"
        # log2-nearest: D=2^15 ties split by first-wins, D=2^16 -> segsum
        assert cal.lookup_winner("cpu", 1 << 16, 1 << 10) == "segsum"
        # unmeasured backend: None (callers fall back to the guess)
        assert cal.lookup_winner("tpu", 1 << 18, 1 << 10) is None

    def test_missing_or_corrupt_table(self, tmp_path, monkeypatch):
        monkeypatch.setenv(cal.ENV_TABLE, str(tmp_path / "absent.json"))
        assert cal.lookup_winner("cpu", 1 << 18, 1 << 10) is None
        bad = tmp_path / "bad.json"
        bad.write_text("{not json")
        monkeypatch.setenv(cal.ENV_TABLE, str(bad))
        assert cal.lookup_winner("cpu", 1 << 18, 1 << 10) is None

    def test_auto_dispatch_reads_table(self, tmp_path, monkeypatch):
        """sparse_scatter_add_auto's trace-time resolution follows the
        committed table for the active backend."""
        import jax

        backend = jax.default_backend()
        path = tmp_path / "table.json"
        path.write_text(json.dumps(_table({
            backend: {"entries": [_entry(1 << 10, 256, "segsum")]},
        })))
        monkeypatch.setenv(cal.ENV_TABLE, str(path))
        monkeypatch.delenv("OMLDM_SPARSE_SCATTER", raising=False)
        assert _resolve_impl(1 << 10, 256) == "segsum"
        # env knob beats the table
        monkeypatch.setenv("OMLDM_SPARSE_SCATTER", "scatter")
        assert _resolve_impl(1 << 10, 256) == "scatter"


class TestCalibrate:
    def test_measure_entry_covers_all_kernels(self):
        e = cal.measure_entry(256, 16, 4, steps=2)
        assert set(e["rates_updates_per_sec"]) == set(SCATTER_IMPLS)
        assert e["winner"] in SCATTER_IMPLS
        assert e["updates"] == 16 * 4
        assert e["duplicate_factor"] >= 1.0

    def test_calibrate_merges_per_backend(self, tmp_path, monkeypatch):
        """A re-calibration on one backend must not clobber another
        backend's committed section."""
        import jax

        path = tmp_path / "table.json"
        path.write_text(json.dumps(_table({
            "faux-tpu": {"entries": [_entry(1 << 18, 1 << 10, "mxu")]},
        })))
        monkeypatch.setenv(cal.ENV_TABLE, str(path))
        table = cal.calibrate([(256, 16, 4)], steps=2)
        assert "faux-tpu" in table["backends"]
        assert jax.default_backend() in table["backends"]
        on_disk = json.loads(path.read_text())
        assert set(on_disk["backends"]) == set(table["backends"])
        [e] = on_disk["backends"][jax.default_backend()]["entries"]
        assert e["winner"] in SCATTER_IMPLS

    def test_committed_table_has_cpu_section(self):
        """The repo ships a calibrated CPU section so the dispatch never
        falls back to the guess on the tier-1 host; the smoke CI run
        regenerates the same shape."""
        table = cal.load_table(cal.DEFAULT_TABLE)
        assert table is not None, "ops/sparse_dispatch.json missing/corrupt"
        cpu = table["backends"].get("cpu")
        assert cpu and cpu["entries"], "no CPU section in committed table"
        for e in cpu["entries"]:
            assert e["winner"] in SCATTER_IMPLS
            assert set(e["rates_updates_per_sec"]) == set(SCATTER_IMPLS)

    def test_tpu_guess_retired(self, tmp_path, monkeypatch):
        """The round-5 ``D >= 2^16 -> mxu`` TPU guess is retired: an
        UNCALIBRATED backend (no table section) resolves to the plain
        scatter at any D — the guessed crossover was never measured (the
        committed table's "tpu_status" annotation records the unreachable
        chip), and a number nobody measured must not steer the dispatch.
        A real TPU table section, once calibrated, still wins."""
        import jax

        from omldm_tpu.ops import sparse as sp

        monkeypatch.delenv("OMLDM_SPARSE_SCATTER", raising=False)
        monkeypatch.setenv(cal.ENV_TABLE, str(tmp_path / "absent.json"))
        monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
        assert sp._resolve_impl(1 << 20, 1 << 10) == "scatter"
        assert sp._resolve_impl(1 << 10, 1 << 10) == "scatter"
        # a measured tpu section reinstates mxu where it actually won
        path = tmp_path / "table.json"
        path.write_text(json.dumps(_table({
            "tpu": {"entries": [_entry(1 << 20, 1 << 10, "mxu")]},
        })))
        monkeypatch.setenv(cal.ENV_TABLE, str(path))
        assert sp._resolve_impl(1 << 20, 1 << 10) == "mxu"
        # the committed table records the honest no-chip annotation
        committed = cal.load_table(cal.DEFAULT_TABLE)
        status = committed.get("tpu_status")
        assert status and status["calibrated"] is False
        assert "tpu" not in committed["backends"]


class TestLearnerWiring:
    def test_sparse_pa_update_honors_scatter_override(self, monkeypatch):
        """The learner hot path reaches sparse_scatter_add_auto; pinning
        the impl via dataStructure.scatterImpl (config twin of the env
        knob) stays numerically inside the twin envelope."""
        import jax.numpy as jnp

        from omldm_tpu.api.requests import LearnerSpec
        from omldm_tpu.learners.registry import make_learner

        rng = np.random.RandomState(0)
        d, b, k = 512, 16, 6
        idx = rng.randint(0, d, size=(b, k)).astype(np.int32)
        val = rng.randn(b, k).astype(np.float32)
        y = (rng.randn(b) > 0).astype(np.float32)
        mask = np.ones(b, np.float32)
        params = {}
        for impl in ("scatter", "segsum"):
            learner = make_learner(LearnerSpec(
                "PA", hyper_parameters={"C": 0.5, "variant": "PA-II"},
                data_structure={"sparse": True, "scatterImpl": impl},
            ))
            p = learner.init(d, None)
            p, _ = learner.update(
                p, (jnp.asarray(idx), jnp.asarray(val)), jnp.asarray(y),
                jnp.asarray(mask),
            )
            params[impl] = np.asarray(p["w"])
        np.testing.assert_allclose(
            params["segsum"], params["scatter"], rtol=2e-5, atol=2e-5
        )
