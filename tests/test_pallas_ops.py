"""Pallas kernel tests (interpret mode on CPU; real lowering on TPU)."""

import jax
import jax.numpy as jnp
import numpy as np

from omldm_tpu.api.requests import LearnerSpec
from omldm_tpu.learners import PAClassifier, append_bias
from omldm_tpu.learners.registry import make_learner
from omldm_tpu.ops.pa_scan import pa_scan_update


class TestPAScanKernel:
    def _reference_scan(self, w0, x, y, mask, variant, C):
        """Textbook sequential PA for comparison."""
        w = np.asarray(w0, np.float64).copy()
        losses = []
        for i in range(x.shape[0]):
            xi = np.asarray(x[i], np.float64)
            ys = 1.0 if y[i] > 0 else -1.0
            hinge = max(0.0, 1.0 - ys * float(w @ xi))
            sq = max(float(xi @ xi), 1e-12)
            if variant == "PA":
                tau = hinge / sq
            elif variant == "PA-I":
                tau = min(C, hinge / sq)
            else:
                tau = hinge / (sq + 1.0 / (2.0 * C))
            m = float(mask[i])
            losses.append(hinge * m)
            w = w + (tau * ys * m) * xi
        total = max(float(mask.sum()), 1.0)
        return w, sum(losses) / total

    def test_matches_reference_all_variants(self):
        rng = np.random.RandomState(0)
        x = rng.randn(64, 7).astype(np.float32)
        y = rng.choice([-1.0, 1.0], 64).astype(np.float32)
        mask = np.ones(64, np.float32)
        mask[50:] = 0.0
        w0 = rng.randn(7).astype(np.float32) * 0.1
        for variant in ("PA", "PA-I", "PA-II"):
            got_w, got_loss = pa_scan_update(
                jnp.asarray(w0), jnp.asarray(x), jnp.asarray(y),
                jnp.asarray(mask), variant=variant, C=0.5, interpret=True,
            )
            exp_w, exp_loss = self._reference_scan(w0, x, y, mask, variant, 0.5)
            np.testing.assert_allclose(np.asarray(got_w), exp_w, rtol=2e-4, atol=2e-5)
            assert abs(float(got_loss) - exp_loss) < 1e-3

    def test_learner_use_pallas_flag(self):
        rng = np.random.RandomState(1)
        wtrue = rng.randn(6)
        x = rng.randn(512, 6).astype(np.float32)
        y = (x @ wtrue > 0).astype(np.float32) * 2 - 1
        learner = make_learner(
            LearnerSpec("PA", hyper_parameters={"C": 1.0, "usePallas": True})
        )
        params = learner.init(6)
        for i in range(0, 512, 64):
            params, _ = learner.update_per_record(
                params,
                jnp.asarray(x[i : i + 64]),
                jnp.asarray(y[i : i + 64]),
                jnp.ones(64),
            )
        acc = learner.score(params, jnp.asarray(x), jnp.asarray(y), jnp.ones(512))
        assert acc > 0.9

    def test_pallas_matches_scan_path(self):
        rng = np.random.RandomState(2)
        x = rng.randn(128, 5).astype(np.float32)
        y = rng.choice([-1.0, 1.0], 128).astype(np.float32)
        mask = np.ones(128, np.float32)
        plain = PAClassifier({"C": 0.3})
        fast = PAClassifier({"C": 0.3, "usePallas": True})
        p1, l1 = plain.update_per_record(
            plain.init(5), jnp.asarray(x), jnp.asarray(y), jnp.asarray(mask)
        )
        p2, l2 = fast.update_per_record(
            fast.init(5), jnp.asarray(x), jnp.asarray(y), jnp.asarray(mask)
        )
        np.testing.assert_allclose(
            np.asarray(p1["w"]), np.asarray(p2["w"]), rtol=2e-4, atol=2e-5
        )
