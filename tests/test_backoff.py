"""Direct unit tests of the shared retry/backoff helper (no real sleeping).

This is the single implementation behind every retry loop in the framework
(Kafka metadata fetches, producer sends, the job supervisors' fixed-delay
restart policies) — its semantics are pinned here so the call sites can
stay thin."""

import pytest

from omldm_tpu.utils.backoff import BackoffPolicy, with_backoff


class Clock:
    """Deterministic sleep/clock pair: sleeping advances the clock."""

    def __init__(self):
        self.now = 0.0
        self.sleeps = []

    def sleep(self, s):
        self.sleeps.append(s)
        self.now += s

    def clock(self):
        return self.now


def test_success_first_try_no_sleep():
    clk = Clock()
    calls = []
    out = with_backoff(
        lambda: calls.append(1) or "ok", attempts=5, sleep=clk.sleep
    )
    assert out == "ok"
    assert len(calls) == 1
    assert clk.sleeps == []


def test_retries_on_listed_exception_then_succeeds():
    clk = Clock()
    state = {"n": 0}

    def flaky():
        state["n"] += 1
        if state["n"] < 3:
            raise ConnectionError("transient")
        return state["n"]

    out = with_backoff(
        flaky, attempts=5, base_delay=0.2, retry_on=(ConnectionError,),
        sleep=clk.sleep,
    )
    assert out == 3
    assert clk.sleeps == [0.2, 0.2]  # fixed delay (growth=1.0, Flink-style)


def test_unlisted_exception_propagates_immediately():
    calls = []

    def boom():
        calls.append(1)
        raise ValueError("not transient")

    with pytest.raises(ValueError, match="not transient"):
        with_backoff(boom, attempts=5, retry_on=(ConnectionError,),
                     sleep=lambda s: None)
    assert len(calls) == 1


def test_exhausted_attempts_reraise_last_exception():
    clk = Clock()
    with pytest.raises(ConnectionError):
        with_backoff(
            lambda: (_ for _ in ()).throw(ConnectionError("down")),
            attempts=3, base_delay=0.1, retry_on=(ConnectionError,),
            sleep=clk.sleep,
        )
    assert len(clk.sleeps) == 2  # no sleep after the final attempt


def test_accept_predicate_retries_on_rejected_result():
    clk = Clock()
    results = iter([None, None, {1, 2}])
    out = with_backoff(
        lambda: next(results), attempts=5, base_delay=0.2, accept=bool,
        sleep=clk.sleep,
    )
    assert out == {1, 2}
    assert len(clk.sleeps) == 2


def test_exhausted_accept_returns_last_result():
    """Callers keep their degrade paths: an unaccepted final result comes
    back as-is instead of raising (partitions_for_topic -> None)."""
    out = with_backoff(
        lambda: None, attempts=3, base_delay=0.0, accept=bool,
        sleep=lambda s: None,
    )
    assert out is None


def test_growth_and_jitter_schedule():
    clk = Clock()
    with pytest.raises(ConnectionError):
        with_backoff(
            lambda: (_ for _ in ()).throw(ConnectionError("down")),
            attempts=4, base_delay=0.1, growth=2.0, jitter=0.05,
            retry_on=(ConnectionError,), sleep=clk.sleep, rng=lambda: 0.5,
        )
    # delays 0.1*2^0, 0.1*2^1, 0.1*2^2, each + 0.5*jitter
    assert clk.sleeps == pytest.approx([0.125, 0.225, 0.425])


def test_timeout_deadline_stops_retrying():
    clk = Clock()
    calls = []

    def failing():
        calls.append(clk.now)
        raise ConnectionError("down")

    with pytest.raises(ConnectionError):
        with_backoff(
            failing, attempts=100, base_delay=1.0, timeout=2.5,
            retry_on=(ConnectionError,), sleep=clk.sleep, clock=clk.clock,
        )
    # attempts at t=0, 1, 2; the deadline (2.5) then blocks further retries
    assert len(calls) == 3


def test_on_retry_hook_sees_cause_and_next_attempt():
    seen = []
    state = {"n": 0}

    def flaky():
        state["n"] += 1
        if state["n"] == 1:
            raise ConnectionError("first")
        return "ok"

    out = with_backoff(
        flaky, attempts=3, base_delay=0.0, retry_on=(ConnectionError,),
        on_retry=lambda exc, k: seen.append((type(exc).__name__, k)),
        sleep=lambda s: None,
    )
    assert out == "ok"
    assert seen == [("ConnectionError", 2)]


def test_on_retry_hook_none_exc_for_rejected_result():
    seen = []
    results = iter([None, "ok"])
    with_backoff(
        lambda: next(results), attempts=3, base_delay=0.0, accept=bool,
        on_retry=lambda exc, k: seen.append((exc, k)), sleep=lambda s: None,
    )
    assert seen == [(None, 2)]


def test_attempts_must_be_positive():
    with pytest.raises(ValueError, match="attempts"):
        with_backoff(lambda: 1, attempts=0)


def test_policy_from_flags_ms_units_and_defaults():
    p = BackoffPolicy.from_flags(
        {"retryAttempts": "7", "retryBaseDelayMs": "250",
         "retryJitterMs": "50", "retryTimeoutMs": "3000"},
    )
    assert p.attempts == 7
    assert p.base_delay == pytest.approx(0.25)
    assert p.jitter == pytest.approx(0.05)
    assert p.timeout == pytest.approx(3.0)
    # defaults pass through when flags are absent; kwargs override them
    q = BackoffPolicy.from_flags({}, attempts=2, base_delay=0.01)
    assert (q.attempts, q.base_delay, q.timeout) == (2, 0.01, None)


def test_policy_prefix_namespaces_flags():
    p = BackoffPolicy.from_flags(
        {"sendAttempts": "2", "retryAttempts": "9"}, prefix="send"
    )
    assert p.attempts == 2
