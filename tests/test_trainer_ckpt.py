"""Trainer checkpoint round-trips: save -> fresh trainer -> load -> identical
continued training."""

import jax
import numpy as np

from omldm_tpu.models.transformer import TransformerConfig
from omldm_tpu.parallel.pipeline_parallel import PPTrainer, make_pp_mesh
from omldm_tpu.parallel.seq_trainer import SeqTrainer, make_seq_mesh

CFG = TransformerConfig(
    vocab_size=32, d_model=16, n_heads=2, n_layers=2, d_ff=32, max_len=32,
)


def _batch(rng, b=4, l=16):
    toks = rng.randint(1, 32, size=(b, l + 1))
    return (
        toks[:, :-1].astype(np.int32),
        toks[:, 1:].astype(np.int32),
        np.ones((b, l), np.float32),
    )


def _assert_trees_equal(a, b, atol=0.0):
    for x, y in zip(jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), atol=atol)


def test_seq_trainer_checkpoint_roundtrip(tmp_path):
    rng = np.random.RandomState(0)
    batch = _batch(rng)
    tr = SeqTrainer(CFG, mesh=make_seq_mesh(2, 2, 2), lr=1e-2, seed=1)
    for _ in range(2):
        tr.step(*batch)
    tr.save(str(tmp_path / "ck"))

    fresh = SeqTrainer(CFG, mesh=make_seq_mesh(2, 2, 2), lr=1e-2, seed=99)
    fresh.load(str(tmp_path / "ck"))
    assert fresh.fitted == tr.fitted == 2 * 4 * 16
    _assert_trees_equal(fresh.host_params(), tr.host_params())
    # continued training stays bit-identical (optimizer state restored too)
    l_a = tr.step(*batch)
    l_b = fresh.step(*batch)
    np.testing.assert_allclose(float(np.asarray(l_a)), float(np.asarray(l_b)),
                               atol=1e-6)


def test_pp_trainer_checkpoint_roundtrip(tmp_path):
    rng = np.random.RandomState(1)
    batch = _batch(rng, b=8)
    cfg = TransformerConfig(
        vocab_size=32, d_model=16, n_heads=2, n_layers=4, d_ff=32, max_len=32,
    )
    tr = PPTrainer(cfg, mesh=make_pp_mesh(2, 2), n_micro=2, lr=1e-2, seed=2)
    for _ in range(2):
        tr.step(*batch)
    tr.save(str(tmp_path / "ck"))

    fresh = PPTrainer(cfg, mesh=make_pp_mesh(2, 2), n_micro=2, lr=1e-2, seed=77)
    fresh.load(str(tmp_path / "ck"))
    assert fresh.fitted == tr.fitted
    _assert_trees_equal(fresh.host_params(), tr.host_params())
    l_a = tr.step(*batch)
    l_b = fresh.step(*batch)
    np.testing.assert_allclose(float(np.asarray(l_a)), float(np.asarray(l_b)),
                               atol=1e-6)
