"""Preprocessor + pipeline tests."""

import jax
import jax.numpy as jnp
import numpy as np

from omldm_tpu.api.requests import LearnerSpec, PreprocessorSpec
from omldm_tpu.pipelines import MLPipeline
from omldm_tpu.preprocessors import (
    MinMaxScaler,
    PolynomialFeatures,
    StandardScaler,
)


class TestStandardScaler:
    def test_running_stats_match_numpy(self):
        rng = np.random.RandomState(0)
        x = rng.randn(1000, 5).astype(np.float32) * 3 + 2
        sc = StandardScaler()
        state = sc.init(5)
        for i in range(0, 1000, 100):
            xb = jnp.asarray(x[i : i + 100])
            state = sc.update(state, xb, jnp.ones(100))
        np.testing.assert_allclose(np.asarray(state["mean"]), x.mean(0), rtol=1e-4)
        var = np.asarray(state["m2"]) / (np.asarray(state["count"]) - 1)
        np.testing.assert_allclose(var, x.var(0, ddof=1), rtol=1e-3)
        z = np.asarray(sc.transform(state, jnp.asarray(x)))
        assert abs(z.mean()) < 0.01 and abs(z.std() - 1.0) < 0.01

    def test_mask_excludes_padding(self):
        sc = StandardScaler()
        state = sc.init(2)
        x = jnp.array([[1.0, 1.0], [999.0, 999.0]])
        state = sc.update(state, x, jnp.array([1.0, 0.0]))
        np.testing.assert_allclose(np.asarray(state["mean"]), [1.0, 1.0])
        assert float(state["count"]) == 1.0

    def test_merge(self):
        rng = np.random.RandomState(1)
        x = rng.randn(400, 3).astype(np.float32)
        sc = StandardScaler()
        s_all = sc.update(sc.init(3), jnp.asarray(x), jnp.ones(400))
        sa = sc.update(sc.init(3), jnp.asarray(x[:150]), jnp.ones(150))
        sb = sc.update(sc.init(3), jnp.asarray(x[150:]), jnp.ones(250))
        merged = sc.merge([sa, sb])
        np.testing.assert_allclose(
            np.asarray(merged["mean"]), np.asarray(s_all["mean"]), rtol=1e-5
        )
        np.testing.assert_allclose(
            np.asarray(merged["m2"]), np.asarray(s_all["m2"]), rtol=1e-4
        )


class TestMinMaxScaler:
    def test_scales_to_unit(self):
        mm = MinMaxScaler()
        state = mm.init(2)
        x = jnp.array([[0.0, -10.0], [5.0, 10.0], [2.5, 0.0]])
        state = mm.update(state, x, jnp.ones(3))
        z = np.asarray(mm.transform(state, x))
        np.testing.assert_allclose(z, [[0, 0], [1, 1], [0.5, 0.5]])

    def test_identity_before_any_data(self):
        mm = MinMaxScaler()
        state = mm.init(2)
        x = jnp.array([[3.0, 4.0]])
        np.testing.assert_allclose(np.asarray(mm.transform(state, x)), [[3.0, 4.0]])


class TestPolynomialFeatures:
    def test_degree2_layout(self):
        pf = PolynomialFeatures()
        assert pf.out_dim(3) == 3 + 6
        x = jnp.array([[1.0, 2.0, 3.0]])
        z = np.asarray(pf.transform((), x))[0]
        # [x1,x2,x3, x1*x1, x1*x2, x1*x3, x2*x2, x2*x3, x3*x3]
        np.testing.assert_allclose(z, [1, 2, 3, 1, 2, 3, 4, 6, 9])

    def test_degree3_adds_cubes(self):
        pf = PolynomialFeatures({"degree": 3})
        assert pf.out_dim(2) == 2 + 3 + 2
        z = np.asarray(pf.transform((), jnp.array([[2.0, 3.0]])))[0]
        np.testing.assert_allclose(z, [2, 3, 4, 6, 9, 8, 27])


class TestMLPipeline:
    def test_scaler_plus_pa_learns_unnormalized_stream(self):
        rng = np.random.RandomState(0)
        w = rng.randn(4)
        x = (rng.randn(4096, 4) * np.array([100.0, 0.01, 5.0, 1.0])).astype(np.float32)
        y = ((x / np.array([100.0, 0.01, 5.0, 1.0])) @ w > 0).astype(np.float32) * 2 - 1
        pipe = MLPipeline(
            LearnerSpec("PA", hyper_parameters={"C": 1.0}),
            [PreprocessorSpec("StandardScaler")],
            dim=4,
        )
        for i in range(0, 4096, 128):
            pipe.fit(
                jnp.asarray(x[i : i + 128]),
                jnp.asarray(y[i : i + 128]),
                jnp.ones(128),
            )
        _, score = pipe.evaluate(jnp.asarray(x), jnp.asarray(y), jnp.ones(4096))
        assert score > 0.9
        assert pipe.fitted == 4096

    def test_poly_pipeline_learns_quadratic(self):
        rng = np.random.RandomState(0)
        x = rng.randn(4096, 2).astype(np.float32)
        y = ((x[:, 0] * x[:, 1]) > 0).astype(np.float32) * 2 - 1  # XOR-ish, quadratic
        pipe = MLPipeline(
            LearnerSpec("PA", hyper_parameters={"C": 1.0}),
            [PreprocessorSpec("PolynomialFeatures")],
            dim=2,
        )
        for i in range(0, 4096, 128):
            pipe.fit(jnp.asarray(x[i : i + 128]), jnp.asarray(y[i : i + 128]), jnp.ones(128))
        _, score = pipe.evaluate(jnp.asarray(x), jnp.asarray(y), jnp.ones(4096))
        assert score > 0.9

    def test_fit_many_matches_sequential_fits(self):
        """One lax.scan launch over T staged batches == T fit calls: same
        params, same fitted count, same learning-curve points."""
        rng = np.random.RandomState(1)
        xs = rng.randn(6, 32, 4).astype(np.float32)
        ys = (xs.sum(-1) > 0).astype(np.float32) * 2 - 1
        masks = np.ones((6, 32), np.float32)

        seq = MLPipeline(
            LearnerSpec("Softmax", hyper_parameters={"learningRate": 0.1, "nClasses": 2}),
            [PreprocessorSpec("StandardScaler")],
            dim=4,
        )
        many = MLPipeline(
            LearnerSpec("Softmax", hyper_parameters={"learningRate": 0.1, "nClasses": 2}),
            [PreprocessorSpec("StandardScaler")],
            dim=4,
        )
        for i in range(6):
            seq.fit(jnp.asarray(xs[i]), jnp.asarray(ys[i]), masks[i])
        losses = many.fit_many(jnp.asarray(xs), jnp.asarray(ys), jnp.asarray(masks))
        assert many.fitted == seq.fitted == 6 * 32
        np.testing.assert_allclose(
            np.asarray(jax.flatten_util.ravel_pytree(many.state["params"])[0]),
            np.asarray(jax.flatten_util.ravel_pytree(seq.state["params"])[0]),
            atol=1e-5,
        )
        c_seq = seq.curve_slice()
        c_many = many.curve_slice()
        assert [f for _, f in c_seq] == [f for _, f in c_many]
        np.testing.assert_allclose(
            [l for l, _ in c_seq], [l for l, _ in c_many], atol=1e-5
        )
        assert losses.shape == (6,)

    def test_curve_slices_are_incremental(self):
        pipe = MLPipeline(LearnerSpec("PA"), dim=3)
        x = jnp.ones((8, 3))
        y = jnp.ones((8,))
        pipe.fit(x, y, jnp.ones(8))
        pipe.fit(x, y, jnp.ones(8))
        s1 = pipe.curve_slice()
        assert len(s1) == 2
        assert s1[0][1] == 8 and s1[1][1] == 16
        pipe.fit(x, y, jnp.ones(8))
        s2 = pipe.curve_slice()
        assert len(s2) == 1 and s2[1 - 1][1] == 24
        assert pipe.curve_slice() == []

    def test_flat_params_roundtrip(self):
        pipe = MLPipeline(LearnerSpec("PA"), dim=3)
        pipe.fit(jnp.ones((4, 3)), jnp.ones((4,)), jnp.ones(4))
        flat, _ = pipe.get_flat_params()
        assert flat.shape == (4,)  # w has dim+1
        pipe.set_flat_params(np.zeros_like(flat))
        flat2, _ = pipe.get_flat_params()
        np.testing.assert_allclose(flat2, 0.0)

    def test_merge_from(self):
        a = MLPipeline(LearnerSpec("PA"), dim=2)
        b = MLPipeline(LearnerSpec("PA"), dim=2)
        a.fit(jnp.ones((4, 2)), jnp.ones(4), jnp.ones(4))
        b.fit(-jnp.ones((4, 2)), jnp.ones(4), jnp.ones(4))
        wa, _ = a.get_flat_params()
        wb, _ = b.get_flat_params()
        a.merge_from([b])
        wm, _ = a.get_flat_params()
        np.testing.assert_allclose(wm, (wa + wb) / 2, rtol=1e-6)
        assert a.fitted == 8

    def test_host_side_ht_pipeline(self):
        rng = np.random.RandomState(0)
        x = rng.randn(3000, 3).astype(np.float32)
        y = (x[:, 0] > 0).astype(np.float32)
        pipe = MLPipeline(
            LearnerSpec("HT", hyper_parameters={"gracePeriod": 100, "delta": 1e-3}),
            dim=3,
        )
        for i in range(0, 3000, 200):
            pipe.fit(x[i : i + 200], y[i : i + 200], np.ones(200, np.float32))
        _, score = pipe.evaluate(x, y, np.ones(3000, np.float32))
        assert score > 0.85
