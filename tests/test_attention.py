"""Attention kernels: blockwise and Pallas vs the reference implementation."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from omldm_tpu.ops.attention import (
    attention,
    blockwise_attention,
    flash_attention_pallas,
    mha_reference,
)


def _qkv(b=2, l=64, h=4, dh=16, seed=0):
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(k1, (b, l, h, dh), jnp.float32)
    k = jax.random.normal(k2, (b, l, h, dh), jnp.float32)
    v = jax.random.normal(k3, (b, l, h, dh), jnp.float32)
    return q, k, v


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("block_k", [16, 24, 64])
def test_blockwise_matches_reference(causal, block_k):
    q, k, v = _qkv()
    ref = mha_reference(q, k, v, causal=causal)
    out = blockwise_attention(q, k, v, causal=causal, block_k=block_k)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_pallas_flash_matches_reference(causal):
    q, k, v = _qkv(b=1, l=48, h=2, dh=8)
    ref = mha_reference(q, k, v, causal=causal)
    out = flash_attention_pallas(
        q, k, v, causal=causal, block_q=16, block_k=16, interpret=True
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_cross_chunk_offsets():
    """q_offset/kv_offset give exact causal masking across chunk boundaries
    (the contract ring attention depends on)."""
    q, k, v = _qkv(l=32)
    full = mha_reference(q, k, v, causal=True)
    # second half of queries attending over all keys with absolute positions
    out = blockwise_attention(
        q[:, 16:], k, v, causal=True, block_k=8, q_offset=16, kv_offset=0
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(full[:, 16:]), atol=1e-5)


def test_dispatch_entry_point():
    q, k, v = _qkv(l=32)
    ref = mha_reference(q, k, v, causal=True)
    out = attention(q, k, v, causal=True, use_pallas=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_flash_wrapper_is_differentiable():
    """The TPU dispatch path must be trainable: grads through the Pallas
    forward come from the blockwise-derived custom VJP."""
    from omldm_tpu.ops.attention import _flash_diff

    q, k, v = _qkv(b=1, l=32, h=2, dh=8)

    def loss_flash(q, k, v):
        return jnp.sum(_flash_diff(q, k, v, True, 0, 0, True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(mha_reference(q, k, v, causal=True) ** 2)

    g = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)
