"""Attention kernels: blockwise and Pallas vs the reference implementation."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from omldm_tpu.ops.attention import (
    attention,
    blockwise_attention,
    flash_attention_pallas,
    mha_reference,
)


def _qkv(b=2, l=64, h=4, dh=16, seed=0):
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(k1, (b, l, h, dh), jnp.float32)
    k = jax.random.normal(k2, (b, l, h, dh), jnp.float32)
    v = jax.random.normal(k3, (b, l, h, dh), jnp.float32)
    return q, k, v


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("block_k", [16, 24, 64])
def test_blockwise_matches_reference(causal, block_k):
    q, k, v = _qkv()
    ref = mha_reference(q, k, v, causal=causal)
    out = blockwise_attention(q, k, v, causal=causal, block_k=block_k)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_pallas_flash_matches_reference(causal):
    q, k, v = _qkv(b=1, l=48, h=2, dh=8)
    ref = mha_reference(q, k, v, causal=causal)
    out = flash_attention_pallas(
        q, k, v, causal=causal, block_q=16, block_k=16, interpret=True
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_cross_chunk_offsets():
    """q_offset/kv_offset give exact causal masking across chunk boundaries
    (the contract ring attention depends on)."""
    q, k, v = _qkv(l=32)
    full = mha_reference(q, k, v, causal=True)
    # second half of queries attending over all keys with absolute positions
    out = blockwise_attention(
        q[:, 16:], k, v, causal=True, block_k=8, q_offset=16, kv_offset=0
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(full[:, 16:]), atol=1e-5)


def test_dispatch_entry_point():
    q, k, v = _qkv(l=32)
    ref = mha_reference(q, k, v, causal=True)
    out = attention(q, k, v, causal=True, use_pallas=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_flash_wrapper_is_differentiable():
    """The TPU dispatch path must be trainable: grads through the Pallas
    forward come from the blockwise-derived custom VJP."""
    from omldm_tpu.ops.attention import _flash_diff

    q, k, v = _qkv(b=1, l=32, h=2, dh=8)

    def loss_flash(q, k, v):
        return jnp.sum(_flash_diff(q, k, v, True, 0, 0, True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(mha_reference(q, k, v, causal=True) ** 2)

    g = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


class TestFlashBackwardPallas:
    """The Pallas flash backward (dq / dk-dv passes recomputing scores from
    the saved logsumexp) must match the reference attention's autodiff
    gradients — causal, offsets, ragged lengths."""

    def _grads(self, fn, q, k, v):
        def loss(q, k, v):
            o = fn(q, k, v)
            return jnp.sum(o * jnp.cos(jnp.arange(o.size).reshape(o.shape) * 0.01))

        return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)

    @pytest.mark.parametrize("causal", [False, True])
    def test_grads_match_reference(self, causal):
        from omldm_tpu.ops.attention import _flash_diff

        rng = np.random.RandomState(0)
        b, l, h, dh = 2, 96, 2, 16
        q = jnp.asarray(rng.randn(b, l, h, dh).astype(np.float32) * 0.3)
        k = jnp.asarray(rng.randn(b, l, h, dh).astype(np.float32) * 0.3)
        v = jnp.asarray(rng.randn(b, l, h, dh).astype(np.float32) * 0.3)
        gp = self._grads(
            lambda q, k, v: _flash_diff(q, k, v, causal, 0, 0, True), q, k, v
        )
        gr = self._grads(
            lambda q, k, v: mha_reference(q, k, v, causal=causal), q, k, v
        )
        for a, b_ in zip(gp, gr):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b_), rtol=2e-3, atol=2e-4
            )

    def test_grads_match_with_offsets_and_ragged(self):
        from omldm_tpu.ops.attention import _flash_diff

        rng = np.random.RandomState(1)
        b, h, dh = 1, 2, 16
        lq, lk = 40, 72  # ragged: exercises both pad paths
        q = jnp.asarray(rng.randn(b, lq, h, dh).astype(np.float32) * 0.3)
        k = jnp.asarray(rng.randn(b, lk, h, dh).astype(np.float32) * 0.3)
        v = jnp.asarray(rng.randn(b, lk, h, dh).astype(np.float32) * 0.3)
        gp = self._grads(
            lambda q, k, v: _flash_diff(q, k, v, True, 32, 0, True), q, k, v
        )
        gr = self._grads(
            lambda q, k, v: mha_reference(q, k, v, causal=True, q_offset=32),
            q, k, v,
        )
        for a, b_ in zip(gp, gr):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b_), rtol=2e-3, atol=2e-4
            )

    def test_forward_lse_matches_reference_logsumexp(self):
        from omldm_tpu.ops.attention import flash_attention_pallas

        rng = np.random.RandomState(2)
        b, l, h, dh = 1, 64, 2, 16
        q = jnp.asarray(rng.randn(b, l, h, dh).astype(np.float32) * 0.3)
        k = jnp.asarray(rng.randn(b, l, h, dh).astype(np.float32) * 0.3)
        v = jnp.asarray(rng.randn(b, l, h, dh).astype(np.float32) * 0.3)
        _, lse = flash_attention_pallas(q, k, v, causal=True, interpret=True,
                                        return_lse=True)
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / jnp.sqrt(float(dh))
        qi = jnp.arange(l)[:, None]
        ki = jnp.arange(l)[None, :]
        s = jnp.where(qi >= ki, s, -1e30)
        ref = jax.scipy.special.logsumexp(s, axis=-1)  # [b, h, l]
        got = np.asarray(lse)[:, :l, 0].reshape(b, h, l)
        np.testing.assert_allclose(got, np.asarray(ref), rtol=1e-4, atol=1e-4)
