"""Load harness end-to-end (benchmarks/load_harness.py).

Pins, per ISSUE 19:

- the in-process leg: a composed storm (churn + diurnal + bursts +
  addressed traffic) through the armed plane matrix passes every SLO
  gate, and a replay of the same seed produces a byte-identical
  deterministic report core;
- the full-composition identity leg (satellite 3): every plane
  configured-but-unarmed is bit-identical to the bare path at 256
  tenants;
- the supervised fleet leg: a seeded storm with two composed fault
  classes (launch refusal + mid-stream crash) completes across
  restarts with a passing SLO report — zero healthy-tenant loss,
  exactly-once outputs, no stranded rows, bounded shed, heals observed
  and within budget — and the count-clocked ``--requestSchedule``
  churn survives checkpoint/restore.
"""

import os
import sys

import pytest

jax = pytest.importorskip("jax")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from benchmarks.load_harness import (
    build_composed_storm,
    default_storm_spec,
    run_composition_identity,
    run_inprocess_storm,
    run_supervised_storm,
)
from omldm_tpu.runtime.loadgen import LoadStorm, StormSpec
from omldm_tpu.runtime.slo import SLOBudgets


def _small_storm(seed=11, **kw):
    spec = default_storm_spec(
        seed=seed, tenants=6, records=256, chunk_rows=32, **kw
    )
    return LoadStorm(spec)


class TestInprocessLeg:
    def test_composed_storm_passes_slo(self):
        storm = _small_storm()
        budgets = SLOBudgets(
            allow_shed_tenants=storm.hot_tenant_ids(),
            max_stranded_rows=0,
        )
        report, job = run_inprocess_storm(storm, budgets)
        assert report.passed, [c.to_dict() for c in report.failing()]
        # the scheduled churn actually ran: churned-in tenants produced
        assert any(
            p.mlp_id >= storm.spec.tenants for p in job.predictions
        )

    def test_replay_identical_core(self):
        budgets = SLOBudgets(allow_shed_tenants=[], max_stranded_rows=0)
        a, _ = run_inprocess_storm(_small_storm(), budgets)
        b, _ = run_inprocess_storm(_small_storm(), budgets)
        assert a.core_digest() == b.core_digest()
        assert (
            a.core_digest()
            != run_inprocess_storm(_small_storm(seed=12), budgets)[
                0
            ].core_digest()
        )


class TestCompositionIdentity:
    @pytest.mark.slow  # ~47s: two full 256-pipeline drives; the CI
    # --slo-smoke gate runs this exact identity check as a hard failure
    def test_unarmed_matrix_is_bit_identical_at_256_tenants(self):
        # uniform broadcast traffic: no addressing, no bursts — the one
        # regime where EVERY plane must be transparent (satellite 3)
        storm = LoadStorm(StormSpec(
            seed=5, tenants=256, records=128, chunk_rows=64,
            n_features=4, forecast_ratio=0.4,
        ))
        bare, composed = run_composition_identity(storm)
        assert bare == composed


class TestFleetScaleControlPlane:
    """A fleet-scale Create wave is far larger than one 64 KiB control
    frame: the broadcast must stream it as continuation-flagged frames,
    byte-identically and in order."""

    def _job(self):
        from omldm_tpu.config import JobConfig
        from omldm_tpu.runtime.distributed_job import DistributedStreamJob

        return DistributedStreamJob(
            JobConfig(batch_size=8, test_set_size=8)
        )

    def test_frame_batches_pack_in_order_under_cap(self):
        from omldm_tpu.runtime.distributed_job import CONTROL_CAP

        storm = LoadStorm(StormSpec(
            seed=1, tenants=2000, records=1, protocol="Synchronous",
            training_extra={"syncEvery": 1},
        ))
        lines = storm.request_lines()
        job = self._job()
        batches = job._frame_batches(lines)
        assert len(batches) > 1
        assert [l for b in batches for l in b] == lines
        cap = CONTROL_CAP - job._FRAME_HEADER
        for b in batches:
            assert len("\n".join(b).encode()) <= cap

    def test_oversize_single_line_raises(self):
        job = self._job()
        with pytest.raises(ValueError):
            job._frame_batches(["x" * (1 << 17)])

    @pytest.mark.slow  # ~6s of 400 deploys; the frame-packing units
    # above pin the protocol, --slo-smoke drives it at 10x this scale
    def test_multi_frame_create_wave_deploys_every_tenant(self):
        storm = LoadStorm(StormSpec(
            seed=1, tenants=400, records=1, protocol="Synchronous",
            training_extra={"syncEvery": 1},
        ))
        lines = storm.request_lines()
        job = self._job()
        assert len(job._frame_batches(lines)) > 1
        job.sync_requests(lines)
        assert sorted(job.pipelines) == list(range(400))


class TestSupervisedLeg:
    @pytest.mark.slow  # ~11s subprocess fleet; the CI --slo-smoke gate
    # runs the same composed fault storm as a hard failure
    def test_composed_fault_storm_passes_slo(self, tmp_path):
        storm = build_composed_storm(
            3, tenants=6, records=192, chunk_rows=32, processes=1,
        )
        assert {f.kind for f in storm.spec.faults} == {"launch", "crash"}
        budgets = SLOBudgets(
            heal_after_fault_s=120.0,
            # launch refusal + the crash (which re-fires once per fresh
            # incarnation until its record position is past the restore
            # cursor) => at least two observed heals
            expected_heals=2,
            allow_shed_tenants=storm.hot_tenant_ids(),
            max_stranded_rows=0,
        )
        report, merged, stderr = run_supervised_storm(
            storm, str(tmp_path), budgets, processes=1,
        )
        assert report.passed, [c.to_dict() for c in report.failing()]
        # restarts really happened (the faults fired)
        assert merged is not None
        heal = next(
            c for c in report.checks if c.name == "heal_after_fault"
        )
        assert heal.detail["heals"] >= 2

    @pytest.mark.slow
    def test_supervised_replay_identical_core(self, tmp_path):
        budgets = SLOBudgets(
            heal_after_fault_s=120.0, expected_heals=2,
            allow_shed_tenants=[0, 1], max_stranded_rows=0,
        )
        digests = []
        for run in ("a", "b"):
            storm = build_composed_storm(
                3, tenants=6, records=192, chunk_rows=32, processes=1,
            )
            rep, _, _ = run_supervised_storm(
                storm, str(tmp_path / run), budgets, processes=1,
            )
            assert rep.passed
            digests.append(rep.core_digest())
        assert digests[0] == digests[1]

    @pytest.mark.slow
    def test_two_process_storm_passes_slo(self, tmp_path):
        storm = build_composed_storm(
            9, tenants=6, records=192, chunk_rows=32, processes=2,
        )
        budgets = SLOBudgets(
            heal_after_fault_s=120.0, expected_heals=2,
            allow_shed_tenants=storm.hot_tenant_ids(),
            max_stranded_rows=0,
        )
        report, merged, stderr = run_supervised_storm(
            storm, str(tmp_path), budgets, processes=2,
        )
        assert report.passed, [c.to_dict() for c in report.failing()]
