"""Native parser + bulk ingest tests: parity with the Python codec path."""

import json

import numpy as np
import pytest

from omldm_tpu.ops.native import FastParser, fast_parser_available
from omldm_tpu.runtime.fast_ingest import iter_file_batches

needs_native = pytest.mark.skipif(
    not fast_parser_available(), reason="g++ toolchain unavailable"
)


@needs_native
class TestFastParser:
    def test_training_record(self):
        p = FastParser(4)
        x, y, op, valid = p.parse(
            b'{"numericalFeatures": [1.5, -2.0, 3.25], "target": 1.0, "operation": "training"}\n'
        )
        assert valid[0] == 1
        assert op[0] == 0
        np.testing.assert_allclose(x[0], [1.5, -2.0, 3.25, 0.0])
        assert y[0] == 1.0

    def test_forecasting_and_discrete(self):
        p = FastParser(5)
        x, y, op, valid = p.parse(
            b'{"numericalFeatures": [1.0], "discreteFeatures": [2, 3], "operation": "forecasting"}\n'
        )
        assert valid[0] == 1 and op[0] == 1
        np.testing.assert_allclose(x[0], [1.0, 2.0, 3.0, 0.0, 0.0])

    def test_drop_semantics_match_python(self):
        # EOS, blank, garbage, NaN, featureless -> dropped outright; a
        # string target defers to the Python codec (valid=2), whose
        # float() coercion decides — float("high") raises, so the
        # fallback drops it (float("0") would keep; pinned by the fuzz
        # parity suite)
        lines = (
            b"EOS\n"
            b"\n"
            b"garbage {\n"
            b'{"numericalFeatures": [NaN], "target": 1.0}\n'
            b'{"operation": "training"}\n'
            b'{"numericalFeatures": [1.0], "target": "high"}\n'
        )
        p = FastParser(3)
        x, y, op, valid = p.parse(lines)
        assert valid.tolist() == [0, 0, 0, 0, 0, 2]

    def test_fallback_flag_for_categorical(self):
        p = FastParser(3)
        _, _, _, valid = p.parse(
            b'{"numericalFeatures": [1.0], "categoricalFeatures": ["a"], "target": 0}\n'
        )
        assert valid[0] == 2  # python fallback

    def test_truncates_to_dim(self):
        p = FastParser(2)
        x, y, op, valid = p.parse(
            b'{"numericalFeatures": [1, 2, 3, 4], "target": 1}\n'
        )
        assert valid[0] == 1
        np.testing.assert_allclose(x[0], [1.0, 2.0])


class TestIterFileBatches:
    def test_matches_python_path(self, tmp_path):
        rng = np.random.RandomState(0)
        rows = []
        for i in range(1000):
            rows.append(
                {
                    "numericalFeatures": list(np.round(rng.randn(6), 4)),
                    "target": float(i % 2),
                    "operation": "training" if i % 3 else "forecasting",
                }
            )
        path = tmp_path / "stream.jsonl"
        with open(path, "w") as f:
            for r in rows:
                f.write(json.dumps(r) + "\n")
            f.write("EOS\n")

        got_x, got_y, got_op = [], [], []
        for x, y, op in iter_file_batches(str(path), dim=6, batch_size=128):
            got_x.append(x)
            got_y.append(y)
            got_op.append(op)
        X = np.concatenate(got_x)
        Y = np.concatenate(got_y)
        OP = np.concatenate(got_op)
        assert X.shape == (1000, 6)
        np.testing.assert_allclose(
            X, [r["numericalFeatures"] for r in rows], atol=1e-6
        )
        np.testing.assert_allclose(Y, [r["target"] for r in rows])
        assert OP.tolist() == [0 if i % 3 else 1 for i in range(1000)]

    def test_mixed_fallback_records(self, tmp_path):
        path = tmp_path / "mixed.jsonl"
        with open(path, "w") as f:
            f.write('{"numericalFeatures": [1, 2], "target": 1}\n')
            f.write(
                '{"numericalFeatures": [3], "categoricalFeatures": ["x"], "target": 0}\n'
            )
            f.write("junk\n")
            f.write('{"numericalFeatures": [5, 6], "target": 0}\n')
        batches = list(iter_file_batches(str(path), dim=4, batch_size=8, hash_dims=2))
        x, y, op = batches[0]
        assert x.shape[0] == 3  # junk dropped; categorical went via fallback
        np.testing.assert_allclose(x[0], [1, 2, 0, 0])
        assert x[1][0] == 3.0 and np.abs(x[1][2:]).sum() > 0  # hashed cat
        np.testing.assert_allclose(x[2], [5, 6, 0, 0])


class TestHashDimsLayout:
    def test_c_and_python_paths_agree_with_hash_dims(self):
        """Dense features must stay in the first dim - hash_dims slots on
        BOTH parse paths; the trailing hashed-categorical region is reserved
        (regression: the C parser used to pack into the full width)."""
        from omldm_tpu.runtime.fast_ingest import PackedBatcher

        line = b'{"numericalFeatures": [1, 2, 3], "target": 1}\n'
        with_parser = PackedBatcher(dim=4, batch_size=1, hash_dims=2)
        without = PackedBatcher(dim=4, batch_size=1, hash_dims=2)
        without.parser = None  # force the Python fallback
        if with_parser.parser is None:
            import pytest

            pytest.skip("native parser unavailable")
        (bx, _, _), = list(with_parser.feed(line))
        (px, _, _), = list(without.feed(line))
        np.testing.assert_allclose(bx, px)
        np.testing.assert_allclose(bx[0], [1.0, 2.0, 0.0, 0.0])
