"""The LIVE Kafka loop in ``__main__._run`` (reference: the unbounded
Kafka-sourced job, Job.scala:42-87, with silence-timer termination,
StatisticsOperator.scala:135-142) — driven end to end with fake clients:

- records flow through the loop and train; the silence timer terminates the
  job when the broker goes quiet;
- sink precedence: an explicit ``--*Out`` file flag keeps priority over the
  Kafka producer for that stream, while unflagged streams egress through
  the producer;
- the profile window is bounded: tracing stops after ``--profileSteps``
  events while the job keeps running.
"""

import json

import numpy as np
import pytest

import omldm_tpu.runtime.kafka_io as kafka_io
from omldm_tpu.__main__ import main
from omldm_tpu.runtime.kafka_io import ProducerSinks, polling_events

from tests.test_kafka_io import FakePollingConsumer, FakeProducer, FakeRecord


def _records(n=500, dim=4, seed=0, forecasts=5):
    rng = np.random.RandomState(seed)
    w = rng.randn(dim)
    recs = [
        FakeRecord(
            "requests",
            json.dumps({
                "id": 0,
                "request": "Create",
                "learner": {"name": "PA", "hyperParameters": {"C": 1.0}},
                "trainingConfiguration": {"protocol": "CentralizedTraining"},
            }).encode(),
        )
    ]
    for _ in range(n):
        x = rng.randn(dim)
        recs.append(FakeRecord("trainingData", json.dumps({
            "numericalFeatures": list(np.round(x, 4)),
            "target": float(x @ w > 0),
        }).encode()))
    for _ in range(forecasts):
        x = rng.randn(dim)
        recs.append(FakeRecord("forecastingData", json.dumps({
            "numericalFeatures": list(np.round(x, 4)),
        }).encode()))
    return recs


def _fake_connect(monkeypatch, records):
    producer = FakeProducer()

    def connect(brokers, **kwargs):
        consumer = FakePollingConsumer([records])
        return polling_events(consumer), ProducerSinks(producer)

    monkeypatch.setattr(kafka_io, "connect_kafka", connect)
    return producer


class TestKafkaLoop:
    def test_trains_and_terminates_on_silence(self, tmp_path, monkeypatch):
        producer = _fake_connect(monkeypatch, _records())
        perf = tmp_path / "perf.jsonl"
        rc = main([
            "--kafkaBrokers", "fake:9092",
            "--performanceOut", str(perf),
            "--parallelism", "2",
            "--timeout", "2500",
        ])
        assert rc == 0
        stats = json.loads(perf.read_text())
        [s] = stats["statistics"]
        assert s["fitted"] > 300
        assert s["score"] > 0.8

    def test_sink_precedence_file_flag_beats_producer(self, tmp_path, monkeypatch):
        producer = _fake_connect(monkeypatch, _records())
        preds = tmp_path / "preds.jsonl"
        rc = main([
            "--kafkaBrokers", "fake:9092",
            "--predictionsOut", str(preds),   # explicit file sink
            "--parallelism", "1",
            "--timeout", "2500",
        ])
        assert rc == 0
        # predictions went to the FILE, not the producer
        lines = [l for l in preds.read_text().splitlines() if l.strip()]
        assert len(lines) == 5
        pred_topics = [t for t, _ in producer.sent if t == "predictions"]
        assert pred_topics == []
        # performance (no file flag) egressed through the producer
        perf_msgs = [v for t, v in producer.sent if t == "performance"]
        assert len(perf_msgs) == 1
        payload = json.loads(perf_msgs[0].decode())
        assert payload["statistics"][0]["fitted"] > 300

    def test_profile_window_bounded(self, tmp_path, monkeypatch):
        import jax

        producer = _fake_connect(monkeypatch, _records(n=120))
        calls = {"start": 0, "stop": 0, "events_at_stop": None}
        seen = {"n": 0}

        def fake_start(path):
            calls["start"] += 1

        def fake_stop():
            calls["stop"] += 1

        monkeypatch.setattr(jax.profiler, "start_trace", fake_start)
        monkeypatch.setattr(jax.profiler, "stop_trace", fake_stop)
        rc = main([
            "--kafkaBrokers", "fake:9092",
            "--performanceOut", str(tmp_path / "p.jsonl"),
            "--profileDir", str(tmp_path / "trace"),
            "--profileSteps", "10",
            "--parallelism", "1",
            "--timeout", "2500",
        ])
        assert rc == 0
        assert calls["start"] == 1
        assert calls["stop"] == 1  # stopped ONCE, at the window bound —
        # not re-stopped in the finally block, and the job ran to
        # termination afterwards (rc 0 with stats emitted)
        assert (tmp_path / "p.jsonl").read_text().strip()
