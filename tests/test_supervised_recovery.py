"""Distributed fault tolerance, end to end: supervised recovery of the
multi-process job under injected faults.

The reference gets all of this from Flink — checkpoint barriers plus
``RestartStrategies.fixedDelayRestart`` restore state and rewind the Kafka
sources on any TaskManager loss (Job.scala:14, FlinkSpoke.scala:233-334).
Here the :class:`DistributedJobSupervisor` plays the JobManager: every test
kills a REAL worker process mid-stream through the flag-armed
:class:`DistributedFaultInjector`, lets the supervisor relaunch the fleet
from the latest consistent snapshot, and asserts the recovered run
converges to the exact statistics of a fault-free run — recovery is
exercised, not claimed.

Economy: the tier-1 tests run single-process fleets (one jax worker per
incarnation, ~5s each); the multi-process chosen-worker kill — same code
paths plus gloo collectives — is the slow-marked finale.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TESTS = os.path.join(REPO, "tests")

# worker bootstrap that installs the file-backed kafka fake before
# production code imports `kafka` (real subprocesses cannot share an
# in-process fake); the supervisor injects it via --workerBoot
FSKAFKA_BOOT = (
    "import sys; sys.path.insert(0, {tests!r}); "
    "import fskafka; fskafka.install(); "
    "from omldm_tpu.runtime.distributed_job import run_distributed; "
    "sys.exit(run_distributed(sys.argv[1:]))"
).format(tests=TESTS)


def _rows(n, dim=12, seed=0, forecast_every=0):
    rng = np.random.RandomState(seed)
    w = rng.randn(dim)
    lines = []
    for i in range(n):
        x = np.round(rng.randn(dim), 6)
        if forecast_every and i % forecast_every == 0:
            lines.append(json.dumps({
                "numericalFeatures": [float(v) for v in x],
                "operation": "forecasting",
            }))
        else:
            lines.append(json.dumps({
                "numericalFeatures": [float(v) for v in x],
                "target": float(x @ w > 0),
                "operation": "training",
            }))
    return lines


def _create(dim=12):
    return json.dumps({
        "id": 0,
        "request": "Create",
        "learner": {
            "name": "PA",
            "hyperParameters": {"C": 1.0},
            "dataStructure": {"nFeatures": dim},
        },
        "preProcessors": [],
        "trainingConfiguration": {"protocol": "Synchronous", "syncEvery": 1},
    })


def _run(args, tag, tmp_path, env_extra=None, expect_rc=0, timeout=240):
    """One CLI invocation of the distributed entry point (worker fleet or
    supervisor, depending on args); returns (report or None, stderr)."""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # one CPU device per worker process
    env["JAX_PLATFORMS"] = "cpu"
    env.update(env_extra or {})
    perf = tmp_path / f"perf_{tag}.jsonl"
    out = subprocess.run(
        [sys.executable, "-m", "omldm_tpu.runtime.distributed_job",
         "--performanceOut", str(perf),
         "--batchSize", "64", "--testSetSize", "32", "--chunkRows", "100",
         ] + args,
        cwd=REPO, env=env, capture_output=True, text=True, timeout=timeout,
    )
    assert out.returncode == expect_rc, (
        f"rc {out.returncode} (wanted {expect_rc}):\n"
        f"{out.stdout[-2000:]}\n{out.stderr[-4000:]}"
    )
    report = None
    if perf.exists():
        [line] = perf.read_text().strip().splitlines()
        report = json.loads(line)
    return report, out.stderr


def _stat(report):
    [s] = report["statistics"]
    return s


def _assert_converged(recovered, clean):
    """The recovered run must land on the fault-free run's statistics —
    same rows fitted, same holdout residency, float-equal score (identical
    step sequence after replay-from-checkpoint, no loss, no double-train)."""
    sr, sc = _stat(recovered), _stat(clean)
    assert sr["fitted"] == sc["fitted"]
    assert recovered["holdout"] == clean["holdout"]
    assert abs(sr["score"] - sc["score"]) < 1e-6
    assert sr["learningCurve"] == pytest.approx(sc["learningCurve"])


@pytest.fixture(scope="module")
def clean_file_run(tmp_path_factory):
    """ONE fault-free run of the standard 600-row file stream, shared by
    every file-source test in this module (the faulted runs must converge
    to exactly these statistics, so one baseline serves them all).
    Returns (base_flags, report, clean predictions lines)."""
    d = tmp_path_factory.mktemp("clean_file")
    train = d / "train.jsonl"
    reqs = d / "reqs.jsonl"
    train.write_text("\n".join(_rows(600, forecast_every=50)) + "\n")
    reqs.write_text(_create() + "\n")
    base = ["--requests", str(reqs), "--trainingData", str(train)]
    preds = d / "preds_clean.jsonl"
    report, _ = _run(base + ["--predictionsOut", str(preds)], "clean", d)
    return base, report, preds.read_text().strip().splitlines()


def test_supervised_kill_recovery_file_source(tmp_path, clean_file_run):
    """Worker killed mid-stream (hard exit at 350 ingested records) under
    the supervisor: one fixed-delay restart restores the latest snapshot,
    replays the file cursor from the checkpoint floor, and converges to
    the fault-free statistics — file-source half of the Flink
    checkpoint-and-replay contract."""
    base, clean, preds_clean = clean_file_run

    preds = tmp_path / "preds_sup.jsonl"
    recovered, err = _run(
        base + [
            "--supervise", "true", "--processes", "1",
            "--predictionsOut", str(preds),
            "--checkpointDir", str(tmp_path / "ckpts"),
            "--checkpointEvery", "2",
            "--failProcess", "0", "--failAfterRecords", "350",
            "--restartAttempts", "2", "--restartDelayMs", "50",
        ],
        "sup", tmp_path,
    )
    assert "injected crash: worker 0 after" in err
    assert "relaunching fleet from latest consistent checkpoint" in err
    _assert_converged(recovered, clean)
    # emitted outputs dedupe across incarnations: same predictions, once
    assert preds.read_text().strip().splitlines() == preds_clean


def test_supervised_kill_recovery_kafka_source(tmp_path):
    """Same kill/recover contract over the (file-backed) Kafka source:
    the restart seeks every assigned partition back to its checkpointed
    offset — rows conserve exactly and the statistics match a fault-free
    consumption of the same topics."""
    sys.path.insert(0, TESTS)
    import fskafka

    broker = tmp_path / "broker"
    os.environ["FSKAFKA_DIR"] = str(broker)
    try:
        for i, line in enumerate(_rows(600, seed=3)):
            fskafka.append("trainingData", line, partition=i % 2)
        fskafka.append("requests", _create())
    finally:
        os.environ.pop("FSKAFKA_DIR", None)

    kafka = ["--kafkaBrokers", "fs://local", "--workerBoot", FSKAFKA_BOOT]
    env = {"FSKAFKA_DIR": str(broker)}
    # the supervisor route works for the clean run too (0 faults injected)
    clean, _ = _run(
        kafka + ["--supervise", "true", "--processes", "1"],
        "kclean", tmp_path, env_extra=env,
    )
    assert _stat(clean)["fitted"] + clean["holdout"]["0"] == 600

    recovered, err = _run(
        kafka + [
            "--supervise", "true", "--processes", "1",
            "--checkpointDir", str(tmp_path / "kckpts"),
            "--checkpointEvery", "1",
            "--failProcess", "0", "--failAfterRecords", "400",
            "--restartAttempts", "2", "--restartDelayMs", "50",
        ],
        "ksup", tmp_path, env_extra=env,
    )
    assert "injected crash" in err
    assert "relaunching fleet from latest consistent checkpoint" in err
    _assert_converged(recovered, clean)


@pytest.mark.parametrize("mode", ["truncate", "withhold"])
def test_corrupt_checkpoint_shard_falls_back(tmp_path, mode, clean_file_run):
    """A snapshot with a corrupt (torn-write truncated) or withheld
    (lost-file) shard must not be restored — and must not crash restore.
    The fleet falls back to the previous COMPLETE snapshot, prunes the bad
    one, and still converges to the fault-free statistics. The truncate
    variant then corrupts the LAST remaining snapshot too and asserts the
    next restore degrades all the way to a fresh run (Flink restoring an
    uncheckpointed job) instead of crashing or half-loading."""
    base, clean, _preds = clean_file_run
    ckpt = tmp_path / "ckpts"

    # snapshots at chunks 2 (seq 0) and 4 (seq 1); the injector corrupts
    # seq 1 right after it commits, then the whole fleet dies at chunk 5
    _run(
        base + [
            "--checkpointDir", str(ckpt), "--checkpointEvery", "2",
            "--corruptShardProcess", "0", "--corruptShardSeq", "1",
            "--corruptShardMode", mode,
            "--failAfterChunks", "5",
        ],
        "faulted", tmp_path, expect_rc=3,
    )
    assert (ckpt / "ckpt-0").is_dir()
    recovered, err = _run(
        base + ["--checkpointDir", str(ckpt), "--restore", "true"],
        "resumed", tmp_path,
    )
    assert "failed validation" in err
    assert "falling back from ckpt-1 to ckpt-0" in err
    assert "restored; resuming at row 200" in err
    # the unusable snapshot was pruned so no later incarnation retries it
    assert not (ckpt / "ckpt-1").exists()
    _assert_converged(recovered, clean)

    if mode != "truncate":
        return
    # the disk fault now hits the only remaining snapshot as well: restore
    # must degrade to a fresh start, never crash or half-load
    shard = ckpt / "ckpt-0" / "proc0.npz"
    shard.write_bytes(shard.read_bytes()[: shard.stat().st_size // 2])
    fresh, err = _run(
        base + ["--checkpointDir", str(ckpt), "--restore", "true"],
        "fresh", tmp_path,
    )
    assert "no usable distributed snapshot" in err
    _assert_converged(fresh, clean)


def test_broker_severed_mid_stream_degrades(tmp_path):
    """The broker dying WHILE the job streams (injector renames the
    file-backed broker away at chunk 2) must not crash the pump loop:
    consumption goes idle (the agreed termination fires), topic
    publication degrades to warnings, and the run still exits 0 with its
    report on the file sink."""
    sys.path.insert(0, TESTS)
    import fskafka

    broker = tmp_path / "broker"
    os.environ["FSKAFKA_DIR"] = str(broker)
    try:
        # forecast rows so predictions exist and topic publication (no
        # --predictionsOut) is attempted against the severed broker
        for i, line in enumerate(_rows(600, seed=5, forecast_every=50)):
            fskafka.append("trainingData", line, partition=i % 2)
        fskafka.append("requests", _create())
    finally:
        os.environ.pop("FSKAFKA_DIR", None)

    report, err = _run(
        ["--kafkaBrokers", "fs://local", "--workerBoot", FSKAFKA_BOOT,
         "--supervise", "true", "--processes", "1",
         "--severBrokerAfterChunks", "2",
         "--restartAttempts", "0"],
        "sever", tmp_path, env_extra={"FSKAFKA_DIR": str(broker)},
    )
    assert "severed file-backed broker" in err
    # rows ingested before the cut were trained; the job finished cleanly
    s = _stat(report)
    assert 0 < s["fitted"] + report["holdout"]["0"] <= 600
    # publication was attempted against the dead broker and degraded
    assert "dropping record" in err


@pytest.mark.slow
def test_supervised_kill_recovery_with_duplicating_broker(tmp_path):
    """--supervise + chaos: the worker is killed mid-stream AND the broker
    duplicates records (seeded ChaosConsumer, --kafkaChaos) — the
    at-least-once misbehavior a real broker shows during replay after a
    restart. Recovery must still converge: every unique row trains at
    least once (duplicates can only ADD training passes, never lose rows),
    the holdout score lands in the fault-free envelope, and nothing
    crashes."""
    sys.path.insert(0, TESTS)
    import fskafka

    broker = tmp_path / "broker"
    os.environ["FSKAFKA_DIR"] = str(broker)
    try:
        for i, line in enumerate(_rows(600, seed=7)):
            fskafka.append("trainingData", line, partition=i % 2)
        fskafka.append("requests", _create())
    finally:
        os.environ.pop("FSKAFKA_DIR", None)

    kafka = ["--kafkaBrokers", "fs://local", "--workerBoot", FSKAFKA_BOOT]
    env = {"FSKAFKA_DIR": str(broker)}
    clean, _ = _run(
        kafka + ["--supervise", "true", "--processes", "1"],
        "dupclean", tmp_path, env_extra=env,
    )
    sc = _stat(clean)

    recovered, err = _run(
        kafka + [
            "--supervise", "true", "--processes", "1",
            "--checkpointDir", str(tmp_path / "dupckpts"),
            "--checkpointEvery", "1",
            "--failProcess", "0", "--failAfterRecords", "400",
            "--restartAttempts", "2", "--restartDelayMs", "50",
            "--kafkaChaos", "seed=9,dup=0.1",
        ],
        "dupsup", tmp_path, env_extra=env,
    )
    assert "kafka consumer chaos armed" in err
    assert "injected crash" in err
    assert "relaunching fleet from latest consistent checkpoint" in err
    sr = _stat(recovered)
    # at-least-once: duplicates only add training passes — rows conserve
    assert sr["fitted"] + recovered["holdout"]["0"] >= 600
    # and the model still converges. (The duplicated records change which
    # rows land in the 32-point holdout window, so the two scores are
    # measured on different samples — an absolute convergence floor is the
    # meaningful envelope here, not a tight delta.)
    assert sc["score"] > 0.8
    assert sr["score"] > 0.8


@pytest.mark.slow
def test_supervised_kill_chosen_worker_two_processes(tmp_path):
    """The acceptance scenario at full cluster shape: TWO real worker
    processes over gloo collectives, the injector kills worker 1 only,
    the supervisor detects the death (exit-code channel), tears down the
    surviving peer wedged in its collective (heartbeat channel standing
    by), and relaunches the whole fleet from the snapshot — statistics
    equal to the fault-free two-process run."""
    train = tmp_path / "train.jsonl"
    reqs = tmp_path / "reqs.jsonl"
    train.write_text("\n".join(_rows(1200)) + "\n")
    reqs.write_text(_create() + "\n")
    base = [
        "--requests", str(reqs), "--trainingData", str(train),
        "--chunkRows", "200",
        "--supervise", "true", "--processes", "2",
        "--heartbeatTimeoutMs", "120000",
    ]
    clean, _ = _run(base, "clean2p", tmp_path, timeout=420)
    # the injector re-arms on every incarnation (flags are re-passed), so
    # each restart advances the checkpoint floor by one cadence until the
    # remaining stream is shorter than the kill threshold: crashes at rows
    # 600 (floor 400) and 600-past-restore (floor 800), then the 400-row
    # tail survives — two restarts needed, exercising repeated recovery
    recovered, err = _run(
        base + [
            "--checkpointDir", str(tmp_path / "ckpts"),
            "--checkpointEvery", "2",
            "--failProcess", "1", "--failAfterRecords", "500",
            "--restartAttempts", "2", "--restartDelayMs", "100",
        ],
        "sup2p", tmp_path, timeout=420,
    )
    assert "injected crash: worker 1 after" in err
    assert "fleet failure (process 1 exited 3)" in err
    assert "relaunching fleet from latest consistent checkpoint" in err
    _assert_converged(recovered, clean)
