"""Background prefetch (runtime/prefetch.py): error propagation.

Regression pins for the producer's exception path: a source that raises
while the bounded queue is FULL must still deliver the exception to the
consumer (the old fire-and-forget error put could be dropped/stuck, so the
consumer hung until sentinel starvation), and an abandoned iterator must
release the producer thread.
"""

import threading
import time

import pytest

from omldm_tpu.runtime.prefetch import prefetch


def _drain_with_watchdog(it, consume_delay=0.0, timeout=10.0):
    """Consume ``it`` on a worker thread so a hung iterator fails the test
    instead of hanging the suite; returns (items, exception)."""
    out = {"items": [], "exc": None}

    def run():
        try:
            for item in it:
                out["items"].append(item)
                if consume_delay:
                    time.sleep(consume_delay)
        except BaseException as e:  # noqa: BLE001 - the assertion target
            out["exc"] = e

    t = threading.Thread(target=run, daemon=True)
    t.start()
    t.join(timeout)
    assert not t.is_alive(), "consumer hung: the source's error was lost"
    return out["items"], out["exc"]


class TestPrefetchErrors:
    def test_error_propagates_when_queue_full(self):
        """The regression: depth-1 queue, a slow consumer keeps it full at
        the moment the source raises — the stop-aware error put must wait
        for a slot and deliver, after every buffered item."""

        def source():
            yield 1
            yield 2
            raise RuntimeError("boom")

        items, exc = _drain_with_watchdog(
            prefetch(source(), depth=1), consume_delay=0.3
        )
        assert items == [1, 2]
        assert isinstance(exc, RuntimeError) and "boom" in str(exc)

    def test_error_before_first_item(self):
        def source():
            raise ValueError("early")
            yield  # pragma: no cover

        items, exc = _drain_with_watchdog(prefetch(source(), depth=2))
        assert items == []
        assert isinstance(exc, ValueError)

    def test_clean_stream_unchanged(self):
        items, exc = _drain_with_watchdog(prefetch(iter(range(64)), depth=2))
        assert items == list(range(64))
        assert exc is None

    def test_abandoned_consumer_releases_producer(self):
        """Breaking out of the iterator (stop set in the finally) must let
        the producer exit even when it is mid-retry on a full queue —
        including the raising producer's error put."""

        def source():
            for i in range(100):
                yield i
            raise RuntimeError("never consumed")

        before = threading.active_count()
        it = prefetch(source(), depth=1)
        assert next(it) == 0
        it.close()  # generator finally -> stop.set()
        deadline = time.time() + 10.0
        while threading.active_count() > before and time.time() < deadline:
            time.sleep(0.05)
        assert threading.active_count() <= before, (
            "producer thread still alive after the consumer abandoned"
        )
