"""DistributedJobSupervisor mechanics, isolated from jax.

The supervisor never imports jax (it only spawns/monitors worker
processes), so its restart policy, health channels, and flag plumbing are
testable with trivial stand-in workers — each a tiny ``python -c`` script
injected via ``worker_cmd``. The full-stack recovery paths (real jax
workers, checkpoints, source replay) live in test_supervised_recovery.py.

Reference counterpart: Flink's JobManager restart handling —
``RestartStrategies.fixedDelayRestart(attempts, delay)`` (Job.scala:14)
plus TaskManager heartbeat-loss detection.
"""

import os
import sys
import time

import pytest

from omldm_tpu.runtime.supervisor import (
    DistributedJobSupervisor,
    FleetFailure,
    supervise_from_flags,
)

# worker that logs its argv, then exits 1 on the first incarnation (state
# file absent) and 0 on the second — the transient failure a fixed-delay
# restart is for
FLAKY = """
import os, sys
args = dict(zip(sys.argv[1::2], sys.argv[2::2]))
with open(args["--argvLog"], "a") as f:
    f.write(" ".join(sys.argv[1:]) + "\\n")
marker = args["--marker"]
if os.path.exists(marker):
    sys.exit(0)
open(marker, "w").close()
sys.exit(1)
"""

# worker that beats once, then wedges (a process stuck in a collective
# whose peer died: alive, silent, never exits)
WEDGED = """
import os, sys, time
args = dict(zip(sys.argv[1::2], sys.argv[2::2]))
d = args["--heartbeatDir"]
os.makedirs(d, exist_ok=True)
with open(os.path.join(d, "proc%s.hb" % args["--processId"]), "w") as f:
    f.write("beat")
time.sleep(300)
"""


def _supervisor(tmp_path, script, nproc=1, extra_args=(), **kw):
    return DistributedJobSupervisor(
        list(extra_args),
        nproc,
        worker_cmd=[sys.executable, "-c", script],
        run_dir=str(tmp_path / "run"),
        **kw,
    )


def test_flaky_worker_restarts_and_succeeds(tmp_path):
    argv_log = tmp_path / "argv.log"
    sup = _supervisor(
        tmp_path, FLAKY, max_restarts=1,
        extra_args=["--marker", str(tmp_path / "marker"),
                    "--argvLog", str(argv_log)],
    )
    assert sup.run() == 0
    [rec] = sup.failures
    assert rec.attempt == 1
    assert "exited 1" in rec.cause
    assert rec.failed == [0]
    assert not rec.restored  # no --checkpointDir in worker_args
    first, second = argv_log.read_text().strip().splitlines()
    # the relaunch — and only the relaunch — carries --restore true
    assert "--restore true" not in first
    assert "--restore true" in second


def test_restart_budget_exhausts_with_incident_log(tmp_path):
    sup = _supervisor(
        tmp_path, "import sys; sys.exit(7)", max_restarts=2,
        extra_args=["--x", "y"],
    )
    with pytest.raises(FleetFailure) as exc_info:
        sup.run()
    assert exc_info.value.returncode == 7
    # every attempt (initial + 2 restarts) is an incident
    assert [r.attempt for r in sup.failures] == [1, 2, 3]
    assert all("exited 7" in r.cause for r in sup.failures)


def test_heartbeat_timeout_detects_wedged_worker(tmp_path):
    sup = _supervisor(
        tmp_path, WEDGED, max_restarts=0, heartbeat_timeout_s=0.4,
    )
    start = time.monotonic()
    with pytest.raises(FleetFailure) as exc_info:
        sup.run()
    # detected by staleness, well before the worker's 300s sleep ends,
    # and the wedged process was killed on the way out
    assert time.monotonic() - start < 30
    assert "heartbeat timeout" in exc_info.value.cause
    assert exc_info.value.failed == [0]


def test_never_beating_worker_times_out_from_spawn_clock(tmp_path):
    # no beat file ever appears: the timeout clock runs from spawn
    sup = _supervisor(
        tmp_path, "import time; time.sleep(300)",
        max_restarts=0, heartbeat_timeout_s=0.4,
    )
    start = time.monotonic()
    with pytest.raises(FleetFailure, match="heartbeat timeout"):
        sup.run()
    assert time.monotonic() - start < 30


def test_one_bad_worker_fails_whole_fleet(tmp_path):
    # Flink's global restart: any lost TaskManager restarts the job, so a
    # healthy peer must be torn down with the failed one
    script = """
import sys, time
args = dict(zip(sys.argv[1::2], sys.argv[2::2]))
sys.exit(3) if args["--processId"] == "1" else time.sleep(300)
"""
    sup = _supervisor(tmp_path, script, nproc=2, max_restarts=0)
    start = time.monotonic()
    with pytest.raises(FleetFailure) as exc_info:
        sup.run()
    assert time.monotonic() - start < 30  # peer was killed, not awaited
    assert exc_info.value.failed == [1]


def test_supervise_from_flags_passthrough_and_exit_code(tmp_path):
    # the CLI adapter: supervisor-only flags are consumed, everything else
    # reaches the worker; exhausted restarts surface the worker's code
    rc = supervise_from_flags({
        "supervise": "true",
        "processes": "1",
        "restartAttempts": "1",
        "restartDelayMs": "0",
        "supervisorDir": str(tmp_path / "run"),
        "workerBoot": (
            "import sys; "
            "assert '--restartAttempts' not in sys.argv; "
            "assert '--processes' in sys.argv; "
            "sys.exit(5)"
        ),
    })
    assert rc == 5
