"""Compile sharing across multiplexed pipelines (SURVEY.md section 7 hard
part (f)): K pipelines with identical (learner, preprocessors, dim,
per_record) share ONE set of jitted step programs — the K-th identical
Create costs zero recompiles (the reference hosts one wrapper per network
over shared JVM code, SpokeLogic.scala:28-29)."""

import json

import numpy as np

from omldm_tpu.api.requests import LearnerSpec, PreprocessorSpec
from omldm_tpu.pipelines import MLPipeline


def _spec():
    return LearnerSpec("PA", hyper_parameters={"C": 1.0, "variant": "PA-I"})


def test_ten_pipelines_share_jitted_steps_and_compile_once():
    pipes = [
        MLPipeline(_spec(), [PreprocessorSpec("StandardScaler")], dim=12)
        for _ in range(10)
    ]
    # the mechanism: one shared jit callable object across all instances
    for p in pipes[1:]:
        assert p._fit is pipes[0]._fit
        assert p._predict is pipes[0]._predict
        assert p._fit_many is pipes[0]._fit_many
    rng = np.random.RandomState(0)
    x = rng.randn(32, 12).astype(np.float32)
    y = (x.sum(axis=1) > 0).astype(np.float32)
    m = np.ones(32, np.float32)
    for p in pipes:
        p.fit(x, y, m)
    # the compile counter: ONE traced/compiled entry serves all 10
    assert pipes[0]._fit._cache_size() == 1


def test_distinct_specs_do_not_share():
    a = MLPipeline(_spec(), dim=12)
    b = MLPipeline(
        LearnerSpec("PA", hyper_parameters={"C": 2.0, "variant": "PA-I"}),
        dim=12,
    )
    c = MLPipeline(_spec(), dim=16)
    assert a._fit is not b._fit  # different hyper-parameters
    assert a._fit is not c._fit  # different dim


def test_shared_programs_keep_states_independent():
    a = MLPipeline(_spec(), dim=8)
    b = MLPipeline(_spec(), dim=8)
    assert a._fit is b._fit
    rng = np.random.RandomState(1)
    xa = rng.randn(16, 8).astype(np.float32)
    ya = (xa.sum(axis=1) > 0).astype(np.float32)
    m = np.ones(16, np.float32)
    a.fit(xa, ya, m)  # only a trains
    fa, _ = a.get_flat_params()
    fb, _ = b.get_flat_params()
    assert np.abs(fa).sum() > 0
    assert np.abs(fb).sum() == 0  # b untouched
    assert a.fitted == 16 and b.fitted == 0


def test_job_level_multiplexing_shares_compiles():
    """10 identical Creates through the streaming runtime: every spoke-net
    pipeline multiplexes through the same programs."""
    from omldm_tpu.config import JobConfig
    from omldm_tpu.runtime import StreamJob
    from omldm_tpu.runtime.job import REQUEST_STREAM, TRAINING_STREAM

    job = StreamJob(JobConfig(parallelism=2, batch_size=32, test_set_size=16))
    for i in range(10):
        job.process_event(REQUEST_STREAM, json.dumps({
            "id": i, "request": "Create",
            "learner": {"name": "SVM", "hyperParameters": {"lambda": 1e-3}},
            "preProcessors": [],
            "trainingConfiguration": {"protocol": "Asynchronous"},
        }))
    rng = np.random.RandomState(2)
    for _ in range(300):
        x = rng.randn(6)
        job.process_event(TRAINING_STREAM, json.dumps({
            "numericalFeatures": [round(float(v), 5) for v in x],
            "target": float(x.sum() > 0),
        }))
    fits = {
        net.pipeline._fit
        for spoke in job.spokes
        for net in spoke.nets.values()
    }
    assert len(fits) == 1  # 20 pipeline replicas, one traced program
    assert next(iter(fits))._cache_size() <= 2
