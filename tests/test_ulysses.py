"""Ulysses all_to_all sequence parallelism: equals full attention and ring."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from omldm_tpu.models.transformer import TransformerConfig
from omldm_tpu.ops.attention import mha_reference
from omldm_tpu.ops.ulysses import ulysses_attention_sharded
from omldm_tpu.parallel.seq_trainer import SeqTrainer, make_seq_mesh


def _qkv(b=2, l=64, h=4, dh=8, seed=0):
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(seed), 3)
    return (
        jax.random.normal(k1, (b, l, h, dh), jnp.float32),
        jax.random.normal(k2, (b, l, h, dh), jnp.float32),
        jax.random.normal(k3, (b, l, h, dh), jnp.float32),
    )


@pytest.mark.parametrize("sp", [2, 4])
@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_full(sp, causal):
    mesh = Mesh(np.asarray(jax.devices()[:sp]), ("sp",))
    q, k, v = _qkv()
    ref = mha_reference(q, k, v, causal=causal)
    out = ulysses_attention_sharded(q, k, v, mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_ulysses_rejects_indivisible_heads():
    mesh = Mesh(np.asarray(jax.devices()[:8]), ("sp",))
    q, k, v = _qkv(h=4)  # 4 heads over 8-way sp
    with pytest.raises(ValueError, match="not divisible"):
        ulysses_attention_sharded(q, k, v, mesh, causal=False)


def test_seqtrainer_ulysses_matches_ring():
    """The two sequence-parallel strategies train identically (same math,
    different collectives)."""

    def build(strategy, dp, sp, tp):
        cfg = TransformerConfig(
            vocab_size=32, d_model=16, n_heads=4, n_layers=2, d_ff=32,
            max_len=64, seq_parallel=strategy,
        )
        return SeqTrainer(cfg, mesh=make_seq_mesh(dp, sp, tp), lr=1e-2, seed=21)

    rng = np.random.RandomState(0)
    base = rng.randint(1, 32, size=(4, 4))
    toks = np.tile(base, (1, 5))[:, :17]
    tokens = toks[:, :-1].astype(np.int32)
    targets = toks[:, 1:].astype(np.int32)
    mask = np.ones((4, 16), np.float32)

    ring = build("ring", 2, 2, 2)
    uly = build("ulysses", 2, 2, 2)
    single = build("ring", 1, 1, 1)
    for _ in range(3):
        l_ring = ring.step(tokens, targets, mask)
        l_uly = uly.step(tokens, targets, mask)
        l_one = single.step(tokens, targets, mask)
    np.testing.assert_allclose(
        float(np.asarray(l_ring)), float(np.asarray(l_uly)), atol=1e-4
    )
    np.testing.assert_allclose(
        float(np.asarray(l_one)), float(np.asarray(l_uly)), atol=1e-4
    )
    for a, b in zip(
        jax.tree_util.tree_leaves(ring.host_params()),
        jax.tree_util.tree_leaves(uly.host_params()),
    ):
        np.testing.assert_allclose(a, b, atol=2e-4)
