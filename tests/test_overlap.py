"""Double-buffered (overlapped) fused ingest: SPMDBridge.ingest_file_overlapped.

Pins the two properties the e2e benchmark's overlapped measurement rests on:

1. EQUIVALENCE — stages are dispatched strictly in order, so the overlapped
   run trains the exact same launch sequence as the serial fused loop:
   identical parameters, fitted count, holdout ring and predictions
   (including mid-stream forecasts and Python-fallback lines, which quiesce
   the dispatch queue before running inline).
2. OVERLAP — the parse thread demonstrably keeps parsing while the
   dispatch thread is busy: with a sleeping device stub, later chunks are
   parsed strictly inside an earlier stage's train interval.
"""

import json
import time

import numpy as np
import pytest

from omldm_tpu.config import JobConfig
from omldm_tpu.ops.native import fast_parser_available
from omldm_tpu.runtime import StreamJob
from omldm_tpu.runtime.job import REQUEST_STREAM

pytestmark = pytest.mark.skipif(
    not fast_parser_available(), reason="native parser unavailable"
)

DIM = 10


def _request(extra=None):
    return {
        "id": 0,
        "request": "Create",
        "learner": {
            "name": "PA",
            "hyperParameters": {"C": 0.1},
            "dataStructure": {"nFeatures": DIM},
        },
        "preProcessors": [],
        "trainingConfiguration": {
            "protocol": "Synchronous",
            "engine": "spmd",
            "extra": {"stageChain": 2, **(extra or {})},
        },
    }


def _write_stream(path, n=6000, seed=0, specials=True):
    rng = np.random.RandomState(seed)
    w = rng.randn(DIM)
    with open(path, "w") as f:
        for i in range(n):
            x = np.round(rng.randn(DIM), 6)
            y = 1.0 if float(x @ w) > 0 else -1.0
            if specials and i % 613 == 100:
                f.write(json.dumps({
                    "numericalFeatures": [round(float(v), 6) for v in x],
                    "operation": "forecasting",
                }) + "\n")
                continue
            if specials and i % 509 == 77:
                # categorical features force the Python-codec fallback
                f.write(json.dumps({
                    "numericalFeatures": [round(float(v), 6) for v in x],
                    "categoricalFeatures": ["blue"],
                    "target": y,
                    "operation": "training",
                }) + "\n")
                continue
            f.write(json.dumps({
                "numericalFeatures": [round(float(v), 6) for v in x],
                "target": y,
                "operation": "training",
            }) + "\n")


def _make_bridge():
    preds = []
    config = JobConfig(
        parallelism=2, batch_size=32, test=True, test_set_size=32
    )
    job = StreamJob(config)
    job.set_sinks(on_prediction=preds.append)
    job.process_event(REQUEST_STREAM, json.dumps(_request()))
    [bridge] = job.spmd_bridges.values()
    return job, bridge, preds


def _flat(bridge):
    return bridge.trainer.global_flat_params()


class TestOverlappedIngest:
    def test_bit_identical_to_serial_fused(self, tmp_path):
        path = tmp_path / "stream.jsonl"
        _write_stream(str(path))

        _, serial, serial_preds = _make_bridge()
        serial.ingest_file(str(path))
        serial.flush()

        _, over, over_preds = _make_bridge()
        over.ingest_file_overlapped(str(path), depth=2)
        over.flush()

        assert over.trainer.fitted == serial.trainer.fitted
        assert len(over.test_set) == len(serial.test_set)
        np.testing.assert_array_equal(_flat(over), _flat(serial))
        sx, sy = serial.test_set.arrays()
        ox, oy = over.test_set.arrays()
        np.testing.assert_array_equal(ox, sx)
        np.testing.assert_array_equal(oy, sy)
        # forecasts emitted in order with identical values
        assert len(over_preds) == len(serial_preds) > 0
        for a, b in zip(over_preds, serial_preds):
            assert a.value == b.value

    def test_small_chunks_and_deep_queue(self, tmp_path):
        """Chunk boundaries (partial lines carried) and a deeper buffer
        pool must not change the result."""
        path = tmp_path / "stream.jsonl"
        _write_stream(str(path), n=3000, specials=False)
        _, serial, _ = _make_bridge()
        serial.ingest_file(str(path))
        serial.flush()
        _, over, _ = _make_bridge()
        over.ingest_file_overlapped(str(path), chunk_bytes=777, depth=4)
        over.flush()
        assert over.trainer.fitted == serial.trainer.fitted
        np.testing.assert_array_equal(_flat(over), _flat(serial))

    def test_parse_proceeds_during_device_time(self, tmp_path):
        """With a sleeping device stub, chunk parses land strictly inside
        a stage's train interval — the parse thread did not wait for the
        'device'."""
        path = tmp_path / "stream.jsonl"
        _write_stream(str(path), n=4000, specials=False)
        _, bridge, _ = _make_bridge()
        intervals = []
        chunk_times = []

        def stub(sx, sy, n):
            t0 = time.perf_counter()
            time.sleep(0.15)
            intervals.append((t0, time.perf_counter()))

        bridge.ingest_file_overlapped(
            str(path), chunk_bytes=4096, depth=2, train_fn=stub,
            on_chunk=lambda: chunk_times.append(time.perf_counter()),
        )
        assert len(intervals) >= 2 and len(chunk_times) >= 3
        overlapped = any(
            a < t < b for t in chunk_times for (a, b) in intervals
        )
        assert overlapped, (
            "no chunk was parsed during any train interval: "
            f"{chunk_times} vs {intervals}"
        )

    def test_worker_exception_propagates(self, tmp_path):
        path = tmp_path / "stream.jsonl"
        _write_stream(str(path), n=4000, specials=False)
        _, bridge, _ = _make_bridge()

        def boom(sx, sy, n):
            raise RuntimeError("device on fire")

        with pytest.raises(RuntimeError, match="device on fire"):
            bridge.ingest_file_overlapped(
                str(path), chunk_bytes=4096, train_fn=boom
            )

    def test_sparse_overlapped_matches_serial(self, tmp_path):
        """The sparse (padded-COO) double-buffered ingest dispatches stage
        sets strictly in order: identical trained params, fitted count,
        holdout and predictions to the serial COO route — including
        mid-stream forecasts (which quiesce the dispatch queue) and
        escape-bearing fallback lines."""
        import json as _json

        rng = np.random.RandomState(3)
        path = tmp_path / "sparse.jsonl"
        with open(path, "w") as f:
            for i in range(4000):
                nums = [round(float(v), 6) for v in rng.randn(5)]
                cats = [f"c{j}_{rng.randint(50)}" for j in range(6)]
                if i % 701 == 200:
                    f.write(_json.dumps({
                        "numericalFeatures": nums,
                        "categoricalFeatures": cats,
                        "operation": "forecasting",
                    }) + "\n")
                    continue
                if i % 997 == 500:  # escaped category -> Python fallback
                    cats[0] = 'a"b'
                f.write(_json.dumps({
                    "numericalFeatures": nums,
                    "categoricalFeatures": cats,
                    "target": float(rng.randint(2)),
                    "operation": "training",
                }) + "\n")

        def make_sparse_bridge():
            preds = []
            config = JobConfig(
                parallelism=2, batch_size=32, test=True, test_set_size=32
            )
            job = StreamJob(config)
            job.set_sinks(on_prediction=preds.append)
            job.process_event(REQUEST_STREAM, json.dumps({
                "id": 0, "request": "Create",
                "learner": {
                    "name": "PA", "hyperParameters": {"C": 0.5},
                    "dataStructure": {
                        "sparse": True, "nFeatures": 5 + 512,
                        "hashSpace": 512, "maxNnz": 12,
                    },
                },
                "trainingConfiguration": {
                    "protocol": "Synchronous", "engine": "spmd",
                    "extra": {"stageChain": 2},
                },
            }))
            [bridge] = job.spmd_bridges.values()
            return bridge, preds

        serial, s_preds = make_sparse_bridge()
        serial.ingest_file(str(path))
        serial.flush()
        over, o_preds = make_sparse_bridge()
        over.ingest_file_overlapped(str(path), depth=2)
        over.flush()
        assert over.trainer.fitted == serial.trainer.fitted > 0
        assert len(over.test_set) == len(serial.test_set)
        np.testing.assert_array_equal(_flat(over), _flat(serial))
        assert len(o_preds) == len(s_preds) > 0
        for a, b in zip(o_preds, s_preds):
            assert a.value == b.value

    def test_ssp_rejected(self, tmp_path):
        preds = []
        config = JobConfig(
            parallelism=2, batch_size=32, test=True, test_set_size=32
        )
        job = StreamJob(config)
        job.set_sinks(on_prediction=preds.append)
        req = _request(extra={"staleness": 1})
        req["trainingConfiguration"]["protocol"] = "SSP"
        job.process_event(REQUEST_STREAM, json.dumps(req))
        [bridge] = job.spmd_bridges.values()
        path = tmp_path / "stream.jsonl"
        _write_stream(str(path), n=200, specials=False)
        with pytest.raises(ValueError, match="overlapped ingest"):
            bridge.ingest_file_overlapped(str(path))
