"""CLI entry-point tests: ``python -m omldm_tpu`` file-replay jobs
(the Job.main analogue, reference Job.scala:110-171)."""

import json

import numpy as np
import pytest

from omldm_tpu.__main__ import build_job, combined_events, main, parse_flags


def _write_stream(path, n=800, dim=6, seed=0):
    rng = np.random.RandomState(seed)
    w = rng.randn(dim)
    x = rng.randn(n, dim)
    y = (x @ w > 0).astype(float)
    with open(path, "w") as f:
        for i in range(n):
            f.write(
                json.dumps(
                    {
                        "numericalFeatures": list(np.round(x[i], 5)),
                        "target": y[i],
                        "operation": "training",
                    }
                )
                + "\n"
            )
        f.write("EOS\n")
    return x, y


CREATE = {
    "id": 0,
    "request": "Create",
    "learner": {"name": "PA", "hyperParameters": {"C": 1.0}},
    "preProcessors": [],
    "trainingConfiguration": {"protocol": "CentralizedTraining"},
}


class TestParseFlags:
    def test_pairs_and_booleans(self):
        flags = parse_flags(
            ["--parallelism", "4", "--test", "--jobName", "run1"]
        )
        assert flags == {"parallelism": "4", "test": "true", "jobName": "run1"}

    def test_rejects_positional(self):
        with pytest.raises(SystemExit):
            parse_flags(["oops"])


class TestFileReplayJob:
    def test_end_to_end_files(self, tmp_path):
        train = tmp_path / "train.jsonl"
        reqs = tmp_path / "requests.jsonl"
        perf = tmp_path / "perf.jsonl"
        _write_stream(str(train))
        reqs.write_text(json.dumps(CREATE) + "\n")
        rc = main(
            [
                "--trainingData", str(train),
                "--requests", str(reqs),
                "--performanceOut", str(perf),
                "--parallelism", "2",
                "--batchSize", "64",
                "--testSetSize", "32",
            ]
        )
        assert rc == 0
        [line] = perf.read_text().strip().splitlines()
        report = json.loads(line)
        [stats] = report["statistics"]
        assert stats["pipeline"] == 0
        assert stats["fitted"] > 400

    def test_fused_route_matches_packed(self, tmp_path):
        """An SPMD-plane file job takes the fused C ingest route and lands
        the same fitted count / score as the packed event route."""
        train = tmp_path / "train.jsonl"
        reqs = tmp_path / "requests.jsonl"
        _write_stream(str(train))
        create = json.loads(json.dumps(CREATE))
        create["trainingConfiguration"] = {
            "protocol": "Synchronous",
            "engine": "spmd",
        }
        create["learner"]["dataStructure"] = {"nFeatures": 6}
        reqs.write_text(json.dumps(create) + "\n")
        reports = {}
        for route, flag in (("fused", "auto"), ("packed", "false")):
            perf = tmp_path / f"perf_{route}.jsonl"
            rc = main(
                [
                    "--trainingData", str(train),
                    "--requests", str(reqs),
                    "--performanceOut", str(perf),
                    "--parallelism", "2",
                    "--batchSize", "64",
                    "--testSetSize", "32",
                    "--fusedIngest", flag,
                ]
            )
            assert rc == 0
            [line] = perf.read_text().strip().splitlines()
            [stats] = json.loads(line)["statistics"]
            reports[route] = stats
        assert reports["fused"]["fitted"] == reports["packed"]["fitted"]
        assert reports["fused"]["score"] == pytest.approx(
            reports["packed"]["score"], rel=1e-5
        )

    def test_combined_events_preserves_order(self, tmp_path):
        combined = tmp_path / "events.jsonl"
        resp_out = tmp_path / "responses.jsonl"
        rng = np.random.RandomState(1)
        dim, n = 4, 600
        w = rng.randn(dim)
        lines = [{"stream": "requests", "data": CREATE}]
        for i in range(n):
            x = rng.randn(dim)
            lines.append(
                {
                    "stream": "trainingData",
                    "data": {
                        "numericalFeatures": list(np.round(x, 5)),
                        "target": float(x @ w > 0),
                        "operation": "training",
                    },
                }
            )
        # Query arrives AFTER training — combined mode must preserve that
        lines.append(
            {
                "stream": "requests",
                "data": {"id": 0, "request": "Query", "requestId": 7},
            }
        )
        combined.write_text("\n".join(json.dumps(l) for l in lines))
        rc = main(
            [
                "--events", str(combined),
                "--responsesOut", str(resp_out),
                "--performanceOut", str(tmp_path / "perf.jsonl"),
                "--parallelism", "1",
                "--batchSize", "32",
            ]
        )
        assert rc == 0
        responses = [
            json.loads(l) for l in resp_out.read_text().strip().splitlines()
        ]
        assert any(r["responseId"] == 7 for r in responses)

    def test_no_sources_exits(self):
        with pytest.raises(SystemExit):
            main(["--parallelism", "2"])


class TestCompileCache:
    def test_compile_cache_flag_configures_jax(self, tmp_path, monkeypatch):
        """--compileCache <dir> turns on the persistent XLA compilation
        cache; 'off' leaves it untouched."""
        import jax

        from omldm_tpu.__main__ import _enable_compile_cache

        before_dir = jax.config.jax_compilation_cache_dir
        before_min = jax.config.jax_persistent_cache_min_compile_time_secs
        cache = tmp_path / "xla"
        try:
            _enable_compile_cache({"compileCache": str(cache),
                                   "compileCacheMinSecs": "0.0"})
            assert jax.config.jax_compilation_cache_dir == str(cache)
            assert cache.is_dir()
        finally:
            jax.config.update("jax_compilation_cache_dir", before_dir)
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", before_min
            )

    def test_compile_cache_off(self, monkeypatch):
        import jax

        from omldm_tpu.__main__ import _enable_compile_cache

        before = jax.config.jax_compilation_cache_dir
        _enable_compile_cache({"compileCache": "off"})
        assert jax.config.jax_compilation_cache_dir == before
