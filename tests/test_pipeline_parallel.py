"""Pipeline parallelism: pipelined loss/training == single-device exactly."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from omldm_tpu.models.transformer import TransformerConfig
from omldm_tpu.parallel.pipeline_parallel import PPTrainer, make_pp_mesh

CFG = TransformerConfig(
    vocab_size=32, d_model=16, n_heads=2, n_layers=4, d_ff=32, max_len=32,
)


def _batch(rng, b, l, vocab):
    base = rng.randint(1, vocab, size=(b, 4))
    toks = np.tile(base, (1, l // 4 + 1))[:, : l + 1]
    return (
        toks[:, :-1].astype(np.int32),
        toks[:, 1:].astype(np.int32),
        np.ones((b, l), np.float32),
    )


# pre-vma jax: manual grad_sync (jaxcompat) reorders the replicated
# leaves' gradient reduction, which exceeds this test's 1e-4 equality
# envelope on most mesh shapes; (1, 2, 8) stays live everywhere and pins
# the fallback path in tier-1.
_vma_exact = pytest.mark.skipif(
    not __import__(
        "omldm_tpu.utils.jaxcompat", fromlist=["auto_grad_sync"]
    ).auto_grad_sync(),
    reason="pre-vma jax: manual grad_sync reorder exceeds the 1e-4 "
    "equality envelope (the (1,2,8) case still pins the fallback path)",
)


@pytest.mark.parametrize("dp,pp,n_micro", [
    pytest.param(1, 4, 4, marks=_vma_exact),
    pytest.param(2, 2, 2, marks=_vma_exact),
    (1, 2, 8),
    pytest.param(2, 4, 2, marks=_vma_exact),
])
def test_pp_matches_single_device(dp, pp, n_micro):
    rng = np.random.RandomState(0)
    tokens, targets, mask = _batch(rng, 8, 16, CFG.vocab_size)
    ref = PPTrainer(CFG, mesh=make_pp_mesh(1, 1), n_micro=n_micro, lr=1e-2, seed=2)
    shr = PPTrainer(CFG, mesh=make_pp_mesh(dp, pp), n_micro=n_micro, lr=1e-2, seed=2)
    for _ in range(3):
        l_ref = ref.step(tokens, targets, mask)
        l_shr = shr.step(tokens, targets, mask)
    np.testing.assert_allclose(
        float(np.asarray(l_ref)), float(np.asarray(l_shr)), atol=1e-4
    )
    for a, b in zip(
        jax.tree_util.tree_leaves(ref.host_params()),
        jax.tree_util.tree_leaves(shr.host_params()),
    ):
        np.testing.assert_allclose(a, b, atol=2e-4)


def test_pp_training_learns():
    rng = np.random.RandomState(1)
    tokens, targets, mask = _batch(rng, 8, 16, CFG.vocab_size)
    tr = PPTrainer(CFG, mesh=make_pp_mesh(2, 4), n_micro=2, lr=3e-3, seed=3)
    first = float(np.asarray(tr.step(tokens, targets, mask)))
    for _ in range(50):
        loss = tr.step(tokens, targets, mask)
    assert float(np.asarray(loss)) < first * 0.5
    assert tr.fitted == 51 * 8 * 16


def test_pp_validates_divisibility():
    with pytest.raises(ValueError, match="not divisible by pp"):
        PPTrainer(
            TransformerConfig(vocab_size=8, d_model=8, n_heads=1, n_layers=3,
                              d_ff=8, max_len=8),
            mesh=make_pp_mesh(1, 2),
        )
