"""SPMD engine inside the streaming job: {"engine": "spmd"} pipelines train
on the collective mesh while keeping the full streaming contract."""

import json

import numpy as np

from omldm_tpu.config import JobConfig
from omldm_tpu.runtime import StreamJob
from omldm_tpu.runtime.job import (
    FORECASTING_STREAM,
    REQUEST_STREAM,
    TRAINING_STREAM,
)


def stream_lines(n, dim=6, seed=0):
    rng = np.random.RandomState(seed)
    w = rng.randn(dim)
    x = rng.randn(n, dim)
    y = (x @ w > 0).astype(np.float64)
    return [
        json.dumps(
            {"numericalFeatures": list(np.round(x[i], 5)), "target": float(y[i])}
        )
        for i in range(n)
    ]


def make_create(net_id=0, protocol="Synchronous", engine="spmd", learner="PA"):
    return {
        "id": net_id,
        "request": "Create",
        "learner": {"name": learner, "hyperParameters": {"C": 1.0}},
        "trainingConfiguration": {
            "protocol": protocol,
            "syncEvery": 2,
            "engine": engine,
        },
    }


def test_spmd_pipeline_full_lifecycle():
    job = StreamJob(JobConfig(parallelism=4, batch_size=32, test_set_size=32))
    events = [(REQUEST_STREAM, json.dumps(make_create()))] + [
        (TRAINING_STREAM, l) for l in stream_lines(3000)
    ]
    report = job.run(events)
    assert 0 in job.spmd_bridges
    assert report is not None
    [stats] = report.statistics
    assert stats.protocol == "Synchronous"
    assert stats.score > 0.85, stats.score
    assert stats.fitted > 2000
    assert stats.bytes_shipped > 0 and stats.models_shipped > 0
    assert len(stats.learning_curve) > 0


def test_spmd_pipeline_forecasting_and_query():
    job = StreamJob(JobConfig(parallelism=2, batch_size=32, test_set_size=32))
    rng = np.random.RandomState(1)
    query = {"id": 0, "request": "Query", "requestId": 9}
    events = (
        [(REQUEST_STREAM, json.dumps(make_create(protocol="GM")))]
        + [(TRAINING_STREAM, l) for l in stream_lines(1200)]
        + [
            (FORECASTING_STREAM, json.dumps(
                {"id": i, "numericalFeatures": list(np.round(rng.randn(6), 4))}
            ))
            for i in range(5)
        ]
        + [(REQUEST_STREAM, json.dumps(query))]
    )
    job.run(events)
    assert len(job.predictions) == 5
    user = [r for r in job.responses if r.response_id == 9]
    assert user, "no query response from the spmd pipeline"
    assert user[0].learner["name"] == "PA"
    # the merger re-assembles the param buckets into one "values" vector
    assert len(user[0].learner.get("parameters", {}).get("values", [])) > 0
    assert user[0].protocol == "GM"


def test_mixed_host_and_spmd_pipelines():
    """A host-plane pipeline and an SPMD-engine pipeline coexist; both learn."""
    job = StreamJob(JobConfig(parallelism=2, batch_size=32, test_set_size=32))
    events = (
        [
            (REQUEST_STREAM, json.dumps(make_create(net_id=0, engine="spmd"))),
            (REQUEST_STREAM, json.dumps(
                make_create(net_id=1, engine="", protocol="Asynchronous")
            )),
        ]
        + [(TRAINING_STREAM, l) for l in stream_lines(2400)]
    )
    report = job.run(events)
    assert report is not None
    by_id = {s.pipeline: s for s in report.statistics}
    assert set(by_id) == {0, 1}
    assert by_id[0].score > 0.8, f"spmd: {by_id[0].score}"
    assert by_id[1].score > 0.8, f"host: {by_id[1].score}"


def test_spmd_delete_removes_bridge():
    job = StreamJob(JobConfig(parallelism=2, batch_size=16, test_set_size=16))
    delete = {"id": 0, "request": "Delete"}
    events = (
        [(REQUEST_STREAM, json.dumps(make_create()))]
        + [(TRAINING_STREAM, l) for l in stream_lines(200)]
        + [(REQUEST_STREAM, json.dumps(delete))]
    )
    job.run(events, terminate_on_end=False)
    assert 0 not in job.spmd_bridges


def test_unsupported_protocol_falls_back_to_host_plane():
    """engine=spmd with a non-collective protocol deploys on the host plane."""
    job = StreamJob(JobConfig(parallelism=1, batch_size=16, test_set_size=16))
    events = [
        (REQUEST_STREAM, json.dumps(
            make_create(protocol="CentralizedTraining", engine="spmd")
        )),
    ] + [(TRAINING_STREAM, l) for l in stream_lines(200)]
    report = job.run(events)
    assert 0 not in job.spmd_bridges
    assert report is not None  # trained on the host plane instead
