"""Overload-control plane (runtime/overload.py).

Pins, per ISSUE 10 acceptance:

- ``overload`` unset runs the exact pre-plane routes — no controller
  objects anywhere — across the composition matrix (cohort x codec int8
  x guard x serving exact), and an ARMED controller under uniform
  traffic is bit-identical to unarmed (fair-share accounting can never
  flag uniform fan-out traffic);
- per-tenant fair-share admission: a flooded tenant goes over limit,
  uniform tenants never do, flags recompute at boundary ticks;
- the pressure ladder: immediate upward transitions, ``cool``-tick
  hysteresis downward, degraded (widened/relaxed) serving limits for
  over-limit tenants ONLY, idle ticks decay a paused source back to OK;
- under a seeded hot-tenant burst the hot tenant's forecasts SHED with
  reason-coded dead letters carrying the tenant + queue depth, its
  training rows deprioritize (and still train — late, never lost),
  healthy tenants shed NOTHING and serve every forecast;
- burst determinism: same seed/spec => the same shed schedule, the same
  dead-letter stream, the same counters;
- upstream backpressure: ``polling_events`` consumes nothing while
  ``pause_when`` holds (offsets untracked = replayable);
- the bounded emission mirrors, the uniform queue-depth accessors, and
  the Statistics plumbing for the new counters.
"""

import json
import types

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from omldm_tpu.api.requests import TrainingConfiguration
from omldm_tpu.api.stats import Statistics
from omldm_tpu.config import JobConfig
from omldm_tpu.runtime import StreamJob
from omldm_tpu.runtime.job import (
    FORECASTING_STREAM,
    REQUEST_STREAM,
    TRAINING_STREAM,
)
from omldm_tpu.runtime.kafka_io import polling_events
from omldm_tpu.runtime.overload import (
    CRITICAL,
    ELEVATED,
    OK,
    OverloadConfig,
    OverloadController,
    TICK_STRIDE,
    overload_config,
    parse_overload_spec,
    validate_overload,
)
from omldm_tpu.runtime.prefetch import Prefetcher, prefetch
from omldm_tpu.runtime.serving import ServingConfig
from omldm_tpu.runtime.supervisor import BurstInjector, parse_chaos_spec
from omldm_tpu.runtime.vectorizer import MicroBatcher

DIM = 8

# a controller tuned small enough that a few hundred records traverse the
# whole ladder (ELEVATED throttling -> CRITICAL shedding) and decay back
OVR = "window=8,share=2,hotHigh=6,hotCritical=12,cool=8"
SRV = {"maxBatch": 8, "maxDelayMs": 200.0}


# --- config parsing / validation ---------------------------------------------


class TestOverloadConfig:
    def test_unset_is_none(self):
        assert parse_overload_spec(None) is None
        assert parse_overload_spec(False) is None
        assert parse_overload_spec("") is None
        assert overload_config(TrainingConfiguration()) is None

    def test_defaults_and_spec_strings(self):
        assert parse_overload_spec(True) == OverloadConfig()
        assert parse_overload_spec("on") == OverloadConfig()
        cfg = parse_overload_spec(OVR)
        assert (cfg.window, cfg.share, cfg.hot_high, cfg.hot_critical,
                cfg.cool) == (8, 2.0, 6.0, 12.0, 8)
        cfg = parse_overload_spec(
            {"tenantRate": 4, "widen": 2, "relax": "false", "shed": True,
             "deferCap": 16, "queueHigh": 100, "queueCritical": 200}
        )
        assert (cfg.tenant_rate, cfg.widen, cfg.relax, cfg.shed,
                cfg.defer_cap, cfg.queue_high, cfg.queue_critical) == (
            4.0, 2.0, False, True, 16, 100, 200)

    def test_job_default_and_per_pipeline_override(self):
        tc = TrainingConfiguration()
        assert overload_config(tc, "window=16").window == 16
        tc_off = TrainingConfiguration(extra={"overload": False})
        assert overload_config(tc_off, "window=16") is None
        tc_own = TrainingConfiguration(extra={"overload": {"window": 4}})
        assert overload_config(tc_own, "window=16").window == 4

    @pytest.mark.parametrize("bad", [
        {"window": 0}, {"share": 0}, {"widen": 0.5}, {"cool": 0},
        {"hotHigh": 10, "hotCritical": 5}, {"deferCap": 0},
        {"notAKnob": 1}, "window", 7,
    ])
    def test_invalid_specs_raise_and_gate(self, bad):
        with pytest.raises((ValueError, TypeError)):
            parse_overload_spec(bad)
        tc = TrainingConfiguration(extra={"overload": bad})
        assert validate_overload(tc) is not None

    def test_bad_request_quarantined_not_fatal(self):
        job = StreamJob(JobConfig(parallelism=1))
        job.process_event(REQUEST_STREAM, json.dumps({
            "id": 0, "request": "Create",
            "learner": {"name": "PA", "hyperParameters": {"C": 1.0},
                        "dataStructure": {"nFeatures": DIM}},
            "trainingConfiguration": {"overload": {"window": 0}},
        }))
        assert 0 not in job.pipeline_manager.node_map
        assert "rejected_request" in [
            e["reason"] for e in job.dead_letter.entries
        ]

    def test_bad_job_default_fails_fast(self):
        with pytest.raises(ValueError):
            StreamJob(JobConfig(parallelism=1, overload="window=0"))


# --- controller units (stub spoke) -------------------------------------------


def _stub_controller(n_tenants=4, **knobs):
    spec = dict(window=8, share=2.0, hot_high=6.0, hot_critical=12.0, cool=4)
    spec.update(knobs)
    cfg = OverloadConfig(**spec)
    spoke = types.SimpleNamespace(serving_plane=None, serve_timer=None)
    ctl = OverloadController(spoke, clock=lambda: 0.0)
    nets = []
    for nid in range(n_tenants):
        net = types.SimpleNamespace(
            request=types.SimpleNamespace(id=nid),
            overload=cfg,
            serving=ServingConfig(max_batch=8, max_delay_ms=100.0),
        )
        ctl.arm(net)
        nets.append(net)
    return ctl, nets


class TestFairShareAdmission:
    def test_uniform_traffic_never_flags(self):
        ctl, nets = _stub_controller()
        for _ in range(200):
            for net in nets:
                ctl.spend(net, 1)
            ctl.tick(force=True)
        assert ctl.level == OK
        assert not any(ctl.is_over(n.request.id) for n in nets)
        assert ctl._hot == 0.0

    def test_flooded_tenant_goes_over_and_critical(self):
        ctl, nets = _stub_controller()
        for _ in range(40):
            ctl.spend(nets[0], 8)
            for net in nets[1:]:
                ctl.spend(net, 1)
            ctl.tick(force=True)
        assert ctl.is_over(0)
        assert not any(ctl.is_over(nid) for nid in (1, 2, 3))
        assert ctl.level == CRITICAL
        assert ctl.level_peak == CRITICAL
        assert ctl.budget(0) < 0 < ctl.budget(1)

    def test_flags_update_at_boundary_ticks_only(self):
        # 24 rows: over the 2 x window = 16 limit, under one 32-row decay
        # window (the count clock advances with the spends themselves)
        ctl, nets = _stub_controller()
        for _ in range(3):
            ctl.spend(nets[0], 8)
        # no tick yet: the verdict is still the last boundary's
        assert not ctl.is_over(0)
        ctl.tick(force=True)
        assert ctl.is_over(0)

    def test_tick_stride_defers_evaluation(self):
        ctl, nets = _stub_controller()
        for _ in range(3):
            ctl.spend(nets[0], 8)
        for _ in range(TICK_STRIDE - 1):
            ctl.tick()
        assert not ctl.is_over(0)
        ctl.tick()  # the TICK_STRIDE-th boundary evaluates
        assert ctl.is_over(0)

    def test_tenant_rate_absolute_cap(self):
        ctl, nets = _stub_controller(tenant_rate=0.25, hot_high=1e9,
                                     hot_critical=1e9)
        # everyone runs uniform WAY above the tenantRate x window = 2 row
        # cap (the decayed steady-state count stays in [4, 12] at every
        # halving phase) — fair share alone would never flag uniform
        # traffic, so only the absolute cap can be flagging here
        for _ in range(30):
            for net in nets:
                ctl.spend(net, 4)
            ctl.tick(force=True)
        assert all(ctl.is_over(n.request.id) for n in nets)

    def test_retire_drops_accounting(self):
        ctl, nets = _stub_controller()
        for _ in range(3):
            ctl.spend(nets[0], 8)
        ctl.tick(force=True)
        assert ctl.is_over(0)
        ctl.retire(0)
        assert not ctl.is_over(0)
        assert 0 not in ctl._tenants and 0 not in ctl.deferred
        assert ctl.n_live == 3


class TestPressureLadder:
    def test_hysteresis_cool_down(self):
        ctl, nets = _stub_controller(cool=4)
        for _ in range(40):
            ctl.spend(nets[0], 8)
            ctl.tick(force=True)
        assert ctl.level == CRITICAL
        # decay below every threshold: the level must hold for `cool`
        # consecutive below-threshold ticks, then step down
        steps = []
        for _ in range(300):
            ctl.idle_tick()
            steps.append(ctl.level)
            if ctl.level == OK:
                break
        assert ctl.level == OK
        assert steps.count(CRITICAL) >= 1  # held before cooling
        assert not ctl.is_over(0)

    def test_degraded_serving_over_limit_tenant_only(self):
        ctl, nets = _stub_controller()
        for _ in range(40):
            ctl.spend(nets[0], 8)
            for net in nets[1:]:
                ctl.spend(net, 1)
            ctl.tick(force=True)
        assert ctl.level == CRITICAL and ctl.is_over(0)
        hot = ctl.degraded_serving(nets[0])
        assert hot.max_batch == nets[0].serving.max_batch * 4
        assert hot.max_delay_ms == nets[0].serving.max_delay_ms * 4
        assert hot.staleness == "relaxed"
        # healthy tenants keep the exact static config object
        assert ctl.degraded_serving(nets[1]) is nets[1].serving

    def test_degraded_serving_identity_at_ok(self):
        ctl, nets = _stub_controller()
        assert ctl.degraded_serving(nets[0]) is nets[0].serving

    def test_external_signal_probe(self):
        ctl, nets = _stub_controller()
        fill = [0.0]
        ctl.extra_signals["prefetch"] = lambda: (fill[0], 0.8, 0.95)
        ctl.tick(force=True)
        assert ctl.level == OK
        fill[0] = 0.9
        ctl.tick(force=True)
        assert ctl.level == ELEVATED
        fill[0] = 1.0
        ctl.tick(force=True)
        assert ctl.level == CRITICAL

    def test_shed_log_and_counters(self):
        ctl, _ = _stub_controller()
        ctl.note_shed(0, 3)
        ctl.note_shed(0, 2, latency_ms=7.5)
        ctl.note_throttled(1, 4)
        assert ctl.shed_log == [(0, 0, 3), (0, 0, 2)]
        assert (ctl.total_shed, ctl.total_throttled) == (5, 4)
        assert ctl.take_shed(0) == 5 and ctl.take_shed(0) == 0
        assert ctl.take_throttled(1) == 4
        assert ctl.shed_latency_p99(0) == 7.5
        assert ctl.total_shed == 5  # cumulative survives the fold


# --- job harness -------------------------------------------------------------


def _job(overload, n_pipe=4, serving=SRV, chaos="", cohort="off",
         codec=None, guard=False, protocol="Asynchronous", parallelism=1,
         learner=None, test=True, job_overload="", **cfg_kw):
    cfg = JobConfig(parallelism=parallelism, batch_size=16, test_set_size=16,
                    cohort=cohort, cohort_min=2, test=test, chaos=chaos,
                    overload=job_overload, **cfg_kw)
    job = StreamJob(cfg)
    learner = learner or {"name": "PA", "hyperParameters": {"C": 1.0}}
    for pid in range(n_pipe):
        tc = {"protocol": protocol, "syncEvery": 4}
        if serving is not None:
            tc["serving"] = serving
        if overload is not None:
            tc["overload"] = overload
        if codec:
            tc["comm"] = {"codec": codec}
        if guard:
            tc["guard"] = True
        job.process_event(REQUEST_STREAM, json.dumps({
            "id": pid, "request": "Create",
            "learner": {**learner, "dataStructure": {"nFeatures": DIM}},
            "trainingConfiguration": tc,
        }))
    return job


def _feed_records(job, records=320, seed=3):
    """50/50 train/forecast per-record stream (the route burst clones
    need: tenant-addressed records route at record granularity)."""
    rng = np.random.RandomState(seed)
    w = np.random.RandomState(5).randn(DIM)
    for i in range(records):
        f = rng.randn(DIM).astype(np.float32)
        if i % 2 == 0:
            job.process_event(FORECASTING_STREAM, json.dumps(
                {"numericalFeatures": f.tolist()}))
        else:
            job.process_event(TRAINING_STREAM, json.dumps(
                {"numericalFeatures": f.tolist(),
                 "target": float(f @ w > 0)}))
    return job.terminate()


def _digest(job, report):
    ordered = {}
    for p in job.predictions:
        feats = tuple(np.asarray(p.data_instance.numerical_features).tolist())
        ordered.setdefault(p.mlp_id, []).append((feats, p.value))
    scores = {s.pipeline: s.score for s in report.statistics}
    return ordered, scores


# a burst spec flooding tenant 0 with 8x forecasts through the middle of
# a 320-record (160-forecast) stream, leaving a ramp and a decay tail
BURST = "seed=7,burst=8,burstFrom=20,burstLen=100,hotTenant=0"


# --- unarmed identity (the composition matrix) -------------------------------


MATRIX = [
    dict(),
    dict(cohort="on"),
    dict(codec="int8"),
    dict(guard=True),
    dict(serving=None),
    dict(cohort="on", codec="int8", guard=True),
]


class TestUnarmedIdentity:
    @pytest.mark.parametrize("kw", MATRIX)
    def test_no_controller_objects_when_unset(self, kw):
        job = _job(None, **kw)
        _feed_records(job, records=64)
        for spoke in job.spokes:
            assert spoke.overload is None
            for net in spoke.nets.values():
                assert net.overload is None and net._octl is None

    @pytest.mark.parametrize("kw", MATRIX)
    def test_armed_uniform_traffic_bit_identical(self, kw):
        """Fair-share admission can never flag uniform fan-out traffic,
        so an armed controller at level OK must not perturb a single
        bit of the stream."""
        off = _job(None, **kw)
        d_off = _digest(off, _feed_records(off))
        on = _job("on", **kw)
        d_on = _digest(on, _feed_records(on))
        assert d_off == d_on
        stats = {}
        for spoke in on.spokes:
            assert spoke.overload is not None
            assert spoke.overload.level_peak == OK
            stats[id(spoke)] = spoke.overload.total_shed
        assert all(v == 0 for v in stats.values())

    def test_job_default_arms_every_pipeline(self):
        job = _job(None, job_overload=OVR)
        for spoke in job.spokes:
            assert spoke.overload is not None
            for net in spoke.nets.values():
                assert net.overload is not None and net.overload.window == 8

    def test_non_dict_metadata_never_routes_or_crashes(self):
        """The validation boundary admits any JSON ``metadata`` value
        (the reference parses and ignores it); a string/list there must
        broadcast exactly like a metadata-free record — with the plane
        armed AND unarmed — never raise."""
        for overload in (None, "on"):
            job = _job(overload, n_pipe=2)
            for meta in ("clientA", ["x"], 7, {"other": 1}):
                job.process_event(FORECASTING_STREAM, json.dumps(
                    {"numericalFeatures": [0.0] * DIM, "metadata": meta}))
            report = job.terminate()
            # every record fanned out to both pipelines
            for s in report.statistics:
                assert s.forecasts_served == 4

    def test_tenant_key_ignored_when_plane_and_burst_unarmed(self):
        """Pre-PR, ``metadata`` was parsed and ignored: with neither the
        overload plane nor the burst injector armed, a record carrying a
        live ``tenant`` id must still BROADCAST (the bit-identity
        invariant), not route to that pipeline alone."""
        job = _job(None, n_pipe=3)
        job.process_event(FORECASTING_STREAM, json.dumps(
            {"numericalFeatures": [0.0] * DIM, "metadata": {"tenant": 1}}))
        report = job.terminate()
        for s in report.statistics:
            assert s.forecasts_served == 1

    def test_tenant_key_routes_when_armed(self):
        job = _job(OVR, n_pipe=3)
        job.process_event(FORECASTING_STREAM, json.dumps(
            {"numericalFeatures": [0.0] * DIM, "metadata": {"tenant": 1}}))
        report = job.terminate()
        by_pipe = {s.pipeline: s.forecasts_served for s in report.statistics}
        assert by_pipe == {0: 0, 1: 1, 2: 0}

    def test_armed_parallel_2_identity(self):
        off = _job(None, protocol="Synchronous", parallelism=2)
        d_off = _digest(off, _feed_records(off))
        on = _job("on", protocol="Synchronous", parallelism=2)
        d_on = _digest(on, _feed_records(on))
        assert d_off == d_on


# --- burst shedding / throttling ---------------------------------------------


class TestBurstShedding:
    def _burst_job(self, **kw):
        job = _job(OVR, chaos=BURST, **kw)
        report = _feed_records(job)
        return job, report

    def test_hot_tenant_sheds_healthy_tenants_do_not(self):
        job, report = self._burst_job()
        by_pipe = {s.pipeline: s for s in report.statistics}
        hot, healthy = by_pipe[0], [by_pipe[p] for p in (1, 2, 3)]
        assert hot.forecasts_shed > 0
        assert hot.pressure_level == CRITICAL
        assert all(s.forecasts_shed == 0 for s in healthy)
        # every healthy tenant served every one of the 160 stream
        # forecasts — the flood was absorbed by the hot tenant alone
        assert all(s.forecasts_served == 160 for s in healthy)
        assert job.dead_letter.by_reason.get("shed_overload", 0) > 0

    def test_shed_entries_carry_tenant_and_queue_depth(self):
        job, _ = self._burst_job()
        sheds = [e for e in job.dead_letter.entries
                 if e["reason"] == "shed_overload"]
        assert sheds
        for e in sheds:
            assert e["tenant"] == 0
            assert "queueDepth" in e and e["queueDepth"] >= 0
            assert e["stream"] == "forecastingData"

    def test_training_deprioritized_but_never_lost(self):
        job, report = self._burst_job()
        by_pipe = {s.pipeline: s for s in report.statistics}
        assert by_pipe[0].records_throttled > 0
        # deferred rows drained (terminate trains them): nothing stranded
        depths = job.queue_depths()
        assert depths["throttled"] == 0
        # the hot tenant still fitted its training rows — late, not lost
        assert by_pipe[0].fitted > 0

    def test_controller_recovers_to_ok(self):
        job, _ = self._burst_job()
        assert job.overload_level() == OK
        for spoke in job.spokes:
            assert spoke.overload.level == OK
            assert spoke.overload.level_peak == CRITICAL

    def test_defer_cap_overflow_quarantined_as_throttled(self):
        job = _job(OVR + ",deferCap=4", chaos=BURST)
        _feed_records(job)
        assert job.dead_letter.by_reason.get("throttled", 0) > 0
        throttled = [e for e in job.dead_letter.entries
                     if e["reason"] == "throttled"]
        assert all(e["tenant"] == 0 for e in throttled)

    def test_shed_latency_gauge_on_queue_drains(self):
        """Entering CRITICAL sheds the hot tenant's already-queued rows;
        their enqueue->shed wait feeds the shedLatencyMs percentile."""
        job, report = self._burst_job()
        assert any(s.shed_latency_ms > 0 for s in report.statistics)

    def test_shedding_disabled_serves_everything(self):
        job, report = self._burst_job(serving=SRV)
        total = sum(s.forecasts_shed for s in report.statistics)
        assert total > 0
        job2 = _job(OVR + ",shed=false", chaos=BURST)
        report2 = _feed_records(job2)
        assert sum(s.forecasts_shed for s in report2.statistics) == 0
        assert job2.dead_letter.by_reason.get("shed_overload", 0) == 0


class TestBurstDeterminism:
    def _run(self, chaos=BURST):
        job = _job(OVR, chaos=chaos)
        report = _feed_records(job)
        sched = []
        for spoke in job.spokes:
            sched.extend(spoke.overload.shed_log)
        letters = [
            (e["reason"], e.get("tenant"), e.get("queueDepth"), e["payload"])
            for e in job.dead_letter.entries
        ]
        counters = {
            s.pipeline: (s.forecasts_shed, s.records_throttled,
                         s.pressure_level)
            for s in report.statistics
        }
        return sched, letters, counters

    def test_same_seed_same_shed_schedule(self):
        a = self._run()
        b = self._run()
        assert a == b
        assert a[0]  # non-vacuous: the schedule engaged

    def test_different_window_different_schedule(self):
        a = self._run()
        b = self._run(chaos="seed=7,burst=8,burstFrom=40,burstLen=100,"
                            "hotTenant=0")
        assert a[0] != b[0]

    def test_burst_injector_unit(self):
        spec = parse_chaos_spec("burst=4,burstFrom=1,burstLen=2,hotTenant=9")
        inj = BurstInjector.from_spec(spec)
        from omldm_tpu.api.data import DataInstance, FORECASTING

        train = DataInstance(numerical_features=[1.0], target=0.0)
        fore = DataInstance(numerical_features=[1.0], operation=FORECASTING)
        assert inj.clones(train) == ()       # training never amplifies
        assert inj.clones(fore) == ()        # forecast 0: before the window
        clones = inj.clones(fore)            # forecast 1: in the window
        assert len(clones) == 3
        assert all(c.metadata["tenant"] == 9 for c in clones)
        assert inj.clones(fore) and not inj.clones(fore)  # window closes
        assert inj.injected == 6

    def test_burst_off_spec_is_none(self):
        assert BurstInjector.from_spec(parse_chaos_spec("drop=0.1")) is None
        assert BurstInjector.from_spec(None) is None


# --- upstream backpressure ---------------------------------------------------


class TestBackpressure:
    def test_polling_events_pause_consumes_nothing(self):
        class Rec:
            def __init__(self, i):
                self.topic = "trainingData"
                self.value = b"{}"
                self.partition = 0
                self.offset = i

        consumed = []

        class Consumer:
            def __init__(self):
                self._it = iter([Rec(i) for i in range(3)])

            def __next__(self):
                r = next(self._it)
                consumed.append(r.offset)
                return r

        paused = [True]
        tracker = {}
        events = polling_events(
            Consumer(), tracker=tracker,
            pause_when=lambda: paused[0], pause_sleep_s=0.0,
        )
        # paused: idle markers only, nothing consumed, offsets untracked
        for _ in range(5):
            assert next(events) is None
        assert consumed == [] and tracker == {}
        paused[0] = False
        assert next(events) is not None
        assert consumed == [0]
        assert tracker == {("trainingData", 0): 1}

    def test_job_overload_level_folds_spokes(self):
        job = _job(OVR, parallelism=2)
        assert job.overload_level() == OK
        job.spokes[1].overload.level = CRITICAL
        assert job.overload_level() == CRITICAL

    def test_idle_ticks_clear_a_critical_pause(self):
        """The backpressure dead-lock guard: nothing admits while the
        source is paused, so idle ticks must decay the buckets and step
        the level back down — or the pause would never lift."""
        job = _job(OVR, chaos=BURST, n_pipe=4)
        rng = np.random.RandomState(3)
        hit_critical = False
        for i in range(320):
            f = rng.randn(DIM).astype(np.float32)
            if i % 2 == 0:
                job.process_event(FORECASTING_STREAM, json.dumps(
                    {"numericalFeatures": f.tolist()}))
            else:
                job.process_event(TRAINING_STREAM, json.dumps(
                    {"numericalFeatures": f.tolist(), "target": 1.0}))
            if job.overload_level() >= CRITICAL:
                hit_critical = True
                break
        assert hit_critical
        for _ in range(400):
            job.overload_idle_tick()
            if job.overload_level() == OK:
                break
        assert job.overload_level() == OK
        job.terminate()


# --- bounded emission mirrors ------------------------------------------------


class TestEmissionBufferCap:
    def test_mirror_trimmed_with_sink_attached(self):
        job = _job(None, n_pipe=2, emission_buffer_cap=50)
        sunk = []
        job.set_sinks(on_prediction=sunk.append)
        _feed_records(job, records=300)
        assert len(job.predictions) <= 50
        assert job.predictions_trimmed > 0
        # every prediction still reached the sink — only the mirror trims
        assert len(sunk) == len(job.predictions) + job.predictions_trimmed

    def test_unbounded_without_sink(self):
        """Without a sink the list IS the job's output: never trimmed."""
        job = _job(None, n_pipe=2, emission_buffer_cap=50)
        _feed_records(job, records=300)
        assert len(job.predictions) == 2 * 150
        assert job.predictions_trimmed == 0

    def test_cap_zero_disables_trimming(self):
        job = _job(None, n_pipe=2, emission_buffer_cap=0)
        job.set_sinks(on_prediction=lambda p: None)
        _feed_records(job, records=300)
        assert len(job.predictions) == 2 * 150


# --- uniform queue-depth accessors -------------------------------------------


class TestQueueDepths:
    def test_micro_batcher_queued(self):
        b = MicroBatcher(DIM, 8)
        assert b.queued() == 0
        b.add(np.zeros(DIM, np.float32), 1.0)
        b.add(np.zeros(DIM, np.float32), 0.0)
        assert b.queued() == 2 == len(b)
        b.flush()
        assert b.queued() == 0

    def test_prefetcher_occupancy(self):
        import threading

        gate = threading.Semaphore(0)

        def slow_source():
            for i in range(4):
                yield i
                gate.acquire()

        pf = prefetch(slow_source(), depth=2)
        assert isinstance(pf, Prefetcher)
        assert pf.depth == 2
        assert next(pf) == 0
        # one release per yield boundary (4 yields), so the source can
        # run to exhaustion and deliver the sentinel
        for _ in range(4):
            gate.release()
        out = list(pf)
        assert out == [1, 2, 3]
        assert pf.queued() == 0 and pf.occupancy() == 0.0

    def test_spoke_and_job_depth_snapshots(self):
        # 84 records = 42 training = 34 batched rows per net after the
        # 20% holdout — NOT a multiple of the 16-row batch, so the
        # batchers hold a ragged tail mid-stream
        job = _job(OVR, chaos=BURST)
        rng = np.random.RandomState(3)
        for i in range(84):
            f = rng.randn(DIM).astype(np.float32)
            if i % 2 == 0:
                job.process_event(FORECASTING_STREAM, json.dumps(
                    {"numericalFeatures": f.tolist()}))
            else:
                job.process_event(TRAINING_STREAM, json.dumps(
                    {"numericalFeatures": f.tolist(), "target": 1.0}))
        keys = {"serving", "batcher", "throttled", "paused", "pre_create"}
        for spoke in job.spokes:
            assert set(spoke.queue_depths()) == keys
        agg = job.queue_depths()
        assert keys < set(agg)
        assert "backlog" in agg and "pressure_level" in agg
        # mid-stream the batchers hold staged rows
        assert agg["batcher"] > 0
        topo = job.tenant_topology()
        assert topo["queues"]["batcher"] == agg["batcher"]
        job.terminate()
        after = job.queue_depths()
        assert all(after[k] == 0 for k in keys)


# --- statistics plumbing -----------------------------------------------------


class TestStatsPlumbing:
    def test_update_merge_and_to_dict(self):
        a = Statistics(pipeline=1)
        a.update_stats(forecasts_shed=5, records_throttled=3,
                       pressure_level=1)
        a.update_stats(forecasts_shed=2, pressure_level=2)
        a.note_shed_latency(12.0)
        a.note_shed_latency(7.0)
        assert (a.forecasts_shed, a.records_throttled, a.pressure_level,
                a.shed_latency_ms) == (7, 3, 2, 12.0)
        b = Statistics(pipeline=1)
        b.update_stats(forecasts_shed=1, records_throttled=9,
                       pressure_level=1)
        m = a.merge(b)
        # counters sum; the pressure level and shed-latency p99 are
        # gauges: max-combined
        assert (m.forecasts_shed, m.records_throttled) == (8, 12)
        assert (m.pressure_level, m.shed_latency_ms) == (2, 12.0)
        d = m.to_dict()
        assert (d["forecastsShed"], d["recordsThrottled"],
                d["pressureLevel"], d["shedLatencyMs"]) == (8, 12, 2, 12.0)

    def test_counters_zero_when_unarmed(self):
        job = _job(None)
        report = _feed_records(job, records=64)
        for s in report.statistics:
            assert (s.forecasts_shed, s.records_throttled,
                    s.pressure_level, s.shed_latency_ms) == (0, 0, 0, 0.0)


# --- tenant routing on rescaled-in spokes (ISSUE 12 satellite) ---------------


class TestRescaledSpokeTenantRouting:
    """Spokes added by a live ``rescale()`` grow are built by the SAME
    factory as the originals (StreamJob._spawn_spoke), so every opt-in
    rule — the burst injector's job-level tenant_routing flag and the
    per-deploy overload-controller arming — holds identically on them.
    Regression pins: a tenant-addressed record landing on a rescaled-in
    spoke routes (armed) or broadcasts (unarmed) exactly like one landing
    on an original spoke."""

    def _tenant_record(self, tenant=1):
        return json.dumps({
            "numericalFeatures": [0.0] * DIM,
            "metadata": {"tenant": tenant},
        })

    def test_armed_controller_routes_on_grown_spoke(self):
        job = _job(OVR, n_pipe=3)
        job.rescale(2)
        grown = job.spokes[1]
        assert grown.overload is not None  # re-armed at re-deploy
        # two records: round-robin lands the second on the grown spoke
        job.process_event(FORECASTING_STREAM, self._tenant_record())
        job.process_event(FORECASTING_STREAM, self._tenant_record())
        report = job.terminate()
        by = {s.pipeline: s.forecasts_served for s in report.statistics}
        # BOTH routed to tenant 1 alone — no broadcast fan-out leak on
        # the rescaled-in spoke
        assert by == {0: 0, 1: 2, 2: 0}

    def test_job_level_flag_survives_grow(self):
        """With the burst injector armed (job-level tenant_routing) and
        NO overload controller, grown spokes still route."""
        job = _job(None, n_pipe=3, chaos=BURST)
        assert job._burst is not None
        job.rescale(2)
        assert job.spokes[1].tenant_routing is True
        job.process_event(FORECASTING_STREAM, self._tenant_record())
        job.process_event(FORECASTING_STREAM, self._tenant_record())
        report = job.terminate()
        by = {s.pipeline: s.forecasts_served for s in report.statistics}
        assert by == {0: 0, 1: 2, 2: 0}

    def test_unarmed_grown_spoke_keeps_broadcast(self):
        """Neither plane armed: a tenant key on a record landing on a
        rescaled-in spoke still BROADCASTS (the bit-identity invariant
        of the unarmed route)."""
        job = _job(None, n_pipe=3)
        job.rescale(2)
        assert job.spokes[1].tenant_routing is False
        assert job.spokes[1].overload is None
        job.process_event(FORECASTING_STREAM, self._tenant_record())
        job.process_event(FORECASTING_STREAM, self._tenant_record())
        report = job.terminate()
        for s in report.statistics:
            assert s.forecasts_served == 2  # full fan-out, both records

    def test_rescale_counter_reported(self):
        job = _job(None, n_pipe=2)
        job.rescale(3)
        job.rescale(1)
        report = _feed_records(job, records=32)
        for s in report.statistics:
            assert s.rescales_performed == 2
