"""Learner kernel tests: convergence on synthetic streams, jit-ability,
masking, per-record vs mini-batch semantics."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from omldm_tpu.api.requests import LearnerSpec
from omldm_tpu.learners import (
    LEARNERS,
    HoeffdingTree,
    KMeans,
    make_learner,
)


def linear_binary_data(n, dim, seed=0, labels01=False):
    rng = np.random.RandomState(seed)
    w = rng.randn(dim)
    x = rng.randn(n, dim).astype(np.float32)
    y = (x @ w > 0).astype(np.float32)
    if not labels01:
        y = 2 * y - 1
    return jnp.asarray(x), jnp.asarray(y)


def regression_data(n, dim, seed=0):
    rng = np.random.RandomState(seed)
    w = rng.randn(dim)
    x = rng.randn(n, dim).astype(np.float32)
    y = (x @ w + 0.5 + 0.01 * rng.randn(n)).astype(np.float32)
    return jnp.asarray(x), jnp.asarray(y)


def train_stream(learner, x, y, batch=64, per_record=False):
    params = learner.init(x.shape[1], jax.random.PRNGKey(0))
    fn = learner.update_per_record if per_record else learner.update
    if not learner.host_side:
        fn = jax.jit(fn)
    for i in range(0, x.shape[0] - batch + 1, batch):
        xb, yb = x[i : i + batch], y[i : i + batch]
        mask = jnp.ones((xb.shape[0],), jnp.float32)
        params, _ = fn(params, xb, yb, mask)
    return params


class TestPA:
    def test_converges(self):
        x, y = linear_binary_data(4096, 10)
        learner = make_learner(LearnerSpec("PA", hyper_parameters={"C": 1.0}))
        params = train_stream(learner, x, y)
        acc = learner.score(params, x, y, jnp.ones(x.shape[0]))
        assert acc > 0.9

    def test_per_record_matches_reference_rule(self):
        # single-record batch: mini-batch update must equal the textbook
        # per-record PA-I projection
        learner = make_learner(LearnerSpec("PA", hyper_parameters={"C": 10.0, "variant": "PA-I"}))
        params = learner.init(3)
        x = jnp.array([[1.0, 2.0, -1.0]])
        y = jnp.array([1.0])
        mask = jnp.ones((1,))
        new_params, loss = learner.update(params, x, y, mask)
        xb = np.array([1.0, 2.0, -1.0, 1.0])  # appended bias
        l = max(0.0, 1.0 - 0.0)
        tau = min(10.0, l / (xb @ xb))
        np.testing.assert_allclose(new_params["w"], tau * xb, rtol=1e-5)
        assert float(loss) == 1.0

    def test_mask_excludes_rows(self):
        learner = make_learner(LearnerSpec("PA"))
        params = learner.init(3)
        x = jnp.array([[1.0, 0.0, 0.0], [5.0, 5.0, 5.0]])
        y = jnp.array([1.0, -1.0])
        p_masked, _ = learner.update(params, x, y, jnp.array([1.0, 0.0]))
        p_solo, _ = learner.update(params, x[:1], y[:1], jnp.array([1.0]))
        np.testing.assert_allclose(p_masked["w"], p_solo["w"], rtol=1e-6)

    def test_per_record_scan_runs(self):
        x, y = linear_binary_data(512, 5)
        learner = make_learner(LearnerSpec("PA", hyper_parameters={"C": 1.0}))
        params = train_stream(learner, x, y, per_record=True)
        acc = learner.score(params, x, y, jnp.ones(x.shape[0]))
        assert acc > 0.9


class TestRegressorPA:
    def test_converges(self):
        x, y = regression_data(4096, 8)
        learner = make_learner(
            LearnerSpec("RegressorPA", hyper_parameters={"C": 1.0, "epsilon": 0.01})
        )
        params = train_stream(learner, x, y, per_record=True)
        rmse = -float(learner.score(params, x, y, jnp.ones(x.shape[0])))
        assert rmse < 0.5


class TestORR:
    def test_matches_closed_form_ridge(self):
        x, y = regression_data(1024, 6)
        learner = make_learner(LearnerSpec("ORR", hyper_parameters={"lambda": 1.0}))
        params = train_stream(learner, x, y, batch=128)
        # closed form on the same 1024 rows (batches cover all rows)
        xb = np.concatenate([np.asarray(x), np.ones((x.shape[0], 1))], axis=1)
        w_ref = np.linalg.solve(xb.T @ xb + np.eye(7), xb.T @ np.asarray(y))
        w_ours = np.asarray(jax.scipy.linalg.solve(params["A"], params["b"]))
        np.testing.assert_allclose(w_ours, w_ref, rtol=1e-3, atol=1e-3)

    def test_order_independent(self):
        # sufficient statistics: batch split must not change the result
        x, y = regression_data(256, 4)
        learner = make_learner(LearnerSpec("ORR"))
        p1 = train_stream(learner, x, y, batch=256)
        p2 = train_stream(learner, x, y, batch=32)
        np.testing.assert_allclose(np.asarray(p1["A"]), np.asarray(p2["A"]), rtol=1e-4)

    def test_merge_sums_statistics(self):
        x, y = regression_data(512, 4)
        learner = make_learner(LearnerSpec("ORR"))
        p_all = train_stream(learner, x, y, batch=256)
        pa = train_stream(learner, x[:256], y[:256], batch=256)
        pb = train_stream(learner, x[256:], y[256:], batch=256)
        merged = learner.merge([pa, pb])
        np.testing.assert_allclose(np.asarray(merged["A"]), np.asarray(p_all["A"]), rtol=1e-4)


class TestSVM:
    def test_linear_converges(self):
        x, y = linear_binary_data(4096, 10)
        learner = make_learner(LearnerSpec("SVM", hyper_parameters={"lambda": 1e-3}))
        params = train_stream(learner, x, y)
        acc = learner.score(params, x, y, jnp.ones(x.shape[0]))
        assert acc > 0.9

    def test_rff_learns_nonlinear(self):
        # ring dataset: not linearly separable; RFF-SVM must beat linear SVM
        rng = np.random.RandomState(1)
        x = rng.randn(4096, 2).astype(np.float32)
        r = np.linalg.norm(x, axis=1)
        y = jnp.asarray((r < 1.1).astype(np.float32) * 2 - 1)
        x = jnp.asarray(x)
        rff = make_learner(
            LearnerSpec(
                "SVM",
                hyper_parameters={"lambda": 1e-4},
                data_structure={"rffDim": 256, "gamma": 1.0},
            )
        )
        params = train_stream(rff, x, y, batch=128)
        acc = rff.score(params, x, y, jnp.ones(x.shape[0]))
        assert acc > 0.8


class TestMultiClassPA:
    def test_converges_3class(self):
        rng = np.random.RandomState(0)
        centers = np.array([[3, 0], [-3, 3], [-3, -3]], dtype=np.float32)
        idx = rng.randint(0, 3, size=4096)
        x = jnp.asarray(centers[idx] + 0.5 * rng.randn(4096, 2).astype(np.float32))
        y = jnp.asarray(idx.astype(np.float32))
        learner = make_learner(
            LearnerSpec("MultiClassPA", hyper_parameters={"C": 1.0, "nClasses": 3})
        )
        params = train_stream(learner, x, y)
        acc = learner.score(params, x, y, jnp.ones(x.shape[0]))
        assert acc > 0.9


class TestSoftmax:
    def test_converges(self):
        rng = np.random.RandomState(0)
        centers = np.array([[2, 0], [-2, 2], [-2, -2]], dtype=np.float32)
        idx = rng.randint(0, 3, size=4096)
        x = jnp.asarray(centers[idx] + 0.5 * rng.randn(4096, 2).astype(np.float32))
        y = jnp.asarray(idx.astype(np.float32))
        learner = make_learner(
            LearnerSpec("Softmax", hyper_parameters={"learningRate": 0.5, "nClasses": 3})
        )
        params = train_stream(learner, x, y)
        acc = learner.score(params, x, y, jnp.ones(x.shape[0]))
        assert acc > 0.9


class TestKMeans:
    def test_finds_clusters(self):
        rng = np.random.RandomState(0)
        centers = np.array([[4, 4], [-4, -4]], dtype=np.float32)
        idx = rng.randint(0, 2, size=2048)
        x = jnp.asarray(centers[idx] + 0.3 * rng.randn(2048, 2).astype(np.float32))
        learner = make_learner(LearnerSpec("K-means", hyper_parameters={"k": 2}))
        params = train_stream(learner, x, jnp.zeros(2048), batch=64)
        c = np.sort(np.asarray(params["centroids"]), axis=0)
        np.testing.assert_allclose(c, np.sort(centers, axis=0), atol=0.5)

    def test_merge_weighted(self):
        learner = KMeans({"k": 2})
        pa = {"centroids": jnp.array([[1.0, 1.0], [0.0, 0.0]]), "counts": jnp.array([3.0, 0.0])}
        pb = {"centroids": jnp.array([[3.0, 3.0], [9.0, 9.0]]), "counts": jnp.array([1.0, 0.0])}
        merged = learner.merge([pa, pb])
        np.testing.assert_allclose(np.asarray(merged["centroids"])[0], [1.5, 1.5])
        np.testing.assert_allclose(np.asarray(merged["counts"]), [4.0, 0.0])


class TestNN:
    def test_learns_xor(self):
        rng = np.random.RandomState(0)
        x = rng.randn(4096, 2).astype(np.float32)
        y = jnp.asarray(((x[:, 0] > 0) ^ (x[:, 1] > 0)).astype(np.float32))
        x = jnp.asarray(x)
        learner = make_learner(
            LearnerSpec(
                "NN",
                hyper_parameters={"learningRate": 1e-2},
                data_structure={"hiddenLayers": [32, 32]},
            )
        )
        params = learner.init(2, jax.random.PRNGKey(42))
        step = jax.jit(learner.update)
        mask = jnp.ones((128,))
        for epoch in range(3):
            for i in range(0, 4096, 128):
                params, _ = step(params, x[i : i + 128], y[i : i + 128], mask)
        acc = learner.score(params, x, y, jnp.ones(4096))
        assert acc > 0.9

    def test_multiclass_head(self):
        learner = make_learner(
            LearnerSpec("NN", data_structure={"nClasses": 4, "hiddenLayers": [8]})
        )
        params = learner.init(3, jax.random.PRNGKey(0))
        preds = learner.predict(params, jnp.zeros((5, 3)))
        assert preds.shape == (5,)


class TestHoeffdingTree:
    def test_learns_threshold_split(self):
        rng = np.random.RandomState(0)
        x = rng.randn(6000, 3).astype(np.float32)
        y = (x[:, 1] > 0.3).astype(np.float32)
        learner = HoeffdingTree({"gracePeriod": 100, "delta": 1e-3})
        params = learner.init(3)
        for i in range(0, 6000, 200):
            mask = np.ones(200, dtype=np.float32)
            params, _ = learner.update(params, x[i : i + 200], y[i : i + 200], mask)
        assert params["n_nodes"] > 1  # it split
        acc = float(learner.score(params, x, y, np.ones(6000)))
        assert acc > 0.9


class TestRegistry:
    def test_allowlist_complete(self):
        # PipelineMap.scala:68 allowlist
        for name in ("PA", "RegressorPA", "ORR", "SVM", "MultiClassPA", "K-means", "NN", "HT"):
            assert name in LEARNERS

    @pytest.mark.parametrize("name", sorted(LEARNERS))
    def test_init_update_predict_shapes(self, name):
        learner = make_learner(LearnerSpec(name))
        params = learner.init(4, jax.random.PRNGKey(0))
        x = jnp.asarray(np.random.RandomState(0).randn(8, 4).astype(np.float32))
        y = jnp.zeros((8,))
        mask = jnp.ones((8,))
        params, loss = learner.update(params, x, y, mask)
        preds = learner.predict(params, x)
        assert preds.shape == (8,)
        assert np.isfinite(float(loss))
        assert np.isfinite(float(learner.loss(params, x, y, mask)))
        assert np.isfinite(float(learner.score(params, x, y, mask)))
