"""Model-integrity guard: divergence detection, rollback, containment.

Pins the ISSUE 7 acceptance bars:

- guard UNSET => every route (solo, cohort, codec int8) is bit-identical
  to the pre-guard path, and arming the guard on a CLEAN stream changes
  nothing either (the health reductions ride the fit launches without
  touching the state math);
- seeded poison (NaN delta, exploding delta, poison record) on all six
  parameter protocols: the job never crashes, the guard counters engage,
  and the final holdout score stays within 0.05 of the fault-free run;
- a cohort member that diverges is EVICTED to solo execution while every
  healthy sibling's result stays bitwise unchanged;
- malformed records land in the dead-letter sink with reason codes and
  never mutate model state.
"""

import json

import numpy as np
import pytest

from omldm_tpu.api.requests import LearnerSpec, TrainingConfiguration
from omldm_tpu.config import JobConfig
from omldm_tpu.guard import (
    GuardConfig,
    ModelGuard,
    admission_reason,
    guard_config,
)
from omldm_tpu.pipelines import MLPipeline
from omldm_tpu.runtime import StreamJob
from omldm_tpu.runtime.job import REQUEST_STREAM, TRAINING_STREAM

DIM = 12
PARAM_PROTOCOLS = ("Asynchronous", "Synchronous", "SSP", "EASGD", "GM", "FGM")


def make_stream(records, dim=DIM, seed=11):
    rng = np.random.RandomState(seed)
    w = np.random.RandomState(42).randn(dim)
    x = rng.randn(records, dim).astype(np.float32)
    y = (x @ w > 0).astype(np.float32)
    return x, y


def create_request(pid=0, protocol="Asynchronous", dim=DIM, guard=None,
                   codec=None, sync_every=2, extra=None):
    tc = {"protocol": protocol, "syncEvery": sync_every}
    if guard is not None:
        tc["guard"] = guard
    if codec is not None:
        tc["comm"] = {"codec": codec}
    tc.update(extra or {})
    return json.dumps({
        "id": pid,
        "request": "Create",
        "learner": {
            "name": "PA",
            "hyperParameters": {"C": 1.0},
            "dataStructure": {"nFeatures": dim},
        },
        "trainingConfiguration": tc,
    })


def run_job(x, y, requests, parallelism=2, batch=32, chaos="", cohort="off",
            chunk=512, poke=None):
    """Drive a packed stream through a StreamJob; ``poke(job)`` runs once
    between two chunks (mid-stream fault injection)."""
    job = StreamJob(JobConfig(
        parallelism=parallelism, batch_size=batch, test_set_size=64,
        chaos=chaos, cohort=cohort, cohort_min=2,
    ))
    for req in requests:
        job.process_event(REQUEST_STREAM, req)
    op = np.zeros((x.shape[0],), np.uint8)
    poke_at = 2 * chunk
    for i in range(0, x.shape[0], chunk):
        job.process_packed_batch(x[i:i+chunk], y[i:i+chunk], op[i:i+chunk])
        if poke is not None and i == poke_at:
            poke(job)
            poke = None
    report = job.terminate()
    return report, job


def nan_poke(spoke_idx=0, net_id=0):
    def poke(job):
        net = job.spokes[spoke_idx].nets[net_id]
        flat, _ = net.pipeline.get_flat_params()
        net.pipeline.set_flat_params(np.full_like(flat, np.nan))
    return poke


# --- units ------------------------------------------------------------------


class TestGuardConfig:
    def test_unset_is_none(self):
        assert guard_config(TrainingConfiguration()) is None
        assert guard_config(
            TrainingConfiguration(extra={"guard": False})
        ) is None

    def test_true_gives_defaults(self):
        cfg = guard_config(TrainingConfiguration(extra={"guard": True}))
        assert cfg == GuardConfig()

    def test_table_overrides(self):
        cfg = guard_config(TrainingConfiguration(extra={"guard": {
            "normLimit": 10.0, "maxStrikes": 3, "lkgDepth": 2,
            "snapshotEvery": 5,
        }}))
        assert cfg.norm_limit == 10.0
        assert cfg.max_strikes == 3
        assert cfg.lkg_depth == 2
        assert cfg.snapshot_every == 5


class TestAdmissionReason:
    def test_healthy_payloads_admit(self):
        ok = np.ones(8, np.float32)
        assert admission_reason({"params": ok, "fitted": 3}, 1e6) is None
        assert admission_reason(ok, 1e6) is None
        assert admission_reason({"inc": 2, "curve": []}, 1e6) is None
        assert admission_reason({"gap": True}, 1e6) is None

    def test_non_finite_rejects(self):
        bad = np.ones(8, np.float32)
        bad[3] = np.nan
        assert admission_reason({"params": bad}, 1e6) == "non_finite"
        bad[3] = np.inf
        assert admission_reason(bad, 1e6) == "non_finite"

    def test_norm_explosion_rejects(self):
        big = np.full(8, 1e9, np.float32)
        assert admission_reason({"params": big}, 1e6) == "norm_exploded"
        assert admission_reason({"params": big}, 1e12) is None

    def test_scalar_float_poison_rejects(self):
        # FGM ships phi floats that fold into the shared quantum
        assert admission_reason({"phi": float("nan")}, 1e6) == "non_finite"
        # ...but NaN curve points must not block a healed worker's push
        assert admission_reason(
            {"params": np.ones(4, np.float32),
             "curve": [(float("nan"), 3)], "fitted": 3},
            1e6,
        ) is None


class TestModelGuard:
    def _pipeline(self, cfg=None):
        return MLPipeline(
            LearnerSpec("PA", hyper_parameters={"C": 1.0}), dim=4,
            guard=cfg or GuardConfig(),
        )

    def test_fit_notes_health_and_check_trips_on_nan(self):
        p = self._pipeline()
        x = np.random.RandomState(0).randn(8, 4).astype(np.float32)
        y = np.ones(8, np.float32)
        m = np.ones(8, np.float32)
        p.fit(x, y, m)
        assert p.guard.check() is None
        flat, _ = p.get_flat_params()
        p.guard.maybe_snapshot(p)
        p.set_flat_params(np.full_like(flat, np.nan))
        p.fit(x, y, m)
        assert p.guard.check() == "non_finite"

    def test_rollback_restores_last_known_good(self):
        p = self._pipeline()
        x = np.random.RandomState(0).randn(8, 4).astype(np.float32)
        y = np.ones(8, np.float32)
        m = np.ones(8, np.float32)
        p.fit(x, y, m)
        good, _ = p.get_flat_params()
        p.guard.maybe_snapshot(p)
        p.set_flat_params(np.full_like(good, np.nan))
        assert p.guard.rollback(p)
        flat, _ = p.get_flat_params()
        np.testing.assert_array_equal(flat, good)

    def test_ring_is_bounded_and_keeps_newest(self):
        p = self._pipeline(GuardConfig(lkg_depth=2, snapshot_every=1))
        vals = []
        for k in range(4):
            p.set_flat_params(np.full(5, float(k), np.float32))
            p.guard._fits_since_snapshot = 1
            p.guard.maybe_snapshot(p)
            vals.append(p.get_flat_params()[0].copy())
        assert p.guard.lkg_depth == 2
        p.guard.rollback(p)
        np.testing.assert_array_equal(p.get_flat_params()[0], vals[-1])

    def test_norm_limit_trips(self):
        p = self._pipeline(GuardConfig(norm_limit=10.0))
        x = np.random.RandomState(0).randn(8, 4).astype(np.float32)
        y = np.ones(8, np.float32)
        m = np.ones(8, np.float32)
        p.fit(x, y, m)
        assert p.guard.check() is None
        p.set_flat_params(np.full(5, 1e4, np.float32))
        p.fit(x, y, m)
        assert p.guard.check() == "norm_exploded"

    def test_unguarded_pipeline_has_no_guard_state(self):
        p = MLPipeline(LearnerSpec("PA", hyper_parameters={"C": 1.0}), dim=4)
        assert p.guard is None
        assert p.cache_key[-1] is False


# --- guard-off / guard-on clean-stream identity -----------------------------


class TestGuardIdentity:
    def _scores(self, guard, codec=None, cohort="off", n_pipe=1,
                parallelism=2):
        x, y = make_stream(3072)
        reqs = [
            create_request(pid, guard=guard, codec=codec)
            for pid in range(n_pipe)
        ]
        report, job = run_job(
            x, y, reqs, parallelism=parallelism, cohort=cohort
        )
        flats = {
            nid: net.pipeline.get_flat_params()[0]
            for nid, net in job.spokes[0].nets.items()
        }
        return {s.pipeline: s.score for s in report.statistics}, flats

    def test_solo_clean_stream_bitwise(self):
        off_scores, off_flats = self._scores(None)
        on_scores, on_flats = self._scores(True)
        assert on_scores == off_scores
        for nid in off_flats:
            np.testing.assert_array_equal(off_flats[nid], on_flats[nid])

    def test_codec_int8_clean_stream_bitwise(self):
        off_scores, off_flats = self._scores(None, codec="int8")
        on_scores, on_flats = self._scores(True, codec="int8")
        assert on_scores == off_scores
        for nid in off_flats:
            np.testing.assert_array_equal(off_flats[nid], on_flats[nid])

    def test_cohort_clean_stream_bitwise(self):
        off_scores, off_flats = self._scores(
            None, cohort="on", n_pipe=3, parallelism=1
        )
        on_scores, on_flats = self._scores(
            True, cohort="on", n_pipe=3, parallelism=1
        )
        assert on_scores == off_scores
        for nid in off_flats:
            np.testing.assert_array_equal(off_flats[nid], on_flats[nid])


# --- poisoned-worker recovery, per protocol family --------------------------


class TestPoisonedWorkerRecovery:
    """Seeded channel corruption (NaN + exploding deltas) against every
    parameter protocol: hub-side admission rejects the poison before it
    enters round accounting; the job finishes inside the fault-free score
    envelope with the counters engaged."""

    # GM/FGM exchange params only on violation collections, so their few
    # pushes need a higher corruption probability to be hit at all
    CHAOS = "seed=7,up.nan=0.05,up.explode=0.05"
    CHAOS_RARE_PUSH = "seed=3,up.nan=0.3,up.explode=0.3"

    @pytest.mark.parametrize("protocol", PARAM_PROTOCOLS)
    def test_recovery_within_envelope(self, protocol):
        x, y = make_stream(4096)
        chaos = (
            self.CHAOS_RARE_PUSH if protocol in ("GM", "FGM") else self.CHAOS
        )
        extra = {"threshold": 0.3} if protocol in ("GM", "FGM") else {}
        clean, _ = run_job(
            x, y, [create_request(protocol=protocol, guard=True, extra=extra)]
        )
        poisoned, _ = run_job(
            x, y,
            [create_request(protocol=protocol, guard=True, extra=extra)],
            chaos=chaos,
        )
        [cs] = clean.statistics
        [ps] = poisoned.statistics
        assert ps.deltas_rejected > 0, (
            f"{protocol}: corruption never hit the admission boundary — "
            "the test is vacuous"
        )
        assert abs(ps.score - cs.score) <= 0.05

    def test_unguarded_chaos_poison_corrupts_or_survives(self):
        # control: the SAME corruption with the guard off must actually
        # reach protocol state (otherwise the recovery test proves
        # nothing). Asynchronous averages every push, so one NaN push
        # poisons the global model and every replica it touches.
        x, y = make_stream(4096)
        report, job = run_job(
            x, y, [create_request(protocol="Asynchronous")],
            chaos=self.CHAOS,
        )
        [s] = report.statistics
        flats = [
            net.pipeline.get_flat_params()[0]
            for spoke in job.spokes for net in spoke.nets.values()
        ]
        poisoned = (not np.isfinite(s.score)) or any(
            not np.isfinite(f).all() for f in flats
        ) or s.score < 0.6
        assert poisoned, (
            "unguarded chaos corruption left no trace — raise the "
            "injection rate so the guarded test stays meaningful"
        )
        assert s.deltas_rejected == 0  # guard off: nothing rejected


class TestWorkerRollback:
    def test_nan_poke_rolls_back_and_recovers(self):
        # CentralizedTraining (parallelism 1): the hub holds no usable
        # authoritative params for this worker's recovery, so the LKG
        # ring is what saves it
        x, y = make_stream(4096)
        req = create_request(protocol="CentralizedTraining", guard=True)
        clean, _ = run_job(x, y, [req], parallelism=1)
        poisoned, job = run_job(
            x, y, [req], parallelism=1, poke=nan_poke()
        )
        [cs] = clean.statistics
        [ps] = poisoned.statistics
        assert ps.rollbacks_performed >= 1
        flat, _ = job.spokes[0].nets[0].pipeline.get_flat_params()
        assert np.isfinite(flat).all()
        assert abs(ps.score - cs.score) <= 0.05

    def test_sync_nan_poke_heals_via_hub_resync(self):
        # with live hub state, admission rejects the poisoned push and the
        # OP_RESYNC catch-up restores the worker (no crash, envelope held)
        x, y = make_stream(4096)
        req = create_request(protocol="Synchronous", guard=True)
        clean, _ = run_job(x, y, [req])
        poisoned, job = run_job(x, y, [req], poke=nan_poke())
        [cs] = clean.statistics
        [ps] = poisoned.statistics
        assert ps.deltas_rejected + ps.rollbacks_performed >= 1
        for spoke in job.spokes:
            flat, _ = spoke.nets[0].pipeline.get_flat_params()
            assert np.isfinite(flat).all()
        assert abs(ps.score - cs.score) <= 0.05

    def test_guarded_int8_codec_nan_never_crashes(self):
        # dim >= minLeafSize so params actually encode: the int8 kernel's
        # loud non-finite failure must be contained by the guard (ship
        # suppressed, rollback recovers) instead of crashing the job
        x, y = make_stream(4096, dim=32)
        req = create_request(
            protocol="Asynchronous", dim=32, guard=True, codec="int8"
        )
        clean, _ = run_job(x, y, [req])
        poisoned, job = run_job(x, y, [req], poke=nan_poke())
        [cs] = clean.statistics
        [ps] = poisoned.statistics
        assert ps.rollbacks_performed >= 1
        flat, _ = job.spokes[0].nets[0].pipeline.get_flat_params()
        assert np.isfinite(flat).all()
        assert abs(ps.score - cs.score) <= 0.05


# --- cohort eviction --------------------------------------------------------


class TestCohortEviction:
    N_PIPE = 4
    BAD = 2

    def _run(self, poke):
        x, y = make_stream(4096)
        reqs = [
            create_request(pid, guard=True) for pid in range(self.N_PIPE)
        ]
        return run_job(
            x, y, reqs, parallelism=1, cohort="on", poke=poke
        )

    def test_diverging_member_evicts_solo_and_recovers(self):
        report, job = self._run(nan_poke(net_id=self.BAD))
        bad_net = job.spokes[0].nets[self.BAD]
        assert bad_net.pipeline._cohort is None  # checked out to solo
        total_evicted = sum(s.members_evicted for s in report.statistics)
        total_rollbacks = sum(
            s.rollbacks_performed for s in report.statistics
        )
        assert total_evicted == 1
        assert total_rollbacks >= 1
        flat, _ = bad_net.pipeline.get_flat_params()
        assert np.isfinite(flat).all()
        # healthy members stay attached
        for pid in range(self.N_PIPE):
            if pid == self.BAD:
                continue
            assert job.spokes[0].nets[pid].pipeline._cohort is not None

    def test_healthy_members_bitwise_unchanged_by_eviction(self):
        clean, clean_job = self._run(None)
        poisoned, pois_job = self._run(nan_poke(net_id=self.BAD))
        clean_scores = {s.pipeline: s.score for s in clean.statistics}
        pois_scores = {s.pipeline: s.score for s in poisoned.statistics}
        for pid in range(self.N_PIPE):
            if pid == self.BAD:
                continue
            assert pois_scores[pid] == clean_scores[pid]
            np.testing.assert_array_equal(
                clean_job.spokes[0].nets[pid].pipeline.get_flat_params()[0],
                pois_job.spokes[0].nets[pid].pipeline.get_flat_params()[0],
            )


# --- record quarantine ------------------------------------------------------


POISON_LINES = [
    '{"numericalFeatures": [NaN, 1.0], "target": 1.0}',
    '{"numericalFeatures": [1e999], "target": 0.0}',
    '{"numericalFeatures": [1.0], "target": Infinity}',
    '{"numericalFeatures": [1.0], "operation": "explode"}',
    'garbage{{{',
    '[]',
    '{"target": 1.0}',
]


class TestRecordQuarantine:
    def _event_job(self, lines, dead_letter_path=""):
        job = StreamJob(JobConfig(
            parallelism=1, batch_size=8, test_set_size=16,
            dead_letter_path=dead_letter_path,
        ))
        job.process_event(REQUEST_STREAM, create_request(dim=4))
        for line in lines:
            job.process_event(TRAINING_STREAM, line)
        return job

    @staticmethod
    def _valid_lines(n=64, dim=4, seed=5):
        rng = np.random.RandomState(seed)
        return [
            json.dumps({
                "numericalFeatures": [float(v) for v in rng.randn(dim)],
                "target": float(i % 2),
            })
            for i in range(n)
        ]

    def test_poison_records_quarantined_with_reasons(self):
        lines = self._valid_lines()
        mixed = []
        for i, line in enumerate(lines):
            mixed.append(line)
            if i < len(POISON_LINES):
                mixed.append(POISON_LINES[i])
        job = self._event_job(mixed)
        assert job.dead_letter.record_count == len(POISON_LINES)
        reasons = {e["reason"] for e in job.dead_letter.entries}
        assert reasons == {
            "non_finite_feature", "non_finite_target", "unknown_operation",
            "malformed_json", "not_an_object", "no_features",
        }
        report = job.terminate()
        [s] = report.statistics
        assert s.records_quarantined == len(POISON_LINES)

    def test_eos_and_blank_are_markers_not_poison(self):
        job = self._event_job(["EOS", '"EOS"', "", "   "])
        assert job.dead_letter.total == 0

    def test_poison_never_mutates_model_state(self):
        lines = self._valid_lines()
        mixed = []
        for i, line in enumerate(lines):
            mixed.append(line)
            mixed.append(POISON_LINES[i % len(POISON_LINES)])
        job_clean = self._event_job(lines)
        job_mixed = self._event_job(mixed)
        np.testing.assert_array_equal(
            job_clean.spokes[0].nets[0].pipeline.get_flat_params()[0],
            job_mixed.spokes[0].nets[0].pipeline.get_flat_params()[0],
        )

    def test_dead_letter_file_written(self, tmp_path):
        path = str(tmp_path / "dead.jsonl")
        job = self._event_job(POISON_LINES, dead_letter_path=path)
        job.dead_letter.close()
        with open(path) as fh:
            entries = [json.loads(line) for line in fh]
        assert len(entries) == len(POISON_LINES)
        assert all(
            e["stream"] == TRAINING_STREAM and e["reason"] and "payload" in e
            for e in entries
        )

    def test_rejected_requests_quarantined_with_detail(self):
        job = StreamJob(JobConfig(parallelism=1))
        job.process_event(REQUEST_STREAM, "not json at all {{")
        job.process_event(REQUEST_STREAM, json.dumps({
            "id": 7, "request": "Create",
            "learner": {"name": "NoSuchLearner"},
        }))
        assert job.dead_letter.request_count == 2
        reasons = [e["reason"] for e in job.dead_letter.entries]
        assert reasons == ["malformed_request", "rejected_request"]
        assert "NoSuchLearner" in job.dead_letter.entries[-1]["detail"]
        assert job.pipeline_manager.live_pipelines == []


# --- chaos injector units ---------------------------------------------------


class TestChaosPoisonInjectors:
    def _channel(self, **kw):
        from omldm_tpu.runtime.supervisor import ChaosChannel

        out = []
        chan = ChaosChannel(
            lambda *args: out.append(args), seed=5, name="t", **kw
        )
        return chan, out

    def _send_pushes(self, chan, n=60):
        for i in range(n):
            chan.send(0, 0, 0, "push",
                      {"params": np.ones(8, np.float32), "fitted": i}, i)

    def test_nan_injection_is_seeded_and_counted(self):
        chan, out = self._channel(nan=0.2)
        self._send_pushes(chan)
        corrupted = [
            a for a in out if not np.isfinite(a[4]["params"]).all()
        ]
        assert chan.corrupted > 0
        assert len(corrupted) == chan.corrupted
        # determinism: same seed, same schedule
        chan2, out2 = self._channel(nan=0.2)
        self._send_pushes(chan2)
        assert chan2.corrupted == chan.corrupted
        for a, b in zip(out, out2):
            np.testing.assert_array_equal(a[4]["params"], b[4]["params"])

    def test_explode_scales_past_guard_limit(self):
        chan, out = self._channel(explode=1.0)
        chan.send(0, 0, 0, "push", {"params": np.ones(8, np.float32)}, 0)
        [args] = out
        assert float(np.linalg.norm(args[4]["params"])) > 1e6
        assert admission_reason(args[4], 1e6) == "norm_exploded"

    def test_control_payloads_never_corrupt(self):
        chan, out = self._channel(nan=1.0, explode=1.0)
        chan.send(0, 0, 0, "zeta", {"inc": 3, "curve": []}, 0)
        chan.send(0, 0, 0, "nack", {"gap": True}, 1)
        assert chan.corrupted == 0
        assert out[0][4] == {"inc": 3, "curve": []}

    def test_original_payload_object_not_mutated(self):
        chan, _ = self._channel(nan=1.0)
        params = np.ones(8, np.float32)
        chan.send(0, 0, 0, "push", {"params": params}, 0)
        assert np.isfinite(params).all()

    def test_loss_only_specs_keep_their_schedule(self):
        # arming ZERO corruption draws nothing extra from the RNG: the
        # drop/dup schedule of pre-existing specs is unchanged
        chan_a, out_a = self._channel(drop=0.3)
        chan_b, out_b = self._channel(drop=0.3, nan=0.0, explode=0.0)
        self._send_pushes(chan_a)
        self._send_pushes(chan_b)
        assert chan_a.dropped == chan_b.dropped
        assert len(out_a) == len(out_b)

    def test_consumer_poison_records(self):
        from omldm_tpu.api.data import DataInstance
        from omldm_tpu.runtime.supervisor import ChaosConsumer

        class Rec:
            def __init__(self, i):
                self.topic = "trainingData"
                self.value = json.dumps(
                    {"numericalFeatures": [1.0, 2.0], "target": 1.0}
                )
                self.partition = 0
                self.offset = i

        inner = iter([Rec(i) for i in range(200)])
        consumer = ChaosConsumer(inner, seed=9, poison=0.2)
        seen = list(consumer)
        assert consumer.poisoned > 0
        bad = [r for r in seen if DataInstance.from_json(r.value) is None]
        assert len(bad) == consumer.poisoned
        # every poisoned record still names its topic/offset (quarantine
        # entries stay attributable)
        assert all(r.topic == "trainingData" for r in bad)


# --- hub admission through a real Hub ---------------------------------------


class TestHubAdmission:
    def _hub(self, protocol="Asynchronous", max_strikes=1, workers=3):
        from omldm_tpu.api.requests import Request, RequestType
        from omldm_tpu.runtime.hub import Hub

        sent = []
        request = Request(
            id=0, request=RequestType.CREATE,
            learner=LearnerSpec(
                "PA", hyper_parameters={"C": 1.0},
                data_structure={"nFeatures": 8},
            ),
            training_configuration=TrainingConfiguration(
                protocol=protocol,
                extra={"guard": {"maxStrikes": max_strikes}},
            ),
        )
        hub = Hub(
            0, 0, request, 8, JobConfig(parallelism=workers),
            reply=lambda w, op, payload: sent.append((w, op)),
            broadcast=lambda op, payload: sent.append(("*", op)),
        )
        return hub, sent

    def _push(self, vec, fitted=1):
        return {"params": vec, "curve": [], "fitted": fitted}

    def test_reject_then_retire_then_readmit(self):
        hub, sent = self._hub()
        good = np.ones(13, np.float32)
        bad = good.copy()
        bad[0] = np.nan
        hub.receive(0, "push", self._push(good))
        assert hub.node.stats.deltas_rejected == 0
        hub.receive(1, "push", self._push(bad))
        assert hub.node.stats.deltas_rejected == 1
        assert 1 in hub.node._guard_retired
        assert hub.node.round_target() == 2
        # authoritative resync went to the offender
        assert (1, "resync") in sent
        # healthy params push re-admits
        hub.receive(1, "push", self._push(good, fitted=2))
        assert 1 not in hub.node._guard_retired
        assert hub.node.round_target() == 3

    def test_rejected_push_never_reaches_round_accounting(self):
        hub, _ = self._hub(protocol="Synchronous", workers=2)
        bad = np.full(13, np.nan, np.float32)
        hub.receive(0, "push", self._push(bad))
        assert hub.node._round == {}
        assert hub.node.stats.fitted == 0

    def test_sync_barrier_releases_without_poisoned_worker(self):
        hub, sent = self._hub(protocol="Synchronous", workers=2)
        good = np.ones(13, np.float32)
        bad = np.full(13, np.inf, np.float32)
        hub.receive(0, "push", self._push(good))
        assert not any(op == "update" for _, op in sent)
        # worker 1 is poisoned: its push rejects, it retires, and the
        # round releases on worker 0's contribution alone
        hub.receive(1, "push", self._push(bad))
        assert any(op == "update" for _, op in sent)

    def test_strike_budget_respected(self):
        hub, _ = self._hub(max_strikes=2)
        bad = np.full(13, np.nan, np.float32)
        hub.receive(1, "push", self._push(bad))
        assert 1 not in hub.node._guard_retired
        hub.receive(1, "push", self._push(bad))
        assert 1 in hub.node._guard_retired

    def test_guard_off_has_no_admission(self):
        from omldm_tpu.api.requests import Request, RequestType
        from omldm_tpu.runtime.hub import Hub

        request = Request(
            id=0, request=RequestType.CREATE,
            learner=LearnerSpec(
                "PA", hyper_parameters={"C": 1.0},
                data_structure={"nFeatures": 8},
            ),
            training_configuration=TrainingConfiguration(
                protocol="Asynchronous"
            ),
        )
        hub = Hub(
            0, 0, request, 8, JobConfig(parallelism=2),
            reply=lambda *a: None, broadcast=lambda *a: None,
        )
        assert not hub.node.guard_armed
        bad = np.full(13, np.nan, np.float32)
        hub.receive(0, "push", self._push(bad))
        # pre-guard behavior: the poison lands in the global (silently)
        assert not np.isfinite(hub.node.global_params).all()
        assert hub.node.stats.deltas_rejected == 0


class TestReviewRegressions:
    """Pins for the review findings on the guard layer."""

    def test_trip_with_no_hub_state_does_not_starve_sync_barrier(self):
        # poison BEFORE any round completes: the hub has no authoritative
        # params to resync, so recovery must come from the LKG rollback +
        # healthy re-push (not from a resync that ships nothing)
        x, y = make_stream(4096)
        req = create_request(protocol="Synchronous", guard=True)
        job = StreamJob(JobConfig(
            parallelism=2, batch_size=32, test_set_size=64,
        ))
        job.process_event(REQUEST_STREAM, req)
        nan_poke()(job)  # worker 0 is corrupt from record zero
        op = np.zeros((x.shape[0],), np.uint8)
        for i in range(0, x.shape[0], 512):
            job.process_packed_batch(x[i:i+512], y[i:i+512], op[i:i+512])
        report = job.terminate()
        [s] = report.statistics
        # the fleet kept training (no permanently-blocked worker)...
        assert s.fitted > x.shape[0] // 2
        assert s.score > 0.8
        # ...and the poisoned worker recovered to finite params
        for spoke in job.spokes:
            flat, _ = spoke.nets[0].pipeline.get_flat_params()
            assert np.isfinite(flat).all()
            assert not spoke.nets[0].node.waiting

    def test_finite_payload_encode_failure_still_raises_under_guard(self):
        # the guarded ship boundary only swallows encode failures caused
        # by genuinely non-finite payloads; any other codec error is a
        # bug and must propagate even with the guard armed
        from omldm_tpu.protocols.registry import make_worker_node

        tc = TrainingConfiguration(
            protocol="Asynchronous",
            extra={"guard": True, "comm": {"codec": "int8"}},
        )
        pipeline = MLPipeline(
            LearnerSpec("PA", hyper_parameters={"C": 1.0}), dim=32,
            guard=GuardConfig(),
        )
        node = make_worker_node(
            "Asynchronous", pipeline, 0, 2, tc, lambda *a: None
        )

        class BrokenCodec:
            def encode(self, payload, stream):
                raise ValueError("unrelated codec defect")

        node.codec = BrokenCodec()
        finite = {"params": np.ones(32, np.float32)}
        with pytest.raises(ValueError, match="unrelated codec defect"):
            node._send_encoded("push", finite, 0)
        # ...while a genuinely non-finite payload is suppressed
        bad = {"params": np.full(32, np.nan, np.float32)}
        node._send_encoded("push", bad, 0)  # must not raise

    def test_per_record_target_clamps_to_float32_range(self):
        # a finite-double target beyond float32 range must clamp (the
        # packed/C route behavior), not overflow to inf in the batcher
        job = StreamJob(JobConfig(parallelism=1, batch_size=4, test=False))
        job.process_event(REQUEST_STREAM, create_request(dim=4))
        for i in range(8):
            job.process_event(TRAINING_STREAM, json.dumps({
                "numericalFeatures": [1.0, 0.5, -0.5, 0.25],
                "target": 1e200 if i % 2 else -1e200,
            }))
        net = job.spokes[0].nets[0]
        flat, _ = net.pipeline.get_flat_params()
        assert np.isfinite(flat).all()
        assert net.pipeline.fitted == 8

    def test_validate_then_apply_still_admits_update_and_delete(self):
        job = StreamJob(JobConfig(parallelism=1))
        job.process_event(REQUEST_STREAM, create_request(dim=4))
        assert job.pipeline_manager.live_pipelines == [0]
        update = json.loads(create_request(dim=4))
        update["request"] = "Update"
        job.process_event(REQUEST_STREAM, json.dumps(update))
        assert job.pipeline_manager.live_pipelines == [0]
        job.process_event(
            REQUEST_STREAM, json.dumps({"id": 0, "request": "Delete"})
        )
        assert job.pipeline_manager.live_pipelines == []
        assert job.dead_letter.request_count == 0


class TestChaosCorruptionUnderCodec:
    """The nan/explode injectors must not go silently inert when a
    transport codec is armed: the on-wire (encoded) params corrupt too,
    and the guard's admission boundary still catches the decode."""

    def test_encoded_leaf_corruption_engages_admission(self):
        # dim >= minLeafSize so the int8 codec actually encodes params
        x, y = make_stream(4096, dim=32)
        req = create_request(
            protocol="Asynchronous", dim=32, guard=True, codec="int8"
        )
        clean, _ = run_job(x, y, [req])
        poisoned, job = run_job(
            x, y, [req], chaos="seed=7,up.nan=0.05,up.explode=0.05"
        )
        [cs] = clean.statistics
        [ps] = poisoned.statistics
        assert job._chaos_up.corrupted > 0, (
            "codec-armed pipeline saw zero injected corruptions — the "
            "nan/explode classes are inert again"
        )
        assert ps.deltas_rejected > 0
        assert abs(ps.score - cs.score) <= 0.05

    def test_corrupt_payload_handles_each_leaf_kind(self):
        from omldm_tpu.runtime.codec import TransportCodec, decode_payload
        from omldm_tpu.runtime.supervisor import _chaos_rng, _corrupt_payload

        rng = _chaos_rng(5, "t")
        vec = np.random.RandomState(0).randn(64).astype(np.float32)
        for kind in ("fp16", "int8", "topk"):
            tx = TransportCodec(kind, min_leaf_size=4, top_k=8)
            rx = TransportCodec(kind, min_leaf_size=4, top_k=8)
            payload = tx.encode({"params": vec.copy()}, stream="w0>h0")
            bad = _corrupt_payload(payload, "nan", rng)
            assert bad is not None, f"{kind}: corruption returned None"
            dec = decode_payload(bad, rx)["params"]
            assert not np.isfinite(dec).all(), (
                f"{kind}: corrupted leaf decoded finite"
            )
            # the original encoded payload was not mutated
            dec_orig = decode_payload(
                payload, TransportCodec(kind, min_leaf_size=4, top_k=8)
            )["params"]
            assert np.isfinite(dec_orig).all()


class TestRoundThreeRegressions:
    """Pins for the codec-interaction and snapshot-integrity findings."""

    def test_topk_rejection_realigns_delta_bases(self):
        # a chaos-corrupted topk delta poisons the hub's rx base at decode
        # time (before admission): the rejection must reset the base and
        # re-anchor the sender, or every later HEALTHY delta from that
        # worker keeps decoding corrupt and being rejected until the
        # anchor cycle (up to anchorEvery=64 pushes away)
        x, y = make_stream(6144, dim=32)
        req = create_request(
            protocol="Asynchronous", dim=32, guard=True, codec="topk",
            sync_every=2,
        )
        clean, _ = run_job(x, y, [req])
        poisoned, job = run_job(
            x, y, [req], chaos="seed=11,up.nan=0.04"
        )
        [cs] = clean.statistics
        [ps] = poisoned.statistics
        assert job._chaos_up.corrupted > 0
        assert ps.deltas_rejected > 0
        # realignment bound: rejections stay commensurate with injected
        # corruptions instead of snowballing toward the anchor cycle
        assert ps.deltas_rejected <= 4 * job._chaos_up.corrupted
        # containment, not parity: a forced re-anchor restarts the topk
        # stream from a zero base and the k-sparse rebuild transiently
        # degrades the averaged model — topk's documented contract is
        # "converges within one anchor cycle", so the bar here is a
        # finite, learning model (score >> chance), not the 0.05 envelope
        # the dense codecs hold
        assert ps.score > 0.7
        assert abs(ps.score - cs.score) <= 0.25
        for spoke in job.spokes:
            flat, _ = spoke.nets[0].pipeline.get_flat_params()
            assert np.isfinite(flat).all()

    def test_snapshot_refuses_corrupt_params(self):
        # a hub broadcast can replace params AFTER the last fit's health
        # evidence: the ring must reject a non-finite copy instead of
        # storing it as "last known good"
        p = MLPipeline(
            LearnerSpec("PA", hyper_parameters={"C": 1.0}), dim=4,
            guard=GuardConfig(snapshot_every=1),
        )
        x = np.random.RandomState(0).randn(8, 4).astype(np.float32)
        p.fit(x, np.ones(8, np.float32), np.ones(8, np.float32))
        p.guard.check()
        p.guard.maybe_snapshot(p)
        good = p.get_flat_params()[0]
        p.set_flat_params(np.full_like(good, np.nan))
        p.guard._fits_since_snapshot = 99
        p.guard.maybe_snapshot(p)  # must refuse the NaN copy
        assert p.guard.rollback(p)
        np.testing.assert_array_equal(p.get_flat_params()[0], good)

    def test_empty_or_nonfloat_params_never_readmit(self):
        # re-admission requires a model vector admission actually judged
        from omldm_tpu.protocols.base import HubNode

        assert not HubNode._carries_params(
            {"params": np.zeros((0,), np.float32)}
        )
        assert not HubNode._carries_params(
            {"params": np.ones(4, np.int32)}
        )
        assert HubNode._carries_params({"params": np.ones(4, np.float32)})

    def test_dead_letter_file_closed_at_terminate(self, tmp_path):
        path = str(tmp_path / "dl.jsonl")
        job = StreamJob(JobConfig(parallelism=1, dead_letter_path=path))
        job.process_event(REQUEST_STREAM, create_request(dim=4))
        job.process_event(TRAINING_STREAM, "garbage{{{")
        assert job.dead_letter._fh is not None
        job.terminate()
        assert job.dead_letter._fh is None


class TestRoundFourRegressions:
    def test_poison_never_mutates_request_topic(self):
        # a poisoned record's offset advances (no replay), so the control
        # stream must be exempt — destroying a Create would silently
        # change the topology forever
        from omldm_tpu.runtime.supervisor import ChaosConsumer

        class Rec:
            def __init__(self, i, topic):
                self.topic = topic
                self.value = json.dumps({"id": i, "request": "Delete"}) \
                    if topic == "requests" else json.dumps(
                        {"numericalFeatures": [1.0], "target": 0.0})
                self.partition = 0
                self.offset = i

        recs = [Rec(i, "requests" if i % 3 == 0 else "trainingData")
                for i in range(300)]
        consumer = ChaosConsumer(
            iter(recs), seed=9, poison=0.5,
            poison_exempt_topics=("requests",),
        )
        seen = list(consumer)
        assert consumer.poisoned > 0
        for r in seen:
            if r.topic == "requests":
                assert json.loads(r.value)["request"] == "Delete"

    def test_guard_retirement_respects_quorum_floor(self):
        from omldm_tpu.api.requests import Request, RequestType
        from omldm_tpu.runtime.hub import Hub

        request = Request(
            id=0, request=RequestType.CREATE,
            learner=LearnerSpec(
                "PA", hyper_parameters={"C": 1.0},
                data_structure={"nFeatures": 8},
            ),
            training_configuration=TrainingConfiguration(
                protocol="Synchronous",
                extra={"guard": True, "comm": {"quorum": 3}},
            ),
        )
        hub = Hub(
            0, 0, request, 8, JobConfig(parallelism=4),
            reply=lambda *a: None, broadcast=lambda *a: None,
        )
        bad = np.full(13, np.nan, np.float32)
        push = {"params": bad, "curve": [], "fitted": 1}
        hub.receive(0, "push", dict(push))
        assert 0 in hub.node._guard_retired  # 4 -> 3 active: allowed
        hub.receive(1, "push", dict(push))
        # 3 active == quorum floor: worker 1 must NOT retire
        assert 1 not in hub.node._guard_retired
        assert hub.node.round_target() == 3
        assert hub.node.stats.deltas_rejected == 2
