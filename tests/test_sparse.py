"""Sparse (padded-COO) feature path: ops, learners, pipeline, vectorizer.

The reference treats SparseVector as a first-class input type
(DataPointParser.scala:4,20-47); these tests pin the TPU-native equivalent:
dense/sparse twin-equality on the same data, high-dimensional training at
Criteo/Avazu-class widths (where densifying would be wrong or impossible),
and the end-to-end sparse pipeline surface.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from omldm_tpu.api.data import DataInstance
from omldm_tpu.api.requests import LearnerSpec
from omldm_tpu.learners.registry import make_learner
from omldm_tpu.ops.sparse import sparse_matvec, sparse_scatter_add
from omldm_tpu.pipelines import MLPipeline
from omldm_tpu.runtime.vectorizer import SparseMicroBatcher, SparseVectorizer


def dense_to_coo(x: np.ndarray, k: int):
    """Dense [B, D] -> padded COO (idx[B, k], val[B, k])."""
    b = x.shape[0]
    idx = np.zeros((b, k), np.int32)
    val = np.zeros((b, k), np.float32)
    for i in range(b):
        nz = np.nonzero(x[i])[0][:k]
        idx[i, : nz.size] = nz
        val[i, : nz.size] = x[i, nz]
    return idx, val


class TestSparseOps:
    def test_matvec_matches_dense(self):
        rng = np.random.RandomState(0)
        d, b, k = 50, 8, 12
        w = rng.randn(d).astype(np.float32)
        x = np.zeros((b, d), np.float32)
        for i in range(b):
            cols = rng.choice(d, k, replace=False)
            x[i, cols] = rng.randn(k)
        idx, val = dense_to_coo(x, k)
        np.testing.assert_allclose(
            np.asarray(sparse_matvec(jnp.asarray(w), jnp.asarray(idx), jnp.asarray(val))),
            x @ w, rtol=1e-5, atol=1e-5,
        )

    def test_scatter_add_matches_dense_and_pads_inert(self):
        rng = np.random.RandomState(1)
        d, b, k = 30, 4, 6
        w = np.zeros(d, np.float32)
        x = np.zeros((b, d), np.float32)
        for i in range(b):
            cols = rng.choice(d, 3, replace=False)  # k=6 budget, 3 used
            x[i, cols] = rng.randn(3)
        idx, val = dense_to_coo(x, k)
        coef = rng.randn(b).astype(np.float32)
        out = sparse_scatter_add(
            jnp.asarray(w), jnp.asarray(idx), jnp.asarray(coef), jnp.asarray(val)
        )
        np.testing.assert_allclose(
            np.asarray(out), coef @ x, rtol=1e-5, atol=1e-5
        )

    def test_mxu_scatter_matches_xla_scatter(self):
        """The kron-factored one-hot matmul reformulation
        (sparse_scatter_add_mxu) is the same scatter-add up to f32
        reduction order: one-hot products are exact, u rides a bf16x2
        split. Covers duplicates, pad slots, D not a lane multiple, and
        D > MXU_LANES (hi factor exercised)."""
        from omldm_tpu.ops.sparse import MXU_LANES, sparse_scatter_add_mxu

        rng = np.random.RandomState(7)
        for d in (37, MXU_LANES, MXU_LANES * 3 + 11, 4096):
            b, k = 16, 9
            w = rng.randn(d).astype(np.float32)
            idx = rng.randint(0, d, size=(b, k)).astype(np.int32)
            idx[:, -2:] = 0  # pad slots (val 0) plus forced duplicates
            val = rng.randn(b, k).astype(np.float32)
            val[:, -2:] = 0.0
            idx[3] = idx[2]  # whole-record duplicate index pattern
            coef = rng.randn(b).astype(np.float32)
            ref = sparse_scatter_add(
                jnp.asarray(w), jnp.asarray(idx), jnp.asarray(coef),
                jnp.asarray(val),
            )
            out = sparse_scatter_add_mxu(
                jnp.asarray(w), jnp.asarray(idx), jnp.asarray(coef),
                jnp.asarray(val),
            )
            np.testing.assert_allclose(
                np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5,
                err_msg=f"mxu scatter diverged at D={d}",
            )

    def test_auto_dispatch_matches_scatter_under_jit(self):
        """sparse_scatter_add_auto resolves at trace time and must be
        jittable; with the explicit scatter impl pinned it is the plain
        scatter bit-for-bit."""
        import jax

        from omldm_tpu.ops.sparse import sparse_scatter_add_auto

        rng = np.random.RandomState(8)
        d, b, k = 300, 8, 5
        w = rng.randn(d).astype(np.float32)
        idx = rng.randint(0, d, size=(b, k)).astype(np.int32)
        val = rng.randn(b, k).astype(np.float32)
        coef = rng.randn(b).astype(np.float32)
        out = jax.jit(
            lambda *a: sparse_scatter_add_auto(*a, impl="scatter")
        )(
            jnp.asarray(w), jnp.asarray(idx), jnp.asarray(coef),
            jnp.asarray(val),
        )
        ref = sparse_scatter_add(
            jnp.asarray(w), jnp.asarray(idx), jnp.asarray(coef),
            jnp.asarray(val),
        )
        np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))

    def test_segsum_scatter_matches_xla_scatter(self):
        """The sort + segmented pre-combine reformulation
        (sparse_scatter_add_segsum) is the same scatter-add up to f32
        accumulation order (per-run totals are exact segment sums, no
        prefix-difference cancellation). Covers DUPLICATE-HEAVY index
        streams — the hashed-categorical case the pre-combine exists for —
        plus pad slots and whole-record duplicates."""
        from omldm_tpu.ops.sparse import sparse_scatter_add_segsum

        rng = np.random.RandomState(11)
        for d, vocab in ((37, 5), (4096, 3), (4096, 500), (1 << 15, 7)):
            b, k = 32, 9
            w = rng.randn(d).astype(np.float32)
            # duplicate-heavy: every slot draws from a tiny vocabulary
            idx = rng.choice(
                rng.randint(0, d, size=vocab), size=(b, k)
            ).astype(np.int32)
            idx[:, -2:] = 0  # pad slots (val 0)
            val = rng.randn(b, k).astype(np.float32)
            val[:, -2:] = 0.0
            idx[3] = idx[2]  # whole-record duplicate pattern
            coef = rng.randn(b).astype(np.float32)
            ref = sparse_scatter_add(
                jnp.asarray(w), jnp.asarray(idx), jnp.asarray(coef),
                jnp.asarray(val),
            )
            out = sparse_scatter_add_segsum(
                jnp.asarray(w), jnp.asarray(idx), jnp.asarray(coef),
                jnp.asarray(val),
            )
            np.testing.assert_allclose(
                np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5,
                err_msg=f"segsum scatter diverged at D={d} vocab={vocab}",
            )

    def test_dispatch_precedence_env_and_config(self, monkeypatch):
        """_resolve_impl precedence: explicit config impl > env knob >
        calibration table > guess. The env knob rejects junk loudly."""
        from omldm_tpu.ops import sparse as sp

        monkeypatch.delenv("OMLDM_SPARSE_SCATTER", raising=False)
        assert sp._resolve_impl(300, 40, impl="segsum") == "segsum"
        monkeypatch.setenv("OMLDM_SPARSE_SCATTER", "mxu")
        assert sp._resolve_impl(300, 40) == "mxu"
        assert sp._resolve_impl(300, 40, impl="scatter") == "scatter"
        monkeypatch.setenv("OMLDM_SPARSE_SCATTER", "bogus")
        with pytest.raises(ValueError, match="OMLDM_SPARSE_SCATTER"):
            sp._resolve_impl(300, 40)
        with pytest.raises(ValueError, match="unknown sparse scatter"):
            sp._resolve_impl(300, 40, impl="bogus")


class TestSparseLearnerTwinEquality:
    """A sparse learner on the COO form of a dense batch must produce the
    same model as its dense twin."""

    def _data(self, n=400, d=24, seed=0):
        rng = np.random.RandomState(seed)
        w = rng.randn(d)
        x = np.zeros((n, d), np.float32)
        for i in range(n):
            cols = rng.choice(d, 6, replace=False)
            x[i, cols] = rng.randn(6)
        y = (x @ w > 0).astype(np.float32)
        return x, y

    @pytest.mark.parametrize("variant", ["PA", "PA-I", "PA-II"])
    def test_pa_matches_dense_twin(self, variant):
        x, y = self._data()
        d = x.shape[1]
        hp = {"C": 0.5, "variant": variant}
        dense = make_learner(LearnerSpec("PA", hyper_parameters=hp))
        sparse = make_learner(
            LearnerSpec("PA", hyper_parameters=hp,
                        data_structure={"sparse": True})
        )
        pd = dense.init(d, jax.random.PRNGKey(0))
        ps = sparse.init(d, jax.random.PRNGKey(0))
        idx, val = dense_to_coo(x, 8)
        mask = np.ones(len(y), np.float32)
        for s in range(0, len(y), 64):
            sl = slice(s, s + 64)
            m = mask[sl]
            pd, ld = dense.update(pd, jnp.asarray(x[sl]), jnp.asarray(y[sl]), jnp.asarray(m))
            ps, ls = sparse.update(
                ps, (jnp.asarray(idx[sl]), jnp.asarray(val[sl])),
                jnp.asarray(y[sl]), jnp.asarray(m),
            )
            np.testing.assert_allclose(float(ld), float(ls), rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(
            np.asarray(pd["w"]), np.asarray(ps["w"]), rtol=1e-4, atol=1e-5
        )

    def test_softmax_matches_dense_twin(self):
        x, y = self._data(seed=3)
        d = x.shape[1]
        hp = {"learningRate": 0.1, "nClasses": 2}
        dense = make_learner(LearnerSpec("Softmax", hyper_parameters=hp))
        sparse = make_learner(
            LearnerSpec("Softmax", hyper_parameters=hp,
                        data_structure={"sparse": True})
        )
        pd = dense.init(d, jax.random.PRNGKey(0))
        ps = sparse.init(d, jax.random.PRNGKey(0))
        idx, val = dense_to_coo(x, 8)
        mask = np.ones(len(y), np.float32)
        for s in range(0, len(y), 64):
            sl = slice(s, s + 64)
            pd, _ = dense.update(pd, jnp.asarray(x[sl]), jnp.asarray(y[sl]), jnp.asarray(mask[sl]))
            ps, _ = sparse.update(
                ps, (jnp.asarray(idx[sl]), jnp.asarray(val[sl])),
                jnp.asarray(y[sl]), jnp.asarray(mask[sl]),
            )
        wd = np.asarray(jax.tree_util.tree_leaves(pd)[0])
        ws = np.asarray(jax.tree_util.tree_leaves(ps)[0])
        np.testing.assert_allclose(wd, ws, rtol=1e-4, atol=1e-5)


class TestSparseHighDim:
    """Criteo/Avazu-class widths: the whole point of the sparse path."""

    def _hashed_stream(self, n, d_dense, hash_space, k_cat, seed=0):
        """Synthetic categorical stream: k_cat categorical slots drawn from
        per-slot vocabularies; label decided by a hidden weight over the
        hashed space."""
        rng = np.random.RandomState(seed)
        dim = d_dense + hash_space
        k = d_dense + k_cat
        idx = np.zeros((n, k), np.int32)
        val = np.zeros((n, k), np.float32)
        xs_dense = rng.randn(n, d_dense).astype(np.float32)
        idx[:, :d_dense] = np.arange(d_dense)
        val[:, :d_dense] = xs_dense
        for c in range(k_cat):
            vocab = rng.randint(0, hash_space, size=50)
            picks = vocab[rng.randint(0, 50, size=n)]
            idx[:, d_dense + c] = d_dense + picks
            val[:, d_dense + c] = 1.0
        w_hid = rng.randn(dim) * 0.5
        margins = np.array(
            [val[i] @ w_hid[idx[i]] for i in range(n)], np.float32
        )
        y = (margins > 0).astype(np.float32)
        return dim, k, idx, val, y

    def test_pa_learns_at_2e18_width(self):
        dim_target = (1 << 18) + 13
        n = 4096
        dim, k, idx, val, y = self._hashed_stream(
            n, d_dense=13, hash_space=1 << 18, k_cat=26
        )
        assert dim == dim_target
        learner = make_learner(
            LearnerSpec("PA", hyper_parameters={"C": 0.5, "variant": "PA-II"},
                        data_structure={"sparse": True, "nFeatures": dim})
        )
        p = learner.init(dim, jax.random.PRNGKey(0))
        mask = np.ones(n, np.float32)
        # per-record online semantics (the reference's pipePoint loop)
        upd = jax.jit(learner.update_per_record)
        for _ in range(3):
            for s in range(0, n, 256):
                sl = slice(s, s + 256)
                p, _ = upd(p, (jnp.asarray(idx[sl]), jnp.asarray(val[sl])),
                           jnp.asarray(y[sl]), jnp.asarray(mask[sl]))
        score = float(learner.score(
            p, (jnp.asarray(idx), jnp.asarray(val)), jnp.asarray(y), jnp.asarray(mask)
        ))
        assert score > 0.8, score

    def test_sparse_pipeline_surface(self):
        """MLPipeline hosts a sparse learner: fit/fit_many/predict/evaluate/
        query-path flat params all work on (idx, val) batches."""
        dim, k, idx, val, y = self._hashed_stream(
            1024, d_dense=4, hash_space=1 << 12, k_cat=8, seed=5
        )
        pipe = MLPipeline(
            LearnerSpec("Softmax",
                        hyper_parameters={"learningRate": 0.2, "nClasses": 2},
                        data_structure={"sparse": True}),
            dim=dim,
            per_record=True,  # reference pipePoint semantics
        )
        mask = np.ones(256, np.float32)
        for _ in range(8):
            for s in range(0, 1024, 256):
                sl = slice(s, s + 256)
                pipe.fit((idx[sl], val[sl]), y[sl], mask)
        loss, score = pipe.evaluate((idx, val), y, np.ones(1024, np.float32))
        assert score > 0.75, score
        preds = np.asarray(pipe.predict((idx[:16], val[:16])))
        assert preds.shape == (16,)
        flat, _ = pipe.get_flat_params()
        assert flat.size == (dim + 1) * 2  # W[D+1, 2]
        # fit_many chained launch
        xs = (np.stack([idx[:256]] * 3), np.stack([val[:256]] * 3))
        pipe.fit_many(xs, np.stack([y[:256]] * 3), np.stack([mask] * 3))

    def test_sparse_rejects_preprocessors(self):
        with pytest.raises(ValueError):
            MLPipeline(
                LearnerSpec("PA", data_structure={"sparse": True}),
                [__import__("omldm_tpu.api.requests", fromlist=["PreprocessorSpec"]).PreprocessorSpec("StandardScaler")],
                dim=64,
            )


class TestSparseVectorizer:
    def test_dense_slots_and_hashed_cats(self):
        v = SparseVectorizer(dim=8 + 64, hash_space=64, max_nnz=6)
        inst = DataInstance(
            numerical_features=[1.5, 0.0, -2.0],
            discrete_features=[3],
            categorical_features=["a", "b"],
        )
        idx, val = v.vectorize(inst)
        # zero numeric feature skipped; slots: 0->1.5, 2->-2.0, 3->3
        assert list(idx[:3]) == [0, 2, 3]
        np.testing.assert_allclose(val[:3], [1.5, -2.0, 3.0])
        assert (idx[3:5] >= 8).all()  # hashed region
        assert set(np.abs(val[3:5])) == {1.0}

    def test_matches_dense_vectorizer_model(self):
        """A model trained on sparse records equals one trained on the
        dense Vectorizer's output when the hash space matches."""
        from omldm_tpu.runtime.vectorizer import Vectorizer

        dv = Vectorizer(dim=4 + 32, hash_dims=32)
        sv = SparseVectorizer(dim=4 + 32, hash_space=32, max_nnz=8)
        inst = DataInstance(
            numerical_features=[0.5, -1.0, 2.0, 3.0],
            categorical_features=["x", "y"],
        )
        dense = dv.vectorize(inst)
        idx, val = sv.vectorize(inst)
        rebuilt = np.zeros_like(dense)
        np.add.at(rebuilt, idx, val)
        # pad slots add 0 at index 0
        np.testing.assert_allclose(rebuilt, dense)

    def test_batcher_roundtrip(self):
        b = SparseMicroBatcher(max_nnz=4, batch_size=3)
        b.add(np.array([1, 2, 0, 0]), np.array([1.0, -1.0, 0, 0]), 1.0)
        b.add(np.array([5, 0, 0, 0]), np.array([2.0, 0, 0, 0]), 0.0)
        (idx, val), y, mask = b.flush()
        assert idx.shape == (3, 4)
        assert list(mask) == [1.0, 1.0, 0.0]
        assert list(y[:2]) == [1.0, 0.0]
        assert len(b) == 0


class TestSparseRuntimeE2E:
    """A sparse pipeline through the full streaming runtime: JSON records
    with categorical features -> SparseVectorizer -> padded-COO micro-
    batches -> protocol training -> predictions + final statistics."""

    def _events(self, n, seed=0):
        rng = np.random.RandomState(seed)
        hidden = {}
        lines = []
        labels = []
        for _ in range(n):
            num = rng.randn(3)
            cats = [f"c{rng.randint(40)}", f"d{rng.randint(40)}"]
            m = float(num.sum())
            for i, c in enumerate(cats):
                if (i, c) not in hidden:
                    hidden[(i, c)] = rng.randn() * 2.0
                m += hidden[(i, c)]
            y = float(m > 0)
            labels.append(y)
            lines.append(json.dumps({
                "numericalFeatures": [round(float(v), 5) for v in num],
                "categoricalFeatures": cats,
                "target": y,
                "operation": "training",
            }))
        return lines, labels

    def test_sparse_pipeline_streams_end_to_end(self):
        from omldm_tpu.config import JobConfig
        from omldm_tpu.runtime import StreamJob
        from omldm_tpu.runtime.job import REQUEST_STREAM, TRAINING_STREAM

        hash_space = 1 << 14
        dim = 3 + hash_space
        create = {
            "id": 0,
            "request": "Create",
            "learner": {
                "name": "PA",
                "hyperParameters": {"C": 1.0, "variant": "PA-II"},
                "dataStructure": {
                    "sparse": True, "nFeatures": dim,
                    "hashSpace": hash_space, "maxNnz": 8,
                },
            },
            "preProcessors": [],
            "trainingConfiguration": {
                "protocol": "Synchronous", "perRecord": True,
            },
        }
        job = StreamJob(JobConfig(parallelism=2, batch_size=64, test_set_size=64))
        lines, _ = self._events(6000)
        events = [(REQUEST_STREAM, json.dumps(create))] + [
            (TRAINING_STREAM, l) for l in lines
        ]
        report = job.run(events)
        [stats] = report.statistics
        assert stats.fitted > 4000
        assert stats.score > 0.8, stats.score
        # the model is genuinely wide: flat params = dim + 1 bias
        [spoke] = job.spokes[:1]
        flat, _ = spoke.nets[0].pipeline.get_flat_params()
        assert flat.size == dim + 1

    def test_sparse_create_without_width_rejected(self):
        from omldm_tpu.config import JobConfig
        from omldm_tpu.runtime import StreamJob
        from omldm_tpu.runtime.job import REQUEST_STREAM

        job = StreamJob(JobConfig(parallelism=1))
        bad = {
            "id": 0, "request": "Create",
            "learner": {"name": "PA", "dataStructure": {"sparse": True}},
            "trainingConfiguration": {"protocol": "Synchronous"},
        }
        job.process_event(REQUEST_STREAM, json.dumps(bad))
        assert job.pipeline_manager.live_pipelines == []
