"""Device-sharded cohort execution (runtime/cohort.py, ISSUE 9).

The cohort tenant axis lays across the forced 8-device host mesh
(conftest.py sets ``--xla_force_host_platform_device_count=8``) as a
``tenants`` shard_map axis. Pins, per the ISSUE 9 acceptance:

- shard count 1 resolves to the EXACT single-device cohort path (no mesh,
  no sharded programs) and is bitwise identical to it end to end;
- sharded gang execution (2 and 8 shards) is BIT-IDENTICAL to solo
  per-pipeline execution for every dense learner at the engine level, and
  sharded jobs are bitwise identical to cohort-off jobs at parallelism 1;
- members balance across shards; churn compacts within a shard (capacity
  unchanged — no recompile); capacity stays a multiple of the shard count;
- the composition matrix holds: sharded cohort x codec int8 x serving
  exact x guard armed, mid-stream churn, and rescale grow/shrink with
  shards active;
- the 6 parameter protocols stay inside the 0.05 score envelope at
  parallelism 2 with 8 shards;
- the tenant-mesh width gauge (Statistics.cohort_shards) and the
  serving-launch timing keys (launch_timing serve_*) are populated.
"""

import json

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from omldm_tpu.api.requests import LearnerSpec
from omldm_tpu.config import JobConfig
from omldm_tpu.pipelines import MLPipeline
from omldm_tpu.runtime import StreamJob
from omldm_tpu.runtime.cohort import (
    Cohort,
    CohortEngine,
    resolve_cohort_shards,
)
from omldm_tpu.runtime.job import REQUEST_STREAM

DIM = 8

DENSE_LEARNERS = [
    ("PA", {"C": 1.0}, False),
    ("PA", {"C": 1.0}, True),
    ("RegressorPA", {"C": 0.1, "epsilon": 0.1}, False),
    ("ORR", {"lambda": 1.0}, False),
    ("SVM", {}, False),
    ("MultiClassPA", {"C": 1.0, "nClasses": 3}, False),
    ("NN", {"hidden": 8}, False),
    ("Softmax", {"learningRate": 0.05, "nClasses": 2}, False),
]


class _Cfg:
    def __init__(self, cohort="on", cohort_min=1, cohort_impl="map",
                 cohort_shards="off"):
        self.cohort = cohort
        self.cohort_min = cohort_min
        self.cohort_impl = cohort_impl
        self.cohort_shards = cohort_shards


def _engine(**kw):
    return CohortEngine(_Cfg(**kw))


def _pipes(name, hp, per_record, n, dim=DIM):
    return [
        MLPipeline(
            LearnerSpec(name, hyper_parameters=hp),
            dim=dim,
            rng=jax.random.PRNGKey(11 + i),
            per_record=per_record,
        )
        for i in range(n)
    ]


def _batches(n, t, b, dim=DIM, seed=0):
    rng = np.random.RandomState(seed)
    w = np.random.RandomState(1).randn(dim)
    xs = rng.randn(n, t, b, dim).astype(np.float32)
    ys = (xs @ w > 0).astype(np.float32)
    ms = np.ones((n, t, b), np.float32)
    return xs, ys, ms


def _assert_tree_equal(a, b, msg=""):
    for la, lb in zip(
        jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    ):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb), msg)


# --- shard resolution --------------------------------------------------------


class TestShardResolution:
    def test_off_and_one_are_single_device(self):
        assert resolve_cohort_shards(_Cfg(cohort_shards="off")) == 1
        assert resolve_cohort_shards(_Cfg(cohort_shards="")) == 1
        assert resolve_cohort_shards(_Cfg(cohort_shards="1")) == 1

    def test_auto_takes_pow2_of_mesh(self):
        assert resolve_cohort_shards(_Cfg(cohort_shards="auto")) == 8

    def test_integer_clamps_and_floors_pow2(self):
        assert resolve_cohort_shards(_Cfg(cohort_shards="64")) == 8
        assert resolve_cohort_shards(_Cfg(cohort_shards="5")) == 4
        assert resolve_cohort_shards(_Cfg(cohort_shards="2")) == 2

    def test_unrecognized_spelling_degrades_to_single_device(self):
        """Misconfigured knob must not kill the job — same degrade-to-
        default policy as the sibling cohort/cohort_impl fields."""
        assert resolve_cohort_shards(_Cfg(cohort_shards="on")) == 1
        assert resolve_cohort_shards(_Cfg(cohort_shards="banana")) == 1

    def test_shard_count_one_builds_no_mesh(self):
        """The PR6 single-device path is the shards=1 path verbatim: no
        mesh object, no sharding constraint anywhere."""
        engine = _engine(cohort_shards="1")
        p = _pipes("PA", {"C": 1.0}, False, 1)[0]
        engine.consider(p)
        cohort = p._cohort
        assert engine.n_shards == 1
        assert cohort._mesh is None and cohort._sharding is None


# --- engine-level bit-identity, sharded vs solo ------------------------------


class TestShardedBitIdentity:
    @pytest.mark.parametrize("name,hp,per_record", DENSE_LEARNERS)
    @pytest.mark.parametrize("shards", ["2", "8"])
    def test_sharded_gang_fit_matches_solo(self, name, hp, per_record,
                                           shards):
        """Members are independent, so the per-member math under the
        sharded launch is the SAME program: params, losses, predictions
        and flat params all bitwise equal to detached solo execution —
        including ragged staging depths across members."""
        n, t, b = 5, 2, 16
        solo = _pipes(name, hp, per_record, n)
        gang = _pipes(name, hp, per_record, n)
        engine = _engine(cohort_shards=shards)
        for p in gang:
            engine.consider(p)
        cohort = gang[0]._cohort
        assert cohort.n_shards == int(shards)
        assert cohort.capacity % cohort.n_shards == 0

        xs, ys, ms = _batches(n, t, b)
        ms[n - 1, 1:] = 0.0  # ragged depth for the last member
        losses_solo, losses_gang = [], []
        for i in range(n):
            t_i = 1 if i == n - 1 else t
            for ti in range(t_i):
                losses_solo.append(
                    float(solo[i].fit(xs[i, ti], ys[i, ti], ms[i, ti]))
                )
        for i in range(n):
            t_i = 1 if i == n - 1 else t
            for ti in range(t_i):
                losses_gang.append(
                    gang[i].fit(xs[i, ti], ys[i, ti], ms[i, ti])
                )
        engine.flush()
        assert [float(l) for l in losses_gang] == losses_solo
        xq = np.random.RandomState(9).randn(8, DIM).astype(np.float32)
        for i in range(n):
            _assert_tree_equal(solo[i].state, gang[i].state, f"member {i}")
            np.testing.assert_array_equal(
                np.asarray(solo[i].predict(xq)),
                np.asarray(gang[i].predict(xq)),
            )
            fa, _ = solo[i].get_flat_params()
            fb, _ = gang[i].get_flat_params()
            np.testing.assert_array_equal(fa, fb)

    def test_flat_writes_scatter_back_sharded(self):
        pipes = _pipes("PA", {"C": 1.0}, False, 6)
        engine = _engine(cohort_shards="8")
        for p in pipes:
            engine.consider(p)
        new = [p.get_flat_params()[0] * 2.0 + 1.0 for p in pipes]
        for p, r in zip(pipes, new):
            p.set_flat_params(r)
        for p, r in zip(pipes, new):
            np.testing.assert_array_equal(p.get_flat_params()[0], r)
        # and the scattered rows feed the next sharded launch
        xs, ys, ms = _batches(6, 1, 16)
        for i, p in enumerate(pipes):
            p.fit(xs[i, 0], ys[i, 0], ms[i, 0])
        engine.flush()
        solo = _pipes("PA", {"C": 1.0}, False, 6)
        for i, p in enumerate(solo):
            p.set_flat_params(new[i])
            p.fit(xs[i, 0], ys[i, 0], ms[i, 0])
            np.testing.assert_array_equal(
                p.get_flat_params()[0], pipes[i].get_flat_params()[0]
            )

    def test_state_checkout_mutation_lands_sharded(self):
        pipes = _pipes("PA", {"C": 1.0}, False, 3)
        engine = _engine(cohort_shards="2")
        for p in pipes:
            engine.consider(p)
        xs, ys, ms = _batches(3, 1, 16)
        for i, p in enumerate(pipes):
            p.fit(xs[i, 0], ys[i, 0], ms[i, 0])
        engine.flush()
        sib_before, _ = pipes[1].get_flat_params()
        st = pipes[0].state
        st["params"] = jax.tree_util.tree_map(
            lambda l: l * 0.0, st["params"]
        )
        flat, _ = pipes[0].get_flat_params()
        np.testing.assert_array_equal(flat, np.zeros_like(flat))
        sib, _ = pipes[1].get_flat_params()
        np.testing.assert_array_equal(sib, sib_before)
        assert np.any(sib != 0.0)


# --- placement, balance and churn --------------------------------------------


class TestShardPlacement:
    def test_members_balance_across_shards(self):
        pipes = _pipes("PA", {"C": 1.0}, False, 8)
        engine = _engine(cohort_shards="4")
        for p in pipes:
            engine.consider(p)
        cohort = pipes[0]._cohort
        assert cohort.capacity == 8  # multiple of 4, pow2 bucket
        assert cohort.shard_placement() == [2, 2, 2, 2]

    def test_churn_compacts_within_least_loaded_shard(self):
        pipes = _pipes("PA", {"C": 1.0}, False, 8)
        engine = _engine(cohort_shards="4")
        for p in pipes:
            engine.consider(p)
        cohort = pipes[0]._cohort
        victim = pipes[3]
        victim_shard = cohort._shard_of(victim._slot)
        engine.retire(victim)
        assert cohort.shard_placement()[victim_shard] == 1
        late = _pipes("PA", {"C": 1.0}, False, 1)[0]
        engine.consider(late)
        # the freed slot on the least-loaded shard is reused: capacity
        # unchanged (no recompile), balance restored
        assert cohort.capacity == 8
        assert cohort._shard_of(late._slot) == victim_shard
        assert cohort.shard_placement() == [2, 2, 2, 2]

    def test_growth_keeps_shard_multiple(self):
        pipes = _pipes("PA", {"C": 1.0}, False, 9)
        engine = _engine(cohort_shards="4")
        for p in pipes:
            engine.consider(p)
        cohort = pipes[0]._cohort
        assert cohort.capacity == 16
        assert cohort.capacity % 4 == 0
        assert sorted(cohort.shard_placement(), reverse=True) == [3, 2, 2, 2]
        # survivors keep training bitwise after the grow reshard
        solo = _pipes("PA", {"C": 1.0}, False, 9)
        xs, ys, ms = _batches(9, 1, 16)
        for i in range(9):
            pipes[i].fit(xs[i, 0], ys[i, 0], ms[i, 0])
            solo[i].fit(xs[i, 0], ys[i, 0], ms[i, 0])
        engine.flush()
        for i in range(9):
            _assert_tree_equal(solo[i].state, pipes[i].state, f"member {i}")


# --- job-level composition matrix --------------------------------------------


def _mt_job(cohort, n_pipe, records, protocol="Asynchronous", test=True,
            parallelism=1, learner=None, tc_extra=None, chaos="",
            cohort_shards="off", serving=""):
    cfg = JobConfig(
        parallelism=parallelism, batch_size=32, test_set_size=32,
        cohort=cohort, cohort_min=2, chaos=chaos,
        cohort_shards=cohort_shards, serving=serving,
    )
    job = StreamJob(cfg)
    job.config.test = test
    learner = learner or {"name": "PA", "hyperParameters": {"C": 1.0}}
    for pid in range(n_pipe):
        tc = {"protocol": protocol, "syncEvery": 4}
        if tc_extra:
            tc.update(tc_extra)
        job.process_event(REQUEST_STREAM, json.dumps({
            "id": pid, "request": "Create",
            "learner": {**learner, "dataStructure": {"nFeatures": DIM}},
            "trainingConfiguration": tc,
        }))
    rng = np.random.RandomState(3)
    w = np.random.RandomState(5).randn(DIM)
    x = rng.randn(records, DIM).astype(np.float32)
    y = (x @ w > 0).astype(np.float32)
    op = np.zeros((records,), np.uint8)
    op[::61] = 1
    for i in range(0, records, 256):
        job.process_packed_batch(x[i:i+256], y[i:i+256], op[i:i+256])
    report = job.terminate()
    preds = {}
    for p in job.predictions:
        preds.setdefault(p.mlp_id, []).append(p.value)
    return job, report, preds


def _assert_job_bitwise(off, on):
    _, r_off, p_off = off
    _, r_on, p_on = on
    s_off = {s.pipeline: s for s in r_off.statistics}
    s_on = {s.pipeline: s for s in r_on.statistics}
    assert s_off.keys() == s_on.keys()
    for pid, a in s_off.items():
        b = s_on[pid]
        assert a.score == b.score, f"pid {pid} score"
        assert a.fitted == b.fitted, f"pid {pid} fitted"
        assert a.learning_curve == b.learning_curve, f"pid {pid} curve"
        assert a.lcx == b.lcx, f"pid {pid} lcx"
    assert p_off == p_on


class TestShardedJobBitIdentity:
    @pytest.mark.parametrize("test", [True, False])
    def test_sharded_job_bitwise_vs_cohort_off(self, test):
        """Both serving modes: test=True (holdout harness, per-member
        staging) and test=False (production mode — the SHARED-ingest fast
        path, whose one-[T,B,D]-input program broadcasts in-program on
        every shard)."""
        off = _mt_job("off", 6, 2000, test=test)
        sh = _mt_job("on", 6, 2000, cohort_shards="8", test=test)
        _assert_job_bitwise(off, sh)

    def test_shard_count_one_bitwise_vs_single_device_cohort(self):
        """ISSUE 9 acceptance: shards=1 is bitwise the PR6 cohort path."""
        base = _mt_job("on", 6, 2000)
        one = _mt_job("on", 6, 2000, cohort_shards="1")
        _assert_job_bitwise(base, one)

    def test_sharded_serving_exact_bitwise(self):
        off = _mt_job("off", 4, 1600, serving="on")
        sh = _mt_job("on", 4, 1600, cohort_shards="8", serving="on")
        _assert_job_bitwise(off, sh)

    def test_mesh_width_gauge_and_serve_timing(self):
        job, report, _ = _mt_job(
            "on", 4, 1200, cohort_shards="8", serving="on"
        )
        for s in report.statistics:
            assert s.cohort_shards == 8
            assert "cohortShards" in s.to_dict()
        timing = job.launch_timing()
        assert timing["count"] > 0
        assert timing["serve_count"] > 0
        assert timing["serve_p50_ms"] >= 0.0
        topo = job.tenant_topology()
        assert topo["cohort_shards"] == 8
        assert topo["placement"] and all(
            sum(p) > 0 for p in topo["placement"]
        )

    def test_unsharded_job_reports_zero_gauge(self):
        _, report, _ = _mt_job("on", 3, 600)
        for s in report.statistics:
            assert s.cohort_shards == 0

    def test_never_cohorted_pipeline_reports_zero_gauge(self):
        """Sharding configured but never engaged (auto pool below
        cohort_min): the gauge must stay 0 — it records the ACTUAL mesh
        width the pipeline's launches ran across, not the config."""
        cfg = JobConfig(parallelism=1, batch_size=32, test_set_size=32,
                        cohort="auto", cohort_min=8, cohort_shards="auto")
        job = StreamJob(cfg)
        for pid in range(2):  # below the auto threshold: pooled, solo
            job.process_event(REQUEST_STREAM, json.dumps({
                "id": pid, "request": "Create",
                "learner": {"name": "PA", "hyperParameters": {"C": 1.0},
                            "dataStructure": {"nFeatures": DIM}},
                "trainingConfiguration": {"protocol": "Asynchronous"},
            }))
        rng = np.random.RandomState(3)
        x = rng.randn(512, DIM).astype(np.float32)
        y = np.ones((512,), np.float32)
        job.process_packed_batch(x, y, np.zeros((512,), np.uint8))
        report = job.terminate()
        for s in report.statistics:
            assert s.cohort_shards == 0


class TestShardedComposition:
    def test_sharded_codec_serving_guard_bitwise_vs_off(self):
        """The full composition cell: sharded cohort x int8 codec x exact
        serving x armed guard, bitwise against the same stack cohort-off
        at parallelism 1."""
        extra = {"comm": {"codec": "int8"}, "guard": True}
        off = _mt_job("off", 4, 1600, tc_extra=extra, serving="on")
        sh = _mt_job(
            "on", 4, 1600, tc_extra=extra, serving="on", cohort_shards="8"
        )
        _assert_job_bitwise(off, sh)

    def test_sharded_churn_mid_stream(self):
        """Create/Delete/Update churn against a live SHARDED cohort:
        survivors bitwise vs the cohort-off run of the same events."""
        def run(cohort, shards):
            cfg = JobConfig(parallelism=1, batch_size=16, test_set_size=16,
                            cohort=cohort, cohort_min=2,
                            cohort_shards=shards)
            job = StreamJob(cfg)
            rng = np.random.RandomState(7)
            w = np.random.RandomState(5).randn(DIM)
            x = rng.randn(1500, DIM).astype(np.float32)
            y = (x @ w > 0).astype(np.float32)
            op = np.zeros((1500,), np.uint8)

            def create(pid):
                job.process_event(REQUEST_STREAM, json.dumps({
                    "id": pid, "request": "Create",
                    "learner": {"name": "PA",
                                "hyperParameters": {"C": 1.0},
                                "dataStructure": {"nFeatures": DIM}},
                    "trainingConfiguration": {"protocol": "Asynchronous"},
                }))

            for pid in range(3):
                create(pid)
            job.process_packed_batch(x[:500], y[:500], op[:500])
            create(3)
            job.process_packed_batch(x[500:800], y[500:800], op[500:800])
            job.process_event(REQUEST_STREAM, json.dumps(
                {"id": 1, "request": "Delete"}))
            job.process_packed_batch(x[800:1100], y[800:1100], op[800:1100])
            job.process_event(REQUEST_STREAM, json.dumps({
                "id": 2, "request": "Update",
                "learner": {"name": "PA", "hyperParameters": {"C": 0.5},
                            "dataStructure": {"nFeatures": DIM}},
                "trainingConfiguration": {"protocol": "Asynchronous"},
            }))
            job.process_packed_batch(x[1100:], y[1100:], op[1100:])
            report = job.terminate()
            return {s.pipeline: (s.score, s.fitted, tuple(s.learning_curve))
                    for s in report.statistics}

        assert run("off", "off") == run("on", "8")

    def test_rescale_grow_shrink_with_shards(self):
        cfg = JobConfig(parallelism=2, batch_size=16, test_set_size=16,
                        cohort="on", cohort_min=1, cohort_shards="8")
        job = StreamJob(cfg)
        for pid in range(3):
            job.process_event(REQUEST_STREAM, json.dumps({
                "id": pid, "request": "Create",
                "learner": {"name": "PA", "hyperParameters": {"C": 1.0},
                            "dataStructure": {"nFeatures": DIM}},
                "trainingConfiguration": {"protocol": "Asynchronous"},
            }))
        rng = np.random.RandomState(3)
        w = np.random.RandomState(5).randn(DIM)
        x = rng.randn(3072, DIM).astype(np.float32)
        y = (x @ w > 0).astype(np.float32)
        op = np.zeros((3072,), np.uint8)
        for i in range(0, 1024, 256):
            job.process_packed_batch(x[i:i+256], y[i:i+256], op[i:i+256])
        job.rescale(4)
        for spoke in job.spokes:
            for net in spoke.nets.values():
                assert net.pipeline._cohort is not None
                assert net.pipeline._cohort.n_shards == 8
        for i in range(1024, 2048, 256):
            job.process_packed_batch(x[i:i+256], y[i:i+256], op[i:i+256])
        job.rescale(1)
        for i in range(2048, 3072, 256):
            job.process_packed_batch(x[i:i+256], y[i:i+256], op[i:i+256])
        report = job.terminate()
        assert len(report.statistics) == 3
        for s in report.statistics:
            assert s.score > 0.8
            assert s.fitted > 0


class TestShardedProtocolParity:
    """At parallelism 2 the gang schedule differs from the sequential
    path (same caveat as PR6's TestMultiWorkerParity), so the sharded
    runs pin the 0.05 convergence envelope, not bit-identity."""

    @pytest.mark.parametrize(
        "protocol",
        ["Asynchronous", "Synchronous", "SSP", "EASGD", "GM", "FGM"],
    )
    def test_score_parity_at_8_shards(self, protocol):
        off = _mt_job("off", 3, 2000, protocol=protocol, parallelism=2)
        sh = _mt_job("on", 3, 2000, protocol=protocol, parallelism=2,
                     cohort_shards="8")
        s_off = {s.pipeline: s.score for s in off[1].statistics}
        s_sh = {s.pipeline: s.score for s in sh[1].statistics}
        for pid in s_off:
            assert abs(s_off[pid] - s_sh[pid]) <= 0.05, (
                f"{protocol} pid {pid}: {s_off[pid]} vs {s_sh[pid]}"
            )
        assert {k: len(v) for k, v in off[2].items()} == \
               {k: len(v) for k, v in sh[2].items()}
