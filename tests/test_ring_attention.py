"""Ring attention over an sp mesh axis matches full attention exactly."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from omldm_tpu.ops.attention import mha_reference
from omldm_tpu.ops.ring_attention import ring_attention, ring_attention_sharded
from omldm_tpu.utils.jaxcompat import shard_map


def _qkv(b=2, l=64, h=2, dh=8, seed=0):
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(seed), 3)
    return (
        jax.random.normal(k1, (b, l, h, dh), jnp.float32),
        jax.random.normal(k2, (b, l, h, dh), jnp.float32),
        jax.random.normal(k3, (b, l, h, dh), jnp.float32),
    )


@pytest.mark.parametrize("sp", [2, 4, 8])
@pytest.mark.parametrize("causal", [False, True])
def test_ring_matches_full(sp, causal):
    mesh = Mesh(np.asarray(jax.devices()[:sp]), ("sp",))
    q, k, v = _qkv()
    ref = mha_reference(q, k, v, causal=causal)
    out = ring_attention_sharded(q, k, v, mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_ring_grad_flows():
    """Autodiff through the ring (ppermute inside scan) works — required by
    the sequence-parallel training step."""
    sp = 4
    mesh = Mesh(np.asarray(jax.devices()[:sp]), ("sp",))
    q, k, v = _qkv(b=1, l=32)

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention_sharded(q, k, v, mesh, causal=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(mha_reference(q, k, v, causal=True) ** 2)

    g_ring = jax.grad(loss_ring)(q, k, v)
    g_ref = jax.grad(loss_ref)(q, k, v)
    np.testing.assert_allclose(np.asarray(g_ring), np.asarray(g_ref), atol=1e-4)


def test_ring_inside_shard_map_2d_mesh():
    """Ring composes with a dp axis (batch sharded) on a 2D mesh."""
    devs = np.asarray(jax.devices()[:8]).reshape(2, 4)
    mesh = Mesh(devs, ("dp", "sp"))
    q, k, v = _qkv(b=4, l=32)
    ref = mha_reference(q, k, v, causal=True)

    spec = P("dp", "sp", None, None)
    fn = shard_map(
        lambda q, k, v: ring_attention(q, k, v, "sp", causal=True),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    out = fn(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)
