"""Packed (C++ bulk-ingest) data path: equivalence with the per-record path
and proof that the CLI file-replay route uses the native parser.

The packed path exists so streaming JSON reaches the device plane without
per-record Python (VERDICT round 1, item 1); these tests pin that the bulk
route computes EXACTLY what the per-record route computes."""

import json

import numpy as np
import pytest

import omldm_tpu.__main__ as cli
import omldm_tpu.ops.native as native
from omldm_tpu.api import Request
from omldm_tpu.config import JobConfig
from omldm_tpu.runtime import StreamJob
from omldm_tpu.runtime.job import (
    PACKED_STREAM,
    REQUEST_STREAM,
    TRAINING_STREAM,
)


def make_rows(n, dim=8, seed=0, forecast_every=0):
    rng = np.random.RandomState(seed)
    w = rng.randn(dim)
    # values that survive the JSON round trip bit-exactly: float32 of the
    # 6-decimal float64 the line will carry
    x = np.round(rng.randn(n, dim), 6).astype(np.float32)
    y = (x @ w.astype(np.float32) > 0).astype(np.float32)
    op = np.zeros((n,), np.uint8)
    if forecast_every:
        op[::forecast_every] = 1
    return x, y, op


def lines_for(x, y, op):
    out = []
    for i in range(x.shape[0]):
        out.append(
            json.dumps(
                {
                    # float32 -> float64 is exact, so the JSON value parses
                    # back to exactly x[i, j]
                    "numericalFeatures": [float(v) for v in x[i]],
                    "target": float(y[i]),
                    "operation": "forecasting" if op[i] else "training",
                }
            )
        )
    return out


CREATE = {
    "id": 0,
    "request": "Create",
    "learner": {
        "name": "PA",
        "hyperParameters": {"C": 1.0},
        "dataStructure": {"nFeatures": 8},
    },
    "preProcessors": [],
    "trainingConfiguration": {"protocol": "Synchronous"},
}


def run_job(events, parallelism=2, terminate=True):
    cfg = JobConfig(parallelism=parallelism, batch_size=32, test_set_size=32)
    job = StreamJob(cfg)
    job.run(events, terminate_on_end=terminate)
    return job


class TestSpokePackedEquivalence:
    def test_single_spoke_exact_equivalence(self):
        """At parallelism 1 the packed path must be BIT-equivalent to the
        per-record path: same params, same holdout set, same predictions in
        the same order."""
        x, y, op = make_rows(1500, forecast_every=97)
        recs = [(REQUEST_STREAM, json.dumps(CREATE))] + [
            (TRAINING_STREAM, l) for l in lines_for(x, y, op)
        ]
        job_a = run_job(recs, parallelism=1, terminate=False)
        # packed: same rows in arbitrary-size blocks (x is already the
        # vectorized form of the JSON rows; float32 round-trips exactly)
        packed = [(REQUEST_STREAM, json.dumps(CREATE))]
        for s in range(0, 1500, 277):
            packed.append(
                (PACKED_STREAM, (x[s : s + 277], y[s : s + 277], op[s : s + 277]))
            )
        job_b = run_job(packed, parallelism=1, terminate=False)

        net_a = job_a.spokes[0].nets[0]
        net_b = job_b.spokes[0].nets[0]
        net_a.flush_batch()
        net_b.flush_batch()
        fa, _ = net_a.pipeline.get_flat_params()
        fb, _ = net_b.pipeline.get_flat_params()
        np.testing.assert_array_equal(fa, fb)
        assert net_a.holdout_count == net_b.holdout_count
        assert len(net_a.test_set) == len(net_b.test_set)
        assert len(job_a.predictions) == len(job_b.predictions)
        va = [p.value for p in job_a.predictions]
        vb = [p.value for p in job_b.predictions]
        np.testing.assert_array_equal(va, vb)

    def test_multi_spoke_converges_like_per_record(self):
        """Across coupled spokes (Synchronous hub sync) packed processing
        interleaves workers at block granularity instead of per record —
        the reference's Flink rebalance ordering is likewise nondeterministic
        — so final params must agree, transient predictions may not."""
        x, y, op = make_rows(1500, forecast_every=97)
        recs = [(REQUEST_STREAM, json.dumps(CREATE))] + [
            (TRAINING_STREAM, l) for l in lines_for(x, y, op)
        ]
        job_a = run_job(recs, terminate=False)
        packed = [(REQUEST_STREAM, json.dumps(CREATE))]
        for s in range(0, 1500, 277):
            packed.append(
                (PACKED_STREAM, (x[s : s + 277], y[s : s + 277], op[s : s + 277]))
            )
        job_b = run_job(packed, terminate=False)
        for w in range(2):
            net_a = job_a.spokes[w].nets[0]
            net_b = job_b.spokes[w].nets[0]
            net_a.flush_batch()
            net_b.flush_batch()
            fa, _ = net_a.pipeline.get_flat_params()
            fb, _ = net_b.pipeline.get_flat_params()
            np.testing.assert_allclose(fa, fb, rtol=1e-4, atol=1e-5)
            assert net_a.holdout_count == net_b.holdout_count
        assert len(job_a.predictions) == len(job_b.predictions)

    def test_packed_buffers_before_create(self):
        x, y, op = make_rows(200)
        events = [(PACKED_STREAM, (x, y, op))] + [
            (REQUEST_STREAM, json.dumps(CREATE))
        ]
        job = run_job(events, parallelism=1, terminate=False)
        net = job.spokes[0].nets[0]
        net.flush_batch()
        # all 200 rows reached the pipeline (train share after holdout)
        assert net.holdout_count == 200

    def test_pending_create_infers_dim_from_packed(self):
        create = dict(CREATE)
        create["learner"] = {"name": "PA", "hyperParameters": {"C": 1.0}}
        x, y, op = make_rows(100, dim=5)
        events = [(REQUEST_STREAM, json.dumps(create))] + [
            (PACKED_STREAM, (x, y, op))
        ]
        job = run_job(events, parallelism=1, terminate=False)
        assert job.spokes[0].nets[0].dim == 5


class TestBridgePackedEquivalence:
    def test_spmd_bridge_batch_matches_per_record(self):
        create = dict(CREATE)
        create["trainingConfiguration"] = {
            "protocol": "Synchronous",
            "engine": "spmd",
        }
        x, y, op = make_rows(1200, forecast_every=113)
        recs = [(REQUEST_STREAM, json.dumps(create))] + [
            (TRAINING_STREAM, l) for l in lines_for(x, y, op)
        ]
        job_a = run_job(recs, terminate=False)
        packed = [(REQUEST_STREAM, json.dumps(create))]
        for s in range(0, 1200, 331):
            packed.append(
                (PACKED_STREAM, (x[s : s + 331], y[s : s + 331], op[s : s + 331]))
            )
        job_b = run_job(packed, terminate=False)
        ba = job_a.spmd_bridges[0]
        bb = job_b.spmd_bridges[0]
        ba.flush()
        bb.flush()
        np.testing.assert_allclose(
            ba.trainer.global_flat_params(),
            bb.trainer.global_flat_params(),
            rtol=1e-5,
            atol=1e-6,
        )
        assert ba.holdout_count == bb.holdout_count
        assert ba.trainer.fitted == bb.trainer.fitted
        va = [p.value for p in job_a.predictions]
        vb = [p.value for p in job_b.predictions]
        np.testing.assert_allclose(va, vb, rtol=1e-4, atol=1e-5)


@pytest.mark.skipif(
    not native.fast_parser_available(), reason="g++ toolchain unavailable"
)
class TestCliUsesNativeParser:
    def test_file_replay_routes_through_fast_parser(self, tmp_path, monkeypatch):
        """--trainingData replay must hit FastParser.parse (the C++ path),
        not the per-record JSON codec (VERDICT: 'a test proving the CLI path
        uses the native parser')."""
        x, y, op = make_rows(400)
        train = tmp_path / "train.jsonl"
        train.write_text("\n".join(lines_for(x, y, op)) + "\nEOS\n")
        reqs = tmp_path / "reqs.jsonl"
        reqs.write_text(json.dumps(CREATE) + "\n")
        perf = tmp_path / "perf.jsonl"

        calls = {"n": 0}
        real_parser = native.FastParser

        class SpyParser(real_parser):
            # _parse_region underlies both parse() and parse_range(), so the
            # spy counts the C++ path regardless of which entry the route uses
            def _parse_region(self, addr, length):
                calls["n"] += 1
                return super()._parse_region(addr, length)

        monkeypatch.setattr(native, "FastParser", SpyParser)
        rc = cli.main(
            [
                "--trainingData", str(train),
                "--requests", str(reqs),
                "--performanceOut", str(perf),
                "--parallelism", "2",
            ]
        )
        assert rc == 0
        assert calls["n"] > 0, "CLI file replay did not use the native parser"
        report = json.loads(perf.read_text().splitlines()[-1])
        [stats] = report["statistics"]
        assert stats["fitted"] > 0

    def test_fast_ingest_off_flag(self, tmp_path, monkeypatch):
        x, y, op = make_rows(50)
        train = tmp_path / "train.jsonl"
        train.write_text("\n".join(lines_for(x, y, op)) + "\n")
        reqs = tmp_path / "reqs.jsonl"
        reqs.write_text(json.dumps(CREATE) + "\n")
        calls = {"n": 0}
        real_parser = native.FastParser

        class SpyParser(real_parser):
            # _parse_region underlies both parse() and parse_range(), so the
            # spy counts the C++ path regardless of which entry the route uses
            def _parse_region(self, addr, length):
                calls["n"] += 1
                return super()._parse_region(addr, length)

        monkeypatch.setattr(native, "FastParser", SpyParser)
        rc = cli.main(
            [
                "--trainingData", str(train),
                "--requests", str(reqs),
                "--fastIngest", "false",
                "--performanceOut", str(tmp_path / "p.jsonl"),
            ]
        )
        assert rc == 0
        assert calls["n"] == 0
