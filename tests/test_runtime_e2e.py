"""End-to-end runtime tests: the reference's full operating loop — create via
request stream, train on a JSON record stream, serve forecasts, query models,
and terminate with final JobStatistics (SURVEY.md sections 3.2-3.5)."""

import json

import numpy as np

from omldm_tpu.api import DataInstance, Request
from omldm_tpu.config import JobConfig
from omldm_tpu.runtime import StreamJob
from omldm_tpu.runtime.ingest import interleave, memory_events
from omldm_tpu.runtime.job import (
    FORECASTING_STREAM,
    REQUEST_STREAM,
    TRAINING_STREAM,
)


def make_stream(n, dim=8, seed=0):
    """Synthetic HIGGS-like binary classification JSON stream."""
    rng = np.random.RandomState(seed)
    w = rng.randn(dim)
    x = rng.randn(n, dim).astype(np.float64)
    y = (x @ w > 0).astype(np.float64)
    lines = [
        json.dumps(
            {
                "numericalFeatures": list(np.round(x[i], 5)),
                "target": float(y[i]),
                "operation": "training",
            }
        )
        for i in range(n)
    ]
    return lines, x, y, w


CREATE = {
    "id": 0,
    "request": "Create",
    "learner": {"name": "PA", "hyperParameters": {"C": 1.0}},
    "preProcessors": [],
    "trainingConfiguration": {"protocol": "CentralizedTraining"},
}


class TestCentralizedEndToEnd:
    def test_full_lifecycle(self):
        cfg = JobConfig(parallelism=1, batch_size=64, test_set_size=64)
        job = StreamJob(cfg)
        lines, x, y, w = make_stream(4000)
        events = [(REQUEST_STREAM, json.dumps(CREATE))] + [
            (TRAINING_STREAM, l) for l in lines
        ]
        report = job.run(events)
        # termination emitted one JobStatistics with one pipeline entry
        assert report is not None
        assert job.stats.terminated
        [stats] = report.statistics
        assert stats.pipeline == 0
        assert stats.protocol == "CentralizedTraining"
        # 20% holdout: roughly 80% trained (holdout set keeps 64, evictions
        # get trained)
        assert stats.fitted > 2500
        assert stats.score > 0.85  # learned the stream
        assert stats.bytes_shipped > 0  # model pushes were accounted
        assert len(stats.learning_curve) > 0
        assert report.duration_ms >= 0

    def test_forecasting_emits_predictions(self):
        cfg = JobConfig(parallelism=1, batch_size=32, test_set_size=32)
        job = StreamJob(cfg)
        lines, x, y, w = make_stream(1500, dim=4)
        fore = [
            json.dumps({"id": i, "numericalFeatures": list(np.round(x[i], 5))})
            for i in range(200)
        ]
        events = (
            [(REQUEST_STREAM, json.dumps(CREATE))]
            + [(TRAINING_STREAM, l) for l in lines]
            + [(FORECASTING_STREAM, l) for l in fore]
        )
        job.run(events)
        assert len(job.predictions) == 200
        # predictions should correlate with the true labels
        preds = np.array([p.value for p in job.predictions])
        signed = y[:200] * 2 - 1
        acc = float((preds == signed).mean())
        assert acc > 0.8

    def test_query_merges_fragments(self):
        cfg = JobConfig(parallelism=4, batch_size=32, test_set_size=32)
        job = StreamJob(cfg)
        lines, *_ = make_stream(2000, dim=4)
        query = {"id": 0, "request": "Query", "requestId": 7}
        events = (
            [(REQUEST_STREAM, json.dumps(CREATE))]
            + [(TRAINING_STREAM, l) for l in lines]
            + [(REQUEST_STREAM, json.dumps(query))]
        )
        job.run(events, terminate_on_end=False)
        # one merged response from 4 worker fragments
        assert len(job.responses) == 1
        resp = job.responses[0]
        assert resp.response_id == 7
        assert resp.mlp_id == 0
        assert resp.learner["name"] == "PA"
        assert resp.data_fitted > 0

    def test_multi_pipeline_multiplexing(self):
        """Two concurrent pipelines over the same stream (the reference's
        task parallelism across networks, SpokeLogic.scala:28-29)."""
        cfg = JobConfig(parallelism=2, batch_size=32, test_set_size=32)
        job = StreamJob(cfg)
        lines, *_ = make_stream(2000, dim=4)
        create2 = dict(CREATE, id=1, learner={"name": "SVM", "hyperParameters": {"lambda": 0.001}})
        events = (
            [(REQUEST_STREAM, json.dumps(CREATE)), (REQUEST_STREAM, json.dumps(create2))]
            + [(TRAINING_STREAM, l) for l in lines]
        )
        report = job.run(events)
        assert report is not None
        assert len(report.statistics) == 2
        pipelines = {s.pipeline for s in report.statistics}
        assert pipelines == {0, 1}
        for s in report.statistics:
            assert s.score > 0.8

    def test_invalid_requests_dropped(self):
        cfg = JobConfig(parallelism=1)
        job = StreamJob(cfg)
        bad = [
            '{"id": 0, "request": "Create", "learner": {"name": "Bogus"}}',
            '{"id": 5, "request": "Delete"}',  # nonexistent
            "not json",
            '{"id": 0, "request": "Query"}',  # nonexistent pipeline
        ]
        job.run([(REQUEST_STREAM, b) for b in bad], terminate_on_end=False)
        assert job.pipeline_manager.live_pipelines == []
        assert job.responses == []

    def test_records_before_create_are_buffered(self):
        """Records arriving before pipeline creation are buffered and trained
        after the Create lands (FlinkSpoke.scala:69-80)."""
        cfg = JobConfig(parallelism=1, batch_size=32, test_set_size=16)
        job = StreamJob(cfg)
        lines, *_ = make_stream(500, dim=4)
        events = (
            [(TRAINING_STREAM, l) for l in lines[:100]]
            + [(REQUEST_STREAM, json.dumps(CREATE))]
            + [(TRAINING_STREAM, l) for l in lines[100:]]
        )
        report = job.run(events)
        [stats] = report.statistics
        # all 500 records participate (minus holdout + ragged tail)
        assert stats.fitted > 300

    def test_update_replaces_pipeline(self):
        """Update recreates the pipeline with the new spec (the reference
        broadcasts Update like Create, FlinkSpoke.scala:144-156)."""
        import json as _json

        job = StreamJob(JobConfig(parallelism=2, batch_size=16, test_set_size=16))
        create = {
            "id": 0, "request": "Create", "requestId": 1,
            "learner": {"name": "PA", "hyperParameters": {"C": 1.0}},
            "trainingConfiguration": {"protocol": "Asynchronous"},
        }
        update = dict(create)
        update["request"] = "Update"
        update["requestId"] = 2
        update["learner"] = {"name": "ORR", "hyperParameters": {"lambda": 0.1}}
        query = {"id": 0, "request": "Query", "requestId": 3}
        rng = np.random.RandomState(0)

        def recs(n, seed):
            r = np.random.RandomState(seed)
            out = []
            for i in range(n):
                x = r.randn(4)
                out.append((TRAINING_STREAM, _json.dumps({
                    "id": i,
                    "numericalFeatures": [round(float(v), 4) for v in x],
                    "target": float(x.sum() > 0),
                })))
            return out

        events = (
            [(REQUEST_STREAM, _json.dumps(create))]
            + recs(200, 1)
            + [(REQUEST_STREAM, _json.dumps(update))]
            + recs(200, 2)
            + [(REQUEST_STREAM, _json.dumps(query))]
        )
        job.run(events)
        user_resps = [r for r in job.responses if r.response_id == 3]
        assert user_resps, "no query response after update"
        assert user_resps[0].learner["name"] == "ORR"
        # the replaced pipeline restarted its fitted counter
        assert user_resps[0].data_fitted <= 200 * 2

    def test_delete_stops_training(self):
        cfg = JobConfig(parallelism=1, batch_size=16)
        job = StreamJob(cfg)
        lines, *_ = make_stream(200, dim=4)
        events = (
            [(REQUEST_STREAM, json.dumps(CREATE))]
            + [(TRAINING_STREAM, l) for l in lines[:100]]
            + [(REQUEST_STREAM, json.dumps({"id": 0, "request": "Delete"}))]
            + [(TRAINING_STREAM, l) for l in lines[100:]]
        )
        report = job.run(events)
        assert report is None or report.statistics == []

    def test_single_learner_protocol_forced_for_kmeans(self):
        """HT/K-means force SingleLearner: the central model trains on the
        hub from forwarded tuples (FlinkSpoke.scala:203-210)."""
        cfg = JobConfig(parallelism=2, batch_size=32, test_set_size=32)
        job = StreamJob(cfg)
        rng = np.random.RandomState(0)
        centers = np.array([[5, 5], [-5, -5]])
        pts = centers[rng.randint(0, 2, 1000)] + rng.randn(1000, 2) * 0.5
        lines = [
            json.dumps({"numericalFeatures": list(np.round(p, 4)), "target": 0.0})
            for p in pts
        ]
        create = {
            "id": 0,
            "request": "Create",
            "learner": {"name": "K-means", "hyperParameters": {"k": 2}},
            "trainingConfiguration": {"protocol": "Asynchronous"},  # overridden
        }
        events = [(REQUEST_STREAM, json.dumps(create))] + [
            (TRAINING_STREAM, l) for l in lines
        ]
        report = job.run(events)
        [stats] = report.statistics
        assert stats.protocol == "SingleLearner"
        assert stats.fitted > 500
        assert stats.models_shipped > 0  # hub shipped the model back


class TestSilenceTimer:
    def test_silence_triggers_termination(self):
        cfg = JobConfig(parallelism=1, timeout_ms=1000, batch_size=16)
        job = StreamJob(cfg)
        lines, *_ = make_stream(100, dim=4)
        events = [(REQUEST_STREAM, json.dumps(CREATE))] + [
            (TRAINING_STREAM, l) for l in lines
        ]
        job.run(events, terminate_on_end=False)
        assert not job.stats.terminated
        # no activity for > timeout
        now = job.stats.last_activity + 1.5
        report = job.check_silence(now)
        assert report is not None
        assert job.stats.terminated

    def test_activity_resets_timer(self):
        cfg = JobConfig(parallelism=1, timeout_ms=1000)
        job = StreamJob(cfg)
        job.stats.mark_activity(100.0)
        assert not job.stats.silence_exceeded(100.5)
        assert job.stats.silence_exceeded(101.1)


class TestPreCreateBacklog:
    """Data that precedes the Create request must still train the pipeline
    once it deploys — on EITHER plane (the reference buffers pre-creation
    records and drains them after createWrapper, FlinkSpoke.scala:69-80;
    the CLI's interleaved file replay routinely delivers the first packed
    block before the request stream's Create)."""

    def _packed_events(self, n=3000, dim=8, seed=0, batch=1024):
        from omldm_tpu.runtime.job import PACKED_STREAM

        rng = np.random.RandomState(seed)
        w = rng.randn(dim)
        x = rng.randn(n, dim).astype(np.float32)
        y = (x @ w > 0).astype(np.float32)
        op = np.zeros(n, np.uint8)
        return [
            (PACKED_STREAM, (x[i : i + batch], y[i : i + batch], op[i : i + batch]))
            for i in range(0, n, batch)
        ]

    def test_packed_rows_before_create_reach_spmd_bridge(self):
        create = {
            "id": 0,
            "request": "Create",
            "learner": {
                "name": "Softmax",
                "hyperParameters": {"learningRate": 0.1, "nClasses": 2},
                "dataStructure": {"nFeatures": 8},
            },
            "preProcessors": [],
            "trainingConfiguration": {
                "protocol": "Synchronous",
                "engine": "spmd",
                "extra": {"stageChain": 2},
            },
        }
        cfg = JobConfig(parallelism=1, batch_size=256, test_set_size=64)
        job = StreamJob(cfg)
        # ALL data arrives before the Create (the failure mode: one packed
        # block holding the whole small file)
        for stream, payload in self._packed_events():
            job.process_event(stream, payload)
        job.process_event(REQUEST_STREAM, json.dumps(create))
        [bridge] = job.spmd_bridges.values()
        bridge.flush()
        assert bridge.trainer.fitted > 2000
        stats = bridge.network_statistics()
        assert stats.score > 0.8

    def test_packed_rows_before_create_reach_host_plane(self):
        create = dict(CREATE)
        cfg = JobConfig(parallelism=2, batch_size=256, test_set_size=64)
        job = StreamJob(cfg)
        for stream, payload in self._packed_events():
            job.process_event(stream, payload)
        job.process_event(REQUEST_STREAM, json.dumps(create))
        # drive termination for the full statistics path
        report = job.run([])
        [stats] = report.statistics
        assert stats.fitted > 2000

    def test_backlog_capped(self):
        from omldm_tpu.runtime.job import PRE_CREATE_BACKLOG_CAP

        cfg = JobConfig(parallelism=1, batch_size=256)
        job = StreamJob(cfg)
        dim = 4
        x = np.zeros((60_000, dim), np.float32)
        y = np.zeros((60_000,), np.float32)
        op = np.zeros((60_000,), np.uint8)
        from omldm_tpu.runtime.job import PACKED_STREAM

        for _ in range(3):  # 180k rows > cap
            job.process_event(PACKED_STREAM, (x, y, op))
        assert len(job._backlog) == PRE_CREATE_BACKLOG_CAP

    def test_backlog_single_oversized_batch_keeps_newest(self):
        from omldm_tpu.runtime.job import (
            PACKED_STREAM,
            PRE_CREATE_BACKLOG_CAP,
        )

        cfg = JobConfig(parallelism=1, batch_size=256)
        job = StreamJob(cfg)
        n = PRE_CREATE_BACKLOG_CAP + 5000
        x = np.arange(n, dtype=np.float32)[:, None]
        y = np.zeros((n,), np.float32)
        op = np.zeros((n,), np.uint8)
        job.process_event(PACKED_STREAM, (x, y, op))
        assert len(job._backlog) == PRE_CREATE_BACKLOG_CAP
        kind, (bx, _, _), _, _ = job._backlog.peek()
        # newest rows kept (partial trim, not a whole-entry drop)
        assert kind == "__packed__" and float(bx[-1, 0]) == float(n - 1)
        assert float(bx[0, 0]) == 5000.0


class TestLiveRescale:
    """Mid-stream parallelism changes without restart (the reference's
    elastic rescale: spokeParallelism bump + wrapper merge +
    mergingDataBuffers, FlinkSpoke.scala:345-348, SpokeLogic.scala:37-50)."""

    def _create(self, protocol="Synchronous"):
        return {
            "id": 0,
            "request": "Create",
            "learner": {"name": "PA", "hyperParameters": {"C": 1.0}},
            "preProcessors": [],
            "trainingConfiguration": {"protocol": protocol},
        }

    def test_train_through_4_8_2_without_restart(self):
        cfg = JobConfig(parallelism=4, batch_size=64, test_set_size=64)
        job = StreamJob(cfg)
        lines, x, y, w = make_stream(9000, dim=8)
        job.process_event(REQUEST_STREAM, json.dumps(self._create()))
        it = iter(lines)
        for _ in range(3000):
            job.process_event(TRAINING_STREAM, next(it))
        job.rescale(8)
        assert len(job.spokes) == 8
        # every worker (old and new) hosts the pipeline with n_workers=8
        for s in job.spokes:
            assert 0 in s.nets
            assert s.nets[0].node.n_workers == 8
        for _ in range(3000):
            job.process_event(TRAINING_STREAM, next(it))
        job.rescale(2)
        assert len(job.spokes) == 2
        for _ in range(3000):
            job.process_event(TRAINING_STREAM, next(it))
        # drive termination: countdown must use the CURRENT parallelism (2)
        report = job.run([])
        assert report is not None and job.stats.terminated
        [stats] = report.statistics
        # loss continuity: all three phases' records trained somewhere —
        # and none double-counted through the shrink merge (the fitted
        # watermark folds into the survivor, protocols/base.py)
        assert 7000 < stats.fitted <= 9000
        assert stats.score > 0.85

    def test_shrink_merges_pending_rows_and_holdout(self):
        cfg = JobConfig(parallelism=4, batch_size=256, test_set_size=32)
        job = StreamJob(cfg)
        lines, *_ = make_stream(1000, dim=8, seed=3)
        job.process_event(REQUEST_STREAM, json.dumps(self._create()))
        for l in lines:
            job.process_event(TRAINING_STREAM, l)
        pending = sum(len(s.nets[0].batcher) for s in job.spokes)
        holdout = sum(len(s.nets[0].test_set) for s in job.spokes)
        fitted_before = sum(s.nets[0].pipeline.fitted for s in job.spokes)
        assert pending > 0
        job.rescale(1)
        [spoke] = job.spokes
        # pending rows from retired spokes re-entered the survivor (either
        # still pending or already trained when a batch filled)
        assert len(spoke.nets[0].batcher) + spoke.nets[0].pipeline.fitted >= (
            pending + fitted_before
        )
        # survivor's sliding holdout absorbed retired points up to capacity
        assert len(spoke.nets[0].test_set) == min(holdout, 32)

    def test_grow_then_query_counts_all_workers(self):
        cfg = JobConfig(parallelism=2, batch_size=64, test_set_size=32)
        job = StreamJob(cfg)
        lines, *_ = make_stream(2000, dim=8, seed=4)
        job.process_event(REQUEST_STREAM, json.dumps(self._create()))
        for l in lines[:1000]:
            job.process_event(TRAINING_STREAM, l)
        job.rescale(4)
        for l in lines[1000:]:
            job.process_event(TRAINING_STREAM, l)
        query = {"id": 0, "request": "Query", "requestId": 7}
        job.process_event(REQUEST_STREAM, json.dumps(query))
        merged = [r for r in job.responses if r.response_id == 7]
        # the merger waited for all 4 workers' fragment sets
        assert merged, "no merged query response after rescale"

    def test_shrink_mid_round_does_not_freeze_training(self):
        """Shrinking while a sync round is half-complete must re-evaluate
        the hub barrier — otherwise every survivor waits forever and live
        training freezes (regression)."""
        cfg = JobConfig(parallelism=4, batch_size=32, test_set_size=16)
        job = StreamJob(cfg)
        lines, *_ = make_stream(6000, dim=6, seed=8)
        job.process_event(
            REQUEST_STREAM, json.dumps(self._create("Synchronous"))
        )
        it = iter(lines)
        for _ in range(2000):
            job.process_event(TRAINING_STREAM, next(it))
        fitted_mid = sum(s.nets[0].pipeline.fitted for s in job.spokes)
        job.rescale(2)
        for _ in range(4000):
            job.process_event(TRAINING_STREAM, next(it))
        fitted_end = sum(s.nets[0].pipeline.fitted for s in job.spokes)
        # live training kept flowing after the shrink
        assert fitted_end > fitted_mid + 2000, (fitted_mid, fitted_end)

    def test_grow_from_parallelism_one_keeps_resolved_protocol(self):
        """A pipeline created at parallelism 1 was forced to
        CentralizedTraining (FlinkSpoke.scala:213-215); growing must deploy
        the SAME resolved protocol on new workers, not re-resolve the
        original request against the new parallelism (regression: new
        SynchronousWorkers waiting on a SimplePS hub froze)."""
        cfg = JobConfig(parallelism=1, batch_size=32, test_set_size=16)
        job = StreamJob(cfg)
        lines, *_ = make_stream(6000, dim=6, seed=9)
        job.process_event(
            REQUEST_STREAM, json.dumps(self._create("Synchronous"))
        )
        it = iter(lines)
        for _ in range(1000):
            job.process_event(TRAINING_STREAM, next(it))
        job.rescale(4)
        protos = {s.nets[0].protocol for s in job.spokes}
        assert protos == {"CentralizedTraining"}, protos
        for _ in range(5000):
            job.process_event(TRAINING_STREAM, next(it))
        for s in job.spokes:
            assert s.nets[0].pipeline.fitted > 500, (
                s.worker_id, s.nets[0].pipeline.fitted
            )
