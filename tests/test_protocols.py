"""Distributed-protocol tests: all 8 protocols end-to-end through the stream
runtime at parallelism 4, plus protocol-specific semantic checks."""

import json

import numpy as np
import pytest

from omldm_tpu.config import JobConfig
from omldm_tpu.runtime import StreamJob
from omldm_tpu.runtime.job import REQUEST_STREAM, TRAINING_STREAM


def stream_lines(n, dim=6, seed=0):
    rng = np.random.RandomState(seed)
    w = rng.randn(dim)
    x = rng.randn(n, dim)
    y = (x @ w > 0).astype(np.float64)
    return [
        json.dumps(
            {"numericalFeatures": list(np.round(x[i], 5)), "target": float(y[i])}
        )
        for i in range(n)
    ]


def run_protocol(protocol, n=3000, parallelism=4, extra=None, learner="PA"):
    cfg = JobConfig(parallelism=parallelism, batch_size=32, test_set_size=32)
    job = StreamJob(cfg)
    tc = {"protocol": protocol, "syncEvery": 2}
    if extra:
        tc.update(extra)
    create = {
        "id": 0,
        "request": "Create",
        "learner": {"name": learner, "hyperParameters": {"C": 1.0}},
        "trainingConfiguration": tc,
    }
    events = [(REQUEST_STREAM, json.dumps(create))] + [
        (TRAINING_STREAM, l) for l in stream_lines(n)
    ]
    report = job.run(events)
    assert report is not None, f"{protocol}: no job statistics emitted"
    [stats] = report.statistics
    return job, stats


ALL_PROTOCOLS = [
    "Asynchronous",
    "Synchronous",
    "SSP",
    "EASGD",
    "GM",
    "FGM",
]


class TestDrainBlockedChaining:
    def test_chained_drain_matches_sequential(self):
        """drain_blocked's fit_many chaining must train exactly the batches
        a sequential drain would, respecting sync-point cadence."""
        from omldm_tpu.api.requests import LearnerSpec, TrainingConfiguration
        from omldm_tpu.pipelines import MLPipeline
        from omldm_tpu.protocols.registry import make_worker_node

        rng = np.random.RandomState(0)
        batches = [
            (
                rng.randn(16, 4).astype(np.float32),
                (rng.randn(16) > 0).astype(np.float32),
                np.ones(16, np.float32),
            )
            for _ in range(7)
        ]

        def build():
            syncs = []
            node = make_worker_node(
                "Synchronous",
                MLPipeline(LearnerSpec("PA", hyper_parameters={"C": 1.0}), dim=4),
                0, 1,
                TrainingConfiguration(protocol="Synchronous", extra={"syncEvery": 3}),
                lambda *a, **k: None,
            )
            # isolate chaining from the protocol's wait-for-reply behavior:
            # record sync-point firings without blocking
            node.on_sync_point = lambda: syncs.append(node._batches)
            return node, syncs

        seq_node, seq_syncs = build()
        for b in batches:
            seq_node.on_training_batch(*b)

        blk_node, blk_syncs = build()
        blk_node.waiting = True
        for b in batches:
            blk_node.on_training_batch(*b)   # all go to the backlog
        blk_node.waiting = False
        blk_node.drain_blocked()

        assert blk_node._batches == seq_node._batches == 7
        assert blk_syncs == seq_syncs == [3, 6]  # same sync cadence
        a = seq_node.pipeline.get_flat_params()[0]
        b = blk_node.pipeline.get_flat_params()[0]
        np.testing.assert_allclose(a, b, atol=1e-5)
        assert [f for _, f in blk_node.pipeline.curve_slice()] == [
            f for _, f in seq_node.pipeline.curve_slice()
        ]


class TestAllProtocolsLearn:
    @pytest.mark.parametrize("protocol", ALL_PROTOCOLS)
    def test_protocol_trains_and_reports(self, protocol):
        job, stats = run_protocol(protocol)
        assert stats.protocol == protocol
        assert stats.fitted > 1500, f"{protocol}: fitted={stats.fitted}"
        assert stats.score > 0.8, f"{protocol}: score={stats.score}"
        assert stats.bytes_shipped > 0
        assert len(stats.learning_curve) > 0


class TestAsynchronous:
    def test_unknown_protocol_falls_back(self):
        # MLNodeGenerator.scala:28,57: unknown keys -> Asynchronous
        job, stats = run_protocol("TotallyMadeUp")
        assert stats.protocol == "Asynchronous"

    def test_ps_replies_only_to_pusher(self):
        job, stats = run_protocol("Asynchronous", n=1000)
        hub = job.hub_manager.hubs[(0, 0)].node
        assert hub.global_params is not None


class TestSynchronous:
    def test_rounds_complete_without_deadlock(self):
        """Mid-stream (before the terminate flush), workers must be cycling
        through rounds, not stuck blocked with batches piling up."""
        cfg = JobConfig(parallelism=4, batch_size=32, test_set_size=32)
        job = StreamJob(cfg)
        create = {
            "id": 0,
            "request": "Create",
            "learner": {"name": "PA", "hyperParameters": {"C": 1.0}},
            "trainingConfiguration": {"protocol": "Synchronous", "syncEvery": 2},
        }
        events = [(REQUEST_STREAM, json.dumps(create))] + [
            (TRAINING_STREAM, l) for l in stream_lines(3000)
        ]
        job.run(events, terminate_on_end=False)
        # inspect BEFORE terminate: every worker trained a healthy share and
        # nobody is sitting on a pile of blocked batches
        for spoke in job.spokes:
            node = spoke.nets[0].node
            assert len(node._blocked) <= 2, f"worker {spoke.worker_id} stalled"
            assert spoke.nets[0].pipeline.fitted > 300
        hub = job.hub_manager.hubs[(0, 0)].node
        assert hub.global_params is not None
        job.terminate()

    def test_workers_converge_to_same_model(self):
        job, stats = run_protocol("Synchronous", n=2000)
        flats = [
            s.nets[0].pipeline.get_flat_params()[0]
            for s in job.spokes
            if 0 in s.nets
        ]
        # after the final round all workers received the same global model;
        # they may have trained a few local batches since, so allow slack
        spread = max(np.linalg.norm(f - flats[0]) for f in flats)
        assert spread < np.linalg.norm(flats[0]) * 0.5 + 1.0


class TestSSP:
    def test_staleness_bound_enforced_during_run(self):
        job, stats = run_protocol("SSP", n=3000, extra={"staleness": 2})
        hub = job.hub_manager.hubs[(0, 0)].node
        clocks = list(hub._clocks.values())
        assert len(clocks) == 4
        # bounded divergence at quiesce (all workers processed equal shares,
        # so clocks should be tight)
        assert max(clocks) - min(clocks) <= 2 + 1


class TestEASGD:
    def test_center_tracks_workers(self):
        job, stats = run_protocol("EASGD", n=2000, extra={"alpha": 0.2})
        hub = job.hub_manager.hubs[(0, 0)].node
        assert hub.center is not None
        flats = [
            s.nets[0].pipeline.get_flat_params()[0]
            for s in job.spokes
            if 0 in s.nets
        ]
        mean_w = np.stack(flats).mean(0)
        # the center should live near the worker cloud
        assert np.linalg.norm(hub.center - mean_w) < np.linalg.norm(mean_w) + 1.0


class TestGM:
    def test_communication_skipping(self):
        """GM ships far fewer bytes than Synchronous for the same stream —
        the whole point of the protocol."""
        _, gm_stats = run_protocol("GM", n=3000, extra={"threshold": 2.0})
        _, sync_stats = run_protocol("Synchronous", n=3000)
        assert gm_stats.bytes_shipped < sync_stats.bytes_shipped
        assert gm_stats.score > 0.8

    def test_violation_triggers_round(self):
        job, stats = run_protocol("GM", n=3000, extra={"threshold": 0.05})
        hub = job.hub_manager.hubs[(0, 0)].node
        assert hub.rounds > 0  # tight threshold forces synchronizations


class TestFGM:
    def test_subrounds_and_rounds(self):
        job, stats = run_protocol("FGM", n=4000, extra={"threshold": 0.3})
        hub = job.hub_manager.hubs[(0, 0)].node
        # the two-phase protocol actually cycled
        assert hub.rounds + hub.subrounds > 0

    def test_cheaper_than_synchronous(self):
        _, fgm_stats = run_protocol("FGM", n=3000, extra={"threshold": 2.0})
        _, sync_stats = run_protocol("Synchronous", n=3000)
        assert fgm_stats.bytes_shipped < sync_stats.bytes_shipped


class TestHubSharding:
    @pytest.mark.parametrize("protocol", ["Asynchronous", "Synchronous", "SSP", "EASGD"])
    def test_hub_parallelism_shards_params(self, protocol):
        """HubParallelism shards the PS: each hub holds a contiguous slice of
        the flat model and receives real traffic; stats merge across hubs
        (FlinkSpoke.scala:181-195, FlinkNetwork.scala:48-149)."""
        job, stats = run_protocol(
            protocol, n=2000, extra={"HubParallelism": 2}
        )
        assert len(job.hub_manager.hubs) == 2
        # both hub shards saw traffic
        for key, hub in job.hub_manager.hubs.items():
            assert hub.node.stats.bytes_shipped > 0, f"hub {key} idle"
        # shard sizes: dim 6 + bias = 7 params -> shards of 4 and 3
        h0 = job.hub_manager.hubs[(0, 0)].node
        h1 = job.hub_manager.hubs[(0, 1)].node
        g0 = h0.global_params if h0.global_params is not None else h0.center
        g1 = h1.global_params if h1.global_params is not None else h1.center
        assert g0.shape == (4,) and g1.shape == (3,)
        assert stats.fitted > 1000
        assert stats.score > 0.8

    def test_single_hub_models_match_sharded(self):
        """Synchronous averaging sharded over 2 hubs equals the unsharded
        result (elementwise protocol => shard-decomposable)."""
        _, s1 = run_protocol("Synchronous", n=2000)
        _, s2 = run_protocol("Synchronous", n=2000, extra={"HubParallelism": 2})
        assert abs(s1.score - s2.score) < 0.05


class TestToggleFairness:
    """Cooperative multi-pipeline fairness: every hub RPC for one net
    TOGGLES the other hosted nets (FlinkSpoke.scala:127-131). Paused nets
    buffer records instead of dropping them and drain on resume, so K
    pipelines multiplexed on one spoke all keep training — no starvation,
    no data loss."""

    def _creates(self, k):
        return [
            {
                "id": i,
                "request": "Create",
                "learner": {"name": "PA", "hyperParameters": {"C": 1.0}},
                "trainingConfiguration": {
                    "protocol": "Asynchronous", "syncEvery": 2,
                },
            }
            for i in range(k)
        ]

    def test_toggle_is_driven_by_hub_rpcs(self):
        # parallelism 2: at 1 the protocol resolves to CentralizedTraining
        # (FlinkSpoke.scala:213-215), whose PS does not RPC back
        cfg = JobConfig(parallelism=2, batch_size=16, test_set_size=16, test=False)
        job = StreamJob(cfg)
        for c in self._creates(2):
            job.process_event(REQUEST_STREAM, json.dumps(c))
        lines = stream_lines(600, dim=6)
        # first record pins the feature dim and deploys the pipelines
        job.process_event(TRAINING_STREAM, lines[0])
        spoke = job.spokes[0]
        assert set(spoke.nets) == {0, 1}
        toggles = {0: 0, 1: 0}
        orig = {}
        for nid, net in spoke.nets.items():
            orig[nid] = net.node.toggle

            def spy(nid=nid):
                toggles[nid] += 1
                return orig[nid]()

            net.node.toggle = spy
        for l in lines[1:]:
            job.process_event(TRAINING_STREAM, l)
        # async pushes for net 0 toggled net 1 and vice versa
        assert toggles[0] > 0 and toggles[1] > 0

    def test_no_starvation_and_no_loss_across_k_pipelines(self):
        k, n = 4, 4000
        cfg = JobConfig(parallelism=2, batch_size=16, test_set_size=16)
        job = StreamJob(cfg)
        for c in self._creates(k):
            job.process_event(REQUEST_STREAM, json.dumps(c))
        for l in stream_lines(n, dim=6):
            job.process_event(TRAINING_STREAM, l)
        report = job.run([])  # drive termination (drains pauses, flushes)
        assert report is not None
        assert len(report.statistics) == k
        for s in report.statistics:
            # every pipeline saw (nearly) the whole stream: holdout keeps
            # test_set_size and the final ragged batch stays pending, but
            # a starved or record-dropping pipeline would sit far below
            assert s.fitted > n - 200, (s.pipeline, s.fitted)
            assert s.score > 0.8

    def test_paused_net_buffers_and_drains(self):
        cfg = JobConfig(parallelism=2, batch_size=8, test_set_size=8, test=False)
        job = StreamJob(cfg)
        spoke = job.spokes[0]
        for c in self._creates(2):
            job.process_event(REQUEST_STREAM, json.dumps(c))
        lines = stream_lines(81, dim=6, seed=3)
        job.process_event(TRAINING_STREAM, lines[0])  # deploy on first record
        net1 = spoke.nets[1]
        net1.node.paused = True
        for l in lines[1:]:
            job.process_event(TRAINING_STREAM, l)
        assert len(net1.pause_buffer) > 0  # held, not dropped
        before = net1.pipeline.fitted
        net1.node.paused = False
        spoke._drain_pause_buffer(net1)
        net1.flush_batch()
        assert net1.pipeline.fitted > before
        assert net1.pause_buffer.is_empty
