"""Kafka adapter tests with fake clients (no broker)."""

import dataclasses
import json

import numpy as np
import pytest

from omldm_tpu.config import JobConfig
from omldm_tpu.runtime import StreamJob
from omldm_tpu.runtime.kafka_io import (
    ProducerSinks,
    connect_kafka,
    consumer_events,
    polling_events,
)


@dataclasses.dataclass
class FakeRecord:
    topic: str
    value: bytes
    partition: int = 0
    offset: int = None  # None -> polling_events falls back to a counter


class FakeProducer:
    def __init__(self):
        self.sent = []

    def send(self, topic, value):
        self.sent.append((topic, value))


def test_full_job_over_fake_kafka():
    rng = np.random.RandomState(0)
    w = rng.randn(4)
    records = [
        FakeRecord(
            "requests",
            json.dumps(
                {
                    "id": 0,
                    "request": "Create",
                    "learner": {"name": "PA", "hyperParameters": {"C": 1.0}},
                    "trainingConfiguration": {"protocol": "CentralizedTraining"},
                }
            ).encode(),
        )
    ]
    for i in range(600):
        x = rng.randn(4)
        records.append(
            FakeRecord(
                "trainingData",
                json.dumps(
                    {"numericalFeatures": list(np.round(x, 4)), "target": float(x @ w > 0)}
                ).encode(),
            )
        )
    records.append(FakeRecord("ignoredTopic", b"junk"))
    for i in range(5):
        x = rng.randn(4)
        records.append(
            FakeRecord(
                "forecastingData",
                json.dumps({"id": i, "numericalFeatures": list(np.round(x, 4))}).encode(),
            )
        )

    producer = FakeProducer()
    sinks = ProducerSinks(producer)
    job = StreamJob(
        JobConfig(parallelism=1, batch_size=32, test_set_size=32),
        on_prediction=sinks.on_prediction,
        on_response=sinks.on_response,
        on_performance=sinks.on_performance,
    )
    job.run(consumer_events(iter(records)))

    topics = [t for t, _ in producer.sent]
    assert topics.count("predictions") == 5
    assert topics.count("performance") == 1
    perf = json.loads([v for t, v in producer.sent if t == "performance"][0])
    assert perf["statistics"][0]["fitted"] > 300


def test_connect_kafka_gated():
    with pytest.raises(ImportError, match="kafka-python"):
        connect_kafka("localhost:9092")


class FakePollingConsumer:
    """kafka-python shape with consumer_timeout_ms: next() raises
    StopIteration on an idle window but subsequent next() calls resume."""

    def __init__(self, windows):
        # windows: list of lists of FakeRecord; each gap between lists is an
        # idle poll window
        self._flat = []
        for w in windows:
            self._flat.extend(w)
            self._flat.append(None)  # idle marker -> StopIteration

    def __next__(self):
        if not self._flat:
            raise StopIteration
        item = self._flat.pop(0)
        if item is None:
            raise StopIteration
        return item


class DeadProducer:
    """Broker gone mid-run: every send raises, and so does close()."""

    def __init__(self):
        self.calls = 0

    def send(self, topic, value):
        self.calls += 1
        raise ConnectionError("broker gone")

    def close(self):
        raise RuntimeError("already dead")


def test_producer_sinks_degrade_when_broker_dies(capsys):
    """A producer that fails mid-run downgrades topic publication to
    warnings + drop counting — it must never raise out of the streaming
    pump loop (the job and its file sinks keep flowing)."""
    from omldm_tpu.utils.backoff import BackoffPolicy

    sinks = ProducerSinks(
        DeadProducer(), retry=BackoffPolicy(attempts=2, base_delay=0.0)
    )
    for i in range(5):
        sinks.on_performance({"i": i})  # must not raise
    assert sinks.dropped == 5
    sinks.close()  # a dead client's close() must not mask shutdown either
    err = capsys.readouterr().err
    assert "dropping record" in err
    assert "5 output record(s) dropped" in err


def test_producer_sinks_breaker_stops_paying_retries():
    """After _BREAKER_AFTER consecutive exhausted sends the sink stops
    retrying (one probe per record, no backoff) so a dead broker does not
    multiply the pump loop's wall-clock; a healed broker closes the
    breaker again via the probe."""
    from omldm_tpu.utils.backoff import BackoffPolicy

    class HealableProducer:
        def __init__(self):
            self.calls = 0
            self.dead = True
            self.sent = []

        def send(self, topic, value):
            self.calls += 1
            if self.dead:
                raise ConnectionError("broker gone")
            self.sent.append((topic, value))

    producer = HealableProducer()
    sinks = ProducerSinks(
        producer, retry=BackoffPolicy(attempts=2, base_delay=0.0)
    )
    trip = sinks._BREAKER_AFTER
    for i in range(trip + 10):
        sinks.on_performance({"i": i})
    # first `trip` records paid 2 attempts each; the rest probed once
    assert producer.calls == trip * 2 + 10
    assert sinks.dropped == trip + 10
    producer.dead = False  # broker heals: the probe succeeds and resets
    sinks.on_performance({"ok": 1})
    assert len(producer.sent) == 1
    assert sinks._consecutive_failures == 0
    # closed breaker: full retry budget is back for the next failure
    producer.dead = True
    before = producer.calls
    sinks.on_performance({"i": -1})
    assert producer.calls == before + 2


def test_producer_sinks_retry_recovers_transient_send():
    from omldm_tpu.utils.backoff import BackoffPolicy

    class FlakyProducer:
        def __init__(self):
            self.calls = 0
            self.sent = []

        def send(self, topic, value):
            self.calls += 1
            if self.calls <= 2:
                raise ConnectionError("transient")
            self.sent.append((topic, value))

    producer = FlakyProducer()
    sinks = ProducerSinks(
        producer, retry=BackoffPolicy(attempts=3, base_delay=0.0)
    )
    sinks.on_performance({"ok": 1})
    assert sinks.dropped == 0
    assert len(producer.sent) == 1


def test_partitions_with_retry():
    """partitions_for_topic returning None transiently (fresh client, no
    metadata yet) retries under the shared policy; a still-empty answer
    after the budget comes back as None so callers keep their degrade
    paths."""
    from omldm_tpu.runtime.kafka_io import _partitions_with_retry
    from omldm_tpu.utils.backoff import BackoffPolicy

    class LaggingMetadata:
        def __init__(self, ready_after):
            self.calls = 0
            self.ready_after = ready_after

        def partitions_for_topic(self, topic):
            self.calls += 1
            return {0, 2, 1} if self.calls >= self.ready_after else None

    ok = LaggingMetadata(ready_after=3)
    policy = BackoffPolicy(attempts=5, base_delay=0.0)
    assert _partitions_with_retry(ok, "t", policy) == {0, 1, 2}
    assert ok.calls == 3

    never = LaggingMetadata(ready_after=99)
    assert _partitions_with_retry(
        never, "t", BackoffPolicy(attempts=2, base_delay=0.0)
    ) is None
    assert never.calls == 2


def test_polling_events_yields_idle_markers():
    """The polling adapter never ends: quiet windows come out as None so the
    driver can run the silence-timer termination check."""
    consumer = FakePollingConsumer(
        [
            [FakeRecord("trainingData", b"{}")],
            [],  # pure idle window
            [FakeRecord("requests", b"{}"), FakeRecord("unknownTopic", b"x")],
        ]
    )
    events = polling_events(consumer)
    seen = [next(events) for _ in range(5)]
    assert seen[0] == ("trainingData", "{}")
    assert seen[1] is None  # first idle window
    assert seen[2] is None  # the empty window
    assert seen[3] == ("requests", "{}")  # unknown topic skipped silently
    assert seen[4] is None
    # exhausted fake keeps signalling idle forever — the iterator never ends
    assert next(events) is None
