"""Kafka adapter tests with fake clients (no broker)."""

import dataclasses
import json

import numpy as np
import pytest

from omldm_tpu.config import JobConfig
from omldm_tpu.runtime import StreamJob
from omldm_tpu.runtime.kafka_io import (
    ProducerSinks,
    connect_kafka,
    consumer_events,
    polling_events,
)


@dataclasses.dataclass
class FakeRecord:
    topic: str
    value: bytes
    partition: int = 0
    offset: int = None  # None -> polling_events falls back to a counter


class FakeProducer:
    def __init__(self):
        self.sent = []

    def send(self, topic, value):
        self.sent.append((topic, value))


def test_full_job_over_fake_kafka():
    rng = np.random.RandomState(0)
    w = rng.randn(4)
    records = [
        FakeRecord(
            "requests",
            json.dumps(
                {
                    "id": 0,
                    "request": "Create",
                    "learner": {"name": "PA", "hyperParameters": {"C": 1.0}},
                    "trainingConfiguration": {"protocol": "CentralizedTraining"},
                }
            ).encode(),
        )
    ]
    for i in range(600):
        x = rng.randn(4)
        records.append(
            FakeRecord(
                "trainingData",
                json.dumps(
                    {"numericalFeatures": list(np.round(x, 4)), "target": float(x @ w > 0)}
                ).encode(),
            )
        )
    records.append(FakeRecord("ignoredTopic", b"junk"))
    for i in range(5):
        x = rng.randn(4)
        records.append(
            FakeRecord(
                "forecastingData",
                json.dumps({"id": i, "numericalFeatures": list(np.round(x, 4))}).encode(),
            )
        )

    producer = FakeProducer()
    sinks = ProducerSinks(producer)
    job = StreamJob(
        JobConfig(parallelism=1, batch_size=32, test_set_size=32),
        on_prediction=sinks.on_prediction,
        on_response=sinks.on_response,
        on_performance=sinks.on_performance,
    )
    job.run(consumer_events(iter(records)))

    topics = [t for t, _ in producer.sent]
    assert topics.count("predictions") == 5
    assert topics.count("performance") == 1
    perf = json.loads([v for t, v in producer.sent if t == "performance"][0])
    assert perf["statistics"][0]["fitted"] > 300


def test_connect_kafka_gated():
    with pytest.raises(ImportError, match="kafka-python"):
        connect_kafka("localhost:9092")


class FakePollingConsumer:
    """kafka-python shape with consumer_timeout_ms: next() raises
    StopIteration on an idle window but subsequent next() calls resume."""

    def __init__(self, windows):
        # windows: list of lists of FakeRecord; each gap between lists is an
        # idle poll window
        self._flat = []
        for w in windows:
            self._flat.extend(w)
            self._flat.append(None)  # idle marker -> StopIteration

    def __next__(self):
        if not self._flat:
            raise StopIteration
        item = self._flat.pop(0)
        if item is None:
            raise StopIteration
        return item


def test_polling_events_yields_idle_markers():
    """The polling adapter never ends: quiet windows come out as None so the
    driver can run the silence-timer termination check."""
    consumer = FakePollingConsumer(
        [
            [FakeRecord("trainingData", b"{}")],
            [],  # pure idle window
            [FakeRecord("requests", b"{}"), FakeRecord("unknownTopic", b"x")],
        ]
    )
    events = polling_events(consumer)
    seen = [next(events) for _ in range(5)]
    assert seen[0] == ("trainingData", "{}")
    assert seen[1] is None  # first idle window
    assert seen[2] is None  # the empty window
    assert seen[3] == ("requests", "{}")  # unknown topic skipped silently
    assert seen[4] is None
    # exhausted fake keeps signalling idle forever — the iterator never ends
    assert next(events) is None
