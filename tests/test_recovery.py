"""Failure detection / restart-from-checkpoint / fault-injection tests.

Reference behavior being pinned: restart-from-checkpoint recovery (the
Flink machinery the reference delegates to — RestartStrategies import at
Job.scala:14, Checkpointing.scala:9-25) with resume at the checkpointed
source offset, plus Flink's fixed-delay restart semantics (bounded
attempts; an uncheckpointed job restarts from scratch)."""

import json

import jax
import numpy as np
import pytest

from omldm_tpu.checkpoint import CheckpointManager
from omldm_tpu.config import JobConfig
from omldm_tpu.runtime import StreamJob
from omldm_tpu.runtime.job import REQUEST_STREAM, TRAINING_STREAM
from omldm_tpu.runtime.recovery import (
    FaultInjector,
    InjectedFault,
    JobSupervisor,
    replayable,
    skip_events,
)


def stream_lines(n, dim=5, seed=0):
    w = np.random.RandomState(42).randn(dim)
    rng = np.random.RandomState(seed)
    x = rng.randn(n, dim)
    y = (x @ w > 0).astype(np.float64)
    return [
        json.dumps(
            {"numericalFeatures": list(np.round(x[i], 5)), "target": float(y[i])}
        )
        for i in range(n)
    ]


CREATE = {
    "id": 0,
    "request": "Create",
    "learner": {"name": "PA", "hyperParameters": {"C": 1.0}},
    "trainingConfiguration": {"protocol": "Synchronous", "syncEvery": 2},
}


def make_events(n=1200, seed=0):
    return [(REQUEST_STREAM, json.dumps(CREATE))] + [
        (TRAINING_STREAM, l) for l in stream_lines(n, seed=seed)
    ]


def checkpointed_job(tmp_path, **kw):
    cfg = JobConfig(
        parallelism=kw.pop("parallelism", 2),
        batch_size=32,
        test_set_size=32,
        checkpointing=True,
        checkpoint_dir=str(tmp_path / "ck"),
        # force a save on every maybe_save call: deterministic coverage
        check_interval_ms=0,
        **kw,
    )
    return StreamJob(cfg)


class TestSupervisorRecovery:
    def test_transient_crash_recovers_and_finishes(self, tmp_path):
        """A mid-stream worker crash restores the latest checkpoint, resumes
        the replay at the snapshot offset, and the job still terminates with
        a trained model."""
        events = make_events()
        job = checkpointed_job(tmp_path)
        fault = FaultInjector()
        fault.arm(job, worker_id=0, after_records=300)
        sup = JobSupervisor(job, replayable(lambda: list(events)))
        report = sup.run()
        assert fault.fired == 1
        assert len(sup.failures) == 1
        assert sup.failures[0].restored_from is not None
        [stats] = report.statistics
        assert stats.score > 0.8
        # every event was consumed by the final incarnation
        assert sup.job.events_processed == len(events)

    def test_recovery_matches_unfaulted_run_exactly(self, tmp_path):
        """Checkpoint state corresponds exactly to the saved offset and the
        checkpoint carries the routing cursor, so a recovered run fits the
        same records as a run that never crashed."""
        events = make_events(n=900)
        clean = checkpointed_job(tmp_path / "clean")
        clean_report = clean.run(list(events))

        job = checkpointed_job(tmp_path / "faulted")
        fault = FaultInjector()
        fault.arm(job, worker_id=1, after_records=200)
        sup = JobSupervisor(job, replayable(lambda: list(events)))
        report = sup.run()

        [clean_stats] = clean_report.statistics
        [stats] = report.statistics
        assert stats.fitted == clean_stats.fitted
        assert stats.score == pytest.approx(clean_stats.score, abs=1e-6)
        w_clean, _ = clean.spokes[0].nets[0].pipeline.get_flat_params()
        w_rec, _ = sup.job.spokes[0].nets[0].pipeline.get_flat_params()
        np.testing.assert_allclose(w_clean, w_rec, rtol=1e-5, atol=1e-6)

    def test_uncheckpointed_job_restarts_from_scratch(self, tmp_path):
        events = make_events(n=600)
        job = StreamJob(JobConfig(parallelism=2, batch_size=32, test_set_size=32))
        fault = FaultInjector()
        fault.arm(job, worker_id=0, after_records=150)
        sup = JobSupervisor(job, replayable(lambda: list(events)))
        report = sup.run()
        assert sup.failures[0].restored_from is None
        # the fresh incarnation replayed the whole stream
        assert sup.job.events_processed == len(events)
        [stats] = report.statistics
        assert stats.score > 0.8

    def test_poison_event_exhausts_restarts(self, tmp_path):
        """A deterministic fault re-armed on every incarnation crashes each
        attempt until max_restarts is exceeded (Flink semantics)."""
        events = make_events(n=2000)
        job = checkpointed_job(tmp_path)

        def arm(j):
            inj = FaultInjector()
            inj.arm(j, worker_id=0, after_records=50)

        arm(job)
        sup = JobSupervisor(
            job,
            replayable(lambda: list(events)),
            max_restarts=2,
            on_failure=lambda rec: arm(sup.job),
        )
        with pytest.raises(InjectedFault):
            sup.run()
        assert len(sup.failures) == 3  # initial + 2 restarts

    def test_failure_record_contents(self, tmp_path):
        events = make_events(n=400)
        job = checkpointed_job(tmp_path)
        FaultInjector().arm(job, worker_id=0, after_records=100)
        sup = JobSupervisor(job, replayable(lambda: list(events)))
        sup.run()
        [rec] = sup.failures
        assert "InjectedFault" in rec.error
        assert rec.offset > 0


class TestOffsetTracking:
    def test_events_processed_counts_and_checkpoints(self, tmp_path):
        events = make_events(n=100)
        job = checkpointed_job(tmp_path)
        job.run(list(events), terminate_on_end=False)
        assert job.events_processed == len(events)
        restored = CheckpointManager(job.config.checkpoint_dir).restore()
        assert restored.events_processed == len(events)

    def test_skip_events(self):
        evs = [("a", 1), ("b", 2), ("c", 3)]
        assert list(skip_events(evs, 2)) == [("c", 3)]
        assert list(skip_events(evs, 5)) == []


class TestSPMDBridgeCheckpoint:
    CREATE_SPMD = {
        "id": 0,
        "request": "Create",
        "learner": {"name": "PA", "hyperParameters": {"C": 1.0}},
        "trainingConfiguration": {
            "protocol": "Synchronous",
            "syncEvery": 2,
            "engine": "spmd",
            "stageChain": 1,
        },
    }

    def _events(self, n=800, seed=0):
        return [(REQUEST_STREAM, json.dumps(self.CREATE_SPMD))] + [
            (TRAINING_STREAM, l) for l in stream_lines(n, seed=seed)
        ]

    def test_bridge_state_roundtrip(self, tmp_path):
        """Fleet state, holdout, stage and progress counters all survive a
        save/restore on the same mesh."""
        cfg = JobConfig(parallelism=2, batch_size=16, test_set_size=32)
        job = StreamJob(cfg)
        job.run(self._events(), terminate_on_end=False)
        bridge = job.spmd_bridges[0]
        mgr = CheckpointManager(str(tmp_path / "ck"))
        mgr.save(job)
        restored = mgr.restore()
        rbridge = restored.spmd_bridges[0]
        np.testing.assert_allclose(
            bridge.trainer.global_flat_params(),
            rbridge.trainer.global_flat_params(),
            rtol=1e-6,
        )
        assert rbridge.trainer.fitted == bridge.trainer.fitted
        assert rbridge.holdout_count == bridge.holdout_count
        assert len(rbridge.test_set) == len(bridge.test_set)
        assert rbridge._stage_n == bridge._stage_n

    def test_bridge_continues_training_after_restore(self, tmp_path):
        cfg = JobConfig(parallelism=2, batch_size=16, test_set_size=32)
        job = StreamJob(cfg)
        job.run(self._events(), terminate_on_end=False)
        mgr = CheckpointManager(str(tmp_path / "ck"))
        mgr.save(job)
        restored = mgr.restore()
        report = restored.run(
            [(TRAINING_STREAM, l) for l in stream_lines(800, seed=1)]
        )
        [stats] = report.statistics
        assert stats.score > 0.8
        assert stats.fitted > job.spmd_bridges[0].trainer.fitted

    def test_supervised_recovery_with_spmd_bridge(self, tmp_path):
        """Crash-and-restore through the supervisor with the pipeline on the
        SPMD engine: the bridge resumes from the checkpointed fleet state."""
        events = self._events(n=1000)
        cfg = JobConfig(
            parallelism=2,
            batch_size=16,
            test_set_size=32,
            checkpointing=True,
            checkpoint_dir=str(tmp_path / "ck"),
            check_interval_ms=0,
        )
        job = StreamJob(cfg)
        fault = FaultInjector()
        # SPMD-engine records still route through host spokes round-robin,
        # so a spoke trip-wire models a worker crash mid-stream
        fault.arm(job, worker_id=0, after_records=120)
        sup = JobSupervisor(job, replayable(lambda: list(events)))
        report = sup.run()
        assert fault.fired == 1
        assert sup.failures[0].restored_from is not None
        [stats] = report.statistics
        assert stats.score > 0.8


    def test_rescale_restore_merges_diverged_replicas(self, tmp_path):
        """Restoring under a DIFFERENT mesh shape must seed every replica
        from the MEAN of the saved dp replicas, not worker 0's shard —
        checkpoints land between events, and under Asynchronous the
        replicas diverge mid-round (worker-0-only would silently discard
        the other workers' progress since the last fold)."""
        import pickle

        create = dict(self.CREATE_SPMD)
        create["trainingConfiguration"] = {
            "protocol": "Asynchronous",
            "syncEvery": 8,  # long rounds: snapshot lands mid-round
            "engine": "spmd",
            "stageChain": 1,
        }
        cfg = JobConfig(parallelism=2, batch_size=16, test_set_size=32)
        job = StreamJob(cfg)
        events = [(REQUEST_STREAM, json.dumps(create))] + [
            (TRAINING_STREAM, l) for l in stream_lines(500, seed=0)
        ]
        job.run(events, terminate_on_end=False)
        # drain the stage so the restore trains nothing (staged rows are
        # re-staged on restore and would retrain on the new mesh)
        job.spmd_bridges[0].flush()
        mgr = CheckpointManager(str(tmp_path / "ck"))
        path = mgr.save(job)
        with open(path, "rb") as f:
            snapshot = pickle.load(f)
        fleet = snapshot["bridges"][0]["fleet"]
        leaves = jax.tree_util.tree_leaves(fleet["params"])
        saved = np.asarray(leaves[0])  # [dp, hub, ...]
        assert saved.shape[0] == 2
        # the premise: replicas actually diverged mid-round
        assert not np.allclose(saved[0, 0], saved[1, 0])
        restored = mgr.restore(parallelism=1)
        rleaves = jax.tree_util.tree_leaves(
            restored.spmd_bridges[0].trainer.state["params"]
        )
        got = np.asarray(rleaves[0])
        expect = saved[:, 0].mean(axis=0)
        np.testing.assert_allclose(got[0, 0], expect, rtol=1e-6, atol=1e-7)


class TestCentralModelRescaleRestore:
    CREATE_SL = {
        "id": 0,
        "request": "Create",
        "learner": {"name": "PA", "hyperParameters": {"C": 1.0}},
        "trainingConfiguration": {"protocol": "SingleLearner"},
    }

    def test_rescale_restore_keeps_hub_model(self, tmp_path):
        """SingleLearner: THE model lives on the hub; restoring under a
        DIFFERENT parallelism must still carry it (round state resets, the
        central model must not)."""
        cfg = JobConfig(parallelism=2, batch_size=32, test_set_size=32)
        job = StreamJob(cfg)
        job.run(
            [(REQUEST_STREAM, json.dumps(self.CREATE_SL))]
            + [(TRAINING_STREAM, l) for l in stream_lines(600)],
            terminate_on_end=False,
        )
        central = job.hub_manager.hubs[(0, 0)].node.pipeline
        w_before, _ = central.get_flat_params()
        assert central.fitted > 0
        mgr = CheckpointManager(str(tmp_path / "ck"))
        mgr.save(job)
        restored = mgr.restore(parallelism=4)
        rcentral = restored.hub_manager.hubs[(0, 0)].node.pipeline
        w_after, _ = rcentral.get_flat_params()
        np.testing.assert_allclose(w_before, w_after, rtol=1e-6)
        assert rcentral.fitted == central.fitted


class TestStaleCheckpointGuard:
    def test_supervisor_ignores_preexisting_checkpoint(self, tmp_path):
        """A snapshot left in a reused checkpoint directory by an EARLIER
        run must not be restored — it would skip (and mask) nearly the
        whole new stream."""
        events = make_events(n=600)
        old = checkpointed_job(tmp_path)
        old.run(list(events), terminate_on_end=False)  # leaves snapshots

        # new run, same directory, checkpoint INTERVAL too long to ever
        # save; crashes on its first records
        cfg = JobConfig(
            parallelism=2,
            batch_size=32,
            test_set_size=32,
            checkpointing=True,
            checkpoint_dir=str(tmp_path / "ck"),
            check_interval_ms=10_000_000,
        )
        job = StreamJob(cfg)
        import time as _time

        job.checkpoint_manager._last_save = _time.time()  # arm the interval
        FaultInjector().arm(job, worker_id=0, after_records=50)
        sup = JobSupervisor(job, replayable(lambda: list(events)))
        report = sup.run()
        # fresh restart, not a restore of the stale snapshot
        assert sup.failures[0].restored_from is None
        assert sup.job.events_processed == len(events)
        [stats] = report.statistics
        assert stats.score > 0.8


class TestCLIRecoveryFlags:
    def test_restart_attempts_flag_supervises(self, tmp_path, monkeypatch):
        """--restartAttempts routes file replay through the supervisor."""
        train = tmp_path / "train.jsonl"
        train.write_text("\n".join(stream_lines(400)) + "\n")
        reqs = tmp_path / "reqs.jsonl"
        reqs.write_text(json.dumps(CREATE) + "\n")
        perf = tmp_path / "perf.jsonl"

        from omldm_tpu.__main__ import main

        calls = {"n": 0}
        from omldm_tpu.runtime import recovery

        orig_run = recovery.JobSupervisor.run

        def spy_run(self, *a, **kw):
            calls["n"] += 1
            return orig_run(self, *a, **kw)

        monkeypatch.setattr(recovery.JobSupervisor, "run", spy_run)
        rc = main(
            [
                "--trainingData", str(train),
                "--requests", str(reqs),
                "--parallelism", "2",
                "--restartAttempts", "2",
                "--performanceOut", str(perf),
            ]
        )
        assert rc == 0
        assert calls["n"] == 1
        out = json.loads(perf.read_text().strip().splitlines()[-1])
        assert out["statistics"][0]["fitted"] > 0


class TestRescaleRecoveryInterplay:
    def test_recover_after_live_rescale_restores_new_parallelism(
        self, tmp_path
    ):
        """A live rescale mid-stream changes config.parallelism; a
        checkpoint taken AFTER it must restore the rescaled worker count
        and keep training through recovery."""
        events = make_events(n=1200)
        job = checkpointed_job(tmp_path, parallelism=2)
        # the supervisor's stale-snapshot floor is recorded at construction:
        # build it BEFORE the deliberate post-rescale checkpoint so that
        # snapshot is above the floor and genuinely restorable
        sup = JobSupervisor(job, replayable(lambda: list(events)))
        # consume half the stream, rescale live, checkpoint, then crash
        job.run(list(events)[:600], terminate_on_end=False)
        job.rescale(4)
        assert len(job.spokes) == 4
        job.checkpoint_manager.maybe_save(job)  # interval 0: saves now

        fault = FaultInjector()
        fault.arm(job, worker_id=3, after_records=30)
        report = sup.run()
        assert fault.fired == 1
        assert sup.failures[0].restored_from is not None
        assert len(sup.job.spokes) == 4
        assert sup.job.config.parallelism == 4
        [stats] = report.statistics
        assert stats.score > 0.8
        assert sup.job.events_processed == len(events)


class TestSparseCheckpointRecovery:
    HASH_SPACE = 1 << 12
    DIM = 3 + HASH_SPACE

    def _create(self):
        return {
            "id": 0,
            "request": "Create",
            "learner": {
                "name": "PA",
                "hyperParameters": {"C": 1.0, "variant": "PA-II"},
                "dataStructure": {
                    "sparse": True, "nFeatures": self.DIM,
                    "hashSpace": self.HASH_SPACE, "maxNnz": 8,
                },
            },
            "preProcessors": [],
            "trainingConfiguration": {"protocol": "Synchronous"},
        }

    def _lines(self, n, seed=0):
        rng = np.random.RandomState(seed)
        hidden = {}
        lines = []
        for _ in range(n):
            num = rng.randn(3)
            cats = [f"c{rng.randint(30)}", f"d{rng.randint(30)}"]
            m = float(num.sum())
            for i, c in enumerate(cats):
                if (i, c) not in hidden:
                    hidden[(i, c)] = rng.randn() * 2.0
                m += hidden[(i, c)]
            lines.append(json.dumps({
                "numericalFeatures": [round(float(v), 5) for v in num],
                "categoricalFeatures": cats,
                "target": float(m > 0),
            }))
        return lines

    def test_sparse_job_checkpoints_and_recovers(self, tmp_path):
        """A job hosting a sparse (padded-COO) pipeline must checkpoint —
        including PENDING rows in the SparseMicroBatcher — and recover
        through the supervisor (previously save() crashed on the sparse
        batcher's attribute layout, making recovery impossible)."""
        events = [(REQUEST_STREAM, json.dumps(self._create()))] + [
            (TRAINING_STREAM, l) for l in self._lines(1800)
        ]
        cfg = JobConfig(
            parallelism=2,
            batch_size=64,
            test_set_size=32,
            checkpointing=True,
            checkpoint_dir=str(tmp_path / "ck"),
            check_interval_ms=0,
        )
        job = StreamJob(cfg)
        fault = FaultInjector()
        fault.arm(job, worker_id=0, after_records=400)
        sup = JobSupervisor(job, replayable(lambda: list(events)))
        report = sup.run()
        assert fault.fired == 1
        assert sup.failures[0].restored_from is not None
        [stats] = report.statistics
        assert stats.fitted > 1200
        # the sparse task at 1800 records is hard; the pin here is the
        # recovery mechanics (save no longer crashes, restore resumes),
        # not asymptotic accuracy
        assert stats.score > 0.6

    def test_sparse_pending_rows_survive_roundtrip(self, tmp_path):
        cfg = JobConfig(parallelism=1, batch_size=64, test_set_size=16)
        job = StreamJob(cfg)
        # 30 records: far fewer than one batch, so rows sit PENDING in the
        # sparse batcher at save time
        job.run(
            [(REQUEST_STREAM, json.dumps(self._create()))]
            + [(TRAINING_STREAM, l) for l in self._lines(30)],
            terminate_on_end=False,
        )
        net = job.spokes[0].nets[0]
        assert len(net.batcher) > 0
        mgr = CheckpointManager(str(tmp_path / "ck"))
        mgr.save(job)  # crashed before the fix
        restored = mgr.restore()
        rnet = restored.spokes[0].nets[0]
        assert len(rnet.batcher) == len(net.batcher)
        np.testing.assert_array_equal(rnet.batcher._idx, net.batcher._idx)
        # and a rescale restore re-feeds the sparse rows without error
        mgr.restore(parallelism=2)
