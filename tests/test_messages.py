"""payload_size exactness: array leaves report real buffer bytes (the
reference's CountableSerial.getSize contract, FlinkMessage.scala:16-23),
pinned for the payload shapes the protocols actually ship."""

import numpy as np

from omldm_tpu.runtime.messages import BroadcastMessage, Message, payload_size


class TestPayloadSizePins:
    def test_dense_model_payload(self):
        flat = np.zeros((8, 28), np.float32)
        assert payload_size(flat) == 8 * 28 * 4

    def test_float64_counts_double(self):
        assert payload_size(np.zeros((10,), np.float64)) == 80

    def test_coo_payload(self):
        idx = np.zeros((4, 16), np.int32)
        val = np.zeros((4, 16), np.float32)
        assert payload_size((idx, val)) == 4 * 16 * 4 * 2

    def test_nested_dict_payload(self):
        params = np.zeros((7,), np.float32)
        payload = {
            "params": params,           # 28
            "curve": [(0.5, 10)],       # two python scalars -> 16
            "fitted": 3,                # 8
            "clock": 2,                 # 8
        }
        assert payload_size(payload) == 28 + 16 + 8 + 8

    def test_numpy_scalars_exact_nbytes(self):
        assert payload_size(np.float32(1.5)) == 4
        assert payload_size(np.float64(1.5)) == 8
        assert payload_size(np.int32(7)) == 4

    def test_python_scalars_and_strings(self):
        assert payload_size(1) == 8
        assert payload_size(1.5) == 8
        assert payload_size(True) == 8
        assert payload_size("abc") == 3
        assert payload_size(None) == 0

    def test_message_header_accounting(self):
        m = Message(0, "push", None, None, np.zeros((4,), np.float32))
        assert m.get_size() == 16 + 16 + 16

    def test_broadcast_message_per_destination_ids(self):
        b = BroadcastMessage(0, "update", None, [1, 2, 3],
                             np.zeros((4,), np.float32))
        assert b.get_size() == 16 + 8 * 4 + 16


class TestEncodedLeafIntegration:
    def test_encoded_leaf_counts_wire_bytes(self):
        from omldm_tpu.runtime.codec import TransportCodec

        codec = TransportCodec("int8", min_leaf_size=4)
        raw = {"params": np.zeros((64,), np.float32), "fitted": 1}
        enc = codec.encode(raw, stream="s")
        assert payload_size(raw) == 64 * 4 + 8
        assert payload_size(enc) == 64 + 8 + 8  # q + meta + fitted
