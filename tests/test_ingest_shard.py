"""Sharded multi-process ingest plane (runtime/ingest_shard.py) + the
device-resident hot loop (spmd_bridge._ResidentIngest).

Pins the ISSUE 17 contracts:
- the sharded block stream is BITWISE the single-process parse, for any
  shard count / chunk size (block boundaries carry no semantics);
- the interleave is deterministic under seeded worker chaos: a parser
  killed (or wedged) mid-stream degrades to in-process parsing from the
  exact row the sharded stream stopped at, reason-coded with the
  selfheal failure class — the consumed row sequence never changes;
- unarmed identity: an empty ``ingest`` spec is the exact pre-plane
  route (run_file dispatches to the fused path, no worker processes);
- the device-resident path is bit-identical to the host stage/holdout
  path and refuses to arm when it could not be (SSP pacing, mid-stream);
- the backpressure probes (driver starvation, prefetch emptiness) wire
  into the overload plane's extra_signals and detach cleanly.
"""

import json
import os
import signal
import tempfile
import threading

import numpy as np
import pytest

from omldm_tpu.config import JobConfig
from omldm_tpu.runtime.fast_ingest import iter_file_batches
from omldm_tpu.runtime.ingest_shard import (
    IngestConfig,
    ShardedIngest,
    chunk_span,
    n_chunks,
    parse_ingest_spec,
)
from omldm_tpu.runtime.selfheal import CRASH, HANG


def _write_stream(path, n, dim=6, seed=0):
    rng = np.random.RandomState(seed)
    w = rng.randn(dim)
    x = rng.randn(n, dim)
    y = (x @ w > 0).astype(np.float64)
    with open(path, "w") as f:
        for i in range(n):
            f.write(json.dumps({
                "numericalFeatures": list(np.round(x[i], 5)),
                "target": float(y[i]),
            }) + "\n")


@pytest.fixture(scope="module")
def stream_file():
    tmp = tempfile.NamedTemporaryFile(suffix=".jsonl", delete=False)
    tmp.close()
    _write_stream(tmp.name, 3000, dim=6)
    yield tmp.name, 6, 3000
    os.unlink(tmp.name)


def _reference_rows(path, dim):
    parts = list(iter_file_batches(path, dim, 8192))
    return tuple(np.concatenate([p[i] for p in parts]) for i in range(3))


def _sharded_rows(si):
    xs, ys, ops = [], [], []
    for x, y, op in si.blocks():
        xs.append(x)
        ys.append(y)
        ops.append(op)
    return (
        np.concatenate(xs) if xs else np.zeros((0, si.dim), np.float32),
        np.concatenate(ys) if ys else np.zeros((0,), np.float32),
        np.concatenate(ops) if ops else np.zeros((0,), np.uint8),
    )


# --- spec parsing --------------------------------------------------------


def test_spec_unarmed_forms():
    assert parse_ingest_spec(None) is None
    assert parse_ingest_spec("") is None
    assert parse_ingest_spec(False) is None


def test_spec_on_arms_default_shape():
    cfg = parse_ingest_spec("on")
    assert cfg is not None
    assert cfg.shards >= 1  # one parser per spare core
    assert cfg.device is False
    assert parse_ingest_spec(True) is not None


def test_spec_knobs():
    cfg = parse_ingest_spec(
        "shards=2, chunkKb=256, ring=3, slotRows=500, device=on, waitMs=750"
    )
    assert (cfg.shards, cfg.chunk_kb, cfg.ring, cfg.slot_rows) == (
        2, 256, 3, 500,
    )
    assert cfg.device is True
    assert cfg.wait_ms == 750.0
    # dict form (embedded config tables)
    cfg = parse_ingest_spec({"shards": 1, "device": "false"})
    assert cfg.shards == 1 and cfg.device is False


def test_spec_validation_fails_fast():
    with pytest.raises(ValueError, match="unknown ingest knob"):
        parse_ingest_spec("shards=2,bogus=1")
    with pytest.raises(ValueError, match="want k=v"):
        parse_ingest_spec("junk")
    with pytest.raises(ValueError, match="ring"):
        parse_ingest_spec("ring=0")
    with pytest.raises(ValueError, match="shards"):
        parse_ingest_spec("shards=-1")
    with pytest.raises(ValueError, match="table"):
        parse_ingest_spec(3.5)


def test_bad_spec_raises_at_job_construction():
    from omldm_tpu.runtime import StreamJob

    with pytest.raises(ValueError, match="unknown ingest knob"):
        StreamJob(JobConfig(parallelism=1, ingest="nope=1"))


# --- deterministic chunk grid --------------------------------------------


def test_chunk_spans_partition_file(stream_file):
    path, _, _ = stream_file
    fsize = os.path.getsize(path)
    for chunk_kb in (1, 4, 64):
        cb = chunk_kb * 1024
        spans = []
        with open(path, "rb") as f:
            for k in range(n_chunks(fsize, cb)):
                span = chunk_span(f, k, cb, fsize)
                assert span is not None
                spans.append(span)
            assert chunk_span(f, n_chunks(fsize, cb), cb, fsize) is None
        # contiguous, non-overlapping, covering [0, fsize)
        assert spans[0][0] == 0
        assert spans[-1][1] == fsize
        for (a0, a1), (b0, b1) in zip(spans, spans[1:]):
            assert a1 == b0
            assert a0 <= a1


def test_chunk_span_line_longer_than_chunk():
    """A line spanning several grid windows: interior chunks are empty
    spans, the line belongs to the chunk holding its first byte."""
    tmp = tempfile.NamedTemporaryFile(suffix=".jsonl", delete=False)
    tmp.close()
    try:
        dim = 400  # one line is several KB > the 1 KB chunk grid
        _write_stream(tmp.name, 12, dim=dim)
        ref = _reference_rows(tmp.name, dim)
        si = ShardedIngest(
            tmp.name, dim, IngestConfig(shards=2, chunk_kb=1)
        )
        got = _sharded_rows(si)
        for a, b in zip(ref, got):
            assert np.array_equal(a, b)
    finally:
        os.unlink(tmp.name)


# --- bit-identity --------------------------------------------------------


@pytest.mark.parametrize("shards,chunk_kb", [(1, 64), (2, 16), (3, 7)])
def test_sharded_stream_bitwise_single_process(stream_file, shards, chunk_kb):
    path, dim, n = stream_file
    ref = _reference_rows(path, dim)
    assert ref[0].shape[0] == n
    si = ShardedIngest(
        path, dim, IngestConfig(shards=shards, chunk_kb=chunk_kb)
    )
    got = _sharded_rows(si)
    for a, b in zip(ref, got):
        assert np.array_equal(a, b)
    st = si.stats()
    assert st["rows"] == n
    assert st["workers"] == shards
    assert st["chunks"] == n_chunks(os.path.getsize(path), chunk_kb * 1024)
    assert 0.0 <= si.starvation() <= 1.0
    assert si.degraded is None


def test_ring_smaller_than_chunks_still_exact(stream_file):
    """Workers block on full rings (bounded look-ahead) without changing
    the stream."""
    path, dim, _ = stream_file
    ref = _reference_rows(path, dim)
    si = ShardedIngest(
        path, dim, IngestConfig(shards=2, chunk_kb=4, ring=1, slot_rows=64)
    )
    got = _sharded_rows(si)
    for a, b in zip(ref, got):
        assert np.array_equal(a, b)


# --- failure: degrade to in-process, reason-coded ------------------------


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_worker_kill_midstream_bit_identical(stream_file, seed):
    """Seeded chaos: SIGKILL one parser after a seeded number of blocks.
    The consumed row sequence must be EXACTLY the no-failure sequence and
    the degrade must be reason-coded with the selfheal crash class."""
    path, dim, _ = stream_file
    ref = _reference_rows(path, dim)
    rng = np.random.RandomState(seed)
    kill_after = int(rng.randint(1, 12))
    degrades = []
    si = ShardedIngest(
        path, dim, IngestConfig(shards=2, chunk_kb=8, wait_ms=2000),
        on_degrade=degrades.append,
    )
    victim = si._procs[int(rng.randint(0, 2))]
    xs, ys, ops = [], [], []
    for i, (x, y, op) in enumerate(si.blocks()):
        if i == kill_after and victim.is_alive():
            os.kill(victim.pid, signal.SIGKILL)
            victim.join(timeout=5.0)
        xs.append(x)
        ys.append(y)
        ops.append(op)
    got = (np.concatenate(xs), np.concatenate(ys), np.concatenate(ops))
    for a, b in zip(ref, got):
        assert np.array_equal(a, b)
    assert si.degraded is not None
    assert si.degraded["class"] == CRASH
    assert degrades == [si.degraded]
    assert si.degraded["chunk"] >= 0


def test_wedged_worker_classified_hang(stream_file):
    """A SIGSTOP'd parser (alive but silent past waitMs) degrades with
    the hang class; the stream still completes bit-identically."""
    path, dim, _ = stream_file
    ref = _reference_rows(path, dim)
    si = ShardedIngest(
        path, dim, IngestConfig(shards=2, chunk_kb=16, wait_ms=250)
    )
    victim = si._procs[1]
    os.kill(victim.pid, signal.SIGSTOP)
    # un-wedge shortly after the degrade fires so close() can reap it
    timer = threading.Timer(
        1.0, lambda: os.kill(victim.pid, signal.SIGCONT)
    )
    timer.start()
    try:
        got = _sharded_rows(si)
    finally:
        timer.cancel()
        try:
            os.kill(victim.pid, signal.SIGCONT)
        except (ProcessLookupError, OSError):
            pass
        si.close()
    for a, b in zip(ref, got):
        assert np.array_equal(a, b)
    assert si.degraded is not None
    assert si.degraded["class"] == HANG


# --- unarmed identity / job routing --------------------------------------


def test_unarmed_job_routes_to_fused(monkeypatch):
    from omldm_tpu.runtime import StreamJob

    assert JobConfig().ingest == ""
    job = StreamJob(JobConfig(parallelism=1))
    assert job.ingest_cfg is None
    calls = []
    monkeypatch.setattr(
        job, "run_file_fused", lambda *a, **k: calls.append("fused") or True
    )
    monkeypatch.setattr(
        job, "run_file_sharded",
        lambda *a, **k: calls.append("sharded") or True,
    )
    assert job.run_file("/nonexistent.jsonl", dim=4)
    assert calls == ["fused"]


def test_armed_job_routes_to_sharded(monkeypatch):
    from omldm_tpu.runtime import StreamJob

    job = StreamJob(JobConfig(parallelism=1, ingest="shards=1"))
    assert job.ingest_cfg is not None and job.ingest_cfg.shards == 1
    calls = []
    monkeypatch.setattr(
        job, "run_file_fused", lambda *a, **k: calls.append("fused") or True
    )
    monkeypatch.setattr(
        job, "run_file_sharded",
        lambda *a, **k: calls.append("sharded") or True,
    )
    assert job.run_file("/nonexistent.jsonl", dim=4)
    assert calls == ["sharded"]


def _pa_create(protocol="Synchronous"):
    return json.dumps({
        "id": 0,
        "request": "Create",
        "learner": {"name": "PA", "hyperParameters": {"C": 1.0}},
        "trainingConfiguration": {
            "protocol": protocol, "syncEvery": 2,
            "engine": "spmd", "stageChain": 2,
        },
    })


def _run_job(path, dim, mode, ingest=""):
    from omldm_tpu.runtime import StreamJob
    from omldm_tpu.runtime.job import REQUEST_STREAM

    job = StreamJob(JobConfig(
        parallelism=2, batch_size=64, test_set_size=64, ingest=ingest,
    ))
    job.process_event(REQUEST_STREAM, _pa_create())
    job.ensure_deployed(dim)
    if mode == "sharded":
        assert job.run_file_sharded(path, dim=dim)
    else:
        for blk in iter_file_batches(path, dim, 4096):
            job.process_packed_batch(*blk)
    br = job.spmd_bridges[0]
    if br._resident is not None:
        br._resident.sync_host()
    rep = job.terminate()
    st = rep.statistics[0]
    hx, hy = br.test_set.arrays()
    return {
        "params": br.trainer.global_flat_params().copy(),
        "fitted": st.fitted, "score": st.score,
        "hx": hx.copy(), "hy": hy.copy(),
        "stats": job._ingest_stats,
    }


def test_streamjob_sharded_and_resident_bitwise_parity(stream_file):
    """The core acceptance pin: packed, sharded, and sharded+device runs
    of the SAME stream produce bitwise-equal trained params, fitted
    counts, scores, and holdout contents."""
    path, dim, n = stream_file
    base = _run_job(path, dim, "packed")
    assert base["fitted"] > 0
    for ingest in ("shards=2,chunkKb=16", "shards=2,chunkKb=16,device=on"):
        got = _run_job(path, dim, "sharded", ingest=ingest)
        assert got["fitted"] == base["fitted"], ingest
        assert got["score"] == base["score"], ingest
        assert np.array_equal(got["params"], base["params"]), ingest
        assert np.array_equal(got["hx"], base["hx"]), ingest
        assert np.array_equal(got["hy"], base["hy"]), ingest
        # phase attribution fodder survives the run
        assert got["stats"]["rows"] == n
        assert got["stats"]["parse_s"] >= 0.0


# --- device-resident hot loop --------------------------------------------


def _mk_bridge(preds, protocol="Synchronous", dim=6):
    from omldm_tpu.api.requests import Request
    from omldm_tpu.runtime.spmd_bridge import make_spmd_bridge

    req = Request.from_json(_pa_create(protocol))
    cfg = JobConfig(parallelism=2, batch_size=32, test_set_size=32)
    return make_spmd_bridge(
        req, dim, cfg, lambda p: preds.append(p.value), lambda r: None
    )


def test_resident_bridge_bit_identical_to_host():
    rng = np.random.RandomState(0)
    dim, n = 6, 1500
    w = rng.randn(dim)
    X = rng.randn(n, dim).astype(np.float32)
    Y = (X @ w > 0).astype(np.float32)
    results = {}
    for mode in ("host", "resident"):
        preds = []
        br = _mk_bridge(preds)
        if mode == "resident":
            assert br.enable_resident_ingest()
            assert not br.supports_fused_ingest()
        i, sizes, s = 0, [1, 7, 150, 333, 64, 945], 0
        while i < n:
            m = min(sizes[s % len(sizes)], n - i)
            s += 1
            op = np.zeros(m, np.int64)
            if m > 10:
                op[m // 2] = 1  # forecast mid-block
            br.handle_batch(X[i:i + m], Y[i:i + m], op)
            i += m
        snap = br.snapshot_buffers()
        br.flush()
        loss, score = br._evaluate()
        if mode == "resident":
            br._resident.sync_host()
        hx, hy = br.test_set.arrays()
        results[mode] = (
            br.trainer.global_flat_params().copy(), br.trainer.fitted,
            loss, score, hx.copy(), hy.copy(), list(preds),
            snap["test_x"].copy(),
        )
    a, b = results["host"], results["resident"]
    assert a[1] == b[1]  # fitted
    assert (a[2], a[3]) == (b[2], b[3])  # loss, score
    assert np.array_equal(a[0], b[0])  # params
    assert np.array_equal(a[4], b[4]) and np.array_equal(a[5], b[5])
    assert a[6] == b[6] and len(a[6]) > 0  # forecasts
    assert np.array_equal(a[7], b[7])  # snapshot


def test_resident_restore_roundtrip():
    rng = np.random.RandomState(3)
    dim = 6
    X = rng.randn(700, dim).astype(np.float32)
    Y = (X @ rng.randn(dim) > 0).astype(np.float32)
    preds = []
    src = _mk_bridge(preds)
    assert src.enable_resident_ingest()
    src.handle_batch(X, Y, np.zeros(len(X), np.int64))
    snap = src.snapshot_buffers()
    dst = _mk_bridge(preds)
    assert dst.enable_resident_ingest()
    dst.restore_buffers(snap)
    dst._resident.sync_host()
    src._resident.sync_host()
    sx, sy = src.test_set.arrays()
    dx, dy = dst.test_set.arrays()
    assert np.array_equal(sx, dx) and np.array_equal(sy, dy)
    assert len(dst.test_set) == len(src.test_set)


def test_resident_arming_refusals():
    # SSP pacing keeps per-row admission on the host: refuse
    preds = []
    br = _mk_bridge(preds, protocol="SSP")
    assert not br.supports_resident_ingest()
    assert not br.enable_resident_ingest()
    # mid-stream arming (rows already buffered) is refused
    br2 = _mk_bridge(preds)
    br2.handle_batch(
        np.ones((20, 6), np.float32), np.ones(20, np.float32),
        np.zeros(20, np.int64),
    )
    assert not br2.enable_resident_ingest()
    # a fresh bridge arms
    br3 = _mk_bridge(preds)
    assert br3.enable_resident_ingest()


# --- backpressure probes --------------------------------------------------


def test_prefetcher_as_signal_reports_emptiness():
    from omldm_tpu.runtime.prefetch import Prefetcher

    pf = Prefetcher(iter([1, 2, 3]), depth=2)
    probe = pf.as_signal()
    for item in pf:
        pass
    value, high, critical = probe()
    assert (high, critical) == (0.75, 0.95)
    assert value == 1.0  # drained ring = fully parse-bound
    pf.close()


def test_spoke_probe_attach_detach():
    from omldm_tpu.runtime import StreamJob
    from omldm_tpu.runtime.job import REQUEST_STREAM

    # overload unarmed: no-op, no crash
    job = StreamJob(JobConfig(parallelism=1))
    job.process_event(REQUEST_STREAM, _pa_create())
    for spoke in job.spokes:
        spoke.attach_ingest_probe("x", lambda: (0.0, 1.0, 1.0))
        spoke.detach_ingest_probe("x")
    # overload armed (host-plane net: the controller arms per-net at
    # deploy): the probe lands in extra_signals and detaches
    job2 = StreamJob(JobConfig(
        parallelism=1,
        overload="window=8,share=2,hotHigh=6,hotCritical=12,cool=8",
    ))
    job2.process_event(REQUEST_STREAM, json.dumps({
        "id": 0,
        "request": "Create",
        "learner": {"name": "PA", "hyperParameters": {"C": 1.0}},
        "trainingConfiguration": {"protocol": "CentralizedTraining"},
    }))
    job2.ensure_deployed(6)
    probe = lambda: (0.0, 0.5, 0.9)
    armed = 0
    for spoke in job2.spokes:
        spoke.attach_ingest_probe("ingest_starvation", probe)
        if spoke.overload is not None:
            armed += 1
            assert spoke.overload.extra_signals["ingest_starvation"] is probe
        spoke.detach_ingest_probe("ingest_starvation")
        if spoke.overload is not None:
            assert "ingest_starvation" not in spoke.overload.extra_signals
    assert armed > 0
