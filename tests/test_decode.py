"""KV-cache decode: incremental forward == full forward; generation works."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from omldm_tpu.models.decode import forward_with_cache, generate, init_kv_cache
from omldm_tpu.models.transformer import (
    TransformerConfig,
    init_transformer,
    transformer_forward,
)
from omldm_tpu.parallel.seq_trainer import SeqTrainer, make_seq_mesh

CFG = TransformerConfig(
    vocab_size=32, d_model=16, n_heads=2, n_layers=2, d_ff=32, max_len=64,
)


def test_prefill_matches_full_forward():
    params = init_transformer(CFG, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 12), 0, 32)
    full = transformer_forward(CFG, params, tokens)
    cache = init_kv_cache(CFG, 2)
    cached, cache = forward_with_cache(CFG, params, tokens, cache)
    np.testing.assert_allclose(np.asarray(cached), np.asarray(full), atol=1e-4)
    assert int(cache["pos"]) == 12


def test_incremental_decode_matches_full_forward():
    """Feeding tokens one at a time through the cache gives the same logits
    as one causal forward over the whole sequence."""
    params = init_transformer(CFG, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(2), (2, 10), 0, 32)
    full = transformer_forward(CFG, params, tokens)
    cache = init_kv_cache(CFG, 2)
    outs = []
    for i in range(10):
        logits, cache = forward_with_cache(CFG, params, tokens[:, i : i + 1], cache)
        outs.append(logits[:, 0])
    inc = jnp.stack(outs, axis=1)
    np.testing.assert_allclose(np.asarray(inc), np.asarray(full), atol=1e-4)


def test_generate_reproduces_learned_pattern():
    """Train on a repeating pattern, then greedy-generate it from a prompt."""
    rng = np.random.RandomState(0)
    trainer = SeqTrainer(CFG, mesh=make_seq_mesh(1, 1, 1), lr=5e-3, seed=3)
    base = rng.randint(1, 32, size=(8, 4))
    toks = np.tile(base, (1, 9))[:, :33]
    x, y = toks[:, :-1].astype(np.int32), toks[:, 1:].astype(np.int32)
    for _ in range(150):
        trainer.step(x, y)
    params = jax.tree_util.tree_map(jnp.asarray, trainer.host_params())
    prompt = x[:, :8]  # two full periods
    out = np.asarray(generate(CFG, params, jnp.asarray(prompt), 8))
    expected = toks[:, 8:16]
    acc = (out == expected).mean()
    assert acc > 0.9, f"generation accuracy {acc}"


def test_generate_sampled_shape_and_range():
    params = init_transformer(CFG, jax.random.PRNGKey(0))
    prompt = jnp.ones((2, 4), jnp.int32)
    out = generate(CFG, params, prompt, 5, temperature=1.0,
                   rng=jax.random.PRNGKey(7))
    assert out.shape == (2, 5)
    assert int(out.min()) >= 0 and int(out.max()) < 32


def test_generate_rejects_overflow():
    params = init_transformer(CFG, jax.random.PRNGKey(0))
    with pytest.raises(ValueError, match="exceeds"):
        generate(CFG, params, jnp.ones((1, 60), jnp.int32), 10)


def test_generate_rejects_max_len_past_pos_table():
    params = init_transformer(CFG, jax.random.PRNGKey(0))
    with pytest.raises(ValueError, match="positional table"):
        generate(CFG, params, jnp.ones((1, 4), jnp.int32), 4, max_len=128)


def test_forward_with_cache_rejects_bad_configs_and_overflow():
    import dataclasses

    params = init_transformer(CFG, jax.random.PRNGKey(0))
    cache = init_kv_cache(CFG, 1, max_len=8)
    ccfg = dataclasses.replace(CFG, causal=False)
    with pytest.raises(ValueError, match="causal lm"):
        forward_with_cache(ccfg, params, jnp.ones((1, 4), jnp.int32), cache)
    # eager cache overflow is caught
    _, cache = forward_with_cache(CFG, params, jnp.ones((1, 6), jnp.int32), cache)
    with pytest.raises(ValueError, match="cache overflow"):
        forward_with_cache(CFG, params, jnp.ones((1, 4), jnp.int32), cache)


def test_generate_zero_tokens_returns_empty():
    """generate(n_tokens=0) must return [B, 0], not IndexError on an empty
    key split (regression)."""
    params = init_transformer(CFG, jax.random.PRNGKey(0))
    prompt = jnp.asarray(np.zeros((3, 4), np.int32))
    out = generate(CFG, params, prompt, n_tokens=0)
    assert out.shape == (3, 0)
    assert out.dtype == jnp.int32
