"""Execute the REAL ``connect_kafka`` body against a loopback fake broker.

The other Kafka tests stub ``connect_kafka`` itself; here a fake ``kafka``
module (kafka-python's exact client surface: KafkaConsumer subscribe/assign/
seek/seek_to_beginning/seek_to_end/partitions_for_topic/end_offsets,
KafkaProducer.send, TopicPartition) is injected into ``sys.modules`` so the
production wiring — topic mapping, tracker seeding, metadata retry, the
recovery seek split (tracked offset / request rewind / data live-end) — runs
for real. Reference counterpart: KafkaUtils.scala:11-54 and the consumer
group wiring of RequestDeserializer.scala:24-30.
"""

import sys
import types
from collections import namedtuple

import pytest

from omldm_tpu.runtime import kafka_io
from omldm_tpu.runtime.kafka_io import DEFAULT_TOPICS, connect_kafka

TopicPartition = namedtuple("TopicPartition", ["topic", "partition"])
ConsumerRecord = namedtuple(
    "ConsumerRecord", ["topic", "partition", "offset", "value"]
)


class FakeBroker:
    """Topic/partition logs with offsets — the loopback 'cluster'."""

    def __init__(self, partitions_per_topic=1, metadata_failures=0):
        self.logs = {}  # (topic, partition) -> list[bytes]
        self.partitions_per_topic = dict(partitions_per_topic) if isinstance(
            partitions_per_topic, dict
        ) else None
        self.default_parts = (
            partitions_per_topic if self.partitions_per_topic is None else 1
        )
        # transient metadata unavailability: the first N
        # partitions_for_topic calls per topic return None (fresh-client
        # behavior the production code retries around)
        self.metadata_failures = metadata_failures
        self._metadata_calls = {}

    def n_parts(self, topic):
        if self.partitions_per_topic is not None:
            return self.partitions_per_topic.get(topic, 1)
        return self.default_parts

    def append(self, topic, value, partition=0):
        log = self.logs.setdefault((topic, partition), [])
        log.append(value if isinstance(value, bytes) else value.encode())

    def end_offset(self, topic, partition):
        return len(self.logs.get((topic, partition), []))

    def partitions_for_topic(self, topic):
        calls = self._metadata_calls.get(topic, 0)
        self._metadata_calls[topic] = calls + 1
        if calls < self.metadata_failures:
            return None
        return set(range(self.n_parts(topic)))


class FakeKafkaConsumer:
    def __init__(self, broker, *topics, consumer_timeout_ms=1000, **_):
        self._broker = broker
        self._positions = {}  # TopicPartition -> next offset
        if topics:
            # subscribe mode: start at the live END of each partition
            for t in topics:
                for p in range(broker.n_parts(t)):
                    tp = TopicPartition(t, p)
                    self._positions[tp] = broker.end_offset(t, p)
        self.closed = False
        self.seeks = {}  # record of explicit seeks for assertions

    # --- metadata / assignment surface ---

    def partitions_for_topic(self, topic):
        return self._broker.partitions_for_topic(topic)

    def end_offsets(self, tps):
        return {
            tp: self._broker.end_offset(tp.topic, tp.partition) for tp in tps
        }

    def assign(self, tps):
        self._positions = {tp: 0 for tp in tps}

    def seek(self, tp, offset):
        self._positions[tp] = offset
        self.seeks[tp] = ("seek", offset)

    def seek_to_beginning(self, tp):
        self._positions[tp] = 0
        self.seeks[tp] = ("beginning", 0)

    def seek_to_end(self, tp):
        self._positions[tp] = self._broker.end_offset(tp.topic, tp.partition)
        self.seeks[tp] = ("end", self._positions[tp])

    def position(self, tp):
        return self._positions[tp]

    # --- iteration (consumer_timeout_ms shape: StopIteration on idle) ---

    def __next__(self):
        for tp in sorted(self._positions):
            log = self._broker.logs.get((tp.topic, tp.partition), [])
            off = self._positions[tp]
            if off < len(log):
                self._positions[tp] = off + 1
                return ConsumerRecord(tp.topic, tp.partition, off, log[off])
        raise StopIteration

    def close(self):
        self.closed = True


class FakeKafkaProducer:
    def __init__(self, broker, **_):
        self._broker = broker
        self.closed = False

    def send(self, topic, value):
        self._broker.append(topic, value)

    def close(self):
        self.closed = True


def _module_for(broker):
    """A fake ``kafka`` module bound to ``broker``; installed into
    ``sys.modules`` so the production ``from kafka import ...`` resolves
    to it."""
    mod = types.ModuleType("kafka")
    mod.TopicPartition = TopicPartition

    class _Consumer(FakeKafkaConsumer):
        def __init__(self, *topics, **kw):
            kw.pop("bootstrap_servers", None)
            super().__init__(broker, *topics, **kw)

    class _Producer(FakeKafkaProducer):
        def __init__(self, **kw):
            kw.pop("bootstrap_servers", None)
            super().__init__(broker, **kw)

    mod.KafkaConsumer = _Consumer
    mod.KafkaProducer = _Producer
    return mod


def _install(monkeypatch, broker):
    monkeypatch.setitem(sys.modules, "kafka", _module_for(broker))


TRAIN_REC = b'{"numericalFeatures": [1.0, 2.0], "target": 1.0, "operation": "training"}'


class TestFreshConnect:
    def test_subscribe_starts_at_live_end(self, monkeypatch):
        broker = FakeBroker()
        broker.append("trainingData", b"old-1")
        broker.append("trainingData", b"old-2")
        _install(monkeypatch, broker)
        tracker = {}
        events, sinks = connect_kafka("fake:9092", tracker=tracker)
        broker.append("trainingData", TRAIN_REC)
        got = [next(events) for _ in range(2)]
        # pre-connect records never replay; the new record arrives; idle
        # windows surface as None
        assert got[0] == ("trainingData", TRAIN_REC.decode())
        assert got[1] is None
        sinks.close()

    def test_tracker_seeded_with_start_positions(self, monkeypatch):
        """Idle partitions are recorded at their starting offset so a later
        snapshot seeks them back there instead of replaying history."""
        broker = FakeBroker(partitions_per_topic={"forecastingData": 2})
        for _ in range(5):
            broker.append("forecastingData", b"ancient")
        _install(monkeypatch, broker)
        tracker = {}
        connect_kafka("fake:9092", tracker=tracker)
        assert tracker[("forecastingData", 0)] == 5
        assert tracker[("forecastingData", 1)] == 0
        assert tracker[("trainingData", 0)] == 0
        assert tracker[("requests", 0)] == 0

    def test_consumed_records_advance_tracker(self, monkeypatch):
        broker = FakeBroker()
        _install(monkeypatch, broker)
        tracker = {}
        events, _ = connect_kafka("fake:9092", tracker=tracker)
        broker.append("trainingData", TRAIN_REC)
        broker.append("trainingData", TRAIN_REC)
        assert next(events) == ("trainingData", TRAIN_REC.decode())
        assert next(events) == ("trainingData", TRAIN_REC.decode())
        assert tracker[("trainingData", 0)] == 2

    def test_producer_sinks_publish(self, monkeypatch):
        broker = FakeBroker()
        _install(monkeypatch, broker)
        _, sinks = connect_kafka("fake:9092")
        sinks.on_performance({"fitted": 7})
        assert broker.logs[("performance", 0)] == [b'{"fitted": 7}']


class TestRecoveryConnect:
    def test_tracked_partition_resumes_at_offset(self, monkeypatch):
        broker = FakeBroker()
        for i in range(6):
            broker.append("trainingData", b"rec-%d" % i)
        _install(monkeypatch, broker)
        events, _ = connect_kafka(
            "fake:9092", position={("trainingData", 0): 4}
        )
        assert next(events) == ("trainingData", "rec-4")
        assert next(events) == ("trainingData", "rec-5")
        assert next(events) is None

    def test_untracked_data_partition_seeks_to_live_end(self, monkeypatch):
        """A data partition absent from the snapshot must NOT replay its
        retained history (the original consumer started at the end)."""
        broker = FakeBroker(partitions_per_topic={"forecastingData": 1})
        for i in range(8):
            broker.append("forecastingData", b"stale-%d" % i)
        _install(monkeypatch, broker)
        events, sinks = connect_kafka(
            "fake:9092", position={("trainingData", 0): 0}
        )
        consumer = sinks.consumer
        tp = TopicPartition("forecastingData", 0)
        assert consumer.seeks[tp] == ("end", 8)
        # nothing stale comes out; fresh records do
        assert next(events) is None
        broker.append("forecastingData", b"fresh")
        assert next(events) == ("forecastingData", "fresh")

    def test_request_partition_rewinds_to_beginning(self, monkeypatch):
        """The control stream rewinds deliberately when its keys were
        dropped (fresh-state incarnations re-consume Create/Update)."""
        broker = FakeBroker()
        broker.append("requests", b'{"id": 0, "request": "Create"}')
        _install(monkeypatch, broker)
        events, sinks = connect_kafka(
            "fake:9092", position={("trainingData", 0): 0}
        )
        tp = TopicPartition("requests", 0)
        assert sinks.consumer.seeks[tp] == ("beginning", 0)
        assert next(events) == ("requests", '{"id": 0, "request": "Create"}')

    def test_snapshot_only_partition_still_assigned(self, monkeypatch):
        """A partition recorded in the snapshot but missing from current
        metadata (e.g. shrunk fake metadata) is still assigned and sought."""
        broker = FakeBroker()
        broker.append("trainingData", b"a", partition=0)
        log = broker.logs.setdefault(("trainingData", 3), [])
        log.extend([b"x", b"y"])
        _install(monkeypatch, broker)
        events, sinks = connect_kafka(
            "fake:9092",
            position={("trainingData", 0): 1, ("trainingData", 3): 1},
        )
        assert next(events) == ("trainingData", "y")

    def test_metadata_retry_then_fallback_warning(self, monkeypatch, capsys):
        """partitions_for_topic failing transiently is retried; permanent
        failure falls back to snapshot partitions + 0 with a warning."""
        broker = FakeBroker(metadata_failures=2)
        broker.append("trainingData", b"r0")
        _install(monkeypatch, broker)
        events, _ = connect_kafka(
            "fake:9092", position={("trainingData", 0): 0}
        )
        assert next(events) == ("trainingData", "r0")  # retry succeeded

        broker2 = FakeBroker(metadata_failures=99)
        broker2.append("trainingData", b"z0")
        _install(monkeypatch, broker2)
        events2, _ = connect_kafka(
            "fake:9092", position={("trainingData", 0): 0}
        )
        assert next(events2) == ("trainingData", "z0")
        assert "no partition metadata" in capsys.readouterr().err


class TestCrashResumeRoundTrip:
    def test_offset_resume_neither_loses_nor_duplicates(self, monkeypatch):
        """Consume some records, 'crash', reconnect with the tracker as the
        position: the stream continues exactly where it left off."""
        broker = FakeBroker()
        _install(monkeypatch, broker)
        tracker = {}
        events, sinks = connect_kafka("fake:9092", tracker=tracker)
        for i in range(10):
            broker.append("trainingData", b"rec-%d" % i)
        seen = [next(events) for _ in range(4)]
        assert [s[1] for s in seen] == ["rec-0", "rec-1", "rec-2", "rec-3"]
        sinks.close()  # crash + supervised teardown
        assert sinks.consumer.closed

        events2, _ = connect_kafka(
            "fake:9092", position=dict(tracker), tracker=tracker
        )
        rest = []
        while True:
            ev = next(events2)
            if ev is None:
                break
            rest.append(ev[1])
        assert rest == ["rec-%d" % i for i in range(4, 10)]
