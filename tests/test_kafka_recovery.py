"""Supervised recovery on the LIVE Kafka path: source offsets in
checkpoints, seek-and-replay on restart (the Kafka-source side of Flink's
restore-from-checkpoint), and fresh-restart-from-live-position without a
snapshot."""

import json

import numpy as np
import pytest

import omldm_tpu.runtime.kafka_io as kafka_io
from omldm_tpu.__main__ import main
from omldm_tpu.runtime.kafka_io import ProducerSinks, polling_events
from omldm_tpu.runtime.spoke import Spoke

from tests.test_kafka_io import FakePollingConsumer, FakeProducer, FakeRecord


@pytest.fixture(scope="module", autouse=True)
def warm_compile_cache():
    """Compile the PA/CentralizedTraining step for dim=4 ONCE before the
    clocked tests: pipelines share jitted programs by (learner, dim,
    batch) spec, and a cold first-event compile (seconds on CPU) would
    otherwise blow the silence timeout mid-stream and terminate the job
    before the injected crash fires."""
    from omldm_tpu.config import JobConfig
    from omldm_tpu.runtime import StreamJob
    from omldm_tpu.runtime.job import REQUEST_STREAM, TRAINING_STREAM

    job = StreamJob(JobConfig(parallelism=1))
    events = [(REQUEST_STREAM, json.dumps({
        "id": 0, "request": "Create",
        "learner": {"name": "PA", "hyperParameters": {"C": 1.0}},
        "trainingConfiguration": {"protocol": "CentralizedTraining"},
    }))]
    rng = np.random.RandomState(9)
    for _ in range(300):
        x = rng.randn(4)
        events.append((TRAINING_STREAM, json.dumps({
            "numericalFeatures": list(np.round(x, 4)), "target": 1.0,
        })))
    job.run(events)


def _records(n=500, dim=4, seed=0):
    """One partition per topic, offsets assigned in stream order."""
    rng = np.random.RandomState(seed)
    w = rng.randn(dim)
    recs = [
        FakeRecord(
            "requests",
            json.dumps({
                "id": 0,
                "request": "Create",
                "learner": {"name": "PA", "hyperParameters": {"C": 1.0}},
                "trainingConfiguration": {"protocol": "CentralizedTraining"},
            }).encode(),
            offset=0,
        )
    ]
    for i in range(n):
        x = rng.randn(dim)
        recs.append(FakeRecord("trainingData", json.dumps({
            "numericalFeatures": list(np.round(x, 4)),
            "target": float(x @ w > 0),
        }).encode(), offset=i))
    return recs


class SeekableFakeBroker:
    """connect_kafka stand-in whose consumers honor ``position``: a rebuilt
    consumer replays exactly the records at-or-after the seeked offsets."""

    def __init__(self, records):
        self.records = records
        self.connects = []  # position passed to each connect
        self.producer = FakeProducer()

    def connect(self, brokers, **kw):
        position = kw.get("position")
        self.connects.append(None if position is None else dict(position))
        recs = [
            r for r in self.records
            if position is None
            or r.offset >= position.get((r.topic, r.partition), 0)
        ]
        consumer = FakePollingConsumer([recs])
        return (
            polling_events(consumer, tracker=kw.get("tracker")),
            ProducerSinks(self.producer),
        )


def _crash_once(monkeypatch, after_records):
    """Class-level transient fault: the first spoke record after the
    threshold raises, once, across all job incarnations."""
    orig = Spoke.handle_data
    state = {"n": 0, "fired": False}

    def crashing(self, inst):
        state["n"] += 1
        if not state["fired"] and state["n"] > after_records:
            state["fired"] = True
            raise RuntimeError("injected kafka-path crash")
        return orig(self, inst)

    monkeypatch.setattr(Spoke, "handle_data", crashing)
    return state


def test_polling_events_tracks_offsets():
    recs = [
        FakeRecord("trainingData", b"{}", partition=0, offset=7),
        FakeRecord("trainingData", b"{}", partition=1, offset=3),
        FakeRecord("requests", b"{}"),  # no offset -> counter fallback
    ]
    tracker = {}
    events = polling_events(FakePollingConsumer([recs]), tracker=tracker)
    for _ in range(3):
        next(events)
    assert tracker[("trainingData", 0)] == 8
    assert tracker[("trainingData", 1)] == 4
    assert tracker[("requests", 0)] == 1


def test_supervised_kafka_recovery_seeks_checkpoint_offsets(
    tmp_path, monkeypatch
):
    broker = SeekableFakeBroker(_records())
    monkeypatch.setattr(kafka_io, "connect_kafka", broker.connect)
    state = _crash_once(monkeypatch, after_records=200)
    perf = tmp_path / "perf.jsonl"
    rc = main([
        "--kafkaBrokers", "fake:9092",
        "--performanceOut", str(perf),
        "--parallelism", "2",
        "--timeout", "2500",
        "--checkpointing",
        "--checkpointDir", str(tmp_path / "ck"),
        "--checkInterval", "0",
        "--restartAttempts", "2",
    ])
    assert rc == 0
    assert state["fired"]
    # reconnected exactly once, seeked to the checkpoint's offsets
    assert len(broker.connects) == 2
    assert broker.connects[0] is None
    seeked = broker.connects[1]
    assert seeked[("trainingData", 0)] > 0
    # the checkpoint matched the crash point exactly (saved every event),
    # so every record was handled exactly once: 20% of 500 holds out,
    # 400 train — more would mean replay double-training, fewer a gap
    stats = json.loads(perf.read_text())
    [s] = stats["statistics"]
    assert s["fitted"] == 400
    assert s["score"] > 0.8


def test_fresh_restart_resumes_from_live_position(tmp_path, monkeypatch):
    """No checkpointing: the next incarnation starts fresh-state but does
    NOT rewind the stream (live-source semantics) — records before the
    crash are not replayed."""
    broker = SeekableFakeBroker(_records())
    monkeypatch.setattr(kafka_io, "connect_kafka", broker.connect)
    state = _crash_once(monkeypatch, after_records=200)
    perf = tmp_path / "perf.jsonl"
    rc = main([
        "--kafkaBrokers", "fake:9092",
        "--performanceOut", str(perf),
        "--parallelism", "2",
        "--timeout", "2500",
        "--restartAttempts", "1",
    ])
    assert rc == 0
    assert state["fired"]
    assert len(broker.connects) == 2
    seeked = broker.connects[1]
    # resumed at the live position (around the crash record), not offset 0
    assert seeked[("trainingData", 0)] >= 190
    stats = json.loads(perf.read_text())
    [s] = stats["statistics"]
    # only the post-crash tail trained into the fresh model
    assert 0 < s["fitted"] < 400


def test_restarts_exhausted_raises(tmp_path, monkeypatch):
    broker = SeekableFakeBroker(_records())
    monkeypatch.setattr(kafka_io, "connect_kafka", broker.connect)

    orig = Spoke.handle_data

    def always_crash(self, inst):
        raise RuntimeError("poison")

    monkeypatch.setattr(Spoke, "handle_data", always_crash)
    with pytest.raises(RuntimeError, match="poison"):
        main([
            "--kafkaBrokers", "fake:9092",
            "--performanceOut", str(tmp_path / "p.jsonl"),
            "--parallelism", "1",
            "--timeout", "2500",
            "--restartAttempts", "2",
        ])
    assert len(broker.connects) == 3  # initial + 2 restarts
