"""Cohort execution engine (runtime/cohort.py): gang-scheduled
multi-pipeline co-hosting.

Pins, per ISSUE 6 acceptance:

- cohort-OFF jobs run the exact pre-cohort code path (no engine, no gang
  objects anywhere);
- cohort-ON execution is BIT-IDENTICAL to per-pipeline execution for every
  dense learner — at the engine level (stage+launch vs direct fit /
  predict / flat params) and end-to-end for multi-tenant jobs (the
  cohort-off job is the per-pipeline reference);
- membership churn (Create/Delete/Update) compacts slots without
  perturbing surviving members; rescale grow/shrink works with cohorts
  active; cohort + codec + reliable-transport compose;
- the bounded `_JIT_CACHE` LRU stays bounded under create/delete churn;
- `programLaunches` counts host-plane program launches (and collapses
  under gang dispatch);
- the strided liveness walk still retires silent workers off records.
"""

import json

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from omldm_tpu.api.requests import LearnerSpec
from omldm_tpu.config import JobConfig
from omldm_tpu.pipelines import MLPipeline
from omldm_tpu.pipelines.pipeline import _JIT_CACHE
from omldm_tpu.runtime import StreamJob
from omldm_tpu.runtime.cohort import Cohort, CohortEngine
from omldm_tpu.runtime.job import (
    FORECASTING_STREAM,
    REQUEST_STREAM,
    TRAINING_STREAM,
)

DIM = 8

# every dense (device-side) learner spec: HT is host-side, K-means params
# carry int counts (flat dtype != f32) — both stay per-pipeline by design
DENSE_LEARNERS = [
    ("PA", {"C": 1.0}, False),
    ("PA", {"C": 1.0}, True),
    ("RegressorPA", {"C": 0.1, "epsilon": 0.1}, False),
    ("ORR", {"lambda": 1.0}, False),
    ("SVM", {}, False),
    ("MultiClassPA", {"C": 1.0, "nClasses": 3}, False),
    ("NN", {"hidden": 8}, False),
    ("Softmax", {"learningRate": 0.05, "nClasses": 2}, False),
]


def _pipes(name, hp, per_record, n, dim=DIM):
    return [
        MLPipeline(
            LearnerSpec(name, hyper_parameters=hp),
            dim=dim,
            rng=jax.random.PRNGKey(11 + i),
            per_record=per_record,
        )
        for i in range(n)
    ]


def _batches(n, t, b, dim=DIM, seed=0):
    rng = np.random.RandomState(seed)
    w = np.random.RandomState(1).randn(dim)
    xs = rng.randn(n, t, b, dim).astype(np.float32)
    ys = (xs @ w > 0).astype(np.float32)
    ms = np.ones((n, t, b), np.float32)
    return xs, ys, ms


def _assert_tree_equal(a, b, msg=""):
    for la, lb in zip(
        jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    ):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb), msg)


class _Cfg:
    """Minimal config stub for CohortEngine construction in unit tests."""

    def __init__(self, cohort="on", cohort_min=1, cohort_impl="map"):
        self.cohort = cohort
        self.cohort_min = cohort_min
        self.cohort_impl = cohort_impl


def _engine(**kw):
    return CohortEngine(_Cfg(**kw))


# --- engine-level bit-identity across every dense learner --------------------


class TestGangBitIdentity:
    @pytest.mark.parametrize("name,hp,per_record", DENSE_LEARNERS)
    def test_staged_gang_fit_matches_solo_fit(self, name, hp, per_record):
        """N attached pipelines staged+launched == N detached pipelines
        fit directly: params, losses, predictions, flat params all
        BITWISE equal (the map-based gang program is the same fit_impl)."""
        n, t, b = 3, 2, 16
        solo = _pipes(name, hp, per_record, n)
        gang = _pipes(name, hp, per_record, n)
        engine = _engine()
        for p in gang:
            engine.consider(p)
        assert all(p._cohort is not None for p in gang)
        cohort = gang[0]._cohort
        assert cohort is gang[-1]._cohort

        xs, ys, ms = _batches(n, t, b)
        ms[n - 1, 1:] = 0.0  # ragged staging depth for the last member
        losses_solo, losses_gang = [], []
        for i in range(n):
            t_i = 1 if i == n - 1 else t
            for ti in range(t_i):
                losses_solo.append(
                    float(solo[i].fit(xs[i, ti], ys[i, ti], ms[i, ti]))
                )
        for i in range(n):
            t_i = 1 if i == n - 1 else t
            for ti in range(t_i):
                losses_gang.append(
                    gang[i].fit(xs[i, ti], ys[i, ti], ms[i, ti])
                )
        engine.flush()
        assert [float(l) for l in losses_gang] == losses_solo
        xq = np.random.RandomState(9).randn(8, DIM).astype(np.float32)
        for i in range(n):
            _assert_tree_equal(solo[i].state, gang[i].state, f"member {i}")
            np.testing.assert_array_equal(
                np.asarray(solo[i].predict(xq)),
                np.asarray(gang[i].predict(xq)),
            )
            fa, _ = solo[i].get_flat_params()
            fb, _ = gang[i].get_flat_params()
            np.testing.assert_array_equal(fa, fb)
            assert solo[i].fitted == gang[i].fitted

    def test_gang_flat_roundtrip_and_writes(self):
        """member_flat reads one shared launch; set_flat_params scatters
        back bitwise (the batched unravel + scatter path)."""
        pipes = _pipes("PA", {"C": 1.0}, False, 4)
        engine = _engine()
        for p in pipes:
            engine.consider(p)
        ref = [p.get_flat_params()[0] for p in pipes]
        new = [r * 2.0 + 1.0 for r in ref]
        for p, r in zip(pipes, new):
            p.set_flat_params(r)
        for p, r in zip(pipes, new):
            np.testing.assert_array_equal(p.get_flat_params()[0], r)
        # and the scattered state is what the next fit consumes
        xs, ys, ms = _batches(4, 1, 16)
        for i, p in enumerate(pipes):
            p.fit(xs[i, 0], ys[i, 0], ms[i, 0])
        engine.flush()
        solo = _pipes("PA", {"C": 1.0}, False, 4)
        for i, p in enumerate(solo):
            p.set_flat_params(new[i])
            p.fit(xs[i, 0], ys[i, 0], ms[i, 0])
            np.testing.assert_array_equal(
                p.get_flat_params()[0], pipes[i].get_flat_params()[0]
            )

    def test_state_checkout_mutation_lands(self):
        """In-place edits of `pipeline.state` (checkpoint restore path)
        reach the stacked tree before the next launch."""
        pipes = _pipes("PA", {"C": 1.0}, False, 2)
        engine = _engine()
        for p in pipes:
            engine.consider(p)
        # train both so params are nonzero (PA initializes at zero)
        xs, ys, ms = _batches(2, 1, 16)
        for i, p in enumerate(pipes):
            p.fit(xs[i, 0], ys[i, 0], ms[i, 0])
        engine.flush()
        sib_before, _ = pipes[1].get_flat_params()
        st = pipes[0].state
        st["params"] = jax.tree_util.tree_map(lambda l: l * 0.0, st["params"])
        flat, _ = pipes[0].get_flat_params()
        np.testing.assert_array_equal(flat, np.zeros_like(flat))
        # the sibling is untouched
        sib, _ = pipes[1].get_flat_params()
        np.testing.assert_array_equal(sib, sib_before)
        assert np.any(sib != 0.0)


# --- membership churn --------------------------------------------------------


class TestCohortChurn:
    def test_detach_preserves_survivors_bitwise(self):
        n = 5
        gang = _pipes("PA", {"C": 1.0}, False, n)
        solo = _pipes("PA", {"C": 1.0}, False, n)
        engine = _engine()
        for p in gang:
            engine.consider(p)
        cohort = gang[0]._cohort
        xs, ys, ms = _batches(n, 4, 16)
        for t in range(2):
            for i in range(n):
                gang[i].fit(xs[i, t], ys[i, t], ms[i, t])
                solo[i].fit(xs[i, t], ys[i, t], ms[i, t])
            engine.flush()
        # detach the middle member mid-stream; its slot frees for reuse
        engine.retire(gang[2])
        assert gang[2]._cohort is None
        freed = cohort.n_active
        late = _pipes("PA", {"C": 1.0}, False, 1)[0]
        engine.consider(late)
        assert cohort.n_active == freed + 1
        for t in range(2, 4):
            for i in range(n):
                gang[i].fit(xs[i, t], ys[i, t], ms[i, t])
                solo[i].fit(xs[i, t], ys[i, t], ms[i, t])
            engine.flush()
        for i in range(n):
            _assert_tree_equal(solo[i].state, gang[i].state, f"member {i}")

    def test_capacity_buckets_and_slot_reuse(self):
        engine = _engine()
        pipes = _pipes("PA", {"C": 1.0}, False, 5)
        for p in pipes:
            engine.consider(p)
        cohort = pipes[0]._cohort
        assert cohort.capacity == 8  # pow2 bucket
        engine.retire(pipes[1])
        engine.retire(pipes[3])
        assert cohort.n_active == 3
        p6 = _pipes("PA", {"C": 1.0}, False, 1)[0]
        engine.consider(p6)
        # churn compacts: the freed slot is reused, capacity unchanged
        assert cohort.capacity == 8
        assert p6._slot in (1, 3)

    def test_empty_cohort_is_dropped(self):
        engine = _engine()
        pipes = _pipes("PA", {"C": 1.0}, False, 2)
        for p in pipes:
            engine.consider(p)
        for p in pipes:
            engine.retire(p)
        assert not engine.cohorts

    def test_auto_threshold(self):
        engine = CohortEngine(_Cfg(cohort="auto", cohort_min=3))
        pipes = _pipes("PA", {"C": 1.0}, False, 3)
        engine.consider(pipes[0])
        engine.consider(pipes[1])
        assert pipes[0]._cohort is None  # below the threshold: pooled
        engine.consider(pipes[2])
        assert all(p._cohort is not None for p in pipes)

    def test_ineligible_learners_stay_solo(self):
        engine = _engine()
        ht = MLPipeline(LearnerSpec("HT"), dim=DIM)
        engine.consider(ht)
        assert ht._cohort is None
        km = MLPipeline(
            LearnerSpec("K-means", hyper_parameters={"k": 2}), dim=DIM
        )
        engine.consider(km)
        assert km._cohort is None


# --- job-level: multi-tenant cohort-on == cohort-off -------------------------


def _mt_job(cohort, n_pipe, records, protocol="Asynchronous", test=True,
            parallelism=1, learner=None, tc_extra=None, chaos=""):
    cfg = JobConfig(
        parallelism=parallelism, batch_size=32, test_set_size=32,
        cohort=cohort, cohort_min=2, chaos=chaos,
    )
    job = StreamJob(cfg)
    job.config.test = test
    learner = learner or {"name": "PA", "hyperParameters": {"C": 1.0}}
    for pid in range(n_pipe):
        tc = {"protocol": protocol, "syncEvery": 4}
        if tc_extra:
            tc.update(tc_extra)
        job.process_event(REQUEST_STREAM, json.dumps({
            "id": pid, "request": "Create",
            "learner": {**learner, "dataStructure": {"nFeatures": DIM}},
            "trainingConfiguration": tc,
        }))
    rng = np.random.RandomState(3)
    w = np.random.RandomState(5).randn(DIM)
    x = rng.randn(records, DIM).astype(np.float32)
    y = (x @ w > 0).astype(np.float32)
    op = np.zeros((records,), np.uint8)
    op[::61] = 1
    for i in range(0, records, 256):
        job.process_packed_batch(x[i:i+256], y[i:i+256], op[i:i+256])
    report = job.terminate()
    preds = {}
    for p in job.predictions:
        preds.setdefault(p.mlp_id, []).append(p.value)
    return job, report, preds


def _assert_job_bitwise(off, on):
    j_off, r_off, p_off = off
    j_on, r_on, p_on = on
    s_off = {s.pipeline: s for s in r_off.statistics}
    s_on = {s.pipeline: s for s in r_on.statistics}
    assert s_off.keys() == s_on.keys()
    for pid, a in s_off.items():
        b = s_on[pid]
        assert a.score == b.score, f"pid {pid} score"
        assert a.fitted == b.fitted, f"pid {pid} fitted"
        assert a.learning_curve == b.learning_curve, f"pid {pid} curve"
        assert a.lcx == b.lcx, f"pid {pid} lcx"
    assert p_off == p_on


class TestMultiTenantBitIdentity:
    """Multi-tenant serving jobs (parallelism 1 — the CentralizedTraining
    route with no mid-stream hub replies): cohort-on is bit-identical to
    the per-pipeline job, for every dense learner, with and without the
    holdout/test harness (the shared-ingest fast path)."""

    @pytest.mark.parametrize("name,hp,per_record", DENSE_LEARNERS)
    def test_bitwise_all_dense_learners(self, name, hp, per_record):
        learner = {"name": name, "hyperParameters": hp}
        tc = {"perRecord": True} if per_record else None
        off = _mt_job("off", 4, 1200, learner=learner, tc_extra=tc)
        on = _mt_job("on", 4, 1200, learner=learner, tc_extra=tc)
        _assert_job_bitwise(off, on)

    @pytest.mark.parametrize("test", [True, False])
    def test_bitwise_serving_modes(self, test):
        off = _mt_job("off", 6, 2000, test=test)
        on = _mt_job("on", 6, 2000, test=test)
        _assert_job_bitwise(off, on)
        # the whole point: gang dispatch collapses program launches
        pl_off = sum(s.program_launches for s in off[1].statistics)
        pl_on = sum(s.program_launches for s in on[1].statistics)
        assert 0 < pl_on < pl_off / 2

    def test_per_record_stream_bitwise(self):
        """The per-record route (handle_data incl. gang forecast serving)."""
        def run(cohort):
            cfg = JobConfig(parallelism=1, batch_size=16, test_set_size=16,
                            cohort=cohort, cohort_min=2)
            job = StreamJob(cfg)
            for pid in range(3):
                job.process_event(REQUEST_STREAM, json.dumps({
                    "id": pid, "request": "Create",
                    "learner": {"name": "PA", "hyperParameters": {"C": 1.0},
                                "dataStructure": {"nFeatures": DIM}},
                    "trainingConfiguration": {"protocol": "Asynchronous"},
                }))
            rng = np.random.RandomState(2)
            w = np.random.RandomState(5).randn(DIM)
            for i in range(600):
                feats = rng.randn(DIM).astype(np.float32)
                if i % 53 == 0:
                    job.process_event(FORECASTING_STREAM, json.dumps(
                        {"numericalFeatures": feats.tolist()}))
                else:
                    job.process_event(TRAINING_STREAM, json.dumps(
                        {"numericalFeatures": feats.tolist(),
                         "target": float(feats @ w > 0)}))
            report = job.terminate()
            preds = [(p.mlp_id, p.value) for p in job.predictions]
            return report, preds

        r_off, p_off = run("off")
        r_on, p_on = run("on")
        assert p_off == p_on
        a = {s.pipeline: (s.score, s.fitted, tuple(s.learning_curve))
             for s in r_off.statistics}
        b = {s.pipeline: (s.score, s.fitted, tuple(s.learning_curve))
             for s in r_on.statistics}
        assert a == b

    def test_churn_mid_stream_does_not_perturb_survivors(self):
        """Create/Delete/Update joining and leaving a cohort mid-stream:
        the surviving members' results stay bitwise equal to the
        cohort-off run of the same event sequence."""
        def run(cohort):
            cfg = JobConfig(parallelism=1, batch_size=16, test_set_size=16,
                            cohort=cohort, cohort_min=2)
            job = StreamJob(cfg)
            rng = np.random.RandomState(7)
            w = np.random.RandomState(5).randn(DIM)
            x = rng.randn(1500, DIM).astype(np.float32)
            y = (x @ w > 0).astype(np.float32)
            op = np.zeros((1500,), np.uint8)

            def create(pid):
                job.process_event(REQUEST_STREAM, json.dumps({
                    "id": pid, "request": "Create",
                    "learner": {"name": "PA", "hyperParameters": {"C": 1.0},
                                "dataStructure": {"nFeatures": DIM}},
                    "trainingConfiguration": {"protocol": "Asynchronous"},
                }))

            for pid in range(3):
                create(pid)
            job.process_packed_batch(x[:500], y[:500], op[:500])
            create(3)  # joins the live cohort
            job.process_packed_batch(x[500:800], y[500:800], op[500:800])
            job.process_event(REQUEST_STREAM, json.dumps(
                {"id": 1, "request": "Delete"}))  # leaves mid-stream
            job.process_packed_batch(x[800:1100], y[800:1100], op[800:1100])
            job.process_event(REQUEST_STREAM, json.dumps({
                "id": 2, "request": "Update",
                "learner": {"name": "PA", "hyperParameters": {"C": 0.5},
                            "dataStructure": {"nFeatures": DIM}},
                "trainingConfiguration": {"protocol": "Asynchronous"},
            }))
            job.process_packed_batch(x[1100:], y[1100:], op[1100:])
            return job.terminate()

        r_off = run("off")
        r_on = run("on")
        a = {s.pipeline: (s.score, s.fitted, tuple(s.learning_curve))
             for s in r_off.statistics}
        b = {s.pipeline: (s.score, s.fitted, tuple(s.learning_curve))
             for s in r_on.statistics}
        assert a == b


# --- multi-worker protocols: convergence parity ------------------------------


class TestMultiWorkerParity:
    """At parallelism > 1 the gang replaces the cooperative pause-toggle
    time slicing, so stream partitioning into batches differs from the
    sequential path — pinned here: every protocol still converges to the
    same quality (the reference makes no cross-pipeline scheduling
    guarantee either; Flink rebalance order is nondeterministic)."""

    @pytest.mark.parametrize(
        "protocol", ["Asynchronous", "Synchronous", "SSP", "EASGD", "GM", "FGM"]
    )
    def test_score_parity(self, protocol):
        off = _mt_job("off", 3, 2000, protocol=protocol, parallelism=2)
        on = _mt_job("on", 3, 2000, protocol=protocol, parallelism=2)
        s_off = {s.pipeline: s.score for s in off[1].statistics}
        s_on = {s.pipeline: s.score for s in on[1].statistics}
        for pid in s_off:
            assert abs(s_off[pid] - s_on[pid]) <= 0.05, (
                f"{protocol} pid {pid}: {s_off[pid]} vs {s_on[pid]}"
            )
        # forecasts all served in both schedules
        assert {k: len(v) for k, v in off[2].items()} == \
               {k: len(v) for k, v in on[2].items()}


# --- rescale with cohorts active ---------------------------------------------


class TestRescaleWithCohorts:
    def _job(self, n_pipe=3, parallelism=2):
        cfg = JobConfig(parallelism=parallelism, batch_size=16,
                        test_set_size=16, cohort="on", cohort_min=1)
        job = StreamJob(cfg)
        for pid in range(n_pipe):
            job.process_event(REQUEST_STREAM, json.dumps({
                "id": pid, "request": "Create",
                "learner": {"name": "PA", "hyperParameters": {"C": 1.0},
                            "dataStructure": {"nFeatures": DIM}},
                "trainingConfiguration": {"protocol": "Asynchronous"},
            }))
        return job

    def _stream(self, job, lo, hi, seed=3):
        rng = np.random.RandomState(seed)
        w = np.random.RandomState(5).randn(DIM)
        x = rng.randn(hi, DIM).astype(np.float32)
        y = (x @ w > 0).astype(np.float32)
        op = np.zeros((hi,), np.uint8)
        for i in range(lo, hi, 256):
            job.process_packed_batch(x[i:i+256], y[i:i+256], op[i:i+256])

    def test_grow_then_shrink(self):
        job = self._job()
        self._stream(job, 0, 1024)
        job.rescale(4)   # new spokes host + cohort the live pipelines
        for spoke in job.spokes:
            assert spoke.cohorts is not None
            for net in spoke.nets.values():
                assert net.pipeline._cohort is not None
        self._stream(job, 1024, 2048)
        job.rescale(1)   # retiring spokes dissolve cohorts and merge in
        self._stream(job, 2048, 3072)
        report = job.terminate()
        assert len(report.statistics) == 3
        for s in report.statistics:
            assert s.score > 0.8
            assert s.fitted > 0

    def test_shrink_marks_shared_taint(self):
        job = self._job()
        self._stream(job, 0, 512)
        job.rescale(1)
        for net in job.spokes[0].nets.values():
            assert net.shared_taint


# --- composition: cohort + codec + reliable transport ------------------------


class TestCohortComposition:
    def test_cohort_codec_chaos_smoke(self):
        """Cohorts + int8 transport codec + seeded chaos (which arms the
        reliable channel): the job converges and the resilience plane
        engaged."""
        chaos = "seed=7,drop=0.03,dup=0.1,reorder=0.1,window=4"
        job, report, _ = _mt_job(
            "on", 3, 3000, protocol="Synchronous", parallelism=2,
            tc_extra={"comm": {"codec": "int8"}}, chaos=chaos,
        )
        for s in report.statistics:
            assert s.score > 0.75
            assert s.bytes_on_wire > 0
        total_dup = sum(s.duplicates_dropped for s in report.statistics)
        assert total_dup > 0, "reliable channel never engaged under chaos"

    def test_cohort_with_codec_bitwise_vs_off_at_par1(self):
        off = _mt_job("off", 3, 1200, tc_extra={"comm": {"codec": "int8"}})
        on = _mt_job("on", 3, 1200, tc_extra={"comm": {"codec": "int8"}})
        _assert_job_bitwise(off, on)


# --- satellites --------------------------------------------------------------


class TestJitCacheLRU:
    def test_churn_keeps_cache_bounded(self):
        """A long Create/Delete churn over varying dims must not grow the
        jit cache without bound (it was an unbounded dict)."""
        start = len(_JIT_CACHE)
        for i in range(_JIT_CACHE.cap + 40):
            MLPipeline(
                LearnerSpec("PA", hyper_parameters={"C": 1.0}),
                dim=3 + i,  # a fresh spec every time
            )
        assert len(_JIT_CACHE) <= _JIT_CACHE.cap

    def test_lru_evicts_oldest_and_reuses_hot(self):
        from omldm_tpu.pipelines.pipeline import _LRUCache

        lru = _LRUCache(2)
        lru.put("a", 1)
        lru.put("b", 2)
        assert lru.get("a") == 1  # refresh a
        lru.put("c", 3)           # evicts b
        assert "b" not in lru and "a" in lru and "c" in lru


class TestProgramLaunchCounter:
    def test_counts_solo_dispatches(self):
        job, report, _ = _mt_job("off", 2, 600)
        for s in report.statistics:
            assert s.program_launches > 0
        # merge carries it
        a = report.statistics[0]
        merged = a.merge(
            type(a)(pipeline=a.pipeline, program_launches=5)
        )
        assert merged.program_launches == a.program_launches + 5
        assert "programLaunches" in a.to_dict()

    def test_gang_dispatch_collapses_counts(self):
        off = _mt_job("off", 6, 1500)
        on = _mt_job("on", 6, 1500)
        pl_off = sum(s.program_launches for s in off[1].statistics)
        pl_on = sum(s.program_launches for s in on[1].statistics)
        assert pl_on < pl_off / 2

    def test_spoke_flush_timer_records(self):
        job, _, _ = _mt_job("on", 3, 600)
        timing = job.launch_timing()
        assert timing["count"] > 0
        assert timing["p50_ms"] >= 0.0


class TestLivenessStride:
    def test_strided_walk_still_retires_silent_worker(self):
        """The liveness walk now strides over data events; a silent worker
        must still retire within a stride's worth of records."""
        cfg = JobConfig(parallelism=3, batch_size=16, test_set_size=16,
                        liveness_stride=8)
        job = StreamJob(cfg)
        job.process_event(REQUEST_STREAM, json.dumps({
            "id": 0, "request": "Create",
            "learner": {"name": "PA", "hyperParameters": {"C": 1.0},
                        "dataStructure": {"nFeatures": 6}},
            "trainingConfiguration": {
                "protocol": "Synchronous", "syncEvery": 1,
                "comm": {"quorum": 2, "workerTimeoutMs": 1000},
            },
        }))
        hub = job.hub_manager.hubs[(0, 0)].node
        now = [0.0]
        hub._clock = lambda: now[0]
        rng = np.random.RandomState(0)
        w = np.random.RandomState(1).randn(6)

        def lines(n, seed):
            r = np.random.RandomState(seed)
            return [
                json.dumps({"numericalFeatures": f.tolist(),
                            "target": float(f @ w > 0)})
                for f in r.randn(n, 6).astype(np.float32)
            ]

        job.spokes[2].nets[0].node.send = lambda *a, **k: None
        for l in lines(200, 2):
            job.process_event(TRAINING_STREAM, l)
        assert hub._retired_live == set()
        now[0] = 2.0
        for l in lines(64, 3):
            job.process_event(TRAINING_STREAM, l)
        assert hub._retired_live == {2}

    def test_unarmed_job_never_walks(self):
        cfg = JobConfig(parallelism=2, batch_size=16)
        job = StreamJob(cfg)
        assert not job.hub_manager.any_liveness
        job.hub_manager.check_liveness()  # flag-read fast path, no-op
        assert job.hub_manager._liveness_tick == 0


class TestCohortOffIsInert:
    def test_off_builds_no_engine(self):
        cfg = JobConfig(parallelism=1, cohort="off")
        job = StreamJob(cfg)
        assert all(s.cohorts is None for s in job.spokes)
        assert job.hub_manager.gang is None

    def test_auto_below_threshold_stays_solo(self):
        job, _, _ = _mt_job("auto", 2, 300)  # cohort_min is 2 in _mt_job
        # _mt_job sets cohort_min=2, so 2 pipelines DO cohort; rebuild
        cfg = JobConfig(parallelism=1, cohort="auto", cohort_min=8)
        job = StreamJob(cfg)
        for pid in range(3):
            job.process_event(REQUEST_STREAM, json.dumps({
                "id": pid, "request": "Create",
                "learner": {"name": "PA", "hyperParameters": {"C": 1.0},
                            "dataStructure": {"nFeatures": DIM}},
                "trainingConfiguration": {"protocol": "Asynchronous"},
            }))
        for net in job.spokes[0].nets.values():
            assert net.pipeline._cohort is None
