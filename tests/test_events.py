"""Flight recorder (ISSUE 14): structured decision-event journal, fleet
incident bundles, watchdog alerts.

Pins:

- spec parsing (unknown knobs raise -> the control gate drops the one
  request; per-pipeline override wins; false opts out);
- EventJournal semantics: monotonic ids, bounded ring, per-pipeline tails,
  counters/high-water, atomic JSONL dumps that never raise;
- merge_timeline: transport stamps order cross-process chains even when
  the processes' wall clocks disagree; same-stamp events order by the
  causal rank (rejection -> retire -> resync -> re-admit); unstamped
  events interleave by wall time; bundle write/read round-trips and
  gather_blackbox skips garbage;
- watchdog rules: each rule's fire/clear hysteresis with an injectable
  clock, alert events recorded + surfaced through on_alert, flapping
  bounded by clearAfter;
- UNARMED = zero recorder objects and bitwise-identical predictions /
  scores / stats vs an armed run, across the composition matrix (cohort x
  codec int8 x guard x serving exact x overload x lifecycle x telemetry);
- journal determinism: the same seeded chaos run records the same event
  stream (wall clock stripped);
- the in-process decision chain: a poisoned worker produces
  delta_rejected -> worker_retired -> guard_trip/rollback ->
  worker_readmitted in causal order, dumps a black box, cross-references
  dead letters, and rides the Query response tail;
- kind="alert" records on the performance sink;
- the supervised bundle: recovery.JobSupervisor gathers worker-death
  rings + its own restart decision into one merged bundle;
- Statistics eventsRecorded/alertsRaised plumbing.
"""

import json
import os

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from omldm_tpu.api.requests import TrainingConfiguration
from omldm_tpu.api.responses import QueryResponse
from omldm_tpu.api.stats import Statistics
from omldm_tpu.config import JobConfig
from omldm_tpu.runtime.events import (
    ALERT,
    ALERT_CLEAR,
    DELTA_REJECTED,
    EventJournal,
    EventsConfig,
    FlightRecorder,
    GUARD_ROLLBACK,
    GUARD_TRIP,
    RESTART,
    Watchdog,
    WORKER_READMITTED,
    WORKER_RETIRED,
    events_config,
    gather_blackbox,
    merge_timeline,
    parse_events_spec,
    validate_events,
    write_bundle,
)
from omldm_tpu.runtime.job import (
    FORECASTING_STREAM,
    REQUEST_STREAM,
    TRAINING_STREAM,
    StreamJob,
)
from omldm_tpu.runtime.responses import ResponseMerger

DIM = 6


def _create_line(nid=0, protocol="Asynchronous", tc_extra=None):
    tc = {"protocol": protocol, "syncEvery": 2}
    tc.update(tc_extra or {})
    return json.dumps({
        "id": nid,
        "request": "Create",
        "learner": {
            "name": "PA",
            "hyperParameters": {"C": 1.0},
            "dataStructure": {"nFeatures": DIM},
        },
        "trainingConfiguration": tc,
    })


def _stream(n, fore_every=5, seed=0):
    rng = np.random.RandomState(seed)
    w = np.random.RandomState(1).randn(DIM)
    events = []
    for i in range(n):
        x = np.round(rng.randn(DIM), 6)
        feats = [float(v) for v in x]
        if i % fore_every == 4:
            events.append(
                (FORECASTING_STREAM,
                 json.dumps({"numericalFeatures": feats}))
            )
        else:
            events.append(
                (TRAINING_STREAM,
                 json.dumps({
                     "numericalFeatures": feats,
                     "target": float(x @ w > 0),
                 }))
            )
    return events


def _run_job(events="", n=200, protocol="Asynchronous", parallelism=1,
             creates=(0,), tc_extra=None, stream=None, **cfg_kw):
    job = StreamJob(JobConfig(
        parallelism=parallelism, batch_size=16, test_set_size=16,
        events=events, **cfg_kw,
    ))
    for nid in creates:
        job.process_event(
            REQUEST_STREAM, _create_line(nid, protocol, tc_extra)
        )
    for s, line in (stream or _stream(n)):
        job.process_event(s, line)
    report = job.terminate()
    return job, report


# --- spec parsing ------------------------------------------------------------


class TestSpecParsing:
    def test_unset_unarmed(self):
        assert parse_events_spec("") is None
        assert parse_events_spec(None) is None
        assert parse_events_spec(False) is None

    def test_on_defaults(self):
        cfg = parse_events_spec("on")
        assert cfg.cap == 4096
        assert cfg.watchdog_every == 10_000
        assert not cfg.any_rule_armed()

    def test_kv_and_table(self):
        cfg = parse_events_spec(
            "cap=128,watchdogEvery=64,shedHigh=2,blackboxPath=/tmp/bb"
        )
        assert (cfg.cap, cfg.watchdog_every, cfg.shed_high) == (128, 64, 2.0)
        assert cfg.blackbox_path == "/tmp/bb"
        assert cfg.any_rule_armed()
        cfg = parse_events_spec({"p99BudgetMs": 250, "clearAfter": 3})
        assert cfg.p99_budget_ms == 250.0 and cfg.clear_after == 3

    def test_bad_specs_raise(self):
        with pytest.raises(ValueError):
            parse_events_spec("nope=1")
        with pytest.raises(ValueError):
            parse_events_spec("cap=0")
        with pytest.raises(ValueError):
            parse_events_spec("collapseFrac=1.5")
        with pytest.raises(ValueError):
            parse_events_spec("cap")
        with pytest.raises(ValueError):
            parse_events_spec(3.14)

    def test_pipeline_override_wins(self):
        tc = TrainingConfiguration.from_dict({"events": {"cap": 7}})
        assert events_config(tc, "cap=99").cap == 7
        tc = TrainingConfiguration.from_dict({"events": False})
        assert events_config(tc, "cap=99") is None
        tc = TrainingConfiguration.from_dict({})
        assert events_config(tc, "cap=99").cap == 99
        assert events_config(tc, "") is None

    def test_validate_events_gate(self):
        tc = TrainingConfiguration.from_dict({"events": {"bogus": 1}})
        assert validate_events(tc) is not None
        tc = TrainingConfiguration.from_dict({"events": True})
        assert validate_events(tc) is None

    def test_bad_table_drops_request_not_job(self):
        job = StreamJob(JobConfig(parallelism=1))
        bad = json.dumps({
            "id": 0, "request": "Create",
            "learner": {"name": "PA", "hyperParameters": {"C": 1.0},
                        "dataStructure": {"nFeatures": DIM}},
            "trainingConfiguration": {"protocol": "Asynchronous",
                                      "events": {"bogus": 1}},
        })
        job.process_event(REQUEST_STREAM, bad)
        assert 0 not in job.pipeline_manager.node_map
        assert job.dead_letter.by_reason.get("rejected_request") == 1

    def test_bad_job_spec_fails_fast(self):
        with pytest.raises(ValueError):
            StreamJob(JobConfig(parallelism=1, events="bogus=1"))

    def test_cli_flag_separation(self):
        # the bare --events CLI flag is the combined replay FILE
        # (__main__.py): it must NOT reach the flight-recorder spec; the
        # spec rides --flightRecorder instead
        cfg = JobConfig.from_args({"events": "/tmp/replay.jsonl"})
        assert cfg.events == ""
        cfg = JobConfig.from_args({
            "events": "/tmp/replay.jsonl", "flightRecorder": "cap=64",
        })
        assert cfg.events == "cap=64"
        cfg = JobConfig.from_args({"blackboxPath": "/tmp/bb"})
        assert cfg.blackbox_path == "/tmp/bb"


# --- journal -----------------------------------------------------------------


class TestJournal:
    def test_ids_counts_high_water(self):
        j = EventJournal(cap=100, pid=3, clock=lambda: 1.0,
                         position=lambda: 42)
        e1 = j.record(GUARD_TRIP, "non_finite", pipeline=0, worker=1)
        e2 = j.record(ALERT, "shed_rate", delta=5)
        assert (e1["id"], e2["id"]) == (1, 2)
        assert e1["clock"] == 42 and e1["pid"] == 3 and e1["wall"] == 1.0
        assert j.total == 2 and j.alerts == 1 and j.high_water == 2
        assert j.by_kind == {GUARD_TRIP: 1, ALERT: 1}

    def test_ring_bounded_ids_keep_growing(self):
        j = EventJournal(cap=4)
        for i in range(10):
            j.record("k", f"c{i}")
        assert len(j.events) == 4
        assert [e["id"] for e in j.events] == [7, 8, 9, 10]
        assert j.total == 10

    def test_tail_for_pipeline(self):
        j = EventJournal(cap=100, tail_len=2)
        j.record("k", "a", pipeline=0)
        j.record("k", "b", pipeline=1)
        j.record("k", "c", pipeline=0)
        j.record("k", "d", pipeline=0)
        tail = j.tail_for(0)
        assert [e["cause"] for e in tail] == ["c", "d"]
        assert j.tail_for(7) == []

    def test_stamp_field(self):
        j = EventJournal()
        e = j.record(DELTA_REJECTED, "non_finite", stamp=(2, 9))
        assert e["stamp"] == [2, 9]
        e = j.record(DELTA_REJECTED, "non_finite", stamp=None)
        assert "stamp" not in e
        e = j.record(DELTA_REJECTED, "non_finite", stamp=(2, None))
        assert "stamp" not in e

    def test_dump_roundtrip(self, tmp_path):
        j = EventJournal(cap=10, pid=7, path=str(tmp_path))
        j.record("k", "a")
        j.record("k", "b", pipeline=1)
        assert j.dirty
        path = j.dump()
        assert path == str(tmp_path / "blackbox-proc7.jsonl")
        assert not j.dirty
        lines = [json.loads(l) for l in open(path).read().splitlines()]
        assert [e["cause"] for e in lines] == ["a", "b"]

    def test_dump_never_raises(self):
        j = EventJournal(path="/proc/definitely/not/writable")
        j.record("k", "a")
        assert j.dump() is None  # degraded, no exception

    def test_incident_records_and_dumps(self, tmp_path):
        j = EventJournal(path=str(tmp_path))
        j.record("k", "a")
        path = j.incident("guard_trip", pipeline=0)
        assert path is not None
        lines = [json.loads(l) for l in open(path).read().splitlines()]
        assert lines[-1]["kind"] == "incident_dump"
        assert lines[-1]["cause"] == "guard_trip"


# --- bundle merge ordering ---------------------------------------------------


class TestMergeTimeline:
    def test_stamps_beat_reordered_receives(self):
        # a chaos reorder made the hub PROCESS seq 7 before seq 5 (the
        # journal's local order is processing order): the bundle must
        # read the stream in SEND order — one sender stream, one ring
        ring = [
            {"id": 1, "kind": "delta_rejected", "cause": "non_finite",
             "wall": 10.0, "pid": 0, "worker": 1, "stamp": [0, 7],
             "clock": 0},
            {"id": 2, "kind": "delta_rejected", "cause": "non_finite",
             "wall": 10.1, "pid": 0, "worker": 1, "stamp": [0, 5],
             "clock": 0},
            {"id": 3, "kind": "worker_retired", "cause": "guard_strikes",
             "wall": 10.2, "pid": 0, "worker": 1, "stamp": [0, 7],
             "clock": 0},
        ]
        merged = merge_timeline([ring])
        seqs = [e["stamp"][1] for e in merged]
        assert seqs == [5, 7, 7]

    def test_independent_seq_streams_never_cross_sorted(self):
        # (a) different WORKERS' up-streams count seqs independently: a
        # rescaled-in worker's seq 3 must not jump ahead of a veteran's
        # seq 400; (b) different RINGS (a restarted incarnation counting
        # from 0 again) are never cross-compared either
        ring = [
            {"id": 1, "kind": "delta_rejected", "cause": "non_finite",
             "wall": 1.0, "pid": 0, "worker": 0, "stamp": [0, 400],
             "clock": 0},
            {"id": 2, "kind": "delta_rejected", "cause": "non_finite",
             "wall": 2.0, "pid": 0, "worker": 5, "stamp": [0, 3],
             "clock": 0},
        ]
        merged = merge_timeline([ring])
        assert [e["worker"] for e in merged] == [0, 5]  # wall order kept
        later_ring = [
            {"id": 1, "kind": "delta_rejected", "cause": "non_finite",
             "wall": 50.0, "pid": 0, "worker": 0, "stamp": [0, 2],
             "clock": 0},
        ]
        merged = merge_timeline([ring, later_ring])
        # incarnation 2's seq 2 stays AFTER incarnation 1's seq 400
        assert [e["stamp"][1] for e in merged] == [400, 3, 2]

    def test_same_stamp_orders_by_causal_rank(self):
        # deliberately reversed wall times within one stamp
        events = [
            {"id": 1, "kind": "worker_readmitted", "cause": "healthy_push",
             "wall": 1.0, "pid": 0, "stamp": [0, 4], "clock": 0},
            {"id": 2, "kind": "delta_rejected", "cause": "non_finite",
             "wall": 2.0, "pid": 0, "stamp": [0, 4], "clock": 0},
            {"id": 3, "kind": "resync", "cause": "authoritative_reship",
             "wall": 3.0, "pid": 0, "stamp": [0, 4], "clock": 0},
        ]
        merged = merge_timeline([events])
        assert [e["kind"] for e in merged] == [
            "delta_rejected", "resync", "worker_readmitted",
        ]

    def test_unstamped_interleave_by_wall(self):
        a = [{"id": 1, "kind": "restart", "cause": "x", "wall": 5.0,
              "pid": "sup", "clock": 0}]
        b = [{"id": 1, "kind": "guard_trip", "cause": "y", "wall": 1.0,
              "pid": 0, "clock": 0},
             {"id": 2, "kind": "terminate", "cause": "z", "wall": 9.0,
              "pid": 0, "clock": 0}]
        merged = merge_timeline([a, b])
        assert [e["kind"] for e in merged] == [
            "guard_trip", "restart", "terminate",
        ]

    def test_rescale_epoch_separates_streams(self):
        # a LIVE rescale restarts the per-net sequence counters while the
        # journal ring persists: the epoch bump keeps post-rescale seqs
        # out of pre-rescale stream groups (seq 1 after the rescale must
        # NOT jump ahead of pre-rescale seq 40)
        j = EventJournal()
        j.record(DELTA_REJECTED, "x", pipeline=0, worker=1,
                 stamp=(0, 40), hub=0)
        j.bump_epoch()
        e = j.record(DELTA_REJECTED, "x", pipeline=0, worker=1,
                     stamp=(0, 1), hub=0)
        assert e["epoch"] == 1
        merged = merge_timeline([j.tail()])
        assert [ev["stamp"][1] for ev in merged] == [40, 1]

    def test_garbled_stamp_degrades_to_unstamped(self, tmp_path):
        events = [
            {"id": 1, "kind": "delta_rejected", "cause": "x", "wall": 1.0,
             "pid": 0, "clock": 0, "stamp": "garbled"},
            {"id": 2, "kind": "terminate", "cause": "y", "wall": 2.0,
             "pid": 0, "clock": 0},
        ]
        merged = merge_timeline([events])
        assert [e["id"] for e in merged] == [1, 2]
        assert write_bundle(
            str(tmp_path / "b.json"), [events]
        ) is not None

    def test_bundle_write_read_and_gather(self, tmp_path):
        j0 = EventJournal(pid=0, path=str(tmp_path))
        j0.record("guard_trip", "norm_exploded", pipeline=0)
        j0.dump()
        j1 = EventJournal(pid=1, path=str(tmp_path))
        j1.record("rescale", "agreed")
        j1.dump()
        # garbage must be skipped, not fatal
        (tmp_path / "blackbox-procX.jsonl").write_text("{torn json\n")
        streams = gather_blackbox(str(tmp_path))
        assert len(streams) == 2
        path = write_bundle(
            str(tmp_path / "incident-0.json"), streams,
            meta={"reason": "test"},
        )
        bundle = json.load(open(path))
        assert bundle["meta"]["reason"] == "test"
        assert len(bundle["timeline"]) == 2
        assert bundle["byKind"] == {"guard_trip": 1, "rescale": 1}
        assert {p["pid"] for p in bundle["processes"]} == {0, 1}


# --- watchdog rules ----------------------------------------------------------


def _watchdog(clock, on_alert=None, **knobs):
    knobs.setdefault("watchdog_every", 10)
    cfg = EventsConfig(**knobs)
    j = EventJournal(clock=clock)
    return Watchdog(cfg, j, on_alert=on_alert, clock=clock), j


class TestWatchdog:
    def test_count_clock(self):
        wd, _ = _watchdog(lambda: 0.0, shed_high=1)
        assert not wd.note_records(4)
        assert not wd.note_records(5)
        assert wd.note_records(1)
        wd.evaluate({"shed": 0})
        assert not wd.note_records(9)

    def test_shed_rate_fire_and_clear(self):
        fired = []
        wd, j = _watchdog(
            lambda: 0.0, on_alert=fired.append, shed_high=5, clear_after=2
        )
        wd.evaluate({"shed": 0}, now=0.0)       # baseline
        wd.evaluate({"shed": 10}, now=1.0)      # delta 10 >= 5: FIRE
        assert len(fired) == 1
        assert fired[0]["kind"] == ALERT and fired[0]["cause"] == "shed_rate"
        wd.evaluate({"shed": 20}, now=2.0)      # still breaching: no refire
        assert len(fired) == 1
        wd.evaluate({"shed": 20}, now=3.0)      # healthy 1
        wd.evaluate({"shed": 20}, now=4.0)      # healthy 2: CLEAR
        assert j.by_kind.get(ALERT_CLEAR) == 1
        wd.evaluate({"shed": 40}, now=5.0)      # breach again: re-FIRE
        assert len(fired) == 2 and j.alerts == 2

    def test_p99_budget(self):
        wd, j = _watchdog(lambda: 0.0, p99_budget_ms=100)
        wd.evaluate({"serve_p99_ms": 50}, now=0.0)
        assert j.alerts == 0
        wd.evaluate({"serve_p99_ms": 150}, now=1.0)
        assert j.alerts == 1
        [alert] = [e for e in j.events if e["kind"] == ALERT]
        assert alert["p99Ms"] == 150.0 and alert["budgetMs"] == 100.0

    def test_throughput_collapse(self):
        wd, j = _watchdog(
            lambda: 0.0, collapse_frac=0.5, collapse_windows=2
        )
        # steady 100 rec/s for 3 windows (builds trailing history)
        for t, r in [(1.0, 100), (2.0, 200), (3.0, 300)]:
            wd.evaluate({"records": r}, now=t)
        assert j.alerts == 0
        # collapse to 10 rec/s: < 0.5 * trailing(100)
        wd.evaluate({"records": 310}, now=4.0)
        assert j.alerts == 1

    def test_curve_regression(self):
        wd, j = _watchdog(lambda: 0.0, curve_slope=0.5)
        wd.evaluate({"loss": 1.0}, now=0.0)
        wd.evaluate({"loss": 1.2}, now=1.0)   # +0.2 over floor: healthy
        assert j.alerts == 0
        wd.evaluate({"loss": 1.8}, now=2.0)   # +0.8 over floor 1.0: FIRE
        assert j.alerts == 1

    def test_silence_poll(self):
        wd, j = _watchdog(lambda: 0.0, silence_ms=1000)
        assert wd.poll_silence(10.0, now=10.5) == []
        fired = wd.poll_silence(10.0, now=11.5)
        assert len(fired) == 1 and j.alerts == 1
        assert fired[0]["cause"] == "heartbeat_silence"
        # activity resumes: clears after clear_after healthy polls
        wd.poll_silence(11.4, now=11.6)
        wd.poll_silence(11.5, now=11.7)
        assert j.by_kind.get(ALERT_CLEAR) == 1

    def test_broken_on_alert_never_raises(self):
        def boom(_e):
            raise RuntimeError("sink died")

        wd, j = _watchdog(lambda: 0.0, on_alert=boom, p99_budget_ms=1)
        wd.evaluate({"serve_p99_ms": 5}, now=0.0)
        assert j.alerts == 1

    def test_recorder_arms_watchdog_only_with_rules(self):
        rec = FlightRecorder(parse_events_spec("on"))
        assert rec.watchdog is None
        rec = FlightRecorder(parse_events_spec("shedHigh=1"))
        assert rec.watchdog is not None
        rec = FlightRecorder(parse_events_spec("shedHigh=1,watchdogEvery=0"))
        assert rec.watchdog is None


# --- unarmed identity --------------------------------------------------------


class TestUnarmedIdentity:
    def test_unarmed_no_objects(self):
        job, _ = _run_job(events="", n=60)
        assert job.events is None
        for spoke in job.spokes:
            assert spoke.events is None
        for hub in job.hub_manager.hubs.values():
            assert hub.node.events is None
        assert job.dead_letter.event_ring is None

    # the composition matrix of the acceptance bar: cohort x codec int8 x
    # guard x serving exact x overload x lifecycle x telemetry — armed
    # must be bitwise identical to unarmed everywhere the recorder only
    # OBSERVES (serving maxDelayMs pinned far out: wall-clock deadlines
    # are load-dependent on both legs, the telemetry suite's note)
    @pytest.mark.parametrize("compose,tc_extra", [
        ({}, None),
        ({"cohort": "on", "cohort_min": 2,
          "serving": "maxBatch=8,maxDelayMs=1000000"}, None),
        ({"cohort": "on", "cohort_min": 2,
          "serving": "maxBatch=8,maxDelayMs=1000000",
          "overload": "window=64", "lifecycle": "on",
          "telemetry": "statsEvery=64"},
         {"comm": {"codec": "int8"}, "guard": True}),
    ])
    def test_armed_bitwise_identical(self, compose, tc_extra):
        creates = (0, 1) if compose else (0,)
        base_job, base = _run_job(
            events="", n=240, protocol="Synchronous", parallelism=2,
            creates=creates, tc_extra=tc_extra, **compose,
        )
        ev_job, ev = _run_job(
            events="watchdogEvery=64,shedHigh=10000", n=240,
            protocol="Synchronous", parallelism=2, creates=creates,
            tc_extra=tc_extra, **compose,
        )
        assert ev_job.events is not None
        assert [p.value for p in base_job.predictions] == [
            p.value for p in ev_job.predictions
        ]
        assert [p.mlp_id for p in base_job.predictions] == [
            p.mlp_id for p in ev_job.predictions
        ]
        for sb, se in zip(base.statistics, ev.statistics):
            assert sb.score == se.score
            assert sb.fitted == se.fitted
            assert sb.models_shipped == se.models_shipped
            assert sb.bytes_on_wire == se.bytes_on_wire
            assert sb.events_recorded == 0
            assert se.events_recorded >= 1  # at least the terminate event

    def test_pipeline_false_opts_out_under_job_default(self):
        # job-wide plane armed; pipeline 1 explicitly opts out: its
        # decision sites never record, its hub shards carry no journal,
        # and its Query responses carry no event tail — while pipeline 0
        # keeps recording (the telemetry span-opt-out rule)
        job = StreamJob(JobConfig(
            parallelism=1, batch_size=16, test_set_size=16, events="on",
        ))
        job.process_event(REQUEST_STREAM, _create_line(
            0, "Asynchronous", {"guard": True}
        ))
        job.process_event(REQUEST_STREAM, _create_line(
            1, "Asynchronous", {"guard": True, "events": False}
        ))
        assert job.spokes[0].nets[0].events_cfg is not None
        assert job.spokes[0].nets[1].events_cfg is None
        for (nid, _h), hub in job.hub_manager.hubs.items():
            if nid == 0:
                assert hub.node.events is job.events.journal
            else:
                assert hub.node.events is None
        for s, line in _stream(40):
            job.process_event(s, line)
        job.process_event(REQUEST_STREAM, json.dumps(
            {"id": 0, "request": "Query", "requestId": 3}
        ))
        job.process_event(REQUEST_STREAM, json.dumps(
            {"id": 1, "request": "Query", "requestId": 4}
        ))
        [r0] = [r for r in job.responses if r.response_id == 3]
        [r1] = [r for r in job.responses if r.response_id == 4]
        assert r0.events is not None
        assert r1.events is None
        job.terminate()
        assert not any(
            e.get("pipeline") == 1 for e in job.events.journal.tail()
        )

    def test_lazy_arming_by_pipeline_table(self):
        job = StreamJob(JobConfig(parallelism=1))
        assert job.events is None
        job.process_event(REQUEST_STREAM, _create_line(
            0, tc_extra={"events": {"cap": 64}}
        ))
        assert job.events is not None
        assert job.events.cfg.cap == 64
        assert job.spokes[0].events is job.events.journal
        for hub in job.hub_manager.hubs.values():
            assert hub.node.events is job.events.journal


# --- chaos-replay determinism ------------------------------------------------


def _strip_wall(events):
    return [{k: v for k, v in e.items() if k != "wall"} for e in events]


class TestDeterminism:
    def test_same_seed_same_event_stream(self):
        def run():
            return _run_job(
                events="on", n=400, protocol="Asynchronous", parallelism=2,
                tc_extra={"guard": True, "syncEvery": 1},
                chaos="seed=7,drop=0.2,dup=0.2,reorder=0.2,window=2,"
                      "up.nan=0.3",
            )[0]

        j1, j2 = run(), run()
        e1 = _strip_wall(j1.events.journal.tail())
        e2 = _strip_wall(j2.events.journal.tail())
        assert e1 == e2
        assert j1.events.journal.total == j2.events.journal.total
        # the chaos actually produced decision events (non-vacuous)
        assert j1.events.journal.total > 1


# --- the in-process decision chain -------------------------------------------


def _run_poisoned(tmp_path=None, events="on", parallelism=2, n=400,
                  poison_at=200):
    cfg = dict(parallelism=parallelism, batch_size=16, test_set_size=16,
               events=events)
    if tmp_path is not None:
        cfg["blackbox_path"] = str(tmp_path)
    job = StreamJob(JobConfig(**cfg))
    job.process_event(REQUEST_STREAM, _create_line(0, "Asynchronous", {
        "guard": {"maxStrikes": 1},
        "comm": {"reliable": True},
        # push on EVERY flush: the first poisoned fit ships before the
        # record-end guard tick rolls the worker back, so the hub-side
        # rejection chain and the worker-side trip chain both record
        "syncEvery": 1,
    }))
    for i, (s, line) in enumerate(_stream(n)):
        if i == poison_at:
            net = job.spokes[1].nets[0]
            flat, _ = net.pipeline.get_flat_params()
            net.pipeline.set_flat_params(np.full_like(flat, 1.0e9))
        job.process_event(s, line)
    report = job.terminate()
    return job, report


class TestDecisionChain:
    def test_rejection_retire_rollback_readmit_in_order(self, tmp_path):
        job, report = _run_poisoned(tmp_path)
        events = job.events.journal.tail()
        kinds = [e["kind"] for e in events]
        for kind in (DELTA_REJECTED, WORKER_RETIRED, GUARD_TRIP,
                     GUARD_ROLLBACK, WORKER_READMITTED):
            assert kind in kinds, f"missing {kind} in {kinds}"
        # causal order within the journal
        assert kinds.index(DELTA_REJECTED) < kinds.index(WORKER_RETIRED)
        assert kinds.index(WORKER_RETIRED) < kinds.index(WORKER_READMITTED)
        assert kinds.index(GUARD_TRIP) < kinds.index(GUARD_ROLLBACK)
        # the rejection is stamped with the transport (networkId, seq)
        rej = next(e for e in events if e["kind"] == DELTA_REJECTED)
        assert rej["stamp"][0] == 0 and rej["strikes"] == 1
        assert rej["worker"] == 1
        # statistics mirror
        [stats] = report.statistics
        assert stats.deltas_rejected >= 1
        assert stats.events_recorded == job.events.journal.total
        # black-box dumps: the guard trip dumped mid-stream, terminate
        # re-dumped
        dump = tmp_path / "blackbox-proc0.jsonl"
        assert dump.exists()
        lines = [json.loads(l) for l in open(dump).read().splitlines()]
        assert lines[-1]["kind"] == "terminate"

    def test_guard_trip_without_blackbox_stays_in_memory(self):
        job, _ = _run_poisoned(tmp_path=None)
        assert job.events.journal.dumps_written == 0
        assert job.events.journal.by_kind.get("incident_dump", 0) >= 1

    def test_query_response_carries_event_tail(self):
        job, _ = _run_poisoned()
        merged = [r for r in job.responses
                  if r.response_id != -1] or job.responses
        # drive an explicit Query after the fact is impossible
        # post-terminate; instead pin the termination fragments' merge:
        # the merger kept a non-null tail
        frags = []
        merger = ResponseMerger(frags.append)
        merger.expect(9, 1)
        merger.add_fragment(QueryResponse(
            response_id=9, mlp_id=0,
            events=job.events.journal.tail_for(0),
        ))
        [out] = frags
        assert out.events, "tail missing from merged response"
        assert all(e.get("pipeline") == 0 for e in out.events)
        assert "events" in out.to_dict()

    def test_live_query_rides_tail(self):
        job, _ = _run_poisoned(n=260, poison_at=120)
        # fresh job still live: issue a Query before terminate
        job2 = StreamJob(JobConfig(
            parallelism=1, batch_size=16, test_set_size=16, events="on",
        ))
        job2.process_event(REQUEST_STREAM, _create_line(
            0, "Asynchronous", {"guard": {"maxStrikes": 1}}
        ))
        for s, line in _stream(60):
            job2.process_event(s, line)
        job2.process_event(REQUEST_STREAM, json.dumps(
            {"id": 0, "request": "Query", "requestId": 5}
        ))
        [resp] = [r for r in job2.responses if r.response_id == 5]
        # no decision events tagged pipeline 0 yet -> empty-or-populated
        # list, but the field exists (not None) because the plane is armed
        assert resp.events is not None

    def test_dead_letter_cross_references_high_water(self):
        job = StreamJob(JobConfig(parallelism=1, events="on"))
        job.process_event(REQUEST_STREAM, _create_line(0))
        job.events.journal.record("k", "marker")
        hw = job.events.journal.high_water
        job.process_event(TRAINING_STREAM, "{torn")
        entry = job.dead_letter.entries[-1]
        assert entry["eventId"] == hw
        job.terminate()

    def test_unarmed_dead_letter_shape_unchanged(self):
        job = StreamJob(JobConfig(parallelism=1))
        job.process_event(REQUEST_STREAM, _create_line(0))
        job.process_event(TRAINING_STREAM, "{torn")
        assert "eventId" not in job.dead_letter.entries[-1]
        job.terminate()


# --- alerts on the performance sink ------------------------------------------


class TestAlertRecords:
    def test_alert_rides_sink_as_kind_alert(self):
        perf = []
        job = StreamJob(
            JobConfig(
                parallelism=2, batch_size=16, test_set_size=16,
                events="watchdogEvery=64,shedHigh=1",
            ),
            on_performance=perf.append,
        )
        job.process_event(REQUEST_STREAM, _create_line(0, "Asynchronous", {
            "guard": {"maxStrikes": 1}, "comm": {"reliable": True},
            "syncEvery": 1,
        }))
        for i, (s, line) in enumerate(_stream(400)):
            if i == 100:
                net = job.spokes[1].nets[0]
                flat, _ = net.pipeline.get_flat_params()
                net.pipeline.set_flat_params(np.full_like(flat, 1.0e9))
            job.process_event(s, line)
        report = job.terminate()
        alerts = [p for p in perf if p.kind == "alert"]
        assert alerts, "no kind=alert record reached the sink"
        payload = alerts[0].to_dict()
        assert payload["kind"] == "alert"
        assert payload["alert"]["cause"] == "shed_rate"
        assert payload["statistics"] == []
        # the final report stays the terminate-time fold (kind None)
        assert report.kind is None
        [stats] = report.statistics
        assert stats.alerts_raised >= 1


# --- supervised bundles ------------------------------------------------------


class TestSupervisedBundle:
    def test_worker_death_bundle(self, tmp_path):
        from omldm_tpu.runtime.recovery import (
            FaultInjector,
            JobSupervisor,
            replayable,
        )

        events = _stream(300)
        job = StreamJob(JobConfig(
            parallelism=2, batch_size=16, test_set_size=16,
            events="on", blackbox_path=str(tmp_path),
        ))
        job.process_event(REQUEST_STREAM, _create_line(0))
        injector = FaultInjector()
        injector.arm(job, worker_id=0, after_records=80)
        sup = JobSupervisor(
            job,
            replayable(lambda: list(events)),
            max_restarts=1,
        )
        report = sup.run()
        assert report is not None
        assert injector.fired == 1
        assert len(sup.failures) == 1
        # supervisor decision log recorded the restart
        assert sup.journal.by_kind.get(RESTART) == 1
        # one merged bundle: the dead incarnation's ring + the finishing
        # job's ring + the supervisor log
        assert sup.bundle_path is not None
        bundle = json.load(open(sup.bundle_path))
        kinds = [e["kind"] for e in bundle["timeline"]]
        assert "incident_dump" in kinds     # worker-death ring dump
        assert RESTART in kinds             # the restart decision
        assert "terminate" in kinds         # the finishing incarnation
        pids = {str(e["pid"]) for e in bundle["timeline"]}
        assert "sup" in pids
        # the dead incarnation's black box is on disk too
        assert (tmp_path / "blackbox-proc0.jsonl").exists()

    def test_unarmed_supervisor_zero_objects(self):
        from omldm_tpu.runtime.recovery import JobSupervisor, replayable

        job = StreamJob(JobConfig(parallelism=1, batch_size=16,
                                  test_set_size=16))
        job.process_event(REQUEST_STREAM, _create_line(0))
        sup = JobSupervisor(job, replayable(lambda: _stream(40)))
        sup.run()
        assert sup.journal is None and sup.bundle_path is None

    def test_distributed_supervisor_gather(self, tmp_path):
        # unit-level: the DistributedJobSupervisor's gather merges worker
        # dumps + its own decision log into incident-<n>.json
        from omldm_tpu.runtime.supervisor import DistributedJobSupervisor

        # a STALE dump from an earlier run predates the supervisor's
        # freshness floor and must be excluded from its bundles
        stale = EventJournal(pid=9, path=str(tmp_path))
        stale.record("guard_trip", "old_run")
        stale_path = stale.dump()
        os.utime(stale_path, (1.0, 1.0))
        sup = DistributedJobSupervisor(
            ["--checkpointDir", str(tmp_path / "ck")], 1,
            run_dir=str(tmp_path / "run"), blackbox_dir=str(tmp_path),
        )
        j = EventJournal(pid=0, path=str(tmp_path))
        j.record("rescale", "agreed", from_procs=1, to_procs=2)
        j.dump()
        sup.journal.record(RESTART, "fleet_failure", error="exit 1")
        path = sup.gather_incident("worker_death")
        assert path == str(tmp_path / "incident-0.json")
        bundle = json.load(open(path))
        assert bundle["meta"]["reason"] == "worker_death"
        kinds = {e["kind"] for e in bundle["timeline"]}
        assert kinds == {"rescale", RESTART}
        # a second gather writes a NEW bundle (history preserved)
        assert sup.gather_incident("x") == str(tmp_path / "incident-1.json")


# --- checkpoint composition --------------------------------------------------


class TestCheckpointComposition:
    def test_snapshot_excludes_journal_and_restores_rewired(self, tmp_path):
        # the journal holds clock closures: a snapshot must never try to
        # pickle it (_NODE_SKIP), and the restored job re-arms + rewires
        # a FRESH journal through the normal construction path
        job = StreamJob(JobConfig(
            parallelism=2, batch_size=16, test_set_size=16, events="on",
            checkpointing=True, checkpoint_dir=str(tmp_path),
            check_interval_ms=0,
        ))
        job.process_event(REQUEST_STREAM, _create_line(
            0, "Asynchronous", {"guard": True}
        ))
        for s, line in _stream(80):
            job.process_event(s, line)
        job.events.journal.record("k", "marker", pipeline=0)
        path = job.checkpoint_manager.save(job)
        restored = job.checkpoint_manager.restore(path=path)
        assert restored.events is not None
        assert all(
            sp.events is restored.events.journal for sp in restored.spokes
        )
        assert all(
            h.node.events is restored.events.journal
            for h in restored.hub_manager.hubs.values()
        )
        # fresh incarnation, fresh ring (the old ring lives in the old
        # process's black box, not in the model snapshot)
        assert restored.events.journal.total == 0


# --- statistics plumbing -----------------------------------------------------


class TestStatsPlumbing:
    def test_update_merge_to_dict(self):
        a = Statistics(pipeline=0)
        a.update_stats(events_recorded=10, alerts_raised=2)
        a.update_stats(events_recorded=12, alerts_raised=2)
        assert a.events_recorded == 12  # job-level mirror: max, not sum
        b = Statistics(pipeline=0)
        b.update_stats(events_recorded=5, alerts_raised=7)
        m = a.merge(b)
        assert m.events_recorded == 12 and m.alerts_raised == 7
        d = m.to_dict()
        assert d["eventsRecorded"] == 12 and d["alertsRaised"] == 7

    def test_unarmed_report_zero(self):
        _, report = _run_job(events="", n=60)
        [stats] = report.statistics
        assert stats.events_recorded == 0 and stats.alerts_raised == 0
