"""Seeded storm generator (runtime/loadgen.py).

Pins, per ISSUE 19:

- same seed => same byte stream (fingerprint), different seed differs;
- churn waves land on the chunk grid with Update/Delete-before-Create
  ordering and non-colliding tenant ids;
- the exact accounting (``expected_forecasts``) matches what a real
  in-process run actually produces, fan-out and routed, with and
  without Update-discard semantics;
- fault specs render onto the existing injector flags verbatim;
- fskafka preloading writes replayable topic logs the file-backed
  consumer reads back byte-identically (offsets = line numbers).
"""

import json
import os

import pytest

jax = pytest.importorskip("jax")

from omldm_tpu.runtime.loadgen import (
    CREATE,
    DELETE,
    UPDATE,
    ChurnEvent,
    FaultSpec,
    LoadStorm,
    StormSpec,
)

DIM = 4


def _spec(**kw):
    base = dict(
        seed=11, tenants=6, records=256, chunk_rows=32, n_features=DIM,
        forecast_ratio=0.4, churn_waves=2, churn_tenants_per_wave=2,
        churn_updates_per_wave=1,
    )
    base.update(kw)
    return StormSpec(**base)


# --- spec validation ---------------------------------------------------------


class TestSpecValidation:
    @pytest.mark.parametrize("bad", [
        dict(tenants=0), dict(records=0), dict(chunk_rows=0),
        dict(forecast_ratio=1.5), dict(forecast_ratio=-0.1),
        dict(hot_tenants=99),
    ])
    def test_invalid_specs_raise(self, bad):
        with pytest.raises(ValueError):
            _spec(**bad)

    def test_unknown_fault_kind_raises(self):
        with pytest.raises(ValueError):
            FaultSpec(kind="meteor")


# --- determinism -------------------------------------------------------------


class TestDeterminism:
    def test_same_seed_same_bytes(self):
        a, b = LoadStorm(_spec()), LoadStorm(_spec())
        assert a.fingerprint() == b.fingerprint()
        assert list(a.data_lines()) == list(b.data_lines())
        assert a.request_lines() == b.request_lines()
        assert a.schedule_lines() == b.schedule_lines()

    def test_different_seed_differs(self):
        assert (
            LoadStorm(_spec()).fingerprint()
            != LoadStorm(_spec(seed=12)).fingerprint()
        )

    def test_every_knob_reaches_the_stream(self):
        base = LoadStorm(_spec()).fingerprint()
        for kw in (
            dict(records=224), dict(forecast_ratio=0.6),
            dict(diurnal_amplitude=0.5, diurnal_period=64),
            dict(hot_tenants=2, burst_every=64, burst_len=8),
            dict(addressed_fraction=0.5), dict(churn_waves=3),
        ):
            assert LoadStorm(_spec(**kw)).fingerprint() != base, kw


# --- churn schedule ----------------------------------------------------------


class TestChurn:
    def test_waves_are_chunk_aligned_and_ordered(self):
        storm = LoadStorm(_spec())
        s = storm.spec
        assert storm.churn
        for e in storm.churn:
            assert e.at % s.chunk_rows == 0
            assert 0 < e.at <= s.records

    def test_churn_ids_never_collide_with_core(self):
        storm = LoadStorm(_spec())
        created = [e.tenant for e in storm.churn if e.action == CREATE]
        assert min(created) >= storm.spec.tenants
        assert len(created) == len(set(created))

    def test_update_delete_target_previous_wave(self):
        storm = LoadStorm(_spec(churn_waves=2, churn_tenants_per_wave=3,
                                churn_updates_per_wave=1))
        wave1 = [e for e in storm.churn if e.action == CREATE][:3]
        managed = [e for e in storm.churn
                   if e.action in (UPDATE, DELETE)]
        assert {e.tenant for e in managed} == {e.tenant for e in wave1}
        assert sum(e.action == UPDATE for e in managed) == 1
        assert sum(e.action == DELETE for e in managed) == 2

    def test_healthy_core_untouched(self):
        storm = LoadStorm(_spec())
        healthy = storm.healthy_tenants()
        assert healthy == list(range(storm.spec.tenants))
        churned = {e.tenant for e in storm.churn}
        assert not churned & set(healthy)

    def test_schedule_lines_sorted_and_parseable(self):
        storm = LoadStorm(_spec())
        ats = []
        for line in storm.schedule_lines():
            obj = json.loads(line)
            ats.append(obj["atRecord"])
            assert obj["request"]["request"] in (CREATE, UPDATE, DELETE)
        assert ats == sorted(ats)


# --- traffic shaping ---------------------------------------------------------


class TestTraffic:
    def test_bursts_address_hot_tenants_round_robin(self):
        storm = LoadStorm(_spec(
            churn_waves=0, churn_tenants_per_wave=0, hot_tenants=2,
            burst_every=64, burst_len=8, addressed_fraction=0.0,
        ))
        lines = list(storm.data_lines())
        for b in range(1, storm.spec.records // 64):
            want = (b - 1) % 2
            for i in range(b * 64, b * 64 + 8):
                obj = json.loads(lines[i])
                assert obj["metadata"]["tenant"] == want

    def test_addressed_traffic_targets_alive_tenants_only(self):
        storm = LoadStorm(_spec(addressed_fraction=0.6))
        windows = storm.windows()
        for i, line in enumerate(storm.data_lines()):
            obj = json.loads(line)
            t = (obj.get("metadata") or {}).get("tenant")
            if t is None:
                continue
            assert any(a <= i < b for a, b, _ in windows[t]), (i, t)

    def test_diurnal_curve_modulates_forecast_share(self):
        storm = LoadStorm(_spec(
            records=512, forecast_ratio=0.5, diurnal_amplitude=0.9,
            diurnal_period=512, churn_waves=0, churn_tenants_per_wave=0,
        ))
        ops = [json.loads(l)["operation"] for l in storm.data_lines()]
        peak = sum(op == "forecasting" for op in ops[:256])
        trough = sum(op == "forecasting" for op in ops[256:])
        assert peak > trough


# --- exact accounting vs a real run -----------------------------------------


def _drive(storm, **cfg_kw):
    from omldm_tpu.config import JobConfig
    from omldm_tpu.runtime.job import StreamJob

    job = StreamJob(JobConfig(batch_size=16, test_set_size=16, **cfg_kw))
    for line in storm.request_lines():
        job.process_event("requests", line)
    for stream, line in storm.events():
        job.process_event(stream, line)
    job.terminate()
    counts = {}
    for p in job.predictions:
        counts[p.mlp_id] = counts.get(p.mlp_id, 0) + 1
    return counts


class TestExactAccounting:
    def test_fanout_accounting_matches_real_run(self):
        storm = LoadStorm(_spec(tenants=3, records=128))
        counts = _drive(storm)
        # in-process emits live: outputs of an Update-closed window survive
        assert counts == storm.expected_forecasts(
            routed=False, update_discards=False
        )

    def test_routed_accounting_matches_real_run(self):
        storm = LoadStorm(_spec(
            tenants=3, records=128, addressed_fraction=0.5,
            hot_tenants=2, burst_every=32, burst_len=4,
        ))
        # overload armed => tenant-addressed records route to their
        # addressee only; thresholds high enough that nothing sheds
        counts = _drive(
            storm, overload="window=64,share=64,hotHigh=1e8,hotCritical=1e9"
        )
        assert counts == storm.expected_forecasts(
            routed=True, update_discards=False
        )

    def test_update_discard_accounting(self):
        storm = LoadStorm(_spec())
        keep = storm.expected_forecasts(update_discards=False)
        drop = storm.expected_forecasts(update_discards=True)
        updated = {e.tenant for e in storm.churn if e.action == UPDATE}
        assert updated
        for t in updated:
            assert drop[t] < keep[t]
        for t in storm.healthy_tenants():
            assert drop[t] == keep[t]

    def test_windows_partition_the_stream(self):
        storm = LoadStorm(_spec())
        for t, wins in storm.windows().items():
            spans = sorted(wins)
            for (a, b, _), (c, d, _) in zip(spans, spans[1:]):
                assert b <= c
            assert all(a < b or a == b for a, b, _ in spans)


# --- fleet rendering ---------------------------------------------------------


class TestFleetRendering:
    def test_fault_flags_render_injector_argv(self, tmp_path):
        storm = LoadStorm(_spec(faults=(
            FaultSpec(kind="crash", process=1, at_records=64),
            FaultSpec(kind="launch", process=0, count=2),
            FaultSpec(kind="hang", process=2, at_chunks=3),
            FaultSpec(kind="chaos", spec="seed=1,drop=0.1"),
            FaultSpec(kind="sever", at_chunks=5),
        )))
        flags = storm.fault_flags(str(tmp_path / "faults"))
        joined = " ".join(flags)
        assert "--failProcess 1 --failAfterRecords 64" in joined
        assert "--refuseLaunchProcess 0 --refuseLaunchCount 2" in joined
        assert "--hangProcess 2 --hangAfterChunks 3" in joined
        assert "--kafkaChaos seed=1,drop=0.1" in joined
        assert "--severBrokerAfterChunks 5" in joined
        assert "--faultStateDir" in joined

    def test_no_faults_no_state_dir(self, tmp_path):
        assert LoadStorm(_spec()).fault_flags(str(tmp_path)) == []

    def test_write_files_and_worker_args(self, tmp_path):
        storm = LoadStorm(_spec())
        args = storm.worker_args(
            str(tmp_path), checkpoint_every=2, extra=["--foo", "bar"],
        )
        joined = " ".join(args)
        assert "--requestSchedule" in joined
        assert "--checkpointEvery 2" in joined
        assert joined.endswith("--foo bar")
        paths = storm.write_files(str(tmp_path))
        data = open(paths["data"]).read().splitlines()
        assert data == list(storm.data_lines())
        assert (
            open(paths["schedule"]).read().splitlines()
            == storm.schedule_lines()
        )


# --- fskafka preloading (satellite 1) ----------------------------------------


class TestFskafkaPreload:
    def test_preload_partitions_and_counts(self, tmp_path, monkeypatch):
        from tests import fskafka

        storm = LoadStorm(_spec())
        d = str(tmp_path / "broker")
        counts = storm.preload_fskafka(d, partitions=2)
        n_fc = sum(1 for is_fc, _ in storm._records if is_fc)
        assert counts["forecastingData"] == n_fc
        assert counts["trainingData"] == storm.spec.records - n_fc
        assert counts["requests"] == (
            storm.spec.tenants + len(storm.churn)
        )
        # the file-backed consumer reads the identical byte stream back,
        # offsets = line numbers
        monkeypatch.setenv("FSKAFKA_DIR", d)
        got = []
        for part in (0, 1):
            for topic in ("trainingData", "forecastingData"):
                tp = fskafka.TopicPartition(topic, part)
                log = fskafka._Log(topic, part)
                if not os.path.exists(log.path):
                    continue
                end = fskafka.KafkaConsumer().end_offsets([tp])[tp]
                lines = log.lines()
                assert end == len(lines)
                got.extend(l.decode() for l in lines)
        assert sorted(got) == sorted(storm.data_lines())

    def test_preload_truncates_previous_logs(self, tmp_path):
        storm = LoadStorm(_spec())
        d = str(tmp_path / "broker")
        first = storm.preload_fskafka(d, partitions=1)
        again = storm.preload_fskafka(d, partitions=1)
        assert first == again
        n = sum(
            1 for _ in open(os.path.join(d, "trainingData--0.log"))
        )
        assert n == first["trainingData"]

    def test_request_log_preserves_schedule_order(self, tmp_path):
        storm = LoadStorm(_spec())
        d = str(tmp_path / "broker")
        storm.preload_fskafka(d)
        lines = open(os.path.join(d, "requests--0.log")).read().splitlines()
        want = storm.request_lines() + [
            json.dumps(req) for _, req in storm.schedule_entries()
        ]
        assert lines == want
